"""Decode-fleet fault tolerance (docs/serving.md §Fleet fault tolerance):
mid-stream failover, live KV migration on drain, chaos-hardened routing.

The load-bearing invariant is the same byte parity test_fleet.py pins,
extended across failures: a stream whose worker dies (or drains away)
mid-generation must finish with EXACTLY the tokens the no-fault run
would have produced — greedy AND seeded — because sampling keys are
counter-based on absolute position, so re-prefilling prompt+delivered
(or adopting the migrated pages) reconstructs the mid-run state bit for
bit.  These tests exercise every recovery path: resume-by-re-prefill,
migration adoption, corrupt-handoff degradation, client-disconnect slot
reclaim, breaker-driven snapshot invalidation, and (slow) a real
SIGKILL / scale-down drain against subprocess pool workers.
"""

import json
import os
import threading
import time
from urllib import request as urlreq

import jax
import numpy as np
import pytest

from bigdl_tpu.nn.attention import Transformer
from bigdl_tpu.obs import sentinel
from bigdl_tpu.resilience import faults
from bigdl_tpu.serving.decode_engine import (DecodeConfig, DecodeEngine,
                                             DecodeRequest, LMAdapter)
from bigdl_tpu.serving.fleet.handoff import (HandoffError, pack_handoff,
                                             unpack_handoff)

BOS, EOS = 0, 1


@pytest.fixture(scope="module")
def lm():
    model = Transformer(vocab_size=32, hidden_size=16, num_heads=2,
                        num_layers=2, dropout=0.0, mode="lm")
    v = model.init(jax.random.PRNGKey(0),
                   np.arange(6, dtype=np.int32)[None])
    return model, v


@pytest.fixture(autouse=True)
def _no_faults():
    yield
    faults.clear()


def _engine(lm, **over):
    model, v = lm
    kw = dict(slots=4, page_size=4, pages_per_slot=4, prompt_chunk=4,
              max_new_tokens=16, eos_id=EOS, prefill_batch=2,
              prefix_cache_pages=8)
    kw.update(over)
    cfg = DecodeConfig(**kw)
    return DecodeEngine(LMAdapter(model, v["params"], cap=cfg.cap),
                        cfg).warmup()


def _serving_pair(lm, **decode_over):
    from bigdl_tpu.serving.http_frontend import HttpFrontend
    from bigdl_tpu.serving.inference_model import InferenceModel
    from bigdl_tpu.serving.server import ServingConfig, ServingServer

    model, v = lm
    kw = dict(slots=4, page_size=4, pages_per_slot=4, prompt_chunk=4,
              max_new_tokens=16, eos_id=EOS, prefill_batch=2,
              prefix_cache_pages=8)
    kw.update(decode_over)
    srv = ServingServer(InferenceModel(model, v, decode=DecodeConfig(**kw)),
                        ServingConfig()).start()
    fe = HttpFrontend(srv, port=0).start()
    return srv, fe


def _slow_engine(eng, sleep_s=0.03):
    """Throttle the decode loop so a test can act mid-stream
    deterministically; the wrapper runs inside ``_iter_lock``, so
    ``drain_decode``/``cancel`` still interleave atomically.  The rate
    is re-tunable via ``eng._test_sleep_s`` (fixture-shared engines)."""
    orig = eng._decode_step
    eng._test_sleep_s = sleep_s

    def _step():
        time.sleep(eng._test_sleep_s)
        return orig()

    eng._decode_step = _step


# engine warmup dominates this file's wall time, so the serving pairs
# are module fixtures; tests assert on stat DELTAS, never absolutes


@pytest.fixture(scope="module")
def pair(lm):
    srv, fe = _serving_pair(lm)
    yield srv, fe
    fe.stop()
    srv.stop()


@pytest.fixture(scope="module")
def nocache(lm):
    """A bare exporter engine + an importing pair, prefix cache OFF so
    adoption vs re-prefill is decided by the parked handoff alone."""
    eng_a = _engine(lm, prefix_cache_pages=0)
    srv_b, fe_b = _serving_pair(lm, prefix_cache_pages=0)
    yield eng_a, srv_b, fe_b
    fe_b.stop()
    srv_b.stop()
    eng_a.stop()


@pytest.fixture(scope="module")
def drain_pair(lm):
    """Victim A (decode throttled so tests can act mid-stream) and
    adopting peer B."""
    srv_a, fe_a = _serving_pair(lm)
    srv_b, fe_b = _serving_pair(lm)
    _slow_engine(srv_a.model.decode_engine)
    yield srv_a, fe_a, srv_b, fe_b
    fe_a.stop()
    fe_b.stop()
    srv_a.stop()
    srv_b.stop()


def _prompt(n=4, seed=3):
    rs = np.random.RandomState(seed)
    return np.asarray(rs.randint(2, 32, size=n), np.int32)


def _ref_tokens(eng, prompt, max_new, **kw):
    r = eng.static_generate([DecodeRequest(
        tokens=np.asarray(prompt, np.int32),
        max_new_tokens=max_new, **kw)])[0]
    return [int(t) for t in r.tokens]


SEEDED = dict(temperature=0.8, top_k=8, top_p=0.9, seed=13)


# ---------------------------------------------------------------------------
# proxy relay units: failover bookkeeping without any worker process


def test_track_line_records_and_dedups():
    from bigdl_tpu.serving.pool import _ProxyHandler

    d = []
    track = _ProxyHandler._track_line
    assert track(b'{"token": 7, "index": 0}', d) and d == [7]
    assert track(b'{"token": 9, "index": 1}', d) and d == [7, 9]
    # an adopting worker re-emits the boundary token: dropped, not doubled
    assert not track(b'{"token": 9, "index": 1}', d)
    assert d == [7, 9]
    # final verdicts / non-token lines pass through untouched
    assert track(b'{"done": true, "tokens": [7, 9]}', d)
    assert track(b"not json at all", d)
    assert track(b"[1, 2]", d)
    # blanks are swallowed (keep-alive noise must not be re-framed)
    assert not track(b"   ", d)
    assert d == [7, 9]


def test_resume_body_rebuilds_request():
    from bigdl_tpu.serving.pool import _ProxyHandler

    body = json.dumps({"tokens": [2, 3], "stream": True,
                       "seed": 5}).encode()
    out = _ProxyHandler._resume_body(None, body, [7, 9])
    payload = json.loads(out)
    assert payload["resume_from"] == [7, 9]
    assert payload["seed"] == 5 and payload["stream"] is True
    # nothing delivered yet: a plain fresh re-request, no resume_from
    fresh = json.loads(_ProxyHandler._resume_body(None, body, []))
    assert "resume_from" not in fresh
    # unreconstructable bodies orphan instead of corrupting
    assert _ProxyHandler._resume_body(None, b"\xff\xfe", [1]) is None
    assert _ProxyHandler._resume_body(None, b"[1]", [1]) is None


def test_breaker_open_invalidates_fleet_snapshot():
    from bigdl_tpu.serving.pool import ServingPool

    pool = ServingPool("tests.test_fleet_chaos:_fleet_loader", workers=2)
    try:
        pool._fleet_cache = [("stale", None)]
        pool._fleet_t = time.time()
        pool.invalidate_fleet_snapshot()
        assert pool._fleet_cache is None and pool._fleet_t == 0.0
        # a worker breaker tripping open must evict the routing snapshot
        # (the cached healths still score the dying worker as routable)
        pool._fleet_cache = [("stale", None)]
        pool._fleet_t = time.time()
        w = pool._new_worker()
        for _ in range(pool.breaker_threshold):
            w.breaker.record_failure()
        assert w.breaker.snapshot()["state"] == "open"
        assert pool._fleet_cache is None
    finally:
        pool._httpd.server_close()


def test_fleet_fault_points_registered():
    for point in ("fleet_worker_kill", "fleet_handoff_corrupt",
                  "fleet_stream_sever", "fleet_health_stale"):
        assert point in faults.POINTS
    specs = faults.parse_plan("fleet_stream_sever:every=1;"
                              "fleet_health_stale:every=1")
    faults.install(specs)
    with pytest.raises(faults.StreamSeveredError) as ei:
        faults.fire("fleet_stream_sever")
    # the relay's worker-read try treats it as a connection dying
    assert isinstance(ei.value, ConnectionResetError)
    with pytest.raises(faults.HealthStaleFault):
        faults.fire("fleet_health_stale")


def test_unpack_handoff_hardening_bounds():
    rs = np.random.RandomState(0)
    h = {"tokens": [3, 4, 5], "first_token": 6, "first_logp": -0.5,
         "request_id": "hard-1",
         "k": rs.randn(2, 2, 2, 4, 3).astype(np.float32),
         "v": rs.randn(2, 2, 2, 4, 3).astype(np.float32)}
    blob = pack_handoff(h)
    # request_id rides the wire: what /fleet/import parks by
    assert unpack_handoff(blob)["request_id"] == "hard-1"
    with pytest.raises(HandoffError, match="exceeds"):
        unpack_handoff(blob, max_bytes=16)
    with pytest.raises(HandoffError, match="page"):
        unpack_handoff(blob, max_pages=1)
    with pytest.raises(HandoffError, match="magic"):
        unpack_handoff(b"XXXXXXXX" + blob[8:])
    # HandoffError stays a ValueError: pre-existing callers keep working
    assert issubclass(HandoffError, ValueError)


# ---------------------------------------------------------------------------
# resume_from: the frontend half of mid-stream failover


def test_resume_reprefill_parity_greedy(lm, pair):
    from bigdl_tpu.serving.http_frontend import HttpClient

    srv, fe = pair
    eng = srv.model.decode_engine
    p = _prompt()
    ref = _ref_tokens(eng, p, 8)
    assert len(ref) >= 6  # the split below needs a mid-stream point
    c = HttpClient(fe.url)
    got = c.generate(p, max_new_tokens=8, resume_from=ref[:4],
                     request_id="rg-1")
    assert [int(t) for t in got] == ref


def test_resume_reprefill_parity_seeded(lm, pair):
    from bigdl_tpu.serving.http_frontend import HttpClient

    srv, fe = pair
    eng = srv.model.decode_engine
    p = _prompt()
    ref = _ref_tokens(eng, p, 8, **SEEDED)
    assert len(ref) >= 6
    c = HttpClient(fe.url)
    got = c.generate(p, max_new_tokens=8, resume_from=ref[:4],
                     request_id="rs-1", **SEEDED)
    assert [int(t) for t in got] == ref


def test_resume_stream_indices_continue_past_delivered(lm, pair):
    """A resumed stream must only emit tokens the client does NOT hold,
    indexed where the dead worker stopped — the relay dedups by index."""
    import http.client

    srv, fe = pair
    eng = srv.model.decode_engine
    p = _prompt()
    ref = _ref_tokens(eng, p, 8, **SEEDED)
    assert len(ref) >= 6
    conn = http.client.HTTPConnection(fe.host, fe.port, timeout=30)
    conn.request("POST", "/generate", body=json.dumps(dict(
        tokens=[int(t) for t in p], stream=True, max_new_tokens=8,
        resume_from=ref[:4], request_id="ri-1", **SEEDED)).encode(),
        headers={"Content-Type": "application/json",
                 "Connection": "close"})
    resp = conn.getresponse()
    assert resp.status == 200
    events, final = [], None
    while True:
        line = resp.readline()
        if not line:
            break
        ev = json.loads(line)
        if ev.get("done"):
            final = ev
            break
        events.append((ev["index"], ev["token"]))
    conn.close()
    assert final is not None and "error" not in final
    assert [int(t) for t in final["tokens"]] == ref
    # re-prefill path: generation restarts at index r, never below
    assert events and events[0][0] == 4
    assert [t for _, t in events] == ref[4:]


def test_resume_short_circuits_when_nothing_left(lm, pair):
    """resume_from covering the whole effective budget (or ending at
    EOS) answers immediately with what the client already holds — the
    original run would have stopped exactly there."""
    from bigdl_tpu.serving.http_frontend import HttpClient

    srv, fe = pair
    eng = srv.model.decode_engine
    p = _prompt()
    ref = _ref_tokens(eng, p, 4)
    requests_before = eng.stats["requests"]  # no engine work at all
    c = HttpClient(fe.url)
    got = c.generate(p, max_new_tokens=4, resume_from=ref,
                     request_id="rc-1")
    assert [int(t) for t in got] == ref
    # EOS-terminated delivery short-circuits too
    got = c.generate(p, max_new_tokens=8, resume_from=[5, EOS],
                     request_id="rc-2")
    assert [int(t) for t in got] == [5, EOS]
    assert eng.stats["requests"] == requests_before


def test_resume_reprefill_hits_warm_prefix_cache(lm, pair):
    """Failover re-prefill pays page-aligned prefix-cache hits for the
    prompt the original run already donated — recovery cost is the
    delivered suffix, not the whole prompt."""
    from bigdl_tpu.serving.http_frontend import HttpClient

    srv, fe = pair
    eng = srv.model.decode_engine
    p = _prompt(8, seed=11)  # page-aligned: 2 full pages cacheable
    c = HttpClient(fe.url)
    ref = [int(t) for t in c.generate(p, max_new_tokens=6,
                                      request_id="pc-0")]
    assert len(ref) == 6
    st = eng._prefix_cache.stats()
    assert st["insertions"] >= 1
    hits_before = st["hits"]
    got = c.generate(p, max_new_tokens=6, resume_from=ref[:3],
                     request_id="pc-1")
    assert [int(t) for t in got] == ref
    assert eng._prefix_cache.stats()["hits"] > hits_before


# ---------------------------------------------------------------------------
# migration adoption: parked pages instead of re-prefill


def test_resume_adopts_parked_migration_handoff(lm, nocache):
    """A parked handoff whose state matches prompt+delivered exactly is
    adopted: no re-prefill, the boundary token re-emits at index r-1,
    and the continuation is byte-identical to the no-fault run."""
    import http.client

    eng_a, srv_b, fe_b = nocache
    eng_b = srv_b.model.decode_engine
    imports_before = eng_b.stats["kv_imports"]
    p = _prompt()
    ref = _ref_tokens(eng_b, p, 8, **SEEDED)
    assert len(ref) == 8
    r = 4
    # the state a drained victim would export at r delivered tokens
    # IS a prefill export of prompt + delivered[:-1]: same pages,
    # same pending first token (the byte-parity invariant)
    pre = eng_a.submit(DecodeRequest(
        tokens=np.concatenate([p, np.asarray(ref[:r - 1], np.int32)]),
        max_new_tokens=1, export_kv=True, **SEEDED))
    pre.wait(30)
    assert pre.error is None and pre.kv_export is not None
    h = dict(pre.kv_export)
    h.update(request_id="adopt-1", **SEEDED)
    assert int(h["first_token"]) == ref[r - 1]
    req = urlreq.Request(fe_b.url + "/fleet/import",
                         data=pack_handoff(h),
                         headers={"Content-Type":
                                  "application/octet-stream"})
    with urlreq.urlopen(req, timeout=30) as resp:
        assert json.loads(resp.read())["parked"] == "adopt-1"
    conn = http.client.HTTPConnection(fe_b.host, fe_b.port, timeout=30)
    conn.request("POST", "/generate", body=json.dumps(dict(
        tokens=[int(t) for t in p], stream=True, max_new_tokens=8,
        resume_from=ref[:r], request_id="adopt-1",
        **SEEDED)).encode(),
        headers={"Content-Type": "application/json",
                 "Connection": "close"})
    resp = conn.getresponse()
    assert resp.status == 200
    events, final = [], None
    while True:
        line = resp.readline()
        if not line:
            break
        ev = json.loads(line)
        if ev.get("done"):
            final = ev
            break
        events.append((ev["index"], ev["token"]))
    conn.close()
    assert final is not None and "error" not in final
    assert [int(t) for t in final["tokens"]] == ref
    # adoption, not re-prefill: the pages were IMPORTED, and the
    # boundary token re-emitted at index r-1 (the relay's dedup
    # point) — a re-prefill would have started at index r
    assert eng_b.stats["kv_imports"] == imports_before + 1
    assert events[0] == (r - 1, ref[r - 1])
    # parked state is single-use
    assert srv_b.take_parked("adopt-1") is None


def test_resume_rejects_mismatched_parked_state(lm, nocache):
    """A parked handoff that does not exactly match prompt+delivered
    (here: different sampling seed) must NOT be adopted — byte parity
    is safer served by re-prefill."""
    from bigdl_tpu.serving.http_frontend import HttpClient

    eng_a, srv_b, fe_b = nocache
    eng_b = srv_b.model.decode_engine
    imports_before = eng_b.stats["kv_imports"]
    p = _prompt()
    ref = _ref_tokens(eng_b, p, 8, **SEEDED)
    pre = eng_a.submit(DecodeRequest(
        tokens=np.concatenate([p, np.asarray(ref[:3], np.int32)]),
        max_new_tokens=1, export_kv=True, **SEEDED))
    pre.wait(30)
    h = dict(pre.kv_export)
    h.update(request_id="mism-1", **dict(SEEDED, seed=99))
    req = urlreq.Request(fe_b.url + "/fleet/import",
                         data=pack_handoff(h),
                         headers={"Content-Type":
                                  "application/octet-stream"})
    with urlreq.urlopen(req, timeout=30) as resp:
        assert resp.status == 200
    got = HttpClient(fe_b.url).generate(
        p, max_new_tokens=8, resume_from=ref[:4],
        request_id="mism-1", **SEEDED)
    assert [int(t) for t in got] == ref
    # re-prefilled, no adoption
    assert eng_b.stats["kv_imports"] == imports_before


def test_fleet_import_rejects_corrupt_blob(lm, pair):
    srv, fe = pair
    rs = np.random.RandomState(0)
    blob = pack_handoff({
        "tokens": [3, 4, 5], "first_token": 6, "first_logp": -0.5,
        "request_id": "bad-1",
        "k": rs.randn(2, 2, 2, 4, 3).astype(np.float32),
        "v": rs.randn(2, 2, 2, 4, 3).astype(np.float32)})
    req = urlreq.Request(fe.url + "/fleet/import",
                         data=b"XXXXXXXX" + blob[8:],
                         headers={"Content-Type":
                                  "application/octet-stream"})
    try:
        urlreq.urlopen(req, timeout=10)
        raise AssertionError("expected HTTP 400")
    except Exception as e:  # noqa: BLE001 — urllib HTTPError
        assert getattr(e, "code", None) == 400
    assert srv.take_parked("bad-1") is None  # rejected whole


# ---------------------------------------------------------------------------
# live drain: freeze-export-ship-evict between two real frontends


def _read_stream_until_severed(resp):
    """Collect token events until the stream ends.  ``severed`` means
    it ended WITHOUT a ``done`` verdict — the worker aborted the
    chunked body short of the terminator.  (The pool relay's ``read1``
    sees that as IncompleteRead; ``readline`` here surfaces it as a
    bare EOF because http.client's peek path swallows the exception —
    either way, no verdict is the failover trigger.)"""
    delivered, final, severed = [], None, False
    while True:
        try:
            line = resp.readline()
        except Exception:  # noqa: BLE001 — IncompleteRead: truncation
            severed = True
            break
        if not line:
            severed = final is None
            break
        ev = json.loads(line)
        if ev.get("done"):
            final = ev
            break
        if "token" in ev:
            delivered.append(int(ev["token"]))
    return delivered, final, severed


def test_drain_migrates_live_slot_and_resume_adopts(lm, drain_pair):
    """End-to-end two-phase drain, in process: a live stream on A is
    frozen+exported+shipped to B, evicted (stream aborts WITHOUT a
    terminator — the failover trigger), and the resume on B adopts the
    parked pages; the joined token sequence is byte-identical."""
    import http.client

    from bigdl_tpu.serving.http_frontend import HttpClient

    srv_a, fe_a, srv_b, fe_b = drain_pair
    eng_a = srv_a.model.decode_engine
    eng_b = srv_b.model.decode_engine
    exports_before = eng_a.stats["kv_exports"]
    imports_before = eng_b.stats["kv_imports"]
    cancelled_before = eng_a.stats["cancelled"]
    p = _prompt()
    ref = _ref_tokens(eng_b, p, 10, **SEEDED)
    assert len(ref) == 10
    conn = http.client.HTTPConnection(fe_a.host, fe_a.port,
                                      timeout=30)
    conn.request("POST", "/generate", body=json.dumps(dict(
        tokens=[int(t) for t in p], stream=True, max_new_tokens=10,
        request_id="mig-1", **SEEDED)).encode(),
        headers={"Content-Type": "application/json",
                 "Connection": "close"})
    resp = conn.getresponse()
    assert resp.status == 200
    first = [json.loads(resp.readline()) for _ in range(2)]
    assert all("token" in ev for ev in first)
    # phase 1: freeze + export + ship; the migration map returns
    # BEFORE anything is severed (what the pool records)
    out = srv_a.drain_decode([fe_b.url], evict=False)
    assert out["migrated"] == {"mig-1": fe_b.url}
    assert out["frozen"] == ["mig-1"] and out["failed"] == []
    assert eng_a.stats["kv_exports"] == exports_before + 1
    # phase 2: evict -> the victim-side stream aborts truncated
    srv_a.evict_migrated(out["frozen"])
    rest, final, severed = _read_stream_until_severed(resp)
    conn.close()
    assert severed and final is None
    delivered = [int(ev["token"]) for ev in first] + rest
    # the relay's move: resume on the adopting peer
    got = HttpClient(fe_b.url).generate(
        p, max_new_tokens=10, resume_from=delivered,
        request_id="mig-1", **SEEDED)
    assert [int(t) for t in got] == ref
    # the migrated pages were adopted — no re-prefill on B
    assert eng_b.stats["kv_imports"] == imports_before + 1
    assert eng_a.stats["cancelled"] > cancelled_before  # evicted slot


def test_drain_corrupt_handoff_degrades_to_reprefill(lm, drain_pair):
    """fleet_handoff_corrupt at the export seam: the peer rejects the
    blob whole, drain reports the failure — and the stream STILL
    completes byte-identically via re-prefill failover."""
    import http.client

    from bigdl_tpu.serving.http_frontend import HttpClient

    srv_a, fe_a, srv_b, fe_b = drain_pair
    eng_a = srv_a.model.decode_engine
    eng_b = srv_b.model.decode_engine
    imports_before = eng_b.stats["kv_imports"]
    p = _prompt()
    ref = _ref_tokens(eng_b, p, 10)
    assert len(ref) == 10
    conn = http.client.HTTPConnection(fe_a.host, fe_a.port,
                                      timeout=30)
    conn.request("POST", "/generate", body=json.dumps(dict(
        tokens=[int(t) for t in p], stream=True, max_new_tokens=10,
        request_id="cor-1")).encode(),
        headers={"Content-Type": "application/json",
                 "Connection": "close"})
    resp = conn.getresponse()
    assert resp.status == 200
    first = [json.loads(resp.readline()) for _ in range(2)]
    faults.install([faults.FaultSpec("fleet_handoff_corrupt",
                                     every=1)])
    out = srv_a.drain_decode([fe_b.url], evict=False)
    faults.clear()
    assert out["migrated"] == {} and out["failed"] == ["cor-1"]
    # nothing parked on the peer: the corrupt blob was rejected
    assert srv_b.take_parked("cor-1") is None
    srv_a.evict_migrated(out["frozen"] or ["cor-1"])
    rest, final, severed = _read_stream_until_severed(resp)
    conn.close()
    assert severed and final is None
    delivered = [int(ev["token"]) for ev in first] + rest
    got = HttpClient(fe_b.url).generate(
        p, max_new_tokens=10, resume_from=delivered,
        request_id="cor-1")
    assert [int(t) for t in got] == ref
    # recovered by re-prefill, not adoption
    assert eng_b.stats["kv_imports"] == imports_before


def test_drain_int8_to_f32_degrades_to_reprefill(lm):
    """Mixed-dtype drain (docs/quantization.md §Serving memory
    hierarchy): an int8 victim draining to an f32 peer must NOT ship
    pages the peer can't read — the peer refuses the import naming both
    dtypes, drain reports the failure, and the re-placed stream still
    completes byte-identically via re-prefill failover (int8 greedy
    token parity makes the joined stream exact)."""
    import http.client

    from bigdl_tpu.serving.http_frontend import HttpClient

    srv_a, fe_a = _serving_pair(lm, kv_dtype="int8")
    srv_b, fe_b = _serving_pair(lm)
    _slow_engine(srv_a.model.decode_engine)
    try:
        eng_b = srv_b.model.decode_engine
        imports_before = eng_b.stats["kv_imports"]
        p = _prompt()
        ref = _ref_tokens(eng_b, p, 10)
        assert len(ref) == 10
        conn = http.client.HTTPConnection(fe_a.host, fe_a.port,
                                          timeout=30)
        conn.request("POST", "/generate", body=json.dumps(dict(
            tokens=[int(t) for t in p], stream=True, max_new_tokens=10,
            request_id="dt-1")).encode(),
            headers={"Content-Type": "application/json",
                     "Connection": "close"})
        resp = conn.getresponse()
        assert resp.status == 200
        first = [json.loads(resp.readline()) for _ in range(2)]
        out = srv_a.drain_decode([fe_b.url], evict=False)
        # the peer refused the int8 pages whole: failed, nothing parked
        assert out["migrated"] == {} and out["failed"] == ["dt-1"]
        assert srv_b.take_parked("dt-1") is None
        srv_a.evict_migrated(out["frozen"] or ["dt-1"])
        rest, final, severed = _read_stream_until_severed(resp)
        conn.close()
        assert severed and final is None
        delivered = [int(ev["token"]) for ev in first] + rest
        got = HttpClient(fe_b.url).generate(
            p, max_new_tokens=10, resume_from=delivered,
            request_id="dt-1")
        assert [int(t) for t in got] == ref
        # recovered by re-prefill on the f32 peer, never an adoption
        assert eng_b.stats["kv_imports"] == imports_before
    finally:
        fe_a.stop()
        fe_b.stop()
        srv_a.stop()
        srv_b.stop()


def test_mixed_dtype_parked_handoff_not_adopted(lm):
    """Defense in depth behind the import gate: a parked handoff whose
    page dtype contradicts the engine's is skipped at adoption time —
    the resume re-prefills instead of submitting pages the engine would
    reject."""
    from bigdl_tpu.serving.http_frontend import HttpClient

    eng_a = _engine(lm, kv_dtype="int8", prefix_cache_pages=0)
    srv_b, fe_b = _serving_pair(lm, prefix_cache_pages=0)
    try:
        eng_b = srv_b.model.decode_engine
        imports_before = eng_b.stats["kv_imports"]
        p = _prompt()
        ref = _ref_tokens(eng_b, p, 8)
        pre = eng_a.submit(DecodeRequest(
            tokens=np.concatenate([p, np.asarray(ref[:3], np.int32)]),
            max_new_tokens=1, export_kv=True))
        pre.wait(30)
        h = dict(pre.kv_export, request_id="dtp-1")
        assert h["kv_dtype"] == "int8"
        # park directly (bypassing the /fleet/import dtype gate)
        srv_b.park_handoff(h)
        got = HttpClient(fe_b.url).generate(
            p, max_new_tokens=8, resume_from=ref[:4],
            request_id="dtp-1")
        assert [int(t) for t in got] == ref
        assert eng_b.stats["kv_imports"] == imports_before
    finally:
        fe_b.stop()
        srv_b.stop()
        eng_a.stop()


def test_client_disconnect_frees_slot_mid_stream(lm, drain_pair):
    """A client hanging up mid-stream must free the slot + pages NOW
    (counted as a client_disconnect cancel), not decode to
    max_new_tokens against a dead socket."""
    import http.client

    srv, fe = drain_pair[0], drain_pair[1]
    eng = srv.model.decode_engine
    # slow enough that the whole budget takes seconds: the cancel
    # must land MID-generation, not after a fast run finished
    eng._test_sleep_s = 0.15
    try:
        p = _prompt()
        conn = http.client.HTTPConnection(fe.host, fe.port, timeout=30)
        conn.request("POST", "/generate", body=json.dumps(dict(
            tokens=[int(t) for t in p], stream=True,
            max_new_tokens=14, request_id="gone-1")).encode(),
            headers={"Content-Type": "application/json",
                     "Connection": "close"})
        resp = conn.getresponse()
        assert resp.status == 200
        assert "token" in json.loads(resp.readline())
        before = eng.stats["cancelled"]
        # abrupt client death: shutdown acts on the fd NOW (a bare
        # close() would linger — resp's makefile still holds a ref),
        # and further server writes draw an RST
        import socket as _socket
        conn.sock.shutdown(_socket.SHUT_RDWR)
        conn.sock.close()
        deadline = time.time() + 10
        while time.time() < deadline:
            if eng.stats["cancelled"] > before:
                break
            time.sleep(0.05)
        assert eng.stats["cancelled"] > before
        deadline = time.time() + 5
        while time.time() < deadline:
            if srv.decode_pressure().get("free_slots") == eng.cfg.slots:
                break
            time.sleep(0.05)
        assert srv.decode_pressure().get("free_slots") == eng.cfg.slots
    finally:
        eng._test_sleep_s = 0.03


# ---------------------------------------------------------------------------
# sentinel: the DECODE_CHAOS_r* family


def test_sentinel_normalizes_decode_chaos_rows():
    row = {"bench": "decode_chaos", "geometry": "decode_chaos_w2_c24",
           "workers": 2, "recovery_ms_p99": 812.5,
           "chaos_tokens_per_s": 950.0, "failovers": 3}
    fams = {r.family: r for r in sentinel.normalize(row, "t")}
    assert fams["chaos_recovery_ms_p99_decode_chaos_w2_c24"].direction \
        == sentinel.LOWER
    assert fams["chaos_tokens_per_s_decode_chaos_w2_c24"].direction \
        == sentinel.HIGHER
    # the chaos row must NOT leak into the decode-bench families
    assert not any(f.startswith("decode_tokens_per_s") for f in fams)
    assert "DECODE_CHAOS_r[0-9]*.json" in sentinel._ARTIFACT_GLOBS


# ---------------------------------------------------------------------------
# subprocess pool chaos: SIGKILL mid-stream and scale-down drain


def _fleet_loader():
    """Worker-side factory (tests.test_fleet_chaos:_fleet_loader): the
    test_fleet.py tiny-LM worker, plus an optional decode throttle
    (``BIGDL_TPU_TEST_DECODE_SLEEP``) so a kill/drain deterministically
    lands while streams are mid-flight."""
    import os as _os
    import time as _time

    import jax
    import numpy as np

    from bigdl_tpu.nn.attention import Transformer
    from bigdl_tpu.serving.decode_engine import DecodeConfig
    from bigdl_tpu.serving.inference_model import InferenceModel

    jax.config.update("jax_threefry_partitionable", True)
    model = Transformer(vocab_size=32, hidden_size=16, num_heads=2,
                        num_layers=2, dropout=0.0, mode="lm")
    v = model.init(jax.random.PRNGKey(0),
                   np.arange(6, dtype=np.int32)[None])
    im = InferenceModel(model, v, decode=DecodeConfig(
        slots=4, page_size=4, pages_per_slot=4, prompt_chunk=4,
        max_new_tokens=16, eos_id=1, prefill_batch=2,
        prefix_cache_pages=8))
    eng = im.decode_engine
    eng.warmup()
    sleep_s = float(_os.environ.get("BIGDL_TPU_TEST_DECODE_SLEEP",
                                    "0") or 0)
    if sleep_s > 0:
        orig = eng._decode_step

        def _slow_step():
            _time.sleep(sleep_s)
            return orig()

        eng._decode_step = _slow_step
    return im


def _chaos_reqs(lm, n=6, max_new=10):
    """n streaming requests (half greedy, half seeded) with their local
    static references — prompts/seeds pinned so every reference runs
    the full max_new (no early EOS: a finished stream cannot fail
    over, and parity against a truncated reference is vacuous)."""
    ref_eng = _engine(lm, max_new_tokens=16)
    rs = np.random.RandomState(17)
    reqs = []
    tries = 0
    while len(reqs) < n and tries < 100:
        tries += 1
        p = np.asarray(rs.randint(2, 32, size=4), np.int32)
        if len(reqs) % 2 == 0:
            kw = dict(temperature=0.0, top_k=0, top_p=1.0, seed=0)
        else:
            kw = dict(temperature=0.8, top_k=8, top_p=0.9,
                      seed=int(rs.randint(0, 2 ** 31 - 1)))
        ref = _ref_tokens(ref_eng, p, max_new, **kw)
        if len(ref) < max_new:
            continue  # early EOS: not a useful chaos stream
        reqs.append({"rid": f"chaos-{len(reqs)}", "ref": ref,
                     "mid": threading.Event(),
                     "payload": dict(tokens=[int(t) for t in p],
                                     stream=True, max_new_tokens=max_new,
                                     **kw)})
    ref_eng.stop()
    assert len(reqs) == n
    return reqs


def _stream_through_pool(pool, req, results, errors):
    import http.client

    conn = http.client.HTTPConnection(pool.host, pool.port, timeout=120)
    try:
        conn.request("POST", "/generate",
                     body=json.dumps(req["payload"]).encode(),
                     headers={"Content-Type": "application/json",
                              "X-Request-Id": req["rid"],
                              "Connection": "close"})
        resp = conn.getresponse()
        if resp.status != 200:
            errors.append((req["rid"], f"HTTP {resp.status}"))
            return
        toks, final = [], None
        while True:
            line = resp.readline()
            if not line:
                break
            ev = json.loads(line)
            if ev.get("done"):
                final = ev
                break
            if "token" in ev:
                toks.append(int(ev["token"]))
                if len(toks) == 2:
                    req["mid"].set()
        if final is None:
            errors.append((req["rid"], "truncated stream"))
        elif "error" in final:
            errors.append((req["rid"], str(final["error"])))
        else:
            results[req["rid"]] = ([int(t) for t in final["tokens"]],
                                   toks)
    except Exception as e:  # noqa: BLE001 — a failed stream IS the bug
        errors.append((req["rid"], repr(e)))
    finally:
        req["mid"].set()
        conn.close()


def _pool_env():
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    pythonpath = os.pathsep.join(
        p for p in [repo_root, os.environ.get("PYTHONPATH")] if p)
    return {"PYTHONPATH": pythonpath, "BIGDL_TPU_POOL_CPU": "1",
            "JAX_PLATFORMS": "cpu",
            "BIGDL_TPU_TEST_DECODE_SLEEP": "0.05"}


def _run_chaos_streams(pool, reqs):
    results, errors = {}, []
    threads = [threading.Thread(target=_stream_through_pool,
                                args=(pool, r, results, errors))
               for r in reqs]
    for t in threads:
        t.start()
    for r in reqs:
        assert r["mid"].wait(60), f"{r['rid']} never got 2 tokens"
    return threads, results, errors


def _join_and_check_parity(threads, reqs, results, errors):
    for t in threads:
        t.join(timeout=180)
    assert not errors, errors
    for r in reqs:
        final, streamed = results[r["rid"]]
        assert final == r["ref"], \
            f"{r['rid']}: {final} != {r['ref']}"
        # the relay's dedup means streamed events == final, in order
        assert streamed == r["ref"]


@pytest.mark.slow
def test_fleet_pool_failover_on_worker_kill(lm):
    """The chaos acceptance run, in miniature: SIGKILL a decode worker
    with >=4 streams mid-flight; every stream must finish byte-
    identical to its no-fault reference (greedy AND seeded), failovers
    counted, fleet_failover flight events recorded, and the federated
    /metrics scrape exposing the canonical counters."""
    from bigdl_tpu.obs import flight
    from bigdl_tpu.serving.pool import ServingPool

    pool = ServingPool("tests.test_fleet_chaos:_fleet_loader", workers=2,
                       batch_size=8, worker_env=_pool_env(),
                       roles=["both", "both"], supervise_interval_s=0.3,
                       predict_timeout=60.0, fleet_health_max_age_s=0.0)
    pool.start()
    try:
        reqs = _chaos_reqs(lm, n=6)
        threads, results, errors = _run_chaos_streams(pool, reqs)
        # pick a victim that actually holds live streams
        with urlreq.urlopen(pool.url + "/health", timeout=10) as r:
            h = json.loads(r.read())
        victim_name = next(
            w["name"] for w in h["workers"]
            if w.get("decode", {}).get("generate_inflight", 0) >= 1)
        victim = next(w for w in pool.worker_list()
                      if w.name == victim_name)
        victim.proc.kill()  # SIGKILL: no drain, no goodbye
        _join_and_check_parity(threads, reqs, results, errors)
        assert pool.stats["fleet_failovers"] >= 1
        assert pool.stats["fleet_resumed_tokens"] >= 1
        assert pool.stats["fleet_orphans"] == 0
        evs = flight.global_recorder().snapshot()
        assert any(e["kind"] == "fleet_failover" for e in evs)
        with urlreq.urlopen(pool.url + "/metrics", timeout=10) as r:
            scrape = r.read().decode()
        assert "serving_fleet_failovers" in scrape
        assert "serving_fleet_recovery_s" in scrape
    finally:
        pool.stop()


@pytest.mark.slow
def test_fleet_pool_scale_down_drains_live_streams(lm):
    """Scale-down with live streams: the victim's slots migrate to the
    survivor BEFORE its streams abort, the relay resumes each on the
    adopting peer, and no client loses a token — zero dropped, byte
    parity, migrations counted."""
    from bigdl_tpu.serving.pool import ServingPool

    pool = ServingPool("tests.test_fleet_chaos:_fleet_loader", workers=2,
                       batch_size=8, worker_env=_pool_env(),
                       roles=["both", "both"], supervise_interval_s=0.3,
                       predict_timeout=60.0, fleet_health_max_age_s=0.0,
                       min_workers=1, autoscale_interval_s=600.0)
    pool.start()
    try:
        reqs = _chaos_reqs(lm, n=6)
        threads, results, errors = _run_chaos_streams(pool, reqs)
        # _scale_down picks the NEWEST healthy worker; rotate a worker
        # that holds live streams into that position so the drain has
        # real state to migrate
        with urlreq.urlopen(pool.url + "/health", timeout=10) as r:
            h = json.loads(r.read())
        victim_name = next(
            w["name"] for w in h["workers"]
            if w.get("decode", {}).get("generate_inflight", 0) >= 1)
        with pool._workers_lock:
            pool.workers.sort(key=lambda w: w.name == victim_name)
        pool._scale_down(pool.pool_pressure())
        _join_and_check_parity(threads, reqs, results, errors)
        assert pool.stats["scale_down"] == 1
        assert len(pool.worker_list()) == 1
        assert pool.stats["fleet_migrations"] >= 1
        assert pool.stats["fleet_orphans"] == 0
        # every migrated slot was claimed by its resume
        assert pool._migrated == {}
    finally:
        pool.stop()
