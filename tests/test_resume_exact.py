"""Exactly-once mid-epoch resume: a preempted-and-resumed run must train
on the SAME batch sequence as an uninterrupted run — the resumed epoch
fast-forwards past already-trained batches instead of replaying them.
The assertion is the strongest available: final weights match the
uninterrupted reference bit-for-bit-close (same batches, same per-
iteration rng folds, same momentum trajectory)."""

import numpy as np

import jax

from bigdl_tpu import nn
from bigdl_tpu.data.dataset import ArrayDataSet
from bigdl_tpu.nn.criterion import MSECriterion
from bigdl_tpu.optim.optim_method import SGD
from bigdl_tpu.optim.optimizer import Optimizer
from bigdl_tpu.optim.trigger import Trigger

N, D = 80, 4  # 5 batches of 16 per epoch


def _data():
    rs = np.random.RandomState(0)
    x = rs.randn(N, D).astype(np.float32)
    y = (x @ rs.randn(D, 1)).astype(np.float32)
    return x, y


def _fit(x, y, n_iters, ckpt_dir=None):
    model = nn.Sequential([nn.Linear(D, 6), nn.Tanh(), nn.Linear(6, 1)])
    opt = Optimizer(model, ArrayDataSet(x, y), MSECriterion(),
                    batch_size=16, seed=3)
    opt.set_optim_method(SGD(learning_rate=0.1, momentum=0.9))
    opt.set_end_when(Trigger.max_iteration(n_iters))
    if ckpt_dir is not None:
        opt.set_checkpoint(str(ckpt_dir), Trigger.several_iteration(1))
    opt.log_every = 1000
    return opt.optimize()


def _weights(trained):
    return [np.asarray(l) for l in
            jax.tree_util.tree_leaves(trained.variables["params"])]


def test_mid_epoch_resume_trains_each_batch_exactly_once(tmp_path):
    x, y = _data()
    ref = _fit(x, y, 8)  # uninterrupted: epoch 1 (5 batches) + 3 of epoch 2

    # interrupted at iteration 3 (mid-epoch 1), resumed to 8
    ckpt_dir = tmp_path / "ck"
    _fit(x, y, 3, ckpt_dir=ckpt_dir)
    resumed = _fit(x, y, 8, ckpt_dir=ckpt_dir)

    for a, b in zip(_weights(resumed), _weights(ref)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)


def test_resume_at_epoch_boundary_still_exact(tmp_path):
    x, y = _data()
    ref = _fit(x, y, 7)

    ckpt_dir = tmp_path / "ck"
    _fit(x, y, 5, ckpt_dir=ckpt_dir)  # exactly one full epoch
    resumed = _fit(x, y, 7, ckpt_dir=ckpt_dir)
    for a, b in zip(_weights(resumed), _weights(ref)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)


class _SigtermOnce:
    """end_when wrapper that raises a REAL SIGTERM (the TPU-VM preemption
    signal) the first time the run reaches ``at_iter`` — deterministic,
    and delivered through the optimizer's own signal handler."""

    def __init__(self, inner, at_iter):
        import os
        import signal

        self._inner = inner
        self._at = at_iter
        self._fired = False
        self._kill = lambda: os.kill(os.getpid(), signal.SIGTERM)
        b = getattr(inner, "boundary", None)
        if b is not None:  # keep the bundle-edge clamping hints intact
            self.boundary = b

    def __call__(self, state):
        if not self._fired and state["iteration"] >= self._at:
            self._fired = True
            self._kill()
        return self._inner(state)


def _sigterm_fit(x, y, n_iters, ckpt_dir, sigterm_at=None, k=2):
    from bigdl_tpu.optim.trigger import Trigger as T

    model = nn.Sequential([nn.Linear(D, 6), nn.Tanh(), nn.Linear(6, 1)])
    opt = Optimizer(model, ArrayDataSet(x, y), MSECriterion(),
                    batch_size=16, seed=3)
    opt.set_optim_method(SGD(learning_rate=0.1, momentum=0.9))
    end = T.max_iteration(n_iters)
    opt.set_end_when(_SigtermOnce(end, sigterm_at)
                     if sigterm_at is not None else end)
    opt.set_checkpoint(str(ckpt_dir), T.several_iteration(100))
    opt.set_preemption_checkpoint()
    opt.steps_per_call = k
    opt.log_every = 1000
    return opt, opt.optimize()


def test_sigterm_mid_epoch_checkpoints_next_step_and_resumes_exact(
        tmp_path):
    """SIGTERM mid-epoch under ``steps_per_call=K``: the preemption flag
    is honoured at the next BUNDLE EDGE with the next bundle shortened to
    ONE step — the just-in-time checkpoint lands ~1 step after the
    signal, not up to K steps later — and the restarted run resumes
    step-exact against the uninterrupted trajectory."""
    rs = np.random.RandomState(0)
    x = rs.randn(96, D).astype(np.float32)  # 6 batches of 16 per epoch
    y = (x @ rs.randn(D, 1)).astype(np.float32)
    _, ref = _sigterm_fit(x, y, 8, tmp_path / "ref")

    # signal lands while iteration 4's bundle-edge work runs (the K=2
    # grid is 2/4/6/...): without the shortened bundle the checkpoint
    # would wait for iteration 6
    opt1, _ = _sigterm_fit(x, y, 8, tmp_path / "ck", sigterm_at=3)
    stopped_at = opt1.final_state["iteration"]
    assert stopped_at == 5  # one step past the signal, not a full bundle
    import os

    assert os.path.isdir(tmp_path / "ck" / f"ckpt-{stopped_at}")

    _, resumed = _sigterm_fit(x, y, 8, tmp_path / "ck")
    for a, b in zip(_weights(resumed), _weights(ref)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)
