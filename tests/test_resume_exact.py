"""Exactly-once mid-epoch resume: a preempted-and-resumed run must train
on the SAME batch sequence as an uninterrupted run — the resumed epoch
fast-forwards past already-trained batches instead of replaying them.
The assertion is the strongest available: final weights match the
uninterrupted reference bit-for-bit-close (same batches, same per-
iteration rng folds, same momentum trajectory)."""

import numpy as np

import jax

from bigdl_tpu import nn
from bigdl_tpu.data.dataset import ArrayDataSet
from bigdl_tpu.nn.criterion import MSECriterion
from bigdl_tpu.optim.optim_method import SGD
from bigdl_tpu.optim.optimizer import Optimizer
from bigdl_tpu.optim.trigger import Trigger

N, D = 80, 4  # 5 batches of 16 per epoch


def _data():
    rs = np.random.RandomState(0)
    x = rs.randn(N, D).astype(np.float32)
    y = (x @ rs.randn(D, 1)).astype(np.float32)
    return x, y


def _fit(x, y, n_iters, ckpt_dir=None):
    model = nn.Sequential([nn.Linear(D, 6), nn.Tanh(), nn.Linear(6, 1)])
    opt = Optimizer(model, ArrayDataSet(x, y), MSECriterion(),
                    batch_size=16, seed=3)
    opt.set_optim_method(SGD(learning_rate=0.1, momentum=0.9))
    opt.set_end_when(Trigger.max_iteration(n_iters))
    if ckpt_dir is not None:
        opt.set_checkpoint(str(ckpt_dir), Trigger.several_iteration(1))
    opt.log_every = 1000
    return opt.optimize()


def _weights(trained):
    return [np.asarray(l) for l in
            jax.tree_util.tree_leaves(trained.variables["params"])]


def test_mid_epoch_resume_trains_each_batch_exactly_once(tmp_path):
    x, y = _data()
    ref = _fit(x, y, 8)  # uninterrupted: epoch 1 (5 batches) + 3 of epoch 2

    # interrupted at iteration 3 (mid-epoch 1), resumed to 8
    ckpt_dir = tmp_path / "ck"
    _fit(x, y, 3, ckpt_dir=ckpt_dir)
    resumed = _fit(x, y, 8, ckpt_dir=ckpt_dir)

    for a, b in zip(_weights(resumed), _weights(ref)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)


def test_resume_at_epoch_boundary_still_exact(tmp_path):
    x, y = _data()
    ref = _fit(x, y, 7)

    ckpt_dir = tmp_path / "ck"
    _fit(x, y, 5, ckpt_dir=ckpt_dir)  # exactly one full epoch
    resumed = _fit(x, y, 7, ckpt_dir=ckpt_dir)
    for a, b in zip(_weights(resumed), _weights(ref)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)
