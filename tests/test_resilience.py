"""``bigdl_tpu.resilience`` — fault injection, detection, retry policies,
and the training supervisor.

The load-bearing spec is ``test_faulted_run_matches_fault_free``: under
``step_fail`` + intermittent ``checkpoint_write_fail`` injection a training
run must reach the SAME final iteration as a fault-free run (recovering
only through shard-complete checkpoints), with the recovery visible in
``Metrics`` counters.  Everything else covers the layers that make that
possible.
"""

import json
import os

import numpy as np
import pytest

from bigdl_tpu.resilience import faults
from bigdl_tpu.resilience.detector import (Heartbeat, HeartbeatMonitor,
                                           StepWatchdog)
from bigdl_tpu.resilience.faults import (FaultInjector, FaultSpec,
                                         InjectedStepFailure,
                                         InjectedStorageError, parse_plan)
from bigdl_tpu.resilience.retry import (FailureCause, FailurePolicy,
                                        PoisonedStepError, RetryPolicy,
                                        TopologyChangedError, classify)


@pytest.fixture(autouse=True)
def _clear_faults():
    yield
    faults.clear()


class _LogCapture:
    """Collects records from a ``bigdl_tpu.*`` logger directly — the
    package root has ``propagate=False``, so pytest's caplog (root-logger
    handler) never sees them."""

    def __init__(self, name):
        import logging

        self.records = []
        self._logger = logging.getLogger(name)
        self._handler = logging.Handler()
        self._handler.emit = self.records.append

    def __enter__(self):
        self._logger.addHandler(self._handler)
        return self

    def __exit__(self, *a):
        self._logger.removeHandler(self._handler)


# ---------------------------------------------------------------------------
# faults: deterministic injection


def test_fault_plan_is_deterministic():
    """Two injectors over the same plan fire at identical invocations —
    the property every recovery test depends on."""
    def pattern():
        inj = FaultInjector([
            FaultSpec("step_fail", probability=0.3, seed=7, max_fires=100),
            FaultSpec("storage_io_fail", every=5),
        ])
        for i in range(100):
            try:
                inj.fire("step_fail", step=i)
            except InjectedStepFailure:
                pass
            try:
                inj.fire("storage_io_fail")
            except InjectedStorageError:
                pass
        return inj.events

    a, b = pattern(), pattern()
    assert a == b
    assert any(p == "step_fail" for p, _, _ in a)
    # every=5 fires exactly on every 5th invocation
    assert [c for p, _, c in a if p == "storage_io_fail"] == \
        list(range(4, 100, 5))


def test_fault_at_step_fires_once_by_default():
    """at_step defaults to max_fires=1: a resumed run REPLAYS the step and
    must not die on it forever."""
    inj = FaultInjector([FaultSpec("step_fail", at_step=3)])
    with pytest.raises(InjectedStepFailure):
        inj.fire("step_fail", step=3)
    inj.fire("step_fail", step=3)  # replay: no fire
    assert len(inj.events) == 1


def test_fault_env_plan_parsing():
    specs = parse_plan(
        "step_fail@5; checkpoint_write_fail:p=0.5:seed=2 ;"
        "slow_host@3:delay=0.01;storage_io_fail:every=4:max=2")
    by_point = {s.point: s for s in specs}
    assert by_point["step_fail"].at_step == 5
    assert by_point["step_fail"].max_fires == 1
    assert by_point["checkpoint_write_fail"].probability == 0.5
    assert by_point["checkpoint_write_fail"].seed == 2
    assert by_point["slow_host"].delay_s == 0.01
    assert by_point["slow_host"].action == "sleep"
    assert by_point["storage_io_fail"].every == 4
    assert by_point["storage_io_fail"].max_fires == 2
    with pytest.raises(ValueError, match="unknown fault point"):
        parse_plan("bogus_point@1")
    with pytest.raises(ValueError, match="unknown fault option"):
        parse_plan("step_fail:frequency=2")


def test_storage_io_fault_reaches_storage_seam(tmp_path):
    from bigdl_tpu.utils import storage

    faults.install([FaultSpec("storage_io_fail", every=1, max_fires=1)])
    with pytest.raises(InjectedStorageError):
        storage.open_file(str(tmp_path / "x"), "wb")
    # max_fires exhausted: the seam works again
    with storage.open_file(str(tmp_path / "x"), "wb") as f:
        f.write(b"ok")


# ---------------------------------------------------------------------------
# retry: backoff math + classification


def test_backoff_exponential_capped_and_deterministic():
    p = RetryPolicy(max_retries=10, base_s=1.0, multiplier=2.0,
                    max_s=8.0, jitter=0.25, seed=5)
    seq = [p.backoff(a) for a in range(1, 8)]
    assert seq == [p.backoff(a) for a in range(1, 8)]  # deterministic
    for a, v in enumerate(seq, start=1):
        raw = min(8.0, 2.0 ** (a - 1))
        assert raw * 0.75 <= v <= raw * 1.25
    # capped: late attempts all sit at max_s (± jitter)
    assert max(seq[4:]) <= 8.0 * 1.25
    assert RetryPolicy(jitter=0.0, base_s=3.0).backoff(1) == 3.0


def test_retry_call_retries_then_raises():
    p = RetryPolicy(max_retries=2, base_s=0.0, jitter=0.0)
    calls = []

    def flaky(fail_times):
        calls.append(1)
        if len(calls) <= fail_times:
            raise OSError("blip")
        return "ok"

    assert p.call(flaky, 2, sleep=lambda s: None) == "ok"
    calls.clear()
    with pytest.raises(OSError):
        p.call(flaky, 99, sleep=lambda s: None)
    assert len(calls) == 3  # initial + max_retries


def test_classify_causes():
    assert classify(OSError("x")) is FailureCause.TRANSIENT_STORAGE
    assert classify(InjectedStorageError("storage_io_fail")) \
        is FailureCause.TRANSIENT_STORAGE
    assert classify(InjectedStepFailure("step_fail")) \
        is FailureCause.STEP_FAILURE
    assert classify(PoisonedStepError("nan")) is FailureCause.POISONED_BATCH
    assert classify(RuntimeError("loss is NaN")) \
        is FailureCause.POISONED_BATCH
    assert classify(TopologyChangedError("2->3")) \
        is FailureCause.TOPOLOGY_CHANGE
    assert classify(faults.ProcessKilledError("process_kill")) \
        is FailureCause.PROCESS_FAILURE
    assert classify(ValueError("shape")) is FailureCause.UNKNOWN
    # wrapped errors classify by the cause chain (e.g. AsyncCheckpointer's
    # escalation RuntimeError around a storage error)
    try:
        raise RuntimeError("async checkpoint writes failed; escalating") \
            from OSError("gcs 503")
    except RuntimeError as wrapped:
        assert classify(wrapped) is FailureCause.TRANSIENT_STORAGE


def test_failure_policy_per_cause():
    fp = FailurePolicy()
    assert fp.policy_for(FailureCause.TRANSIENT_STORAGE).max_retries > \
        fp.policy_for(FailureCause.POISONED_BATCH).max_retries
    assert fp.policy_for(FailureCause.TOPOLOGY_CHANGE).max_retries == 0
    custom = FailurePolicy(by_cause={
        FailureCause.POISONED_BATCH: RetryPolicy(max_retries=9)})
    assert custom.policy_for(FailureCause.POISONED_BATCH).max_retries == 9


# ---------------------------------------------------------------------------
# detector: heartbeats (phi-accrual) + watchdog — injected clocks, no sleeps


def test_heartbeat_phi_accrual(tmp_path):
    now = [100.0]
    clock = lambda: now[0]  # noqa: E731
    hb = Heartbeat(str(tmp_path), process_index=1, clock=clock)
    mon = HeartbeatMonitor(str(tmp_path), clock=clock)
    for _ in range(10):  # regular 1s beats
        hb.beat()
        mon.poll()
        now[0] += 1.0
    assert mon.phi(1) < 3.0        # just-on-time: low suspicion
    assert mon.suspects(threshold=8.0) == []
    now[0] += 60.0                 # silence: suspicion accrues
    assert mon.phi(1) > 8.0
    assert mon.suspects(threshold=8.0) == [1]
    assert mon.phi(99) == float("inf")  # never seen


def test_heartbeat_over_remote_storage():
    """Heartbeats route through the utils.storage seam, so a gs://-style
    shared bucket works exactly like a shared filesystem (memory:// gives
    the remote semantics without a network)."""
    pytest.importorskip("fsspec")
    now = [100.0]
    root = f"memory://hb{os.getpid()}/run"
    hb = Heartbeat(root, process_index=3, clock=lambda: now[0])
    mon = HeartbeatMonitor(root, clock=lambda: now[0])
    for _ in range(5):
        hb.beat()
        mon.poll()
        now[0] += 1.0
    assert mon.phi(3) < 3.0
    now[0] += 120.0
    assert mon.suspects(threshold=8.0) == [3]


def test_heartbeat_monitor_ignores_torn_files(tmp_path):
    (tmp_path / "hb-00007.json").write_text("{not json")
    mon = HeartbeatMonitor(str(tmp_path))
    assert mon.poll() == {}


def test_watchdog_nan_streak_raises_poisoned():
    wd = StepWatchdog(nan_patience=3)
    wd.observe_loss(0, 1.0)
    wd.observe_loss(1, float("nan"))
    wd.observe_loss(2, float("inf"))
    with pytest.raises(PoisonedStepError):
        wd.observe_loss(3, float("nan"))
    wd.observe_loss(4, float("nan"))  # streak reset after raising
    wd.observe_loss(5, 0.5)
    wd.observe_loss(6, float("nan"))  # finite value also resets


def test_watchdog_hang_detection():
    now = [0.0]
    wd = StepWatchdog(step_timeout_s=10.0, clock=lambda: now[0])
    hangs = []
    wd.on_hang = lambda step, dur: hangs.append((step, dur))
    wd.step_started(4)
    now[0] = 5.0
    assert not wd.check()
    now[0] = 11.0
    assert wd.check() and hangs == [(4, 11.0)]
    assert wd.check()              # still hung; on_hang fires once
    assert len(hangs) == 1
    wd.observe_loss(4, 1.0)        # completion clears the in-flight step
    assert not wd.hung()


# ---------------------------------------------------------------------------
# training under injection — the acceptance spec


def _linreg_optimizer(ckpt_dir, n_iters, seed=3):
    from bigdl_tpu import nn, optim
    from bigdl_tpu.data.dataset import ArrayDataSet

    rs = np.random.RandomState(0)
    x = rs.rand(64, 4).astype(np.float32)
    y = x @ np.asarray([[1.0], [2.0], [-1.0], [0.5]], np.float32)
    opt = (optim.Optimizer(nn.Linear(4, 1), ArrayDataSet(x, y),
                           nn.MSECriterion(), batch_size=16, seed=seed)
           .set_optim_method(optim.SGD(learning_rate=0.2))
           .set_end_when(optim.Trigger.max_iteration(n_iters)))
    opt.set_checkpoint(ckpt_dir, optim.Trigger.several_iteration(2))
    opt.log_every = 100
    return opt


def _fast_engine(retry_times=3):
    from bigdl_tpu.runtime.engine import EngineConfig, init_engine

    init_engine(EngineConfig(failure_retry_times=retry_times,
                             failure_retry_interval_s=0.01,
                             failure_policy=FailurePolicy(
                                 max_restarts=retry_times,
                                 default_retry=RetryPolicy(
                                     max_retries=retry_times, base_s=0.01,
                                     max_s=0.05),
                                 by_cause={c: RetryPolicy(
                                     max_retries=retry_times, base_s=0.01,
                                     max_s=0.05) for c in FailureCause})))


def test_faulted_run_matches_fault_free(tmp_path):
    """step_fail at step 5 + intermittent checkpoint_write_fail: the run
    completes with the SAME final iteration and bit-identical weights as
    the fault-free run, resuming only from complete checkpoints, and the
    recovery shows up in Metrics counters."""
    _fast_engine()
    faults.clear()
    opt_a = _linreg_optimizer(str(tmp_path / "ck_a"), 8)
    trained_a = opt_a.optimize()

    inj = faults.install([
        FaultSpec("step_fail", at_step=5),
        FaultSpec("checkpoint_write_fail", probability=0.5, seed=1,
                  max_fires=2),
    ])
    opt_b = _linreg_optimizer(str(tmp_path / "ck_b"), 8)
    trained_b = opt_b.optimize()

    assert [p for p, _, _ in inj.events].count("step_fail") == 1
    assert any(p == "checkpoint_write_fail" for p, _, _ in inj.events)
    assert opt_a.final_state["iteration"] == 8
    assert opt_b.final_state["iteration"] == 8
    wa = np.asarray(trained_a.variables["params"]["weight"])
    wb = np.asarray(trained_b.variables["params"]["weight"])
    np.testing.assert_array_equal(wa, wb)
    assert opt_b.metrics.counter("recoveries_total") >= 1
    by_cause = {k: v for k, v in opt_b.metrics.counters.items()
                if k.startswith("retries_by_cause.")}
    assert sum(by_cause.values()) == opt_b.metrics.counter("recoveries_total")
    assert opt_b.metrics.counter("time_lost_to_recovery_s") > 0
    assert "recoveries_total" in opt_b.metrics.summary()
    # the fault-free run recovered nothing
    assert opt_a.metrics.counter("recoveries_total") == 0


def test_supervisor_restarts_from_checkpoint(tmp_path):
    """With in-run retries disabled (retry_times=0) a step failure escapes
    optimize(); the Supervisor classifies it, restarts, and the restarted
    run resumes from the newest complete checkpoint to the end."""
    from bigdl_tpu.resilience.supervisor import Supervisor

    _fast_engine(retry_times=0)
    faults.install([FaultSpec("step_fail", at_step=5)])
    opt = _linreg_optimizer(str(tmp_path / "ck"), 8)
    policy = FailurePolicy(
        max_restarts=2,
        by_cause={FailureCause.STEP_FAILURE: RetryPolicy(
            max_retries=2, base_s=0.0, jitter=0.0)})
    sup = Supervisor(opt, policy=policy, sleep=lambda s: None)
    trained = sup.run()
    assert trained is not None
    assert opt.final_state["iteration"] == 8
    assert sup.restarts_total == 1
    assert opt.metrics.counter("recoveries_total") == 1
    assert opt.metrics.counter("retries_by_cause.step_failure") == 1
    assert opt.watchdog is not None  # supervisor installed a watchdog


def test_supervisor_exhausts_policy_and_raises(tmp_path):
    from bigdl_tpu.resilience.supervisor import Supervisor

    _fast_engine(retry_times=0)
    faults.install([FaultSpec("step_fail", at_step=5, max_fires=100)])
    opt = _linreg_optimizer(str(tmp_path / "ck"), 8)
    policy = FailurePolicy(
        max_restarts=2,
        by_cause={FailureCause.STEP_FAILURE: RetryPolicy(
            max_retries=99, base_s=0.0, jitter=0.0)})
    with pytest.raises(InjectedStepFailure):
        Supervisor(opt, policy=policy, sleep=lambda s: None).run()


def test_elastic_resume_reshards_epoch_on_process_count_change(tmp_path):
    """A checkpoint recorded at a different process_count must NOT apply
    its mid-epoch skip verbatim (the per-process batch plan changed):
    the epoch continues on a RE-SHARDED plan over its remaining examples
    — nothing replays, nothing is dropped (docs/distributed_training.md
    §Elastic resume)."""
    from bigdl_tpu.optim import checkpoint as ckpt

    _fast_engine()
    faults.clear()
    d = str(tmp_path / "ck")
    opt1 = _linreg_optimizer(d, 6)
    opt1.optimize()
    latest = ckpt.latest_checkpoint(d)
    manifest_path = os.path.join(latest, "manifest.json")
    manifest = json.load(open(manifest_path))
    assert manifest["driver_state"]["process_count"] == 1  # recorded
    # forge a 2-process origin with a mid-epoch skip pending
    manifest["driver_state"]["process_count"] = 2
    manifest["driver_state"]["epoch_batch"] = 2
    json.dump(manifest, open(manifest_path, "w"))

    opt2 = _linreg_optimizer(d, 10)
    with _LogCapture("bigdl_tpu.optim") as cap:
        opt2.optimize()
    assert opt2.final_state["iteration"] == 10
    assert opt2.metrics.counter("elastic_resumes_total") == 1
    assert opt2.metrics.counter("elastic_resharded_total") == 1
    assert any("elastic resume" in r.getMessage()
               and "process_count=2" in r.getMessage()
               and "re-sharded" in r.getMessage()
               for r in cap.records)

    # same process_count: the skip applies, no elastic fallback
    opt3 = _linreg_optimizer(d, 12)
    opt3.optimize()
    assert opt3.metrics.counter("elastic_resumes_total") == 0


def test_elastic_resume_replays_epoch_when_dataset_cannot_reshard(
        tmp_path):
    """Datasets without ``resharded_batches`` keep the conservative
    fallback: the epoch replays from its start with an explicit warning
    — batches re-trained, never silently dropped."""
    from bigdl_tpu.data.dataset import DataSet
    from bigdl_tpu.optim import checkpoint as ckpt

    class _NoReshard(DataSet):
        def __init__(self, inner):
            self._inner = inner

        def size(self):
            return self._inner.size()

        def batches(self, *a, **kw):
            return self._inner.batches(*a, **kw)

    _fast_engine()
    faults.clear()
    d = str(tmp_path / "ck")
    opt1 = _linreg_optimizer(d, 6)
    opt1.optimize()
    manifest_path = os.path.join(ckpt.latest_checkpoint(d),
                                 "manifest.json")
    manifest = json.load(open(manifest_path))
    manifest["driver_state"]["process_count"] = 2
    manifest["driver_state"]["epoch_batch"] = 2
    json.dump(manifest, open(manifest_path, "w"))

    opt2 = _linreg_optimizer(d, 10)
    opt2.dataset = _NoReshard(opt2.dataset)
    with _LogCapture("bigdl_tpu.optim") as cap:
        opt2.optimize()
    assert opt2.final_state["iteration"] == 10
    assert opt2.metrics.counter("elastic_resumes_total") == 1
    assert opt2.metrics.counter("elastic_resharded_total") == 0
    assert any("REPLAYS from its start" in r.getMessage()
               for r in cap.records)


def test_estimator_fault_tolerance_knob(tmp_path):
    from bigdl_tpu import nn, optim
    from bigdl_tpu.estimator import Estimator

    _fast_engine(retry_times=0)
    faults.install([FaultSpec("step_fail", at_step=2)])
    est = Estimator.from_module(
        lambda cfg: nn.Sequential([nn.Linear(4, 8), nn.ReLU(),
                                   nn.Linear(8, 1)]),
        lambda cfg: optim.SGD(learning_rate=0.1),
        lambda cfg: nn.MSECriterion())
    rs = np.random.RandomState(1)
    x = rs.rand(64, 4).astype(np.float32)
    y = x.sum(1, keepdims=True).astype(np.float32)
    stats = est.fit((x, y), epochs=2, batch_size=16,
                    checkpoint_path=str(tmp_path / "ck"),
                    fault_tolerance=FailurePolicy(
                        max_restarts=2,
                        by_cause={FailureCause.STEP_FAILURE: RetryPolicy(
                            max_retries=2, base_s=0.0, jitter=0.0)}))
    assert stats["recoveries_total"] == 1
    assert est.predict(x).shape == (64, 1)


# ---------------------------------------------------------------------------
# async checkpoint escalation + storage visibility


def test_async_checkpointer_escalates_failure_streak(tmp_path):
    from bigdl_tpu.optim.checkpoint import AsyncCheckpointer

    faults.install([FaultSpec("checkpoint_write_fail", probability=1.0,
                              max_fires=10)])
    ac = AsyncCheckpointer(escalate_after=3)
    kw = dict(flat_params=np.ones(3), opt_state={}, model_state={},
              driver_state={})
    for step in range(3):  # three swallowed failures
        ac.submit(str(tmp_path / "ck"), step, **kw)
    with pytest.raises(RuntimeError, match="escalating"):
        ac.submit(str(tmp_path / "ck"), 3, **kw)
    # a success resets the streak
    faults.clear()
    ac.submit(str(tmp_path / "ck"), 4, **kw)
    ac.wait()
    assert ac.consecutive_failures == 0


def test_remove_tree_swallow_path_logs_warning(monkeypatch):
    from bigdl_tpu.utils import storage

    class FakeFS:
        def rm(self, p, recursive=False):
            raise PermissionError("403 forbidden")

    monkeypatch.setattr(storage, "_fs_path",
                        lambda path: (FakeFS(), path))
    with _LogCapture("bigdl_tpu.storage") as cap:
        storage.remove_tree("memory://bucket/ckpt-2", ignore_errors=True)
    assert any("NOT being reclaimed" in r.getMessage()
               for r in cap.records)
    with pytest.raises(PermissionError):
        storage.remove_tree("memory://bucket/ckpt-2", ignore_errors=False)


# ---------------------------------------------------------------------------
# records cache freshness (satellite): memory:// remote


def test_records_cache_refetches_on_remote_change(tmp_path, monkeypatch):
    pytest.importorskip("fsspec")
    from bigdl_tpu.data.records import RecordDataSet, write_records

    monkeypatch.setenv("BIGDL_TPU_RECORD_CACHE", str(tmp_path / "cache"))
    uri = f"memory://recfresh{os.getpid()}/train.rec"
    x1 = np.arange(12, dtype=np.float32).reshape(6, 2)
    y1 = np.arange(6, dtype=np.int32)
    write_records(uri, {"x": x1, "y": y1})
    ds = RecordDataSet(uri)
    assert ds.size() == 6

    # overwrite the remote object: a new RecordDataSet must see fresh data
    x2 = np.ones((9, 2), np.float32)
    y2 = np.zeros(9, np.int32)
    write_records(uri, {"x": x2, "y": y2})
    ds2 = RecordDataSet(uri)
    assert ds2.size() == 9
    mb = next(iter(ds2.batches(4, shuffle=False)))
    np.testing.assert_array_equal(mb["input"], x2[:4])


# ---------------------------------------------------------------------------
# serving degradation


class _FakeModel:
    def __init__(self, fail=False, scale=1.0):
        self.fail = fail
        self.scale = scale

    def predict(self, x):
        if self.fail:
            raise RuntimeError("replica down")
        return np.asarray(x) * self.scale


def test_serving_falls_back_to_last_good_model():
    from bigdl_tpu.serving.server import ServingConfig, ServingServer

    primary = _FakeModel(scale=2.0)
    srv = ServingServer(primary, ServingConfig(
        batch_timeout_s=0.001, degraded_after_failures=2))
    srv.set_fallback_model(_FakeModel(scale=1.0))
    srv.start()
    try:
        rid = srv.enqueue(np.ones((1, 2), np.float32))
        np.testing.assert_array_equal(srv.query(rid, timeout=10), 2.0)
        primary.fail = True
        # failures answered by the fallback, then degraded mode
        for _ in range(3):
            rid = srv.enqueue(np.ones((1, 2), np.float32))
            np.testing.assert_array_equal(srv.query(rid, timeout=10), 1.0)
        assert srv.degraded
        assert srv.stats["fallback_batches"] >= 3
        # replica restarted: reload clears degradation
        srv.reload_model(_FakeModel(scale=3.0))
        rid = srv.enqueue(np.ones((1, 2), np.float32))
        np.testing.assert_array_equal(srv.query(rid, timeout=10), 3.0)
        assert not srv.degraded
    finally:
        srv.stop()


def test_serving_sheds_load_when_degraded_without_fallback():
    from bigdl_tpu.serving.server import (ServiceUnavailableError,
                                          ServingConfig, ServingServer)

    model = _FakeModel(fail=True)
    srv = ServingServer(model, ServingConfig(
        batch_timeout_s=0.001, degraded_after_failures=2,
        degraded_probe_interval_s=30.0))
    srv.start()
    try:
        # enqueue-then-query serializes the batches: two back-to-back
        # enqueues can coalesce into ONE dynamic batch (= one failure),
        # which would never reach degraded_after_failures=2
        for _ in range(2):
            rid = srv.enqueue(np.ones((1, 2), np.float32))
            with pytest.raises(RuntimeError, match="replica down"):
                srv.query(rid, timeout=10)
        assert srv.degraded
        # first post-degradation enqueue is the half-open PROBE (admitted,
        # still failing); the next within the interval is shed
        rid = srv.enqueue(np.ones((1, 2), np.float32))
        with pytest.raises(RuntimeError, match="replica down"):
            srv.query(rid, timeout=10)
        with pytest.raises(ServiceUnavailableError):
            srv.enqueue(np.ones((1, 2), np.float32))
        assert srv.stats["shed_requests"] == 1

        # the model recovers: the next probe clears degradation entirely
        model.fail = False
        srv._last_probe_t = 0.0  # force the probe window open (no sleeps)
        rid = srv.enqueue(np.ones((1, 2), np.float32))
        np.testing.assert_array_equal(srv.query(rid, timeout=10), 1.0)
        assert not srv.degraded
        srv.enqueue(np.ones((1, 2), np.float32))  # normal admission again
    finally:
        srv.stop()
