"""Fused multi-step execution (docs/performance.md): K training steps
compiled as one ``lax.scan`` XLA program.

The load-bearing invariant: bundle size is a PURE dispatch-granularity
knob — ``steps_per_call=K`` must produce a byte-identical loss trajectory
to ``steps_per_call=1`` from the same seed, including the remainder bundle
at an epoch tail, mid-epoch resume on and off the bundle grid, and
trigger-edge-clamped partial bundles.  The per-step PRNG derives from the
on-device step counter (``fold_in(base_key, step)``), so bundling can
never change what a step computes.
"""

import os

import numpy as np
import pytest

import jax

from bigdl_tpu import nn, optim
from bigdl_tpu.data import ArrayDataSet
from bigdl_tpu.optim import checkpoint as ckpt_mod
from bigdl_tpu.runtime.engine import Engine


def synthetic(n=320, d=12, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, d) * 3
    y = rng.randint(0, classes, size=n)
    x = centers[y] + rng.randn(n, d)
    return x.astype(np.float32), y.astype(np.int32)


def mlp(d=12, classes=3):
    return nn.Sequential([
        nn.Linear(d, 32), nn.ReLU(), nn.Dropout(0.1),
        nn.Linear(32, classes), nn.LogSoftMax(),
    ])


def run_driver(tmp_path, tag, steps_per_call, end_when, dataset=None,
               ckpt_dir=None, ckpt_trigger=None, seed=11, watchdog=None,
               batch_size=32):
    """One driver run; returns the Optimizer (its summary dir holds the
    per-step loss curve)."""
    Engine.reset()
    x, y = synthetic()
    ds = dataset if dataset is not None else ArrayDataSet(x, y)
    opt = optim.Optimizer(mlp(), ds, nn.ClassNLLCriterion(),
                          batch_size=batch_size, seed=seed)
    opt.steps_per_call = steps_per_call
    opt.set_optim_method(optim.SGD(learning_rate=0.1, momentum=0.9))
    opt.set_end_when(end_when)
    opt.set_train_summary(str(tmp_path / tag))
    if ckpt_dir is not None:
        opt.set_checkpoint(ckpt_dir,
                           ckpt_trigger or optim.Trigger.every_epoch())
    if watchdog is not None:
        opt.watchdog = watchdog
    opt.optimize()
    return opt


def loss_curve(opt):
    return opt._train_summary.read_scalar("loss")


class TestBundleParity:
    def test_k4_byte_identical_to_k1_including_remainder(self, tmp_path):
        """320 samples / batch 32 = 10 steps per epoch: K=4 bundles as
        4+4+2 — the epoch tail is a remainder bundle — over 2 epochs.
        The per-step loss curves must be EXACTLY equal (same floats),
        not merely close."""
        end = optim.Trigger.max_epoch(2)
        k1 = run_driver(tmp_path, "k1", 1, end)
        k4 = run_driver(tmp_path, "k4", 4, end)
        c1, c4 = loss_curve(k1), loss_curve(k4)
        assert len(c1) == 20 and c1 == c4
        # the remainder bundle really happened: 10 % 4 != 0
        assert k1.final_state["iteration"] == 20

    def test_trigger_edges_clamp_bundles_exactly(self, tmp_path):
        """Iteration-structured triggers land on their exact step under
        bundling: several_iteration(6) checkpoints at 6 and 12 with K=4
        (6 is OFF the 4-grid), and max_iteration(14) stops at exactly
        14 — no overshoot to a bundle edge."""
        d = str(tmp_path / "ck")
        opt = run_driver(tmp_path, "clamped", 4,
                         optim.Trigger.max_iteration(14), ckpt_dir=d,
                         ckpt_trigger=optim.Trigger.several_iteration(6))
        assert opt.final_state["iteration"] == 14
        names = sorted(p for p in os.listdir(d) if p.startswith("ckpt-"))
        assert names == ["ckpt-12", "ckpt-6"]
        # and the clamped run is still byte-identical to K=1
        ref = run_driver(tmp_path, "clamped-ref", 1,
                         optim.Trigger.max_iteration(14))
        assert loss_curve(opt) == loss_curve(ref)

    @pytest.mark.parametrize("ckpt_every", [4, 6])
    def test_mid_epoch_resume_on_and_off_grid(self, tmp_path, ckpt_every):
        """Resume from a mid-epoch checkpoint that sits ON the bundle grid
        (every 4) and OFF it (every 6): the first post-resume bundle
        shortens to re-align, and the resumed trajectory is byte-identical
        to both an uninterrupted K=4 run and the K=1 reference."""
        ref = run_driver(tmp_path, f"ref{ckpt_every}", 1,
                         optim.Trigger.max_iteration(16))
        d = str(tmp_path / f"ck{ckpt_every}")
        run_driver(tmp_path, f"a{ckpt_every}", 4,
                   optim.Trigger.max_iteration(ckpt_every + 1), ckpt_dir=d,
                   ckpt_trigger=optim.Trigger.several_iteration(ckpt_every))
        latest = ckpt_mod.latest_checkpoint(d)
        assert latest.endswith(f"ckpt-{ckpt_every}")
        resumed = run_driver(tmp_path, f"b{ckpt_every}", 4,
                             optim.Trigger.max_iteration(16), ckpt_dir=d,
                             ckpt_trigger=optim.Trigger.several_iteration(
                                 ckpt_every))
        assert resumed.final_state["iteration"] == 16
        got = dict(loss_curve(resumed))
        want = dict(loss_curve(ref))
        for step in range(ckpt_every + 1, 17):
            assert got[step] == want[step], (step, got[step], want[step])

    def test_remainder_programs_cached_per_size(self):
        """Partial bundles compile once per distinct K' and are reused —
        the bundle cache holds one program per size, not one per call."""
        Engine.reset()
        x, y = synthetic()
        o = optim.Optimizer(mlp(), ArrayDataSet(x, y),
                            nn.ClassNLLCriterion(), batch_size=32, seed=11)
        o.steps_per_call = 4
        o.log_every = 100
        o.set_end_when(optim.Trigger.max_epoch(3))
        trained = o.optimize()
        # 10 steps/epoch at K=4 -> bundle sizes 4 and the 2-step epoch tail
        assert set(trained._engine._bundle_cache.keys()) == {4, 2}


class _PoisonOnce(ArrayDataSet):
    """NaN-poisons one batch of epoch 1 the first time it is served —
    the poisoned-batch (not infrastructure) failure mode."""

    fired = False
    poison_index = 5

    def batches(self, *a, **kw):
        for i, mb in enumerate(super().batches(*a, **kw)):
            if (kw.get("epoch") == 1 and i == self.poison_index
                    and not _PoisonOnce.fired):
                _PoisonOnce.fired = True
                mb = dict(mb, input=np.full_like(mb["input"], np.nan))
            yield mb


class TestBundleRecovery:
    def test_poisoned_bundle_rewinds_to_bundle_start_snapshot(
            self, tmp_path):
        """A NaN inside bundle [4, 8) trips the watchdog at the bundle's
        sync point; the retry loop restores from the bundle-START
        checkpoint (ckpt-4 — checkpoints quantize to bundle edges) and
        replays.  The recovered trajectory matches the clean K=1 run
        everywhere except the single poisoned serving."""
        from bigdl_tpu.resilience.detector import StepWatchdog

        Engine.reset()
        Engine.get().config.failure_retry_interval_s = 0.05
        x, y = synthetic()
        _PoisonOnce.fired = False
        d = str(tmp_path / "ck")
        opt = run_driver(
            tmp_path, "poisoned", 4, optim.Trigger.max_iteration(12),
            dataset=_PoisonOnce(x, y), ckpt_dir=d,
            ckpt_trigger=optim.Trigger.several_iteration(4),
            watchdog=StepWatchdog(nan_patience=1))
        assert _PoisonOnce.fired
        assert opt.metrics.counter("recoveries_total") == 1
        assert opt.metrics.counter("retries_by_cause.poisoned_batch") == 1
        assert opt.final_state["iteration"] == 12
        # post-rewind steps replay from the bundle-start snapshot: the
        # tail of the curve is byte-identical to a clean K=1 run
        ref = run_driver(tmp_path, "poisoned-ref", 1,
                         optim.Trigger.max_iteration(12))
        got, want = dict(loss_curve(opt)), dict(loss_curve(ref))
        for step in range(9, 13):
            assert got[step] == want[step]

    def test_fault_injection_fires_inside_bundle_range(self, tmp_path):
        """``step_fail@5`` fires at step 5 even though the host only sees
        bundle edges 0/4/8 — fire_bundle walks the step range — and the
        driver recovers from the last bundle-edge checkpoint."""
        from bigdl_tpu.resilience import faults

        Engine.reset()
        Engine.get().config.failure_retry_interval_s = 0.05
        inj = faults.install(faults.parse_plan("step_fail@5"))
        try:
            d = str(tmp_path / "ck")
            opt = run_driver(
                tmp_path, "inject", 4, optim.Trigger.max_iteration(12),
                ckpt_dir=d,
                ckpt_trigger=optim.Trigger.several_iteration(4))
        finally:
            faults.clear()
        assert ("step_fail", 5, 5) in inj.events
        assert opt.metrics.counter("recoveries_total") == 1
        assert opt.final_state["iteration"] == 12


class TestBundleKnobsAndObs:
    def test_env_and_config_wiring(self, monkeypatch):
        from bigdl_tpu.runtime.engine import EngineConfig

        monkeypatch.setenv("BIGDL_TPU_STEPS_PER_CALL", "8")
        assert EngineConfig.from_env().steps_per_call == 8
        monkeypatch.setenv("BIGDL_TPU_STEPS_PER_CALL", "auto")
        assert EngineConfig.from_env().steps_per_call == "auto"
        with pytest.raises(ValueError):
            monkeypatch.setenv("BIGDL_TPU_STEPS_PER_CALL", "fast")
            EngineConfig.from_env()

    def test_estimator_config_key(self, tmp_path):
        from bigdl_tpu.estimator import Estimator
        from bigdl_tpu.optim.optim_method import SGD

        x, y = synthetic(n=128)
        est = Estimator.from_module(
            lambda cfg: mlp(),
            lambda cfg: SGD(learning_rate=0.1),
            lambda cfg: nn.ClassNLLCriterion(),
            config={"steps_per_call": 4})
        stats = est.fit((x, y), epochs=2, batch_size=32)
        assert stats["epochs"] == 2
        res = est.evaluate((x, y), [optim.Top1Accuracy()], batch_size=32)
        assert res["Top1Accuracy"] > 0.6

    def test_auto_mode_picks_after_first_window(self, tmp_path):
        opt = run_driver(tmp_path, "auto", "auto",
                         optim.Trigger.max_epoch(3))
        assert opt._bundle_picked
        assert 1 <= opt._bundle_k <= 32
        assert opt.final_state["iteration"] == 30

    def test_invalid_steps_per_call_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="steps_per_call"):
            run_driver(tmp_path, "bad", "fast", optim.Trigger.max_epoch(1))

    def test_bundle_metrics_reach_prometheus(self, tmp_path):
        """train.dispatch_gap_s histogram + bundle-size / in-flight gauges
        land in the registry and render as /metrics lines."""
        from bigdl_tpu.obs.export import render_prometheus

        opt = run_driver(tmp_path, "metrics", 4, optim.Trigger.max_epoch(2))
        summ = opt.metrics.summary()
        assert summ.get("train.dispatch_gap_s.count", 0) > 0
        assert summ.get("train.bundle_size") == 2  # epoch-tail remainder
        assert "train.steps_in_flight" in summ
        text = render_prometheus(opt.metrics)
        assert "train_dispatch_gap_s_bucket" in text
        assert "train_bundle_size" in text
        assert "train_grad_norm_bucket" in text

    def test_watchdog_sees_every_step_of_a_bundle(self, tmp_path):
        """Per-step granularity survives bundling: the watchdog observes
        one loss per STEP, in order, not one per bundle."""
        from bigdl_tpu.resilience.detector import StepWatchdog

        seen = []

        class Spy(StepWatchdog):
            def observe_loss(self, step, loss):
                seen.append(step)
                super().observe_loss(step, loss)

        run_driver(tmp_path, "spy", 4, optim.Trigger.max_iteration(10),
                   watchdog=Spy(nan_patience=3))
        assert seen == list(range(10))
