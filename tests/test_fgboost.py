"""FGBoost tests — reference ppml/fl/fgboost federated GBT."""

import threading

import numpy as np
import pytest

from bigdl_tpu.ppml import (FGBoostClassifier, FGBoostRegression, FLClient,
                            FLServer)


def _friedman(rng, n):
    x = rng.rand(n, 5).astype(np.float32)
    y = (10 * np.sin(np.pi * x[:, 0] * x[:, 1]) + 20 * (x[:, 2] - 0.5) ** 2
         + 10 * x[:, 3] + 5 * x[:, 4]).astype(np.float32)
    return x, y


def test_local_regression_learns():
    rng = np.random.RandomState(0)
    x, y = _friedman(rng, 1500)
    xt, yt = _friedman(rng, 300)
    model = FGBoostRegression(n_trees=40, max_depth=4, learning_rate=0.2)
    model.fit(x, y)
    pred = model.predict(xt)
    base_mse = float(((yt - y.mean()) ** 2).mean())
    mse = float(((yt - pred) ** 2).mean())
    assert mse < 0.25 * base_mse, (mse, base_mse)


def test_local_classifier():
    rng = np.random.RandomState(1)
    x = rng.randn(1200, 4).astype(np.float32)
    y = ((x[:, 0] * x[:, 1] + x[:, 2]) > 0).astype(np.float32)
    model = FGBoostClassifier(n_trees=30, max_depth=4, learning_rate=0.3)
    model.fit(x[:1000], y[:1000])
    acc = (model.predict_class(x[1000:]) == y[1000:]).mean()
    assert acc > 0.85, acc
    proba = model.predict_proba(x[1000:])
    assert ((0 <= proba) & (proba <= 1)).all()


def test_save_load_roundtrip(tmp_path):
    rng = np.random.RandomState(2)
    x, y = _friedman(rng, 400)
    model = FGBoostRegression(n_trees=5, max_depth=3).fit(x, y)
    path = str(tmp_path / "gbt.npz")
    model.save(path)
    loaded = FGBoostRegression.load(path)
    np.testing.assert_allclose(model.predict(x), loaded.predict(x),
                               rtol=1e-6)
    assert loaded.objective == "squared"


def test_federated_two_parties_match_and_learn():
    """Two parties with disjoint halves must build IDENTICAL models whose
    quality approaches the pooled local fit."""
    rng = np.random.RandomState(3)
    x, y = _friedman(rng, 1600)
    xt, yt = _friedman(rng, 300)
    halves = [(x[:800], y[:800]), (x[800:], y[800:])]

    server = FLServer(world_size=2).start()
    models = [FGBoostRegression(n_trees=15, max_depth=4, learning_rate=0.2)
              for _ in range(2)]
    errs = [None, None]

    def party(i):
        try:
            client = FLClient(server.target, f"party{i}")
            models[i].fit(*halves[i], fl_client=client)
        except Exception as e:  # noqa: BLE001
            errs[i] = e

    threads = [threading.Thread(target=party, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    server.stop()
    assert errs == [None, None], errs

    # identical models on every party
    p0, p1 = models[0].predict(xt), models[1].predict(xt)
    np.testing.assert_allclose(p0, p1, rtol=1e-5, atol=1e-5)

    # and the federated model actually learned
    base_mse = float(((yt - y.mean()) ** 2).mean())
    mse = float(((yt - p0) ** 2).mean())
    assert mse < 0.4 * base_mse, (mse, base_mse)

    # pooled local reference: federated should be in the same ballpark
    pooled = FGBoostRegression(n_trees=15, max_depth=4,
                               learning_rate=0.2).fit(x, y)
    mse_pooled = float(((yt - pooled.predict(xt)) ** 2).mean())
    assert mse < 2.5 * mse_pooled, (mse, mse_pooled)


def test_sum_aggregation_is_exact_through_server():
    """Regression: '@sum'-tagged keys must aggregate as SUMS (the pytree
    flattening decorates key names, so substring matching is required)."""
    server = FLServer(world_size=2).start()
    results = [None, None]

    def party(i):
        c = FLClient(server.target, f"p{i}")
        results[i] = c.sync({"h@sum": np.full(3, float(i + 1), np.float32),
                             "avg": np.full(2, float(i + 1), np.float32)},
                            weight=1.0)

    threads = [threading.Thread(target=party, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    server.stop()
    for r in results:
        np.testing.assert_allclose(r["h@sum"], np.full(3, 3.0))   # 1+2
        np.testing.assert_allclose(r["avg"], np.full(2, 1.5))     # mean
