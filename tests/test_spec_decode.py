"""Speculative decoding — weight-shared block-sparse draft + single-call
verify (docs/serving.md §Speculative decoding).

Tier-1 specs: spec-on vs spec-off BYTE PARITY (greedy and seeded sample,
including requests admitted mid-flight — the acceptance rule emits only
target selections, so speculation must be invisible in the output), the
dense-twin (sparsity=0.0) acceptance rate pinned at exactly 1.0, the
zero-recompile mixed sweep with the draft/verify/draft-prefill programs
inside warmup()'s closed bucket set, the spec x ``kv_dtype="int8"``
token-parity budget, draft-side pages freed together with target pages
on cancel/disconnect (the page-leak regression spec), ``decode_pressure``
honesty under draft pages, the multi-query verify kernel's parity with
the gathered-jnp reference, and the ``serving.decode.spec_*`` metric +
sentinel surface.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.attention import Transformer
from bigdl_tpu.serving.decode_engine import (DecodeConfig, DecodeEngine,
                                             DecodeRequest, LMAdapter,
                                             SpecConfig)

BOS, EOS = 0, 1

SAMPLE_KW = dict(temperature=1.3, top_k=5, top_p=0.9)


@pytest.fixture(scope="module")
def lm():
    model = Transformer(vocab_size=32, hidden_size=16, num_heads=2,
                        num_layers=2, dropout=0.0, mode="lm")
    v = model.init(jax.random.PRNGKey(0),
                   np.arange(6, dtype=np.int32)[None])
    return model, v["params"]


def _engine(lm, spec=None, **over):
    model, params = lm
    kw = dict(slots=4, page_size=4, pages_per_slot=4, prompt_chunk=4,
              max_new_tokens=8, eos_id=EOS, prefill_batch=2)
    kw.update(over)
    cfg = DecodeConfig(speculative=spec, **kw)
    return DecodeEngine(LMAdapter(model, params, cap=cfg.cap), cfg)


def _prompts(ns=(3, 5, 9, 2, 7, 11), seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(2, 32, (n,)).astype(np.int32) for n in ns]


def _requests(prompts, temperature=0.0, **kw):
    return [DecodeRequest(tokens=p, temperature=temperature, seed=100 + i,
                          **kw) for i, p in enumerate(prompts)]


def _run(engine, reqs, stagger_at=None):
    split = stagger_at if stagger_at is not None else len(reqs)
    for r in reqs[:split]:
        engine.submit(r)
    if split < len(reqs):
        time.sleep(0.1)
        for r in reqs[split:]:
            engine.submit(r)
    return [r.wait(timeout=120) for r in reqs]


def _assert_same(got, want):
    for a, b in zip(got, want):
        assert a.tokens.tobytes() == b.tokens.tobytes()
        assert np.float32(a.logp) == np.float32(b.logp)
        assert a.finish_reason == b.finish_reason


# ---------------------------------------------------------------------------
# spec-on vs spec-off byte parity: speculation must be invisible
# ---------------------------------------------------------------------------

class TestSpecParity:
    def test_greedy_dense_twin_byte_identical_full_acceptance(self, lm):
        """sparsity=0.0 drafts with a bit-identical twin: every drafted
        token must be accepted (rejected == 0 — drafts past an
        eos/length finish are unadjudicated, not rejected) and the
        output must match the spec-off engine to the byte."""
        off = _engine(lm)
        try:
            want = _run(off, _requests(_prompts()))
        finally:
            off.stop()
        on = _engine(lm, spec=SpecConfig(k=3, sparsity=0.0))
        try:
            got = _run(on, _requests(_prompts()))
            _assert_same(got, want)
            st = on.stats
            assert st["spec_drafted"] > 0
            assert st["spec_accepted"] > 0
            assert st["spec_rejected"] == 0, (
                "a dense twin's drafts disagreed with its own target")
        finally:
            on.stop()

    def test_greedy_sparse_draft_byte_identical(self, lm):
        """A REAL sparse draft mispredicts — and the output still
        matches byte-for-byte, because emitted tokens are always the
        verify call's target selections; the draft only gates how many
        land per iteration."""
        off = _engine(lm)
        try:
            want = _run(off, _requests(_prompts()))
        finally:
            off.stop()
        on = _engine(lm, spec=SpecConfig(k=3, sparsity=0.5))
        try:
            got = _run(on, _requests(_prompts()))
            _assert_same(got, want)
        finally:
            on.stop()

    def test_seeded_sample_byte_identical(self, lm):
        """temperature>0: draft and verify share the counter-based
        fold_in(key, position) Gumbel streams, so the accepted stream
        (correction and resampled tail included) is the spec-off
        sampled stream to the byte."""
        off = _engine(lm)
        try:
            want = _run(off, _requests(_prompts(), **SAMPLE_KW))
        finally:
            off.stop()
        on = _engine(lm, spec=SpecConfig(k=3, sparsity=0.5))
        try:
            got = _run(on, _requests(_prompts(), **SAMPLE_KW))
            _assert_same(got, want)
            st = on.stats
            assert st["spec_accepted"] > 0, (
                "shared-Gumbel coupling broke: a 0.5-sparse draft "
                "should still agree sometimes")
        finally:
            on.stop()

    def test_chunk_verify_seeded_routes_to_scan_parity(self, lm):
        """Regression: the chunk verify's last-ulp logit drift is
        harmless under greedy argmax but flips top-k/top-p threshold
        masks (they are discontinuous in the logits), so a sampled
        iteration under verify_impl="chunk" must route to the scan
        tracing — byte parity holds for seeded sampling even on a
        chunk-configured engine, including after a prior greedy round
        reshuffled slot state."""
        off = _engine(lm)
        try:
            want_g = _run(off, _requests(_prompts()))
            want_s = _run(off, _requests(_prompts(), **SAMPLE_KW))
        finally:
            off.stop()
        on = _engine(lm, spec=SpecConfig(k=3, sparsity=0.5,
                                         verify_impl="chunk"))
        try:
            # greedy rides the chunk tracing: tokens exact, logp
            # allclose (the chunk contract)
            got_g = _run(on, _requests(_prompts()))
            for a, b in zip(got_g, want_g):
                assert a.tokens.tobytes() == b.tokens.tobytes()
                assert np.allclose(a.logp, b.logp, rtol=2e-5, atol=2e-5)
            # sampled routes to scan: byte parity, logp included
            _assert_same(_run(on, _requests(_prompts(), **SAMPLE_KW)),
                         want_s)
        finally:
            on.stop()

    def test_mid_flight_admission_parity(self, lm):
        """Requests admitted while earlier ones are mid-speculation
        join the next draft/verify iteration — and still match the
        static target-only reference byte-for-byte."""
        on = _engine(lm, spec=SpecConfig(k=3, sparsity=0.5))
        try:
            want = on.static_generate(_requests(_prompts(), **SAMPLE_KW))
            got = _run(on, _requests(_prompts(), **SAMPLE_KW),
                       stagger_at=3)
            _assert_same(got, want)
        finally:
            on.stop()


# ---------------------------------------------------------------------------
# zero-recompile sweep: draft + verify join the closed bucket set
# ---------------------------------------------------------------------------

def test_spec_sweep_zero_unexpected_recompiles(lm):
    from bigdl_tpu.obs.attr import recompile_sentinel
    from bigdl_tpu.optim.metrics import global_metrics

    sent = recompile_sentinel()
    eng = _engine(lm, spec=SpecConfig(k=3, sparsity=0.5))
    m = global_metrics()
    try:
        eng.warmup()
        before = m.counter("train.unexpected_recompiles_total")
        sent.mark_steady()
        rs = np.random.RandomState(7)
        reqs = [DecodeRequest(
            tokens=rs.randint(2, 32, (int(rs.randint(1, 12)),)).astype(
                np.int32),
            max_new_tokens=int(rs.randint(1, 9)),
            temperature=float(rs.rand() < 0.5) * 1.2,
            seed=i) for i in range(24)]
        _run(eng, reqs, stagger_at=12)
        after = m.counter("train.unexpected_recompiles_total")
        assert after - before == 0, (
            f"{after - before} unexpected XLA recompiles during the "
            "mixed sweep with speculation enabled")
    finally:
        sent.mark_warmup()
        eng.stop()


# ---------------------------------------------------------------------------
# spec x int8 KV pages: the token-parity budget
# ---------------------------------------------------------------------------

def test_spec_int8_token_parity_budget(lm):
    """int8 pages can't promise byte parity under speculation: a
    mismatch has already requantize-written the rejected tokens' K/V,
    and the monotone per-page scale floor remembers their magnitude.
    The budget: identical token streams, logp drift inside the int8
    bound."""
    off = _engine(lm, kv_dtype="int8")
    try:
        want = _run(off, _requests(_prompts()))
    finally:
        off.stop()
    on = _engine(lm, kv_dtype="int8", spec=SpecConfig(k=3, sparsity=0.5))
    try:
        got = _run(on, _requests(_prompts()))
        for a, b in zip(got, want):
            assert a.tokens.tolist() == b.tokens.tolist(), (
                "speculation changed the int8 greedy token stream")
            assert abs(a.logp - b.logp) < 0.15, (
                f"logp drift {abs(a.logp - b.logp):.4f} blows the int8 "
                "budget under speculation")
    finally:
        on.stop()


# ---------------------------------------------------------------------------
# acceptance accounting + the serving.decode.spec_* metric surface
# ---------------------------------------------------------------------------

def test_acceptance_accounting_and_metric_surface(lm):
    from bigdl_tpu.obs.export import DEFAULT_HELP, render_prometheus

    eng = _engine(lm, spec=SpecConfig(k=3, sparsity=0.5))
    try:
        _run(eng, _requests(_prompts()))
        st = eng.stats
        assert st["spec_drafted"] > 0
        # adjudicated tokens never exceed drafted; the remainder is
        # wasted work from eos/length truncation, not rejection
        assert st["spec_accepted"] + st["spec_rejected"] \
            <= st["spec_drafted"]
        # every accepted draft token was emitted (corrections and bonus
        # tokens add more)
        assert st["tokens"] >= st["spec_accepted"]
        text = render_prometheus(eng.metrics)
        for fam in ("serving_decode_spec_drafted_tokens",
                    "serving_decode_spec_accepted_tokens",
                    "serving_decode_spec_rejected_tokens",
                    "serving_decode_spec_accept_rate",
                    "serving_decode_spec_draft_step_s",
                    "serving_decode_spec_verify_step_s"):
            assert fam in text, fam
        for name in ("serving.decode.spec_accept_rate",
                     "serving.decode.spec_drafted_tokens",
                     "serving.decode.spec_accepted_tokens",
                     "serving.decode.spec_rejected_tokens",
                     "serving.decode.spec_draft_step_s",
                     "serving.decode.spec_verify_step_s"):
            assert name in DEFAULT_HELP and DEFAULT_HELP[name], name
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# draft pages free with target pages (the cancel/disconnect regression)
# ---------------------------------------------------------------------------

def test_cancel_frees_draft_pages_with_target_pages(lm):
    """The draft pool is indexed by the SAME page table as the target
    pool — cancel/disconnect releases ONE page list covering both, so
    a mid-stream disconnect under speculation must restore the exact
    free-page count (the PR 17 client-disconnect reclaim, now with
    draft pages in the slot)."""
    eng = _engine(lm, spec=SpecConfig(k=3, sparsity=0.5))
    try:
        eng.warmup()
        total = eng.cfg.total_pages
        assert len(eng._free_pages) == total
        # throttle the loop so the cancel lands MID-generation (the
        # test_fleet_chaos idiom — wrapper runs inside _iter_lock)
        orig_step = eng._decode_step
        eng._decode_step = lambda: (time.sleep(0.15), orig_step())[1]
        req = DecodeRequest(tokens=_prompts()[2], max_new_tokens=200,
                            on_token=lambda rid, tok, idx: None)
        eng.submit(req)
        deadline = time.time() + 30
        while not any(s is not None for s in eng._slots):
            assert time.time() < deadline, "request never took a slot"
            time.sleep(0.01)
        # pages held mid-stream: taken off the free list or reserved
        assert (total - len(eng._free_pages)) + eng._reserved_pages > 0
        eng.cancel(req.rid, reason="client_disconnect")
        eng._decode_step = orig_step
        deadline = time.time() + 30
        while len(eng._free_pages) != total or eng._reserved_pages:
            assert time.time() < deadline, (
                f"draft/target page leak after cancel: "
                f"{total - len(eng._free_pages)} pages out, "
                f"{eng._reserved_pages} reserved")
            time.sleep(0.01)
        # the freed pages (stale draft K/V included) must be safely
        # reusable: a fresh wave through the same slots still matches
        off = _engine(lm)
        try:
            want = _run(off, _requests(_prompts()))
        finally:
            off.stop()
        got = _run(eng, _requests(_prompts()))
        _assert_same(got, want)
    finally:
        eng.stop()


def test_per_token_expiry_frees_draft_pages(lm):
    """A deadline expiry mid-decode rides the same release path: no
    draft-page leak, accounting restored."""
    eng = _engine(lm, spec=SpecConfig(k=3, sparsity=0.5))
    try:
        total = eng.cfg.total_pages
        req = DecodeRequest(tokens=_prompts()[4], max_new_tokens=200,
                            deadline_t=time.time() + 0.2)
        eng.submit(req)
        with pytest.raises(Exception):
            req.wait(timeout=60)
        deadline = time.time() + 30
        while len(eng._free_pages) != total or eng._reserved_pages:
            assert time.time() < deadline, "page leak after expiry"
            time.sleep(0.01)
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# pressure honesty + config validation
# ---------------------------------------------------------------------------

def test_decode_pressure_honest_under_spec(lm):
    on = _engine(lm, spec=SpecConfig(k=3, sparsity=0.5))
    off = _engine(lm)
    try:
        p_on, p_off = on.decode_pressure(), off.decode_pressure()
        assert p_on["speculative"] is True and p_on["spec_k"] == 3
        assert p_off["speculative"] is False and p_off["spec_k"] == 0
        # the draft pool is real HBM: a spec slot's page cost must
        # include the always-f32 draft K/V rows
        assert on.kv_bytes_per_page() > off.kv_bytes_per_page()
    finally:
        on.stop()
        off.stop()


def test_spec_config_validation(lm):
    with pytest.raises(ValueError, match="continuous"):
        _engine(lm, spec=SpecConfig(k=3), continuous=False)
    with pytest.raises(ValueError, match="SpecConfig.k"):
        _engine(lm, spec=SpecConfig(k=0))
    with pytest.raises(ValueError, match="SpecConfig.k"):
        _engine(lm, spec=SpecConfig(k=16))   # >= cap (4*4)
    with pytest.raises(ValueError, match="draft_impl"):
        _engine(lm, spec=SpecConfig(k=2, sparsity=0.5,
                                    draft_impl="magic"))


# ---------------------------------------------------------------------------
# the multi-query verify kernel (ops.flash_attention.paged_verify_attention)
# ---------------------------------------------------------------------------

def _verify_reference(q, kp, vp, pt, pos):
    """Gathered-jnp reference: per-query causal staircase over the
    slot's pages."""
    S, h, C, d = q.shape
    nb, page = pt.shape[1], kp.shape[2]
    K = nb * page
    kb = kp[pt].transpose(0, 2, 1, 3, 4).reshape(S, h, K, d)
    vb = vp[pt].transpose(0, 2, 1, 3, 4).reshape(S, h, K, d)
    sc = jnp.einsum("shcd,shkd->shck", q, kb) / np.sqrt(d)
    key_pos = jnp.arange(K)[None, None, None, :]
    q_lim = (pos[:, None] + jnp.arange(C)[None, :])[:, None, :, None]
    sc = jnp.where(key_pos <= q_lim, sc, -jnp.inf)
    return jnp.einsum("shck,shkd->shcd", jax.nn.softmax(sc, axis=-1), vb)


def test_paged_verify_attention_matches_reference():
    from bigdl_tpu.ops.flash_attention import paged_verify_attention

    rs = np.random.RandomState(3)
    S, h, C, d, P, nb, page = 4, 2, 4, 8, 16, 4, 4
    q = jnp.asarray(rs.randn(S, h, C, d).astype(np.float32))
    kp = jnp.asarray(rs.randn(P, h, page, d).astype(np.float32))
    vp = jnp.asarray(rs.randn(P, h, page, d).astype(np.float32))
    pt = jnp.asarray(rs.permutation(P)[:S * nb].reshape(S, nb), jnp.int32)
    pos = jnp.asarray(rs.randint(0, page * nb - C, (S,)), jnp.int32)
    out = paged_verify_attention(q, kp, vp, pt, pos, block_h=1,
                                 interpret=True)
    ref = _verify_reference(q, kp, vp, pt, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_paged_verify_attention_int8_matches_dequantized():
    from bigdl_tpu.ops.flash_attention import paged_verify_attention
    from bigdl_tpu.ops.quantized import dequantize_pages, quantize_pages

    rs = np.random.RandomState(5)
    S, h, C, d, P, nb, page = 2, 2, 3, 8, 8, 2, 4
    q = jnp.asarray(rs.randn(S, h, C, d).astype(np.float32))
    k32 = jnp.asarray(rs.randn(P, h, page, d).astype(np.float32))
    v32 = jnp.asarray(rs.randn(P, h, page, d).astype(np.float32))
    kq, ks = quantize_pages(k32)
    vq, vs = quantize_pages(v32)
    pt = jnp.asarray(rs.permutation(P)[:S * nb].reshape(S, nb), jnp.int32)
    pos = jnp.asarray(rs.randint(0, page * nb - C, (S,)), jnp.int32)
    ref = paged_verify_attention(q, dequantize_pages(kq, ks),
                                 dequantize_pages(vq, vs), pt, pos,
                                 block_h=1, interpret=True)
    out = paged_verify_attention(q, kq, vq, pt, pos, k_scales=ks,
                                 v_scales=vs, block_h=1, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError, match="k_scales"):
        paged_verify_attention(q, kq, vq, pt, pos, interpret=True)


# ---------------------------------------------------------------------------
# sentinel: the DECODE_SPEC_r* family
# ---------------------------------------------------------------------------

def test_sentinel_normalizes_decode_spec_rows():
    from bigdl_tpu.obs import sentinel

    row = {"bench": "decode_spec", "geometry": "decode_s8_c24",
           "spec_tokens_per_s_user": 140.0, "accept_rate": 0.74,
           "speedup_vs_off": 1.9, "token_parity": 1.0}
    fams = {r.family: r for r in sentinel.normalize(row, "t")}
    assert fams["decode_spec_tokens_per_s_user_decode_s8_c24"].direction \
        == sentinel.HIGHER
    assert fams["decode_spec_accept_rate_decode_s8_c24"].direction \
        == sentinel.HIGHER
    # the spec row must NOT leak into the plain decode-bench families
    assert not any(f.startswith("decode_tokens_per_s") for f in fams)
    assert "DECODE_SPEC_r[0-9]*.json" in sentinel._ARTIFACT_GLOBS
