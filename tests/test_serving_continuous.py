"""Continuous batching, multi-tenant registry, autoscaling — the serving
hot-path rebuild (docs/serving.md §Continuous batching).

Tier-1 specs: fixed-vs-continuous batching PARITY (byte-identical
responses for the same request set), event-driven wakeup latency (no
50 ms poll), deadline-aware ordering (near-expiry jumps the queue),
weighted multi-tenant admission + per-tenant SLO metrics + per-tenant
degradation isolation, the queue_wait/occupancy exports, the
zero-recompile mixed-size sweep, the pure autoscaling policy, and the
proxy's keep-alive connection pool.  Pool integration (subprocess
workers: autoscale up/down, conn reuse counters, two models behind one
pool) runs as ``slow`` via ``make test-serving``.
"""

import json
import os
import threading
import time
from urllib import request as urlreq

import numpy as np
import pytest

import jax

from bigdl_tpu import nn
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.serving import (InferenceModel, ServiceUnavailableError,
                               ServingConfig, ServingServer)


def _model_and_vars(din=4, dout=2, seed=0):
    model = nn.Sequential([nn.Linear(din, 8), nn.ReLU(), nn.Linear(8, dout)])
    v = model.init(jax.random.PRNGKey(seed), np.zeros((1, din), np.float32))
    return model, v


def _serve_all(srv, xs):
    rids = [srv.enqueue(x) for x in xs]
    return [np.asarray(srv.query(rid, timeout=30)) for rid in rids]


# ---------------------------------------------------------------------------
# batching parity: continuous vs fixed


def test_continuous_matches_fixed_byte_identical_custom_fn():
    """Same request set through both engine modes -> byte-identical
    responses, for arbitrary co-batching (row-wise deterministic fn)."""
    rs = np.random.RandomState(0)
    xs = [rs.rand(rs.randint(1, 5), 3).astype(np.float32)
          for _ in range(24)]

    def run(continuous):
        srv = ServingServer(
            InferenceModel(predict_fn=lambda x: np.asarray(x) * 2.0 + 1.0),
            ServingConfig(batch_size=6, batch_timeout_s=0.002,
                          continuous=continuous)).start()
        try:
            return _serve_all(srv, xs)
        finally:
            srv.stop()

    for a, b in zip(run(True), run(False)):
        assert a.tobytes() == b.tobytes()


def test_continuous_matches_fixed_byte_identical_jitted_model():
    """The jitted path: bucket padding makes per-row results independent
    of co-batching, so the two engines agree to the byte."""
    model, v = _model_and_vars()
    im = InferenceModel(model, v, batch_buckets=(4, 16))
    rs = np.random.RandomState(1)
    xs = [rs.rand(rs.randint(1, 6), 4).astype(np.float32)
          for _ in range(20)]

    def run(continuous):
        srv = ServingServer(im, ServingConfig(
            batch_size=8, batch_timeout_s=0.002,
            continuous=continuous)).start()
        try:
            return _serve_all(srv, xs)
        finally:
            srv.stop()

    for a, b in zip(run(True), run(False)):
        assert a.tobytes() == b.tobytes()


# ---------------------------------------------------------------------------
# event-driven wakeup + deadline-aware ordering


def test_event_driven_wakeup_latency():
    """Sparse traffic pays no polling penalty: a lone request round-trips
    in milliseconds (the old loop polled the queue at 50 ms)."""
    srv = ServingServer(
        InferenceModel(predict_fn=lambda x: np.asarray(x)),
        ServingConfig(batch_size=8, batch_timeout_s=0.0)).start()
    try:
        srv.query(srv.enqueue(np.ones((1, 2), np.float32)), timeout=10)
        lats = []
        for _ in range(20):
            t0 = time.perf_counter()
            srv.query(srv.enqueue(np.ones((1, 2), np.float32)), timeout=10)
            lats.append(time.perf_counter() - t0)
            time.sleep(0.01)   # sparse: every request finds an idle engine
        assert np.median(lats) < 0.02, (
            f"median sparse latency {np.median(lats)*1e3:.1f}ms — the "
            "event-driven wakeup is not waking the assembler")
    finally:
        srv.stop()


def test_near_expiry_request_jumps_queue():
    """Deadline-aware ordering: a later-enqueued request with a deadline
    is predicted BEFORE an earlier no-deadline request."""
    order = []

    def recording(x):
        order.append(float(np.asarray(x).ravel()[0]))
        time.sleep(0.05)
        return np.asarray(x)

    srv = ServingServer(InferenceModel(predict_fn=recording),
                        ServingConfig(batch_size=1,
                                      batch_timeout_s=0.0)).start()
    try:
        r0 = srv.enqueue(np.full((1, 2), 0.0, np.float32))   # occupies engine
        time.sleep(0.02)
        # rA fills the handoff slot, so r1/r2 meet in the HEAP — where
        # deadline ordering decides who goes next
        ra = srv.enqueue(np.full((1, 2), 0.5, np.float32))
        time.sleep(0.02)
        r1 = srv.enqueue(np.full((1, 2), 1.0, np.float32))   # no deadline
        r2 = srv.enqueue(np.full((1, 2), 2.0, np.float32), deadline_s=5.0)
        for rid in (r0, ra, r1, r2):
            srv.query(rid, timeout=10)
        assert order == [0.0, 0.5, 2.0, 1.0], order
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# multi-tenant registry


def test_multi_tenant_routing_and_unknown_model():
    srv = ServingServer(models={
        "double": InferenceModel(predict_fn=lambda x: np.asarray(x) * 2),
        "triple": InferenceModel(predict_fn=lambda x: np.asarray(x) * 3),
    }).start()
    try:
        x = np.ones((1, 2), np.float32)
        np.testing.assert_array_equal(
            srv.query(srv.enqueue(x, model="double"), timeout=10), 2.0)
        np.testing.assert_array_equal(
            srv.query(srv.enqueue(x, model="triple"), timeout=10), 3.0)
        # no "default" key: the FIRST registered model takes unrouted
        # requests
        np.testing.assert_array_equal(
            srv.query(srv.enqueue(x), timeout=10), 2.0)
        with pytest.raises(KeyError, match="unknown model"):
            srv.enqueue(x, model="nope")
        info = srv.models()
        assert set(info) == {"double", "triple"}
        assert info["double"]["default"] and not info["triple"]["default"]
    finally:
        srv.stop()


def test_weighted_admission_shares_engine_by_weight():
    """Stride scheduling: with backlog on both tenants, a weight-3 tenant
    gets ~3x the service of a weight-1 tenant."""
    order = []

    def recorder(tag):
        def predict(x):
            order.append(tag)
            time.sleep(0.002)
            return np.asarray(x)
        return predict

    srv = ServingServer(models={
        "heavy": InferenceModel(predict_fn=recorder("heavy")),
        "light": InferenceModel(predict_fn=recorder("light")),
    }, config=ServingConfig(batch_size=2, batch_timeout_s=0.0))
    srv._tenants["heavy"].weight = 3.0
    rids = []
    for i in range(12):    # backlog BEFORE start: deterministic pops
        rids.append(srv.enqueue(np.ones((1, 2), np.float32), model="heavy"))
        rids.append(srv.enqueue(np.ones((1, 2), np.float32), model="light"))
    srv.start()
    try:
        for rid in rids:
            srv.query(rid, timeout=30)
        first8 = order[:8]
        assert first8.count("heavy") >= 5, (
            f"weight-3 tenant got {first8.count('heavy')}/8 of the first "
            f"batches: {order}")
        assert "light" in order[:8], "weight-1 tenant starved outright"
    finally:
        srv.stop()


def test_tenant_degradation_is_isolated():
    """One tenant's dying model degrades and sheds ONLY that tenant; the
    other keeps answering."""

    class _Dying:
        def predict(self, x):
            raise RuntimeError("replica down")

    srv = ServingServer(models={
        "good": InferenceModel(predict_fn=lambda x: np.asarray(x) * 2),
        "bad": _Dying(),
    }, config=ServingConfig(batch_size=1, batch_timeout_s=0.0,
                            degraded_after_failures=1,
                            degraded_probe_interval_s=60.0)).start()
    try:
        x = np.ones((1, 2), np.float32)
        rid = srv.enqueue(x, model="bad")
        with pytest.raises(RuntimeError, match="replica down"):
            srv.query(rid, timeout=10)
        assert srv._tenants["bad"].degraded
        assert not srv._tenants["good"].degraded
        srv._tenants["bad"].last_probe_t = time.time()  # close the probe
        with pytest.raises(ServiceUnavailableError):
            srv.enqueue(x, model="bad")
        np.testing.assert_array_equal(
            srv.query(srv.enqueue(x, model="good"), timeout=10), 2.0)
    finally:
        srv.stop()


def test_per_tenant_metrics_in_one_scrape():
    """Two tenants' latency histograms land in ONE Prometheus scrape —
    the per-tenant SLO surface."""
    from bigdl_tpu.obs.export import render_prometheus

    reg = Metrics()
    srv = ServingServer(models={
        "alpha": InferenceModel(predict_fn=lambda x: np.asarray(x)),
        "beta": InferenceModel(predict_fn=lambda x: np.asarray(x)),
    }, metrics=reg).start()
    try:
        x = np.ones((1, 2), np.float32)
        srv.query(srv.enqueue(x, model="alpha"), timeout=10)
        srv.query(srv.enqueue(x, model="beta"), timeout=10)
        text = render_prometheus(reg)
        for tenant in ("alpha", "beta"):
            assert f"serving_tenant_{tenant}_latency_s_bucket" in text
            assert f"serving_tenant_{tenant}_queue_wait_s" in text
            assert f"serving_tenant_{tenant}_requests" in text
    finally:
        srv.stop()


def test_register_unregister_live():
    srv = ServingServer(
        InferenceModel(predict_fn=lambda x: np.asarray(x))).start()
    try:
        srv.register_model("extra",
                           InferenceModel(predict_fn=lambda x:
                                          np.asarray(x) * 5))
        x = np.ones((1, 2), np.float32)
        np.testing.assert_array_equal(
            srv.query(srv.enqueue(x, model="extra"), timeout=10), 5.0)
        with pytest.raises(ValueError, match="already registered"):
            srv.register_model("extra", InferenceModel(predict_fn=str))
        with pytest.raises(ValueError, match="default"):
            srv.unregister_model("default")
        srv.unregister_model("extra")
        with pytest.raises(KeyError):
            srv.enqueue(x, model="extra")
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# wait/occupancy exports


def test_queue_wait_and_occupancy_exported():
    from bigdl_tpu.obs.export import render_prometheus

    reg = Metrics()
    srv = ServingServer(
        InferenceModel(predict_fn=lambda x: np.asarray(x)),
        ServingConfig(batch_size=4, batch_timeout_s=0.002),
        metrics=reg).start()
    try:
        rids = [srv.enqueue(np.ones((1, 2), np.float32)) for _ in range(8)]
        for rid in rids:
            srv.query(rid, timeout=10)
        snap = reg.snapshot()
        assert snap["hists"]["serving.queue_wait_s"]["n"] == 8
        occ = snap["gauges"]["serving.batch_occupancy"]
        assert 0.0 < occ <= 1.0
        # occupancy == avg fill / batch_size, from the same stats
        expect = (srv.stats["requests"] / srv.stats["batches"]) / 4
        assert abs(occ - expect) < 1e-9
        text = render_prometheus(reg)
        assert "serving_queue_wait_s_bucket" in text
        assert "serving_batch_occupancy" in text
        # the autoscaling pressure signal rides the same scrape; the
        # engine gauges it after publish, so poll for the drained value
        assert "serving_backlog" in text
        for _ in range(500):
            if reg.snapshot()["gauges"]["serving.backlog"] == 0.0:
                break
            time.sleep(0.002)
        assert reg.snapshot()["gauges"]["serving.backlog"] == 0.0
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# bucket padding: zero unexpected recompiles across a mixed-size sweep


def test_mixed_size_sweep_zero_unexpected_recompiles():
    from bigdl_tpu.obs import attr as obs_attr
    from bigdl_tpu.optim.metrics import global_metrics

    model, v = _model_and_vars()
    im = InferenceModel(model, v, batch_buckets=(2, 4, 8))
    im.warmup(np.zeros((4,), np.float32))
    sent = obs_attr.recompile_sentinel()
    before = global_metrics().counter("train.unexpected_recompiles_total")
    sent.mark_steady()
    try:
        srv = ServingServer(im, ServingConfig(
            batch_size=4, batch_timeout_s=0.001)).start()
        try:
            rs = np.random.RandomState(0)
            for rows in (1, 2, 3, 5, 7, 8, 9, 20):   # incl. > max bucket
                rid = srv.enqueue(rs.rand(rows, 4).astype(np.float32))
                out = srv.query(rid, timeout=30)
                assert out.shape == (rows, 2)
        finally:
            srv.stop()
        after = global_metrics().counter(
            "train.unexpected_recompiles_total")
        assert after == before, (
            f"{after - before} unexpected XLA recompiles in a mixed-size "
            "sweep — bucket padding/chunking broke")
    finally:
        sent.mark_warmup()


def test_inference_model_chunks_past_largest_bucket():
    model, v = _model_and_vars()
    im = InferenceModel(model, v, batch_buckets=(2, 4))
    rs = np.random.RandomState(0)
    x = rs.rand(11, 4).astype(np.float32)
    out = im.predict(x)
    assert out.shape == (11, 2)
    ref, _ = model.apply(v, x)
    np.testing.assert_allclose(out, np.asarray(ref), rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# autoscaling policy (pure function — subprocess integration is slow)


def test_autoscale_decision_policy():
    from bigdl_tpu.serving.pool import ServingPool

    d = ServingPool.autoscale_decision
    base = dict(n_workers=2, min_workers=1, max_workers=4,
                avg_queue_depth=0.0, up_depth=8.0, idle_ticks=0,
                down_after=3, breaker_open=False,
                since_last_scale_s=60.0, cooldown_s=5.0)
    assert d(**base) == "hold"
    assert d(**{**base, "avg_queue_depth": 9.0}) == "up"
    # at the max bound pressure cannot add workers
    assert d(**{**base, "avg_queue_depth": 9.0, "n_workers": 4}) == "hold"
    # cooldown gates BOTH directions
    assert d(**{**base, "avg_queue_depth": 9.0,
                "since_last_scale_s": 1.0}) == "hold"
    assert d(**{**base, "idle_ticks": 3}) == "down"
    assert d(**{**base, "idle_ticks": 2}) == "hold"      # not sustained
    assert d(**{**base, "idle_ticks": 3, "n_workers": 1}) == "hold"
    # an open breaker means load is about to redistribute: never shrink
    assert d(**{**base, "idle_ticks": 3, "breaker_open": True}) == "hold"


# ---------------------------------------------------------------------------
# keep-alive connection pool


def test_conn_pool_reuses_keep_alive_connections():
    from bigdl_tpu.serving import HttpFrontend
    from bigdl_tpu.serving.pool import _ConnPool

    srv = ServingServer(
        InferenceModel(predict_fn=lambda x: np.asarray(x))).start()
    fe = HttpFrontend(srv).start()
    conns = _ConnPool(timeout=10.0)
    try:
        conn, reused = conns.acquire(fe.url)
        assert not reused
        conn.request("GET", "/health")
        assert conn.getresponse().read()
        conns.release(fe.url, conn)
        conn2, reused2 = conns.acquire(fe.url)
        assert reused2 and conn2 is conn   # the parked socket came back
        conn2.request("GET", "/health")
        body = json.loads(conn2.getresponse().read())
        assert body["status"] == "ok"
        conns.release(fe.url, conn2)
        conns.clear(fe.url)
        _, reused3 = conns.acquire(fe.url)
        assert not reused3                 # clear() really dropped it
    finally:
        conns.clear()
        fe.stop()
        srv.stop()


# ---------------------------------------------------------------------------
# frontend surface: /models, model routing, health fields


def test_http_frontend_models_and_health_fields():
    from bigdl_tpu.serving import HttpClient, HttpFrontend

    srv = ServingServer(models={
        "a": InferenceModel(predict_fn=lambda x: np.asarray(x) * 2),
        "b": InferenceModel(predict_fn=lambda x: np.asarray(x) * 3),
    }).start()
    fe = HttpFrontend(srv).start()
    try:
        client = HttpClient(fe.url)
        np.testing.assert_array_equal(
            client.predict(np.ones((1, 2), np.float32), model="b"), 3.0)
        assert set(client.models()) == {"a", "b"}
        h = client.health()
        for key in ("queue_depth", "backlog", "p50_ms", "p99_ms",
                    "occupancy", "models"):
            assert key in h, key
        # unknown model -> 404 with the registry in the error
        from urllib.error import HTTPError
        req = urlreq.Request(
            fe.url + "/predict",
            data=json.dumps({"instances": [[1.0, 2.0]],
                             "model": "nope"}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(HTTPError) as ei:
            urlreq.urlopen(req, timeout=10)
        assert ei.value.code == 404
    finally:
        fe.stop()
        srv.stop()


def test_http_client_keep_alive_roundtrips():
    from bigdl_tpu.serving import HttpClient, HttpFrontend

    srv = ServingServer(
        InferenceModel(predict_fn=lambda x: np.asarray(x) * 2)).start()
    fe = HttpFrontend(srv).start()
    client = HttpClient(fe.url, keep_alive=True)
    try:
        for _ in range(3):
            np.testing.assert_array_equal(
                client.predict(np.ones((1, 2), np.float32)), 2.0)
        assert client._conn is not None    # the socket persisted
    finally:
        client.close()
        fe.stop()
        srv.stop()


# ---------------------------------------------------------------------------
# pool integration (subprocess workers) — slow


def _pool_env(extra=None):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pythonpath = os.pathsep.join(
        p for p in [repo_root, os.environ.get("PYTHONPATH")] if p)
    env = {"PYTHONPATH": pythonpath, "BIGDL_TPU_POOL_CPU": "1",
           "JAX_PLATFORMS": "cpu"}
    env.update(extra or {})
    return env


def _two_model_loader():
    """Worker-side registry factory: two tenants behind one engine."""
    import numpy as np
    import jax

    from bigdl_tpu import nn
    from bigdl_tpu.serving.inference_model import InferenceModel

    def make(seed):
        model = nn.Sequential([nn.Linear(8, 4)])
        variables = model.init(jax.random.PRNGKey(seed),
                               np.zeros((1, 8), np.float32))
        return InferenceModel(model, variables)

    return {"resnet": make(0), "bert": make(1)}


def _post(url, payload, timeout=30.0):
    req = urlreq.Request(url, data=json.dumps(payload).encode(),
                         headers={"Content-Type": "application/json"})
    with urlreq.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


@pytest.mark.slow
def test_pool_serves_two_models_with_per_tenant_metrics():
    """The multi-tenant acceptance: two models behind ONE pool, routed by
    the payload's "model" key, with both tenants' latency histograms in
    one worker /metrics scrape."""
    from bigdl_tpu.serving.pool import ServingPool

    pool = ServingPool("tests.test_serving_continuous:_two_model_loader",
                       workers=1, batch_size=8, worker_env=_pool_env())
    pool.start()
    try:
        rs = np.random.RandomState(0)
        outs = {}
        for name in ("resnet", "bert"):
            out = _post(pool.url + "/predict",
                        {"instances": rs.rand(2, 8).tolist(),
                         "model": name})
            outs[name] = np.asarray(out["predictions"], np.float32)
            assert outs[name].shape == (2, 4)
        # different tenants actually hit different weights
        assert not np.array_equal(outs["resnet"], outs["bert"])
        # header-form routing (X-Model) survives the proxy hop: same
        # input via header-bert == payload-bert, != payload-resnet
        x2 = rs.rand(2, 8).tolist()
        req = urlreq.Request(
            pool.url + "/predict",
            data=json.dumps({"instances": x2}).encode(),
            headers={"Content-Type": "application/json",
                     "X-Model": "bert"})
        with urlreq.urlopen(req, timeout=30) as r:
            via_header = np.asarray(json.loads(r.read())["predictions"],
                                    np.float32)
        np.testing.assert_array_equal(
            via_header,
            np.asarray(_post(pool.url + "/predict",
                             {"instances": x2, "model": "bert"}
                             )["predictions"], np.float32))
        assert not np.array_equal(
            via_header,
            np.asarray(_post(pool.url + "/predict",
                             {"instances": x2, "model": "resnet"}
                             )["predictions"], np.float32))
        # proxy relays the registry
        with urlreq.urlopen(pool.url + "/models", timeout=10) as r:
            models = json.loads(r.read())["models"]
        assert set(models) == {"resnet", "bert"}
        # one scrape of the worker shows BOTH tenants' SLO histograms
        with urlreq.urlopen(pool.workers[0].url + "/metrics",
                            timeout=10) as r:
            text = r.read().decode()
        assert "serving_tenant_resnet_latency_s_bucket" in text
        assert "serving_tenant_bert_latency_s_bucket" in text
        # forwards rode the keep-alive pool
        assert pool.stats["conn_reuse"] >= 1
    finally:
        pool.stop()


@pytest.mark.slow
def test_pool_autoscales_up_under_load_and_down_when_idle():
    """Metrics-driven autoscaling end to end: sustained queue pressure
    grows the pool (within max_workers), sustained idle shrinks it back
    (drain-before-kill), both visible in stats/flight."""
    from bigdl_tpu.serving.pool import ServingPool

    # every batch is a straggler -> the queue backs up behind predict
    slow_env = _pool_env(
        {"BIGDL_TPU_FAULTS": "serving_slow_batch:every=1:delay=0.25"})
    pool = ServingPool("tests.test_serving_multiproc:_pool_loader",
                       workers=1, batch_size=4, worker_env=slow_env,
                       min_workers=1, max_workers=2,
                       autoscale_interval_s=0.3,
                       scale_up_queue_depth=2.0, scale_down_after=3,
                       scale_cooldown_s=0.5, predict_timeout=30.0)
    pool.start()
    try:
        rs = np.random.RandomState(0)
        stop_load = threading.Event()
        errors = []

        def hammer():
            while not stop_load.is_set():
                try:
                    _post(pool.url + "/predict",
                          {"instances": rs.rand(1, 8).tolist()},
                          timeout=30.0)
                except Exception:  # noqa: BLE001 — sheds are expected
                    time.sleep(0.05)

        threads = [threading.Thread(target=hammer) for _ in range(8)]
        [t.start() for t in threads]
        deadline = time.time() + 60
        while time.time() < deadline and len(pool.workers) < 2:
            time.sleep(0.2)
        assert len(pool.workers) == 2, "never scaled up under load"
        assert pool.stats["scale_up"] >= 1
        stop_load.set()
        [t.join(30) for t in threads]
        assert not errors
        deadline = time.time() + 60
        while time.time() < deadline and len(pool.workers) > 1:
            time.sleep(0.2)
        assert len(pool.workers) == 1, "never scaled down after idle"
        assert pool.stats["scale_down"] >= 1
        # the survivor still answers (the drained worker left cleanly)
        out = _post(pool.url + "/predict",
                    {"instances": rs.rand(1, 8).tolist()}, timeout=30.0)
        assert np.asarray(out["predictions"]).shape == (1, 4)
        with urlreq.urlopen(pool.url + "/health", timeout=10) as r:
            h = json.loads(r.read())
        assert h["autoscale"]["min"] == 1 and h["autoscale"]["max"] == 2
    finally:
        pool.stop()
