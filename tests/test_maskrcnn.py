"""Detection ops numerics (vs numpy references) + MaskRCNN end-to-end
forward/compile + functional losses."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # minutes-scale XLA compiles, shape-only checks

from bigdl_tpu.ops import detection as D

RS = np.random.RandomState(0)


# ---------------------------------------------------------------------------
# ops
# ---------------------------------------------------------------------------


def _np_iou(a, b):
    out = np.zeros((len(a), len(b)), np.float32)
    for i, x in enumerate(a):
        for j, y in enumerate(b):
            yy1, xx1 = max(x[0], y[0]), max(x[1], y[1])
            yy2, xx2 = min(x[2], y[2]), min(x[3], y[3])
            inter = max(yy2 - yy1, 0) * max(xx2 - xx1, 0)
            ua = ((x[2] - x[0]) * (x[3] - x[1])
                  + (y[2] - y[0]) * (y[3] - y[1]) - inter)
            out[i, j] = inter / max(ua, 1e-9)
    return out


def test_box_iou_matches_numpy():
    a = np.abs(RS.rand(5, 4)).astype(np.float32) * 50
    a[:, 2:] = a[:, :2] + np.abs(RS.rand(5, 2)).astype(np.float32) * 30 + 1
    b = np.abs(RS.rand(7, 4)).astype(np.float32) * 50
    b[:, 2:] = b[:, :2] + np.abs(RS.rand(7, 2)).astype(np.float32) * 30 + 1
    np.testing.assert_allclose(np.asarray(D.box_iou(jnp.asarray(a),
                                                    jnp.asarray(b))),
                               _np_iou(a, b), rtol=1e-4, atol=1e-5)


def test_encode_decode_roundtrip():
    anchors = np.array([[0, 0, 10, 10], [5, 5, 25, 35]], np.float32)
    boxes = np.array([[1, 2, 12, 9], [4, 8, 30, 30]], np.float32)
    deltas = D.encode_boxes(jnp.asarray(boxes), jnp.asarray(anchors))
    back = D.decode_boxes(deltas, jnp.asarray(anchors))
    np.testing.assert_allclose(np.asarray(back), boxes, rtol=1e-4, atol=1e-3)


def _np_greedy_nms(boxes, scores, thr):
    order = np.argsort(-scores)
    keep = []
    alive = np.ones(len(boxes), bool)
    iou = _np_iou(boxes, boxes)
    for _ in range(len(boxes)):
        cand = [i for i in order if alive[i]]
        if not cand:
            break
        best = cand[0]
        keep.append(best)
        alive &= iou[best] <= thr
        alive[best] = False
    return keep


def test_nms_matches_numpy_greedy():
    n = 20
    boxes = RS.rand(n, 4).astype(np.float32) * 40
    boxes[:, 2:] = boxes[:, :2] + RS.rand(n, 2).astype(np.float32) * 20 + 2
    scores = RS.rand(n).astype(np.float32)
    idx, valid = D.nms_padded(jnp.asarray(boxes), jnp.asarray(scores),
                              0.5, 10)
    got = [int(i) for i, v in zip(np.asarray(idx), np.asarray(valid)) if v]
    want = _np_greedy_nms(boxes, scores, 0.5)[:10]
    assert got == want


def test_class_aware_nms_keeps_cross_class_overlaps():
    boxes = np.array([[0, 0, 10, 10], [0, 0, 10, 10]], np.float32)
    scores = np.array([0.9, 0.8], np.float32)
    classes = np.array([1, 2], np.int32)
    _, valid = D.class_aware_nms(jnp.asarray(boxes), jnp.asarray(scores),
                                 jnp.asarray(classes), 0.5, 2)
    assert np.asarray(valid).sum() == 2  # same-box different-class both kept
    _, valid_same = D.nms_padded(jnp.asarray(boxes), jnp.asarray(scores),
                                 0.5, 2)
    assert np.asarray(valid_same).sum() == 1


def _np_roi_align(feat, box, out_size, scale, sr):
    """Literal re-implementation of torchvision roi_align for one box."""
    y1, x1, y2, x2 = box * scale
    bh, bw = max(y2 - y1, 1e-6), max(x2 - x1, 1e-6)
    ch, cw = bh / out_size, bw / out_size
    h, w, c = feat.shape
    out = np.zeros((out_size, out_size, c), np.float32)
    for i in range(out_size):
        for j in range(out_size):
            acc = np.zeros(c, np.float32)
            for si in range(sr):
                for sj in range(sr):
                    y = y1 + (i * sr + si + 0.5) * (ch / sr) - 0.5
                    x = x1 + (j * sr + sj + 0.5) * (cw / sr) - 0.5
                    if y < -1 or y > h or x < -1 or x > w:
                        continue
                    y, x = max(y, 0.0), max(x, 0.0)  # torchvision clamp
                    y0, x0 = int(np.floor(y)), int(np.floor(x))
                    wy, wx = y - y0, x - x0
                    def at(yy, xx):
                        return feat[min(max(yy, 0), h - 1),
                                    min(max(xx, 0), w - 1)]
                    acc += ((1 - wy) * (1 - wx) * at(y0, x0)
                            + (1 - wy) * wx * at(y0, x0 + 1)
                            + wy * (1 - wx) * at(y0 + 1, x0)
                            + wy * wx * at(y0 + 1, x0 + 1))
            out[i, j] = acc / (sr * sr)
    return out


def test_roi_align_matches_reference():
    feat = RS.rand(16, 16, 3).astype(np.float32)
    boxes = np.array([[2, 2, 12, 12], [0, 0, 31, 31], [5.5, 3.2, 9.9, 14.1],
                      [0, 0, 4, 4]],  # border box: exercises the (-1,0) clamp
                     np.float32)
    got = np.asarray(D.roi_align(jnp.asarray(feat), jnp.asarray(boxes),
                                 4, 0.5, 2))
    for k in range(len(boxes)):
        want = _np_roi_align(feat, boxes[k], 4, 0.5, 2)
        np.testing.assert_allclose(got[k], want, rtol=1e-4, atol=1e-5)


def test_multilevel_roi_align_level_assignment():
    feats = [jnp.asarray(RS.rand(32 // (2 ** i), 32 // (2 ** i), 2)
                         .astype(np.float32)) for i in range(4)]
    strides = (4, 8, 16, 32)
    small = np.array([[0, 0, 20, 20]], np.float32)     # -> low level
    large = np.array([[0, 0, 500, 500]], np.float32)   # -> top level
    out_s = D.multilevel_roi_align(feats, jnp.asarray(small), 2, strides)
    out_l = D.multilevel_roi_align(feats, jnp.asarray(large), 2, strides)
    # small box equals level-0 align; large equals level-3 align
    np.testing.assert_allclose(
        np.asarray(out_s[0]),
        np.asarray(D.roi_align(feats[0], jnp.asarray(small), 2, 1 / 4)[0]),
        rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(out_l[0]),
        np.asarray(D.roi_align(feats[3], jnp.asarray(large), 2, 1 / 32)[0]),
        rtol=1e-5)


def test_generate_anchors_counts_and_geometry():
    anchors = D.generate_anchors([(4, 4), (2, 2)], [8, 16], [32, 64])
    assert anchors.shape == (4 * 4 * 3 + 2 * 2 * 3, 4)
    # ratio=1 anchor at first cell of level 0: centered at (4,4), size 32
    a = anchors[1]
    np.testing.assert_allclose(a, [4 - 16, 4 - 16, 4 + 16, 4 + 16], atol=1e-4)


def test_paste_mask_inside_box():
    mask = jnp.ones((4, 4), jnp.float32)
    out = np.asarray(D.paste_mask(mask, jnp.asarray([2., 3., 8., 9.]),
                                  12, 12))
    assert out.shape == (12, 12)
    assert out[5, 5] > 0.9      # inside box
    assert out[0, 0] == 0.0     # outside box
    assert out[11, 11] == 0.0


# ---------------------------------------------------------------------------
# model
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    from bigdl_tpu.models.maskrcnn import MaskRCNN

    model = MaskRCNN(num_classes=5, image_size=(64, 64), pre_nms_topk=64,
                     num_proposals=16, max_detections=8)
    x = jnp.asarray(RS.rand(1, 64, 64, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    return model, variables, x


def test_maskrcnn_rejects_misaligned_image_size():
    from bigdl_tpu.models.maskrcnn import MaskRCNN

    with pytest.raises(ValueError):
        MaskRCNN(num_classes=3, image_size=(100, 100))


def test_maskrcnn_forward_shapes(tiny_model):
    model, variables, x = tiny_model
    out, _ = model.apply(variables, x)
    assert out["boxes"].shape == (8, 4)
    assert out["scores"].shape == (8,)
    assert out["classes"].shape == (8,)
    assert out["valid"].shape == (8,)
    assert out["masks"].shape == (8, 28, 28)
    b = np.asarray(out["boxes"])
    assert (b >= 0).all() and (b <= 64).all()
    s = np.asarray(out["masks"])
    assert (s >= 0).all() and (s <= 1).all()


def test_maskrcnn_jits(tiny_model):
    model, variables, x = tiny_model

    @jax.jit
    def infer(p, s, xx):
        out, _ = model.forward(p, s, xx)
        return out

    out = infer(variables["params"], variables["state"], x)
    assert np.isfinite(np.asarray(out["scores"])).all()


def test_rpn_loss_decreases_for_better_logits(tiny_model):
    from bigdl_tpu.models import maskrcnn as M

    model, variables, x = tiny_model
    anchors = model.anchors
    gt = jnp.asarray([[10., 10., 40., 40.]])
    gt_valid = jnp.asarray([True])
    iou = np.asarray(D.box_iou(jnp.asarray(anchors), gt))[:, 0]
    good_logits = jnp.asarray((iou > 0.5).astype(np.float32) * 8 - 4)
    bad_logits = -good_logits
    deltas = D.encode_boxes(gt[0], jnp.asarray(anchors))
    l_good = M.rpn_loss(good_logits, deltas, anchors, gt, gt_valid)
    l_bad = M.rpn_loss(bad_logits, deltas, anchors, gt, gt_valid)
    assert float(l_good) < float(l_bad)
    assert np.isfinite(float(l_good))


def test_rpn_loss_ignores_padded_gt(tiny_model):
    """Padded (invalid) gt columns must not mark anchor 0 positive via the
    best-anchor-per-gt rule."""
    from bigdl_tpu.models import maskrcnn as M

    model, _, _ = tiny_model
    anchors = model.anchors
    gt = jnp.asarray([[10., 10., 40., 40.], [0., 0., 0., 0.]])
    valid_both = jnp.asarray([True, False])
    valid_one = jnp.asarray([True])
    a = anchors.shape[0]
    logits = jnp.zeros((a,))
    deltas = jnp.zeros((a, 4))
    l_padded = M.rpn_loss(logits, deltas, anchors, gt, valid_both)
    l_clean = M.rpn_loss(logits, deltas, anchors, gt[:1], valid_one)
    np.testing.assert_allclose(float(l_padded), float(l_clean), rtol=1e-6)


def test_detection_loss_gradients_flow(tiny_model):
    from bigdl_tpu.models import maskrcnn as M

    model, variables, x = tiny_model
    ps, _ = model.features(variables["params"], variables["state"], x)
    logits, deltas = model.rpn_outputs(variables["params"], ps)
    prop, prop_valid = model.proposals(logits, deltas)
    gt = jnp.asarray([[8., 8., 30., 30.]])
    gt_cls = jnp.asarray([2])
    gt_valid = jnp.asarray([True])

    def loss(p):
        rois = D.multilevel_roi_align([pp[0] for pp in ps], prop, 7,
                                      model.STRIDES)
        (cl, bd), _ = model.box_head.forward(p["box_head"], {}, rois)
        return M.detection_loss(cl, bd, prop,
                                prop_valid.astype(jnp.float32),
                                gt, gt_cls, gt_valid)

    g = jax.grad(loss)(variables["params"])
    gn = float(jnp.sqrt(sum(jnp.sum(a ** 2) for a in
                            jax.tree_util.tree_leaves(g["box_head"]))))
    assert np.isfinite(gn) and gn > 0
