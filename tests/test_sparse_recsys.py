"""Sparse tensor + SparseLinear + NCF / Wide&Deep zoo tests."""

import numpy as np
import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.models import NeuralCF, WideAndDeep
from bigdl_tpu.nn.criterion import BCECriterion
from bigdl_tpu.optim.validation import HitRatio, NDCG
from bigdl_tpu.tensor.sparse import SparseTensor, sparse_join

RS = np.random.RandomState(0)
RNG = jax.random.PRNGKey(0)


def _random_sparse(n, d, density=0.2, nnz=None):
    dense = RS.rand(n, d) * (RS.rand(n, d) < density)
    return SparseTensor.from_dense(dense.astype(np.float32), nnz=nnz), dense


def test_sparse_roundtrip_and_padding():
    sp, dense = _random_sparse(5, 8, nnz=32)
    assert sp.nnz == 32  # padded capacity
    np.testing.assert_allclose(np.asarray(sp.to_dense()), dense, rtol=1e-6)


def test_sparse_matmul_matches_dense():
    sp, dense = _random_sparse(6, 10, nnz=40)
    w = RS.rand(10, 3).astype(np.float32)
    got = np.asarray(sp.matmul(jnp.asarray(w)))
    np.testing.assert_allclose(got, dense @ w, atol=1e-5)
    np.testing.assert_allclose(np.asarray(sp.row_sum()), dense.sum(-1),
                               rtol=1e-5)


def test_sparse_is_pytree_and_jits():
    sp, dense = _random_sparse(4, 6, nnz=16)
    w = jnp.asarray(RS.rand(6, 2).astype(np.float32))

    @jax.jit
    def f(s, w):
        return s.matmul(w)

    np.testing.assert_allclose(np.asarray(f(sp, w)), dense @ np.asarray(w),
                               atol=1e-5)


def test_sparse_join():
    a, da = _random_sparse(3, 4, nnz=8)
    b, db = _random_sparse(3, 5, nnz=8)
    j = sparse_join([a, b])
    assert j.shape == (3, 9)
    np.testing.assert_allclose(np.asarray(j.to_dense()),
                               np.concatenate([da, db], -1), rtol=1e-6)


def test_sparse_linear_grad_flows():
    sp, dense = _random_sparse(8, 12, nnz=48)
    layer = nn.SparseLinear(12, 4)
    v = layer.init(RNG, sp)
    y, _ = layer.apply(v, sp)
    np.testing.assert_allclose(
        np.asarray(y), dense @ np.asarray(v["params"]["weight"])
        + np.asarray(v["params"]["bias"]), atol=1e-5)

    def loss(params):
        out, _ = layer.forward(params, {}, sp)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(v["params"])
    assert np.all(np.isfinite(np.asarray(g["weight"])))
    # only touched columns get weight gradient
    touched = set(np.asarray(sp.indices[:, 1])[np.asarray(sp.values) != 0])
    gw = np.asarray(g["weight"])
    for c in range(12):
        if c not in touched:
            np.testing.assert_allclose(gw[c], 0.0)


def test_ncf_trains_and_ranks():
    users = 30
    items = 40
    n = 512
    u = RS.randint(0, users, n).astype(np.int32)
    i = RS.randint(0, items, n).astype(np.int32)
    # learnable rule: positive iff (u + i) even
    y = (((u + i) % 2) == 0).astype(np.float32)[:, None]

    model = NeuralCF(users, items, embed_dim=8, mlp_dims=(16, 8))
    v = model.init(RNG, jnp.asarray(u), jnp.asarray(i))
    crit = BCECriterion()

    params = v["params"]
    lr = 0.15

    @jax.jit
    def step(params):
        def loss(p):
            out, _ = model.forward(p, {}, jnp.asarray(u), jnp.asarray(i))
            return crit(out, jnp.asarray(y))

        l, g = jax.value_and_grad(loss)(params)
        return jax.tree_util.tree_map(lambda p, gg: p - lr * gg, params, g), l

    first = None
    for _ in range(400):
        params, l = step(params)
        first = first if first is not None else float(l)
    assert float(l) < first * 0.7, (first, float(l))

    out, _ = model.forward(params, {}, jnp.asarray(u), jnp.asarray(i))
    acc = float((np.asarray(out)[:, 0] > 0.5).astype(np.float32).__eq__(
        y[:, 0]).mean())
    assert acc > 0.7, acc


def test_ncf_hit_ratio_eval():
    """Scores for 1 positive + 19 negatives per row → HR@k pipeline shape."""
    scores = jnp.asarray(RS.rand(16, 20).astype(np.float32))
    pos = jnp.zeros((16,), jnp.int32)
    s, c = HitRatio(k=20).batch_stats(scores, pos)
    np.testing.assert_allclose(float(s) / float(c), 1.0)  # k=all → always hit
    s, c = NDCG(k=20).batch_stats(scores, pos)
    assert 0.0 < float(s) / float(c) <= 1.0


def test_wide_and_deep_trains():
    n, wide_dim, dense_dim = 256, 24, 5
    cats = [7, 11]
    wide_rows = RS.randint(0, n, n * 3)
    wide_cols = RS.randint(0, wide_dim, n * 3)
    wide_dense = np.zeros((n, wide_dim), np.float32)
    wide_dense[wide_rows, wide_cols] = 1.0
    sp = SparseTensor.from_dense(wide_dense, nnz=n * 3 + 8)
    cat = np.stack([RS.randint(0, c, n) for c in cats], -1).astype(np.int32)
    dense = RS.rand(n, dense_dim).astype(np.float32)
    # label depends on both a wide column and a dense feature
    y = ((wide_dense[:, 0] + (dense[:, 0] > 0.5)) >= 1).astype(
        np.float32)[:, None]

    model = WideAndDeep(wide_dim, cats, dense_dim, embed_dim=4,
                        hidden=(16, 8))
    v = model.init(RNG, sp, jnp.asarray(cat), jnp.asarray(dense))
    crit = BCECriterion()
    params = v["params"]

    @jax.jit
    def step(params):
        def loss(p):
            out, _ = model.forward(p, {}, sp, jnp.asarray(cat),
                                   jnp.asarray(dense))
            return crit(out, jnp.asarray(y))

        l, g = jax.value_and_grad(loss)(params)
        return jax.tree_util.tree_map(
            lambda pp, gg: pp - 0.1 * gg, params, g), l

    first = None
    for _ in range(400):
        params, l = step(params)
        first = first if first is not None else float(l)
    assert float(l) < first * 0.6, (first, float(l))

    out, _ = model.forward(params, {}, sp, jnp.asarray(cat),
                           jnp.asarray(dense))
    acc = float(((np.asarray(out)[:, 0] > 0.5) == y[:, 0]).mean())
    assert acc > 0.8, acc


def test_sparse_eval_shape_and_join_validation():
    import pytest

    sp, _ = _random_sparse(4, 6, nnz=16)
    out = jax.eval_shape(lambda s: s.scale(2.0), sp)
    assert out.shape == (4, 6)
    with pytest.raises(ValueError):
        sparse_join([sp, sp], total_cols=6)  # < combined 12
    with pytest.raises(ValueError):
        nn.MultiCriterion([BCECriterion(), BCECriterion()], weights=[1.0])


def test_auc_two_class_logits():
    from bigdl_tpu.optim.validation import AUC

    # logits where the raw last column ranks WRONG but p1 ranks right
    logits = jnp.asarray([[5.0, 4.0], [-5.0, 0.0]])
    t = jnp.asarray([1, 0])  # row1 is actually more-positive (p1=0.99)
    s, c = AUC().batch_stats(logits, t)
    np.testing.assert_allclose(float(s) / float(c), 0.0)  # true AUC


def test_two_tower_trains_and_retrieves():
    """Two-tower retrieval: in-batch softmax training; after training, the
    user tower retrieves its positive item via MIPS over the item tower
    (the friesian recall-service contract)."""
    import jax

    from bigdl_tpu.models.recsys import TwoTower
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion

    rs = np.random.RandomState(0)
    n_users, n_items, H, N = 40, 30, 4, 32
    # each user prefers item (user % n_items); history = noisy copies
    users = np.arange(N).astype(np.int32) % n_users
    pos = (users % (n_items - 1) + 1).astype(np.int32)
    hist = np.stack([np.where(rs.rand(H) < 0.7, p, 0)
                     for p in pos]).astype(np.int32)

    model = TwoTower(n_users, n_items, dim=16, hidden=(32,))
    variables = model.init(jax.random.PRNGKey(0), users, hist, pos)
    params = variables["params"]
    crit = CrossEntropyCriterion()
    targets = np.arange(N).astype(np.int32)

    @jax.jit
    def step(params):
        def loss_fn(p):
            logits, _ = model.forward(p, {}, users, hist, pos)
            return crit(logits, targets)

        loss, g = jax.value_and_grad(loss_fn)(params)
        return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, params, g), loss

    for _ in range(150):
        params, loss = step(params)
    assert float(loss) < 1.0

    # retrieval: user embedding vs ALL item embeddings (MIPS)
    u = model.encode_users(params, users[:8], hist[:8])
    allv = model.encode_items(params, np.arange(n_items).astype(np.int32))
    top1 = np.asarray(jnp.argmax(u @ allv.T, axis=-1))
    assert (top1 == pos[:8]).mean() >= 0.75, (top1, pos[:8])


def test_dien_learns_history_dependent_ctr():
    """DIEN: the label depends on whether the TARGET item appears in the
    user's history — learnable only through the attention-over-GRU-states
    path (user/target embeddings alone can't separate it)."""
    import jax

    from bigdl_tpu.models.recsys import DIEN
    from bigdl_tpu.nn.criterion import BCEWithLogitsCriterion

    rs = np.random.RandomState(1)
    n_users, n_items, H, N = 20, 15, 5, 256
    users = rs.randint(0, n_users, N).astype(np.int32)
    hist = rs.randint(1, n_items, (N, H)).astype(np.int32)
    hist[rs.rand(N, H) < 0.2] = 0                     # padding holes
    target = rs.randint(1, n_items, N).astype(np.int32)
    y = (hist == target[:, None]).any(1).astype(np.float32)[:, None]

    from bigdl_tpu.optim.optim_method import Adam

    model = DIEN(n_users, n_items, dim=16, gru_hidden=16, hidden=(32,))
    variables = model.init(jax.random.PRNGKey(0), users, hist, target)
    params = variables["params"]
    crit = BCEWithLogitsCriterion()
    method = Adam(learning_rate=5e-3)
    opt_state = method.init_state(params)

    @jax.jit
    def step(i, params, opt_state):
        def loss_fn(p):
            logits, _ = model.forward(p, {}, users, hist, target)
            return crit(logits, y)

        loss, g = jax.value_and_grad(loss_fn)(params)
        params, opt_state = method.update(i, g, params, opt_state)
        return params, opt_state, loss

    for i in range(400):
        params, opt_state, loss = step(i, params, opt_state)
    logits, _ = model.forward(params, {}, users, hist, target)
    acc = ((np.asarray(logits) > 0) == (y > 0.5)).mean()
    assert acc > 0.85, (acc, float(loss))


def test_twotower_init_keys_distinct():
    """ADVICE r3: uw_out and the item tower's first layer must not draw
    from the same RNG key (ki was not incremented after w_out)."""
    from bigdl_tpu.models.recsys import TwoTower

    model = TwoTower(8, 8, dim=16, hidden=(16,))
    params, _ = model.build(
        jax.random.PRNGKey(0), np.zeros(2, np.int32),
        np.zeros((2, 3), np.int32), np.zeros(2, np.int32))
    # same (16,16) shape; under the bug these were the same normal draw
    # at different scales
    a = np.asarray(params["uw_out"]) / np.sqrt(1.0 / 16)
    b = np.asarray(params["iw0"]) / np.sqrt(2.0 / 16)
    assert not np.allclose(a, b, atol=1e-5)
    c = np.asarray(params["iw_out"]) / np.sqrt(1.0 / 16)
    assert not np.allclose(a, c, atol=1e-5)
    assert not np.allclose(b, c, atol=1e-5)
