"""bigdl-tpu launcher specs (the bigdl-submit analog, SURVEY §2 CLI row)."""

import os
import subprocess
import sys
import textwrap


def _repo_env():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in [repo, env.get("PYTHONPATH")] if p)
    return env


def test_cli_run_single_process(tmp_path):
    script = tmp_path / "job.py"
    script.write_text(textwrap.dedent("""
        import sys
        print("ARGS", sys.argv[1:])
    """))
    out = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.cli", "run", str(script),
         "--alpha", "2"],
        env=_repo_env(), capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "ARGS ['--alpha', '2']" in out.stdout


def test_cli_run_local_gang_rendezvous(tmp_path):
    """-n 2 spawns a local gang whose members rendezvous through
    jax.distributed — the local-cluster launch mode."""
    script = tmp_path / "job.py"
    script.write_text(textwrap.dedent("""
        import jax
        jax.config.update("jax_platforms", "cpu")
        from bigdl_tpu.runtime.engine import init_engine
        init_engine()
        print(f"RANK{jax.process_index()}/{jax.process_count()}")
    """))
    out = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.cli", "run", "-n", "2", "--cpu",
         str(script)],
        env=_repo_env(), capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "RANK0/2" in out.stdout and "RANK1/2" in out.stdout


def test_cli_propagates_child_failure(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("raise SystemExit(3)")
    out = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.cli", "run", str(script)],
        env=_repo_env(), capture_output=True, text=True, timeout=120)
    assert out.returncode == 3


def test_cli_gang_kills_peers_when_one_rank_crashes(tmp_path):
    """ADVICE r2: one crashed rank must fail the gang FAST — survivors
    blocked forever (here: rank 0 sleeps 600s) are killed as soon as the
    crash is observed, not after their own wait() returns."""
    import time

    script = tmp_path / "job.py"
    script.write_text(textwrap.dedent("""
        import os, sys, time
        rank = int(os.environ["BIGDL_TPU_PROCESS_ID"])
        if rank == 1:
            sys.exit(7)
        time.sleep(600)   # simulates a peer stuck in rendezvous
    """))
    t0 = time.time()
    out = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.cli", "run", "-n", "2", "--cpu",
         str(script)],
        env=_repo_env(), capture_output=True, text=True, timeout=120)
    assert out.returncode == 7
    assert time.time() - t0 < 60     # fail-fast, not the 600s sleep


def test_cli_pack_npz_and_csv(tmp_path):
    import numpy as np

    np.savez(tmp_path / "d.npz", x=np.random.rand(10, 3).astype("float32"),
             y=np.arange(10, dtype="int32"))
    out = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.cli", "pack",
         str(tmp_path / "d.npz"), str(tmp_path / "d.btrec")],
        env=_repo_env(), capture_output=True, text=True, timeout=180)
    assert out.returncode == 0, out.stderr
    from bigdl_tpu.data.records import RecordDataSet

    ds = RecordDataSet(str(tmp_path / "d.btrec"))
    assert ds.size() == 10 and ds.label == "y"
    ds.close()

    import pandas as pd

    pd.DataFrame({"a": [1.0, 2.0], "b": [3.0, 4.0],
                  "label": [0, 1]}).to_csv(tmp_path / "d.csv", index=False)
    out = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.cli", "pack",
         str(tmp_path / "d.csv"), str(tmp_path / "c.btrec")],
        env=_repo_env(), capture_output=True, text=True, timeout=180)
    assert out.returncode == 0, out.stderr
    ds = RecordDataSet(str(tmp_path / "c.btrec"))
    mb = next(ds.batches(2, shuffle=False, drop_last=False))
    assert mb["input"].shape == (2, 2)
    ds.close()


def test_cli_doctor_reports_environment():
    env = _repo_env()
    env["BIGDL_TPU_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    out = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.cli", "doctor"],
        env=env, capture_output=True, text=True, timeout=200)
    assert out.returncode == 0, out.stderr
    import json

    report = json.loads(out.stdout)
    assert report["backend"]["platform"] == "cpu"
    assert report["backend"]["n_devices"] == 8
    assert report["mesh"]["data"] == 8
    assert "available" in report["native_lib"]


def test_cli_doctor_honors_dcn_env_and_fails_on_bad_mesh():
    import json

    env = _repo_env()
    env["BIGDL_TPU_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["BIGDL_TPU_DCN_SLICES"] = "2"
    out = subprocess.run([sys.executable, "-m", "bigdl_tpu.cli", "doctor"],
                         env=env, capture_output=True, text=True,
                         timeout=200)
    report = json.loads(out.stdout)
    assert report["mesh"] == {"dcn_data": 2, "data": 4, "model": 1,
                              "seq": 1, "expert": 1, "pipe": 1}
    assert out.returncode == 0

    env["BIGDL_TPU_DCN_SLICES"] = "3"   # 8 devices not divisible by 3
    out = subprocess.run([sys.executable, "-m", "bigdl_tpu.cli", "doctor"],
                         env=env, capture_output=True, text=True,
                         timeout=200)
    report = json.loads(out.stdout)
    assert "error" in report["mesh"]
    assert out.returncode == 1
