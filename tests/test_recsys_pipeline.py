"""RecommendationPipeline specs — the production feature->recall->ranking
chain over the multi-tenant ServingServer (docs/recsys.md).

Covers: end-to-end recommend ordering, the two pipeline tenants and their
per-stage SLO metrics, predict_inline's no-re-admission contract (unknown
tenant, degraded shed, accounting), mesh-sharded serving parity
(candidate ids byte-identical to the unsharded twin; scores equal to
float-reduction tolerance), the closed (batch, k) compile set under a
mixed sweep, and POST /recommend through the HTTP frontend."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from bigdl_tpu.friesian.pipeline import (
    RecallTopKModel, RankTowerModel, RecommendationPipeline,
)
from bigdl_tpu.friesian.serving import FeatureService
from bigdl_tpu.models.recsys import TwoTower
from bigdl_tpu.optim.metrics import global_metrics

HIST = 6
N_USERS, N_ITEMS, DIM = 16, 64, 8


def _pipeline(layout=None, k_candidates=16, seed=0, train_iters=0,
              **kw):
    import jax.numpy as jnp

    rs = np.random.RandomState(seed)
    tt = TwoTower(n_users=N_USERS, n_items=N_ITEMS, dim=DIM, hidden=(16,))
    params, _ = tt.build(jax.random.PRNGKey(seed),
                         np.zeros((2,), np.int32),
                         np.zeros((2, HIST), np.int32),
                         np.zeros((2,), np.int32))
    params = {k: np.asarray(v) for k, v in params.items()}
    if train_iters:
        # a few SGD steps: break the zero-bias init so sharded-parity
        # exercises REAL parameters, not the symmetric init
        @jax.jit
        def step(p, u, h, i):
            def loss_fn(p):
                logits, _ = tt.forward(p, None, u, h, i)
                lp = jax.nn.log_softmax(logits, axis=-1)
                lab = jnp.arange(logits.shape[0])
                return -jnp.mean(jnp.take_along_axis(
                    lp, lab[:, None], axis=1))
            _, g = jax.value_and_grad(loss_fn)(p)
            return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)

        for _ in range(train_iters):
            u = rs.randint(1, N_USERS, 32).astype(np.int32)
            h = rs.randint(0, N_ITEMS, (32, HIST)).astype(np.int32)
            i = rs.randint(1, N_ITEMS, 32).astype(np.int32)
            params = step(params, u, h, i)
        params = {k: np.asarray(v) for k, v in params.items()}

    fs = FeatureService()
    p = RecommendationPipeline(tt, params, fs, hist_len=HIST,
                               k_candidates=k_candidates, layout=layout,
                               batch_buckets=(1, 4, 16), **kw)
    for u in range(1, N_USERS):
        p.put_user_history(u, rs.randint(1, N_ITEMS, HIST))
    return p


@pytest.fixture(scope="module")
def pipe():
    p = _pipeline()
    p.start()
    p.warmup()
    yield p
    p.stop()


class TestRecommendEndToEnd:
    def test_ranked_descending_and_sized(self, pipe):
        out = pipe.recommend(3, k=5)
        assert len(out) == 5
        scores = [s for _, s in out]
        assert scores == sorted(scores, reverse=True)
        ids = [i for i, _ in out]
        assert len(set(ids)) == 5
        assert all(0 <= i < N_ITEMS for i in ids)

    def test_k_clamped_to_candidates(self, pipe):
        out = pipe.recommend(3, k=500)
        assert len(out) == pipe.k_candidates

    def test_unknown_user_raises_keyerror(self, pipe):
        with pytest.raises(KeyError, match="unknown user"):
            pipe.recommend(9999)

    def test_tenants_and_stage_metrics_registered(self, pipe):
        assert set(pipe.server._tenants) >= {"recall", "ranking"}
        pipe.recommend(4, k=3)
        m = global_metrics()
        snap = m.snapshot()
        seen = (list(snap["counters"]) + list(snap["gauges"])
                + list(snap["sums"]) + list(snap["hists"]))
        for stage in ("feature_s", "recall_s", "rank_s", "recommend_s",
                      "candidates", "requests"):
            name = f"serving.recsys.{stage}"
            assert any(k.startswith(name) for k in seen), (name, seen)

    def test_recall_only_matches_dense_scores(self, pipe):
        scores, ids = pipe.recall_only(5)
        assert len(ids) == pipe.k_candidates
        row = pipe._user_row(5)
        tt, params = pipe.two_tower, pipe.params
        u = np.asarray(tt.encode_users(
            params, row[:1].astype(np.int32),
            row[None, 1:].astype(np.int32)))
        v = np.asarray(tt.encode_items(
            params, np.arange(N_ITEMS, dtype=np.int32)))
        dense = (u @ v.T)[0]
        want = np.argsort(-dense)[:pipe.k_candidates]
        np.testing.assert_array_equal(np.sort(ids), np.sort(want))
        np.testing.assert_allclose(scores, dense[ids], rtol=1e-5,
                                   atol=1e-6)


class TestPredictInline:
    def test_unknown_tenant_raises(self, pipe):
        with pytest.raises(KeyError, match="unknown model"):
            pipe.server.predict_inline(
                "nope", np.zeros((1, 1 + HIST), np.float32))

    def test_accounting_counts_requests(self, pipe):
        before = pipe.server.stats["requests"]
        rows = np.zeros((3, 1 + HIST + 1), np.float32)
        out = pipe.server.predict_inline("ranking", rows)
        assert out.shape[0] == 3
        assert pipe.server.stats["requests"] == before + 3

    def test_degraded_tenant_sheds_inline(self):
        from bigdl_tpu.serving.server import (
            ServiceUnavailableError, ServingConfig, ServingServer,
        )

        boom = ServingServer(
            config=ServingConfig(degraded_after_failures=1),
            models={"bad": _Failing()})
        try:
            with pytest.raises(RuntimeError, match="boom"):
                boom.predict_inline("bad", np.zeros((1, 2), np.float32))
            with pytest.raises(ServiceUnavailableError):
                boom.predict_inline("bad", np.zeros((1, 2), np.float32))
            assert boom.stats["shed_requests"] >= 1
        finally:
            boom.stop()


class _Failing:
    def predict(self, x):
        raise RuntimeError("boom")


class TestShardedParity:
    @pytest.fixture(scope="class")
    def pair(self):
        plain = _pipeline(train_iters=25)
        shard = _pipeline(layout="fsdp:2,tp:2", train_iters=25)
        plain.start(); shard.start()
        plain.warmup(); shard.warmup()
        yield plain, shard
        plain.stop(); shard.stop()

    def test_candidate_ids_byte_identical(self, pair):
        plain, shard = pair
        for u in range(1, 8):
            _, i1 = plain.recall_only(u)
            _, i2 = shard.recall_only(u)
            np.testing.assert_array_equal(i1, i2)
            r1 = plain.recommend(u, k=6)
            r2 = shard.recommend(u, k=6)
            assert [i for i, _ in r1] == [i for i, _ in r2]

    def test_scores_match_to_reduction_tolerance(self, pair):
        # the tower contractions are mesh-sharded, so partial-sum order
        # differs: scores agree to float tolerance, NOT bit-exactly
        # (docs/recsys.md §Sharded-serving parity)
        plain, shard = pair
        s1, _ = plain.recall_only(2)
        s2, _ = shard.recall_only(2)
        np.testing.assert_allclose(s1, s2, rtol=1e-4, atol=1e-6)

    def test_per_chip_embedding_bytes_shrink(self, pair):
        plain, shard = pair
        full = plain.param_bytes_per_chip()
        per_chip = shard.param_bytes_per_chip()
        for k in ("user_emb", "item_emb"):
            assert full[k] / per_chip[k] >= 4  # fsdp:2 x tp:2 mesh

    def test_lookup_collective_bytes_priced_per_axis(self, pair):
        _, shard = pair
        led = shard.lookup_collective_bytes()
        assert led["total_bytes"] > 0
        assert set(led["per_axis_bytes"]) == {"fsdp", "tp"}
        assert led["rows"] == 1 + HIST + shard.k_candidates


class TestClosedCompileSet:
    def test_mixed_k_recommend_sweep_zero_recompiles(self, pipe):
        from bigdl_tpu.obs.attr import recompile_sentinel

        # pre-touch every k once (top-k width is part of the recall
        # program; the pipeline's compile set closes over its fixed
        # k_candidates, so recommend-k only slices host-side)
        sent = recompile_sentinel().install()
        m = global_metrics()
        pipe.recommend(1, k=2)
        before = m.counter("train.unexpected_recompiles_total")
        sent.mark_steady()
        try:
            for u, k in [(1, 1), (2, 5), (3, 10), (4, 3), (5, 16),
                         (6, 500)]:
                out = pipe.recommend(u, k=k)
                assert len(out) == min(k, pipe.k_candidates)
        finally:
            sent.mark_warmup()
        after = m.counter("train.unexpected_recompiles_total")
        assert after - before == 0, \
            "mixed-k recommend sweep recompiled after warmup"


class TestHttpRecommend:
    @pytest.fixture()
    def frontend(self, pipe):
        from bigdl_tpu.serving.http_frontend import HttpFrontend

        fe = HttpFrontend(pipe.server, recsys_pipeline=pipe).start()
        yield fe
        fe.stop()

    def _post(self, url, payload):
        req = urllib.request.Request(
            url + "/recommend", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    def test_recommend_roundtrip(self, frontend, pipe):
        out = self._post(frontend.url, {"user_id": 3, "k": 4})
        assert len(out["items"]) == 4
        want = pipe.recommend(3, k=4)
        assert [it["id"] for it in out["items"]] == [i for i, _ in want]

    def test_http_client_recommend(self, frontend, pipe):
        from bigdl_tpu.serving.http_frontend import HttpClient

        c = HttpClient(frontend.url, keep_alive=True)
        got = c.recommend(5, k=3)
        assert len(got) == 3
        assert [i for i, _ in got] == [i for i, _ in pipe.recommend(5, k=3)]

    def test_unknown_user_404(self, frontend):
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(frontend.url, {"user_id": 12345, "k": 2})
        assert e.value.code == 404

    def test_missing_user_id_400(self, frontend):
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(frontend.url, {"k": 2})
        assert e.value.code == 400

    def test_no_pipeline_attached_404(self, pipe):
        from bigdl_tpu.serving.http_frontend import HttpFrontend

        fe = HttpFrontend(pipe.server).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                self._post(fe.url, {"user_id": 3})
            assert e.value.code == 404
        finally:
            fe.stop()
