"""Layer-correctness specs.

Mirrors the reference's per-layer ``*Spec.scala`` strategy (SURVEY.md §5):
forward outputs checked against numpy/torch golden oracles, gradients checked
by finite differencing (the ``GradientChecker`` analog).
"""

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree
import numpy as np
import pytest

from bigdl_tpu import nn

KEY = jax.random.PRNGKey(0)


def finite_diff_check(module, variables, x, eps=1e-3, tol=2e-2):
    """Gradient check vs central differences on a few random coordinates."""

    def loss(params, x):
        y, _ = module.forward(params, variables.get("state", {}), x)
        return jnp.sum(y * y)

    params = variables["params"]
    g = jax.grad(loss)(params, x)
    flat_p, unravel = ravel_pytree(params)
    flat_g, _ = ravel_pytree(g)
    rng = np.random.RandomState(0)
    idxs = rng.choice(flat_p.shape[0], size=min(5, flat_p.shape[0]), replace=False)
    for i in idxs:
        fp = flat_p.at[i].add(eps)
        fm = flat_p.at[i].add(-eps)
        num = (loss(unravel(fp), x) - loss(unravel(fm), x)) / (2 * eps)
        assert abs(num - flat_g[i]) < tol * max(1.0, abs(num)), (
            f"grad mismatch at {i}: {num} vs {flat_g[i]}")


class TestLinear:
    def test_forward_matches_numpy(self):
        m = nn.Linear(4, 3)
        x = jax.random.normal(KEY, (2, 4))
        v = m.init(KEY, x)
        y = m(v, x)
        expected = np.asarray(x) @ np.asarray(v["params"]["weight"]) + np.asarray(
            v["params"]["bias"])
        np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-5)

    def test_lazy_in_features(self):
        m = nn.Linear(out_features=5)
        x = jnp.ones((3, 7))
        v = m.init(KEY, x)
        assert v["params"]["weight"].shape == (7, 5)
        assert m(v, x).shape == (3, 5)

    def test_gradcheck(self):
        m = nn.Linear(4, 3)
        x = jax.random.normal(KEY, (2, 4))
        v = m.init(KEY, x)
        finite_diff_check(m, v, x)


class TestConv2D:
    def test_forward_matches_torch(self):
        torch = pytest.importorskip("torch")
        m = nn.Conv2D(3, 8, 3, stride=2, padding=1)
        x = jax.random.normal(KEY, (2, 9, 9, 3))
        v = m.init(KEY, x)
        y = m(v, x)
        tw = torch.tensor(np.asarray(v["params"]["weight"])).permute(3, 2, 0, 1)
        tx = torch.tensor(np.asarray(x)).permute(0, 3, 1, 2)
        ty = torch.nn.functional.conv2d(
            tx, tw, torch.tensor(np.asarray(v["params"]["bias"])), stride=2,
            padding=1)
        np.testing.assert_allclose(
            np.asarray(y), ty.permute(0, 2, 3, 1).numpy(), rtol=1e-4, atol=1e-4)

    def test_groups(self):
        m = nn.Conv2D(4, 8, 3, groups=2, padding="SAME")
        x = jnp.ones((1, 5, 5, 4))
        v = m.init(KEY, x)
        assert v["params"]["weight"].shape == (3, 3, 2, 8)
        assert m(v, x).shape == (1, 5, 5, 8)


class TestConv1DCausal:
    def test_causal_no_future_leak(self):
        m = nn.Conv1D(1, 1, kernel_size=3, causal=True, dilation=2)
        x = jnp.zeros((1, 10, 1))
        v = m.init(KEY, x)
        bumped = x.at[0, 5, 0].set(1.0)
        y0 = m(v, x)
        y1 = m(v, bumped)
        diff = np.asarray(jnp.abs(y1 - y0)[0, :, 0])
        assert diff[:5].max() == 0.0  # strictly before the bump: unchanged
        assert diff[5:].max() > 0.0


class TestPooling:
    def test_maxpool(self):
        torch = pytest.importorskip("torch")
        m = nn.MaxPool2D(2, 2)
        x = jax.random.normal(KEY, (1, 6, 6, 2))
        y = m({}, x)
        tx = torch.tensor(np.asarray(x)).permute(0, 3, 1, 2)
        ty = torch.nn.functional.max_pool2d(tx, 2, 2).permute(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(y), ty.numpy(), rtol=1e-6)

    def test_avgpool(self):
        m = nn.AvgPool2D(2, 2)
        x = jnp.arange(16.0).reshape(1, 4, 4, 1)
        y = m({}, x)
        assert float(y[0, 0, 0, 0]) == pytest.approx((0 + 1 + 4 + 5) / 4)


class TestBatchNorm:
    def test_train_normalizes_and_updates_state(self):
        m = nn.BatchNorm()
        x = 3.0 + 2.0 * jax.random.normal(KEY, (64, 5))
        v = m.init(KEY, x)
        y, st = m.apply(v, x, training=True)
        assert abs(float(jnp.mean(y))) < 1e-4
        assert abs(float(jnp.std(y)) - 1.0) < 1e-2
        assert float(st["running_mean"][0]) != 0.0

    def test_eval_uses_running_stats(self):
        m = nn.BatchNorm(momentum=1.0)
        x = jax.random.normal(KEY, (128, 3)) * 4 + 1
        v = m.init(KEY, x)
        _, st = m.apply(v, x, training=True)
        v2 = {"params": v["params"], "state": st}
        y, _ = m.apply(v2, x, training=False)
        assert abs(float(jnp.mean(y))) < 1e-2


class TestDropout:
    def test_eval_identity(self):
        m = nn.Dropout(0.5)
        x = jnp.ones((4, 4))
        assert np.allclose(np.asarray(m({}, x)), 1.0)

    def test_train_scales(self):
        m = nn.Dropout(0.5)
        x = jnp.ones((1000,))
        y, _ = m.apply({}, x, training=True, rng=KEY)
        vals = np.unique(np.asarray(y))
        assert set(np.round(vals, 3)).issubset({0.0, 2.0})


class TestSequentialAndContainers:
    def test_mlp_shapes(self):
        model = nn.Sequential([
            nn.Linear(10, 32), nn.ReLU(), nn.Dropout(0.1), nn.Linear(32, 4),
            nn.LogSoftMax(),
        ])
        x = jnp.ones((8, 10))
        v = model.init(KEY, x)
        y, _ = model.apply(v, x, training=True, rng=KEY)
        assert y.shape == (8, 4)
        np.testing.assert_allclose(np.asarray(jnp.exp(y).sum(-1)), 1.0, atol=1e-4)

    def test_concat(self):
        m = nn.Concat([nn.Linear(4, 2), nn.Linear(4, 3)], dim=-1)
        x = jnp.ones((5, 4))
        v = m.init(KEY, x)
        assert m(v, x).shape == (5, 5)

    def test_concat_table_and_cadd(self):
        m = nn.Sequential([
            nn.ConcatTable([nn.Linear(4, 4), nn.Identity()]),
            nn.CAddTable(),
        ])
        x = jnp.ones((2, 4))
        v = m.init(KEY, x)
        assert m(v, x).shape == (2, 4)


class TestEmbedding:
    def test_lookup(self):
        m = nn.Embedding(10, 4)
        idx = jnp.array([[1, 2], [3, 4]])
        v = m.init(KEY, idx)
        y = m(v, idx)
        assert y.shape == (2, 2, 4)
        np.testing.assert_allclose(
            np.asarray(y[0, 0]), np.asarray(v["params"]["weight"][1]))


class TestCriterions:
    def test_cross_entropy_matches_torch(self):
        torch = pytest.importorskip("torch")
        logits = jax.random.normal(KEY, (6, 5))
        labels = jnp.array([0, 1, 2, 3, 4, 0])
        loss = nn.CrossEntropyCriterion()(logits, labels)
        tl = torch.nn.functional.cross_entropy(
            torch.tensor(np.asarray(logits)),
            torch.tensor(np.asarray(labels)).long())
        assert float(loss) == pytest.approx(float(tl), rel=1e-5)

    def test_classnll_is_ce_after_logsoftmax(self):
        logits = jax.random.normal(KEY, (6, 5))
        labels = jnp.array([0, 1, 2, 3, 4, 0])
        logp = jax.nn.log_softmax(logits)
        a = nn.ClassNLLCriterion()(logp, labels)
        b = nn.CrossEntropyCriterion()(logits, labels)
        assert float(a) == pytest.approx(float(b), rel=1e-6)

    def test_mse_and_abs(self):
        a = jnp.array([1.0, 2.0])
        b = jnp.array([0.0, 0.0])
        assert float(nn.MSECriterion()(a, b)) == pytest.approx(2.5)
        assert float(nn.AbsCriterion()(a, b)) == pytest.approx(1.5)

    def test_bce_with_logits_matches_torch(self):
        torch = pytest.importorskip("torch")
        x = jax.random.normal(KEY, (8,))
        t = (jax.random.uniform(jax.random.PRNGKey(1), (8,)) > 0.5).astype(
            jnp.float32)
        loss = nn.BCEWithLogitsCriterion()(x, t)
        tl = torch.nn.functional.binary_cross_entropy_with_logits(
            torch.tensor(np.asarray(x)), torch.tensor(np.asarray(t)))
        assert float(loss) == pytest.approx(float(tl), rel=1e-5)


class TestSpaceToDepthStem:
    def test_exactly_matches_7x7_stride2_conv(self):
        import jax
        import jax.numpy as jnp

        from bigdl_tpu import nn
        from bigdl_tpu.models.resnet import SpaceToDepthStem, pack_stem_kernel

        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(2, 32, 32, 3), jnp.float32)
        k7 = jnp.asarray(rs.randn(7, 7, 3, 8) * 0.1, jnp.float32)

        conv = nn.Conv2D(3, 8, 7, stride=2, padding="SAME", with_bias=False)
        ref, _ = conv.forward({"weight": k7}, {}, x)

        stem = SpaceToDepthStem(8)
        got, _ = stem.forward({"weight": pack_stem_kernel(k7)}, {}, x)

        assert got.shape == ref.shape == (2, 16, 16, 8)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_resnet50_s2d_variant_trains(self):
        import jax
        import jax.numpy as jnp

        from bigdl_tpu.models.resnet import resnet50

        model = resnet50(classes=10, stem="s2d")
        rng = jax.random.PRNGKey(0)
        x = jnp.asarray(np.random.RandomState(0).rand(2, 64, 64, 3),
                        jnp.float32)
        variables = model.init(rng, x)
        params, state = variables["params"], variables.get("state", {})

        def loss_fn(p):
            out, _ = model.forward(p, state, x, training=True, rng=rng)
            return -out[:, 0].mean()  # logsoftmax head

        l, g = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(l))
        gn = sum(float(jnp.sum(jnp.abs(a))) for a in
                 jax.tree_util.tree_leaves(g))
        assert gn > 0

    def test_odd_input_rejected(self):
        import jax
        import jax.numpy as jnp

        from bigdl_tpu.models.resnet import SpaceToDepthStem

        stem = SpaceToDepthStem(8)
        with pytest.raises(ValueError, match="even"):
            stem.build(jax.random.PRNGKey(0),
                       jnp.zeros((1, 33, 32, 3), jnp.float32))
