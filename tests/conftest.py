"""Test bootstrap: simulate an 8-device TPU mesh on CPU.

This is the analog of the reference's ``local[N]`` / local-cluster Spark tests
(SURVEY.md §5): distribution is exercised for real (XLA collectives run) inside
one process with 8 virtual devices.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Hermetic kernel-autotune cache: without this, any kernel called with
# default (None) tiles would consult the developer's real
# ~/.cache/bigdl_tpu/autotune and parity tests would compile whatever
# tiles that machine once tuned — test behavior must not depend on
# machine state.  Tests that exercise the cache itself redirect this
# again via monkeypatch.
if "BIGDL_TPU_AUTOTUNE_CACHE" not in os.environ:
    import tempfile

    os.environ["BIGDL_TPU_AUTOTUNE_CACHE"] = tempfile.mkdtemp(
        prefix="bigdl_tpu_autotune_test_")

import jax  # noqa: E402

# NOTE: this image's JAX build (axon platform plugin) ignores the
# JAX_PLATFORMS *env var*; the config update below is what actually forces
# CPU. Keep both — the env vars still gate XLA_FLAGS device-count parsing.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)
# Golden-parity tests need exact f32 matmuls; production keeps the fast
# TPU-native default (bf16 passes on MXU).
jax.config.update("jax_default_matmul_precision", "highest")

# Persistent compile cache: the suite spends most of its wall time
# re-compiling the same tiny XLA programs run after run.  OPT-IN
# (BIGDL_TPU_TEST_CACHE=1): on this image's jax build, deserializing a
# cached XLA:CPU executable segfaults nondeterministically (~30-50% for
# the donated shard_map train step — reproducible via
# test_ema_checkpoints_and_survives_resume with the cache on), and one
# segfault kills the whole pytest process.  A slow suite beats a
# truncated one.
if os.environ.get("BIGDL_TPU_TEST_CACHE", "0") in ("1", "true"):
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(__file__), "..", ".jax_test_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # older jax without the knobs
        pass

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

    return build_mesh(MeshSpec())


@pytest.fixture(autouse=True)
def _reset_engine():
    yield
    from bigdl_tpu.runtime.engine import Engine

    Engine.reset()
