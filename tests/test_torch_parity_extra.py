"""Torch-golden-parity sweep, part 2 (VERDICT r4 item 8): cases weighted
toward the quantization / QAT / LoRA surface, plus layer families the main
sweep (test_torch_parity.py) does not cover.

Quantization parity strategy: our int8 kernels do exact integer
accumulation then rescale; torch.ao's fake-quant path computes the float
op over dequantized values.  For int8 operands the products are exact in
f32 (|q| <= 127, sums << 2^24 at these K), so the two must agree to float
rounding — any larger deviation is a real quantization-grid or scale bug.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.nn.module import EMPTY
from test_torch_parity import check_forward_and_grad, t_

RNG = jax.random.PRNGKey(7)
RS = np.random.RandomState(7)


# ---------------------------------------------------------------------------
# 1. fake-quant grid parity: ours vs torch.fake_quantize_*
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("scale", [0.01, 0.1, 0.37])
def test_fake_quant_per_tensor_matches_torch(scale):
    from bigdl_tpu.nn.qat import fake_quant

    x = RS.randn(64, 32).astype(np.float32) * 2.0
    ours = np.asarray(fake_quant(jnp.asarray(x), scale))
    theirs = torch.fake_quantize_per_tensor_affine(
        t_(x), scale=scale, zero_point=0, quant_min=-127, quant_max=127)
    np.testing.assert_allclose(ours, theirs.numpy(), atol=1e-6, rtol=0)


def test_fake_quant_ste_gradient_matches_torch_in_range():
    """STE backward: identity within the quant range (torch zeroes the
    gradient outside it; ours is used only with in-range amax scales)."""
    from bigdl_tpu.nn.qat import fake_quant

    x = np.clip(RS.randn(16, 8), -1.2, 1.2).astype(np.float32)
    scale = 1.27 / 127.0 * 1.3  # range covers |x| <= 1.3*1.27

    g_ours = np.asarray(jax.grad(
        lambda z: jnp.sum(fake_quant(z, scale) ** 2))(jnp.asarray(x)))
    tx = t_(x).requires_grad_(True)
    ty = torch.fake_quantize_per_tensor_affine(tx, scale, 0, -127, 127)
    (ty ** 2).sum().backward()
    np.testing.assert_allclose(g_ours, tx.grad.numpy(), atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("axis,shape", [(0, (64, 48)), (1, (64, 48)),
                                        (0, (33, 7)), (1, (7, 33))])
def test_quantize_int8_grid_matches_torch_per_channel(axis, shape):
    """Our per-channel symmetric int8 grid == torch's per-channel affine
    grid (zero_point 0) given the same scales."""
    from bigdl_tpu.ops.quantized import dequantize_int8, quantize_int8

    w = (RS.randn(*shape) * 3.0).astype(np.float32)
    w_q, scales = quantize_int8(jnp.asarray(w), axis=axis)
    # torch wants the CHANNEL axis (the non-reduced one)
    ch_axis = 1 - axis
    theirs = torch.fake_quantize_per_channel_affine(
        t_(w), t_(np.asarray(scales, np.float32)),
        torch.zeros(shape[ch_axis], dtype=torch.int32),
        ch_axis, -127, 127)
    ours_dq = np.asarray(dequantize_int8(w_q, scales, axis=axis))
    np.testing.assert_allclose(ours_dq, theirs.numpy(), atol=1e-6, rtol=0)
    assert np.asarray(w_q).dtype == np.int8
    assert np.abs(np.asarray(w_q)).max() <= 127


# ---------------------------------------------------------------------------
# 2. weight-only int8 layers vs torch float op over fake-quantized weight
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("din,dout,bias", [(32, 16, True), (48, 8, False),
                                           (17, 5, True)])
def test_weight_only_linear_matches_torch(din, dout, bias):
    from bigdl_tpu.nn.quantized import WeightOnlyLinear

    layer = nn.Linear(din, dout, with_bias=bias)
    x = RS.randn(6, din).astype(np.float32)
    variables = layer.init(RNG, jnp.asarray(x))
    params = dict(variables["params"])
    q, qp = WeightOnlyLinear.from_linear(layer, params)
    y_ours, _ = q.forward(qp, EMPTY, jnp.asarray(x))

    w = np.asarray(params["weight"])  # (in, out)
    scales = np.abs(w).max(axis=0) / 127.0
    w_fq = torch.fake_quantize_per_channel_affine(
        t_(w), t_(scales.astype(np.float32)),
        torch.zeros(dout, dtype=torch.int32), 1, -127, 127)
    ty = t_(x) @ w_fq
    if bias:
        ty = ty + t_(np.asarray(params["bias"]))
    np.testing.assert_allclose(np.asarray(y_ours), ty.numpy(),
                               atol=2e-5, rtol=1e-5)


@pytest.mark.parametrize("stride,groups", [(1, 1), (2, 1), (1, 4)])
def test_weight_only_conv2d_matches_torch(stride, groups):
    from bigdl_tpu.nn.quantized import WeightOnlyConv2D

    cin, cout, k = 8, 12, 3
    layer = nn.Conv2D(cin, cout, k, stride=stride, padding="same",
                      groups=groups)
    # odd spatial size: XLA SAME padding is symmetric here, matching
    # torch's padding=k//2 (even size + stride 2 pads asymmetrically)
    x = RS.randn(2, 9, 9, cin).astype(np.float32)
    variables = layer.init(RNG, jnp.asarray(x))
    params = dict(variables["params"])
    q, qp = WeightOnlyConv2D.from_conv(layer, params)
    y_ours, _ = q.forward(qp, EMPTY, jnp.asarray(x))

    w = np.asarray(params["weight"])  # (kh, kw, cin/g, cout)
    scales = np.abs(w).max(axis=(0, 1, 2)) / 127.0
    w_fq = torch.fake_quantize_per_channel_affine(
        t_(w), t_(scales.astype(np.float32)),
        torch.zeros(cout, dtype=torch.int32), 3, -127, 127)
    tconv = torch.nn.Conv2d(cin, cout, k, stride=stride,
                            padding=k // 2, groups=groups)
    with torch.no_grad():
        tconv.weight.copy_(w_fq.permute(3, 2, 0, 1))  # HWIO -> OIHW
        tconv.bias.copy_(t_(np.asarray(params["bias"])))
    ty = tconv(t_(np.transpose(x, (0, 3, 1, 2))))
    np.testing.assert_allclose(
        np.asarray(y_ours), np.transpose(ty.detach().numpy(), (0, 2, 3, 1)),
        atol=5e-5, rtol=1e-4)


# ---------------------------------------------------------------------------
# 3. full int8 layers (dynamic activation quant) vs torch.ao-style reference
# ---------------------------------------------------------------------------


def _torch_dynamic_int8_linear(x, w, bias):
    """torch.ao-style reference: per-row dynamic act fake-quant +
    per-out-channel weight fake-quant + float matmul (what
    torch.ao.nn.quantized.dynamic.Linear computes, in float form)."""
    tx = t_(x)
    row_scale = tx.abs().amax(dim=1, keepdim=True).clamp(min=1e-8) / 127.0
    x_fq = (tx / row_scale).round().clamp(-127, 127) * row_scale
    w_scales = t_(np.abs(w).max(axis=0).astype(np.float32)) / 127.0
    w_fq = torch.fake_quantize_per_channel_affine(
        t_(w), w_scales, torch.zeros(w.shape[1], dtype=torch.int32),
        1, -127, 127)
    y = x_fq @ w_fq
    if bias is not None:
        y = y + t_(bias)
    return y.numpy()


@pytest.mark.parametrize("din,dout", [(64, 24), (128, 10)])
def test_quantized_linear_matches_torch_dynamic(din, dout):
    from bigdl_tpu.nn.quantized import QuantizedLinear

    layer = nn.Linear(din, dout)
    x = RS.randn(5, din).astype(np.float32)
    variables = layer.init(RNG, jnp.asarray(x))
    params = dict(variables["params"])
    q, qp = QuantizedLinear.from_linear(layer, params)
    y_ours, _ = q.forward(qp, EMPTY, jnp.asarray(x))
    ref = _torch_dynamic_int8_linear(
        x, np.asarray(params["weight"]), np.asarray(params["bias"]))
    # int accumulation is exact on both sides at this K; agreement is to
    # float rounding of the rescale
    np.testing.assert_allclose(np.asarray(y_ours), ref, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("stride,groups", [(1, 1), (2, 1), (1, 2)])
def test_quantized_conv2d_matches_torch_style_reference(stride, groups):
    """Our int8 conv (channel-major im2col + int8 matmul, DYNAMIC
    per-output-position activation scales) vs a torch reference doing the
    same dynamic quantization over ``F.unfold`` patches — torch.ao's
    dynamic-quant recipe applied to the unfolded conv.  ``F.unfold``
    flattens patches channel-major (C, kh, kw), the same row order our
    ``conv_general_dilated_patches`` path uses."""
    from bigdl_tpu.nn.quantized import QuantizedConv2D

    cin, cout, k = 6, 8, 3
    layer = nn.Conv2D(cin, cout, k, stride=stride, padding="same",
                      groups=groups)
    x = RS.randn(2, 9, 9, cin).astype(np.float32)  # odd: SAME == pad k//2
    variables = layer.init(RNG, jnp.asarray(x))
    params = dict(variables["params"])
    q, qp = QuantizedConv2D.from_conv(layer, params)
    y_ours, _ = q.forward(qp, EMPTY, jnp.asarray(x))

    tx = t_(np.transpose(x, (0, 3, 1, 2)))
    patches = torch.nn.functional.unfold(
        tx, k, padding=k // 2, stride=stride)     # (N, C*k*k, L)
    pat = patches.transpose(1, 2).reshape(-1, cin * k * k)  # (M, rows)
    g, cin_g, og = groups, cin // groups, cout // groups
    pat = pat.reshape(pat.shape[0], g, cin_g * k * k)       # (M, g, rows)
    row_scale = pat.abs().amax(dim=2, keepdim=True).clamp(min=1e-8) / 127.0
    pat_fq = (pat / row_scale).round().clamp(-127, 127) * row_scale

    w = np.asarray(params["weight"])              # (kh, kw, cin_g, cout)
    w2 = t_(w.transpose(2, 0, 1, 3).reshape(cin_g * k * k, cout))
    outs = []
    for j in range(g):
        wg = w2[:, j * og:(j + 1) * og]           # (rows, og)
        w_scales = wg.abs().amax(dim=0).clamp(min=1e-12) / 127.0
        wg_fq = (wg / w_scales).round().clamp(-127, 127) * w_scales
        outs.append(pat_fq[:, j, :] @ wg_fq)      # (M, og)
    ref = torch.cat(outs, dim=1) + t_(np.asarray(params["bias"]))
    n, _, h, wdt = tx.shape
    oh = ow = (h + 2 * (k // 2) - k) // stride + 1
    ref = ref.reshape(n, oh, ow, cout).numpy()
    np.testing.assert_allclose(np.asarray(y_ours), ref,
                               atol=1e-3, rtol=1e-3)


# ---------------------------------------------------------------------------
# 4. QAT layers vs torch fake-quant reference
# ---------------------------------------------------------------------------


def test_qat_linear_matches_torch_fake_quant():
    from bigdl_tpu.nn.qat import QATLinear

    din, dout = 32, 12
    inner = nn.Linear(din, dout)
    x = RS.randn(4, din).astype(np.float32)
    qat = QATLinear(inner)
    variables = qat.init(RNG, jnp.asarray(x))
    params = dict(variables["params"])
    amax = float(np.abs(x).max())
    state = {"act_amax": jnp.asarray(amax, jnp.float32)}

    y_ours, _ = qat.forward(params, state, jnp.asarray(x), training=False)

    a_scale = amax / 127.0
    x_fq = torch.fake_quantize_per_tensor_affine(
        t_(x), a_scale, 0, -127, 127)
    w = np.asarray(params["weight"])
    w_scales = np.maximum(np.abs(w).max(axis=0), 1e-8) / 127.0
    w_fq = torch.fake_quantize_per_channel_affine(
        t_(w), t_(w_scales.astype(np.float32)),
        torch.zeros(dout, dtype=torch.int32), 1, -127, 127)
    ref = x_fq @ w_fq + t_(np.asarray(params["bias"]))
    np.testing.assert_allclose(np.asarray(y_ours), ref.numpy(),
                               atol=2e-5, rtol=1e-5)


def test_qat_conv2d_matches_torch_fake_quant():
    from bigdl_tpu.nn.qat import QATConv2D

    cin, cout, k = 4, 6, 3
    inner = nn.Conv2D(cin, cout, k, padding="same")
    x = RS.randn(2, 7, 7, cin).astype(np.float32)
    qat = QATConv2D(inner)
    variables = qat.init(RNG, jnp.asarray(x))
    params = dict(variables["params"])
    amax = float(np.abs(x).max())
    state = {"act_amax": jnp.asarray(amax, jnp.float32)}
    y_ours, _ = qat.forward(params, state, jnp.asarray(x), training=False)

    x_fq = torch.fake_quantize_per_tensor_affine(
        t_(np.transpose(x, (0, 3, 1, 2))), amax / 127.0, 0, -127, 127)
    w = np.asarray(params["weight"])
    w_scales = np.maximum(np.abs(w).max(axis=(0, 1, 2)), 1e-8) / 127.0
    w_fq = torch.fake_quantize_per_channel_affine(
        t_(w), t_(w_scales.astype(np.float32)),
        torch.zeros(cout, dtype=torch.int32), 3, -127, 127)
    tconv = torch.nn.Conv2d(cin, cout, k, padding=k // 2)
    with torch.no_grad():
        tconv.weight.copy_(w_fq.permute(3, 2, 0, 1))
        tconv.bias.copy_(t_(np.asarray(params["bias"])))
    ref = tconv(x_fq).detach().numpy()
    np.testing.assert_allclose(
        np.asarray(y_ours), np.transpose(ref, (0, 2, 3, 1)),
        atol=1e-4, rtol=1e-4)


def test_convert_qat_int8_close_to_fake_quant_model():
    """convert_qat's real-int8 model must track the QAT fake-quant model
    it was trained as (same grids — the whole point of QAT)."""
    from bigdl_tpu.nn.qat import convert_qat, prepare_qat

    model = nn.Sequential([nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8)])
    x = RS.randn(8, 16).astype(np.float32)
    variables = model.init(RNG, jnp.asarray(x))
    qat_model, qat_vars = prepare_qat(model, variables)
    # a few "training" forwards to populate the amax EMAs
    state = qat_vars["state"]
    for _ in range(4):
        y_fq, state = qat_model.forward(
            qat_vars["params"], state, jnp.asarray(x), training=True)
    qat_vars = {"params": qat_vars["params"], "state": state}
    y_fq, _ = qat_model.forward(
        qat_vars["params"], qat_vars["state"], jnp.asarray(x),
        training=False)

    int8_model, int8_vars = convert_qat(qat_model, qat_vars)
    y_int8, _ = int8_model.forward(
        int8_vars["params"], int8_vars.get("state", EMPTY), jnp.asarray(x),
        training=False)
    scale = float(np.abs(np.asarray(y_fq)).max())
    err = float(np.abs(np.asarray(y_int8) - np.asarray(y_fq)).max())
    assert err <= 0.05 * scale, (err, scale)


# ---------------------------------------------------------------------------
# 5. LoRA merge numerics vs torch
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("rank,alpha", [(2, 4.0), (8, 16.0), (4, 1.0)])
def test_lora_merge_matches_torch_math(rank, alpha):
    from bigdl_tpu.nn.lora import apply_lora, merge_lora

    din, dout = 24, 10
    model = nn.Sequential([nn.Linear(din, dout)])
    x = RS.randn(5, din).astype(np.float32)
    variables = model.init(RNG, jnp.asarray(x))
    lora_model, lora_vars = apply_lora(model, variables, rank=rank,
                                       alpha=alpha)
    # give the adapters non-trivial values (B inits to zero)
    p = dict(lora_vars["params"])
    leaf_key = next(iter(p))
    leaf = dict(p[leaf_key])
    leaf["lora_a"] = jnp.asarray(RS.randn(din, rank).astype(np.float32))
    leaf["lora_b"] = jnp.asarray(RS.randn(rank, dout).astype(np.float32))
    p[leaf_key] = leaf
    lora_vars = {"params": p, "state": lora_vars.get("state", EMPTY)}

    y_adapter, _ = lora_model.forward(
        lora_vars["params"], lora_vars.get("state", EMPTY), jnp.asarray(x),
        training=False)
    merged_model, merged_vars = merge_lora(lora_model, lora_vars)
    y_merged, _ = merged_model.forward(
        merged_vars["params"], merged_vars.get("state", EMPTY),
        jnp.asarray(x), training=False)

    # merged weight == torch's W + (alpha/r) A @ B
    w0 = t_(np.asarray(leaf["weight"]))
    tw = w0 + (alpha / rank) * (t_(np.asarray(leaf["lora_a"]))
                                @ t_(np.asarray(leaf["lora_b"])))
    got_w = np.asarray(merged_vars["params"][leaf_key]["weight"])
    np.testing.assert_allclose(got_w, tw.numpy(), atol=1e-5, rtol=1e-5)
    # and the merged forward equals the adapter forward
    np.testing.assert_allclose(np.asarray(y_merged), np.asarray(y_adapter),
                               atol=1e-4, rtol=1e-4)
    # merged leaves are plain Linear again
    assert type(merged_model.layers[0]).__name__ == "Linear"


# ---------------------------------------------------------------------------
# 6. layer families the main sweep misses
# ---------------------------------------------------------------------------


def test_threshold_parity():
    check_forward_and_grad(nn.Threshold(0.3, 0.0),
                           torch.nn.Threshold(0.3, 0.0),
                           RS.randn(4, 9).astype(np.float32) + 0.5)


def test_rrelu_eval_parity():
    # eval-mode RReLU is deterministic: slope (lower+upper)/2 on both sides
    check_forward_and_grad(nn.RReLU(0.1, 0.3),
                           torch.nn.RReLU(0.1, 0.3),
                           RS.randn(4, 9).astype(np.float32))


@pytest.mark.parametrize("mode", ["nearest", "bilinear"])
def test_upsampling2d_parity(mode):
    tmode = {"nearest": "nearest", "bilinear": "bilinear"}[mode]
    tmod = torch.nn.Upsample(scale_factor=2, mode=tmode,
                             **({"align_corners": False}
                                if mode == "bilinear" else {}))
    check_forward_and_grad(nn.UpSampling2D(2, mode=mode), tmod,
                           RS.randn(2, 5, 6, 3).astype(np.float32),
                           layout="nhwc", atol=1e-3, rtol=1e-3)


def test_zeropadding2d_parity():
    check_forward_and_grad(nn.ZeroPadding2D((2, 3)),
                           torch.nn.ZeroPad2d((3, 3, 2, 2)),
                           RS.randn(2, 5, 6, 3).astype(np.float32),
                           layout="nhwc")


def test_rmsnorm_parity():
    if not hasattr(torch.nn, "RMSNorm"):
        pytest.skip("torch too old for nn.RMSNorm")
    d = 16
    x = RS.randn(4, d).astype(np.float32)
    layer = nn.RMSNorm(d)
    tmod = torch.nn.RMSNorm(d, eps=1e-6)
    check_forward_and_grad(layer, tmod, x)


def test_normalize_parity():
    x = RS.randn(6, 12).astype(np.float32)
    layer = nn.Normalize(2)
    variables = layer.init(RNG, jnp.asarray(x))
    y, _ = layer.forward(variables["params"], variables["state"],
                         jnp.asarray(x))
    ref = torch.nn.functional.normalize(t_(x), p=2, dim=-1)
    np.testing.assert_allclose(np.asarray(y), ref.numpy(),
                               atol=1e-5, rtol=1e-5)


def test_clamp_parity():
    check_forward_and_grad(nn.Clamp(-0.4, 0.6),
                           torch.nn.Hardtanh(-0.4, 0.6),
                           RS.randn(5, 7).astype(np.float32))


@pytest.mark.parametrize("name,ours,theirs", [
    ("exp", lambda: nn.Exp(), lambda: torch.exp),
    ("abs", lambda: nn.Abs(), lambda: torch.abs),
    ("square", lambda: nn.Square(), lambda: torch.square),
])
def test_elementwise_parity(name, ours, theirs):
    layer, tfn = ours(), theirs()
    x = RS.randn(4, 6).astype(np.float32)
    variables = layer.init(RNG, jnp.asarray(x))
    y, _ = layer.forward(variables["params"], variables["state"],
                         jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y), tfn(t_(x)).numpy(),
                               atol=1e-5, rtol=1e-5)
