"""LoRA adapters — parameter-efficient fine-tuning (beyond the
reference, which predates PEFT)."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn.lora import (LoRALinear, apply_lora, lora_filter,
                               merge_lora)
from bigdl_tpu.nn.module import Sequential


def _setup(seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(64, 8).astype(np.float32)
    y = (x @ rs.randn(8, 2).astype(np.float32))
    model = Sequential([nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 2)])
    variables = model.init(jax.random.PRNGKey(0), x[:2])
    return model, variables, x, y


def test_lora_starts_as_identity():
    """B=0 init: the wrapped model computes exactly the base model."""
    model, variables, x, y = _setup()
    lmodel, lvars = apply_lora(model, variables, rank=4)
    assert sum(isinstance(m, LoRALinear) for m in lmodel.layers) == 2
    y0, _ = model.apply(variables, x)
    y1, _ = lmodel.apply(lvars, x)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1), atol=1e-6)


def test_lora_trains_adapters_only_and_merges():
    model, variables, x, y = _setup()
    lmodel, lvars = apply_lora(model, variables, rank=4, alpha=8.0)
    params = lvars["params"]
    mask = lora_filter(params)
    n_trainable = sum(int(np.prod(np.shape(l))) for l, m in zip(
        jax.tree_util.tree_leaves(params),
        jax.tree_util.tree_leaves(mask)) if m)
    n_total = sum(int(np.prod(np.shape(l)))
                  for l in jax.tree_util.tree_leaves(params))
    assert 0 < n_trainable < n_total / 2  # genuinely parameter-efficient

    base_before = {k: np.asarray(v["weight"]).copy()
                   for k, v in params.items() if "weight" in v}

    @jax.jit
    def step(p):
        def loss_fn(p):
            out, _ = lmodel.forward(p, {}, jnp.asarray(x))
            return jnp.mean((out - jnp.asarray(y)) ** 2)

        l, g = jax.value_and_grad(loss_fn)(p)
        # adapters-only update: gradient masked by the lora filter
        g = jax.tree_util.tree_map(
            lambda gi, mi: gi if mi else jnp.zeros_like(gi), g, mask)
        return jax.tree_util.tree_map(lambda a, b: a - 0.05 * b, p, g), l

    l0 = None
    for i in range(120):
        params, loss = step(params)
        if l0 is None:
            l0 = float(loss)
    assert float(loss) < 0.5 * l0, (l0, float(loss))
    # base weights untouched
    for k, w0 in base_before.items():
        np.testing.assert_array_equal(np.asarray(params[k]["weight"]), w0)

    # merge: dense model reproduces the adapted model exactly
    lvars = {"params": params, "state": {}}
    dmodel, dvars = merge_lora(lmodel, lvars)
    assert all(not isinstance(m, LoRALinear) for m in dmodel.layers)
    y_l, _ = lmodel.apply(lvars, x)
    y_d, _ = dmodel.apply(dvars, x)
    np.testing.assert_allclose(np.asarray(y_l), np.asarray(y_d), atol=1e-5)


def test_lora_on_keras_model():
    from bigdl_tpu.keras.engine import Input, Model

    inp = Input((8,))
    h = nn.Linear(8, 16)(inp)
    h = nn.ReLU()(h)
    out = nn.Linear(16, 3)(h)
    model = Model(inp, out)
    rs = np.random.RandomState(1)
    x = rs.randn(16, 8).astype(np.float32)
    v = model.init(jax.random.PRNGKey(0), jnp.asarray(x))

    lmodel, lvars = apply_lora(model, v, rank=2)
    assert sum(isinstance(n.layer, LoRALinear) for n in lmodel.order) == 2
    y0, _ = model.apply(v, jnp.asarray(x))
    y1, _ = lmodel.apply(lvars, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1), atol=1e-6)

    dmodel, dvars = merge_lora(lmodel, lvars)
    y2, _ = dmodel.apply(dvars, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y2), atol=1e-5)


def test_match_predicate_selects_layers():
    model, variables, x, y = _setup()
    lmodel, lvars = apply_lora(
        model, variables, rank=2,
        match=lambda lin: lin.out_features == 2)  # only the head
    assert sum(isinstance(m, LoRALinear) for m in lmodel.layers) == 1
    assert isinstance(lmodel.layers[2], LoRALinear)


def test_lora_on_converted_torch_model():
    """PEFT the interop path: a stock torch MLP converts to a keras graph
    whose Linear nodes LoRA can wrap (adapt a converted model without
    touching its imported weights)."""
    import torch

    from bigdl_tpu.utils.torch_convert import from_torch_module

    tm = torch.nn.Sequential(
        torch.nn.Linear(8, 16), torch.nn.ReLU(), torch.nn.Linear(16, 4))
    model, variables = from_torch_module(
        tm, example_input=torch.zeros(1, 8))

    lmodel, lvars = apply_lora(model, variables, rank=2)
    n_wrapped = sum(isinstance(n.layer, LoRALinear)
                    for n in getattr(lmodel, "order", []))
    assert n_wrapped == 2, n_wrapped

    x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    y0, _ = model.apply(variables, jnp.asarray(x))
    y1, _ = lmodel.apply(lvars, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1), atol=1e-6)


def test_lora_through_sharded_parameter_step():
    """trainable_mask on the production ZeRO-1 engine: adapters train,
    the frozen base stays BITWISE identical even under a weight-decay
    optimizer (which would otherwise drift zero-grad params)."""
    from bigdl_tpu.optim.optim_method import AdamWeightDecay
    from bigdl_tpu.optim.train_step import ShardedParameterStep
    from bigdl_tpu.nn.criterion import MSECriterion
    from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

    model, variables, x, y = _setup()
    lmodel, lvars = apply_lora(model, variables, rank=4)
    mask = lora_filter(lvars["params"])
    mesh = build_mesh(MeshSpec(data=8))
    step = ShardedParameterStep(
        lmodel, MSECriterion(),
        AdamWeightDecay(learning_rate=5e-3, weight_decay=0.1),
        mesh, lvars, trainable_mask=mask)

    base_before = {k: np.asarray(v["weight"]).copy()
                   for k, v in lvars["params"].items() if "weight" in v}
    rng = jax.random.PRNGKey(0)
    losses = [float(step.train_step(i, rng, x, y)) for i in range(40)]
    assert losses[-1] < 0.6 * losses[0], (losses[0], losses[-1])

    after = step.get_variables()["params"]
    for k, w0 in base_before.items():
        np.testing.assert_array_equal(np.asarray(after[k]["weight"]), w0)
    # adapters actually moved
    moved = sum(float(np.abs(np.asarray(after[k]["lora_b"])).sum())
                for k in after if "lora_b" in after[k])
    assert moved > 0
