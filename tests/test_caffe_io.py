"""Caffe import/export tests — reference `utils/caffe` CaffeLoader/Persister
specs.  Foreign nets are fabricated with the wire codec; round-trips check
export→import numerics across the NHWC↔NCHW boundary.
"""

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.keras.engine import Input, Model
from bigdl_tpu.nn.module import Sequential
from bigdl_tpu.utils.caffe import (
    Msg, UnsupportedCaffeLayer, _encode_blob, _decode_blob, load_caffe,
    parse_caffe_net, save_caffe,
)


def test_blob_roundtrip():
    arr = np.random.RandomState(0).randn(4, 3, 2).astype(np.float32)
    out = _decode_blob(bytes(_encode_blob(arr).buf))
    np.testing.assert_array_equal(out, arr)


def _layer_msg(name, type_, bottoms, tops, blobs=(), **params):
    from bigdl_tpu.utils.caffe import _PARAM_FIELDS
    m = Msg().string(1, name).string(2, type_)
    for b in bottoms:
        m.string(3, b)
    for t in tops:
        m.string(4, t)
    for blob in blobs:
        m.msg(7, _encode_blob(blob))
    field_of = {v: k for k, v in _PARAM_FIELDS.items()}
    for pname, pmsg in params.items():
        m.msg(field_of[pname], pmsg)
    return m


def _input_layer(name, nchw):
    bs = Msg()
    for d in nchw:
        bs.varint(1, int(d))
    return _layer_msg(name, "Input", [], [name], input=Msg().msg(1, bs))


def test_import_foreign_lenet_style_net():
    """Conv→ReLU(in-place)→Pool→IP→Softmax fabricated as caffe would freeze
    it, verified against a hand NCHW computation."""
    rng = np.random.RandomState(1)
    wconv = rng.randn(4, 1, 3, 3).astype(np.float32)  # (cout, cin, kh, kw)
    bconv = rng.randn(4).astype(np.float32)
    wip = rng.randn(2, 4 * 3 * 3).astype(np.float32)  # NCHW-flat columns
    bip = rng.randn(2).astype(np.float32)

    net = Msg().string(1, "lenet-ish")
    net.msg(100, _input_layer("data", (1, 1, 8, 8)))
    conv_p = (Msg().varint(1, 4).varint(2, 1).varint(4, 3).varint(6, 2))
    net.msg(100, _layer_msg("conv1", "Convolution", ["data"], ["conv1"],
                            [wconv, bconv], convolution=conv_p))
    net.msg(100, _layer_msg("relu1", "ReLU", ["conv1"], ["conv1"]))  # in-place
    pool_p = Msg().varint(1, 0).varint(2, 1)  # MAX 1x1 (identity pool)
    net.msg(100, _layer_msg("pool1", "Pooling", ["conv1"], ["pool1"],
                            pooling=pool_p))
    ip_p = Msg().varint(1, 2).varint(2, 1)
    net.msg(100, _layer_msg("ip1", "InnerProduct", ["pool1"], ["ip1"],
                            [wip, bip], inner_product=ip_p))
    net.msg(100, _layer_msg("prob", "Softmax", ["ip1"], ["prob"]))

    model, variables = load_caffe(net.bytes())

    x_nhwc = rng.randn(1, 8, 8, 1).astype(np.float32)
    y, _ = model.apply(variables, x_nhwc)

    # hand NCHW reference
    from scipy_free_conv import conv2d_nchw  # noqa — defined below
    x = np.transpose(x_nhwc, (0, 3, 1, 2))
    h = conv2d_nchw(x, wconv, stride=2) + bconv[None, :, None, None]
    h = np.maximum(h, 0)
    flat = h.reshape(1, -1)  # NCHW flatten
    logits = flat @ wip.T + bip
    e = np.exp(logits - logits.max())
    expect = e / e.sum()
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-4, atol=1e-5)


# tiny dependency-free NCHW conv used by the test above
import sys
import types

_m = types.ModuleType("scipy_free_conv")


def _conv2d_nchw(x, w, stride=1):
    n, cin, hh, ww = x.shape
    cout, _, kh, kw = w.shape
    oh = (hh - kh) // stride + 1
    ow = (ww - kw) // stride + 1
    out = np.zeros((n, cout, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, :, i * stride:i * stride + kh,
                      j * stride:j * stride + kw]
            out[:, :, i, j] = np.einsum("nchw,ochw->no", patch, w)
    return out


_m.conv2d_nchw = _conv2d_nchw
sys.modules["scipy_free_conv"] = _m


def test_import_bn_scale_fold_and_eltwise():
    rng = np.random.RandomState(2)
    mean = rng.randn(3).astype(np.float32)
    var = (1 + rng.rand(3)).astype(np.float32)
    gamma = rng.randn(3).astype(np.float32)
    beta = rng.randn(3).astype(np.float32)

    net = Msg().string(1, "bn-net")
    net.msg(100, _input_layer("data", (2, 3, 4, 4)))
    net.msg(100, _layer_msg("bn", "BatchNorm", ["data"], ["bn"],
                            [mean, var, np.asarray([1.0], np.float32)],
                            batch_norm=Msg().f32(3, 1e-5)))
    net.msg(100, _layer_msg("scale", "Scale", ["bn"], ["bn"],
                            [gamma, beta], scale=Msg().boolean(4, True)))
    net.msg(100, _layer_msg("sum", "Eltwise", ["bn", "data"], ["sum"],
                            eltwise=Msg().varint(1, 1)))

    model, variables = load_caffe(net.bytes())
    x = rng.randn(2, 4, 4, 3).astype(np.float32)
    y, _ = model.apply(variables, x)
    norm = (x - mean) / np.sqrt(var + 1e-5) * gamma + beta
    np.testing.assert_allclose(np.asarray(y), norm + x, rtol=1e-4, atol=1e-5)

    # Scale folded into BN affine: exactly one parametered layer
    bns = [n.layer for n in model.order
           if n.layer is not None and isinstance(n.layer, nn.BatchNorm)]
    assert len(bns) == 1


def test_import_concat_channel_axis():
    rng = np.random.RandomState(3)
    net = Msg().string(1, "concat-net")
    net.msg(100, _input_layer("a", (1, 2, 3, 3)))
    net.msg(100, _input_layer("b", (1, 5, 3, 3)))
    net.msg(100, _layer_msg("cat", "Concat", ["a", "b"], ["cat"],
                            concat=Msg().varint(2, 1)))
    model, variables = load_caffe(net.bytes())
    xa = rng.randn(1, 3, 3, 2).astype(np.float32)
    xb = rng.randn(1, 3, 3, 5).astype(np.float32)
    y, _ = model.apply(variables, xa, xb)
    np.testing.assert_allclose(np.asarray(y),
                               np.concatenate([xa, xb], axis=3))


def test_roundtrip_sequential_cnn():
    import jax

    model = Sequential([
        nn.Conv2D(2, 4, 3, padding=(1, 1)),
        nn.BatchNorm(4),
        nn.ReLU(),
        nn.MaxPool2D(2, ceil_mode=True),
        nn.Flatten(),
        nn.Linear(4 * 5 * 5, 7),
        nn.SoftMax(),
    ])
    rng = np.random.RandomState(4)
    x = rng.randn(2, 10, 10, 2).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    k = [k for k in variables["state"] if "BatchNorm" in k][0]
    variables["state"][k]["running_mean"] = rng.randn(4).astype(np.float32) * .1
    variables["state"][k]["running_var"] = (
        1.0 + 0.1 * rng.rand(4)).astype(np.float32)

    data = save_caffe(model, variables, sample=x)
    model2, vars2 = load_caffe(data)

    y1, _ = model.apply(variables, x)
    y2, _ = model2.apply(vars2, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-4, atol=1e-5)


def test_roundtrip_functional_residual():
    import jax

    inp = Input((6, 6, 3))
    a = nn.Conv2D(3, 3, 3, padding=(1, 1))(inp)
    a = nn.ReLU()(a)
    s = nn.CAddTable()([a, inp])
    out = nn.JoinTable(3)([s, a])
    model = Model(inp, out)
    rng = np.random.RandomState(5)
    x = rng.randn(2, 6, 6, 3).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(1), x)

    data = save_caffe(model, variables, sample=x)
    model2, vars2 = load_caffe(data)
    y1, _ = model.apply(variables, x)
    y2, _ = model2.apply(vars2, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-4, atol=1e-5)


def test_unsupported_layer_raises():
    net = Msg().string(1, "bad")
    net.msg(100, _input_layer("data", (1, 3, 4, 4)))
    net.msg(100, _layer_msg("crazy", "SPP", ["data"], ["crazy"]))
    with pytest.raises(UnsupportedCaffeLayer, match="SPP"):
        load_caffe(net.bytes())


def test_parse_caffe_net_structure():
    net = Msg().string(1, "mynet")
    net.msg(100, _input_layer("data", (1, 1, 2, 2)))
    net.msg(100, _layer_msg("r", "ReLU", ["data"], ["r"]))
    name, layers = parse_caffe_net(net.bytes())
    assert name == "mynet"
    assert [l.type for l in layers] == ["Input", "ReLU"]
    assert layers[1].bottoms == ["data"]


def test_bn_scale_not_folded_across_inplace_relu():
    """BN -> in-place ReLU -> Scale: gamma/beta must apply AFTER the relu."""
    rng = np.random.RandomState(6)
    mean = np.zeros(3, np.float32)
    var = np.ones(3, np.float32)
    gamma = rng.randn(3).astype(np.float32)
    beta = rng.randn(3).astype(np.float32)

    net = Msg().string(1, "bn-relu-scale")
    net.msg(100, _input_layer("data", (2, 3, 4, 4)))
    net.msg(100, _layer_msg("bn", "BatchNorm", ["data"], ["a"],
                            [mean, var, np.asarray([1.0], np.float32)],
                            batch_norm=Msg().f32(3, 1e-5)))
    net.msg(100, _layer_msg("relu", "ReLU", ["a"], ["a"]))  # in-place
    net.msg(100, _layer_msg("sc", "Scale", ["a"], ["out"],
                            [gamma, beta], scale=Msg().boolean(4, True)))
    model, variables = load_caffe(net.bytes())
    x = rng.randn(2, 4, 4, 3).astype(np.float32)
    y, _ = model.apply(variables, x)
    expect = np.maximum((x - mean) / np.sqrt(var + 1e-5), 0) * gamma + beta
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-4, atol=1e-5)


def test_floor_pool_export_guard():
    import jax

    # 2x2/2 pool on 10x10 tiles exactly -> exportable even in floor mode
    ok_model = Sequential([nn.MaxPool2D(2)])
    x = np.random.RandomState(7).randn(1, 10, 10, 2).astype(np.float32)
    v = ok_model.init(jax.random.PRNGKey(0), x)
    m2, v2 = load_caffe(save_caffe(ok_model, v, sample=x))
    y1, _ = ok_model.apply(v, x)
    y2, _ = m2.apply(v2, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2))

    # 2x2/2 on 5x5 floor-pools to 2x2 but caffe would ceil to 3x3 -> refuse
    bad = Sequential([nn.MaxPool2D(2)])
    xb = np.random.RandomState(8).randn(1, 5, 5, 2).astype(np.float32)
    vb = bad.init(jax.random.PRNGKey(0), xb)
    with pytest.raises(UnsupportedCaffeLayer, match="ceil"):
        save_caffe(bad, vb, sample=xb)
