"""Performance-attribution layer specs (docs/observability.md §Step-time
attribution, docs/performance.md §Regression sentinel).

Tier-1 coverage for the tentpole: per-step wall-time decomposition summing
back to the measured wall, the analytic cost model agreeing with bench.py's
ResNet-50 convention within 5%, the live train.mfu / collective-bytes
gauges on a real Optimizer run, the recompilation sentinel (counting,
expected-compile suppression, flight events), straggler stats, and the
perf-regression sentinel flagging a synthetic 20% throughput drop against
the committed trajectory."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from bigdl_tpu.obs import attr as obs_attr
from bigdl_tpu.obs import cost as obs_cost
from bigdl_tpu.obs import flight
from bigdl_tpu.obs import sentinel as obs_sentinel
from bigdl_tpu.optim.metrics import Metrics, global_metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_perf_obs():
    flight.global_recorder().clear()
    yield
    # a test that marked the process sentinel steady must not leak the
    # armed state into later tests' compiles
    obs_attr.recompile_sentinel().mark_warmup()


# ---------------------------------------------------------------------------
# StepAttribution
# ---------------------------------------------------------------------------

def test_step_attribution_components_sum_to_wall():
    m = Metrics()
    a = obs_attr.StepAttribution(m)
    a.window(steps=4, wall_s=1.0, data_s=0.2, dispatch_s=0.1,
             overhead_s=0.1)
    a.window(steps=4, wall_s=0.8, data_s=0.1, dispatch_s=0.1,
             overhead_s=0.0)
    rep = a.report()
    assert rep["steps"] == 8 and rep["windows"] == 2
    comp_sum = sum(c["total_s"] for c in rep["components"].values())
    assert comp_sum == pytest.approx(rep["wall_s"], rel=1e-9)
    assert rep["components"]["device"]["total_s"] == pytest.approx(1.2)
    fracs = {k: c["fraction"] for k, c in rep["components"].items()}
    assert sum(fracs.values()) == pytest.approx(1.0)
    # per-step samples landed in the train.attr.* histograms
    for name in obs_attr.COMPONENTS:
        assert m.percentile(f"train.attr.{name}_s", 50) >= 0
        assert m.hists[f"train.attr.{name}_s"].n == 2
    table = a.table()
    for name in obs_attr.COMPONENTS:
        assert name in table
    assert "8 steps" in table


def test_step_attribution_device_residual_clamps_at_zero():
    a = obs_attr.StepAttribution(Metrics())
    # host timers overlap the wall (clock skew): device clamps to 0, the
    # report never shows negative time
    a.window(steps=2, wall_s=0.1, data_s=0.08, dispatch_s=0.05,
             overhead_s=0.0)
    rep = a.report()
    assert rep["components"]["device"]["total_s"] == 0.0


def test_step_time_stats():
    s = obs_attr.step_time_stats([0.10, 0.12, 0.11, 0.19])
    assert s["max"] == pytest.approx(0.19)
    assert s["min"] == pytest.approx(0.10)
    assert s["skew"] == pytest.approx(0.09)
    assert s["n_hosts"] == 4
    assert obs_attr.step_time_stats([]) == {}
    # single process: the driver path returns None (nothing to aggregate)
    assert obs_attr.host_step_time_stats(0.1) is None


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------

def test_cost_model_linear_mlp_exact():
    import jax

    from bigdl_tpu import nn

    model = nn.Sequential([nn.Linear(32, 64), nn.ReLU(),
                           nn.Linear(64, 8)])
    x = np.zeros((16, 32), np.float32)
    variables = model.init(jax.random.PRNGKey(0), x[:1])
    rep = obs_cost.forward_costs(model, variables, x)
    # 2 * batch * (32*64 + 64*8) matmul flops + 2 flops/elem for the ReLU
    expect = 2 * 16 * (32 * 64 + 64 * 8) + 2 * 16 * 64
    assert rep.flops == pytest.approx(expect)
    assert rep.batch == 16
    assert rep.train_flops() == pytest.approx(3 * expect)
    # scaling to a different batch is linear
    assert obs_cost.train_step_flops(model, variables, (x[:1],), 160) \
        == pytest.approx(3 * expect * 10)
    # the shape-capture walk restored every forward (model still runs)
    y, _ = model.apply(variables, x)
    assert y.shape == (16, 8)


def test_cost_model_resnet50_matches_bench_analytic_within_5pct():
    """Acceptance: the per-layer analytic count on the bench geometry
    (ResNet-50 @224) agrees with bench.py's hardcoded analytic_3x_fwd
    convention (4.09 GMACs forward) within 5% — so the live train.mfu
    gauge and bench.py's analytic MFU agree whenever step time and peak
    agree (they share both other factors by construction)."""
    import jax

    from bigdl_tpu.models.resnet import resnet50

    model = resnet50(classes=1000, stem="conv")
    # init at 64x64: conv/BN/fc param shapes are spatial-size independent,
    # and the real forward that init runs is ~12x cheaper than at 224
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 64, 64, 3), np.float32))
    # the cost trace itself is jax.eval_shape — no FLOP executes at 224
    rep = obs_cost.forward_costs(
        model, variables, np.zeros((1, 224, 224, 3), np.float32))
    bench_fwd_flops = 2 * 4.09e9  # bench.py: ~4.09 GMACs fwd per image
    assert rep.flops == pytest.approx(bench_fwd_flops, rel=0.05)
    # and the training convention matches bench's 3x multiplier exactly
    import bench

    assert rep.train_flops() == pytest.approx(
        bench._RESNET50_TRAIN_FLOPS_PER_IMAGE, rel=0.05)


def test_cost_model_attention_counts_projections_and_scores():
    import jax

    from bigdl_tpu.nn.attention import MultiHeadAttention

    b, t, d = 2, 16, 32
    mha = MultiHeadAttention(hidden_size=d, num_heads=4)
    x = np.zeros((b, t, d), np.float32)
    variables = mha.init(jax.random.PRNGKey(0), x)
    rep = obs_cost.forward_costs(mha, variables, x)
    proj = 4 * 2 * b * t * d * d          # wq/wk/wv/wo
    scores = 4 * b * t * t * d            # qk^T + att@v
    assert rep.flops == pytest.approx(proj + scores)


def test_peak_flops_resolution(monkeypatch):
    monkeypatch.delenv("BIGDL_TPU_PEAK_FLOPS", raising=False)
    assert obs_cost.peak_flops("TPU v5 lite") == 197e12
    assert obs_cost.peak_flops("TPU v4") == 275e12
    assert obs_cost.peak_flops("cpu") is None
    assert obs_cost.peak_flops("cpu", override=1e12) == 1e12
    monkeypatch.setenv("BIGDL_TPU_PEAK_FLOPS", "5e11")
    # env pin wins over both the table and the explicit override
    assert obs_cost.peak_flops("TPU v4", override=1e12) == 5e11
    # 1e9 flops / 1ms / 2 chips = 5e11 FLOP/s/chip; peak 1e12 -> 50%
    assert obs_cost.mfu(1e9, 0.001, 2, 1e12) == pytest.approx(0.5)
    assert obs_cost.mfu(1e9, 0.001, 1, None) is None


def test_gspmd_collective_bytes_from_specs(mesh8):
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.parallel.gspmd import collective_bytes_for_specs

    params = {"w": np.zeros((4, 2), np.float32),
              "b": np.zeros((2,), np.float32)}
    specs = {"w": P(), "b": P()}
    rep = collective_bytes_for_specs(params, specs, mesh8)
    n_data = rep["n_data_replicas"]
    assert n_data == 8
    # fully replicated: every gradient element allreduces (~2x bytes)
    assert rep["dp_allreduce_bytes_per_step"] == pytest.approx(
        2 * (4 * 2 + 2) * 4)
    # a model-sharded parameter moves only its shard — shard the matrix
    # over the data axis (size 8) to exercise the divisor
    specs2 = {"w": P("data", None), "b": P()}
    rep2 = collective_bytes_for_specs(params, specs2, mesh8)
    assert rep2["grad_shard_bytes"] == pytest.approx((8 / 8 + 2) * 4)


# ---------------------------------------------------------------------------
# live gauges on a real Optimizer run
# ---------------------------------------------------------------------------

def _train(monkeypatch, iterations=12, batch_size=16):
    from bigdl_tpu import nn, optim
    from bigdl_tpu.data import ArrayDataSet

    monkeypatch.setenv("BIGDL_TPU_PEAK_FLOPS", "1e9")
    x = np.random.RandomState(0).rand(64, 4).astype(np.float32)
    y = (x.sum(-1) > 2).astype(np.int32)
    model = nn.Sequential([nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2),
                           nn.LogSoftMax()])
    opt = optim.Optimizer(model, ArrayDataSet(x, y),
                          nn.ClassNLLCriterion(), batch_size=batch_size)
    opt.set_end_when(optim.Trigger.max_iteration(iterations))
    opt.optimize()
    return opt


def test_optimizer_exports_attribution_and_live_mfu(monkeypatch):
    """Acceptance: a real run exports train.mfu / train.flops_per_step /
    train.attr.* / collective-bytes lines, and the attribution components
    sum to within 10% of the measured wall."""
    opt = _train(monkeypatch)
    snap = opt.metrics.snapshot()
    g = snap["gauges"]
    # analytic FLOPs/step: 3 * fwd * batch; fwd(batch=1) covers the two
    # matmuls plus the elementwise ReLU (8 out) and LogSoftMax (2 out)
    fwd1 = 2 * (4 * 8 + 8 * 2) + 2 * 8 + 2 * 2
    assert g["train.flops_per_step"] == pytest.approx(3 * fwd1 * 16)
    # live MFU is the same arithmetic the bench does: achieved/peak
    assert 0 < g["train.mfu"] < 1
    import jax

    assert g["train.mfu"] == pytest.approx(
        g["train.achieved_flops_per_chip"] / 1e9, rel=1e-6)
    assert g["train.achieved_flops_per_chip"] > 0
    # collective ledger: ZeRO-1 scatter+gather of the padded flat vector
    n_pad = 8 * -(-58 // 8)  # 58 params padded to the 8-device data axis
    assert g["train.collective_ici_bytes_per_step"] == n_pad * 4 + n_pad * 4
    assert snap["counters"]["train.collective_ici_bytes_total"] == \
        pytest.approx(g["train.collective_ici_bytes_per_step"] * 12)
    assert g["train.collective_dcn_bytes_per_step"] == 0.0
    # attribution: components sum back to the wall (within the clamp)
    rep = opt.attribution.report()
    assert rep["steps"] == 12
    comp_sum = sum(c["total_s"] for c in rep["components"].values())
    assert comp_sum == pytest.approx(rep["wall_s"], rel=0.10)
    for name in obs_attr.COMPONENTS:
        assert snap["hists"][f"train.attr.{name}_s"]["n"] >= 1
    assert "device" in opt.attribution.table()


def test_optimizer_run_has_no_unexpected_recompiles(monkeypatch):
    """A steady shape-stable run must not trip the recompilation sentinel:
    warmup compiles and bundle/eval builds are expected, and nothing else
    compiles mid-run."""
    g = global_metrics()
    before = g.counter("train.unexpected_recompiles_total")
    compiles_before = g.counter("train.xla_compiles_total")
    _train(monkeypatch, iterations=10)
    assert g.counter("train.xla_compiles_total") > compiles_before
    assert g.counter("train.unexpected_recompiles_total") == before
    assert not any(e["kind"] == "unexpected_recompile"
                   for e in flight.global_recorder().snapshot())


# ---------------------------------------------------------------------------
# recompilation sentinel
# ---------------------------------------------------------------------------

def test_recompile_sentinel_counts_and_flags():
    import jax
    import jax.numpy as jnp

    sent = obs_attr.recompile_sentinel()
    g = global_metrics()
    sent.mark_warmup()
    base_total = g.counter("train.xla_compiles_total")
    base_unexpected = g.counter("train.unexpected_recompiles_total")

    jax.jit(lambda a: a * 3.0 + 17.0)(jnp.ones((5,)))  # warmup compile
    assert g.counter("train.xla_compiles_total") > base_total
    assert g.counter("train.unexpected_recompiles_total") == \
        base_unexpected

    sent.mark_steady(step=42)
    flight.global_recorder().clear()
    jax.jit(lambda a: a * 5.0 - 3.0)(jnp.ones((6,)))  # mid-run cache miss
    # one jit dispatch may emit several backend-compile events (main
    # computation + subcomputations): >= 1, and all attributed
    flagged = g.counter("train.unexpected_recompiles_total")
    assert flagged > base_unexpected
    evt = next(e for e in flight.global_recorder().snapshot()
               if e["kind"] == "unexpected_recompile")
    assert evt["step"] == 42 and evt["duration_s"] > 0

    # an announced compile region is not flagged
    with obs_attr.expected_compile():
        jax.jit(lambda a: a * 7.0 + 1.0)(jnp.ones((7,)))
    assert g.counter("train.unexpected_recompiles_total") == flagged
    assert g.percentile("train.compile_time_s", 50) > 0


# ---------------------------------------------------------------------------
# perf-regression sentinel
# ---------------------------------------------------------------------------

def test_sentinel_history_covers_committed_trajectory():
    history = obs_sentinel.load_history(REPO)
    assert "resnet50_train_throughput" in history
    assert "train_dispatch_overhead_reduction" in history
    assert "loader_pipeline_img_per_sec" in history
    assert "serving_throughput_rps" in history
    assert "serving_p99_ms" in history
    base = obs_sentinel.baseline_for("resnet50_train_throughput", history)
    assert base.value > 0 and base.source.startswith("BENCH_r")
    p99 = obs_sentinel.baseline_for("serving_p99_ms", history)
    assert p99.direction == obs_sentinel.LOWER
    # lower-better baseline is the BEST (smallest) committed latency
    assert p99.value == min(r.value for r in history["serving_p99_ms"])


def test_sentinel_flags_synthetic_20pct_throughput_drop():
    """Acceptance: a synthetic 20% throughput regression against the
    committed trajectory is flagged; a 5% wiggle (inside the 10%
    threshold) passes; a lower-better latency regression is flagged in
    the other direction."""
    history = obs_sentinel.load_history(REPO)
    base = obs_sentinel.baseline_for("resnet50_train_throughput", history)
    verdicts = obs_sentinel.check(
        {"metric": "resnet50_train_throughput", "value": base.value * 0.8},
        history)
    assert len(verdicts) == 1 and verdicts[0].regressed
    assert verdicts[0].ratio == pytest.approx(0.8, abs=0.001)
    ok = obs_sentinel.check(
        {"metric": "resnet50_train_throughput", "value": base.value * 0.95},
        history)
    assert not ok[0].regressed
    p99 = obs_sentinel.baseline_for("serving_p99_ms", history)
    worse = obs_sentinel.check(
        {"requests": 1, "throughput_rps": 1e9, "p50_ms": 0.01,
         "p99_ms": p99.value * 1.25}, history)
    by_family = {v.family: v for v in worse}
    assert by_family["serving_p99_ms"].regressed
    assert not by_family["serving_throughput_rps"].regressed


def test_sentinel_ignores_bad_rows_and_unknown_families():
    history = obs_sentinel.load_history(REPO)
    # an errored/suspect fresh row yields no verdicts (never a false gate)
    assert obs_sentinel.check(
        {"metric": "resnet50_train_throughput", "value": 1.0,
         "error": "tpu unavailable"}, history) == []
    assert obs_sentinel.check(
        {"metric": "resnet50_train_throughput", "value": 1.0,
         "suspect": True}, history) == []
    # unknown family: nothing to regress from
    assert obs_sentinel.check(
        {"metric": "a_brand_new_metric", "value": 1.0}, history) == []
    # wrapped {parsed} round artifacts unwrap
    rows = obs_sentinel.normalize(
        {"n": 5, "rc": 0,
         "parsed": {"metric": "resnet50_train_throughput", "value": 42.0}},
        "wrapped")
    assert rows and rows[0].value == 42.0


def test_sentinel_smoke_cli_gate():
    """The CI step: --smoke proves the gate flags a synthetic regression
    (and passes an on-trajectory row) using only committed artifacts."""
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.obs.sentinel", "--smoke",
         "--root", REPO],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["smoke"] == "ok" and verdict["families"] >= 4


def test_sentinel_cli_fails_on_regressed_fresh_file(tmp_path):
    history = obs_sentinel.load_history(REPO)
    base = obs_sentinel.baseline_for("resnet50_train_throughput", history)
    fresh = tmp_path / "fresh.json"
    fresh.write_text(json.dumps(
        {"metric": "resnet50_train_throughput", "value": base.value * 0.5}))
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.obs.sentinel", str(fresh),
         "--root", REPO],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["regressed"] is True


# ---------------------------------------------------------------------------
# block-sparse cost model + KERNELS sentinel family (ISSUE 10 satellites)
# ---------------------------------------------------------------------------

def test_cost_model_block_sparse_dense_vs_effective():
    """A pruned BlockSparseLinear reports dense-equivalent flops in
    ``flops`` and density-scaled flops in ``eff_flops`` — so train.mfu
    (dense-equivalent) can't silently inflate: train.effective_mfu sits
    next to it."""
    import jax

    from bigdl_tpu.ops.block_sparse import BlockSparseLinear

    lin = BlockSparseLinear(64, 64, block_shape=(16, 16))
    x = np.zeros((8, 64), np.float32)
    v = lin.init(jax.random.PRNGKey(0), x[:1])
    dense = 2.0 * 8 * 64 * 64
    rep = obs_cost.forward_costs(lin, v, x)
    assert rep.flops == pytest.approx(dense)
    assert rep.eff_flops == pytest.approx(dense)  # unpruned: equal
    lin.prune_to(v["params"], 0.5)
    rep2 = obs_cost.forward_costs(lin, v, x)
    assert rep2.flops == pytest.approx(dense)          # dense-equivalent
    assert rep2.eff_flops == pytest.approx(dense * 0.5)  # executed work
    detail = obs_cost.train_step_flops_detail(lin, v, (x[:1],), 8)
    assert detail["dense"] == pytest.approx(3 * dense)
    # training effective = fwd(eff) + dx(eff) + dw(DENSE — the weight
    # grad is a dense matmul masked on the way out): 2·0.5 + 1 = 2.0
    assert detail["effective"] == pytest.approx(dense * 2.0)


def test_sentinel_kernels_family_normalize_and_gate():
    """KERNELS_r*.json rows gate: per-kernel speedup (higher-better),
    parity_ok rows only, probe_ rows never."""
    doc = {"device_kind": "TPU v5 lite", "all_ok": True, "kernels": {
        "flash_attention_fwd": {"parity_ok": True, "speedup": 1.2,
                                "speedup_amortized": 1.5},
        "fused_layernorm_fwd": {"parity_ok": True, "speedup": 1.0},
        "broken_kernel": {"parity_ok": False, "speedup": 9.9},
        "probe_flash_bq256": {"parity_ok": True, "speedup": 3.0},
    }}
    rows = {r.family: r for r in obs_sentinel.normalize(doc, "t.json")}
    assert rows["kernel_speedup_flash_attention_fwd"].value == 1.5  # amortized preferred
    assert rows["kernel_speedup_fused_layernorm_fwd"].value == 1.0
    assert "kernel_speedup_broken_kernel" not in rows
    assert not any("probe" in f for f in rows)
    assert all(r.direction == obs_sentinel.HIGHER for r in rows.values())


def test_sentinel_kernels_family_in_committed_history_and_gates():
    """The committed KERNELS_r04 rows are in the history, and a 20%
    kernel-speedup regression fails like every other family (the
    `make bench-watch` contract)."""
    history = obs_sentinel.load_history(REPO)
    fam = "kernel_speedup_flash_attention_fwd"
    assert fam in history
    base = obs_sentinel.baseline_for(fam, history)
    assert base.source.startswith("KERNELS_r")
    fresh = {"kernels": {"flash_attention_fwd": {
        "parity_ok": True, "speedup": base.value * 0.8}}}
    verdicts = obs_sentinel.check(fresh, history)
    by_family = {v.family: v for v in verdicts}
    assert by_family[fam].regressed
    ok = obs_sentinel.check({"kernels": {"flash_attention_fwd": {
        "parity_ok": True, "speedup": base.value}}}, history)
    assert not ok[0].regressed


def test_export_help_covers_new_gauges():
    from bigdl_tpu.obs.export import DEFAULT_HELP

    for name in ("train.effective_mfu", "train.effective_flops_per_step",
                 "ops.autotune_trials", "ops.autotune_cache_hits",
                 "ops.autotune_cache_misses"):
        assert name in DEFAULT_HELP and DEFAULT_HELP[name]
