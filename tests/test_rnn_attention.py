"""RNN + attention layer specs (torch golden oracles where available)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn

KEY = jax.random.PRNGKey(0)


class TestLSTM:
    def test_matches_torch_lstm(self):
        torch = pytest.importorskip("torch")
        b, t, d, h = 2, 5, 4, 3
        m = nn.LSTM(d, h)
        x = jax.random.normal(KEY, (b, t, d))
        v = m.init(KEY, x)
        y = m(v, x)

        tl = torch.nn.LSTM(d, h, batch_first=True)
        p = v["params"]
        # ours: fused (d, 4h) in order i,f,g,o ; torch: (4h, d) in i,f,g,o
        tl.weight_ih_l0.data = torch.tensor(np.asarray(p["w_in"]).T)
        tl.weight_hh_l0.data = torch.tensor(np.asarray(p["w_rec"]).T)
        tl.bias_ih_l0.data = torch.tensor(np.asarray(p["bias"]))
        tl.bias_hh_l0.data = torch.zeros(4 * h)
        ty, _ = tl(torch.tensor(np.asarray(x)))
        np.testing.assert_allclose(np.asarray(y), ty.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_return_last(self):
        m = nn.LSTM(4, 3, return_sequences=False)
        x = jax.random.normal(KEY, (2, 5, 4))
        v = m.init(KEY, x)
        assert m(v, x).shape == (2, 3)

    def test_mask_freezes_state(self):
        m = nn.LSTM(4, 3)
        x = jax.random.normal(KEY, (1, 6, 4))
        v = m.init(KEY, x)
        mask = jnp.array([[1, 1, 1, 0, 0, 0]], bool)
        y, _ = m.forward(v["params"], {}, x, mask=mask)
        # masked positions output zeros
        assert float(jnp.abs(y[0, 3:]).max()) == 0.0
        assert float(jnp.abs(y[0, :3]).max()) > 0.0


class TestGRU:
    def test_matches_torch_gru(self):
        torch = pytest.importorskip("torch")
        b, t, d, h = 2, 5, 4, 3
        m = nn.GRU(d, h)
        x = jax.random.normal(KEY, (b, t, d))
        v = m.init(KEY, x)
        y = m(v, x)
        tg = torch.nn.GRU(d, h, batch_first=True)
        p = v["params"]
        tg.weight_ih_l0.data = torch.tensor(np.asarray(p["w_in"]).T)
        tg.weight_hh_l0.data = torch.tensor(np.asarray(p["w_rec"]).T)
        tg.bias_ih_l0.data = torch.tensor(np.asarray(p["bias"]))
        tg.bias_hh_l0.data = torch.zeros(3 * h)
        ty, _ = tg(torch.tensor(np.asarray(x)))
        # NOTE torch applies bias_hh inside r*(W_hn h + b_hn); with b_hh=0
        # both formulations agree.
        np.testing.assert_allclose(np.asarray(y), ty.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestBiRecurrentTimeDistributed:
    def test_birnn_concat(self):
        m = nn.BiRecurrent(nn.LSTM(4, 3))
        x = jax.random.normal(KEY, (2, 5, 4))
        v = m.init(KEY, x)
        y = m(v, x)
        assert y.shape == (2, 5, 6)

    def test_time_distributed_matches_manual(self):
        m = nn.TimeDistributed(nn.Linear(4, 2))
        x = jax.random.normal(KEY, (3, 5, 4))
        v = m.init(KEY, x)
        y = m(v, x)
        assert y.shape == (3, 5, 2)
        inner = nn.Linear(4, 2)
        manual = jnp.stack(
            [inner.forward(v["params"], {}, x[:, i])[0] for i in range(5)], 1)
        np.testing.assert_allclose(np.asarray(y), np.asarray(manual),
                                   rtol=1e-5)

    def test_recurrent_decoder_shapes(self):
        dec = nn.RecurrentDecoder(nn.LSTM(8, 8), seq_length=4)
        x = jax.random.normal(KEY, (2, 8))
        v = dec.init(KEY, x)
        y = dec(v, x)
        assert y.shape == (2, 4, 8)


class TestAttention:
    def test_mha_matches_torch(self):
        torch = pytest.importorskip("torch")
        b, t, d, heads = 2, 6, 8, 2
        m = nn.MultiHeadAttention(d, heads)
        x = jax.random.normal(KEY, (b, t, d))
        v = m.init(KEY, x)
        y = m(v, x)
        p = v["params"]
        tm = torch.nn.MultiheadAttention(d, heads, batch_first=True)
        w_in = np.concatenate([np.asarray(p["wq"]).T, np.asarray(p["wk"]).T,
                               np.asarray(p["wv"]).T])
        tm.in_proj_weight.data = torch.tensor(w_in)
        tm.in_proj_bias.data = torch.tensor(np.concatenate(
            [np.asarray(p["bq"]), np.asarray(p["bk"]), np.asarray(p["bv"])]))
        tm.out_proj.weight.data = torch.tensor(np.asarray(p["wo"]).T)
        tm.out_proj.bias.data = torch.tensor(np.asarray(p["bo"]))
        tx = torch.tensor(np.asarray(x))
        ty, _ = tm(tx, tx, tx)
        np.testing.assert_allclose(np.asarray(y), ty.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_causal_mask_no_future_leak(self):
        m = nn.MultiHeadAttention(8, 2, causal=True)
        x = jax.random.normal(KEY, (1, 6, 8))
        v = m.init(KEY, x)
        y0 = m(v, x)
        x2 = x.at[0, 4].set(99.0)  # perturb a late position
        y1 = m(v, x2)
        diff = np.asarray(jnp.abs(y1 - y0).sum(-1)[0])
        assert diff[:4].max() < 1e-5  # earlier positions unaffected
        assert diff[4:].max() > 1e-3

    def test_transformer_layer_trains(self):
        layer = nn.TransformerLayer(16, 4, dropout=0.0)
        x = jax.random.normal(KEY, (2, 5, 16))
        v = layer.init(KEY, x)
        y = layer(v, x)
        assert y.shape == x.shape

        def loss(p):
            out, _ = layer.forward(p, {}, x)
            return jnp.sum(out ** 2)

        g = jax.grad(loss)(v["params"])
        # every param gets gradient
        assert all(float(jnp.abs(a).max()) > 0
                   for a in jax.tree_util.tree_leaves(g))

    def test_positional_encoding(self):
        pe = nn.positional_encoding(10, 8)
        assert pe.shape == (10, 8)
        np.testing.assert_allclose(float(pe[0, 0]), 0.0)
        np.testing.assert_allclose(float(pe[0, 1]), 1.0)


def test_transformer_translation_mode_trains():
    """Reference nn/Transformer.scala translation mode: encoder-decoder
    with weight-tied embedding; loss falls on a copy task."""
    from bigdl_tpu.nn import Transformer
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion

    rs = np.random.RandomState(0)
    vocab, t, b = 12, 6, 16
    src = rs.randint(2, vocab, (b, t)).astype(np.int32)
    tgt_in = np.concatenate([np.ones((b, 1), np.int32), src[:, :-1]], 1)

    model = Transformer(vocab, hidden_size=16, num_heads=2, num_layers=1,
                        dropout=0.0)
    rng = jax.random.PRNGKey(0)
    variables = model.init(rng, src, tgt_in)
    crit = CrossEntropyCriterion()

    def loss_fn(params):
        logits, _ = model.forward(params, {}, src, tgt_in)
        return crit(logits.reshape(-1, vocab), src.reshape(-1))

    params = variables["params"]
    l0 = float(loss_fn(params))
    g = jax.jit(jax.grad(loss_fn))
    for _ in range(120):
        grads = g(params)
        params = jax.tree_util.tree_map(lambda p, gr: p - 0.1 * gr,
                                        params, grads)
    l1 = float(loss_fn(params))
    assert l1 < 0.5 * l0, (l0, l1)   # learns to copy through cross-attn


def test_transformer_lm_mode_causal():
    """LM mode: a causal model's logits at position i must not depend on
    tokens after i."""
    from bigdl_tpu.nn import Transformer

    rs = np.random.RandomState(1)
    vocab, t = 10, 5
    ids = rs.randint(0, vocab, (2, t)).astype(np.int32)
    model = Transformer(vocab, hidden_size=8, num_heads=2, num_layers=1,
                        dropout=0.0, mode="lm")
    variables = model.init(jax.random.PRNGKey(0), ids)
    base, _ = model.forward(variables["params"], {}, ids)
    ids2 = ids.copy()
    ids2[:, -1] = (ids2[:, -1] + 3) % vocab      # change the LAST token
    pert, _ = model.forward(variables["params"], {}, ids2)
    np.testing.assert_allclose(np.asarray(base[:, :-1]),
                               np.asarray(pert[:, :-1]), atol=1e-5)


def test_recurrent_container_and_multi_rnn_cell():
    from bigdl_tpu.nn import LSTM, MultiRNNCell, Recurrent, RnnCell, GRU

    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(3, 5, 4), jnp.float32)
    # Recurrent().add(cell) drives the cell over time (reference surface)
    rec = Recurrent().add(RnnCell(4, 6))
    v = rec.init(jax.random.PRNGKey(0), x)
    y, _ = rec.apply(v, x)
    assert y.shape == (3, 5, 6)

    # stacked cells: sequence forward == chained cells; decode step chains
    stack = MultiRNNCell([LSTM(4, 6), GRU(6, 5)])
    v = stack.init(jax.random.PRNGKey(1), x)
    y, _ = stack.apply(v, x)
    assert y.shape == (3, 5, 5)
    carry = stack.init_carry(3)
    outs = []
    for i in range(5):
        carry, h = stack.step(v["params"], carry, x[:, i])
        outs.append(h)
    np.testing.assert_allclose(np.asarray(jnp.stack(outs, 1)),
                               np.asarray(y), atol=1e-5)


def test_anchor_layer_and_aliases():
    from bigdl_tpu import nn

    assert nn.Attention is nn.MultiHeadAttention
    assert nn.FeedForwardNetwork is nn.PositionwiseFFN
    assert nn.RnnCell is nn.SimpleRNN
    a = nn.Anchor(stride=8, sizes=(16.0,), ratios=(1.0,))
    x = jnp.zeros((2, 4, 4, 8))
    boxes, _ = a.forward({}, {}, x)
    assert boxes.shape == (16, 4)       # 4*4 cells x 1 ratio
    # centered square anchors of side 16 at stride 8
    np.testing.assert_allclose(np.asarray(boxes[0]),
                               [4 - 8, 4 - 8, 4 + 8, 4 + 8])


def test_transformer_decode_greedy_and_beam():
    """Autoregressive decode (SequenceBeamSearch analog) over a trained
    translation Transformer: greedy reproduces the learned mapping; beam
    search returns it as the top hypothesis."""
    from bigdl_tpu.nn import Transformer
    from bigdl_tpu.nn.attention import transformer_decode
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion
    from bigdl_tpu.optim.optim_method import Adam

    rs = np.random.RandomState(0)
    vocab, t, n = 10, 4, 256
    BOS, EOS = 1, 0
    src = rs.randint(2, vocab, (n, t)).astype(np.int32)
    tgt = src[:, ::-1].copy()                 # learn to reverse
    # decoder length t+1: t tokens then EOS
    tgt_full = np.concatenate([tgt, np.full((n, 1), EOS, np.int32)], 1)
    tgt_in = np.concatenate([np.full((n, 1), BOS, np.int32),
                             tgt_full[:, :-1]], 1)

    model = Transformer(vocab, hidden_size=24, num_heads=2, num_layers=1,
                        dropout=0.0)
    variables = model.init(jax.random.PRNGKey(0), src, tgt_in)
    params = variables["params"]
    crit = CrossEntropyCriterion()
    method = Adam(learning_rate=3e-3)
    opt_state = method.init_state(params)

    @jax.jit
    def step(i, params, opt_state):
        def loss_fn(p):
            logits, _ = model.forward(p, {}, src, tgt_in)
            return crit(logits.reshape(-1, vocab), tgt_full.reshape(-1))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, opt_state = method.update(i, grads, params, opt_state)
        return params, opt_state, loss

    for i in range(300):
        params, opt_state, loss = step(i, params, opt_state)
    assert float(loss) < 0.1, float(loss)

    src_t = src[:6]
    tokens, _ = transformer_decode(model, params, src_t, BOS, EOS,
                                   max_len=t + 1)
    pred = np.asarray(tokens)[:, 1:t + 1]           # strip BOS, take t steps
    assert (pred == src_t[:, ::-1]).mean() > 0.95, pred

    btokens, scores = transformer_decode(model, params, src_t, BOS, EOS,
                                         max_len=t + 1, beam_size=3)
    assert btokens.shape == (6, 3, t + 2)
    bpred = np.asarray(btokens)[:, 0, 1:t + 1]      # best beam
    assert (bpred == src_t[:, ::-1]).mean() > 0.95
    # beams sorted by score
    assert np.all(np.asarray(scores)[:, 0] >= np.asarray(scores)[:, 1] - 1e-6)


def test_cached_decode_matches_uncached():
    """KV-cached greedy decode is numerically the same decode as the
    re-run-the-prefix path (same tokens, log-probs within fp tolerance)."""
    from bigdl_tpu.nn import Transformer
    from bigdl_tpu.nn.attention import (transformer_decode,
                                        transformer_decode_cached)
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion
    from bigdl_tpu.optim.optim_method import Adam

    rs = np.random.RandomState(4)
    vocab, t, n = 10, 4, 128
    BOS, EOS = 1, 0
    src = rs.randint(2, vocab, (n, t)).astype(np.int32)
    tgt_full = np.concatenate([src[:, ::-1],
                               np.full((n, 1), EOS, np.int32)], 1)
    tgt_in = np.concatenate([np.full((n, 1), BOS, np.int32),
                             tgt_full[:, :-1]], 1)
    model = Transformer(vocab, hidden_size=16, num_heads=2, num_layers=2,
                        dropout=0.0)
    variables = model.init(jax.random.PRNGKey(0), src, tgt_in)
    params = variables["params"]
    crit = CrossEntropyCriterion()
    method = Adam(learning_rate=3e-3)
    opt_state = method.init_state(params)

    @jax.jit
    def step(i, params, opt_state):
        def loss_fn(p):
            logits, _ = model.forward(p, {}, src, tgt_in)
            return crit(logits.reshape(-1, vocab), tgt_full.reshape(-1))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        return (*method.update(i, grads, params, opt_state), loss)

    for i in range(100):
        params, opt_state, _ = step(i, params, opt_state)

    src_t = src[:5]
    tok_u, lp_u = transformer_decode(model, params, src_t, BOS, EOS,
                                     max_len=t + 1)
    tok_c, lp_c = transformer_decode_cached(model, params, src_t, BOS, EOS,
                                            max_len=t + 1)
    np.testing.assert_array_equal(np.asarray(tok_u), np.asarray(tok_c))
    np.testing.assert_allclose(np.asarray(lp_u), np.asarray(lp_c),
                               atol=1e-3)
