"""RNN + attention layer specs (torch golden oracles where available)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn

KEY = jax.random.PRNGKey(0)


class TestLSTM:
    def test_matches_torch_lstm(self):
        torch = pytest.importorskip("torch")
        b, t, d, h = 2, 5, 4, 3
        m = nn.LSTM(d, h)
        x = jax.random.normal(KEY, (b, t, d))
        v = m.init(KEY, x)
        y = m(v, x)

        tl = torch.nn.LSTM(d, h, batch_first=True)
        p = v["params"]
        # ours: fused (d, 4h) in order i,f,g,o ; torch: (4h, d) in i,f,g,o
        tl.weight_ih_l0.data = torch.tensor(np.asarray(p["w_in"]).T)
        tl.weight_hh_l0.data = torch.tensor(np.asarray(p["w_rec"]).T)
        tl.bias_ih_l0.data = torch.tensor(np.asarray(p["bias"]))
        tl.bias_hh_l0.data = torch.zeros(4 * h)
        ty, _ = tl(torch.tensor(np.asarray(x)))
        np.testing.assert_allclose(np.asarray(y), ty.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_return_last(self):
        m = nn.LSTM(4, 3, return_sequences=False)
        x = jax.random.normal(KEY, (2, 5, 4))
        v = m.init(KEY, x)
        assert m(v, x).shape == (2, 3)

    def test_mask_freezes_state(self):
        m = nn.LSTM(4, 3)
        x = jax.random.normal(KEY, (1, 6, 4))
        v = m.init(KEY, x)
        mask = jnp.array([[1, 1, 1, 0, 0, 0]], bool)
        y, _ = m.forward(v["params"], {}, x, mask=mask)
        # masked positions output zeros
        assert float(jnp.abs(y[0, 3:]).max()) == 0.0
        assert float(jnp.abs(y[0, :3]).max()) > 0.0


class TestGRU:
    def test_matches_torch_gru(self):
        torch = pytest.importorskip("torch")
        b, t, d, h = 2, 5, 4, 3
        m = nn.GRU(d, h)
        x = jax.random.normal(KEY, (b, t, d))
        v = m.init(KEY, x)
        y = m(v, x)
        tg = torch.nn.GRU(d, h, batch_first=True)
        p = v["params"]
        tg.weight_ih_l0.data = torch.tensor(np.asarray(p["w_in"]).T)
        tg.weight_hh_l0.data = torch.tensor(np.asarray(p["w_rec"]).T)
        tg.bias_ih_l0.data = torch.tensor(np.asarray(p["bias"]))
        tg.bias_hh_l0.data = torch.zeros(3 * h)
        ty, _ = tg(torch.tensor(np.asarray(x)))
        # NOTE torch applies bias_hh inside r*(W_hn h + b_hn); with b_hh=0
        # both formulations agree.
        np.testing.assert_allclose(np.asarray(y), ty.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestBiRecurrentTimeDistributed:
    def test_birnn_concat(self):
        m = nn.BiRecurrent(nn.LSTM(4, 3))
        x = jax.random.normal(KEY, (2, 5, 4))
        v = m.init(KEY, x)
        y = m(v, x)
        assert y.shape == (2, 5, 6)

    def test_time_distributed_matches_manual(self):
        m = nn.TimeDistributed(nn.Linear(4, 2))
        x = jax.random.normal(KEY, (3, 5, 4))
        v = m.init(KEY, x)
        y = m(v, x)
        assert y.shape == (3, 5, 2)
        inner = nn.Linear(4, 2)
        manual = jnp.stack(
            [inner.forward(v["params"], {}, x[:, i])[0] for i in range(5)], 1)
        np.testing.assert_allclose(np.asarray(y), np.asarray(manual),
                                   rtol=1e-5)

    def test_recurrent_decoder_shapes(self):
        dec = nn.RecurrentDecoder(nn.LSTM(8, 8), seq_length=4)
        x = jax.random.normal(KEY, (2, 8))
        v = dec.init(KEY, x)
        y = dec(v, x)
        assert y.shape == (2, 4, 8)


class TestAttention:
    def test_mha_matches_torch(self):
        torch = pytest.importorskip("torch")
        b, t, d, heads = 2, 6, 8, 2
        m = nn.MultiHeadAttention(d, heads)
        x = jax.random.normal(KEY, (b, t, d))
        v = m.init(KEY, x)
        y = m(v, x)
        p = v["params"]
        tm = torch.nn.MultiheadAttention(d, heads, batch_first=True)
        w_in = np.concatenate([np.asarray(p["wq"]).T, np.asarray(p["wk"]).T,
                               np.asarray(p["wv"]).T])
        tm.in_proj_weight.data = torch.tensor(w_in)
        tm.in_proj_bias.data = torch.tensor(np.concatenate(
            [np.asarray(p["bq"]), np.asarray(p["bk"]), np.asarray(p["bv"])]))
        tm.out_proj.weight.data = torch.tensor(np.asarray(p["wo"]).T)
        tm.out_proj.bias.data = torch.tensor(np.asarray(p["bo"]))
        tx = torch.tensor(np.asarray(x))
        ty, _ = tm(tx, tx, tx)
        np.testing.assert_allclose(np.asarray(y), ty.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_causal_mask_no_future_leak(self):
        m = nn.MultiHeadAttention(8, 2, causal=True)
        x = jax.random.normal(KEY, (1, 6, 8))
        v = m.init(KEY, x)
        y0 = m(v, x)
        x2 = x.at[0, 4].set(99.0)  # perturb a late position
        y1 = m(v, x2)
        diff = np.asarray(jnp.abs(y1 - y0).sum(-1)[0])
        assert diff[:4].max() < 1e-5  # earlier positions unaffected
        assert diff[4:].max() > 1e-3

    def test_transformer_layer_trains(self):
        layer = nn.TransformerLayer(16, 4, dropout=0.0)
        x = jax.random.normal(KEY, (2, 5, 16))
        v = layer.init(KEY, x)
        y = layer(v, x)
        assert y.shape == x.shape

        def loss(p):
            out, _ = layer.forward(p, {}, x)
            return jnp.sum(out ** 2)

        g = jax.grad(loss)(v["params"])
        # every param gets gradient
        assert all(float(jnp.abs(a).max()) > 0
                   for a in jax.tree_util.tree_leaves(g))

    def test_positional_encoding(self):
        pe = nn.positional_encoding(10, 8)
        assert pe.shape == (10, 8)
        np.testing.assert_allclose(float(pe[0, 0]), 0.0)
        np.testing.assert_allclose(float(pe[0, 1]), 1.0)
