"""Text pipeline: vocab round-trips, bucketing, LM window prep."""

import numpy as np
import pytest

from bigdl_tpu.data.text import (TextBatcher, Vocabulary, bucket_length,
                                 char_tokenize, language_model_arrays,
                                 pad_to, word_tokenize)


class TestVocabulary:
    def test_build_and_roundtrip(self):
        corpus = [word_tokenize("the cat sat"), word_tokenize("the dog sat")]
        v = Vocabulary.build(corpus)
        assert v.stoi["the"] == 4  # most frequent after 4 specials
        ids = v.encode(["the", "cat", "zebra"], add_bos=True, add_eos=True)
        assert ids[0] == 2 and ids[-1] == 3
        assert ids[3] == 1  # unk
        assert v.decode(ids) == ["the", "cat"]

    def test_min_freq_and_max_size(self):
        corpus = [["a"] * 5 + ["b"] * 2 + ["c"]]
        v = Vocabulary.build(corpus, min_freq=2)
        assert "c" not in v.stoi
        v2 = Vocabulary.build(corpus, max_size=1)
        assert len(v2) == 5  # 4 specials + "a"


class TestBatching:
    def test_bucketing(self):
        assert bucket_length(10, [32, 64]) == 32
        assert bucket_length(33, [32, 64]) == 64
        assert bucket_length(999, [32, 64]) == 64  # truncating bucket
        np.testing.assert_array_equal(pad_to([1, 2], 4), [1, 2, 0, 0])

    def test_text_batcher_shapes_and_masks(self):
        enc = [[5] * 10, [6] * 20, [7] * 40, [8] * 40]
        batcher = TextBatcher(buckets=(16, 48), batch_size=2, shuffle=False)
        batches = list(batcher(enc, labels=[0, 1, 2, 3]))
        shapes = sorted(b["input"].shape for b in batches)
        assert shapes == [(1, 16), (2, 48), (2, 48)] or \
            len(batches) == 3
        for b in batches:
            np.testing.assert_array_equal(b["mask"], b["input"] != 0)
            assert "target" in b


class TestLanguageModel:
    def test_char_lm_windows(self):
        text = "hello world, hello tpu! " * 20
        x, y, vocab = language_model_arrays(text, None, seq_len=16)
        assert x.shape == y.shape and x.shape[1] == 16
        # y is x shifted by one token
        np.testing.assert_array_equal(x.reshape(-1)[1:], y.reshape(-1)[:-1])
        # ids decode back to text chars
        assert "".join(vocab.decode(x[0])) in text

    def test_char_rnn_trains(self):
        """Convergence smoke: a tiny LSTM LM learns a repeating pattern —
        the reference ``models/rnn`` Train path in miniature."""
        import jax

        from bigdl_tpu.data.dataset import DataSet
        from bigdl_tpu.nn.criterion import CrossEntropyCriterion
        from bigdl_tpu.nn.layers import Embedding, Linear
        from bigdl_tpu.nn.module import Sequential
        from bigdl_tpu.nn.rnn import LSTM
        from bigdl_tpu.optim.optim_method import Adam
        from bigdl_tpu.optim.optimizer import Optimizer
        from bigdl_tpu.optim.trigger import Trigger

        text = "abcd" * 200
        x, y, vocab = language_model_arrays(text, None, seq_len=8)
        model = Sequential([
            Embedding(len(vocab), 16),
            LSTM(16, 32, return_sequences=True),
            Linear(32, len(vocab)),
        ])
        opt = Optimizer(model, DataSet.array(x, y),
                        CrossEntropyCriterion(), batch_size=32)
        opt.set_optim_method(Adam(learning_rate=1e-2))
        opt.set_end_when(Trigger.max_epoch(8))
        trained = opt.optimize()
        logits = trained.predict(x[:8])
        pred = np.argmax(np.asarray(logits), axis=-1)
        acc = (pred[:, :-1] == y[:8, :-1]).mean()
        assert acc > 0.9, acc


def test_vocabulary_save_load_roundtrip(tmp_path):
    from bigdl_tpu.data.text import Vocabulary, word_tokenize

    corpus = [word_tokenize("the cat sat"), word_tokenize("the dog ran the")]
    v = Vocabulary.build(corpus)
    p = str(tmp_path / "vocab.txt")
    v.save(p)
    v2 = Vocabulary.load(p)
    assert v2.itos == v.itos and len(v2) == len(v)
    ids = v.encode(word_tokenize("the cat"), add_eos=True)
    assert v2.encode(word_tokenize("the cat"), add_eos=True) == ids
    assert v2.decode(ids) == ["the", "cat"]


def test_vocabulary_newline_token_roundtrip(tmp_path):
    """ADVICE r3: a token containing a newline must not shift every
    subsequent id on reload."""
    from bigdl_tpu.data.text import Vocabulary

    v = Vocabulary.build([["a\nb", "plain", "c\rd", "back\\slash", "z"]],
                         min_freq=1)
    p = str(tmp_path / "v.txt")
    v.save(p)
    v2 = Vocabulary.load(p)
    assert v2.itos == v.itos
    assert v2.stoi == v.stoi


def test_vocabulary_legacy_raw_file_loads_verbatim(tmp_path):
    """Files saved by the pre-escaping format (no version sentinel) must
    load without unescaping — a literal backslash-n token stays two chars."""
    from bigdl_tpu.data.text import Vocabulary

    p = str(tmp_path / "legacy.txt")
    with open(p, "w", encoding="utf-8") as f:
        f.write("<pad>\n<unk>\n<bos>\n<eos>\n\\n\nback\\\\slash\n")
    v = Vocabulary.load(p)
    assert v.itos[4] == "\\n"          # two characters, not a newline
    assert v.itos[5] == "back\\\\slash"


def test_vocabulary_v2_crlf_file_loads(tmp_path):
    """A v2 vocab file rewritten with CRLF endings (git autocrlf etc.) must
    still be detected as v2 and unescaped."""
    from bigdl_tpu.data.text import Vocabulary

    v = Vocabulary.build([["a\nb", "hello"]])
    p = str(tmp_path / "v.txt")
    v.save(p)
    with open(p, encoding="utf-8", newline="") as f:
        content = f.read()
    assert "\r" not in content            # save forces LF
    with open(p, "w", encoding="utf-8", newline="") as f:
        f.write(content.replace("\n", "\r\n"))
    v2 = Vocabulary.load(p)
    assert v2.itos == v.itos
