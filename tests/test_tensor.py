"""Tensor facade specs — the reference ``DenseTensorMathSpec``-style
coverage (torch as golden oracle where semantics are torch-defined)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu.tensor.tensor import Tensor

RS = np.random.RandomState(0)


def A(*shape):
    return RS.randn(*shape).astype(np.float32)


def test_elementwise_math_tranche():
    x = Tensor(A(3, 4) * 0.5)
    for name, ref in [
        ("tan", np.tan), ("sinh", np.sinh), ("cosh", np.cosh),
        ("asin", lambda a: np.arcsin(np.clip(a, -1, 1))),
        ("atan", np.arctan), ("log2", None), ("log10", None),
        ("expm1", np.expm1), ("trunc", np.trunc),
    ]:
        if name in ("asin",):
            t = Tensor(np.clip(np.asarray(x.data), -1, 1))
        else:
            t = x
        got = np.asarray(getattr(t, name)().data)
        if ref is not None:
            np.testing.assert_allclose(got, ref(np.asarray(t.data)),
                                       rtol=1e-5, atol=1e-6)
        assert got.shape == t.shape


def test_frac_remainder_fmod_match_torch():
    torch = pytest.importorskip("torch")
    a = A(4, 5) * 3
    b = np.abs(A(4, 5)) + 0.5
    ta = torch.tensor(a)
    tb = torch.tensor(b)
    np.testing.assert_allclose(np.asarray(Tensor(a).frac().data),
                               torch.frac(ta).numpy(), atol=1e-6)
    np.testing.assert_allclose(np.asarray(Tensor(a).remainder(b).data),
                               torch.remainder(ta, tb).numpy(), atol=1e-5)
    np.testing.assert_allclose(np.asarray(Tensor(a).fmod(b).data),
                               torch.fmod(ta, tb).numpy(), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(Tensor(a).lerp(b, 0.3).data),
        torch.lerp(ta, tb, 0.3).numpy(), atol=1e-6)


def test_sort_kthvalue_median():
    torch = pytest.importorskip("torch")
    a = A(3, 7)
    vals, idx = Tensor(a).sort(dim=1)
    tv, ti = torch.tensor(a).sort(dim=1)
    np.testing.assert_allclose(np.asarray(vals.data), tv.numpy())
    np.testing.assert_array_equal(np.asarray(idx.data), ti.numpy())
    vals, idx = Tensor(a).sort(dim=1, descending=True)
    tv, _ = torch.tensor(a).sort(dim=1, descending=True)
    np.testing.assert_allclose(np.asarray(vals.data), tv.numpy())
    kv, ki = Tensor(a).kthvalue(3, dim=1)
    tkv, tki = torch.tensor(a).kthvalue(3, dim=1)
    np.testing.assert_allclose(np.asarray(kv.data), tkv.numpy())
    np.testing.assert_array_equal(np.asarray(ki.data), tki.numpy())


def test_renorm_caps_row_norms():
    a = A(4, 6) * 5
    out = np.asarray(Tensor(a).renorm(2, 0, 1.0).data)
    norms = np.linalg.norm(out.reshape(4, -1), axis=1)
    assert np.all(norms <= 1.0 + 1e-5)
    # rows already under the cap are untouched
    small = np.asarray(Tensor(a * 1e-3).renorm(2, 0, 1.0).data)
    np.testing.assert_allclose(small, a * 1e-3, rtol=1e-6)


def test_structure_ops():
    a = A(4, 4)
    np.testing.assert_allclose(np.asarray(Tensor(a).triu(1).data),
                               np.triu(a, 1))
    np.testing.assert_allclose(np.asarray(Tensor(a).tril(-1).data),
                               np.tril(a, -1))
    np.testing.assert_allclose(float(Tensor(a).trace().data), np.trace(a),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(Tensor(a).flip(0).data), a[::-1])
    np.testing.assert_allclose(np.asarray(Tensor(a).roll(1, 0).data),
                               np.roll(a, 1, 0))
    np.testing.assert_allclose(np.asarray(Tensor(a).rot90().data),
                               np.rot90(a))
    b = A(2, 3)
    np.testing.assert_allclose(np.asarray(Tensor(b).kron(np.eye(
        2, dtype=np.float32)).data), np.kron(b, np.eye(2)), rtol=1e-6)


def test_unfold_matches_torch():
    torch = pytest.importorskip("torch")
    a = A(2, 10)
    got = np.asarray(Tensor(a).unfold(1, 4, 3).data)
    want = torch.tensor(a).unfold(1, 4, 3).numpy()
    np.testing.assert_allclose(got, want)


def test_linalg_ops():
    a = A(3, 3) + 3 * np.eye(3, dtype=np.float32)
    inv = np.asarray(Tensor(a).inverse().data)
    np.testing.assert_allclose(a @ inv, np.eye(3), atol=1e-4)
    np.testing.assert_allclose(float(Tensor(a).det().data),
                               np.linalg.det(a), rtol=1e-4)
    u, s, vt = Tensor(a).svd()
    np.testing.assert_allclose(
        np.asarray(u.data) @ np.diag(np.asarray(s.data)) @ np.asarray(vt.data),
        a, atol=1e-4)
    q, r = Tensor(a).qr()
    np.testing.assert_allclose(np.asarray(q.data) @ np.asarray(r.data), a,
                               atol=1e-4)
    spd = a @ a.T + np.eye(3, dtype=np.float32)
    ch = np.asarray(Tensor(spd).cholesky().data)
    np.testing.assert_allclose(ch @ ch.T, spd, atol=1e-3)
    b = A(3)
    np.testing.assert_allclose(
        np.asarray(Tensor(a).solve(b).data), np.linalg.solve(a, b),
        atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(Tensor(a).matrix_power(3).data), a @ a @ a, rtol=1e-3)


def test_baddbmm_matches_torch():
    torch = pytest.importorskip("torch")
    m = A(2, 3, 5)
    b1, b2 = A(2, 3, 4), A(2, 4, 5)
    got = np.asarray(Tensor(m).baddbmm(b1, b2, beta=0.5, alpha=2.0).data)
    want = torch.baddbmm(torch.tensor(m), torch.tensor(b1),
                         torch.tensor(b2), beta=0.5, alpha=2.0).numpy()
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_index_ops():
    a = A(4, 3)
    idx = np.array([0, 2])
    out = np.asarray(Tensor(a).index_fill(0, idx, 9.0).data)
    assert np.all(out[[0, 2]] == 9.0) and np.all(out[1] == a[1])
    src = A(2, 3)
    out = np.asarray(Tensor(a).index_copy(0, idx, src).data)
    np.testing.assert_allclose(out[[0, 2]], src)
    out = np.asarray(Tensor(a).index_add(0, idx, src).data)
    np.testing.assert_allclose(out[[0, 2]], a[[0, 2]] + src, rtol=1e-6)
    out = np.asarray(Tensor(a).scatter_add(
        1, np.zeros((4, 1), np.int32), np.ones((4, 1), np.float32)).data)
    np.testing.assert_allclose(out[:, 0], a[:, 0] + 1.0, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(Tensor(a).take(np.array([0, 5, 11])).data),
        a.ravel()[[0, 5, 11]])


def test_random_ops_with_keys():
    t = Tensor.zeros(1000)
    k = jax.random.PRNGKey(0)
    b = np.asarray(t.bernoulli(0.3, key=k).data)
    assert 0.2 < b.mean() < 0.4
    u = np.asarray(t.uniform(2.0, 3.0, key=k).data)
    assert u.min() >= 2.0 and u.max() <= 3.0
    n = np.asarray(t.normal(1.0, 0.1, key=k).data)
    assert abs(n.mean() - 1.0) < 0.05
    w = Tensor(np.asarray([0.0, 0.0, 1.0], np.float32))
    m = np.asarray(w.multinomial(50, replacement=True, key=k).data)
    assert np.all(m == 2)
    wb = Tensor(np.asarray([[1.0, 0.0], [0.0, 1.0]], np.float32))
    mb = np.asarray(wb.multinomial(20, replacement=True, key=k).data)
    assert mb.shape == (2, 20)
    assert np.all(mb[0] == 0) and np.all(mb[1] == 1)


def test_multinomial_without_replacement():
    """torch.multinomial defaults to replacement=False: no duplicate
    indices, heaviest weights dominate the draw (ADVICE r2)."""
    import jax

    k = jax.random.PRNGKey(3)
    w = Tensor(np.asarray([1.0, 5.0, 0.1, 3.0], np.float32))
    m = np.asarray(w.multinomial(4, key=k).data)      # default: no repl.
    assert sorted(m.tolist()) == [0, 1, 2, 3]          # a permutation
    m2 = np.asarray(w.multinomial(2, key=k).data)
    assert len(set(m2.tolist())) == 2                  # distinct
    # batched rows each draw without replacement
    wb = Tensor(np.asarray([[1.0, 1.0, 1.0], [9.0, 1.0, 1.0]], np.float32))
    mb = np.asarray(wb.multinomial(3, key=k).data)
    assert mb.shape == (2, 3)
    assert sorted(mb[0].tolist()) == [0, 1, 2]
    assert sorted(mb[1].tolist()) == [0, 1, 2]
    with pytest.raises(ValueError):
        w.multinomial(5, key=k)                        # 5 > 4 categories


def test_reductions_and_predicates():
    a = np.array([[1.0, np.nan], [2.0, 3.0]], np.float32)
    assert float(Tensor(a).nansum().data) == 6.0
    np.testing.assert_allclose(float(Tensor(a).nanmean().data), 2.0)
    assert bool(Tensor(a).isnan().any().data)
    assert not bool(Tensor(np.ones(3)).isinf().any().data)
    assert Tensor(np.ones(3)).equal(np.ones(3))
    assert not Tensor(np.ones(3)).equal(np.ones(4))
    assert int(Tensor(np.array([0, 1, 2])).count_nonzero().data) == 2
    np.testing.assert_allclose(
        float(Tensor(np.array([0., 3.])).dist(np.array([4., 0.])).data),
        5.0)


def test_constructors():
    np.testing.assert_allclose(np.asarray(Tensor.linspace(0, 1, 5).data),
                               np.linspace(0, 1, 5), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(Tensor.logspace(0, 2, 3).data),
                               [1.0, 10.0, 100.0], rtol=1e-5)


def test_median_cumprod_argsort():
    a = A(3, 5)
    np.testing.assert_allclose(np.asarray(Tensor(a).median(1).data),
                               np.median(a, 1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(Tensor(a).cumprod(1).data),
                               np.cumprod(a, 1), rtol=1e-5)
    np.testing.assert_array_equal(
        np.asarray(Tensor(a).argsort(1, descending=True).data),
        np.argsort(-a, 1))


def test_multinomial_no_replacement_rejects_zero_weight_rows():
    """torch parity: a row without enough NONZERO weights cannot fill the
    draw — raise instead of returning impossible indices."""
    import jax

    k = jax.random.PRNGKey(0)
    w = Tensor(np.asarray([1.0, 0.0, 0.0, 0.0], np.float32))
    with pytest.raises(ValueError):
        w.multinomial(2, key=k)
    # one nonzero → sampling exactly 1 is fine and must pick it
    m = np.asarray(w.multinomial(1, key=k).data)
    assert m.tolist() == [0]


def test_tail_ops_match_torch():
    torch = pytest.importorskip("torch")
    a = A(4, 6)
    t = torch.tensor(a)
    np.testing.assert_allclose(
        np.asarray(Tensor(a).logsumexp(1).data),
        torch.logsumexp(t, 1).numpy(), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(Tensor(a).softmax(-1).data),
        torch.softmax(t, -1).numpy(), rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(
        np.asarray(Tensor(a).diagonal(1).data),
        torch.diagonal(t, 1).numpy())
    cond = a > 0
    b = A(4, 6)
    np.testing.assert_allclose(
        np.asarray(Tensor(a).where(cond, b).data),
        torch.where(torch.tensor(cond), t, torch.tensor(b)).numpy())
    ids = np.array([0, 1, 1, 3, 3, 3], np.int64)
    np.testing.assert_array_equal(
        np.asarray(Tensor(ids).bincount().data),
        torch.bincount(torch.tensor(ids)).numpy())
    np.testing.assert_array_equal(
        np.asarray(Tensor(ids).bincount(minlength=8).data),
        torch.bincount(torch.tensor(ids), minlength=8).numpy())
    h_ours = np.asarray(Tensor(a).histc(10, -2, 2).data)
    h_torch = torch.histc(t, 10, -2, 2).numpy()
    np.testing.assert_allclose(h_ours, h_torch)
