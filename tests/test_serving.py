"""Serving tests: dynamic batching, concurrent clients, bucketed predict
(reference analog: cluster-serving integration tests — SURVEY.md §5)."""

import pytest
import threading

import numpy as np
import jax

from bigdl_tpu import nn
from bigdl_tpu.serving import (
    InferenceModel, InputQueue, OutputQueue, ServingConfig, ServingServer,
)

pytestmark = pytest.mark.slow  # serving integration: excluded from the quick test-fast loop


def _model_and_vars():
    model = nn.Sequential([nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2)])
    v = model.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.float32))
    return model, v


def test_inference_model_bucketing():
    model, v = _model_and_vars()
    im = InferenceModel(model, v, batch_buckets=(4, 16))
    for n in (1, 3, 4, 9, 33):
        out = im.predict(np.random.rand(n, 4).astype(np.float32))
        assert out.shape == (n, 2)


def test_inference_model_matches_direct():
    model, v = _model_and_vars()
    im = InferenceModel(model, v)
    x = np.random.RandomState(0).rand(5, 4).astype(np.float32)
    ref, _ = model.apply(v, x)
    np.testing.assert_allclose(im.predict(x), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_serving_server_roundtrip():
    model, v = _model_and_vars()
    server = ServingServer(InferenceModel(model, v),
                           ServingConfig(batch_size=8)).start()
    try:
        x = np.random.RandomState(1).rand(3, 4).astype(np.float32)
        rid = server.enqueue(x)
        out = server.query(rid, timeout=30)
        ref, _ = model.apply(v, x)
        np.testing.assert_allclose(out, np.asarray(ref), rtol=1e-5, atol=1e-6)
    finally:
        server.stop()


def test_serving_concurrent_clients():
    model, v = _model_and_vars()
    server = ServingServer(InferenceModel(model, v),
                           ServingConfig(batch_size=16)).start()
    inq, outq = InputQueue(server), OutputQueue(server)
    errors = []

    def client(i):
        try:
            x = np.random.RandomState(i).rand(2, 4).astype(np.float32)
            rid = inq.enqueue(f"req-{i}", t=x)
            out = outq.query(rid, timeout=30)
            ref, _ = model.apply(v, x)
            np.testing.assert_allclose(out, np.asarray(ref),
                                       rtol=1e-5, atol=1e-6)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    try:
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(12)]
        [t.start() for t in threads]
        [t.join(60) for t in threads]
        assert not errors, errors
        assert server.stats["requests"] == 12
    finally:
        server.stop()


def test_http_frontend_roundtrip():
    from bigdl_tpu.serving import HttpClient, HttpFrontend

    model, v = _model_and_vars()
    server = ServingServer(InferenceModel(model, v),
                           ServingConfig(batch_size=8)).start()
    frontend = HttpFrontend(server).start()
    try:
        client = HttpClient(frontend.url)
        x = np.random.RandomState(2).rand(3, 4).astype(np.float32)
        pred = client.predict(x)
        ref, _ = model.apply(v, x)
        np.testing.assert_allclose(pred, np.asarray(ref), rtol=1e-4,
                                   atol=1e-5)
        h = client.health()
        assert h["status"] == "ok" and h["requests"] >= 1
    finally:
        frontend.stop()
        server.stop()


def test_http_frontend_bad_request():
    from urllib import request as urlreq
    from urllib.error import HTTPError

    from bigdl_tpu.serving import HttpFrontend

    model, v = _model_and_vars()
    server = ServingServer(InferenceModel(model, v)).start()
    frontend = HttpFrontend(server).start()
    try:
        req = urlreq.Request(frontend.url + "/predict", data=b"not json",
                             headers={"Content-Type": "application/json"})
        try:
            urlreq.urlopen(req, timeout=10)
            assert False, "expected HTTP 400"
        except HTTPError as e:
            assert e.code == 400
    finally:
        frontend.stop()
        server.stop()


def test_inference_model_tf_and_caffe_backends(tmp_path):
    import jax

    from bigdl_tpu import nn
    from bigdl_tpu.nn.module import Sequential
    from bigdl_tpu.serving.inference_model import InferenceModel
    from bigdl_tpu.utils.caffe import save_caffe
    from bigdl_tpu.utils.tfio import save_tf_graph

    model = Sequential([nn.Linear(4, 3), nn.SoftMax()])
    x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    expect, _ = model.apply(variables, x)

    tf_path = str(tmp_path / "m.pb")
    save_tf_graph(model, variables, sample=x, path=tf_path)
    got = InferenceModel.load_tf(tf_path).predict(x)
    np.testing.assert_allclose(got, np.asarray(expect), rtol=1e-4, atol=1e-5)

    cf_path = str(tmp_path / "m.caffemodel")
    save_caffe(model, variables, sample=x, path=cf_path)
    got2 = InferenceModel.load_caffe(cf_path).predict(x)
    np.testing.assert_allclose(got2, np.asarray(expect), rtol=1e-4, atol=1e-5)


def test_seq2seq_service_buckets_and_translates():
    """Decode-as-a-service: a trained translation Transformer served with
    batch bucketing; greedy (KV-cached) and beam modes agree on the task."""
    import jax

    from bigdl_tpu.nn import Transformer
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion
    from bigdl_tpu.optim.optim_method import Adam
    from bigdl_tpu.serving import Seq2SeqService

    rs = np.random.RandomState(0)
    vocab, t, n = 10, 4, 256
    BOS, EOS = 1, 0
    src = rs.randint(2, vocab, (n, t)).astype(np.int32)
    tgt_full = np.concatenate([src[:, ::-1],
                               np.full((n, 1), EOS, np.int32)], 1)
    tgt_in = np.concatenate([np.full((n, 1), BOS, np.int32),
                             tgt_full[:, :-1]], 1)
    model = Transformer(vocab, hidden_size=16, num_heads=2, num_layers=1,
                        dropout=0.0)
    variables = model.init(jax.random.PRNGKey(0), src, tgt_in)
    params = variables["params"]
    crit = CrossEntropyCriterion()
    method = Adam(learning_rate=3e-3)
    opt_state = method.init_state(params)

    @jax.jit
    def step(i, p, o):
        def loss_fn(pp):
            logits, _ = model.forward(pp, {}, src, tgt_in)
            return crit(logits.reshape(-1, vocab), tgt_full.reshape(-1))

        loss, g = jax.value_and_grad(loss_fn)(p)
        return (*method.update(i, g, p, o), loss)

    for i in range(200):
        params, opt_state, _ = step(i, params, opt_state)

    svc = Seq2SeqService(model, params, BOS, EOS, max_len=t + 1,
                         batch_buckets=(2, 4, 8))
    # odd request size -> padded to bucket 4; rows beyond biggest bucket
    # chunk transparently
    for req_n in (3, 8, 11):
        toks, scores = svc.translate(src[:req_n])
        assert toks.shape[0] == req_n and scores.shape == (req_n,)
        pred = toks[:, 1:t + 1]
        assert (pred == src[:req_n, ::-1]).mean() > 0.9
    # one compiled program per bucket actually cached
    assert set(svc._cache) <= {2, 4, 8}

    beam = Seq2SeqService(model, params, BOS, EOS, max_len=t + 1,
                          beam_size=3, batch_buckets=(4,))
    toks, _ = beam.translate(src[:4])
    assert (toks[:, 1:t + 1] == src[:4, ::-1]).mean() > 0.9


def test_seq2seq_service_sampling_mode():
    """sample=True serves stochastic decode; different requests draw
    different tokens (per-request key fold), greedy stays deterministic."""
    import jax

    from bigdl_tpu.nn.attention import Transformer
    from bigdl_tpu.serving.seq2seq import Seq2SeqService

    model = Transformer(vocab_size=16, hidden_size=16, num_heads=2,
                        num_layers=1, dropout=0.0, mode="translation")
    src = np.array([[0, 5, 6, 1]], np.int32)
    v = model.init(jax.random.PRNGKey(0), src, src)

    svc = Seq2SeqService(model, v["params"], bos_id=0, eos_id=1,
                         max_len=8, sample=True, temperature=3.0)
    outs = [svc.translate(src)[0] for _ in range(6)]
    # high temperature on random weights: not every request identical
    assert any(not np.array_equal(outs[0], o) for o in outs[1:])

    greedy = Seq2SeqService(model, v["params"], bos_id=0, eos_id=1,
                            max_len=8)
    g1 = greedy.translate(src)[0]
    g2 = greedy.translate(src)[0]
    np.testing.assert_array_equal(g1, g2)

    import pytest

    with pytest.raises(ValueError, match="exclusive"):
        Seq2SeqService(model, v["params"], 0, 1, sample=True, beam_size=4)


def test_serving_quantized_model_end_to_end():
    """Weight-only int8 model through the dynamic-batch serving engine —
    the quantize-then-serve path users actually deploy."""
    from bigdl_tpu.nn.quantized import quantize

    model, v = _model_and_vars()
    q_model, q_vars = quantize(model, v, weight_only=True)
    server = ServingServer(InferenceModel(q_model, q_vars),
                           ServingConfig(batch_size=8)).start()
    try:
        x = np.random.RandomState(2).rand(5, 4).astype(np.float32)
        rid = server.enqueue(x)
        out = server.query(rid, timeout=30)
        ref, _ = model.apply(v, x)
        # int8 weights: close to the fp32 model, identical shape
        assert out.shape == np.asarray(ref).shape
        denom = np.abs(np.asarray(ref)).max() + 1e-6
        assert np.abs(out - np.asarray(ref)).max() / denom < 0.05
    finally:
        server.stop()
