"""Pallas kernel correctness — flash attention, int8 matmul, fused LN.

Mirrors the reference's layer-correctness spec pattern (SURVEY.md §5:
``nn/LinearSpec.scala``-style golden comparisons): every kernel is checked
against a plain jnp/numpy oracle, on CPU in interpreter mode — the same
code path Mosaic compiles on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu.nn.attention import dot_product_attention
from bigdl_tpu.ops import (flash_attention, fused_layernorm, int8_matmul,
                           quantize_int8, quantized_linear)


def _rand(rng, *shape):
    return jnp.asarray(rng.standard_normal(shape), jnp.float32)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        rng = np.random.default_rng(0)
        q = _rand(rng, 2, 3, 40, 16)
        k = _rand(rng, 2, 3, 40, 16)
        v = _rand(rng, 2, 3, 40, 16)
        out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                              interpret=True)
        mask = jnp.tril(jnp.ones((40, 40), bool)) if causal else None
        ref = dot_product_attention(q, k, v, mask=mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_unaligned_and_cross_lengths(self):
        rng = np.random.default_rng(1)
        q = _rand(rng, 1, 2, 37, 8)
        k = _rand(rng, 1, 2, 53, 8)
        v = _rand(rng, 1, 2, 53, 8)
        out = flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)
        ref = dot_product_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match(self, causal):
        rng = np.random.default_rng(2)
        q = _rand(rng, 1, 2, 24, 8)
        k = _rand(rng, 1, 2, 24, 8)
        v = _rand(rng, 1, 2, 24, 8)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=causal, block_q=8, block_k=8,
                interpret=True) ** 2)

        def loss_ref(q, k, v):
            mask = jnp.tril(jnp.ones((24, 24), bool)) if causal else None
            return jnp.sum(dot_product_attention(q, k, v, mask=mask) ** 2)

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-3)

    def test_jit_compatible(self):
        rng = np.random.default_rng(3)
        q = _rand(rng, 1, 1, 16, 8)
        f = jax.jit(lambda q: flash_attention(q, q, q, interpret=True))
        out = f(q)
        assert out.shape == q.shape


class TestInt8Matmul:
    def test_exact_int_arithmetic(self):
        rng = np.random.default_rng(0)
        x = rng.integers(-127, 128, (50, 70), dtype=np.int8)
        w = rng.integers(-127, 128, (70, 30), dtype=np.int8)
        out = int8_matmul(jnp.asarray(x), jnp.asarray(w), block_m=32,
                          block_n=128, block_k=128, interpret=True)
        ref = x.astype(np.int32) @ w.astype(np.int32)
        np.testing.assert_array_equal(np.asarray(out), ref)

    def test_quantize_roundtrip(self):
        rng = np.random.default_rng(1)
        w = _rand(rng, 64, 32)
        w_q, scales = quantize_int8(w, axis=0)
        assert w_q.dtype == jnp.int8 and scales.shape == (32,)
        deq = np.asarray(w_q, np.float32) * np.asarray(scales)[None, :]
        np.testing.assert_allclose(deq, np.asarray(w), atol=float(
            np.max(np.asarray(scales))) * 0.51)

    def test_quantized_linear_close_to_f32(self):
        rng = np.random.default_rng(2)
        x = _rand(rng, 9, 64)
        w = _rand(rng, 64, 48) * 0.1
        b = _rand(rng, 48) * 0.01
        w_q, scales = quantize_int8(w, axis=0)
        y_q = quantized_linear(x, w_q, scales, b, interpret=True)
        y = x @ w + b
        err = np.abs(np.asarray(y_q) - np.asarray(y)).max()
        scale = float(np.abs(np.asarray(y)).max())
        assert err / scale < 0.05, (err, scale)


class TestQuantizedModules:
    def test_quantize_sequential(self):
        from bigdl_tpu.nn.layers import Linear, ReLU
        from bigdl_tpu.nn.module import Sequential
        from bigdl_tpu.nn.quantized import QuantizedLinear, quantize

        rng = np.random.default_rng(3)
        model = Sequential([Linear(32, 16), ReLU(), Linear(16, 4)])
        x = _rand(rng, 5, 32)
        variables = model.init(jax.random.PRNGKey(0), x)
        y_ref, _ = model.apply(variables, x)

        q_model, q_vars = quantize(model, variables)
        assert isinstance(q_model.layers[0], QuantizedLinear)
        assert isinstance(q_model.layers[2], QuantizedLinear)
        y_q, _ = q_model.apply(q_vars, x)
        rel = (np.abs(np.asarray(y_q) - np.asarray(y_ref)).max()
               / (np.abs(np.asarray(y_ref)).max() + 1e-8))
        assert rel < 0.1, rel
        # original untouched
        y_again, _ = model.apply(variables, x)
        np.testing.assert_array_equal(np.asarray(y_again), np.asarray(y_ref))

    def test_quantize_conv(self):
        from bigdl_tpu.nn.layers import Conv2D
        from bigdl_tpu.nn.module import Sequential
        from bigdl_tpu.nn.quantized import QuantizedConv2D, quantize

        rng = np.random.default_rng(4)
        model = Sequential([Conv2D(3, 8, 3, stride=1, padding="SAME")])
        x = _rand(rng, 2, 8, 8, 3)
        variables = model.init(jax.random.PRNGKey(0), x)
        y_ref, _ = model.apply(variables, x)
        q_model, q_vars = quantize(model, variables)
        assert isinstance(q_model.layers[0], QuantizedConv2D)
        y_q, _ = q_model.apply(q_vars, x)
        assert y_q.shape == y_ref.shape
        rel = (np.abs(np.asarray(y_q) - np.asarray(y_ref)).max()
               / (np.abs(np.asarray(y_ref)).max() + 1e-8))
        assert rel < 0.1, rel

    @pytest.mark.parametrize("groups", [2, 4, 8])
    def test_quantize_grouped_conv(self, groups):
        """reference nGroup int8 conv — incl. depthwise (groups == cin)."""
        from bigdl_tpu.nn.layers import Conv2D
        from bigdl_tpu.nn.module import Sequential
        from bigdl_tpu.nn.quantized import QuantizedConv2D, quantize

        rng = np.random.default_rng(5)
        model = Sequential([Conv2D(8, 16, 3, stride=1, padding="SAME",
                                   groups=groups)])
        x = _rand(rng, 2, 8, 8, 8)
        variables = model.init(jax.random.PRNGKey(0), x)
        y_ref, _ = model.apply(variables, x)
        q_model, q_vars = quantize(model, variables)
        assert isinstance(q_model.layers[0], QuantizedConv2D)
        y_q, _ = q_model.apply(q_vars, x)
        assert y_q.shape == y_ref.shape
        rel = (np.abs(np.asarray(y_q) - np.asarray(y_ref)).max()
               / (np.abs(np.asarray(y_ref)).max() + 1e-8))
        assert rel < 0.1, (groups, rel)

    def test_grouped_conv_per_channel_calibration(self):
        """per-input-channel static activation scales fold per group."""
        import jax.numpy as jnp

        from bigdl_tpu.nn.layers import Conv2D
        from bigdl_tpu.nn.quantized import QuantizedConv2D

        rng = np.random.default_rng(6)
        layer = Conv2D(8, 8, 3, padding="SAME", groups=2)
        x = _rand(rng, 2, 8, 8, 8)
        variables = layer.init(jax.random.PRNGKey(1), x)
        y_ref, _ = layer.apply(variables, x)
        # per-channel scales from the actual activation range
        scales = np.abs(np.asarray(x)).max(axis=(0, 1, 2)) / 127.0
        q, qp = QuantizedConv2D.from_conv(layer, variables["params"],
                                          act_scale=scales)
        y_q, _ = q.forward(qp, {}, jnp.asarray(x))
        rel = (np.abs(np.asarray(y_q) - np.asarray(y_ref)).max()
               / (np.abs(np.asarray(y_ref)).max() + 1e-8))
        assert rel < 0.1, rel


class TestFusedLayerNorm:
    def test_matches_reference(self):
        rng = np.random.default_rng(0)
        x = _rand(rng, 7, 33)
        g = _rand(rng, 33)
        b = _rand(rng, 33)
        out = fused_layernorm(x, g, b, interpret=True)
        mean = np.asarray(x).mean(-1, keepdims=True)
        var = np.asarray(x).var(-1, keepdims=True)
        ref = (np.asarray(x) - mean) / np.sqrt(var + 1e-5)
        ref = ref * np.asarray(g) + np.asarray(b)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-4)

    def test_gradients_match(self):
        rng = np.random.default_rng(2)
        x = _rand(rng, 4, 16)
        g = _rand(rng, 16)
        b = _rand(rng, 16)

        def loss_fused(x, g, b):
            return jnp.sum(fused_layernorm(x, g, b, interpret=True) ** 2)

        def loss_ref(x, g, b):
            mean = jnp.mean(x, axis=-1, keepdims=True)
            var = jnp.var(x, axis=-1, keepdims=True)
            y = (x - mean) * jax.lax.rsqrt(var + 1e-5) * g + b
            return jnp.sum(y ** 2)

        g1 = jax.grad(loss_fused, argnums=(0, 1, 2))(x, g, b)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(x, g, b)
        for a, bb in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                       rtol=1e-3, atol=1e-3)

    def test_3d_input(self):
        rng = np.random.default_rng(1)
        x = _rand(rng, 2, 5, 16)
        g = jnp.ones((16,))
        b = jnp.zeros((16,))
        out = fused_layernorm(x, g, b, interpret=True)
        assert out.shape == x.shape


class TestFlashInMHA:
    def test_mha_flash_path(self):
        from bigdl_tpu.nn.attention import MultiHeadAttention

        rng = np.random.default_rng(5)
        x = _rand(rng, 2, 20, 32)
        mha = MultiHeadAttention(32, 4, causal=True, use_flash=False)
        variables = mha.init(jax.random.PRNGKey(0), x)
        y_ref, _ = mha.apply(variables, x)
        mha_flash = MultiHeadAttention(32, 4, causal=True, use_flash=True)
        y_flash, _ = mha_flash.apply(variables, x)
        np.testing.assert_allclose(np.asarray(y_flash), np.asarray(y_ref),
                                   rtol=2e-3, atol=2e-3)


class TestActivationCalibration:
    """Reference min/max + percentile activation calibration (SURVEY §3.2):
    static per-tensor activation scales from a calibration set, accuracy
    within 1% of float on a trained zoo-style model."""

    def _trained_mlp(self):
        from bigdl_tpu import nn, optim
        from bigdl_tpu.data.dataset import ArrayDataSet
        from bigdl_tpu.runtime.engine import Engine, init_engine

        rs = np.random.RandomState(0)
        x = rs.rand(512, 16).astype(np.float32)
        y = (x[:, :8].sum(1) > x[:, 8:].sum(1)).astype(np.int32)
        Engine.reset()
        init_engine(data=1)
        model = nn.Sequential([nn.Linear(16, 32), nn.ReLU(),
                               nn.Linear(32, 2)])
        opt = optim.Optimizer(model, ArrayDataSet(x, y),
                              nn.CrossEntropyCriterion(), batch_size=64)
        opt.set_optim_method(optim.Adam(learning_rate=5e-3))
        opt.set_end_when(optim.Trigger.max_epoch(20))
        opt.log_every = 10000
        trained = opt.optimize()
        return model, trained.variables, x, y

    def test_calibrated_quantize_accuracy_within_1pct(self):
        from bigdl_tpu.nn.quantized import calibrate, quantize

        model, variables, x, y = self._trained_mlp()

        def top1(variables_, mod):
            out, _ = mod.forward(variables_["params"], variables_["state"],
                                 jnp.asarray(x), training=False)
            return float((np.asarray(out).argmax(1) == y).mean())

        acc_f32 = top1(variables, model)
        calib = calibrate(model, variables,
                          [x[i:i + 64] for i in range(0, 256, 64)],
                          method="percentile", percentile=99.9)
        assert len(calib) == 2  # both Linear leaves calibrated
        q_model, q_vars = quantize(model, variables, calib=calib)
        # calibrated scales recorded as static act_scale params
        flat = str(q_vars["params"])
        assert "act_scale" in flat
        acc_int8 = top1(q_vars, q_model)
        assert acc_f32 - acc_int8 < 0.01, (acc_f32, acc_int8)

    def test_minmax_vs_percentile_scales(self):
        from bigdl_tpu import nn
        from bigdl_tpu.nn.quantized import calibrate

        model = nn.Sequential([nn.Linear(8, 4)])
        rs = np.random.RandomState(1)
        x = rs.randn(64, 8).astype(np.float32)
        x[0, 0] = 100.0  # outlier
        v = model.init(jax.random.PRNGKey(0), jnp.asarray(x))
        mm = calibrate(model, v, [x], method="minmax")
        pc = calibrate(model, v, [x], method="percentile", percentile=99.0)
        (k,) = mm.keys()
        assert mm[k] > 0.5          # dominated by the outlier (100/127)
        assert pc[k] < 0.1 * mm[k]  # percentile clips it away

    def test_nano_quantize_with_calibration(self):
        from bigdl_tpu.nano.inference import InferenceOptimizer

        model, variables, x, y = self._trained_mlp()
        tm = InferenceOptimizer.quantize(
            model, variables, sample=x[:64], precision="int8",
            calib_data=[x[64:128], x[128:192]])
        out = np.asarray(tm(x[:64]))
        acc = (out.argmax(1) == y[:64]).mean()
        assert acc > 0.8

    def test_quantize_and_calibrate_keras_functional_model(self):
        """Regression: quantize/calibrate must descend keras functional
        Models (params keyed by node name), not just Containers."""
        from bigdl_tpu import nn
        from bigdl_tpu.keras.engine import Input, Model
        from bigdl_tpu.nn.quantized import (QuantizedLinear, calibrate,
                                            quantize)

        inp = Input((8,))
        h = nn.Linear(8, 16)(inp)
        h = nn.ReLU()(h)
        out = nn.Linear(16, 3)(h)
        model = Model(inp, out)
        rs = np.random.RandomState(0)
        x = rs.randn(32, 8).astype(np.float32)
        v = model.init(jax.random.PRNGKey(0), jnp.asarray(x))

        calib = calibrate(model, v, [x], method="minmax")
        assert len(calib) == 2

        q_model, q_vars = quantize(model, v, calib=calib)
        qlayers = [n.layer for n in q_model.order
                   if isinstance(n.layer, QuantizedLinear)]
        assert len(qlayers) == 2
        assert "act_scale" in str(q_vars["params"])

        y_f32, _ = model.apply(v, jnp.asarray(x))
        y_q, _ = q_model.apply(q_vars, jnp.asarray(x))
        # int8 with calibrated scales stays close to float
        err = np.abs(np.asarray(y_q) - np.asarray(y_f32)).max()
        assert err < 0.1 * np.abs(np.asarray(y_f32)).max()
        # the ORIGINAL model is untouched
        assert not any(isinstance(n.layer, QuantizedLinear)
                       for n in model.order)

    def test_nano_optimize_with_calibrated_variant(self):
        """Accuracy-vs-speed harness: optimize() ranks fp32 / int8 /
        int8_calibrated under an accuracy budget."""
        from bigdl_tpu.nano.inference import InferenceOptimizer

        model, variables, x, y = self._trained_mlp()

        def acc(outputs):
            return float((outputs.argmax(1) == y[:64]).mean())

        res = InferenceOptimizer.optimize(
            model, variables, x[:64],
            methods=("fp32", "int8", "int8_calibrated"),
            repeats=3, accuracy_fn=acc, accuracy_budget=0.02,
            calib_data=[x[64:192]])
        assert res.results["fp32"]["status"] == "ok"
        assert res.results["int8_calibrated"]["status"] in (
            "ok", "accuracy_drop")
        best, name = res.get_best_model()
        assert name in res.results and best is not None
        assert "int8_calibrated" in res.summary()


class TestPerChannelActivationQuant:
    """VERDICT r3 #6: per-channel calibration — activation scales fold into
    the int8 weight rows, so an outlier input channel no longer dictates
    the whole tensor's quantization resolution."""

    def _outlier_data(self, k=16, n=256):
        rs = np.random.RandomState(0)
        x = rs.randn(n, k).astype(np.float32)
        x[:, 0] *= 60.0          # one outlier channel
        return x

    def test_per_channel_beats_per_tensor_linear(self):
        from bigdl_tpu import nn
        from bigdl_tpu.nn.quantized import calibrate, quantize

        x = self._outlier_data()
        model = nn.Sequential([nn.Linear(16, 8)])
        variables = model.init(jax.random.PRNGKey(0), x[:1])
        ref, _ = model.forward(variables["params"], variables["state"],
                               jnp.asarray(x), training=False)
        errs = {}
        for gran in ("tensor", "channel"):
            calib = calibrate(model, variables, [x], method="minmax",
                              granularity=gran)
            qm, qv = quantize(model, variables, calib=calib)
            out, _ = qm.forward(qv["params"], qv["state"], jnp.asarray(x),
                                training=False)
            errs[gran] = float(np.abs(np.asarray(out)
                                      - np.asarray(ref)).mean())
        assert errs["channel"] < errs["tensor"], errs

    def test_per_channel_beats_per_tensor_conv(self):
        from bigdl_tpu import nn
        from bigdl_tpu.nn.quantized import calibrate, quantize

        rs = np.random.RandomState(1)
        x = rs.randn(8, 8, 8, 6).astype(np.float32)
        x[..., 0] *= 40.0        # outlier input channel
        model = nn.Sequential([nn.Conv2D(6, 4, kernel_size=(3, 3),
                                         padding="same")])
        variables = model.init(jax.random.PRNGKey(0), x[:1])
        ref, _ = model.forward(variables["params"], variables["state"],
                               jnp.asarray(x), training=False)
        errs = {}
        for gran in ("tensor", "channel"):
            calib = calibrate(model, variables, [x], method="minmax",
                              granularity=gran)
            qm, qv = quantize(model, variables, calib=calib)
            out, _ = qm.forward(qv["params"], qv["state"], jnp.asarray(x),
                                training=False)
            errs[gran] = float(np.abs(np.asarray(out)
                                      - np.asarray(ref)).mean())
        assert errs["channel"] < errs["tensor"], errs

    def test_calibration_sweep_all_combos(self):
        """minmax/percentile x tensor/channel all produce working int8
        models (the VERDICT-requested sweep)."""
        from bigdl_tpu import nn
        from bigdl_tpu.nn.quantized import calibrate, quantize

        x = self._outlier_data()
        model = nn.Sequential([nn.Linear(16, 8), nn.ReLU(),
                               nn.Linear(8, 4)])
        variables = model.init(jax.random.PRNGKey(0), x[:1])
        ref, _ = model.forward(variables["params"], variables["state"],
                               jnp.asarray(x), training=False)
        for method in ("minmax", "percentile"):
            for gran in ("tensor", "channel"):
                calib = calibrate(model, variables, [x], method=method,
                                  granularity=gran)
                if gran == "channel":
                    assert all(np.ndim(v) == 1 for v in calib.values())
                qm, qv = quantize(model, variables, calib=calib)
                out, _ = qm.forward(qv["params"], qv["state"],
                                    jnp.asarray(x), training=False)
                err = float(np.abs(np.asarray(out)
                                   - np.asarray(ref)).mean())
                ref_mag = float(np.abs(np.asarray(ref)).mean())
                assert err < 0.25 * ref_mag, (method, gran, err, ref_mag)

    def test_granularity_validation(self):
        from bigdl_tpu import nn
        from bigdl_tpu.nn.quantized import calibrate

        model = nn.Sequential([nn.Linear(4, 2)])
        v = model.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.float32))
        with pytest.raises(ValueError, match="granularity"):
            calibrate(model, v, [np.zeros((2, 4), np.float32)],
                      granularity="row")


class TestQAT:
    """Quantization-aware training: fake-quant fine-tune -> int8 convert
    (beyond the reference's PTQ-only nn/quantized stack)."""

    def _setup(self):
        from bigdl_tpu.nn.layers import Linear, ReLU
        from bigdl_tpu.nn.module import Sequential

        rs = np.random.RandomState(0)
        x = rs.randn(256, 8).astype(np.float32)
        w_true = rs.randn(8, 1).astype(np.float32)
        y = x @ w_true
        model = Sequential([Linear(8, 32), ReLU(), Linear(32, 1)])
        variables = model.init(jax.random.PRNGKey(0), x[:2])
        return model, variables, x, y

    def _train(self, model, variables, x, y, steps=150, lr=0.05):
        import jax.numpy as jnp

        params, state = variables["params"], variables["state"]

        @jax.jit
        def step(p, s):
            def loss_fn(p):
                out, ns = model.forward(p, s, jnp.asarray(x), training=True)
                return jnp.mean((out - jnp.asarray(y)) ** 2), ns

            (l, ns), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
            return jax.tree_util.tree_map(lambda a, b: a - lr * b, p, g), \
                ns, l

        for _ in range(steps):
            params, state, loss = step(params, state)
        return {"params": params, "state": state}, float(loss)

    def _mse(self, model, variables, x, y):
        import jax.numpy as jnp

        out, _ = model.apply(variables, jnp.asarray(x))
        return float(np.mean((np.asarray(out) - y) ** 2))

    def test_qat_roundtrip_and_conversion(self):
        from bigdl_tpu.nn.qat import QATLinear, convert_qat, prepare_qat
        from bigdl_tpu.nn.quantized import QuantizedLinear

        model, variables, x, y = self._setup()
        variables, _ = self._train(model, variables, x, y)
        fp32_mse = self._mse(model, variables, x, y)

        qat_model, qat_vars = prepare_qat(model, variables)
        # params are reused verbatim: same keys, same arrays
        assert set(qat_vars["params"].keys()) == set(
            variables["params"].keys())
        assert any(isinstance(m, QATLinear) for m in qat_model.layers)

        qat_vars, _ = self._train(qat_model, qat_vars, x, y, steps=80,
                                  lr=0.01)
        # EMA activation ranges were tracked
        amaxes = [float(s["act_amax"]) for s in
                  qat_vars["state"].values() if "act_amax" in s]
        assert amaxes and all(a > 0 for a in amaxes)

        int8_model, int8_vars = convert_qat(qat_model, qat_vars)
        assert any(isinstance(m, QuantizedLinear)
                   for m in int8_model.layers)
        # learned ranges became static calibration scales
        leaf = next(m for m in int8_model.layers
                    if isinstance(m, QuantizedLinear))
        k = int8_model._key(int8_model.layers.index(leaf))
        assert "act_scale" in int8_vars["params"][k]

        int8_mse = self._mse(int8_model, int8_vars, x, y)
        # int8 stays close to the fp32 model it was trained from
        assert int8_mse < max(4 * fp32_mse, 5e-2), (int8_mse, fp32_mse)

    def test_qat_on_keras_functional_model(self):
        """prepare_qat/convert_qat descend keras graphs like quantize."""
        from bigdl_tpu import nn
        from bigdl_tpu.keras.engine import Input, Model
        from bigdl_tpu.nn.qat import QATLinear, convert_qat, prepare_qat
        from bigdl_tpu.nn.quantized import QuantizedLinear

        inp = Input((8,))
        h = nn.Linear(8, 16)(inp)
        h = nn.ReLU()(h)
        out = nn.Linear(16, 3)(h)
        model = Model(inp, out)
        rs = np.random.RandomState(0)
        x = rs.randn(32, 8).astype(np.float32)
        v = model.init(jax.random.PRNGKey(0), jnp.asarray(x))

        qat_model, qat_vars = prepare_qat(model, v)
        assert sum(isinstance(n.layer, QATLinear)
                   for n in qat_model.order) == 2
        # params reused verbatim; a forward in training mode tracks ranges
        y, st = qat_model.forward(qat_vars["params"], qat_vars["state"],
                                  jnp.asarray(x), training=True)
        qat_vars = {"params": qat_vars["params"], "state": st}
        amaxes = [float(s["act_amax"]) for s in st.values()
                  if isinstance(s, dict) and "act_amax" in s]
        assert len(amaxes) == 2 and all(a > 0 for a in amaxes)

        int8_model, int8_vars = convert_qat(qat_model, qat_vars)
        assert sum(isinstance(n.layer, QuantizedLinear)
                   for n in int8_model.order) == 2
        y_f32, _ = model.apply(v, jnp.asarray(x))
        y_q, _ = int8_model.apply(int8_vars, jnp.asarray(x))
        err = np.abs(np.asarray(y_q) - np.asarray(y_f32)).max()
        assert err < 0.15 * np.abs(np.asarray(y_f32)).max()

    def test_qat_eval_before_training_passes_through(self):
        """amax untracked (eval before any train step) must NOT quantize
        with the epsilon floor — that collapses activations to ~0."""
        from bigdl_tpu.nn.qat import prepare_qat

        model, variables, x, y = self._setup()
        qat_model, qat_vars = prepare_qat(model, variables)
        y_fp32, _ = model.apply(variables, jnp.asarray(x))
        y_qat, _ = qat_model.apply(qat_vars, jnp.asarray(x))
        # weights fake-quantize (small error); activations pass through
        rel = (np.abs(np.asarray(y_qat) - np.asarray(y_fp32)).max()
               / (np.abs(np.asarray(y_fp32)).max() + 1e-8))
        assert rel < 0.05, rel

    def test_qat_beats_naive_ptq_on_outlier_activations(self):
        """An input channel with a huge range wrecks per-tensor PTQ's
        activation grid; QAT's fine-tune adapts the weights to it."""
        from bigdl_tpu.nn.layers import Linear
        from bigdl_tpu.nn.module import Sequential
        from bigdl_tpu.nn.qat import convert_qat, prepare_qat
        from bigdl_tpu.nn.quantized import calibrate, quantize

        rs = np.random.RandomState(1)
        x = rs.randn(256, 8).astype(np.float32)
        x[:, 0] *= 60.0  # outlier channel
        y = (x @ rs.randn(8, 1).astype(np.float32) / 60.0)
        model = Sequential([Linear(8, 1)])
        variables = model.init(jax.random.PRNGKey(0), x[:2])
        variables, _ = self._train(model, variables, x, y, steps=400,
                                   lr=2e-4)

        # per-tensor static PTQ (minmax) — the naive reference path
        calib = calibrate(model, variables, [x], method="minmax",
                          granularity="tensor")
        ptq_model, ptq_vars = quantize(model, variables, calib=calib)
        ptq_mse = self._mse(ptq_model, ptq_vars, x, y)

        qat_model, qat_vars = prepare_qat(model, variables)
        qat_vars, _ = self._train(qat_model, qat_vars, x, y, steps=300,
                                  lr=2e-4)
        int8_model, int8_vars = convert_qat(qat_model, qat_vars)
        qat_mse = self._mse(int8_model, int8_vars, x, y)

        assert qat_mse <= ptq_mse * 1.05, (qat_mse, ptq_mse)


class TestGradientChecker:
    """Finite-difference validation of the HAND-WRITTEN custom_vjp
    backwards — reference nn/GradientChecker.scala; autodiff ops don't
    need it, the Pallas kernels' bwd rules do."""

    def test_flash_attention_bwd_matches_finite_differences(self):
        from bigdl_tpu.ops.flash_attention import flash_attention
        from bigdl_tpu.utils.gradcheck import check_grad

        rs = np.random.RandomState(0)
        q = rs.randn(1, 1, 8, 4).astype(np.float32) * 0.5
        kv = jnp.asarray(rs.randn(1, 1, 8, 4), jnp.float32) * 0.5

        def loss(qq):
            o = flash_attention(qq, kv, kv, causal=True, interpret=True)
            # a non-uniform weighting so every grad component matters
            w = jnp.arange(o.size, dtype=jnp.float32).reshape(o.shape)
            return jnp.sum(o * w) / o.size

        check_grad(loss, q, eps=1e-2, samples=16)

    def test_fused_layernorm_bwd_matches_finite_differences(self):
        from bigdl_tpu.ops.fused import fused_layernorm
        from bigdl_tpu.utils.gradcheck import check_grad

        rs = np.random.RandomState(1)
        x = rs.randn(4, 16).astype(np.float32)
        g = jnp.asarray(rs.randn(16), jnp.float32)
        b = jnp.asarray(rs.randn(16), jnp.float32)

        def loss(xx):
            o = fused_layernorm(xx, g, b, interpret=True)
            w = jnp.arange(o.size, dtype=jnp.float32).reshape(o.shape)
            return jnp.sum(o * w) / o.size

        check_grad(loss, x, eps=1e-2, samples=24)

    def test_checker_catches_a_wrong_gradient(self):
        """The checker itself must fail on a broken custom backward."""
        import jax

        from bigdl_tpu.utils.gradcheck import check_grad

        @jax.custom_vjp
        def broken_square(x):
            return jnp.sum(x * x)

        def fwd(x):
            return jnp.sum(x * x), x

        def bwd(res, ct):
            return (3.0 * res * ct,)  # wrong: d(x^2)/dx is 2x, not 3x

        broken_square.defvjp(fwd, bwd)
        x = np.random.RandomState(2).randn(8).astype(np.float32)
        with pytest.raises(AssertionError, match="gradient mismatch"):
            check_grad(broken_square, x, samples=8)


class TestWeightOnly:
    """Weight-only int8 (int8 weights, full-precision compute) — the
    decode-bound serving trade; beyond the reference's always-quantized
    activations."""

    def test_weight_only_closer_than_full_int8(self):
        from bigdl_tpu.nn.layers import Linear, ReLU
        from bigdl_tpu.nn.module import Sequential
        from bigdl_tpu.nn.quantized import WeightOnlyLinear, quantize

        rng = np.random.default_rng(0)
        model = Sequential([Linear(32, 64), ReLU(), Linear(64, 8)])
        x = _rand(rng, 16, 32)
        v = model.init(jax.random.PRNGKey(0), x)
        y_ref, _ = model.apply(v, x)

        wo_model, wo_vars = quantize(model, v, weight_only=True)
        assert isinstance(wo_model.layers[0], WeightOnlyLinear)
        y_wo, _ = wo_model.apply(wo_vars, x)

        full_model, full_vars = quantize(model, v)
        y_full, _ = full_model.apply(full_vars, x)

        err_wo = np.abs(np.asarray(y_wo) - np.asarray(y_ref)).max()
        err_full = np.abs(np.asarray(y_full) - np.asarray(y_ref)).max()
        # no activation-quantization error -> strictly tighter
        assert err_wo <= err_full, (err_wo, err_full)
        assert err_wo < 0.05 * np.abs(np.asarray(y_ref)).max()
        # weights really are int8 on disk
        assert wo_vars["params"][wo_model._key(0)]["weight_q"].dtype == \
            jnp.int8

    def test_weight_only_conv_and_nano_surface(self):
        from bigdl_tpu.nano.inference import InferenceOptimizer
        from bigdl_tpu.nn.layers import Conv2D
        from bigdl_tpu.nn.module import Sequential
        from bigdl_tpu.nn.quantized import WeightOnlyConv2D, quantize

        rng = np.random.default_rng(1)
        model = Sequential([Conv2D(3, 8, 3, padding="SAME", groups=1)])
        x = _rand(rng, 2, 8, 8, 3)
        v = model.init(jax.random.PRNGKey(0), x)
        y_ref, _ = model.apply(v, x)
        wo_model, wo_vars = quantize(model, v, weight_only=True)
        assert isinstance(wo_model.layers[0], WeightOnlyConv2D)
        y_wo, _ = wo_model.apply(wo_vars, x)
        err = np.abs(np.asarray(y_wo) - np.asarray(y_ref)).max()
        assert err < 0.05 * np.abs(np.asarray(y_ref)).max()

        tm = InferenceOptimizer.quantize(model, v, sample=x,
                                         precision="int8_wo")
        out = np.asarray(tm(x))
        assert out.shape == np.asarray(y_ref).shape


class TestBlockSparse:
    """Block-sparse matmul (BLaST FFN path, docs/performance.md
    §Block-sparse FFN) — parity vs a dense-masked jnp reference in
    interpret mode, the exact code path Mosaic compiles on TPU."""

    def _mask(self, rng, nkb, nnb, density=0.6):
        m = rng.random((nkb, nnb)) < density
        m[0, 0] = True  # never a fully-empty mask
        return m

    @pytest.mark.parametrize("shape", [(32, 64, 48), (37, 64, 48),
                                       (16, 96, 32)])
    def test_matmul_parity_vs_dense_masked(self, shape):
        from bigdl_tpu.ops.block_sparse import (block_sparse_matmul,
                                                expand_mask)

        rng = np.random.default_rng(0)
        m, k, n = shape
        bk, bn = 16, 16
        x = _rand(rng, m, k)
        w = _rand(rng, k, n)
        mask = self._mask(rng, -(-k // bk), -(-n // bn))
        out = block_sparse_matmul(x, w, mask, block_k=bk, block_n=bn,
                                  interpret=True)
        ref = np.asarray(x) @ (np.asarray(w)
                               * expand_mask(mask, k, n, bk, bn))
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                                   atol=2e-4)

    def test_empty_output_column_yields_zeros(self):
        from bigdl_tpu.ops.block_sparse import block_sparse_matmul

        rng = np.random.default_rng(1)
        x = _rand(rng, 16, 32)
        w = _rand(rng, 32, 32)
        mask = np.ones((2, 2), bool)
        mask[:, 1] = False  # second output block-column fully pruned
        out = np.asarray(block_sparse_matmul(x, w, mask, block_k=16,
                                             block_n=16, interpret=True))
        assert np.all(out[:, 16:] == 0.0)
        ref = np.asarray(x) @ np.asarray(w)
        np.testing.assert_allclose(out[:, :16], ref[:, :16], rtol=2e-4,
                                   atol=2e-4)

    def test_gradients_match_dense_masked(self):
        from bigdl_tpu.ops.block_sparse import (block_sparse_matmul,
                                                expand_mask)

        rng = np.random.default_rng(2)
        k, n = 48, 32
        bk, bn = 16, 16
        x = _rand(rng, 8, k)
        w = _rand(rng, k, n)
        mask = self._mask(rng, 3, 2)
        em = jnp.asarray(expand_mask(mask, k, n, bk, bn), jnp.float32)

        def loss_sparse(x, w):
            y = block_sparse_matmul(x, w, mask, block_k=bk, block_n=bn,
                                    interpret=True)
            return jnp.sum(y ** 2)

        def loss_ref(x, w):
            return jnp.sum((x @ (w * em)) ** 2)

        g1 = jax.grad(loss_sparse, argnums=(0, 1))(x, w)
        g2 = jax.grad(loss_ref, argnums=(0, 1))(x, w)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-3)
        # the weight grad is masked: pruned blocks receive exactly zero
        dw = np.asarray(g1[1])
        assert np.all(dw[np.asarray(em) == 0.0] == 0.0)

    def test_traced_mask_rejected(self):
        from bigdl_tpu.ops.block_sparse import block_sparse_matmul

        x = jnp.ones((8, 16))
        w = jnp.ones((16, 16))

        def f(m):
            return block_sparse_matmul(x, w, m, block_k=16, block_n=16,
                                       interpret=True)

        with pytest.raises(TypeError, match="concrete"):
            jax.jit(f)(jnp.ones((1, 1), bool))

    def test_linear_module_prune_and_parity(self):
        from bigdl_tpu.ops.block_sparse import (BlockSparseLinear,
                                                expand_mask)

        rng = np.random.default_rng(3)
        x = _rand(rng, 9, 64)
        lin = BlockSparseLinear(64, 48, block_shape=(16, 16),
                                target_sparsity=0.5)
        v = lin.init(jax.random.PRNGKey(0), x)
        y_dense, _ = lin.apply(v, x)
        # dense warmup: all-ones mask == plain Linear math
        w = np.asarray(v["params"]["weight"])
        b = np.asarray(v["params"]["bias"])
        np.testing.assert_allclose(np.asarray(y_dense), x @ w + b,
                                   rtol=1e-4, atol=1e-4)
        ach = lin.prune_to(v["params"], 0.5)
        assert ach == pytest.approx(0.5)
        y_sparse, _ = lin.apply(v, x)
        em = expand_mask(lin.mask, 64, 48, 16, 16)
        np.testing.assert_allclose(np.asarray(y_sparse),
                                   x @ (w * em) + b, rtol=1e-3, atol=1e-3)
        # magnitude pruning keeps the heavy blocks: surviving block L1
        # mass >= any pruned block's
        scores = np.abs(w).reshape(4, 16, 3, 16).sum(axis=(1, 3))
        assert scores[lin.mask].min() >= scores[~lin.mask].max()

    def test_prune_is_monotone_no_resurrection(self):
        from bigdl_tpu.ops.block_sparse import BlockSparseLinear

        rng = np.random.default_rng(4)
        x = _rand(rng, 4, 64)
        lin = BlockSparseLinear(64, 64, block_shape=(16, 16))
        v = lin.init(jax.random.PRNGKey(1), x)
        lin.prune_to(v["params"], 0.25)
        kept_25 = lin.mask.copy()
        lin.prune_to(v["params"], 0.5)
        # every survivor of the deeper prune survived the shallow one
        assert np.all(kept_25[lin.mask])
        # and pruning shallower afterwards never resurrects
        lin.prune_to(v["params"], 0.25)
        assert lin.sparsity() == pytest.approx(0.5)

    def test_transformer_ffn_sparsity_end_to_end(self):
        from bigdl_tpu.nn.attention import Transformer
        from bigdl_tpu.ops.block_sparse import (iter_sparse_modules,
                                                prune_model_to_sparsity)

        model = Transformer(vocab_size=64, hidden_size=32, num_heads=2,
                            ffn_size=64, num_layers=2, dropout=0.0,
                            mode="lm", ffn_sparsity=0.5,
                            sparse_block=(16, 16))
        ids = jnp.asarray(np.arange(24).reshape(2, 12) % 64)
        v = model.init(jax.random.PRNGKey(0), ids)
        y_dense, _ = model.apply(v, ids)
        # exact capture-based binding (sample_inputs): one real forward
        # records which params dict each sparse module receives
        achieved = prune_model_to_sparsity(model, v, 0.5,
                                           sample_inputs=(ids,))
        # both FFN linears of both layers pruned
        assert len(achieved) == 4
        assert all(s == pytest.approx(0.5) for s in achieved.values())
        for _, mod in iter_sparse_modules(model):
            assert mod.density() == pytest.approx(0.5)
        y_sparse, _ = model.apply(v, ids)
        assert np.all(np.isfinite(np.asarray(y_sparse)))
        assert not np.allclose(np.asarray(y_sparse), np.asarray(y_dense))
        # training still differentiates through the sparse kernels
        g = jax.grad(lambda p: model.forward(
            p, {}, ids)[0].sum())(v["params"])
        leaves = jax.tree_util.tree_leaves(g)
        assert leaves and all(np.all(np.isfinite(np.asarray(l)))
                              for l in leaves)

    def test_pruning_schedule_monotone(self):
        from bigdl_tpu.ops.block_sparse import BlockPruningSchedule

        sch = BlockPruningSchedule(0.75, warmup_steps=10, ramp_steps=40,
                                   n_events=4)
        vals = [sch.sparsity_at(s) for s in range(80)]
        assert all(b >= a for a, b in zip(vals, vals[1:]))
        assert all(v == 0.0 for v in vals[:10])       # dense warmup
        assert vals[-1] == pytest.approx(0.75)        # reaches target
        steps = sch.prune_steps()
        assert steps and len(steps) <= 4
        # prune_steps are exactly where sparsity_at increases
        for s in steps:
            assert sch.sparsity_at(s) > sch.sparsity_at(s - 1)
        # degenerate schedules
        assert BlockPruningSchedule(0.0, 0, 0).prune_steps() == []
        assert BlockPruningSchedule(0.5, 5, 0).sparsity_at(5) == 0.5

    def test_mask_collect_apply_roundtrip(self):
        from bigdl_tpu.nn.attention import Transformer
        from bigdl_tpu.ops.block_sparse import (apply_masks, collect_masks,
                                                prune_model_to_sparsity)

        mk = lambda: Transformer(vocab_size=32, hidden_size=16,
                                 num_heads=2, ffn_size=32, num_layers=1,
                                 dropout=0.0, mode="lm", ffn_sparsity=0.5,
                                 sparse_block=(16, 16))
        model = mk()
        ids = jnp.asarray(np.arange(8).reshape(1, 8) % 32)
        v = model.init(jax.random.PRNGKey(0), ids)
        prune_model_to_sparsity(model, v, 0.5)
        masks = collect_masks(model)
        fresh = mk()
        fresh.init(jax.random.PRNGKey(0), ids)
        assert apply_masks(fresh, masks) == len(masks) > 0
        y1, _ = model.apply(v, ids)
        y2, _ = fresh.apply(v, ids)
        np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))


class TestAutotune:
    """Kernel tile autotuner (docs/performance.md §Kernel autotuning):
    cache determinism, explicit-kwarg precedence, never-slower-than-
    default."""

    @pytest.fixture()
    def at(self, tmp_path, monkeypatch):
        from bigdl_tpu.ops import autotune

        monkeypatch.setenv("BIGDL_TPU_AUTOTUNE_CACHE", str(tmp_path))
        monkeypatch.delenv("BIGDL_TPU_AUTOTUNE", raising=False)
        autotune.reset_cache()
        yield autotune
        autotune.reset_cache()

    def _fake_measure(self, monkeypatch, at, fn):
        calls = {"n": 0}

        def fake(thunk, repeats=0):
            calls["n"] += 1
            return fn()

        monkeypatch.setattr(at, "_measure_ms", fake)
        return calls

    def test_cache_hit_determinism_second_lookup_zero_trials(
            self, at, monkeypatch):
        # deterministic fake timing: the 4th measured config is fastest
        seen = []

        def fake(thunk, repeats=0):
            seen.append(1)
            return 0.5 if len(seen) == 4 else 1.0 + 0.1 * len(seen)

        monkeypatch.setattr(at, "_measure_ms", fake)
        shape = (512, 256, "float32")
        entry = at.tune("fused_layernorm", shape, n_trials=8)
        assert entry["trials"] > 0
        # tune keys through the SAME bucketed key the kernel computes at
        # call time — an offline winner must be exactly what
        # fused_layernorm(block_rows=None) looks up
        key = at.canonical_key("fused_layernorm", shape)
        assert key == at.full_key("fused_layernorm",
                                  at.rows_key(512, 256, "float32"))
        assert at.get_cache().get(key)["tiles"] == entry["tiles"]
        # a "second process": fresh in-memory handle over the same dir,
        # online mode armed — the disk hit must answer with ZERO timing
        # trials and the identical tiles
        at.reset_cache()
        monkeypatch.setenv("BIGDL_TPU_AUTOTUNE", "online")
        n_before = len(seen)
        got = at.resolve("fused_layernorm", at.rows_key(512, 256,
                                                        "float32"),
                         online_shape=shape)
        assert len(seen) == n_before
        assert got == entry["tiles"]

    def test_explicit_kwarg_beats_cache(self, at):
        key_shape = "r512_c256_float32"
        at.get_cache().put(
            at.full_key("fused_layernorm", key_shape),
            {"tiles": {"block_rows": 1024}, "best_ms": 1.0,
             "default_ms": 2.0, "trials": 5, "winner": "searched"})
        # cache wins over the registry default...
        auto = at.resolve("fused_layernorm", key_shape)
        assert auto["block_rows"] == 1024
        # ...but an explicit kwarg beats the cache
        expl = at.resolve("fused_layernorm", key_shape,
                          explicit={"block_rows": 64})
        assert expl["block_rows"] == 64
        # and None means "not passed", not "explicit"
        expl2 = at.resolve("fused_layernorm", key_shape,
                           explicit={"block_rows": None})
        assert expl2["block_rows"] == 1024

    def test_mode_off_ignores_cache(self, at, monkeypatch):
        key_shape = "r512_c256_float32"
        at.get_cache().put(
            at.full_key("fused_layernorm", key_shape),
            {"tiles": {"block_rows": 1024}, "best_ms": 1.0,
             "default_ms": 2.0, "trials": 5, "winner": "searched"})
        monkeypatch.setenv("BIGDL_TPU_AUTOTUNE", "off")
        assert at.resolve("fused_layernorm",
                          key_shape)["block_rows"] == 256  # the default

    def test_tuner_never_slower_than_default(self, at, monkeypatch):
        # every candidate measures SLOWER than the default -> the tuner
        # must hand back the hand-picked defaults
        def fake_slow(thunk, repeats=0):
            fake_slow.n = getattr(fake_slow, "n", 0) + 1
            return 1.0 if fake_slow.n == 1 else 5.0  # first call = default

        monkeypatch.setattr(at, "_measure_ms", fake_slow)
        entry = at.tune("fused_layernorm", (512, 256, "float32"),
                        n_trials=6)
        assert entry["winner"] == "default"
        assert entry["tiles"] == {"block_rows": 256}
        assert entry["best_ms"] <= entry["default_ms"]

    def test_garbage_cache_entry_falls_back_to_defaults(self, at):
        key_shape = "r512_c256_float32"
        at.get_cache().put(
            at.full_key("fused_layernorm", key_shape),
            {"tiles": {"block_rows": "boom"}, "best_ms": 1.0,
             "default_ms": 2.0, "trials": 1, "winner": "searched"})
        assert at.resolve("fused_layernorm",
                          key_shape)["block_rows"] == 256

    def test_kernels_consult_resolution_without_breaking_parity(
            self, at):
        """flash_attention/fused_layernorm with auto tiles (None) match
        their explicit-tile outputs — the resolution layer changes tile
        choice, never math."""
        from bigdl_tpu.ops import flash_attention, fused_layernorm

        rng = np.random.default_rng(0)
        q = _rand(rng, 1, 2, 24, 8)
        a = flash_attention(q, q, q, causal=True, interpret=True)
        b = flash_attention(q, q, q, causal=True, block_q=8, block_k=8,
                            interpret=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)
        x = _rand(rng, 9, 17)
        g2 = _rand(rng, 17)
        b2 = _rand(rng, 17)
        y_auto = fused_layernorm(x, g2, b2, interpret=True)
        y_expl = fused_layernorm(x, g2, b2, block_rows=8, interpret=True)
        np.testing.assert_allclose(np.asarray(y_auto), np.asarray(y_expl),
                                   rtol=1e-5, atol=1e-5)


class TestBlockSparseServing:
    """The pruned FFN serves through InferenceModel unchanged: the mask
    is a compile-time constant of the jitted forward."""

    def test_inference_model_serves_pruned_transformer(self):
        from bigdl_tpu.nn.attention import Transformer
        from bigdl_tpu.ops.block_sparse import prune_model_to_sparsity
        from bigdl_tpu.serving.inference_model import InferenceModel

        model = Transformer(vocab_size=32, hidden_size=16, num_heads=2,
                            ffn_size=32, num_layers=1, dropout=0.0,
                            mode="lm", ffn_sparsity=0.5,
                            sparse_block=(16, 16))
        ids = np.arange(16).reshape(2, 8).astype(np.int32) % 32
        v = model.init(jax.random.PRNGKey(0), jnp.asarray(ids))
        prune_model_to_sparsity(model, v, 0.5)
        ref, _ = model.apply(v, jnp.asarray(ids))
        im = InferenceModel(model, v, batch_buckets=(2,))
        out = im.predict(ids)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)


class TestAutotuneOnline:
    def test_online_mode_tunes_on_first_eager_call_only(
            self, tmp_path, monkeypatch):
        """BIGDL_TPU_AUTOTUNE=online: the first EAGER kernel call at a
        new shape bucket runs trials and caches; the second call (and any
        jitted call) runs zero trials."""
        from bigdl_tpu.ops import autotune, fused_layernorm

        monkeypatch.setenv("BIGDL_TPU_AUTOTUNE_CACHE", str(tmp_path))
        monkeypatch.setenv("BIGDL_TPU_AUTOTUNE", "online")
        autotune.reset_cache()
        calls = {"n": 0}

        def fake(thunk, repeats=0):
            calls["n"] += 1
            return float(calls["n"])  # first measured (the default) wins

        monkeypatch.setattr(autotune, "_measure_ms", fake)
        rng = np.random.default_rng(0)
        x = _rand(rng, 32, 16)
        g = _rand(rng, 16)
        b = _rand(rng, 16)
        y = fused_layernorm(x, g, b, interpret=True)
        assert calls["n"] > 0  # tuned on the miss
        n_after = calls["n"]
        y2 = fused_layernorm(x, g, b, interpret=True)
        assert calls["n"] == n_after  # cache hit: zero further trials
        np.testing.assert_allclose(np.asarray(y), np.asarray(y2))
        # jitted call: tracers -> no tuning, cache consulted silently
        jax.jit(lambda x: fused_layernorm(x, g, b, interpret=True))(x)
        assert calls["n"] == n_after
        autotune.reset_cache()


class TestPruneBinding:
    def test_capture_binding_survives_same_shaped_dense_linear(self):
        """A dense Linear with the SAME (in, out) ahead of the sparse
        layer must not be mistaken for it: capture-based binding prunes
        by the SPARSE layer's own weights."""
        from bigdl_tpu.nn.layers import Linear, ReLU
        from bigdl_tpu.nn.module import Sequential
        from bigdl_tpu.ops.block_sparse import (BlockSparseLinear,
                                                prune_model_to_sparsity)

        rng = np.random.default_rng(7)
        model = Sequential([Linear(32, 32), ReLU(),
                            BlockSparseLinear(32, 32, block_shape=(16, 16))])
        x = _rand(rng, 4, 32)
        v = model.init(jax.random.PRNGKey(0), x)
        # make the sparse layer's block magnitudes unambiguous: one block
        # overwhelmingly heavy
        key = model._key(2)
        w = np.asarray(v["params"][key]["weight"]).copy()
        w[:16, :16] = 100.0
        v["params"][key]["weight"] = jnp.asarray(w)
        achieved = prune_model_to_sparsity(model, v, 0.75,
                                           sample_inputs=(x,))
        assert list(achieved.values()) == [0.75]
        (_, mod), = [pm for pm in __import__(
            "bigdl_tpu.ops.block_sparse",
            fromlist=["iter_sparse_modules"]).iter_sparse_modules(model)]
        assert mod.mask[0, 0] and mod.mask.sum() == 1  # the heavy block


class TestSparseMaskResume:
    def test_masks_ride_checkpoint_and_restore_on_resume(self, tmp_path):
        """A pruned FFN's masks are host module state: they ride the
        checkpoint driver_state, and a FRESH process (dense all-ones
        modules) resuming from that checkpoint gets them back — without
        this, a preempted sparse run silently resumes dense."""
        from bigdl_tpu import nn, optim
        from bigdl_tpu.data.dataset import ArrayDataSet
        from bigdl_tpu.ops.block_sparse import (BlockSparseLinear,
                                                collect_masks,
                                                prune_model_to_sparsity)
        from bigdl_tpu.runtime.engine import Engine, init_engine

        Engine.reset()
        init_engine(data=1)
        rs = np.random.RandomState(0)
        x = rs.rand(64, 32).astype(np.float32)
        y = (x.sum(1) > 16).astype(np.int32)

        def mk():
            return nn.Sequential([
                BlockSparseLinear(32, 32, block_shape=(16, 16),
                                  target_sparsity=0.5),
                nn.ReLU(), nn.Linear(32, 2)])

        def run(model, epochs):
            opt = optim.Optimizer(model, ArrayDataSet(x, y),
                                  nn.CrossEntropyCriterion(),
                                  batch_size=32)
            opt.set_optim_method(optim.Adam(learning_rate=1e-3))
            opt.set_end_when(optim.Trigger.max_epoch(epochs))
            opt.set_checkpoint(str(tmp_path),
                               optim.Trigger.several_iteration(2))
            opt.log_every = 10000
            return opt.optimize()

        m1 = mk()
        v = m1.init(jax.random.PRNGKey(0), x[:1])
        prune_model_to_sparsity(m1, v, 0.5, sample_inputs=(x[:1],))
        masks1 = collect_masks(m1)
        assert any(not np.asarray(m).all() for m in masks1.values())
        run(m1, 1)

        # "fresh process": new modules (all-ones masks), same ckpt dir
        m2 = mk()
        trained = run(m2, 2)  # resumes epoch 1's checkpoint, trains on
        assert collect_masks(m2) == masks1
        out, _ = m2.apply(trained.variables, x)
        assert np.all(np.isfinite(np.asarray(out)))
