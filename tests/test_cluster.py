"""Pod-scale coordinated fault tolerance (``resilience.membership`` /
``resilience.cluster``): membership views, leader failover, partition
heal, gang recovery, peer-shard restore, preemption propagation, and the
elastic re-sharded mid-epoch resume.

The load-bearing specs are the chaos acceptance tests: under injected
``cluster_host_loss`` mid-run, training completes with weights
bit-identical to the fault-free run (the restored trajectory is the
fault-free trajectory), peer-shard restore is verified bit-identical to a
checkpoint restore of the same step, and MTTR + ``cluster.*`` metrics
appear in /metrics and the flight recorder.  Everything runs
single-process under tier-1 (injected clocks, ``memory://``-style shared
dirs); the true multi-process kill/rejoin drill is a ``slow`` mark.
"""

import json
import os
import signal

import numpy as np
import pytest

from bigdl_tpu.resilience import faults
from bigdl_tpu.resilience.cluster import (ClusterConfig, ClusterCoordinator,
                                          GangAbortedError, PeerShardStore)
from bigdl_tpu.resilience.detector import Heartbeat
from bigdl_tpu.resilience.faults import FaultSpec, HostLostError
from bigdl_tpu.resilience.membership import MembershipBoard, MembershipView
from bigdl_tpu.resilience.retry import (FailureCause, FailurePolicy,
                                        RetryPolicy, classify)


@pytest.fixture(autouse=True)
def _clear_faults():
    yield
    faults.clear()


def _fast_engine(retry_times=3):
    from bigdl_tpu.runtime.engine import EngineConfig, init_engine

    init_engine(EngineConfig(failure_retry_times=retry_times,
                             failure_retry_interval_s=0.01,
                             failure_policy=FailurePolicy(
                                 max_restarts=max(retry_times, 2),
                                 by_cause={c: RetryPolicy(
                                     max_retries=max(retry_times, 2),
                                     base_s=0.0, jitter=0.0)
                                     for c in FailureCause})))


def _coord(directory, rank=0, clock=None, metrics=None, **kw):
    cfg = ClusterConfig(directory=str(directory), process_index=rank,
                        rendezvous_timeout_s=kw.pop("timeout", 10.0),
                        rendezvous_poll_s=0.01, **kw)
    if clock is not None:
        cfg.clock = clock
    return ClusterCoordinator(cfg, metrics=metrics)


def _linreg_optimizer(ckpt_dir, n_iters, cluster_dir=None, seed=3,
                      steps_per_call=None, ckpt_every=2):
    from bigdl_tpu import nn, optim
    from bigdl_tpu.data.dataset import ArrayDataSet

    rs = np.random.RandomState(0)
    x = rs.rand(64, 4).astype(np.float32)
    y = x @ np.asarray([[1.0], [2.0], [-1.0], [0.5]], np.float32)
    opt = (optim.Optimizer(nn.Linear(4, 1), ArrayDataSet(x, y),
                           nn.MSECriterion(), batch_size=16, seed=seed)
           .set_optim_method(optim.SGD(learning_rate=0.2))
           .set_end_when(optim.Trigger.max_iteration(n_iters)))
    opt.set_checkpoint(str(ckpt_dir), optim.Trigger.several_iteration(
        ckpt_every))
    if steps_per_call:
        opt.steps_per_call = steps_per_call
    opt.log_every = 100
    if cluster_dir is not None:
        coord = _coord(cluster_dir, metrics=opt.metrics)
        coord.start()
        opt.set_cluster(coord)
    return opt


# ---------------------------------------------------------------------------
# membership board + views


def test_view_board_highest_epoch_wins(tmp_path):
    board = MembershipBoard(str(tmp_path))
    assert board.current() is None
    board.publish(MembershipView(epoch=1, members=(0, 1), leader=0))
    board.publish(MembershipView(epoch=3, members=(0,), leader=0,
                                 reason="host_loss"))
    board.publish(MembershipView(epoch=2, members=(0, 1), leader=0))
    v = board.current()
    assert v.epoch == 3 and v.members == (0,) and v.reason == "host_loss"


def test_abort_and_preempt_flags_are_epoch_scoped(tmp_path):
    board = MembershipBoard(str(tmp_path))
    board.post_abort(4, rank=1, reason="collective timeout", step=17)
    assert board.abort_posted(4)["rank"] == 1
    assert board.abort_posted(5) is None  # the next epoch is clean
    # first abort wins: a second poster must not overwrite the cause
    board.post_abort(4, rank=0, reason="me too")
    assert board.abort_posted(4)["reason"] == "collective timeout"
    board.post_preempt(4, rank=2)
    assert board.preempt_posted(4) == [2]
    assert board.preempt_posted(5) == []
    board.ack(6, 0)
    board.ack(6, 1)
    assert board.acks(6) == [0, 1]


def test_leader_failover_and_rejoin(tmp_path):
    """The lowest LIVE rank leads: when rank 0 stops beating, rank 1's
    sweep suspects it and publishes the shrink view with itself as
    leader; when rank 0 beats again the view heals with leader 0."""
    now = [100.0]
    clock = lambda: now[0]  # noqa: E731
    c0 = _coord(tmp_path, rank=0, clock=clock)
    c1 = _coord(tmp_path, rank=1, clock=clock)
    c0.start()
    c1.start()
    for _ in range(5):  # build beat history at 1s cadence
        now[0] += 1.0
        c0.sweep()
        c1.sweep()
    v = c1.view
    assert v.members == (0, 1) and v.leader == 0
    epoch0 = v.epoch

    now[0] += 300.0      # rank 0 goes silent
    v = c1.sweep()
    assert v.members == (1,) and v.leader == 1
    assert v.epoch > epoch0 and v.reason == "host_loss"
    assert c1.metrics.counter("cluster.peers_suspected_total") >= 1

    v2 = c0.sweep()      # rank 0 comes back: beats, reclaims leadership
    assert v2.members == (0, 1) and v2.leader == 0
    assert v2.epoch > v.epoch and v2.reason == "rejoin"


def test_partition_blinds_sweep_then_heals(tmp_path):
    """``cluster_partition``: while the spec fires, a sweep sees no peer
    heartbeats (live = self); when max_fires is exhausted the partition
    heals and the full membership is republished."""
    now = [100.0]
    clock = lambda: now[0]  # noqa: E731
    c0 = _coord(tmp_path, rank=0, clock=clock)
    hb1 = Heartbeat(str(tmp_path), process_index=1, clock=clock)
    hb1.beat()
    c0.start()
    v = c0.sweep()
    assert v.members == (0, 1)
    full_epoch = v.epoch

    faults.install([FaultSpec("cluster_partition", every=1, max_fires=2)])
    v = c0.sweep()
    assert v.members == (0,) and v.epoch > full_epoch
    assert v.reason == "host_loss"
    v = c0.sweep()  # still partitioned: view unchanged, no thrash
    assert v.members == (0,)
    hb1.beat()
    healed = c0.sweep()  # fault exhausted: the peer is visible again
    assert healed.members == (0, 1) and healed.reason == "rejoin"
    assert c0.metrics.counter("cluster.peers_suspected_total") >= 1


def test_suspicion_posts_gang_abort_and_unwinds_poster(tmp_path):
    """Heartbeat-detected peer death posts the gang abort (survivors
    wedged in a collective have no local exception to unwind them), and
    the POSTING process's own next bundle edge raises too — then
    recovers onto the shrink view."""
    now = [100.0]
    clock = lambda: now[0]  # noqa: E731
    c0 = _coord(tmp_path, rank=0, clock=clock)
    c1 = _coord(tmp_path, rank=1, clock=clock)
    c0.start()
    c1.start()
    for _ in range(5):
        now[0] += 1.0
        c0.sweep()
        c1.sweep()
    assert c1.view.members == (0, 1)
    epoch0 = c1.view.epoch

    now[0] += 300.0              # rank 0 dies mid-collective
    c1.sweep()
    assert c1.board.abort_posted(epoch0) is not None  # the wedge breaker
    with pytest.raises(GangAbortedError):
        c1.on_step(9)            # the poster's own edge unwinds as well
    view = c1.gang_recover("host loss")
    assert view.members == (1,) and view.epoch > epoch0
    c1.on_step(10)               # the recovered epoch is clean


def test_suspicion_abort_lands_under_freshest_view_epoch(tmp_path):
    """The suspicion abort is posted at the epoch of the view the sweep
    just READ from the board — which may be newer than the
    coordinator's own — so the guard, the flag, and the poster's
    self-unwind marker all agree on one epoch."""
    now = [100.0]
    clock = lambda: now[0]  # noqa: E731
    c0 = _coord(tmp_path, rank=0, clock=clock)
    c1 = _coord(tmp_path, rank=1, clock=clock)
    c0.start()
    c1.start()
    for _ in range(5):
        now[0] += 1.0
        c0.sweep()
        c1.sweep()
    assert c0.view.members == (0, 1)
    # a fresh epoch lands on the board that c1 has NOT adopted yet
    v = c0.sweep(force_publish=True)
    assert v.epoch > c1.view.epoch
    now[0] += 300.0              # rank 0 dies before c1 sweeps again
    c1.sweep()
    assert c1.board.abort_posted(v.epoch) is not None
    with pytest.raises(GangAbortedError):
        c1.on_step(5)


def test_restart_never_reaborts_on_stale_flag(tmp_path):
    """A restarted gang must not re-abort on the previous incarnation's
    abort flag: the leader's start bump retires the old epoch, and the
    restarted members' edge probes scan only from their JOINED epoch."""
    now = [100.0]
    clock = lambda: now[0]  # noqa: E731
    c0 = _coord(tmp_path, rank=0, clock=clock)
    c1 = _coord(tmp_path, rank=1, clock=clock)
    c0.start()
    c1.start()
    for _ in range(5):
        now[0] += 1.0
        c0.sweep()
        c1.sweep()
    epoch0 = c0.view.epoch
    c0.abort("collective timeout", step=3)

    # the whole gang restarts (fresh coordinators over the same board)
    c0b = _coord(tmp_path, rank=0, clock=clock)
    c0b.start()                  # leader start: epoch bump retires flags
    c1b = _coord(tmp_path, rank=1, clock=clock)
    c1b.start()
    assert c0b.view.epoch > epoch0
    c0b.on_step(4)
    c1b.on_step(4)               # stale abort-<epoch0> must not re-fire


def test_abort_probe_covers_epochs_back_to_joined(tmp_path):
    """A view published between two bundle edges must not hide the
    abort: the flag lands under the epoch the member was TRAINING in,
    and its edge probe walks [joined, current] even after a sweep
    adopted a newer view."""
    c0 = _coord(tmp_path, rank=0)
    c1 = _coord(tmp_path, rank=1)
    c0.start()
    c1.start()
    c0.sweep()
    c1.sweep()
    joined = c1.view.epoch
    c0.abort("collective timeout", step=3)   # posted under `joined`
    # the leader's recovery view lands BEFORE c1's next edge, and c1's
    # background sweep adopts it
    v = c0.sweep()
    assert v.epoch > joined
    c1.sweep()
    assert c1.view.epoch == v.epoch
    with pytest.raises(GangAbortedError) as ei:
        c1.on_step(4)
    assert ei.value.epoch == joined
    # recovery rendezvouses on the ALREADY-published post-abort view
    # instead of waiting for yet another epoch
    import threading

    got = {}
    t = threading.Thread(
        target=lambda: got.setdefault("v", c1.gang_recover("late")))
    t.start()
    c0.rendezvous(v)
    t.join(timeout=10)
    assert not t.is_alive()
    assert got["v"].epoch == v.epoch
    c1.on_step(5)                # joined the new epoch: flag retired


def test_edge_probe_is_rate_limited(tmp_path):
    """K=1 training must not pay a board read per step: between probe
    windows on_step serves from the sweep-refreshed cache."""
    now = [100.0]
    clock = lambda: now[0]  # noqa: E731
    c0 = _coord(tmp_path, rank=0, clock=clock)
    c0.start()
    calls = {"n": 0}
    real = c0.board.abort_posted

    def counted(epoch):
        calls["n"] += 1
        return real(epoch)

    c0.board.abort_posted = counted
    c0.on_step(1)
    first = calls["n"]
    assert first > 0
    for s in range(2, 12):       # same second: all served from cache
        c0.on_step(s)
    assert calls["n"] == first
    now[0] += 2.0                # window elapsed: exactly one more probe
    c0.on_step(12)
    assert calls["n"] > first


def test_gang_abort_raises_at_peer_step_edge_and_recovers(tmp_path):
    """A survivor posting the abort flag makes every OTHER member's next
    bundle edge raise GangAbortedError (classified host_lost); both then
    rendezvous on the post-abort view together."""
    import threading

    c0 = _coord(tmp_path, rank=0)
    c1 = _coord(tmp_path, rank=1)
    c0.start()
    c1.start()
    c0.sweep()
    c1.sweep()
    v = c0.sweep()
    assert v.members == (0, 1)

    c1.abort("peer collective timeout", step=7)
    with pytest.raises(GangAbortedError) as ei:
        c0.on_step(8)
    assert classify(ei.value) is FailureCause.HOST_LOST
    assert ei.value.source_rank == 1
    c1.on_step(8)  # the poster's own flag never re-raises on itself
    t = threading.Thread(target=c1.gang_recover, args=("test",))
    t.start()
    view = c0.gang_recover("test")
    t.join(timeout=10)
    assert not t.is_alive()
    assert view.epoch > v.epoch
    assert set(view.members) == {0, 1}
    # the new epoch carries no stale abort: steps run again
    c0.on_step(9)
    c1.on_step(9)


def test_preemption_notice_propagates_to_peers(tmp_path):
    c0 = _coord(tmp_path, rank=0)
    c1 = _coord(tmp_path, rank=1)
    c0.start()
    c1.start()
    c0.sweep()
    c1.sweep()
    c0.sweep()
    c1.notify_preemption(source="signal")
    assert c1.preempt_pending
    c0.sweep()
    assert c0.preempt_pending  # the un-signalled host checkpoints too
    assert c1.metrics.counter("cluster.preempt_notices_total") >= 1


# ---------------------------------------------------------------------------
# peer-shard store


def test_peer_store_completeness_and_gc(tmp_path):
    store = PeerShardStore(str(tmp_path), keep=2)
    sh = {"m@offset": np.asarray(0, np.int64),
          "m": np.arange(4, dtype=np.float32)}
    # step 2: only rank 0 of 2 published — NOT complete (rank 1 died)
    store.publish(0, 2, sh, ranks=2, params=np.ones(3, np.float32))
    assert store.latest_complete_step() is None
    # step 4: both ranks published, params present — complete
    for r in range(2):
        store.publish(r, 4, {"m@offset": np.asarray(4 * r, np.int64),
                             "m": np.full(4, float(r), np.float32)},
                      ranks=2,
                      params=np.ones(3, np.float32) if r == 0 else None,
                      driver_state={"iteration": 4} if r == 0 else None)
    assert store.latest_complete_step() == 4
    got = store.fetch(4)
    assert len(got["payloads"]) == 2
    assert got["driver_state"]["iteration"] == 4
    np.testing.assert_array_equal(got["params"], np.ones(3, np.float32))
    # merge: each rank's slice lands at its offset
    from bigdl_tpu.optim.checkpoint import merge_flat_shards

    merged = merge_flat_shards(got["payloads"],
                               {"m": np.zeros(8, np.float32)})
    np.testing.assert_array_equal(merged["m"],
                                  np.r_[np.zeros(4), np.ones(4)])
    # gc: publishing more complete steps evicts the oldest
    for step in (6, 8):
        for r in range(2):
            store.publish(r, step, sh, ranks=2,
                          params=np.ones(3, np.float32) if r == 0 else None)
    assert store.complete_steps() == [6, 8]
    with pytest.raises(ValueError):
        store.fetch(4)


def test_peer_restore_bit_identical_to_checkpoint_restore(tmp_path):
    """The acceptance parity spec: restoring step N from the peer store
    yields byte-for-byte the state a checkpoint restore of step N yields
    — params, optimizer state, model state, and driver step."""
    from bigdl_tpu.optim import checkpoint as ckpt

    _fast_engine()
    faults.clear()
    opt = _linreg_optimizer(tmp_path / "ck", 4,
                            cluster_dir=tmp_path / "cl")
    trained = opt.optimize()
    eng = trained._engine

    latest = ckpt.latest_checkpoint(str(tmp_path / "ck"))
    assert latest is not None and latest.endswith("ckpt-4")
    c_flat, c_opt, c_ms, c_driver, c_ema = ckpt.load_checkpoint(
        latest, opt_state_template=eng.opt_template,
        model_state_template=eng.model_state_template)

    assert opt.cluster.store.latest_complete_step() == 4
    p_flat, p_opt, p_ms, p_driver, p_ema = opt.cluster.load_peer_state(
        4, eng.opt_template, eng.model_state_template)

    np.testing.assert_array_equal(np.asarray(c_flat), np.asarray(p_flat))
    for a, b in zip(np.asarray(c_ema) if c_ema is not None else [],
                    np.asarray(p_ema) if p_ema is not None else []):
        np.testing.assert_array_equal(a, b)
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(c_opt),
                    jax.tree_util.tree_leaves(p_opt)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree_util.tree_leaves(c_ms),
                    jax.tree_util.tree_leaves(p_ms)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for key in ("iteration", "epoch", "epoch_batch"):
        assert c_driver[key] == p_driver[key]


# ---------------------------------------------------------------------------
# chaos acceptance: gang recovery end to end


def test_host_loss_recovers_to_fault_free_trajectory(tmp_path):
    """Injected ``cluster_host_loss`` mid-run: the gang aborts, bumps the
    membership epoch, restores from the PEER store, and finishes with
    weights bit-identical to the fault-free run; MTTR and ``cluster.*``
    counters land in /metrics and the flight recorder."""
    _fast_engine()
    faults.clear()
    opt_a = _linreg_optimizer(tmp_path / "ck_a", 8)
    trained_a = opt_a.optimize()

    inj = faults.install([FaultSpec("cluster_host_loss", at_step=5)])
    opt_b = _linreg_optimizer(tmp_path / "ck_b", 8,
                              cluster_dir=tmp_path / "cl_b")
    trained_b = opt_b.optimize()

    assert [p for p, _, _ in inj.events] == ["cluster_host_loss"]
    assert opt_b.final_state["iteration"] == 8
    wa = np.asarray(trained_a.variables["params"]["weight"])
    wb = np.asarray(trained_b.variables["params"]["weight"])
    np.testing.assert_array_equal(wa, wb)

    m = opt_b.metrics
    assert m.counter("cluster.recoveries_total") == 1
    assert m.counter("cluster.recovery_by_path.peer_shard") == 1
    assert m.counter("cluster.recovery_bytes_total") > 0
    assert m.counter("cluster.aborts_total") == 1
    assert m.summary()["cluster.mttr_s.count"] == 1
    assert m.counter("recoveries_total") == 1  # the classic counter too
    assert m.counter("retries_by_cause.host_lost") == 1
    # membership: the recovery bumped the view epoch past the start view
    assert opt_b.cluster.view.epoch >= 2

    from bigdl_tpu.obs.export import render_prometheus

    text = render_prometheus(m)
    assert "cluster_recoveries_total 1.0" in text
    assert "cluster_mttr_s_count 1" in text
    assert any(line.startswith("cluster_recovery_bytes_total")
               for line in text.splitlines())

    from bigdl_tpu.obs import flight

    kinds = [e["kind"] for e in flight.global_recorder().snapshot()]
    for expected in ("cluster_abort", "cluster_view", "cluster_rendezvous",
                     "cluster_restore", "cluster_recover",
                     "cluster_publish"):
        assert expected in kinds, expected


def test_host_loss_falls_back_to_checkpoint_when_no_peer_state(tmp_path):
    """Recovery ladder rung 2: with the peer store emptied (no buddy
    holds the shard), restore comes from the newest shard-complete
    checkpoint and is still exact."""
    _fast_engine()
    faults.clear()
    opt_a = _linreg_optimizer(tmp_path / "ck_a", 8)
    trained_a = opt_a.optimize()

    faults.install([FaultSpec("cluster_host_loss", at_step=5)])
    opt_b = _linreg_optimizer(tmp_path / "ck_b", 8,
                              cluster_dir=tmp_path / "cl_b")
    # sabotage the peer store mid-run: drop every publish before the fault
    real_publish = opt_b.cluster.publish_state
    opt_b.cluster.publish_state = lambda *a, **k: 0
    trained_b = opt_b.optimize()
    opt_b.cluster.publish_state = real_publish

    np.testing.assert_array_equal(
        np.asarray(trained_a.variables["params"]["weight"]),
        np.asarray(trained_b.variables["params"]["weight"]))
    m = opt_b.metrics
    assert m.counter("cluster.recovery_by_path.checkpoint") == 1
    assert m.counter("cluster.recovery_by_path.peer_shard") == 0


def test_supervisor_gang_recovers_with_cluster_dir(tmp_path):
    """FailurePolicy.cluster_dir: the Supervisor builds the coordinator,
    and a failure that escapes optimize() goes through gang recovery
    (abort → new view → rendezvous) before re-entering."""
    from bigdl_tpu.resilience.supervisor import Supervisor

    _fast_engine(retry_times=0)
    faults.install([FaultSpec("step_fail", at_step=5)])
    opt = _linreg_optimizer(tmp_path / "ck", 8)
    policy = FailurePolicy(
        max_restarts=2, cluster_dir=str(tmp_path / "cl"),
        by_cause={FailureCause.STEP_FAILURE: RetryPolicy(
            max_retries=2, base_s=0.0, jitter=0.0)})
    sup = Supervisor(opt, policy=policy, sleep=lambda s: None)
    trained = sup.run()
    assert trained is not None
    assert opt.final_state["iteration"] == 8
    assert sup.restarts_total == 1
    assert opt.cluster is None  # supervisor-owned coordinator detached
    assert opt.metrics.counter("cluster.aborts_total") == 1
    assert opt.metrics.counter("cluster.recoveries_total") == 1
    board = MembershipBoard(str(tmp_path / "cl"))
    assert board.current().epoch >= 2  # start view + abort-recovery view


def test_cluster_preempt_notice_stops_with_checkpoint_and_resumes_exact(
        tmp_path):
    """``cluster_preempt_notice`` at a bundle edge acts as a received
    cluster-wide preemption: the run checkpoints just-in-time and stops;
    a restart resumes step-exact to the uninterrupted trajectory."""
    _fast_engine()
    faults.clear()
    ref = _linreg_optimizer(tmp_path / "ck_ref", 8)
    trained_ref = ref.optimize()

    faults.install([FaultSpec("cluster_preempt_notice", at_step=3)])
    opt1 = _linreg_optimizer(tmp_path / "ck", 8,
                             cluster_dir=tmp_path / "cl")
    opt1.optimize()
    stopped_at = opt1.final_state["iteration"]
    assert stopped_at < 8  # preempted mid-run...
    assert opt1.metrics.counter("cluster.preempt_notices_total") >= 1
    from bigdl_tpu.optim import checkpoint as ckpt

    latest = ckpt.latest_checkpoint(str(tmp_path / "ck"))
    assert latest is not None
    assert latest.endswith(f"ckpt-{stopped_at}")  # just-in-time landed

    faults.clear()
    opt2 = _linreg_optimizer(tmp_path / "ck", 8,
                             cluster_dir=tmp_path / "cl")
    trained2 = opt2.optimize()
    assert opt2.final_state["iteration"] == 8
    np.testing.assert_array_equal(
        np.asarray(trained_ref.variables["params"]["weight"]),
        np.asarray(trained2.variables["params"]["weight"]))


# ---------------------------------------------------------------------------
# elastic re-sharded mid-epoch resume (plan level)


@pytest.mark.parametrize("old_pc,new_pc,trained", [
    (2, 1, 1), (1, 4, 2), (4, 2, 1), (2, 4, 2)])
def test_resharded_plan_covers_each_remaining_example_once(
        old_pc, new_pc, trained):
    from bigdl_tpu.data.dataset import (batch_index_plan,
                                        resharded_batch_index_plan)

    n, bs = 48, 16
    done = set()
    for p in range(old_pc):
        for b, (sel, n_real) in enumerate(batch_index_plan(
                n, bs, seed=3, epoch=1, process_id=p,
                process_count=old_pc)):
            if b >= trained:
                break
            done.update(sel[:n_real].tolist())
    assert len(done) == trained * bs
    rem = []
    for p in range(new_pc):
        for sel, n_real in resharded_batch_index_plan(
                n, bs, trained_batches=trained, old_process_count=old_pc,
                seed=3, epoch=1, process_id=p, process_count=new_pc):
            rem.extend(sel[:n_real].tolist())
    assert len(rem) == len(set(rem))        # nothing trained twice
    assert not (done & set(rem))            # nothing replayed
    assert done | set(rem) == set(range(n))  # nothing lost


# ---------------------------------------------------------------------------
# storage mirror (satellite): bounded retry, accounted


def test_mirror_tree_retries_upload_and_accounts(tmp_path):
    from bigdl_tpu.optim.metrics import Metrics
    from bigdl_tpu.utils import storage

    src = tmp_path / "src"
    src.mkdir()
    (src / "a.bin").write_bytes(b"payload")
    (src / "manifest.json").write_text("{}")
    faults.install([FaultSpec("storage_io_fail", every=1, max_fires=1)])
    m = Metrics()
    n = storage.mirror_tree(str(src), str(tmp_path / "dst"), metrics=m,
                            sleep=lambda s: None)
    assert n == len(b"payload") + 2
    assert (tmp_path / "dst" / "a.bin").read_bytes() == b"payload"
    assert m.counter("retries_by_cause.transient_storage") == 1

    # retries exhausted -> raises (the caller decides severity)
    faults.install([FaultSpec("storage_io_fail", every=1, max_fires=50)])
    with pytest.raises(Exception):
        storage.mirror_tree(str(src), str(tmp_path / "dst2"), metrics=m,
                            sleep=lambda s: None)


def test_checkpoint_mirror_produces_restorable_copy(tmp_path):
    from bigdl_tpu import nn, optim
    from bigdl_tpu.data.dataset import ArrayDataSet
    from bigdl_tpu.optim import checkpoint as ckpt

    _fast_engine()
    faults.clear()
    rs = np.random.RandomState(0)
    x = rs.rand(32, 4).astype(np.float32)
    y = x.sum(1, keepdims=True).astype(np.float32)
    opt = (optim.Optimizer(nn.Linear(4, 1), ArrayDataSet(x, y),
                           nn.MSECriterion(), batch_size=16, seed=1)
           .set_optim_method(optim.SGD(learning_rate=0.1))
           .set_end_when(optim.Trigger.max_iteration(4)))
    opt.set_checkpoint(str(tmp_path / "primary"),
                       optim.Trigger.several_iteration(2),
                       mirror=str(tmp_path / "mirror"))
    opt.log_every = 100
    opt.optimize()

    primary = ckpt.latest_checkpoint(str(tmp_path / "primary"))
    mirrored = ckpt.latest_checkpoint(str(tmp_path / "mirror"))
    assert primary is not None and mirrored is not None
    assert os.path.basename(primary) == os.path.basename(mirrored)
    a = json.load(open(os.path.join(primary, "manifest.json")))
    b = json.load(open(os.path.join(mirrored, "manifest.json")))
    assert a == b


def test_checkpoint_mirror_is_garbage_collected(tmp_path):
    """The mirror root is bounded like the primary: a long
    frequent-checkpoint run must not accumulate every checkpoint ever
    taken in the remote bucket."""
    from bigdl_tpu import nn, optim
    from bigdl_tpu.data.dataset import ArrayDataSet

    _fast_engine()
    faults.clear()
    rs = np.random.RandomState(0)
    x = rs.rand(32, 4).astype(np.float32)
    y = x.sum(1, keepdims=True).astype(np.float32)
    opt = (optim.Optimizer(nn.Linear(4, 1), ArrayDataSet(x, y),
                           nn.MSECriterion(), batch_size=16, seed=1)
           .set_optim_method(optim.SGD(learning_rate=0.1))
           .set_end_when(optim.Trigger.max_iteration(10)))
    opt.set_checkpoint(str(tmp_path / "primary"),
                       optim.Trigger.several_iteration(1),
                       mirror=str(tmp_path / "mirror"))
    opt.log_every = 100
    opt.optimize()

    def ckpts(d):
        return sorted(n for n in os.listdir(str(tmp_path / d))
                      if n.startswith("ckpt-"))

    assert len(ckpts("primary")) <= 3  # save_checkpoint keep_last default
    assert ckpts("mirror") == ckpts("primary")


# ---------------------------------------------------------------------------
# sentinel family (satellite): CLUSTER_r*.json gates like latencies


def test_sentinel_gates_cluster_recovery_families(tmp_path):
    from bigdl_tpu.obs import sentinel

    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"metric": "resnet_img_per_sec", "value": 100.0}))
    (tmp_path / "CLUSTER_r01.json").write_text(json.dumps(
        {"mttr_s": 2.0, "recovery_bytes": 1e6}))
    history = sentinel.load_history(str(tmp_path))
    assert "cluster_mttr_s" in history
    assert history["cluster_mttr_s"][0].direction == sentinel.LOWER
    # 50% slower recovery regresses; a faster one passes
    bad = sentinel.check({"mttr_s": 3.0, "recovery_bytes": 1e6}, history)
    assert any(v.family == "cluster_mttr_s" and v.regressed for v in bad)
    ok = sentinel.check({"mttr_s": 1.5, "recovery_bytes": 9e5}, history)
    assert all(not v.regressed for v in ok)


# ---------------------------------------------------------------------------
# true multi-process membership drill (slow: real processes, real clocks)


@pytest.mark.slow
def test_two_process_kill_and_rejoin_membership(tmp_path):
    """A REAL second process beats into the control dir; kill -9 takes it
    out (the leader publishes the shrink view), a relaunch rejoins (the
    leader publishes the grow view).  No jax collectives involved — this
    drills exactly the membership/failover layer."""
    import subprocess
    import sys
    import time as _time

    beater = ("import sys, time\n"
              "from bigdl_tpu.resilience.detector import Heartbeat\n"
              "hb = Heartbeat(sys.argv[1], process_index=1, "
              "interval_s=0.05)\n"
              "hb.start()\n"
              "time.sleep(60)\n")

    def wait_for(pred, timeout=30.0):
        deadline = _time.time() + timeout
        while _time.time() < deadline:
            if pred():
                return True
            _time.sleep(0.05)
        return False

    c0 = _coord(tmp_path, rank=0, heartbeat_interval_s=0.05,
                phi_threshold=3.0)
    c0.start()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen([sys.executable, "-c", beater, str(tmp_path)],
                         env=env)
    try:
        assert wait_for(lambda: c0.sweep() is not None
                        and c0.view.members == (0, 1)), "peer never joined"
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=10)
        assert wait_for(lambda: c0.sweep() is not None
                        and c0.view.members == (0,)), \
            "dead peer never suspected"
        p = subprocess.Popen([sys.executable, "-c", beater, str(tmp_path)],
                             env=env)
        assert wait_for(lambda: c0.sweep() is not None
                        and c0.view.members == (0, 1)), \
            "restarted peer never rejoined"
    finally:
        p.kill()
        p.wait(timeout=10)
