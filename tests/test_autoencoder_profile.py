"""Autoencoder zoo model + profiler integration."""

import glob
import os

import jax
import numpy as np

from bigdl_tpu.models.autoencoder import Encoder, autoencoder


class TestAutoencoder:
    def test_reconstruction_trains(self):
        from bigdl_tpu.data.dataset import DataSet
        from bigdl_tpu.nn.criterion import MSECriterion
        from bigdl_tpu.optim.optim_method import Adam
        from bigdl_tpu.optim.optimizer import Optimizer
        from bigdl_tpu.optim.trigger import Trigger

        rng = np.random.RandomState(0)
        # low-rank data: 64-dim inputs spanning a 4-d subspace
        basis = rng.randn(4, 64).astype(np.float32)
        x = (rng.randn(256, 4).astype(np.float32) @ basis)
        x = 1.0 / (1.0 + np.exp(-x))  # squash into (0,1) for sigmoid output

        model = autoencoder(input_dim=64, hidden=(32, 8))
        opt = Optimizer(model, DataSet.array(x, x), MSECriterion(),
                        batch_size=64)
        opt.set_optim_method(Adam(learning_rate=3e-3))
        opt.set_end_when(Trigger.max_epoch(30))
        trained = opt.optimize()
        recon = np.asarray(trained.predict(x[:64]))
        mse = float(np.mean((recon - x[:64]) ** 2))
        var = float(np.var(x[:64]))
        assert mse < 0.5 * var, (mse, var)

    def test_encoder_slice(self):
        model = autoencoder(input_dim=32, hidden=(16, 4))
        x = np.random.RandomState(1).rand(3, 32).astype(np.float32)
        variables = model.init(jax.random.PRNGKey(0), x)
        enc = Encoder(model, n_hidden_layers=2)
        ev = enc.encoder_variables(variables)
        z, _ = enc.apply(ev, x)
        assert z.shape == (3, 4)


class TestProfiler:
    def test_iteration_profiler_window(self, tmp_path):
        from bigdl_tpu.utils.profiling import IterationProfiler

        p = IterationProfiler(str(tmp_path), start_iter=2, num_iters=2)
        for it in range(6):
            p.step(it)
        p.close()
        assert p.done
        # jax profiler writes a plugins/profile dir with trace files
        found = glob.glob(os.path.join(str(tmp_path), "**", "*"),
                          recursive=True)
        assert found, "no trace output written"

    def test_optimizer_set_profile(self, tmp_path):
        from bigdl_tpu.data.dataset import DataSet
        from bigdl_tpu.nn.criterion import MSECriterion
        from bigdl_tpu.nn.layers import Linear
        from bigdl_tpu.nn.module import Sequential
        from bigdl_tpu.optim.optimizer import Optimizer
        from bigdl_tpu.optim.trigger import Trigger

        rng = np.random.RandomState(0)
        x = rng.randn(64, 8).astype(np.float32)
        y = rng.randn(64, 1).astype(np.float32)
        opt = (Optimizer(Sequential([Linear(8, 1)]), DataSet.array(x, y),
                         MSECriterion(), batch_size=32)
               .set_end_when(Trigger.max_epoch(4))
               .set_profile(str(tmp_path), start_iter=2, num_iters=2))
        opt.optimize()
        files = glob.glob(os.path.join(str(tmp_path), "**", "*"),
                          recursive=True)
        assert files, "profiler produced no output"
