"""Long-tail layer tranche specs (reference per-layer *Spec pattern)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn

RNG = jax.random.PRNGKey(0)
RS = np.random.RandomState(0)


def _run(layer, *xs, training=False):
    v = layer.init(RNG, *map(jnp.asarray, xs))
    y, _ = layer.forward(v["params"], v["state"], *map(jnp.asarray, xs),
                         training=training, rng=jax.random.PRNGKey(1))
    return y, v


def test_activity_regularization_grad_carries_penalty():
    layer = nn.ActivityRegularization(l1=0.3, l2=0.1)
    x = jnp.asarray(RS.randn(4, 5).astype(np.float32))

    def loss(x):
        y, _ = layer.forward({}, {}, x, training=True)
        return jnp.sum(y * 2.0)

    g = jax.grad(loss)(x)
    expect = 2.0 + 0.3 * np.sign(np.asarray(x)) + 2 * 0.1 * np.asarray(x)
    np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-5)
    # inference: pure identity
    y, _ = layer.forward({}, {}, x, training=False)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


def test_binary_threshold():
    y, _ = _run(nn.BinaryThreshold(0.5), np.array([[0.2, 0.7, 0.5, 1.0]],
                                                  np.float32))
    np.testing.assert_array_equal(np.asarray(y), [[0, 1, 0, 1]])


def test_masked_select_compacts_to_front():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    mask = np.array([[1, 0, 1], [0, 0, 1]], bool)
    layer = nn.MaskedSelect()
    (vals, valid), _ = layer.forward({}, {}, (jnp.asarray(x),
                                              jnp.asarray(mask)))
    np.testing.assert_array_equal(np.asarray(vals)[:3], [0.0, 2.0, 5.0])
    assert np.asarray(valid).sum() == 3
    assert not np.asarray(valid)[3:].any()
    np.testing.assert_array_equal(np.asarray(vals)[3:], 0.0)


def test_cross_product_pairwise_dots():
    a = RS.randn(3, 4).astype(np.float32)
    b = RS.randn(3, 4).astype(np.float32)
    c = RS.randn(3, 4).astype(np.float32)
    layer = nn.CrossProduct()
    y, _ = layer.forward({}, {}, tuple(map(jnp.asarray, (a, b, c))))
    assert y.shape == (3, 3)
    np.testing.assert_allclose(np.asarray(y)[:, 0], (a * b).sum(-1),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(y)[:, 2], (b * c).sum(-1),
                               rtol=1e-5)


def test_dense_to_sparse_round_trip():
    x = np.array([[1.0, 0.0], [0.0, 3.0]], np.float32)
    layer = nn.DenseToSparse()
    sp, _ = layer.forward({}, {}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(sp.to_dense()), x)


def test_expand_size():
    y, _ = _run(nn.ExpandSize([3, -1]), np.ones((1, 4), np.float32))
    assert y.shape == (3, 4)


def test_spatial_zero_padding_pad_and_crop():
    x = RS.randn(1, 4, 4, 2).astype(np.float32)
    y, _ = _run(nn.SpatialZeroPadding(1, 2, 0, 1), x)
    assert y.shape == (1, 5, 7, 2)
    # negative pads crop
    y2, _ = _run(nn.SpatialZeroPadding(-1, -1, -1, -1), x)
    assert y2.shape == (1, 2, 2, 2)
    np.testing.assert_allclose(np.asarray(y2), x[:, 1:3, 1:3, :])


def test_group_norm_matches_torch():
    torch = pytest.importorskip("torch")
    c, g = 6, 3
    x = RS.randn(2, 4, 4, c).astype(np.float32)
    layer = nn.GroupNorm(g, c)
    y, v = _run(layer, x)
    tm = torch.nn.GroupNorm(g, c)
    with torch.no_grad():
        ty = tm(torch.tensor(x.transpose(0, 3, 1, 2)))
    np.testing.assert_allclose(np.asarray(y),
                               ty.numpy().transpose(0, 2, 3, 1), atol=1e-5)


def test_instance_norm_matches_torch():
    torch = pytest.importorskip("torch")
    c = 5
    x = RS.randn(2, 6, 6, c).astype(np.float32)
    y, _ = _run(nn.InstanceNorm2D(c), x)
    tm = torch.nn.InstanceNorm2d(c, affine=True)
    with torch.no_grad():
        ty = tm(torch.tensor(x.transpose(0, 3, 1, 2)))
    np.testing.assert_allclose(np.asarray(y),
                               ty.numpy().transpose(0, 2, 3, 1), atol=1e-5)


def test_spatial_convolution_map_respects_connectivity():
    # connect in0->out0 and in1->out1 only
    conn = [[0, 0], [1, 1]]
    layer = nn.SpatialConvolutionMap(conn, 3, 2, 2, padding=1)
    x = RS.randn(1, 5, 5, 2).astype(np.float32)
    y, v = _run(layer, x)
    assert y.shape == (1, 5, 5, 2)
    w = np.asarray(v["params"]["weight"])
    assert np.all(w[:, :, 0, 1] == 0) and np.all(w[:, :, 1, 0] == 0)
    assert np.any(w[:, :, 0, 0] != 0)
    # out0 must not depend on in1: perturb channel 1
    x2 = x.copy()
    x2[..., 1] += 1.0
    y2, _ = layer.forward(v["params"], v["state"], jnp.asarray(x2))
    np.testing.assert_allclose(np.asarray(y2)[..., 0], np.asarray(y)[..., 0],
                               atol=1e-6)


def test_binary_tree_lstm_root_state():
    b, d, h = 2, 4, 6
    # 3-node tree: slots 0,1 leaves; slot 2 = parent(0, 1)
    x = RS.randn(b, 3, d).astype(np.float32)
    children = np.array([[[-1, -1], [-1, -1], [0, 1]]] * b, np.int32)
    layer = nn.BinaryTreeLSTM(d, h)
    v = layer.init(RNG, jnp.asarray(x), jnp.asarray(children))
    y, _ = layer.forward(v["params"], v["state"], jnp.asarray(x),
                         jnp.asarray(children))
    assert y.shape == (b, 3, h)
    assert np.all(np.isfinite(np.asarray(y)))
    # root must depend on both leaves
    x2 = x.copy()
    x2[:, 0] += 1.0
    y2, _ = layer.forward(v["params"], v["state"], jnp.asarray(x2),
                          jnp.asarray(children))
    assert not np.allclose(np.asarray(y2)[:, 2], np.asarray(y)[:, 2])
    # grads flow end to end
    def loss(p):
        out, _ = layer.forward(p, v["state"], jnp.asarray(x),
                               jnp.asarray(children))
        return jnp.sum(out[:, 2] ** 2)
    g = jax.grad(loss)(v["params"])
    assert float(jnp.linalg.norm(g["w_leaf"])) > 0


def test_prior_box_count_and_bounds():
    pb = nn.PriorBox(min_size=30.0, max_size=60.0, aspect_ratios=(2.0,),
                     image_size=(300, 300), clip=True)
    x = jnp.zeros((1, 4, 4, 8))
    boxes, _ = pb.forward({}, {}, x)
    assert boxes.shape == (4 * 4 * pb.num_priors(), 4)
    b = np.asarray(boxes)
    assert b.min() >= 0.0 and b.max() <= 300.0
    assert np.all(b[:, 2] >= b[:, 0]) and np.all(b[:, 3] >= b[:, 1])


def test_proposal_layer_shapes():
    from bigdl_tpu.ops.detection import encode_boxes

    A = 50
    anchors = np.stack([
        RS.uniform(0, 40, A), RS.uniform(0, 40, A),
        RS.uniform(60, 100, A), RS.uniform(60, 100, A)], -1).astype(np.float32)
    gt = np.array([[10, 10, 50, 50]], np.float32).repeat(A, 0)
    deltas = np.asarray(encode_boxes(jnp.asarray(gt), jnp.asarray(anchors)))
    scores = RS.rand(A).astype(np.float32)
    prop = nn.Proposal(pre_nms_topk=32, post_nms_topk=8, nms_thresh=0.7,
                       image_size=(128, 128))
    (boxes, s), _ = prop.forward({}, {}, (jnp.asarray(scores),
                                          jnp.asarray(deltas),
                                          jnp.asarray(anchors)))
    assert boxes.shape == (8, 4) and s.shape == (8,)


def test_detection_output_ssd_decodes_obvious_box():
    P, C = 16, 4
    priors = np.stack([
        np.full(P, 10.0), np.full(P, 10.0),
        np.full(P, 50.0), np.full(P, 50.0)], -1).astype(np.float32)
    loc = np.zeros((1, P, 4), np.float32)   # deltas 0 -> boxes == priors
    conf = np.zeros((1, P, C), np.float32)
    conf[0, :, 2] = 5.0                     # class 2 wins everywhere
    layer = nn.DetectionOutputSSD(C, keep_topk=5)
    out, _ = layer.forward({}, {}, (jnp.asarray(loc), jnp.asarray(conf),
                                    jnp.asarray(priors)))
    assert out.shape == (1, 5, 6)
    row = np.asarray(out)[0, 0]
    assert row[0] == 2.0 and row[1] > 0.5
    np.testing.assert_allclose(row[2:], [10, 10, 50, 50], atol=1e-3)


def test_detection_output_frcnn_shapes():
    P, C = 12, 3
    rois = np.stack([
        RS.uniform(0, 30, P), RS.uniform(0, 30, P),
        RS.uniform(50, 90, P), RS.uniform(50, 90, P)], -1).astype(np.float32)
    logits = RS.randn(P, C).astype(np.float32)
    deltas = (RS.randn(P, C * 4) * 0.1).astype(np.float32)
    layer = nn.DetectionOutputFrcnn(C, keep_topk=6, image_size=(100, 100))
    out, _ = layer.forward({}, {}, (jnp.asarray(logits), jnp.asarray(deltas),
                                    jnp.asarray(rois)))
    assert out.shape == (6, 6)
    o = np.asarray(out)
    assert np.all(o[:, 2:] >= 0) and np.all(o[:, 2:] <= 100)


def test_sequence_beam_search_module():
    d, vocab = 8, 8
    cell = nn.LSTM(d, d, return_sequences=False)
    out_layer = nn.Linear(d, vocab)
    sbs = nn.SequenceBeamSearch(cell, out_layer, vocab_size=vocab,
                                bos_id=0, eos_id=1, beam_size=3, max_len=6)
    x = jnp.asarray(RS.randn(2, d).astype(np.float32))
    v = sbs.init(RNG, x)
    res, _ = sbs.forward(v["params"], v["state"], x)
    assert res.tokens.shape[0] == 2          # batch
    assert res.tokens.shape[1] == 3          # beams
    assert np.all(np.asarray(res.scores)[:, 0] >= np.asarray(res.scores)[:, 1])


def test_time_distributed_mask_criterion_ignores_padding():
    crit = nn.TimeDistributedMaskCriterion(nn.CrossEntropyCriterion(),
                                           padding_value=-1)
    logits = RS.randn(2, 4, 5).astype(np.float32)
    target = np.array([[1, 2, -1, -1], [0, 3, 4, -1]], np.int32)
    loss = crit(jnp.asarray(logits), jnp.asarray(target))
    # equals mean CE over the 5 valid steps only
    valid = [(0, 0, 1), (0, 1, 2), (1, 0, 0), (1, 1, 3), (1, 2, 4)]
    ce = nn.CrossEntropyCriterion()
    manual = np.mean([float(ce(jnp.asarray(logits[b, t][None]),
                               jnp.asarray(np.array([c], np.int32))))
                      for b, t, c in valid])
    np.testing.assert_allclose(float(loss), manual, rtol=1e-5)


def test_pg_criterion():
    probs = np.array([[0.2, 0.8], [0.6, 0.4]], np.float32)
    # action 1 with reward 2.0; action 0 with reward -1.0
    target = np.array([[0.0, 2.0], [-1.0, 0.0]], np.float32)
    crit = nn.PGCriterion()
    loss = float(crit(jnp.asarray(probs), jnp.asarray(target)))
    expect = -(2.0 * np.log(0.8) + (-1.0) * np.log(0.6))
    np.testing.assert_allclose(loss, expect, rtol=1e-5)
