"""Third layer tranche: table/structure ops, gradient-shaping layers, shrink
activations, ConvLSTM, transposed 3-D conv, local normalization.

Mirrors the reference's per-layer Spec + Torch-parity pattern (SURVEY.md §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn

RNG = jax.random.PRNGKey(0)


def _run(layer, *xs, training=False, rng=None):
    v = layer.init(RNG, *xs)
    y, _ = layer.apply(v, *xs, training=training, rng=rng)
    return v, y


# ---------------------------------------------------------------------------
# table / structure ops
# ---------------------------------------------------------------------------


def test_split_pack_roundtrip():
    x = np.random.RandomState(0).rand(3, 4, 5).astype(np.float32)
    _, parts = _run(nn.SplitTable(dim=1), x)
    assert len(parts) == 4 and parts[0].shape == (3, 5)
    _, packed = _run(nn.Pack(dim=1), parts)
    np.testing.assert_allclose(packed, x, rtol=1e-6)


def test_replicate_reverse():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    _, y = _run(nn.Replicate(4, dim=1), x)
    assert y.shape == (2, 4, 3)
    np.testing.assert_allclose(y[:, 0], x)
    _, r = _run(nn.Reverse(dim=1), x)
    np.testing.assert_allclose(r, x[:, ::-1])


def test_mixture_table_matches_manual():
    rs = np.random.RandomState(1)
    g = jax.nn.softmax(jnp.asarray(rs.rand(2, 3), jnp.float32), axis=-1)
    experts = tuple(jnp.asarray(rs.rand(2, 5), jnp.float32) for _ in range(3))
    _, y = _run(nn.MixtureTable(), g, *experts)
    want = sum(np.asarray(g)[:, i:i + 1] * np.asarray(experts[i])
               for i in range(3))
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-5)
    # stacked-tensor expert form
    _, y2 = _run(nn.MixtureTable(), g, jnp.stack(experts, axis=1))
    np.testing.assert_allclose(np.asarray(y2), want, rtol=1e-5)


def test_map_table_shares_params():
    x1 = np.random.RandomState(2).rand(4, 6).astype(np.float32)
    x2 = np.random.RandomState(3).rand(4, 6).astype(np.float32)
    m = nn.MapTable(nn.Linear(6, 2))
    v = m.init(RNG, x1, x2)
    (y1, y2), _ = m.apply(v, x1, x2)
    # same params applied to each element
    inner = nn.Linear(6, 2)
    k = m._key(0)
    y1_direct, _ = inner.forward(v["params"][k], {}, x1)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y1_direct),
                               rtol=1e-5)
    assert y2.shape == (4, 2)


def test_bottle_equals_flat_apply():
    x = np.random.RandomState(4).rand(2, 3, 6).astype(np.float32)
    m = nn.Bottle(nn.Linear(6, 4), n_input_dims=2)
    v = m.init(RNG, x)
    y, _ = m.apply(v, x)
    assert y.shape == (2, 3, 4)
    k = m._key(0)
    flat, _ = nn.Linear(6, 4).forward(v["params"][k], {}, x.reshape(6, 6))
    np.testing.assert_allclose(np.asarray(y).reshape(6, 4),
                               np.asarray(flat), rtol=1e-5)


def test_bottle_higher_rank_inner():
    # rank-4 inner module (Conv2D) on a 5-D (N,T,H,W,C) input: torch Bottle
    # semantics collapse (N,T) into the batch dim
    x = np.random.RandomState(5).rand(2, 3, 6, 6, 2).astype(np.float32)
    m = nn.Bottle(nn.Conv2D(2, 4, 3, padding="SAME"), n_input_dims=4)
    v = m.init(RNG, x)
    y, _ = m.apply(v, x)
    assert y.shape == (2, 3, 6, 6, 4)
    k = m._key(0)
    flat, _ = nn.Conv2D(2, 4, 3, padding="SAME").forward(
        v["params"][k], {}, x.reshape(6, 6, 6, 2))
    np.testing.assert_allclose(np.asarray(y).reshape(6, 6, 6, 4),
                               np.asarray(flat), rtol=1e-5)


def test_infer_reshape():
    x = np.zeros((2, 3, 4), np.float32)
    _, y = _run(nn.InferReshape((0, -1)), x)
    assert y.shape == (2, 12)
    _, y2 = _run(nn.InferReshape((-1,), batch_mode=True), x)
    assert y2.shape == (2, 12)


# ---------------------------------------------------------------------------
# gradient-shaping
# ---------------------------------------------------------------------------


def test_gradient_reversal():
    layer = nn.GradientReversal(lam=0.7)
    v = layer.init(RNG, np.zeros((3,), np.float32))

    def f(x):
        y, _ = layer.apply(v, x)
        return jnp.sum(y ** 2)

    x = jnp.asarray([1.0, -2.0, 3.0])
    g = jax.grad(f)(x)
    np.testing.assert_allclose(np.asarray(g), -0.7 * 2 * np.asarray(x),
                               rtol=1e-6)


def test_l1_penalty_grad():
    layer = nn.L1Penalty(l1weight=0.1)
    v = layer.init(RNG, np.zeros((3,), np.float32))

    def f(x):
        y, _ = layer.apply(v, x, training=True)
        return jnp.sum(y)

    x = jnp.asarray([1.0, -2.0, 3.0])
    g = jax.grad(f)(x)
    np.testing.assert_allclose(np.asarray(g),
                               1.0 + 0.1 * np.sign(np.asarray(x)), rtol=1e-6)
    # eval mode: pure identity
    y, _ = layer.apply(v, x, training=False)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x))


# ---------------------------------------------------------------------------
# shrink activations — torch parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("ours,theirs", [
    (nn.HardShrink(0.5), "hardshrink"),
    (nn.SoftShrink(0.5), "softshrink"),
    (nn.TanhShrink(), "tanhshrink"),
    (nn.Mish(), "mish"),
])
def test_shrink_torch_parity(ours, theirs):
    torch = pytest.importorskip("torch")
    x = np.linspace(-2, 2, 41).astype(np.float32)
    _, y = _run(ours, x)
    want = getattr(torch.nn.functional, theirs)(torch.tensor(x)).numpy()
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-5, atol=1e-6)


def test_rrelu_train_eval():
    x = np.linspace(-3, 1, 64).astype(np.float32)
    layer = nn.RReLU()
    v = layer.init(RNG, x)
    y_eval, _ = layer.apply(v, x, training=False)
    mid = (1 / 8 + 1 / 3) / 2
    np.testing.assert_allclose(
        np.asarray(y_eval), np.where(x >= 0, x, mid * x), rtol=1e-5)
    y_tr, _ = layer.apply(v, x, training=True, rng=jax.random.PRNGKey(7))
    neg = x < 0
    slopes = np.asarray(y_tr)[neg] / x[neg]
    assert slopes.min() >= 1 / 8 - 1e-5 and slopes.max() <= 1 / 3 + 1e-5
    with pytest.raises(ValueError):
        layer.apply(v, x, training=True)


def test_gaussian_sampler_stats():
    mean = np.full((2000,), 3.0, np.float32)
    log_var = np.full((2000,), np.log(0.25), np.float32)
    layer = nn.GaussianSampler()
    v = layer.init(RNG, mean, log_var)
    y, _ = layer.apply(v, mean, log_var, rng=jax.random.PRNGKey(5))
    y = np.asarray(y)
    assert abs(y.mean() - 3.0) < 0.05
    assert abs(y.std() - 0.5) < 0.05


# ---------------------------------------------------------------------------
# conv family
# ---------------------------------------------------------------------------


def test_conv3d_transpose_torch_parity():
    torch = pytest.importorskip("torch")
    rs = np.random.RandomState(6)
    x = rs.rand(2, 3, 4, 5, 2).astype(np.float32)  # NDHWC
    layer = nn.Conv3DTranspose(2, 4, kernel_size=3, stride=2, padding=1)
    v = layer.init(RNG, x)
    y, _ = layer.apply(v, x)

    tconv = torch.nn.ConvTranspose3d(2, 4, 3, stride=2, padding=1, bias=True)
    # ours: (kd,kh,kw,out,in) -> torch: (in,out,kd,kh,kw)
    w = np.asarray(v["params"]["weight"]).transpose(4, 3, 0, 1, 2)
    with torch.no_grad():
        tconv.weight.copy_(torch.tensor(w))
        tconv.bias.copy_(torch.tensor(np.asarray(v["params"]["bias"])))
    want = tconv(torch.tensor(x.transpose(0, 4, 1, 2, 3))).detach().numpy()
    np.testing.assert_allclose(np.asarray(y).transpose(0, 4, 1, 2, 3), want,
                               rtol=1e-3, atol=1e-4)


def test_locally_connected_1d_matches_loop():
    rs = np.random.RandomState(7)
    x = rs.rand(2, 8, 3).astype(np.float32)
    layer = nn.LocallyConnected1D(3, 5, kernel_size=3, stride=2)
    v = layer.init(RNG, x)
    y, _ = layer.apply(v, x)
    w = np.asarray(v["params"]["weight"])
    b = np.asarray(v["params"]["bias"])
    out_len = (8 - 3) // 2 + 1
    assert y.shape == (2, out_len, 5)
    for l in range(out_len):
        win = x[:, l * 2:l * 2 + 3, :]
        want = np.einsum("nkc,kco->no", win, w[l]) + b[l]
        np.testing.assert_allclose(np.asarray(y[:, l]), want, rtol=1e-4,
                                   atol=1e-5)


def test_global_pool_3d():
    x = np.random.RandomState(8).rand(2, 3, 4, 5, 6).astype(np.float32)
    _, ya = _run(nn.GlobalAvgPool3D(), x)
    _, ym = _run(nn.GlobalMaxPool3D(), x)
    np.testing.assert_allclose(np.asarray(ya), x.mean(axis=(1, 2, 3)),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ym), x.max(axis=(1, 2, 3)),
                               rtol=1e-5)


def test_conv_lstm_shapes_and_state():
    rs = np.random.RandomState(9)
    x = rs.rand(2, 4, 6, 6, 3).astype(np.float32)
    layer = nn.ConvLSTM2D(3, 5, kernel_size=3)
    v = layer.init(RNG, x)
    y, _ = layer.apply(v, x)
    assert y.shape == (2, 4, 6, 6, 5)
    last = nn.ConvLSTM2D(3, 5, kernel_size=3, return_sequences=False)
    v2 = last.init(RNG, x)
    y2, _ = last.apply(v2, x)
    assert y2.shape == (2, 6, 6, 5)
    # outputs bounded by tanh*sigmoid
    assert np.abs(np.asarray(y)).max() <= 1.0 + 1e-5
    # gradient flows through the scan
    def loss(p):
        out, _ = layer.forward(p, {}, jnp.asarray(x))
        return jnp.sum(out ** 2)
    g = jax.grad(loss)(v["params"])
    assert float(jnp.linalg.norm(g["weight"])) > 0


def test_conv_lstm_no_peephole():
    x = np.random.RandomState(10).rand(1, 2, 4, 4, 2).astype(np.float32)
    layer = nn.ConvLSTM2D(2, 3, kernel_size=3, peephole=False)
    v = layer.init(RNG, x)
    assert "peep" not in v["params"]
    y, _ = layer.apply(v, x)
    assert y.shape == (1, 2, 4, 4, 3)


# ---------------------------------------------------------------------------
# local normalization
# ---------------------------------------------------------------------------


def test_subtractive_normalization_zero_mean_on_constant():
    x = np.full((1, 8, 8, 3), 5.0, np.float32)
    _, y = _run(nn.SpatialSubtractiveNormalization(5), x)
    # constant input: local mean == value everywhere (edge-corrected)
    np.testing.assert_allclose(np.asarray(y), 0.0, atol=1e-5)


def test_divisive_normalization_scale_invariance():
    rs = np.random.RandomState(11)
    x = rs.rand(1, 10, 10, 2).astype(np.float32)
    _, y1 = _run(nn.SpatialDivisiveNormalization(5), x)
    _, y2 = _run(nn.SpatialDivisiveNormalization(5), 10 * x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=1e-3,
                               atol=1e-4)


def test_contrastive_normalization_runs():
    x = np.random.RandomState(12).rand(2, 9, 9, 3).astype(np.float32)
    _, y = _run(nn.SpatialContrastiveNormalization(5), x)
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(y)).all()
