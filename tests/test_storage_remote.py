"""Remote-filesystem (URI) checkpoint / model / record I/O.

VERDICT r4 Missing #5: the reference saves checkpoints and models to HDFS
as a first-class path (``utils/File.scala`` local-or-HDFS URIs,
``Optimizer.setCheckpoint(hdfs://…)``); the TPU-native analog is object
storage via fsspec.  These tests exercise the real remote code path using
fsspec's built-in ``memory://`` filesystem — genuine remote semantics
(no atomic rename, prefix-only directories) with no network.
"""

import os

import numpy as np
import pytest

from bigdl_tpu.utils import storage

pytest.importorskip("fsspec")

_N = [0]


def _uri(name: str) -> str:
    """Unique memory:// prefix per use (the filesystem is process-global)."""
    _N[0] += 1
    return f"memory://t{os.getpid()}_{_N[0]}/{name}"


# ---------------------------------------------------------------------------
# storage primitives


def test_is_remote():
    assert storage.is_remote("gs://bucket/x")
    assert storage.is_remote("memory://a/b")
    assert not storage.is_remote("/tmp/x")
    assert not storage.is_remote("relative/path")
    assert not storage.is_remote("file:///tmp/x")


def test_join_and_basename():
    assert storage.join("gs://b/a", "c", "d.json") == "gs://b/a/c/d.json"
    assert storage.basename("gs://b/a/ckpt-3/") == "ckpt-3"
    assert storage.join("/tmp/a", "b") == os.path.join("/tmp/a", "b")


def test_memory_roundtrip_and_listdir():
    root = _uri("dir")
    p = storage.join(root, "x.json")
    assert not storage.exists(p)
    storage.write_json(p, {"v": 7})
    assert storage.exists(p)
    assert storage.read_json(p) == {"v": 7}
    storage.write_json(storage.join(root, "sub", "y.json"), {})
    names = sorted(storage.listdir(root))
    assert names == ["sub", "x.json"]
    assert storage.isdir(storage.join(root, "sub"))
    storage.remove_tree(root)
    assert storage.listdir(root) == []


def test_unknown_scheme_raises_actionable():
    with pytest.raises((ImportError, ValueError)) as ei:
        storage.open_file("zz://bucket/x", "rb")
    assert "zz" in str(ei.value) or "fsspec" in str(ei.value)


# ---------------------------------------------------------------------------
# checkpoint save/load over a remote URI


def _fake_state(seed=0):
    rs = np.random.RandomState(seed)
    flat = rs.randn(37).astype(np.float32)
    opt_state = {"momentum": rs.randn(37).astype(np.float32),
                 "t": np.asarray(3, np.int32)}
    model_state = {"bn": {"mean": rs.randn(4).astype(np.float32)}}
    return flat, opt_state, model_state


def test_checkpoint_roundtrip_remote():
    from bigdl_tpu.optim.checkpoint import (latest_checkpoint,
                                            load_checkpoint, save_checkpoint)

    root = _uri("ckpts")
    flat, opt_state, model_state = _fake_state()
    for step in (2, 5, 9):
        d = save_checkpoint(
            root, step, flat_params=flat * step, opt_state=opt_state,
            model_state=model_state, driver_state={"epoch": step, "x": 1.5},
            keep_last=2)
        assert d.startswith("memory://")
    # keep_last=2 garbage-collected ckpt-2
    latest = latest_checkpoint(root)
    assert latest.endswith("ckpt-9")
    assert not storage.exists(storage.join(root, "ckpt-2", "manifest.json"))
    got_flat, got_opt, got_ms, driver, ema = load_checkpoint(
        latest, opt_state_template=opt_state,
        model_state_template=model_state)
    np.testing.assert_allclose(got_flat, flat * 9)
    np.testing.assert_allclose(got_opt["momentum"], opt_state["momentum"])
    assert driver == {"epoch": 9, "x": 1.5}
    assert ema is None


def test_partial_remote_checkpoint_ignored():
    """A prefix without a manifest (crashed mid-write: remote writes order
    the manifest LAST) must be invisible to latest_checkpoint."""
    from bigdl_tpu.optim.checkpoint import latest_checkpoint, save_checkpoint

    root = _uri("partial")
    flat, opt_state, model_state = _fake_state()
    save_checkpoint(root, 1, flat_params=flat, opt_state=opt_state,
                    model_state=model_state, driver_state={})
    # simulate a crash: blobs written for step 7, no manifest
    with storage.open_file(storage.join(root, "ckpt-7", "params.npz"),
                           "wb") as f:
        np.savez(f, flat=flat)
    assert latest_checkpoint(root).endswith("ckpt-1")


def test_remote_rewrite_same_step_drops_stale_manifest():
    """Re-reaching a step must remove the old manifest BEFORE new blobs
    go down — a stale manifest would certify a half-rewritten prefix."""
    from bigdl_tpu.optim.checkpoint import (latest_checkpoint,
                                            load_checkpoint, save_checkpoint)

    root = _uri("rewrite")
    flat, opt_state, model_state = _fake_state()
    save_checkpoint(root, 3, flat_params=flat, opt_state=opt_state,
                    model_state=model_state, driver_state={"run": 1})
    save_checkpoint(root, 3, flat_params=flat * 2, opt_state=opt_state,
                    model_state=model_state, driver_state={"run": 2})
    got_flat, *_, driver, _ema = load_checkpoint(
        latest_checkpoint(root), opt_state_template=opt_state,
        model_state_template=model_state)
    np.testing.assert_allclose(got_flat, flat * 2)
    assert driver == {"run": 2}


def test_gc_sweeps_old_partial_remote_prefixes():
    """Blob-only prefixes older than the newest complete checkpoint are
    garbage, not potential in-flight writes — _gc must remove them."""
    from bigdl_tpu.optim.checkpoint import save_checkpoint

    root = _uri("gcpartial")
    flat, opt_state, model_state = _fake_state()
    # crashed write at step 1: params blob, no manifest
    with storage.open_file(storage.join(root, "ckpt-1", "params.npz"),
                           "wb") as f:
        np.savez(f, flat=flat)
    save_checkpoint(root, 5, flat_params=flat, opt_state=opt_state,
                    model_state=model_state, driver_state={})
    assert not storage.exists(storage.join(root, "ckpt-1", "params.npz"))
    # a YOUNGER partial (possible in-flight write) must survive
    with storage.open_file(storage.join(root, "ckpt-9", "params.npz"),
                           "wb") as f:
        np.savez(f, flat=flat)
    save_checkpoint(root, 7, flat_params=flat, opt_state=opt_state,
                    model_state=model_state, driver_state={})
    assert storage.exists(storage.join(root, "ckpt-9", "params.npz"))


def test_checkpoint_ema_roundtrip_remote():
    from bigdl_tpu.optim.checkpoint import (latest_checkpoint,
                                            load_checkpoint, save_checkpoint)

    root = _uri("ema")
    flat, opt_state, model_state = _fake_state()
    save_checkpoint(root, 4, flat_params=flat, opt_state=opt_state,
                    model_state=model_state, driver_state={},
                    ema_flat=flat * 0.5)
    *_, ema = load_checkpoint(
        latest_checkpoint(root), opt_state_template=opt_state,
        model_state_template=model_state)
    np.testing.assert_allclose(ema, flat * 0.5)


# ---------------------------------------------------------------------------
# durable model format over a remote URI


def test_save_load_model_remote():
    from bigdl_tpu.utils.serializer import load_model, save_model

    root = _uri("model")
    rs = np.random.RandomState(1)
    variables = {"params": {"linear": {"w": rs.randn(3, 4).astype(np.float32),
                                       "b": np.zeros(4, np.float32)}}}
    save_model(root, None, variables)
    got = load_model(root, template=variables)
    np.testing.assert_allclose(got["params"]["linear"]["w"],
                               variables["params"]["linear"]["w"])
    with pytest.raises(FileExistsError):
        save_model(root, None, variables, overwrite=False)


# ---------------------------------------------------------------------------
# record files over a remote URI (download-once local cache)


def test_records_remote_roundtrip(tmp_path, monkeypatch):
    from bigdl_tpu.data.records import RecordDataSet, write_records

    monkeypatch.setenv("BIGDL_TPU_RECORD_CACHE", str(tmp_path / "cache"))
    uri = storage.join(_uri("recs"), "train.btrec")
    rs = np.random.RandomState(2)
    xs = rs.randint(0, 255, (40, 6, 6, 3), np.uint8)
    ys = rs.randint(0, 10, (40,)).astype(np.int32)
    write_records(uri, {"x": xs, "y": ys})

    ds = RecordDataSet(uri, feature="x", label="y")
    try:
        assert ds.size() == 40
        seen = 0
        for mb in ds.batches(16, shuffle=False, drop_last=True):
            seen += len(mb["input"])
            assert mb["input"].dtype == np.uint8
        assert seen == 32  # 40 // 16 full batches
        first = next(iter(ds.batches(16, shuffle=False)))
        np.testing.assert_array_equal(first["input"], xs[:16])
        np.testing.assert_array_equal(first["target"], ys[:16])
    finally:
        ds.close()
    # second open hits the cache (delete the remote object to prove it)
    storage.remove_tree(uri)
    ds2 = RecordDataSet(uri, feature="x", label="y")
    try:
        assert ds2.size() == 40
    finally:
        ds2.close()


# ---------------------------------------------------------------------------
# XShards multihost file reads over a remote URI


def test_read_csv_remote_sharded():
    import pandas as pd

    from bigdl_tpu.data.shards import read_csv

    root = _uri("csvs")
    for i in range(4):
        with storage.open_file(storage.join(root, f"part-{i}.csv"),
                               "w") as f:
            f.write("a,b\n")
            for r in range(3):
                f.write(f"{i},{r}\n")
    # directory form + glob form, unsharded
    xs = read_csv(root)
    assert len(xs._shards) == 4
    total = pd.concat(xs._shards)
    assert len(total) == 12 and sorted(total["a"].unique()) == [0, 1, 2, 3]
    xs2 = read_csv(storage.join(root, "part-*.csv"))
    assert len(xs2._shards) == 4
    # sharded: each simulated process owns its round-robin slice
    own0 = read_csv(root, process_id=0, process_count=2)
    own1 = read_csv(root, process_id=1, process_count=2)
    a0 = sorted(pd.concat(own0._shards)["a"].unique())
    a1 = sorted(pd.concat(own1._shards)["a"].unique())
    assert a0 == [0, 2] and a1 == [1, 3]


# ---------------------------------------------------------------------------
# resume-from-URI through the real Optimizer loop


def test_optimizer_checkpoint_resume_remote():
    import jax

    from bigdl_tpu import nn
    from bigdl_tpu.data.dataset import ArrayDataSet
    from bigdl_tpu.nn.criterion import MSECriterion
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.optim.optimizer import Optimizer
    from bigdl_tpu.optim.trigger import Trigger

    root = _uri("opt")
    rs = np.random.RandomState(3)
    x = rs.randn(64, 5).astype(np.float32)
    y = (x @ rs.randn(5, 1)).astype(np.float32)

    def build(n_iters):
        model = nn.Sequential([nn.Linear(5, 8), nn.Tanh(), nn.Linear(8, 1)])
        opt = Optimizer(model, ArrayDataSet(x, y), MSECriterion(),
                        batch_size=16, seed=5)
        opt.set_optim_method(SGD(learning_rate=0.05))
        opt.set_end_when(Trigger.max_iteration(n_iters))
        opt.set_checkpoint(root, Trigger.several_iteration(2))
        opt.log_every = 100
        return opt

    build(4).optimize()
    from bigdl_tpu.optim.checkpoint import latest_checkpoint

    assert latest_checkpoint(root).endswith("ckpt-4")
    # fresh Optimizer against the same URI resumes from iteration 4
    t = build(8).optimize()
    assert latest_checkpoint(root).endswith("ckpt-8")
    pred = t.predict(x)
    assert np.isfinite(pred).all()
