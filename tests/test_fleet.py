"""Decode fleet (docs/serving.md §Decode fleet): KV-aware routing,
prefill/decode handoff, and prefix-cache reuse.

The load-bearing invariant everywhere here is byte parity: fleet-routed
generation — cached-prefix attach, cross-engine (and cross-process)
prefill→decode handoff — must match ``static_generate`` token for token
and logprob for logprob, greedy AND seeded.  The cache/handoff layers
substitute identical bytes for identical work; these tests are the
proof.
"""

import json
import os
import threading
from urllib import error as _urlerr
from urllib import request as urlreq

import jax
import numpy as np
import pytest

from bigdl_tpu.nn.attention import Transformer
from bigdl_tpu.obs import sentinel
from bigdl_tpu.serving.decode_engine import (DecodeConfig, DecodeEngine,
                                             DecodeRequest, LMAdapter)
from bigdl_tpu.serving.fleet import (FleetRouter, PrefixCache,
                                     pack_handoff, unpack_handoff)
from bigdl_tpu.serving.fleet.handoff import HANDOFF_MAGIC, HandoffError

BOS, EOS = 0, 1


@pytest.fixture(scope="module")
def lm():
    model = Transformer(vocab_size=32, hidden_size=16, num_heads=2,
                        num_layers=2, dropout=0.0, mode="lm")
    v = model.init(jax.random.PRNGKey(0),
                   np.arange(6, dtype=np.int32)[None])
    return model, v


def _engine(lm, **over):
    model, v = lm
    kw = dict(slots=4, page_size=4, pages_per_slot=4, prompt_chunk=4,
              max_new_tokens=8, eos_id=EOS, prefill_batch=2,
              prefix_cache_pages=8)
    kw.update(over)
    cfg = DecodeConfig(**kw)
    return DecodeEngine(LMAdapter(model, v["params"], cap=cfg.cap),
                        cfg).warmup()


def _shared_prompts():
    rs = np.random.RandomState(0)
    common = rs.randint(2, 32, size=9).tolist()
    p1 = np.asarray(common + [5, 7], np.int32)
    p2 = np.asarray(common + [9, 3, 11], np.int32)
    return p1, p2


# ---------------------------------------------------------------------------
# PrefixCache units


def test_prefix_cache_match_is_page_aligned_and_strict():
    c = PrefixCache(max_pages=8, page_size=4)
    key = list(range(2, 10))            # 8 tokens = 2 pages
    assert c.insert(key, [0, 1])
    # a longer prompt sharing the prefix matches the cached entry
    e = c.match(key + [30])
    assert e is not None and e.pages == [0, 1]
    # STRICT prefix: the exact key must not match itself — the final
    # prefill chunk (first-token selection) always runs locally
    assert c.match(key) is None
    # unrelated prompt misses
    assert c.match([31] * 12) is None
    # longest match wins over a shorter cached prefix
    assert c.insert(key[:4], [2])
    e = c.match(key + [30])
    assert e is not None and len(e.key) == 8


def test_prefix_cache_insert_validation():
    c = PrefixCache(max_pages=4, page_size=4)
    assert not c.insert([2, 3, 4], [0])           # not page-aligned
    assert not c.insert([], [])                   # empty
    assert c.insert([2, 3, 4, 5], [0])
    assert not c.insert([2, 3, 4, 5], [1])        # duplicate key
    assert c.stats()["rejected_insertions"] == 1


def test_prefix_cache_eviction_never_frees_live_pages():
    c = PrefixCache(max_pages=8, page_size=4)
    assert c.insert([2, 3, 4, 5], [0])            # e1: 1 page
    assert c.insert([6, 7, 8, 9], [1, ])          # e2: 1 page
    e1 = c.match([2, 3, 4, 5, 10])
    c.attach(e1)                                  # e1 is LIVE (refs=1)
    freed = c.evict(5)
    # only the idle entry's page comes back; the live entry survives
    assert freed == [1]
    assert c.match([2, 3, 4, 5, 10]) is e1
    # still-live entry survives even direct pressure
    assert c.evict(1) == []
    c.detach(e1)
    assert sorted(c.evict(1)) == [0]
    assert len(c) == 0 and c.pages_held == 0


def test_prefix_cache_evict_protect_shields_pending_attach():
    c = PrefixCache(max_pages=8, page_size=4)
    assert c.insert([2, 3, 4, 5], [0])
    e = c.match([2, 3, 4, 5, 9])
    # refs == 0 until the admission commits, but the pages are spoken
    # for: protect= keeps eviction's hands off
    assert c.evict(4, protect=e) == []
    assert c.match([2, 3, 4, 5, 9]) is e


def test_prefix_cache_budget_bounded_with_lru_turnover():
    c = PrefixCache(max_pages=2, page_size=4)
    assert not c.insert(list(range(2, 14)), [0, 1, 2])  # 3 pages > budget
    assert c.insert([2, 3, 4, 5], [0])
    assert c.insert([6, 7, 8, 9], [1])
    assert c.pages_held == 2
    # a third insert evicts the LRU idle entry to make the budget
    c.attach(c.match([6, 7, 8, 9, 30]))  # freshen + pin e2
    assert c.insert([10, 11, 12, 13], [2])
    assert c.pages_held == 2
    assert c.match([2, 3, 4, 5, 30]) is None  # e1 was the LRU victim
    s = c.stats()
    assert s["evictions"] == 1 and s["evicted_pages"] == 1


# ---------------------------------------------------------------------------
# FleetRouter units


def _health(role="both", slots=2, pages=10, total=16, queued=0,
            inflight=0, prefill_backlog=0, slo=1.0, alive=True):
    return {"alive": alive, "role": role, "slo_health": slo,
            "decode": {"free_slots": slots, "free_pages": pages,
                       "total_pages": total, "queued": queued,
                       "generate_inflight": inflight,
                       "prefill_backlog": prefill_backlog}}


def test_router_picks_decode_headroom():
    r = FleetRouter()
    d, p = r.route([_health(slots=0, pages=0),
                    _health(slots=3, pages=12)])
    assert (d, p) == (1, None)


def test_router_penalizes_backlog_and_slo():
    r = FleetRouter()
    # equal capacity, but worker 0 has queued generate work
    d, _ = r.route([_health(queued=4, inflight=4), _health()])
    assert d == 1
    # equal capacity, worker 1's SLO is burning
    d, _ = r.route([_health(), _health(slo=0.2)])
    assert d == 0


def test_router_skips_dead_and_prefill_workers_for_decode():
    r = FleetRouter()
    d, p = r.route([_health(alive=False), _health(role="prefill"),
                    _health(role="decode")])
    assert d == 2 and p == 1
    # a prefill-only fleet cannot decode
    assert r.route([_health(role="prefill")]) == (None, None)
    assert r.route([]) == (None, None)


def test_router_split_only_with_dedicated_prefill_role():
    r = FleetRouter()
    # no prefill-role workers: decode worker prefills locally
    d, p = r.route([_health(), _health()])
    assert d is not None and p is None
    # least-backlogged prefill worker wins
    d, p = r.route([_health(role="prefill", prefill_backlog=5),
                    _health(role="prefill", prefill_backlog=0),
                    _health(role="decode")])
    assert (d, p) == (2, 1)


def test_router_deterministic_tiebreak():
    r = FleetRouter()
    d1, _ = r.route([_health(), _health()])
    d2, _ = r.route([_health(), _health()])
    assert d1 == d2 == 0  # ties break on the lower index


# ---------------------------------------------------------------------------
# handoff wire format


def _fake_handoff():
    rs = np.random.RandomState(3)
    return {"tokens": [4, 9, 2, 7, 5], "first_token": 12,
            "first_logp": -1.25, "temperature": 0.8, "top_k": 8,
            "top_p": 0.9, "seed": 13, "request_id": "req-1",
            "k": rs.randn(2, 2, 2, 4, 3).astype(np.float32),
            "v": rs.randn(2, 2, 2, 4, 3).astype(np.float32)}


def test_handoff_roundtrip_is_exact():
    h = _fake_handoff()
    out = unpack_handoff(pack_handoff(h))
    assert out["k"].tobytes() == h["k"].tobytes()
    assert out["v"].tobytes() == h["v"].tobytes()
    assert out["tokens"].dtype == np.int32
    assert list(out["tokens"]) == h["tokens"]
    assert out["first_token"] == 12
    assert np.float32(out["first_logp"]) == np.float32(-1.25)
    # extra JSON-serializable keys ride along untouched
    assert out["request_id"] == "req-1" and out["seed"] == 13


def test_handoff_rejects_bad_payloads():
    h = _fake_handoff()
    data = pack_handoff(h)
    with pytest.raises(ValueError, match="magic"):
        unpack_handoff(b"nope" + data)
    with pytest.raises(ValueError, match="truncated"):
        unpack_handoff(data[:-8])
    with pytest.raises(ValueError, match="required"):
        pack_handoff({k: v for k, v in h.items() if k != "first_token"})
    bad = dict(h, v=h["v"][:1])
    with pytest.raises(ValueError, match="5-d page-pool shape"):
        pack_handoff(bad)


def test_handoff_kv_dtype_roundtrip():
    """The kv_dtype header rides the wire for BOTH dtypes: f32 blobs
    stay bit-identical to the pre-kv_dtype format, int8 blobs carry the
    per-(layer, page) scale tables behind the V payload and cut wire
    bytes ~4x (docs/quantization.md §Serving memory hierarchy)."""
    rs = np.random.RandomState(4)
    f32 = unpack_handoff(pack_handoff(_fake_handoff()))
    assert f32["kv_dtype"] == "float32"
    assert f32["k"].dtype == np.float32
    assert "k_scales" not in f32
    h8 = dict(_fake_handoff(), kv_dtype="int8",
              k=rs.randint(-127, 128, (2, 2, 2, 4, 3)).astype(np.int8),
              v=rs.randint(-127, 128, (2, 2, 2, 4, 3)).astype(np.int8),
              k_scales=rs.rand(2, 2).astype(np.float32),
              v_scales=rs.rand(2, 2).astype(np.float32))
    blob = pack_handoff(h8)
    f32_blob = pack_handoff(_fake_handoff())
    assert len(blob) < len(f32_blob) / 2   # int8 pages shrink the wire
    out = unpack_handoff(blob)
    assert out["kv_dtype"] == "int8" and out["k"].dtype == np.int8
    assert out["k"].tobytes() == h8["k"].tobytes()
    assert out["v"].tobytes() == h8["v"].tobytes()
    np.testing.assert_array_equal(out["k_scales"], h8["k_scales"])
    np.testing.assert_array_equal(out["v_scales"], h8["v_scales"])
    # int8 without the scale tables is unserializable, not silently f32
    with pytest.raises(ValueError, match="scale tables"):
        pack_handoff(dict(h8, k_scales=None))


def test_handoff_unknown_kv_dtype_rejected_by_name():
    """A future dtype must be rejected NAMING the dtype — never misread
    as f32 pages — and a legacy 'dtype' field that contradicts
    'kv_dtype' is a corrupt header."""
    import json as _json

    h = _fake_handoff()
    with pytest.raises(ValueError, match="fp4"):
        pack_handoff(dict(h, kv_dtype="fp4"))
    # forge the header of a valid blob to claim an unknown dtype
    data = pack_handoff(h)
    off = len(HANDOFF_MAGIC)
    hlen = int.from_bytes(data[off:off + 8], "big")
    hdr = _json.loads(data[off + 8:off + 8 + hlen].decode())

    def _reforge(hdr):
        enc = _json.dumps(hdr, sort_keys=True).encode()
        return (HANDOFF_MAGIC + len(enc).to_bytes(8, "big") + enc
                + data[off + 8 + hlen:])

    forged = _reforge(dict(hdr, kv_dtype="fp4", dtype="fp4"))
    with pytest.raises(HandoffError, match="fp4"):
        unpack_handoff(forged)
    # legacy decoders keyed on "dtype": a blob where the two fields
    # disagree must not be trusted either way
    forged = _reforge(dict(hdr, kv_dtype="int8", dtype="float32"))
    with pytest.raises(HandoffError, match="contradicts"):
        unpack_handoff(forged)


# ---------------------------------------------------------------------------
# engine-level parity: prefix-cache attach and handoff import


def test_prefix_cache_parity_greedy(lm):
    eng = _engine(lm)
    p1, p2 = _shared_prompts()
    r1 = eng.generate([p1], max_new_tokens=6)[0]          # cold: donates
    r2 = eng.generate([p2], max_new_tokens=6)[0]          # warm: attaches
    s1 = eng.static_generate([DecodeRequest(tokens=p1,
                                            max_new_tokens=6)])[0]
    s2 = eng.static_generate([DecodeRequest(tokens=p2,
                                            max_new_tokens=6)])[0]
    assert r1.tokens.tobytes() == s1.tokens.tobytes()
    assert r2.tokens.tobytes() == s2.tokens.tobytes()
    assert r1.logp == s1.logp and r2.logp == s2.logp
    st = eng._prefix_cache.stats()
    assert st["hits"] >= 1 and st["insertions"] >= 1
    eng.stop()


def test_prefix_cache_parity_seeded(lm):
    eng = _engine(lm)
    p1, p2 = _shared_prompts()
    kw = dict(max_new_tokens=6, temperature=0.8, top_k=8, top_p=0.9,
              seed=13)
    eng.generate([p1], max_new_tokens=6)                  # seed the cache
    assert eng._prefix_cache.stats()["insertions"] >= 1
    r = eng.generate([p2], **kw)[0]
    s = eng.static_generate([DecodeRequest(tokens=p2, **kw)])[0]
    assert r.tokens.tobytes() == s.tokens.tobytes()
    assert r.logp == s.logp
    assert eng._prefix_cache.stats()["hits"] >= 1
    eng.stop()


def test_prefix_cache_page_accounting_exact(lm):
    """Cache-held pages leave the free list and come back on eviction —
    free + cached must always equal the pool when the engine idles."""
    eng = _engine(lm)
    total = eng.cfg.total_pages
    p1, p2 = _shared_prompts()
    for p in (p1, p2):
        eng.generate([p], max_new_tokens=4)
    held = eng._prefix_cache.pages_held
    assert held > 0
    assert len(eng._free_pages) + held == total
    freed = eng._prefix_cache.evict(held)
    eng._free_pages.extend(freed)
    assert len(eng._free_pages) == total
    eng.stop()


def test_handoff_cross_engine_parity(lm):
    """Prefill on engine A, decode on engine B (fresh KV pool): byte-
    identical to static_generate — the invariant the physical
    prefill/decode split rests on."""
    eng_a = _engine(lm, prefix_cache_pages=0)
    eng_b = _engine(lm, prefix_cache_pages=0)
    _, p2 = _shared_prompts()
    kw = dict(temperature=0.8, top_k=8, top_p=0.9, seed=13)
    pre = eng_a.submit(DecodeRequest(tokens=p2, max_new_tokens=1,
                                     export_kv=True, **kw))
    pre.wait(30)
    assert pre.error is None and pre.kv_export is not None
    assert eng_a.stats["kv_exports"] == 1
    # the serialized channel is part of the path under test
    h = unpack_handoff(pack_handoff(pre.kv_export))
    got = eng_b.submit_prefilled(h, max_new_tokens=6).wait(30)
    ref = eng_b.static_generate([DecodeRequest(tokens=p2,
                                               max_new_tokens=6, **kw)])[0]
    assert got.tokens.tobytes() == ref.tokens.tobytes()
    assert got.logp == ref.logp
    assert eng_b.stats["kv_imports"] == 1
    eng_a.stop()
    eng_b.stop()


def test_handoff_greedy_parity_and_first_token(lm):
    eng_a = _engine(lm, prefix_cache_pages=0)
    eng_b = _engine(lm, prefix_cache_pages=0)
    p1, _ = _shared_prompts()
    pre = eng_a.submit(DecodeRequest(tokens=p1, max_new_tokens=1,
                                     export_kv=True))
    pre.wait(30)
    h = unpack_handoff(pack_handoff(pre.kv_export))
    ref = eng_b.static_generate([DecodeRequest(tokens=p1,
                                               max_new_tokens=6)])[0]
    # the first token was selected on the PREFILL engine during its
    # final chunk; the decode engine re-emits, never re-selects
    assert int(h["first_token"]) == int(ref.tokens[0])
    got = eng_b.submit_prefilled(h, max_new_tokens=6).wait(30)
    assert got.tokens.tobytes() == ref.tokens.tobytes()
    eng_a.stop()
    eng_b.stop()


def test_fleet_request_validation(lm):
    eng = _engine(lm)
    _, p2 = _shared_prompts()
    pre = eng.submit(DecodeRequest(tokens=p2, max_new_tokens=1,
                                   export_kv=True))
    pre.wait(30)
    h = pre.kv_export
    # token mismatch between handoff and request must be rejected
    # (submit_prefilled takes its tokens FROM the handoff, so the
    # mismatch can only arrive via a hand-built DecodeRequest)
    other = np.asarray(list(p2[:-1]) + [30], np.int32)
    with pytest.raises(ValueError):
        eng.submit(DecodeRequest(tokens=other, handoff=h,
                                 max_new_tokens=4))
    # K/V shaped for a different geometry must be rejected
    bad = dict(h, k=h["k"][:, :1], v=h["v"][:, :1])
    with pytest.raises(ValueError):
        eng.submit_prefilled(bad, max_new_tokens=4)
    eng.stop()


# ---------------------------------------------------------------------------
# server + frontend: backlog, /health decode block, /fleet/prefill, split


def _serving_pair(lm, **decode_over):
    from bigdl_tpu.serving.http_frontend import HttpFrontend
    from bigdl_tpu.serving.inference_model import InferenceModel
    from bigdl_tpu.serving.server import ServingConfig, ServingServer

    model, v = lm
    kw = dict(slots=4, page_size=4, pages_per_slot=4, prompt_chunk=4,
              max_new_tokens=16, eos_id=EOS, prefill_batch=2,
              prefix_cache_pages=8)
    kw.update(decode_over)
    srv = ServingServer(InferenceModel(model, v, decode=DecodeConfig(**kw)),
                        ServingConfig()).start()
    fe = HttpFrontend(srv, port=0).start()
    return srv, fe


def test_backlog_counts_generate_inflight(lm):
    srv, fe = _serving_pair(lm)
    try:
        p1, _ = _shared_prompts()
        hold = threading.Event()
        # the first token's callback parks the engine thread: the
        # request cannot resolve until we release it, so the backlog
        # observation below is deterministic, not a race
        rid = srv.enqueue_generate(p1, max_new_tokens=4,
                                   on_token=lambda r, t, i: hold.wait(10))
        assert srv.backlog() >= 1
        h = json.loads(urlreq.urlopen(fe.url + "/health").read())
        assert h["backlog"] >= 1
        assert h["decode"]["generate_inflight"] >= 1
        hold.set()
        srv.query(rid, timeout=30)
        assert srv.backlog() == 0
    finally:
        fe.stop()
        srv.stop()


def test_health_reports_role_and_decode_pressure(lm):
    srv, fe = _serving_pair(lm)
    try:
        srv.role = "decode"
        h = json.loads(urlreq.urlopen(fe.url + "/health").read())
        assert h["role"] == "decode"
        d = h["decode"]
        for key in ("total_slots", "free_slots", "total_pages",
                    "free_pages", "prefill_backlog", "generate_inflight"):
            assert key in d, key
        assert d["free_slots"] == 4 and d["generate_inflight"] == 0
        assert "prefix_cache" in d
    finally:
        fe.stop()
        srv.stop()


def test_prefix_cache_counters_in_one_metrics_scrape(lm):
    srv, fe = _serving_pair(lm)
    try:
        p1, p2 = _shared_prompts()
        for p in (p1, p2):
            srv.query(srv.enqueue_generate(p, max_new_tokens=4),
                      timeout=30)
        scrape = urlreq.urlopen(fe.url + "/metrics").read().decode()
        # hit AND miss counters land in the same exposition
        assert "serving_fleet_prefix_cache_hits" in scrape
        assert "serving_fleet_prefix_cache_misses" in scrape
        hits = [ln for ln in scrape.splitlines()
                if ln.startswith("serving_fleet_prefix_cache_hits")
                and not ln.startswith("#")]
        assert hits and float(hits[0].split()[-1]) >= 1
    finally:
        fe.stop()
        srv.stop()


def test_fleet_prefill_endpoint_and_split_parity(lm):
    """Two in-process workers — role=prefill and role=decode — split a
    request over HTTP exactly as the pool proxy arranges it (the
    X-Prefill-Url header), byte-identical to a local static decode."""
    srv_p, fe_p = _serving_pair(lm)
    srv_d, fe_d = _serving_pair(lm)
    try:
        srv_p.role, srv_d.role = "prefill", "decode"
        _, p2 = _shared_prompts()
        prompt = [int(t) for t in p2]
        kw = dict(max_new_tokens=8, temperature=0.7, top_k=8, top_p=0.9,
                  seed=21)
        eng = srv_d.model.decode_engine
        ref = eng.static_generate(
            [DecodeRequest(tokens=np.asarray(prompt, np.int32), **kw)])[0]
        body = json.dumps(dict(tokens=prompt, stream=False, **kw)).encode()
        req = urlreq.Request(fe_d.url + "/generate", data=body, headers={
            "Content-Type": "application/json", "X-Prefill-Url": fe_p.url})
        out = json.loads(urlreq.urlopen(req, timeout=30).read())
        got = np.asarray(out["tokens"], np.int32)
        assert got.tobytes() == ref.tokens.tobytes()
        # the prefill ran on the OTHER worker and shipped its pages
        assert srv_p.model.decode_engine.stats["kv_exports"] == 1
        assert eng.stats["kv_imports"] == 1
        # /fleet/prefill error mapping: unknown model is the caller's 404
        try:
            urlreq.urlopen(urlreq.Request(
                fe_p.url + "/fleet/prefill",
                data=json.dumps({"tokens": prompt,
                                 "model": "nope"}).encode(),
                headers={"Content-Type": "application/json"}), timeout=10)
            raise AssertionError("expected HTTP 404")
        except _urlerr.HTTPError as e:
            assert e.code == 404
    finally:
        fe_p.stop()
        fe_d.stop()
        srv_p.stop()
        srv_d.stop()


def test_split_streaming_parity(lm):
    """X-Prefill-Url + stream=true: every token event and the final
    verdict match the local static reference byte for byte."""
    import http.client

    srv_p, fe_p = _serving_pair(lm)
    srv_d, fe_d = _serving_pair(lm)
    try:
        srv_p.role, srv_d.role = "prefill", "decode"
        _, p2 = _shared_prompts()
        prompt = [int(t) for t in p2]
        kw = dict(max_new_tokens=8, temperature=0.7, top_k=8, top_p=0.9,
                  seed=21)
        ref = srv_d.model.decode_engine.static_generate(
            [DecodeRequest(tokens=np.asarray(prompt, np.int32), **kw)])[0]
        conn = http.client.HTTPConnection(fe_d.host, fe_d.port, timeout=30)
        conn.request(
            "POST", "/generate",
            body=json.dumps(dict(tokens=prompt, stream=True, **kw)).encode(),
            headers={"Content-Type": "application/json",
                     "X-Prefill-Url": fe_p.url, "Connection": "close"})
        resp = conn.getresponse()
        assert resp.status == 200
        toks, final = [], None
        while True:
            line = resp.readline()
            if not line:
                break
            ev = json.loads(line)
            if ev.get("done"):
                final = ev
                break
            toks.append(ev["token"])
        conn.close()
        assert final is not None and "error" not in final
        assert np.asarray(final["tokens"],
                          np.int32).tobytes() == ref.tokens.tobytes()
        assert toks == [int(t) for t in ref.tokens]
        assert srv_p.model.decode_engine.stats["kv_exports"] == 1
    finally:
        fe_p.stop()
        fe_d.stop()
        srv_p.stop()
        srv_d.stop()


# ---------------------------------------------------------------------------
# sentinel: the DECODE_POOL_r* family


def test_sentinel_normalizes_decode_pool_rows():
    row = {"engine": "decode_pool", "geometry": "decode_pool_w2_c24",
           "workers": 2, "concurrent_clients": 24,
           "tokens_per_s": 5000.0, "tokens_per_s_user": 40.0,
           "ttft_ms_p50": 300.0, "ttft_ms_p99": 900.0,
           "inter_token_p99_ms": 6.0}
    fams = {r.family: r for r in sentinel.normalize(row, "t")}
    assert fams["decode_tokens_per_s_decode_pool_w2_c24"].direction \
        == sentinel.HIGHER
    assert fams["decode_ttft_ms_p99_decode_pool_w2_c24"].direction \
        == sentinel.LOWER
    assert fams["decode_inter_token_p99_ms_decode_pool_w2_c24"].direction \
        == sentinel.LOWER
    assert "DECODE_POOL_r[0-9]*.json" in sentinel._ARTIFACT_GLOBS


def test_sentinel_gates_committed_decode_pool_artifact():
    """DECODE_POOL_r01.json is committed evidence: the sentinel must load
    it into per-geometry families and flag a regression against it."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.exists(os.path.join(root, "DECODE_POOL_r01.json")):
        pytest.skip("DECODE_POOL_r01.json not committed yet")
    history = sentinel.load_history(root)
    fams = [f for f in history if f.endswith("decode_pool_w2_c24")]
    assert any(f.startswith("decode_tokens_per_s") for f in fams)
    assert any(f.startswith("decode_ttft_ms_p99") for f in fams)
    base = sentinel.baseline_for("decode_ttft_ms_p99_decode_pool_w2_c24",
                                 history)
    bad = {"geometry": "decode_pool_w2_c24",
           "tokens_per_s": 1.0, "ttft_ms_p99": base.value * 2,
           "inter_token_p99_ms": 50.0}
    verdicts = {v.family: v for v in sentinel.check(bad, history)}
    assert verdicts["decode_ttft_ms_p99_decode_pool_w2_c24"].regressed


# ---------------------------------------------------------------------------
# whole-fleet subprocess test: pool proxy + roles + streaming relay


def _fleet_loader():
    """Worker-side factory (resolved as tests.test_fleet:_fleet_loader in
    the worker interpreter): a tiny LM with a fleet-enabled decode
    engine, weights deterministic so every worker — and the in-test
    reference engine — holds identical parameters."""
    import jax
    import numpy as np

    from bigdl_tpu.nn.attention import Transformer
    from bigdl_tpu.serving.decode_engine import DecodeConfig
    from bigdl_tpu.serving.inference_model import InferenceModel

    # conftest.py flips this in the TEST process; the worker must sample
    # from the same threefry variant or seeded parity is vacuously false
    jax.config.update("jax_threefry_partitionable", True)
    model = Transformer(vocab_size=32, hidden_size=16, num_heads=2,
                        num_layers=2, dropout=0.0, mode="lm")
    v = model.init(jax.random.PRNGKey(0),
                   np.arange(6, dtype=np.int32)[None])
    im = InferenceModel(model, v, decode=DecodeConfig(
        slots=4, page_size=4, pages_per_slot=4, prompt_chunk=4,
        max_new_tokens=16, eos_id=1, prefill_batch=2,
        prefix_cache_pages=8))
    im.decode_engine.warmup()
    return im


@pytest.mark.slow
def test_fleet_pool_split_streaming_parity(lm):
    """End to end over real worker processes: ServingPool with a
    dedicated prefill worker and a decode worker, a streaming /generate
    through the proxy relay, byte parity against a local reference
    engine built from the same seed."""
    import http.client

    from bigdl_tpu.serving.pool import ServingPool

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pythonpath = os.pathsep.join(
        p for p in [repo_root, os.environ.get("PYTHONPATH")] if p)
    env = {"PYTHONPATH": pythonpath, "BIGDL_TPU_POOL_CPU": "1",
           "JAX_PLATFORMS": "cpu"}
    pool = ServingPool("tests.test_fleet:_fleet_loader", workers=2,
                       batch_size=8, worker_env=env,
                       roles=["prefill", "decode"],
                       supervise_interval_s=0.3)
    pool.start()
    try:
        ref_eng = _engine(lm, max_new_tokens=16, prefix_cache_pages=8)
        _, p2 = _shared_prompts()
        prompt = [int(t) for t in p2]
        kw = dict(max_new_tokens=8, temperature=0.8, top_k=8, top_p=0.9,
                  seed=5)
        ref = ref_eng.static_generate(
            [DecodeRequest(tokens=np.asarray(prompt, np.int32), **kw)])[0]
        ref_eng.stop()

        conn = http.client.HTTPConnection(pool.host, pool.port, timeout=60)
        conn.request(
            "POST", "/generate",
            body=json.dumps(dict(tokens=prompt, stream=True, **kw)).encode(),
            headers={"Content-Type": "application/json",
                     "Connection": "close"})
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("X-Request-Id")
        toks, final = [], None
        while True:
            line = resp.readline()
            if not line:
                break
            ev = json.loads(line)
            if ev.get("done"):
                final = ev
                break
            toks.append(ev["token"])
        conn.close()
        assert final is not None and "error" not in final, final
        assert np.asarray(final["tokens"],
                          np.int32).tobytes() == ref.tokens.tobytes()
        assert toks == [int(t) for t in ref.tokens]

        # the proxy actually split the request and relayed the stream
        assert pool.stats["stream_relays"] >= 1
        assert pool.stats["fleet_split"] >= 1
        with urlreq.urlopen(pool.url + "/health", timeout=10) as r:
            h = json.loads(r.read())
        roles = sorted(w.get("role") for w in h["workers"])
        assert roles == ["decode", "prefill"]
        # the decode worker imported the prefill worker's pages
        decode_w = next(w for w in h["workers"]
                        if w.get("role") == "decode")
        assert decode_w["decode"]["kv_imports"] >= 1
    finally:
        pool.stop()
