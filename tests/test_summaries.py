"""TensorBoard event writer + summary round-trips."""

import numpy as np

from bigdl_tpu.optim.metrics import (SummaryWriter, TrainSummary,
                                     ValidationSummary)
from bigdl_tpu.utils.tbwriter import TensorBoardWriter, read_scalars


class TestTBWriter:
    def test_scalar_roundtrip(self, tmp_path):
        w = TensorBoardWriter(str(tmp_path))
        w.add_scalar("loss", 1.5, 1)
        w.add_scalar("loss", 0.75, 2)
        w.add_scalar("lr", 0.1, 2)
        w.close()
        recs = read_scalars(w.path)
        assert (1, "loss", 1.5) in recs
        assert (2, "lr") == recs[-1][:2]
        assert abs(recs[1][2] - 0.75) < 1e-6

    def test_long_tag_roundtrip(self, tmp_path):
        w = TensorBoardWriter(str(tmp_path))
        tag = "metrics/" + "x" * 200  # > 127 bytes: length is a 2-byte varint
        w.add_scalar(tag, 2.5, 3)
        w.close()
        recs = read_scalars(w.path)
        assert recs == [(3, tag, 2.5)]

    def test_crc_framing_valid(self, tmp_path):
        """Verify the TFRecord framing CRCs — what stock TensorBoard checks
        before parsing."""
        import struct

        from bigdl_tpu.utils.tbwriter import _masked_crc

        w = TensorBoardWriter(str(tmp_path))
        w.add_scalar("x", 3.0, 7)
        w.close()
        data = open(w.path, "rb").read()
        pos = 0
        n_records = 0
        while pos < len(data):
            header = data[pos:pos + 8]
            (length,) = struct.unpack("<Q", header)
            (hcrc,) = struct.unpack_from("<I", data, pos + 8)
            assert hcrc == _masked_crc(header)
            payload = data[pos + 12:pos + 12 + length]
            (pcrc,) = struct.unpack_from("<I", data, pos + 12 + length)
            assert pcrc == _masked_crc(payload)
            pos += 12 + length + 4
            n_records += 1
        assert n_records == 2  # file_version event + one scalar


class TestPreemption:
    def test_sigterm_checkpoints_and_stops(self, tmp_path):
        import os
        import signal
        import threading

        import jax

        from bigdl_tpu.data.dataset import DataSet
        from bigdl_tpu.nn.criterion import MSECriterion
        from bigdl_tpu.nn.layers import Linear
        from bigdl_tpu.nn.module import Sequential
        from bigdl_tpu.optim.checkpoint import latest_checkpoint
        from bigdl_tpu.optim.optimizer import Optimizer
        from bigdl_tpu.optim.trigger import Trigger

        rng = np.random.RandomState(0)
        x = rng.randn(256, 8).astype(np.float32)
        y = (x @ rng.randn(8, 1)).astype(np.float32)
        ckpt_dir = str(tmp_path / "ck")

        opt = (Optimizer(Sequential([Linear(8, 1)]), DataSet.array(x, y),
                         MSECriterion(), batch_size=32)
               .set_end_when(Trigger.max_epoch(2000))
               .set_checkpoint(ckpt_dir, Trigger.every_epoch())
               .set_preemption_checkpoint(signal.SIGUSR1))

        # deliver the signal shortly after training starts
        threading.Timer(1.0, lambda: os.kill(os.getpid(),
                                             signal.SIGUSR1)).start()
        trained = opt.optimize()  # returns instead of running 2000 epochs
        assert trained is not None
        assert latest_checkpoint(ckpt_dir) is not None


class TestSummaryWriter:
    def test_jsonl_and_tb(self, tmp_path):
        w = SummaryWriter(str(tmp_path), "train")
        for i in range(5):
            w.add_scalar("loss", 1.0 / (i + 1), i)
        w.close()
        pairs = w.read_scalar("loss")
        assert len(pairs) == 5 and pairs[0] == (0, 1.0)
        import glob

        assert glob.glob(str(tmp_path / "train" / "events.out.tfevents.*"))

    def test_reference_constructors(self, tmp_path):
        t = TrainSummary(str(tmp_path), "myapp")
        v = ValidationSummary(str(tmp_path), "myapp")
        t.add_scalar("throughput", 100.0, 1)
        v.add_scalar("Top1Accuracy", 0.9, 1)
        t.close()
        v.close()
        assert t.read_scalar("throughput") == [(1, 100.0)]
        assert v.read_scalar("Top1Accuracy") == [(1, 0.9)]


def test_parameter_histograms_via_summary_trigger(tmp_path):
    import jax
    import numpy as np

    from bigdl_tpu import nn, optim
    from bigdl_tpu.data.dataset import ArrayDataSet
    from bigdl_tpu.nn.module import Sequential
    from bigdl_tpu.utils.tbwriter import _masked_crc  # noqa: F401 (import check)

    rng = np.random.RandomState(0)
    x = rng.randn(64, 6).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int32)
    model = Sequential([nn.Linear(6, 8), nn.ReLU(), nn.Linear(8, 2)])
    opt = optim.Optimizer(model, ArrayDataSet(x, y),
                          nn.CrossEntropyCriterion(), batch_size=32)
    opt.set_end_when(optim.Trigger.max_epoch(2))
    opt.set_train_summary(str(tmp_path))
    opt.set_summary_trigger("Parameters",
                            optim.Trigger.several_iteration(2))
    opt.log_every = 100
    opt.optimize()

    # the event file must contain histogram summaries stock TB can read:
    # scan records for a Summary.Value with field 5 (histo)
    import glob
    import struct

    evt = glob.glob(str(tmp_path / "train" / "events.out.tfevents.*"))
    assert evt
    data = open(evt[0], "rb").read()
    assert len(data) > 0

    from bigdl_tpu.utils import proto as P

    found_hist = False
    i = 0
    while i < len(data):
        (ln,) = struct.unpack("<Q", data[i:i + 8])
        payload = data[i + 12:i + 12 + ln]
        i += 12 + ln + 4
        ev = P.parse(payload)
        summ = P.get_bytes(ev, 5)
        if summ:
            val = P.parse(P.get_bytes(P.parse(summ), 1))
            tag = P.get_str(val, 1)
            if tag.startswith("Parameters/") and P.get_bytes(val, 5):
                hist = P.parse(P.get_bytes(val, 5))
                assert P.repeated(hist, 6) and P.repeated(hist, 7)
                found_hist = True
                break
    assert found_hist


def test_summary_trigger_unknown_tag_raises(tmp_path):
    import numpy as np
    import pytest as _pytest

    from bigdl_tpu import nn, optim
    from bigdl_tpu.data.dataset import ArrayDataSet
    from bigdl_tpu.nn.module import Sequential

    opt = optim.Optimizer(Sequential([nn.Linear(2, 2)]),
                          ArrayDataSet(np.zeros((4, 2), np.float32),
                                       np.zeros((4,), np.int32)),
                          nn.CrossEntropyCriterion())
    with _pytest.raises(ValueError, match="Parameters"):
        opt.set_summary_trigger("LearningRate", optim.Trigger.every_epoch())


def test_histogram_of_nonfinite_values_does_not_crash(tmp_path):
    import numpy as np

    from bigdl_tpu.utils.tbwriter import TensorBoardWriter

    w = TensorBoardWriter(str(tmp_path))
    w.add_histogram("p", np.array([1.0, np.nan, np.inf, 2.0]), step=1)
    w.add_histogram("all_bad", np.array([np.nan, np.nan]), step=2)
    w.close()
