"""NNFrames (DataFrame Estimator/Transformer) + Friesian FeatureTable."""

import numpy as np
import pandas as pd

from bigdl_tpu.friesian import FeatureTable
from bigdl_tpu.nnframes import NNClassifier, NNEstimator


def _clf_df(n=160, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 6).astype(np.float32)
    w = rng.randn(6, 3).astype(np.float32)
    y = np.argmax(x @ w, axis=1)
    return pd.DataFrame({"features": list(x), "label": y})


class TestNNFrames:
    def test_classifier_fit_transform(self):
        from bigdl_tpu.nn.criterion import CrossEntropyCriterion
        from bigdl_tpu.nn.layers import Linear, ReLU
        from bigdl_tpu.nn.module import Sequential
        from bigdl_tpu.optim.optim_method import Adam

        df = _clf_df()
        est = (NNClassifier(
            Sequential([Linear(6, 32), ReLU(), Linear(32, 3)]),
            CrossEntropyCriterion())
            .set_max_epoch(15).set_batch_size(32)
            .set_optim_method(Adam(learning_rate=1e-2)))
        model = est.fit(df)
        out = model.transform(df)
        assert "prediction" in out.columns
        acc = (out["prediction"].to_numpy() == df["label"].to_numpy()).mean()
        assert acc > 0.9, acc

    def test_regression_estimator(self):
        from bigdl_tpu.nn.criterion import MSECriterion
        from bigdl_tpu.nn.layers import Linear
        from bigdl_tpu.nn.module import Sequential
        from bigdl_tpu.optim.optim_method import Adam

        rng = np.random.RandomState(1)
        x = rng.randn(128, 4).astype(np.float32)
        y = (x @ rng.randn(4, 1).astype(np.float32))[:, 0]
        df = pd.DataFrame({"features": list(x), "label": y})
        est = (NNEstimator(Sequential([Linear(4, 1)]), MSECriterion())
               .set_max_epoch(40).set_batch_size(32)
               .set_optim_method(Adam(learning_rate=3e-2)))
        model = est.fit(df)
        out = model.transform(df)
        pred = out["prediction"].to_numpy()  # flat numeric column
        assert pred.dtype == np.float32
        mse = float(np.mean((pred - y) ** 2))
        assert mse < 0.1, mse

    def test_multi_column_features(self):
        from bigdl_tpu.nn.criterion import MSECriterion
        from bigdl_tpu.nn.layers import Linear
        from bigdl_tpu.nn.module import Sequential
        from bigdl_tpu.optim.optim_method import SGD

        vals = np.arange(8, dtype=np.float32)
        df = pd.DataFrame({"a": vals, "b": vals / 2.0,
                           "label": vals})
        est = (NNEstimator(Sequential([Linear(2, 1)]), MSECriterion(),
                           features_col=["a", "b"])
               .set_max_epoch(1).set_batch_size(8)
               .set_optim_method(SGD(learning_rate=1e-2)))
        model = est.fit(df)
        out = model.transform(df)
        assert len(out) == 8


class TestFeatureTable:
    def test_string_index_roundtrip(self):
        df = pd.DataFrame({"cat": ["a", "b", "a", "c", "a", "b"],
                           "v": range(6)})
        tbl = FeatureTable.from_pandas(df)
        idx = tbl.gen_string_idx("cat")
        assert idx.mapping["a"] == 1  # most frequent first
        assert idx.size == 4          # 3 cats + OOV
        enc = tbl.encode_string("cat", idx)
        assert enc.df["cat"].tolist() == [1, 2, 1, 3, 1, 2]
        assert idx.encode(["zzz"])[0] == 0  # OOV

    def test_freq_limit_and_category_encode(self):
        df = pd.DataFrame({"cat": ["a"] * 5 + ["b"] * 2 + ["c"]})
        tbl = FeatureTable.from_pandas(df)
        idx = tbl.gen_string_idx("cat", freq_limit=2)
        assert "c" not in idx.mapping
        enc, idx2 = tbl.category_encode("cat")
        assert enc.df["cat"].nunique() == 3

    def test_cross_and_scale(self):
        df = pd.DataFrame({"u": [1, 2, 1], "i": [10, 10, 20],
                           "price": [1.0, 3.0, 5.0]})
        tbl = FeatureTable.from_pandas(df)
        crossed = tbl.cross_columns([["u", "i"]], [100])
        assert "u_i" in crossed.df.columns
        assert crossed.df["u_i"].between(0, 99).all()
        # same inputs hash equal, different differ (overwhelmingly)
        assert crossed.df["u_i"][0] != crossed.df["u_i"][1]
        scaled, stats = tbl.min_max_scale("price")
        assert scaled.df["price"].min() == 0.0
        assert scaled.df["price"].max() == 1.0
        assert stats["price"] == (1.0, 5.0)

    def test_hist_seq(self):
        df = pd.DataFrame({
            "user": [1, 1, 1, 2, 2],
            "item": [11, 12, 13, 21, 22],
            "ts": [1, 2, 3, 1, 2],
        })
        tbl = FeatureTable.from_pandas(df).add_hist_seq(
            "user", ["item"], "ts", min_len=1, max_len=3)
        # first event per user has no history -> dropped
        assert len(tbl) == 3
        seqs = {tuple(s) for s in tbl.df["item_hist_seq"]}
        assert (0, 0, 11) in seqs and (0, 11, 12) in seqs

    def test_negative_samples(self):
        df = pd.DataFrame({"user": [1, 2], "item": [3, 4]})
        tbl = FeatureTable.from_pandas(df).add_negative_samples(
            item_size=50, item_col="item", neg_num=2, seed=0)
        assert len(tbl) == 6
        assert (tbl.df["label"] == 1).sum() == 2
        negs = tbl.df[tbl.df["label"] == 0]
        pos_items = df["item"].tolist() * 2
        assert all(n != p for n, p in zip(negs["item"], pos_items))

    def test_join_select_fillna(self):
        a = FeatureTable.from_pandas(
            pd.DataFrame({"k": [1, 2], "x": [1.0, np.nan]}))
        b = FeatureTable.from_pandas(pd.DataFrame({"k": [1, 2],
                                                   "y": [5, 6]}))
        j = a.fillna(0.0, ["x"]).join(b, on="k")
        assert j.df["x"].tolist() == [1.0, 0.0]
        assert set(j.select("k", "y").df.columns) == {"k", "y"}


def test_target_and_count_encode():
    """Smoothed target encoding (CTR staple) + popularity counts."""
    import pandas as pd

    from bigdl_tpu.friesian.table import FeatureTable

    df = pd.DataFrame({
        "cat": ["a", "a", "a", "b", "b", "c"],
        "y":   [1.0, 1.0, 0.0, 0.0, 0.0, 1.0],
    })
    t = FeatureTable.from_pandas(df)
    out, maps = t.target_encode("cat", "y", smooth=2.0)
    g = df["y"].mean()                                   # 0.5
    # a: (2 + 2*0.5) / (3 + 2) = 0.6 ; b: (0 + 1)/(2+2)=0.25
    got = out.to_pandas()
    np.testing.assert_allclose(got[got.cat == "a"]["cat_te"].iloc[0], 0.6)
    np.testing.assert_allclose(got[got.cat == "b"]["cat_te"].iloc[0], 0.25)
    # unseen categories fall back to the global mean via the mapping
    np.testing.assert_allclose(maps["cat"]["default"], g)

    out2 = t.count_encode("cat").to_pandas()
    assert out2[out2.cat == "a"]["cat_count"].iloc[0] == 3
    assert out2[out2.cat == "c"]["cat_count"].iloc[0] == 1


class TestNNFramesXShards:
    """nnframes over DISTRIBUTED frames (VERDICT r3 weak #6): XShards and
    ShardedFeatureTable are first-class fit/transform inputs."""

    def test_fit_on_xshards_matches_pandas(self):
        from bigdl_tpu.data.shards import XShards
        from bigdl_tpu.nn.criterion import CrossEntropyCriterion
        from bigdl_tpu.nn.layers import Linear, ReLU
        from bigdl_tpu.nn.module import Sequential
        from bigdl_tpu.optim.optim_method import Adam

        df = _clf_df()

        def build():
            return (NNClassifier(
                Sequential([Linear(6, 32), ReLU(), Linear(32, 3)]),
                CrossEntropyCriterion())
                .set_max_epoch(10).set_batch_size(32)
                .set_optim_method(Adam(learning_rate=1e-2)))

        m_pd = build().fit(df)
        m_xs = build().fit(XShards.partition(df, 4))
        # single-process: shard concat == original frame, so training is
        # bit-identical
        w_pd = np.asarray(
            m_pd.trained.variables["params"]["0_Linear"]["weight"])
        w_xs = np.asarray(
            m_xs.trained.variables["params"]["0_Linear"]["weight"])
        np.testing.assert_allclose(w_pd, w_xs, rtol=1e-6)

    def test_transform_preserves_shards(self):
        from bigdl_tpu.data.shards import XShards
        from bigdl_tpu.friesian.sharded import ShardedFeatureTable
        from bigdl_tpu.nn.criterion import CrossEntropyCriterion
        from bigdl_tpu.nn.layers import Linear, ReLU
        from bigdl_tpu.nn.module import Sequential
        from bigdl_tpu.optim.optim_method import Adam

        df = _clf_df()
        est = (NNClassifier(
            Sequential([Linear(6, 16), ReLU(), Linear(16, 3)]),
            CrossEntropyCriterion())
            .set_max_epoch(5).set_batch_size(32)
            .set_optim_method(Adam(learning_rate=1e-2)))
        model = est.fit(df)

        xs = XShards.partition(df, 4)
        out = model.transform(xs)
        assert isinstance(out, XShards) and out.num_partitions() == 4
        merged = pd.concat(list(out), ignore_index=True)
        single = model.transform(df)
        np.testing.assert_array_equal(
            merged["prediction"].to_numpy(),
            single["prediction"].to_numpy())

        sft_out = model.transform(
            ShardedFeatureTable(XShards.partition(df, 4)))
        assert isinstance(sft_out, ShardedFeatureTable)
        np.testing.assert_array_equal(
            sft_out.to_table().df["prediction"].to_numpy(),
            single["prediction"].to_numpy())
