"""Chronos-equivalent tests: TSDataset pipeline, forecasters converge on a
synthetic seasonal series, detectors flag planted anomalies (reference:
chronos pytest over tiny synthetic series — SURVEY.md §5)."""

import numpy as np
import pandas as pd
import pytest

from bigdl_tpu.forecast import (
    AEDetector, DBScanDetector, LSTMForecaster, NBeatsForecaster,
    Seq2SeqForecaster, TCNForecaster, ThresholdDetector, TSDataset,
)
from bigdl_tpu.forecast.autoformer import Autoformer, series_decomp


def _series(n=400, freq=24, seed=0):
    rs = np.random.RandomState(seed)
    t = np.arange(n)
    y = np.sin(2 * np.pi * t / freq) + 0.05 * rs.randn(n)
    return pd.DataFrame({
        "dt": pd.date_range("2025-01-01", periods=n, freq="h"),
        "value": y.astype(np.float32),
    })


def _tsdata(lookback=24, horizon=4, **kw):
    df = _series(**kw)
    ts = (TSDataset.from_pandas(df, dt_col="dt", target_col="value")
          .impute().scale().roll(lookback, horizon))
    return ts


def test_tsdataset_pipeline():
    df = _series(100)
    df.loc[10, "value"] = np.nan
    ts = (TSDataset.from_pandas(df, dt_col="dt", target_col="value")
          .deduplicate().impute().gen_dt_feature().scale()
          .roll(12, 3))
    x, y = ts.to_numpy()
    assert x.shape[1:] == (12, 1 + 5)  # target + 5 dt features
    assert y.shape[1:] == (3, 1)
    assert np.isfinite(x).all() and np.isfinite(y).all()
    (xt, yt), (xv, yv), (xe, ye) = ts.train_val_test_split(0.1, 0.1)
    assert len(xt) + len(xv) + len(xe) == len(x)


def test_tsdataset_resample_and_multi_id():
    df = _series(96)
    df["id"] = np.where(np.arange(96) < 48, "a", "b")
    ts = TSDataset.from_pandas(df, dt_col="dt", target_col="value",
                               id_col="id").resample("2h")
    assert len(ts.df) == 48  # halved per id


@pytest.mark.parametrize("cls,kw", [
    (TCNForecaster, dict(num_channels=(16, 16))),
    (LSTMForecaster, dict(hidden_dim=32, layer_num=1)),
    (Seq2SeqForecaster, dict(lstm_hidden_dim=32)),
    (NBeatsForecaster, dict(stacks=1, blocks_per_stack=2, hidden_units=32)),
])
def test_forecaster_learns_sine(cls, kw):
    ts = _tsdata()
    x, y = ts.to_numpy()
    f = cls(past_seq_len=24, future_seq_len=4, input_feature_num=1,
            output_feature_num=1, lr=5e-3, **kw)
    f.fit((x, y), epochs=12, batch_size=64)
    res = f.evaluate((x, y), metrics=["mse", "mae"])
    # scaled sine: predicting the mean gives mse ~1.0
    assert res["mse"] < 0.25, res
    pred = f.predict(x[:8])
    assert pred.shape == (8, 4, 1)


def test_forecaster_fit_parallelism_routes_to_layout_driver():
    # fit(parallelism=) is the declarative-layout carry
    # (docs/parallelism.md §Declarative layouts): same Forecaster API,
    # the GSPMD driver underneath, predict/evaluate/save unchanged
    ts = _tsdata()
    x, y = ts.to_numpy()
    f = TCNForecaster(past_seq_len=24, future_seq_len=4,
                      input_feature_num=1, output_feature_num=1,
                      num_channels=(8,), lr=5e-3)
    f.fit((x, y), epochs=2, batch_size=16, parallelism="dp")
    stats = f._layout_stats
    assert stats["losses"] and stats["mesh"]["data"] >= 1
    assert np.isfinite(stats["losses"][-1])
    pred = f.predict(x[:4])
    assert pred.shape == (4, 4, 1)
    assert np.isfinite(f.evaluate((x, y))["mse"])


def test_forecaster_fit_parallelism_rejects_validation_data():
    ts = _tsdata()
    x, y = ts.to_numpy()
    f = TCNForecaster(past_seq_len=24, future_seq_len=4,
                      input_feature_num=1, output_feature_num=1,
                      num_channels=(8,))
    with pytest.raises(ValueError, match="validation_data"):
        f.fit((x, y), epochs=1, batch_size=16, parallelism="dp",
              validation_data=(x, y))


def test_forecaster_save_load(tmp_path):
    ts = _tsdata()
    x, y = ts.to_numpy()
    f = TCNForecaster(past_seq_len=24, future_seq_len=4,
                      input_feature_num=1, output_feature_num=1,
                      num_channels=(8,), lr=5e-3)
    f.fit((x, y), epochs=3, batch_size=64)
    ref = f.predict(x[:4])
    f.save(str(tmp_path / "m"))

    f2 = TCNForecaster(past_seq_len=24, future_seq_len=4,
                       input_feature_num=1, output_feature_num=1,
                       num_channels=(8,), lr=5e-3)
    f2.load(str(tmp_path / "m"))
    np.testing.assert_allclose(f2.predict(x[:4]), ref, rtol=1e-5, atol=1e-5)


def test_autoformer_shapes_and_decomp():
    import jax

    x = np.random.RandomState(0).randn(4, 48, 2).astype(np.float32)
    seasonal, trend = series_decomp(np.asarray(x), 25)
    np.testing.assert_allclose(np.asarray(seasonal + trend), x, atol=1e-5)

    m = Autoformer(in_dim=2, out_dim=2, lookback=48, horizon=8,
                   hidden=32, heads=2, enc_layers=1, dec_layers=1, ff=64)
    v = m.init(jax.random.PRNGKey(0), np.asarray(x))
    out, _ = m.apply(v, np.asarray(x))
    assert out.shape == (4, 8, 2)
    assert np.isfinite(np.asarray(out)).all()


def test_threshold_detector():
    rs = np.random.RandomState(0)
    y = rs.randn(500) * 0.1
    y[[50, 200]] = 5.0
    idx = ThresholdDetector(threshold=(-1.0, 1.0)).anomaly_indexes(y)
    assert set([50, 200]) <= set(idx.tolist())


def test_ae_detector():
    rs = np.random.RandomState(1)
    t = np.arange(600)
    y = np.sin(2 * np.pi * t / 24) + 0.02 * rs.randn(600)
    y[300] = 4.0  # planted spike
    det = AEDetector(roll_len=24, ratio=0.005, epochs=15).fit(y)
    idx = det.anomaly_indexes(y)
    assert any(abs(int(i) - 300) <= 24 for i in idx)


def test_dbscan_detector():
    rs = np.random.RandomState(2)
    y = np.concatenate([rs.randn(300) * 0.05, [9.0, -9.0]])
    idx = DBScanDetector(eps=0.3, min_samples=4).anomaly_indexes(y)
    assert set([300, 301]) <= set(idx.tolist())


def test_xshards_tsdataset_matches_local_and_shares_scaler():
    import pandas as pd

    from bigdl_tpu.data.shards import XShards
    from bigdl_tpu.forecast import TSDataset, XShardsTSDataset

    rng = np.random.RandomState(0)
    def mk(id_, n, scale):
        return pd.DataFrame({
            "t": pd.date_range("2024-01-01", periods=n, freq="h"),
            "v": rng.randn(n).astype(np.float32) * scale + scale,
            "id": id_,
        })

    df_a, df_b = mk("a", 60, 1.0), mk("b", 60, 10.0)
    shards = XShards([df_a, df_b])

    dist = (XShardsTSDataset.from_xshards(shards, "t", "v", id_col="id")
            .impute().scale().roll(12, 3))
    xd, yd = dist.to_numpy()

    local = (TSDataset.from_pandas(pd.concat([df_a, df_b]), "t", "v",
                                   id_col="id")
             .impute().scale().roll(12, 3))
    xl, yl = local.to_numpy()

    assert xd.shape == xl.shape and yd.shape == yl.shape
    # same global scaler stats -> identical windows (row order may differ
    # per shard, but both group by id so ordering matches here)
    np.testing.assert_allclose(xd, xl, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(dist.scaler.mean_), np.asarray(local.scaler.mean_),
        rtol=1e-6)

    sh = dist.to_xshards()
    assert sh.num_partitions() == 2
    parts = sh.collect()
    assert sum(p[0].shape[0] for p in parts) == xd.shape[0]


def test_xshards_tsdataset_short_shard_skipped():
    import pandas as pd

    from bigdl_tpu.data.shards import XShards
    from bigdl_tpu.forecast import XShardsTSDataset

    rng = np.random.RandomState(1)
    def mk(id_, n):
        return pd.DataFrame({
            "t": pd.date_range("2024-01-01", periods=n, freq="h"),
            "v": rng.randn(n).astype(np.float32), "id": id_})

    dist = (XShardsTSDataset.from_xshards(
        XShards([mk("a", 60), mk("b", 5)]), "t", "v", id_col="id")
        .roll(12, 3))
    x, y = dist.to_numpy()  # only shard a contributes, no raise
    assert x.shape[0] == 60 - 12 - 3 + 1
    assert dist.num_partitions() == 2
    assert dist.to_xshards().num_partitions() == 1


def test_arima_forecaster_recovers_ar_process():
    """AR(2) data: ARIMA(2,0,0) must beat the naive last-value forecast
    and roughly recover the coefficients' predictions."""
    rs = np.random.RandomState(0)
    n = 600
    y = np.zeros(n)
    for t in range(2, n):
        y[t] = 0.6 * y[t - 1] - 0.3 * y[t - 2] + rs.randn() * 0.1
    from bigdl_tpu.forecast import ARIMAForecaster

    f = ARIMAForecaster(p=2, d=0, q=0).fit(y[:500])
    res = f.evaluate(y[500:520], metrics=("mse", "mae"))
    naive = float(np.mean((y[500:520] - y[499]) ** 2))
    assert res["mse"] < naive


def test_arima_with_differencing_tracks_trend():
    rs = np.random.RandomState(1)
    t = np.arange(400, dtype=np.float64)
    y = 0.5 * t + 3.0 + np.cumsum(rs.randn(400) * 0.05)
    from bigdl_tpu.forecast import ARIMAForecaster

    f = ARIMAForecaster(p=2, d=1, q=1).fit(y[:380])
    fc = f.predict(20)
    # a d=1 model must keep following the linear trend
    assert abs(fc[-1] - y[399]) < 5.0
    assert np.all(np.diff(fc) > 0)


def test_prophet_native_trend_and_seasonality():
    """Native Prophet-class model: recovers a piecewise-linear trend with a
    weekly Fourier seasonality and extrapolates both (the stub is gone —
    VERDICT r2 weak #4)."""
    from bigdl_tpu.forecast import ProphetForecaster

    n = 200
    t = np.arange(n, dtype=np.float64)
    # slope change at t=120 + weekly pattern + noise
    trend = 0.5 * t + np.where(t > 120, -0.4 * (t - 120), 0.0)
    season = 3.0 * np.sin(2 * np.pi * t / 7) + 1.5 * np.cos(4 * np.pi * t / 7)
    rs = np.random.RandomState(0)
    y = trend + season + 0.3 * rs.randn(n)

    f = ProphetForecaster(n_changepoints=10, seasonalities={7: 3}).fit(y)
    horizon = 28
    future_t = np.arange(n, n + horizon, dtype=np.float64)
    truth = (0.5 * future_t - 0.4 * (future_t - 120)
             + 3.0 * np.sin(2 * np.pi * future_t / 7)
             + 1.5 * np.cos(4 * np.pi * future_t / 7))
    fc = f.predict(horizon)
    assert fc.shape == (horizon,)
    err = np.abs(fc - truth).mean()
    assert err < 1.5, err                      # follows trend + seasonality
    m = f.evaluate(truth, metrics=("mse", "mae", "smape"))
    assert m["mae"] < 1.5

    # pandas ds/y DataFrame surface (the prophet convention)
    import pandas as pd

    df = pd.DataFrame({"ds": t, "y": y})
    f2 = ProphetForecaster(n_changepoints=10, seasonalities={7: 3}).fit(df)
    np.testing.assert_allclose(f2.predict(5), f.predict(5), rtol=1e-8)

    # too-short series raises cleanly
    import pytest

    with pytest.raises(ValueError):
        ProphetForecaster().fit(np.arange(10.0))


def test_forecaster_optimized_predict_variants():
    """reference predict_with_onnx / forecaster.quantize analogs: traced
    bf16 and weight-only int8 predict stay close to the plain path."""
    from bigdl_tpu.forecast import TCNForecaster

    ts = _tsdata()
    x_all, y_all = ts.to_numpy()

    f = TCNForecaster(past_seq_len=24, future_seq_len=4,
                      input_feature_num=1, output_feature_num=1,
                      num_channels=(8,))
    f.fit((x_all, y_all), epochs=2, batch_size=64)
    x = x_all[:8]
    base = f.predict(x)

    for prec in ("bf16", "int8_wo"):
        out = f.optimize_predict(prec).predict_with_optimized(x)
        assert out.shape == base.shape
        denom = np.abs(base).max() + 1e-6
        assert np.abs(out - base).max() / denom < 0.1, prec

    import pytest

    g = TCNForecaster(past_seq_len=24, future_seq_len=4,
                      input_feature_num=1, output_feature_num=1)
    with pytest.raises(RuntimeError):
        g.predict_with_optimized(x)
