"""Single evidence-writer discipline (VERDICT r4 item 6).

Round 4's dual-watcher incident: two long-lived watchers double-appended
the evidence trail for ~80 minutes.  The repo now has EXACTLY ONE watcher
entry point (``chipup.py``) and it takes an exclusive flock, so a second
instance exits immediately.  These tests make the regression impossible:
CI fails if a second watcher script reappears or the lock stops excluding.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# scripts that loop appending to BENCH_attempts.jsonl; exactly one allowed
RETIRED_WATCHERS = ("bench_watch.py", "chipup_r04.py", "chipup_r05.py")


def test_exactly_one_watcher_entry_point():
    assert os.path.exists(os.path.join(REPO, "chipup.py"))
    for name in RETIRED_WATCHERS:
        assert not os.path.exists(os.path.join(REPO, name)), (
            f"{name} reintroduces a second evidence writer; fold it into "
            "chipup.py (VERDICT r4 Weak #7)")


def test_makefile_watch_uses_chipup():
    with open(os.path.join(REPO, "Makefile")) as f:
        mk = f.read()
    assert "chipup.py" in mk
    assert "bench_watch.py" not in mk


def test_flock_excludes_second_instance(tmp_path):
    lock = str(tmp_path / "chipup.lock")
    attempts = str(tmp_path / "attempts.jsonl")
    env = dict(os.environ, CHIPUP_LOCK=lock, CHIPUP_ATTEMPTS=attempts,
               CHIPUP_PROBE_TIMEOUT="1", CHIPUP_INTERVAL="60",
               CHIPUP_STRAY_SWEEP="0")  # tests must not kill real procs
    first = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "chipup.py")], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        # wait for the first instance to take the lock (it logs
        # chipup_start to the attempts trail right after acquiring)
        deadline = time.time() + 20
        while time.time() < deadline:
            if os.path.exists(attempts):
                with open(attempts) as f:
                    if any(json.loads(ln).get("kind") == "chipup_start"
                           for ln in f if ln.strip()):
                        break
            time.sleep(0.1)
        else:
            raise AssertionError("first chipup never logged chipup_start")
        second = subprocess.run(
            [sys.executable, os.path.join(REPO, "chipup.py")], env=env,
            capture_output=True, text=True, timeout=30)
        assert second.returncode == 1, second.stdout + second.stderr
        assert "chipup_duplicate" in second.stdout
        assert first.poll() is None, "first instance must still be running"
    finally:
        first.terminate()
        first.wait(timeout=10)
