"""TRUE multi-process distributed training: two OS processes rendezvous via
jax.distributed and run the ZeRO-1 step with cross-process collectives.

This is the step beyond the in-process 8-device simulation (conftest): the
reference's ``local-cluster`` Spark mode analog (SURVEY.md §5)."""

import pytest
import os
import socket
import subprocess
import sys
import textwrap

pytestmark = pytest.mark.slow  # multi-process/serving integration: excluded from the quick test-fast loop


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]

WORKER = textwrap.dedent("""
    import os
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    from bigdl_tpu.data.dataset import ArrayDataSet
    from bigdl_tpu import nn
    from bigdl_tpu.nn.criterion import MSECriterion
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.runtime.engine import init_engine

    init_engine()
    assert jax.process_count() == 2, jax.process_count()
    rs = np.random.RandomState(0)
    w_true = np.asarray([[2.0], [-1.0]], np.float32)
    x = rs.rand(128, 2).astype(np.float32)
    y = x @ w_true
    model = nn.Linear(2, 1)
    opt = (Optimizer(model, ArrayDataSet(x, y), MSECriterion(), batch_size=32)
           .set_optim_method(SGD(learning_rate=0.4))
           .set_end_when(Trigger.max_epoch(20)))
    trained = opt.optimize()
    w = np.asarray(trained.variables["params"]["weight"])
    err = float(np.abs(w - w_true).max())
    assert err < 0.1, err
    print(f"RANK{jax.process_index()}_ERR={err:.6f}")
""")


def test_two_process_distributed_training(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    procs = []
    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pythonpath = os.pathsep.join(
        p for p in [repo_root, os.environ.get("PYTHONPATH")] if p)
    try:
        for r in range(2):
            env = dict(os.environ,
                       BIGDL_TPU_COORDINATOR=f"127.0.0.1:{port}",
                       BIGDL_TPU_NUM_PROCESSES="2",
                       BIGDL_TPU_PROCESS_ID=str(r),
                       JAX_PLATFORMS="cpu",
                       PYTHONPATH=pythonpath)
            env.pop("XLA_FLAGS", None)  # one device per process
            procs.append(subprocess.Popen(
                [sys.executable, str(script)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
        outs = []
        for p in procs:
            try:
                outs.append(p.communicate(timeout=420)[0])
            except subprocess.TimeoutExpired:
                p.kill()
                outs.append(p.communicate()[0])
        codes = [p.returncode for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert codes == [0, 0], f"exit {codes}\n--- rank0:\n{outs[0]}\n--- rank1:\n{outs[1]}"
    # both ranks converged to the same weights (collectives kept them synced)
    errs = sorted(line for o in outs for line in o.splitlines()
                  if "_ERR=" in line)
    assert len(errs) == 2
    assert errs[0].split("=")[1] == errs[1].split("=")[1], errs
