"""Multi-host sharded ingest specs (ISSUE 15, docs/data.md §Multi-host
ingest): per-host sharded streaming reconstructs the 1-process epoch
byte-identically (no dup / no loss), elastic restart mid-epoch keeps
plan-order determinism (PR 7's resharded ownership math through the
streaming pipeline, augmentation geometry keyed by dataset index), the
double-buffered device dispatch window, worker autosizing, honest
measured-window stage rates, and the backpressure/HELP observability
surface."""

import numpy as np
import pytest

from bigdl_tpu.data.dataset import batch_index_plan, resharded_batch_index_plan
from bigdl_tpu.data.pipeline import autotune_workers, dispatch_to_device
from bigdl_tpu.data.records import RecordDataSet, write_records
from bigdl_tpu.data.vision import AugmentedRecordImages, stream_jpeg_batches
from bigdl_tpu.optim.metrics import Metrics

RS = np.random.RandomState(15)
MEAN = (120.0, 110.0, 100.0)
STD = (60.0, 61.0, 62.0)


@pytest.fixture
def rec(tmp_path):
    x = RS.rand(80, 4, 4, 3).astype(np.float32)
    y = RS.randint(0, 7, 80).astype(np.int32)
    p = str(tmp_path / "train.btrec")
    write_records(p, {"x": x, "y": y})
    return p, x, y


@pytest.fixture
def img_rec(tmp_path):
    xs = RS.randint(0, 255, (96, 36, 36, 3), np.uint8)
    ys = RS.randint(0, 10, 96).astype(np.int32)
    p = str(tmp_path / "imgs.btrec")
    write_records(p, {"image": xs, "label": ys})
    return p, xs, ys


def _snap(mb):
    return {k: np.array(v) for k, v in mb.items()}


def _interleave_check(global_batches, host_batches, pc):
    """Global batch row j must equal host j%pc's row j//pc — the stride-
    shard contract that makes N hosts' streams concatenate to exactly the
    1-process plan order (no dup, no loss, byte-identical)."""
    n_b = len(global_batches)
    assert all(len(hb) == n_b for hb in host_batches)
    for b in range(n_b):
        for key in global_batches[b]:
            g = global_batches[b][key]
            for j in range(len(g)):
                h = host_batches[j % pc][b][key]
                np.testing.assert_array_equal(g[j], h[j // pc])


# ---------------------------------------------------------------------------
# sharded feed parity: no dup / no loss / byte-identical reconstruction
# ---------------------------------------------------------------------------

def test_records_two_host_streams_reconstruct_global_epoch(rec):
    p, _, _ = rec
    ds = RecordDataSet(p)
    ref = [_snap(mb) for mb in ds.batches(20, shuffle=True, seed=9,
                                          epoch=2)]
    hosts = []
    for pid in range(2):
        hosts.append([_snap(mb) for mb in ds.stream_batches(
            20, shuffle=True, seed=9, epoch=2, process_id=pid,
            process_count=2, workers=2)])
    assert len(ref) == 4  # 80 rows / global batch 20
    _interleave_check(ref, hosts, 2)
    ds.close()


def test_augmented_two_host_streams_reconstruct_global_epoch(img_rec):
    """Random crop + flip: geometry is keyed by DATASET INDEX, so each
    host applies exactly the augmentation the 1-process run would —
    sharded streams reconstruct the global epoch byte-identically."""
    p, _, _ = img_rec
    ds = AugmentedRecordImages(p, (24, 24), MEAN, STD, resize_hw=(30, 30),
                               random_crop=True, random_flip=True)
    ref = [_snap(mb) for mb in ds.batches(16, shuffle=True, seed=4,
                                          epoch=1)]
    hosts = []
    for pid in range(2):
        hosts.append([_snap(mb) for mb in ds.stream_batches(
            16, shuffle=True, seed=4, epoch=1, process_id=pid,
            process_count=2, workers=3)])
    assert len(ref) == 6
    _interleave_check(ref, hosts, 2)
    ds.close()


def test_sharded_stream_equals_serial_per_host(img_rec):
    """The per-host invariant the tentpole names: serial
    ``batches(process_id=...)`` and sharded ``stream_batches`` are
    byte-identical from one geometry RNG."""
    p, _, _ = img_rec
    ds = AugmentedRecordImages(p, (24, 24), MEAN, STD, resize_hw=(30, 30),
                               random_crop=True, random_flip=True)
    for pid in range(2):
        ref = [_snap(mb) for mb in ds.batches(
            32, shuffle=True, seed=11, epoch=3, process_id=pid,
            process_count=2)]
        got = [_snap(mb) for mb in ds.stream_batches(
            32, shuffle=True, seed=11, epoch=3, process_id=pid,
            process_count=2, workers=2)]
        assert len(ref) == len(got) == 3  # 48 local rows / 16 per host
        for r, g in zip(ref, got):
            assert set(r) == set(g)
            for k in r:
                np.testing.assert_array_equal(r[k], g[k])
    ds.close()


def test_jpeg_stream_sharded_reconstructs_global_epoch(tmp_path):
    import io

    from PIL import Image

    from bigdl_tpu.native import lib as nat

    if not (nat.available() and nat.jpeg_available()):
        pytest.skip("native libjpeg unavailable")
    srcs = []
    for i in range(24):
        buf = io.BytesIO()
        Image.fromarray(RS.randint(0, 255, (40, 40, 3), np.uint8)).save(
            buf, "JPEG", quality=92)
        srcs.append(buf.getvalue())
    labels = np.arange(24, dtype=np.int32)
    kw = dict(out_hw=(24, 24), mean=MEAN, std=STD, resize_hw=(32, 32),
              random_crop=True, random_flip=True, shuffle=True, seed=6,
              epoch=0, labels=labels)
    ref = [_snap(mb) for mb in stream_jpeg_batches(srcs, 8, **kw)]
    hosts = []
    for pid in range(2):
        hosts.append([_snap(mb) for mb in stream_jpeg_batches(
            srcs, 8, process_id=pid, process_count=2, workers=2, **kw)])
    assert len(ref) == 3
    _interleave_check(ref, hosts, 2)


# ---------------------------------------------------------------------------
# elastic restart mid-epoch: plan-order determinism across a pc change
# ---------------------------------------------------------------------------

def test_resharded_stream_matches_resharded_serial(rec):
    p, _, _ = rec
    ds = RecordDataSet(p)
    kw = dict(trained_batches=2, old_process_count=1, shuffle=True,
              seed=3, epoch=1, process_id=0, process_count=2)
    ref = [_snap(mb) for mb in ds.resharded_batches(20, **kw)]
    got = [_snap(mb) for mb in ds.resharded_stream_batches(
        20, workers=2, **kw)]
    assert len(ref) == len(got) == 2  # (80 - 2*20) remaining / 20 global
    for r, g in zip(ref, got):
        assert set(r) == set(g)
        for k in r:
            np.testing.assert_array_equal(r[k], g[k])
    ds.close()


def test_restart_mid_epoch_determinism_across_process_change(img_rec):
    """The restart-mid-epoch determinism spec: an epoch trained k batches
    by 1 process and finished by 2 re-uses PR 7's resharded ownership
    math — every remaining image is decoded exactly once across the new
    hosts, with BYTE-IDENTICAL pixels to the uninterrupted epoch (the
    index-keyed geometry survives the process-count change)."""
    p, _, _ = img_rec
    n, bs, trained = 96, 16, 2
    ds = AugmentedRecordImages(p, (24, 24), MEAN, STD, resize_hw=(30, 30),
                               random_crop=True, random_flip=True)
    kw = dict(shuffle=True, seed=8, epoch=5)
    # reference: the uninterrupted 1-process epoch, pixels by dataset index
    ref_px = {}
    plan = batch_index_plan(n, bs, **kw)
    for mb, (sel, _) in zip(ds.batches(bs, **kw), plan):
        for j, i in enumerate(sel):
            ref_px[int(i)] = (np.array(mb["input"][j]),
                              int(mb["target"][j]))
    # the examples the interrupted run already covered
    done = {int(i)
            for sel, _ in list(batch_index_plan(n, bs, **kw))[:trained]
            for i in sel}
    remaining = set(ref_px) - done
    # resume under process_count=2: a FRESH dataset object per host (a
    # restart has no in-memory state to lean on)
    seen = {}
    for pid in range(2):
        ds2 = AugmentedRecordImages(p, (24, 24), MEAN, STD,
                                    resize_hw=(30, 30), random_crop=True,
                                    random_flip=True)
        plan2 = resharded_batch_index_plan(
            n, bs, trained_batches=trained, old_process_count=1,
            process_id=pid, process_count=2, **kw)
        stream = ds2.resharded_stream_batches(
            bs, trained_batches=trained, old_process_count=1,
            process_id=pid, process_count=2, workers=2, **kw)
        for mb, (sel, n_real) in zip(stream, plan2):
            for j, i in enumerate(sel[:n_real]):
                assert int(i) not in seen, "duplicate across hosts"
                seen[int(i)] = (np.array(mb["input"][j]),
                                int(mb["target"][j]))
        ds2.close()
    assert set(seen) == remaining, "dup/loss in the resharded remainder"
    for i, (px, lb) in seen.items():
        np.testing.assert_array_equal(px, ref_px[i][0])
        assert lb == ref_px[i][1]
    ds.close()


# ---------------------------------------------------------------------------
# early errors: non-divisible geometries reject at call time
# ---------------------------------------------------------------------------

def test_non_divisible_global_batch_rejected_early(rec, img_rec):
    p, _, _ = rec
    ds = RecordDataSet(p)
    with pytest.raises(ValueError, match=r"10.*3"):
        ds.stream_batches(10, process_id=0, process_count=3)
    with pytest.raises(ValueError, match=r"10.*3"):
        ds.steps_per_epoch(10, process_count=3)
    ds.close()
    ip, _, _ = img_rec
    ids = AugmentedRecordImages(ip, (24, 24), MEAN, STD)
    with pytest.raises(ValueError, match=r"16.*5"):
        ids.stream_batches(16, process_id=0, process_count=5)
    ids.close()
    with pytest.raises(ValueError, match=r"8.*3"):
        stream_jpeg_batches([b"x"] * 24, 8, (24, 24), MEAN, STD,
                            resize_hw=(32, 32), process_id=0,
                            process_count=3)


# ---------------------------------------------------------------------------
# double-buffered dispatch
# ---------------------------------------------------------------------------

def test_dispatch_double_buffer_overlaps_and_stays_correct(rec):
    """The transfer window keeps 2 puts in flight (overlap counter > 0),
    the in-flight gauge drains to 0, and every device batch still matches
    the serial epoch — the slot-reuse aliasing invariant under the new
    release-at-next-issue rule."""
    import jax

    p, _, _ = rec
    ds = RecordDataSet(p)
    m = Metrics()
    stream = ds.stream_batches(10, shuffle=True, seed=2, epoch=0,
                               workers=2, ring_depth=2, raw_depth=1,
                               metrics=m)
    devs = list(dispatch_to_device(
        stream, lambda mb: (jax.device_put(np.asarray(mb["input"])),
                            jax.device_put(np.asarray(mb["target"]))),
        size=2, metrics=m))
    ref = list(ds.batches(10, shuffle=True, seed=2, epoch=0))
    assert len(devs) == len(ref) == 8
    for (xd, yd), mb in zip(devs, ref):
        np.testing.assert_array_equal(np.asarray(xd), mb["input"])
        np.testing.assert_array_equal(np.asarray(yd), mb["target"])
    snap = m.snapshot()
    assert snap["counters"]["data.dispatch_overlapped_total"] > 0
    assert snap["gauges"]["data.dispatch.in_flight"] == 0  # drained
    ds.close()


def test_accelerator_path_defers_slot_release_past_next_pull(rec,
                                                             monkeypatch):
    """On accelerator backends the stream's post-yield auto-release fires
    when the consumer pulls batch k+1 — BEFORE transfer k is synced — so
    the dispatch stage must take ownership of the release
    (``RingBatch.defer_release``) and free slot k only at its drain
    point.  This spec pins the ordering: at the issue of put k, exactly
    max(0, k-1) slots have been released (slot k-1 frees during put k,
    after the sync), never k — which is what the pre-fix auto-release
    would produce."""
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    p, _, _ = rec
    ds = RecordDataSet(p)
    stream = ds.stream_batches(10, shuffle=True, seed=5, epoch=0,
                               workers=2, ring_depth=2, raw_depth=1)
    released = []
    orig = stream.ring.release
    monkeypatch.setattr(stream.ring, "release",
                        lambda s: (released.append(s), orig(s))[1])
    snapshots = []

    def put(mb):
        snapshots.append(len(released))
        # copy before device_put: the real accelerator DMA copies; on the
        # CPU test backend a zero-copy of the (later-recycled) slot would
        # alias — the copy keeps this a pure release-ORDERING spec
        return (jax.device_put(np.array(mb["input"])),
                jax.device_put(np.array(mb["target"])))

    devs = list(dispatch_to_device(stream, put, size=2))
    ref = list(ds.batches(10, shuffle=True, seed=5, epoch=0))
    assert len(devs) == len(ref) == 8
    for (xd, yd), mb in zip(devs, ref):
        np.testing.assert_array_equal(np.asarray(xd), mb["input"])
        np.testing.assert_array_equal(np.asarray(yd), mb["target"])
    assert len(released) == 8  # every slot went back, exactly once
    assert snapshots == [max(0, k - 1) for k in range(8)]
    ds.close()


def test_ring_batch_defer_release_transfers_ownership():
    """defer_release marks the batch released (auto-release no-ops) and
    hands back the one real release; double-defer is inert."""
    from bigdl_tpu.data.pipeline import RingBatch

    calls = []
    mb = RingBatch(lambda: calls.append("freed"), input=np.zeros(2))
    rel = mb.defer_release()
    mb.release()  # the stream's post-yield auto-release
    assert calls == []  # ownership moved: auto-release no longer frees
    assert mb.defer_release()() is None and calls == []  # second defer inert
    rel()
    assert calls == ["freed"]


def test_dispatch_inflight_one_is_the_serial_window(rec):
    """inflight=1 degenerates to the old block-inline behaviour: correct,
    and never more than one transfer in the window."""
    import jax

    p, _, _ = rec
    ds = RecordDataSet(p)
    stream = ds.stream_batches(10, shuffle=True, seed=2, epoch=1,
                               workers=2, ring_depth=2, raw_depth=1)
    devs = list(dispatch_to_device(
        stream, lambda mb: jax.device_put(np.asarray(mb["input"])),
        size=2, inflight=1))
    ref = list(ds.batches(10, shuffle=True, seed=2, epoch=1))
    for xd, mb in zip(devs, ref):
        np.testing.assert_array_equal(np.asarray(xd), mb["input"])
    with pytest.raises(ValueError):
        dispatch_to_device([], lambda mb: mb, inflight=0)
    ds.close()


# ---------------------------------------------------------------------------
# decode-pool autosizing + honest stage rates
# ---------------------------------------------------------------------------

def test_autotune_workers_policy():
    # no rates: the whole ceiling (cores minus reserve), floor of 2 so a
    # 2-core host keeps the geometry BENCH_loader_r06 won on
    assert autotune_workers(host_cores=24) == 22
    assert autotune_workers(host_cores=2) == 2
    assert autotune_workers(host_cores=1) == 1
    # need-based: enough workers to meet the target at the probed rate
    assert autotune_workers(decode_rate=10.0, target_rate=35.0,
                            host_cores=24) == 4
    assert autotune_workers(decode_rate=10.0, target_rate=1e9,
                            host_cores=24) == 22  # capped at the ceiling
    assert autotune_workers(decode_rate=100.0, target_rate=1.0,
                            host_cores=24) == 1


def test_stage_rates_measured_window(rec):
    """stage_rates reports counts, busy seconds, and rates over the
    MEASURED window — not a count divided by a near-zero busy interval
    (the bogus 102595.69 batches/s of BENCH_loader_r06)."""
    p, _, _ = rec
    ds = RecordDataSet(p)
    sp = ds.stream_batches(10, shuffle=False, workers=2)
    n = sum(1 for _ in sp)
    r = sp.stage_rates()
    assert r["window_s"] > 0
    assert r["read_batches"] == n == 8
    assert r["read_busy_s"] >= 0
    # windowed rate is count/window by definition...
    assert r["read_batches_per_s"] == pytest.approx(
        r["read_batches"] / r["window_s"], rel=0.25)
    # ...and capacity (count/busy) can only exceed it
    assert r["read_capacity_batches_per_s"] >= r["read_batches_per_s"]
    assert r["decode_capacity_batches_per_s"] >= r["decode_batches_per_s"]
    ds.close()


def test_backpressure_and_shard_rate_gauges_exported(rec):
    p, _, _ = rec
    ds = RecordDataSet(p)
    m = Metrics()
    for _ in ds.stream_batches(10, shuffle=False, workers=2, metrics=m):
        pass
    g = m.snapshot()["gauges"]
    for name in ("data.backpressure.read", "data.backpressure.decode",
                 "data.rate.shard_img_per_s",
                 "data.rate.read_batches_per_s"):
        assert name in g, name
    assert 0.0 <= g["data.backpressure.read"] <= 1.0
    assert 0.0 <= g["data.backpressure.decode"] <= 1.0
    assert g["data.rate.shard_img_per_s"] > 0
    ds.close()


def test_slow_consumer_not_blamed_on_read_stage(rec):
    """Device-bound runs: the consumer holds ring slots, the raw queue
    drains, decode workers idle — but that idleness is NOT read-stage
    backpressure.  decode starvation only accumulates while a ring slot
    was free (read had room to produce), so a slow consumer shows up as
    backpressure.read, never as a read-bound verdict."""
    import time as _time

    p, _, _ = rec
    ds = RecordDataSet(p)
    m = Metrics()
    sp = ds.stream_batches(10, shuffle=False, workers=2, ring_depth=2,
                           raw_depth=1, metrics=m)
    for mb in sp:
        _time.sleep(0.08)  # consumer (device) is the bottleneck
    g = m.snapshot()["gauges"]
    assert g["data.backpressure.read"] > 0.5  # blocked on the full ring
    assert g["data.backpressure.decode"] < 0.3  # ...but read isn't blamed
    ds.close()


def test_host_core_count_is_affinity_aware():
    import os

    from bigdl_tpu.data.pipeline import host_core_count

    n = host_core_count()
    assert n >= 1
    if hasattr(os, "sched_getaffinity"):
        assert n == len(os.sched_getaffinity(0))


def test_export_help_covers_ingest_gauges():
    """Every data.* family the ingest pipeline exports carries a HELP
    string — the HELP-coverage discipline from PR 6."""
    from bigdl_tpu.obs.export import DEFAULT_HELP

    for name in ("data.read_batches", "data.decoded_images",
                 "data.ready_batches", "data.queue_depth.raw",
                 "data.queue_depth.ring", "data.backpressure.read",
                 "data.backpressure.decode", "data.dispatch.in_flight",
                 "data.dispatch_overlapped_total",
                 "data.rate.shard_img_per_s",
                 "data.rate.read_batches_per_s",
                 "data.rate.decode_batches_per_s",
                 "data.rate.read_capacity_batches_per_s",
                 "data.rate.decode_capacity_batches_per_s"):
        assert name in DEFAULT_HELP and DEFAULT_HELP[name], name
