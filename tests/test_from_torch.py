"""Estimator.from_torch — stock torch modules trained on the mesh.

Reference call stack being replaced (SURVEY.md §4.3):
``Estimator.from_torch(backend="spark")`` pickling the torch module into
Spark workers.  Here the module's fx graph is converted to a native NHWC
keras-engine model once, weights carried over, and trained with the ZeRO-1
sharded step; weights export back as a torch state_dict."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from bigdl_tpu.estimator import Estimator, init_context
from bigdl_tpu.optim.validation import Top1Accuracy
from bigdl_tpu.utils.torch_convert import (export_state_dict,
                                           from_torch_module)

RS = np.random.RandomState(0)


class SmallCNN(torch.nn.Module):
    """torchvision-style: conv/bn/relu/pool features + flatten + fc head,
    with a residual add."""

    def __init__(self, classes=4):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(3, 8, 3, padding=1)
        self.bn1 = torch.nn.BatchNorm2d(8)
        self.conv2 = torch.nn.Conv2d(8, 8, 3, padding=1)
        self.pool = torch.nn.MaxPool2d(2)
        self.fc1 = torch.nn.Linear(8 * 4 * 4, 16)
        self.fc2 = torch.nn.Linear(16, classes)

    def forward(self, x):
        y = torch.relu(self.bn1(self.conv1(x)))
        y = y + torch.relu(self.conv2(y))      # residual
        y = self.pool(y)
        y = torch.flatten(y, 1)
        return self.fc2(torch.relu(self.fc1(y)))


class TinyBert(torch.nn.Module):
    """BERT-config encoder block: embeddings + MHA + FFN with residuals
    and LayerNorms + pooled classifier."""

    def __init__(self, vocab=32, d=16, heads=2, classes=2):
        super().__init__()
        self.emb = torch.nn.Embedding(vocab, d)
        self.ln1 = torch.nn.LayerNorm(d)
        self.mha = torch.nn.MultiheadAttention(d, heads, batch_first=True)
        self.ln2 = torch.nn.LayerNorm(d)
        self.ff1 = torch.nn.Linear(d, 4 * d)
        self.ff2 = torch.nn.Linear(4 * d, d)
        self.cls = torch.nn.Linear(d, classes)

    def forward(self, ids):
        h = self.emb(ids)
        a, _ = self.mha(h, h, h)
        h = self.ln1(h + a)
        f = self.ff2(torch.nn.functional.gelu(self.ff1(h)))
        h = self.ln2(h + f)
        return self.cls(h.mean(dim=[1]))


def test_cnn_conversion_forward_parity():
    tm = SmallCNN().eval()
    x = RS.rand(4, 3, 8, 8).astype(np.float32)    # torch NCHW
    model, variables = from_torch_module(tm, example_input=x)
    y, _ = model.apply(variables, x.transpose(0, 2, 3, 1))   # ours NHWC
    with torch.no_grad():
        ty = tm(torch.tensor(x))
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=2e-4)


def test_bert_conversion_forward_parity():
    tm = TinyBert().eval()
    ids = RS.randint(0, 32, (3, 7)).astype(np.int64)
    model, variables = from_torch_module(tm, example_input=ids)
    y, _ = model.apply(variables, ids.astype(np.int32))
    with torch.no_grad():
        ty = tm(torch.tensor(ids))
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=2e-4)


def test_estimator_from_torch_finetunes_cnn():
    init_context("local")
    n, classes = 256, 4
    x = RS.rand(n, 3, 8, 8).astype(np.float32)
    # separable-by-channel-mean labels
    y = (x.mean(axis=(1, 2, 3)) * 8).astype(np.int32) % classes

    est = Estimator.from_torch(
        model_creator=lambda cfg: SmallCNN(classes),
        optimizer_creator=lambda model, cfg: torch.optim.Adam(
            model.parameters(), lr=cfg["lr"]),
        loss_creator=lambda cfg: torch.nn.CrossEntropyLoss(),
        config={"lr": 5e-3},
        example_input=x[:1])

    x_nhwc = x.transpose(0, 2, 3, 1)
    before = est.evaluate((x_nhwc, y), [Top1Accuracy()])["Top1Accuracy"]
    est.fit((x_nhwc, y), epochs=20, batch_size=64)
    after = est.evaluate((x_nhwc, y), [Top1Accuracy()])["Top1Accuracy"]
    assert after > max(before, 0.5), (before, after)

    # trained weights round-trip into the ORIGINAL torch module and agree
    sd = est.state_dict()
    tm2 = SmallCNN(classes)
    tm2.load_state_dict(sd)
    tm2.eval()
    ours = est.predict(x_nhwc[:8])
    with torch.no_grad():
        theirs = tm2(torch.tensor(x[:8])).numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-3)


def test_estimator_from_torch_finetunes_bert():
    init_context("local")
    n, vocab = 192, 32
    ids = RS.randint(0, vocab, (n, 7)).astype(np.int32)
    y = (ids.sum(1) % 2).astype(np.int32)

    est = Estimator.from_torch(
        model_creator=lambda cfg: TinyBert(vocab),
        optimizer_creator=lambda model, cfg: torch.optim.AdamW(
            model.parameters(), lr=1e-3),
        loss_creator=lambda cfg: torch.nn.CrossEntropyLoss(),
        example_input=ids[:1].astype(np.int64))
    stats = est.fit((ids, y), epochs=10, batch_size=64)
    assert stats["num_samples"] == n
    pred = est.predict(ids[:8])
    assert pred.shape == (8, 2)


def test_optimizer_and_loss_mapping():
    from bigdl_tpu.optim.optim_method import SGD as OurSGD
    from bigdl_tpu.nn.criterion import MSECriterion
    from bigdl_tpu.utils.torch_convert import (convert_torch_loss,
                                               convert_torch_optimizer)

    lin = torch.nn.Linear(2, 2)
    topt = torch.optim.SGD(lin.parameters(), lr=0.05, momentum=0.9,
                           weight_decay=1e-4)
    ours = convert_torch_optimizer(topt)
    assert isinstance(ours, OurSGD) and ours.lr == 0.05
    assert isinstance(convert_torch_loss(torch.nn.MSELoss()), MSECriterion)


def test_unsupported_module_raises_with_node_name():
    class Odd(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.p = torch.nn.Parameter(torch.zeros(3))

        def forward(self, x):
            return torch.einsum("bi,i->b", x, self.p)

    with pytest.raises(NotImplementedError):
        from_torch_module(Odd(), example_input=RS.rand(2, 3).astype(
            np.float32))


def test_dropout_between_flatten_and_linear_keeps_permutation():
    """Regression: elementwise ops between flatten and fc must propagate
    the NCHW->NHWC Linear weight-permutation marker."""

    class Net(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.conv = torch.nn.Conv2d(2, 3, 3, padding=1)
            self.drop = torch.nn.Dropout(0.5)
            self.fc = torch.nn.Linear(3 * 4 * 4, 5)

        def forward(self, x):
            y = torch.relu(self.conv(x))
            y = torch.flatten(y, 1)
            y = self.drop(y)
            return self.fc(y)

    tm = Net().eval()
    x = RS.rand(2, 2, 4, 4).astype(np.float32)
    model, variables = from_torch_module(tm, example_input=x)
    y, _ = model.apply(variables, x.transpose(0, 2, 3, 1))
    with torch.no_grad():
        ty = tm(torch.tensor(x))
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=2e-4)


def test_unsupported_configs_raise_cleanly():
    # partial flatten is not a batch-preserving vectorization
    class Partial(torch.nn.Module):
        def forward(self, x):
            return torch.flatten(x, start_dim=2)

    with pytest.raises(NotImplementedError):
        from_torch_module(Partial(),
                          example_input=RS.rand(2, 3, 4, 4).astype(
                              np.float32))

    # output_padding has no equivalent
    with pytest.raises(NotImplementedError):
        from_torch_module(
            torch.nn.Sequential(torch.nn.ConvTranspose2d(
                2, 2, 3, stride=2, padding=1, output_padding=1)),
            example_input=RS.rand(1, 2, 4, 4).astype(np.float32))

    # BatchNorm cumulative averaging has no equivalent
    with pytest.raises(NotImplementedError):
        from_torch_module(
            torch.nn.Sequential(torch.nn.Conv2d(2, 2, 1),
                                torch.nn.BatchNorm2d(2, momentum=None)),
            example_input=RS.rand(1, 2, 4, 4).astype(np.float32))

    # multi-param-group optimizers refuse loudly
    from bigdl_tpu.utils.torch_convert import convert_torch_optimizer

    lin1, lin2 = torch.nn.Linear(2, 2), torch.nn.Linear(2, 2)
    topt = torch.optim.Adam([
        {"params": lin1.parameters(), "lr": 1e-5},
        {"params": lin2.parameters(), "lr": 1e-3}])
    with pytest.raises(NotImplementedError):
        convert_torch_optimizer(topt)


def test_scalar_arithmetic_and_sub_div():
    """Inline normalization (x/255 - 0.5) and tensor-tensor sub/div."""

    class Net(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.fc = torch.nn.Linear(4, 4)

        def forward(self, x):
            y = x / 2.0 - 0.5      # scalar div + scalar sub
            z = self.fc(y)
            w = z - y              # tensor sub
            return w * 3.0 + (z / (y + 2.0))   # scalar mul, tensor div

    tm = Net().eval()
    x = RS.rand(3, 4).astype(np.float32) + 0.5
    model, variables = from_torch_module(tm, example_input=x)
    y, _ = model.apply(variables, x)
    with torch.no_grad():
        ty = tm(torch.tensor(x))
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-5)


def test_multi_input_torch_module():
    """Two placeholders become a two-input converted model; the estimator
    predict path takes the tuple pack."""

    class TwoTower(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.a = torch.nn.Linear(4, 8)
            self.b = torch.nn.Linear(6, 8)
            self.head = torch.nn.Linear(16, 2)

        def forward(self, u, v):
            return self.head(torch.cat([torch.relu(self.a(u)),
                                        torch.relu(self.b(v))], dim=1))

    tm = TwoTower().eval()
    u = RS.rand(3, 4).astype(np.float32)
    v = RS.rand(3, 6).astype(np.float32)
    model, variables = from_torch_module(tm, example_input=(u[:1], v[:1]))
    y, _ = model.apply(variables, u, v)
    with torch.no_grad():
        ty = tm(torch.tensor(u), torch.tensor(v))
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-5)


def test_non_batch_view_is_not_flatten():
    """ADVICE r2: x.view(6, -1) on a (2,3,4,5) tensor is NOT a
    batch-preserving flatten — it must raise, not silently convert to
    Flatten() with wrong numerics.  x.view(batch, -1) still converts."""

    class BadView(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.fc = torch.nn.Linear(20, 2)   # (2,3,4,5).view(6,-1) → (6,20)

        def forward(self, x):
            return self.fc(x.view(6, -1))

    x = RS.rand(2, 3, 4, 5).astype(np.float32)
    with pytest.raises((NotImplementedError, ValueError)):
        from_torch_module(BadView(), example_input=x)

    class GoodView(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.fc = torch.nn.Linear(60, 2)

        def forward(self, x):
            return self.fc(x.view(2, -1))

    tm = GoodView().eval()
    model, variables = from_torch_module(tm, example_input=x)
    y, _ = model.apply(variables, x.transpose(0, 2, 3, 1))   # ours NHWC
    with torch.no_grad():
        ty = tm(torch.tensor(x))
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-5)


def test_dynamic_batch_view_converts():
    """x.view(x.size(0), -1) — the standard dynamic-batch flatten idiom —
    must keep converting (the batch-size check accepts the size(0) node)."""

    class DynView(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.fc = torch.nn.Linear(60, 2)

        def forward(self, x):
            return self.fc(x.view(x.size(0), -1))

    x = RS.rand(2, 3, 4, 5).astype(np.float32)
    tm = DynView().eval()
    model, variables = from_torch_module(tm, example_input=x)
    y, _ = model.apply(variables, x.transpose(0, 2, 3, 1))
    with torch.no_grad():
        ty = tm(torch.tensor(x))
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-5)


def test_shape_getitem_view_converts():
    """x.reshape(x.shape[0], -1) — the other dynamic-batch flatten idiom."""

    class ShapeView(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.fc = torch.nn.Linear(60, 2)

        def forward(self, x):
            return self.fc(x.reshape(x.shape[0], -1))

    x = RS.rand(2, 3, 4, 5).astype(np.float32)
    tm = ShapeView().eval()
    model, variables = from_torch_module(tm, example_input=x)
    y, _ = model.apply(variables, x.transpose(0, 2, 3, 1))
    with torch.no_grad():
        ty = tm(torch.tensor(x))
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-5)


def test_multilayer_bidirectional_rnn_parity():
    """nn.LSTM/nn.GRU with num_layers>1 and bidirectional=True convert as
    chains of scan layers with exact weight carry-over (VERDICT r2 item 3).
    torch's GRU candidate bias b_hn maps onto the native recurrent bias."""
    for kind in (torch.nn.LSTM, torch.nn.GRU):
        for layers, bidi in [(2, False), (1, True), (2, True)]:
            class Net(torch.nn.Module):
                def __init__(self):
                    super().__init__()
                    self.rnn = kind(5, 6, num_layers=layers,
                                    bidirectional=bidi, batch_first=True)
                    self.fc = torch.nn.Linear(6 * (2 if bidi else 1), 3)

                def forward(self, x):
                    y, _ = self.rnn(x)
                    return self.fc(y[:, -1])

            tm = Net().eval()
            x = RS.rand(3, 7, 5).astype(np.float32)
            model, variables = from_torch_module(tm, example_input=x)
            y, _ = model.apply(variables, x)
            with torch.no_grad():
                ty = tm(torch.tensor(x))
            np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=5e-4,
                                       err_msg=f"{kind.__name__} "
                                               f"L{layers} bidi={bidi}")
            # weights round-trip into a fresh torch module exactly
            sd = export_state_dict(model, variables)
            tm2 = Net()
            tm2.load_state_dict(sd)
            tm2.eval()
            with torch.no_grad():
                ty2 = tm2(torch.tensor(x))
            np.testing.assert_allclose(ty2.numpy(), ty.numpy(), atol=1e-5)


class _BasicBlock(torch.nn.Module):
    """torchvision.models.resnet.BasicBlock, reconstructed faithfully
    (torchvision is not installed in this image — VERDICT r2 item 3 allows
    a faithful equivalent): conv3x3-bn-relu-conv3x3-bn + identity/downsample
    residual, relu."""

    def __init__(self, cin, cout, stride=1):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = torch.nn.BatchNorm2d(cout)
        self.relu = torch.nn.ReLU(inplace=True)
        self.conv2 = torch.nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = torch.nn.BatchNorm2d(cout)
        self.downsample = None
        if stride != 1 or cin != cout:
            self.downsample = torch.nn.Sequential(
                torch.nn.Conv2d(cin, cout, 1, stride, bias=False),
                torch.nn.BatchNorm2d(cout))

    def forward(self, x):
        idn = x if self.downsample is None else self.downsample(x)
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return self.relu(out + idn)


class _ResNet18(torch.nn.Module):
    """torchvision resnet18 topology (same layer names/state_dict keys):
    7x7/2 stem, 3x3/2 maxpool, 4 stages of 2 BasicBlocks (64-512), adaptive
    avgpool, fc."""

    def __init__(self, classes=1000, width=64):
        super().__init__()
        w = width
        self.conv1 = torch.nn.Conv2d(3, w, 7, 2, 3, bias=False)
        self.bn1 = torch.nn.BatchNorm2d(w)
        self.relu = torch.nn.ReLU(inplace=True)
        self.maxpool = torch.nn.MaxPool2d(3, 2, 1)
        self.layer1 = torch.nn.Sequential(_BasicBlock(w, w),
                                          _BasicBlock(w, w))
        self.layer2 = torch.nn.Sequential(_BasicBlock(w, 2 * w, 2),
                                          _BasicBlock(2 * w, 2 * w))
        self.layer3 = torch.nn.Sequential(_BasicBlock(2 * w, 4 * w, 2),
                                          _BasicBlock(4 * w, 4 * w))
        self.layer4 = torch.nn.Sequential(_BasicBlock(4 * w, 8 * w, 2),
                                          _BasicBlock(8 * w, 8 * w))
        self.avgpool = torch.nn.AdaptiveAvgPool2d((1, 1))
        self.fc = torch.nn.Linear(8 * w, classes)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        x = self.avgpool(x)
        x = torch.flatten(x, 1)
        return self.fc(x)


def test_resnet18_conversion_forward_parity():
    """Full resnet18 topology (residual adds + 1x1 downsample convs +
    adaptive pool) converts with forward parity <= 1e-3 (VERDICT r2)."""
    tm = _ResNet18(classes=10, width=8).eval()   # thin width, full topology
    x = RS.rand(2, 3, 64, 64).astype(np.float32)
    model, variables = from_torch_module(tm, example_input=x)
    y, _ = model.apply(variables, x.transpose(0, 2, 3, 1))
    with torch.no_grad():
        ty = tm(torch.tensor(x))
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-3)
    # 20 residual convs + downsamples all present in the converted params
    n_convs = sum(1 for k in variables["params"] if "Conv2D" in k)
    assert n_convs == 20, n_convs


def test_estimator_finetunes_resnet18():
    """2-epoch fine-tune of the reconstructed resnet18 on the mesh, trained
    weights exported back into the torch module (VERDICT r2 done-check)."""
    init_context("local")
    n, classes = 64, 4
    x = RS.rand(n, 3, 32, 32).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) * 11).astype(np.int32) % classes

    est = Estimator.from_torch(
        model_creator=lambda cfg: _ResNet18(classes, width=8),
        optimizer_creator=lambda m, cfg: torch.optim.Adam(
            m.parameters(), lr=1e-3),
        loss_creator=lambda cfg: torch.nn.CrossEntropyLoss(),
        example_input=x[:1])
    x_nhwc = x.transpose(0, 2, 3, 1)
    stats = est.fit((x_nhwc, y), epochs=2, batch_size=16)
    assert stats["num_samples"] == n
    # round trip: trained weights load into a FRESH torch resnet18
    sd = est.state_dict()
    tm2 = _ResNet18(classes, width=8)
    tm2.load_state_dict(sd)
    tm2.eval()
    ours = est.predict(x_nhwc[:4])
    with torch.no_grad():
        theirs = tm2(torch.tensor(x[:4])).numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-3)


class _InvertedResidual(torch.nn.Module):
    """torchvision.models.mobilenet_v2 InvertedResidual, reconstructed:
    1x1 expand + ReLU6, 3x3 depthwise (groups=hidden) + ReLU6, 1x1 project,
    residual when stride 1 and cin==cout."""

    def __init__(self, cin, cout, stride, expand):
        super().__init__()
        hid = cin * expand
        self.use_res = stride == 1 and cin == cout
        layers = []
        if expand != 1:
            layers += [torch.nn.Conv2d(cin, hid, 1, bias=False),
                       torch.nn.BatchNorm2d(hid), torch.nn.ReLU6()]
        layers += [
            torch.nn.Conv2d(hid, hid, 3, stride, 1, groups=hid, bias=False),
            torch.nn.BatchNorm2d(hid), torch.nn.ReLU6(),
            torch.nn.Conv2d(hid, cout, 1, bias=False),
            torch.nn.BatchNorm2d(cout),
        ]
        self.conv = torch.nn.Sequential(*layers)

    def forward(self, x):
        return x + self.conv(x) if self.use_res else self.conv(x)


def test_mobilenet_v2_style_conversion():
    """Depthwise (groups=channels) convs, ReLU6, expand/project bottlenecks
    and Hardswish heads convert with forward parity."""

    class MiniMobileNet(torch.nn.Module):
        def __init__(self, classes=5):
            super().__init__()
            self.stem = torch.nn.Sequential(
                torch.nn.Conv2d(3, 8, 3, 2, 1, bias=False),
                torch.nn.BatchNorm2d(8), torch.nn.Hardswish())
            self.blocks = torch.nn.Sequential(
                _InvertedResidual(8, 8, 1, 1),
                _InvertedResidual(8, 12, 2, 4),
                _InvertedResidual(12, 12, 1, 4),
            )
            self.pool = torch.nn.AdaptiveAvgPool2d(1)
            self.fc = torch.nn.Linear(12, classes)

        def forward(self, x):
            y = self.blocks(self.stem(x))
            y = torch.flatten(self.pool(y), 1)
            return self.fc(y)

    tm = MiniMobileNet().eval()
    x = RS.rand(2, 3, 32, 32).astype(np.float32)
    model, variables = from_torch_module(tm, example_input=x)
    y, _ = model.apply(variables, x.transpose(0, 2, 3, 1))
    with torch.no_grad():
        ty = tm(torch.tensor(x))
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-3)
    # round trip back to torch
    sd = export_state_dict(model, variables)
    tm2 = MiniMobileNet()
    tm2.load_state_dict(sd)
    tm2.eval()
    with torch.no_grad():
        ty2 = tm2(torch.tensor(x))
    np.testing.assert_allclose(ty2.numpy(), ty.numpy(), atol=1e-5)


def test_unet_style_upsample_and_skip():
    """nn.Upsample (nearest + bilinear, align_corners=False) converts; a
    UNet-style skip concat across the upsample keeps forward parity."""

    class MiniUNet(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.down = torch.nn.Conv2d(3, 6, 3, 2, 1)
            self.mid = torch.nn.Conv2d(6, 6, 3, 1, 1)
            self.up = torch.nn.Upsample(scale_factor=2, mode="nearest")
            self.out = torch.nn.Conv2d(9, 2, 1)

        def forward(self, x):
            d = torch.relu(self.down(x))
            u = self.up(torch.relu(self.mid(d)))
            return self.out(torch.cat([u, x], dim=1))

    tm = MiniUNet().eval()
    x = RS.rand(2, 3, 8, 8).astype(np.float32)
    model, variables = from_torch_module(tm, example_input=x)
    y, _ = model.apply(variables, x.transpose(0, 2, 3, 1))
    with torch.no_grad():
        ty = tm(torch.tensor(x))
    np.testing.assert_allclose(np.asarray(y),
                               ty.numpy().transpose(0, 2, 3, 1), atol=1e-4)

    class Bilin(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.up = torch.nn.Upsample(scale_factor=2, mode="bilinear",
                                        align_corners=False)

        def forward(self, x):
            return self.up(x)

    tm2 = Bilin().eval()
    model2, v2 = from_torch_module(tm2, example_input=x)
    y2, _ = model2.apply(v2, x.transpose(0, 2, 3, 1))
    with torch.no_grad():
        ty2 = tm2(torch.tensor(x))
    np.testing.assert_allclose(np.asarray(y2),
                               ty2.numpy().transpose(0, 2, 3, 1), atol=1e-5)


def test_functional_interpolate_converts():
    class Net(torch.nn.Module):
        def forward(self, x):
            return torch.nn.functional.interpolate(x, scale_factor=2,
                                                   mode="nearest")

    x = RS.rand(2, 3, 4, 4).astype(np.float32)
    model, variables = from_torch_module(Net().eval(), example_input=x)
    y, _ = model.apply(variables, x.transpose(0, 2, 3, 1))
    with torch.no_grad():
        ty = Net()(torch.tensor(x))
    np.testing.assert_allclose(np.asarray(y),
                               ty.numpy().transpose(0, 2, 3, 1), atol=1e-6)


def test_transformer_encoder_conversion():
    """torch.nn.TransformerEncoder (both norm orders) converts as a
    structural leaf — its forward's mask canonicalization breaks fx — with
    forward parity and exact state_dict export round-trip."""
    for norm_first in (False, True):
        enc = torch.nn.TransformerEncoder(
            torch.nn.TransformerEncoderLayer(
                16, 2, 32, batch_first=True, dropout=0.0,
                activation="gelu", norm_first=norm_first),
            num_layers=2).eval()
        x = RS.rand(2, 5, 16).astype(np.float32)
        model, variables = from_torch_module(enc, example_input=x)
        y, _ = model.apply(variables, x)
        with torch.no_grad():
            ty = enc(torch.tensor(x))
        np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-3,
                                   err_msg=f"norm_first={norm_first}")
        enc2 = torch.nn.TransformerEncoder(
            torch.nn.TransformerEncoderLayer(
                16, 2, 32, batch_first=True, dropout=0.0,
                activation="gelu", norm_first=norm_first), 2)
        enc2.load_state_dict(export_state_dict(model, variables),
                             strict=True)
        enc2.eval()
        with torch.no_grad():
            ty2 = enc2(torch.tensor(x))
        np.testing.assert_allclose(ty2.numpy(), ty.numpy(), atol=1e-5)


def test_bert_like_classifier_with_encoder_stack():
    """Embedding + TransformerEncoder + mean-pool + head — the standard
    huggingface-ish composition — converts and fine-tunes."""

    class Clf(torch.nn.Module):
        def __init__(self, vocab=40, d=16):
            super().__init__()
            self.emb = torch.nn.Embedding(vocab, d)
            self.enc = torch.nn.TransformerEncoder(
                torch.nn.TransformerEncoderLayer(
                    d, 2, 32, batch_first=True, dropout=0.0), 1)
            self.cls = torch.nn.Linear(d, 2)

        def forward(self, ids):
            h = self.enc(self.emb(ids))
            return self.cls(h.mean(dim=[1]))

    tm = Clf().eval()
    ids = RS.randint(0, 40, (3, 6)).astype(np.int64)
    model, variables = from_torch_module(tm, example_input=ids)
    y, _ = model.apply(variables, ids.astype(np.int32))
    with torch.no_grad():
        ty = tm(torch.tensor(ids))
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-3)


def test_activation_module_tail_converts():
    acts = [torch.nn.LogSoftmax(dim=-1), torch.nn.Mish(),
            torch.nn.Softplus(), torch.nn.Softsign(),
            torch.nn.Tanhshrink(), torch.nn.Softshrink(0.3),
            torch.nn.Hardshrink(0.3), torch.nn.LogSigmoid()]

    class Net(torch.nn.Module):
        def __init__(self, act):
            super().__init__()
            self.fc = torch.nn.Linear(6, 6)
            self.act = act

        def forward(self, x):
            return self.act(self.fc(x))

    x = RS.rand(3, 6).astype(np.float32)
    for act in acts:
        tm = Net(act).eval()
        model, variables = from_torch_module(tm, example_input=x)
        y, _ = model.apply(variables, x)
        with torch.no_grad():
            ty = tm(torch.tensor(x))
        np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-5,
                                   err_msg=type(act).__name__)


def test_functional_activation_tail_converts():
    import torch.nn.functional as F

    cases = [lambda x: F.silu(x), lambda x: F.leaky_relu(x, 0.2),
             lambda x: F.elu(x, 0.7), lambda x: F.log_softmax(x, dim=-1),
             lambda x: F.hardswish(x), lambda x: F.softplus(x)]

    x = RS.rand(3, 6).astype(np.float32)
    for i, f in enumerate(cases):
        class Net(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.fc = torch.nn.Linear(6, 6)

            def forward(self, z):
                return f(self.fc(z))

        tm = Net().eval()
        model, variables = from_torch_module(tm, example_input=x)
        y, _ = model.apply(variables, x)
        with torch.no_grad():
            ty = tm(torch.tensor(x))
        np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-5,
                                   err_msg=f"case {i}")
