"""Augmentation extensions + segmentation mask utilities."""

import numpy as np

from bigdl_tpu.data import (AspectScale, Brightness, ChannelOrder,
                            ColorJitter, Contrast, Expand, Filler, FixedCrop,
                            Grayscale, Hue, PixelNormalizer,
                            RandomTransformer, Saturation, annotation_to_mask,
                            mask_to_bbox, polygons_to_mask, rle_area,
                            rle_decode, rle_encode)
from bigdl_tpu.data.vision import ImageFeature, ImageFrame

RS = np.random.RandomState(0)


def _img(h=16, w=20):
    return ImageFeature(RS.randint(0, 255, (h, w, 3), dtype=np.uint8).astype(
        np.uint8), label=1)


def _run(t, f):
    return next(iter(t(iter([f]))))


def test_color_ops_preserve_shape_dtype():
    for t in [Brightness(seed=0), Contrast(seed=0), Saturation(seed=0),
              Hue(seed=0), Grayscale(), ChannelOrder(), ColorJitter(seed=0)]:
        f = _run(t, _img())
        assert f.image.shape == (16, 20, 3)
        assert f.image.dtype == np.uint8, type(t).__name__


def test_brightness_shifts_mean():
    f0 = _img()
    before = f0.image.astype(np.float32).mean()
    f = _run(Brightness(50, 50, seed=0), f0)
    assert f.image.astype(np.float32).mean() > before + 20


def test_hue_identity_when_zero():
    f0 = _img()
    ref = f0.image.copy()
    f = _run(Hue(0.0, seed=0), f0)
    np.testing.assert_allclose(f.image.astype(int), ref.astype(int), atol=2)


def test_grayscale_channels_equal():
    f = _run(Grayscale(), _img())
    assert np.array_equal(f.image[..., 0], f.image[..., 1])


def test_expand_filler_fixedcrop_aspect():
    f = _run(Expand(max_ratio=2.0, seed=1), _img())
    assert f.image.shape[0] >= 16 and f.image.shape[1] >= 20

    f = _run(Filler(0.0, 0.0, 0.5, 0.5, value=7), _img())
    assert np.all(f.image[:8, :10] == 7)
    assert not np.all(f.image[8:, 10:] == 7)

    f = _run(FixedCrop(0.25, 0.25, 0.75, 0.75), _img())
    assert f.image.shape == (8, 10, 3)

    f = _run(AspectScale(32, max_size=100), _img())
    assert min(f.image.shape[:2]) == 32


def test_random_transformer_probability():
    always = RandomTransformer(ChannelOrder(), 1.0, seed=0)
    never = RandomTransformer(ChannelOrder(), 0.0, seed=0)
    f0 = _img()
    ref = f0.image.copy()
    f = _run(always, ImageFeature(ref.copy()))
    assert np.array_equal(f.image, ref[..., ::-1])
    f = _run(never, ImageFeature(ref.copy()))
    assert np.array_equal(f.image, ref)


def test_pixel_normalizer():
    f0 = _img()
    mean = np.full((16, 20, 3), 10.0, np.float32)
    f = _run(PixelNormalizer(mean), f0)
    assert f.image.dtype == np.float32


def test_pipeline_chains_on_imageframe():
    frame = ImageFrame([_img() for _ in range(4)])
    out = frame.transform(ColorJitter(seed=0))
    assert len(out) == 4


# ---- segmentation ---------------------------------------------------------

def test_rle_roundtrip():
    mask = (RS.rand(13, 17) > 0.6).astype(np.uint8)
    rle = rle_encode(mask)
    np.testing.assert_array_equal(rle_decode(rle), mask)
    assert rle_area(rle) == int(mask.sum())


def test_rle_edge_cases():
    zeros = np.zeros((4, 5), np.uint8)
    np.testing.assert_array_equal(rle_decode(rle_encode(zeros)), zeros)
    ones = np.ones((4, 5), np.uint8)
    np.testing.assert_array_equal(rle_decode(rle_encode(ones)), ones)


def test_polygon_rasterization_and_bbox():
    # square from (2,3) to (8,9)
    poly = [2, 3, 8, 3, 8, 9, 2, 9]
    mask = polygons_to_mask([poly], 12, 12)
    assert mask[5, 5] == 1
    assert mask[0, 0] == 0
    x, y, w, h = mask_to_bbox(mask)
    assert (x, y) == (2.0, 3.0)
    assert w >= 6 and h >= 6

    ann_poly = {"segmentation": [poly]}
    np.testing.assert_array_equal(annotation_to_mask(ann_poly, 12, 12), mask)
    ann_rle = {"segmentation": rle_encode(mask)}
    np.testing.assert_array_equal(annotation_to_mask(ann_rle, 12, 12), mask)


def test_mask_to_bbox_empty():
    assert mask_to_bbox(np.zeros((5, 5))) == [0.0, 0.0, 0.0, 0.0]


def test_coco_compressed_rle_decode():
    from bigdl_tpu.data.segmentation import _coco_string_to_counts

    # round-trip through the COCO varint coder: encode counts with the
    # inverse algorithm, decode, compare
    def counts_to_string(counts):
        s = []
        for i, x in enumerate(counts):
            if i > 2:
                x -= counts[i - 2]
            more = True
            while more:
                c = x & 0x1F
                x >>= 5
                more = not ((x == 0 and not (c & 0x10))
                            or (x == -1 and (c & 0x10)))
                if more:
                    c |= 0x20
                s.append(chr(c + 48))
        return "".join(s)

    mask = (RS.rand(9, 11) > 0.55).astype(np.uint8)
    rle = rle_encode(mask)
    compressed = {"counts": counts_to_string(rle["counts"]),
                  "size": rle["size"]}
    assert _coco_string_to_counts(compressed["counts"]) == rle["counts"]
    np.testing.assert_array_equal(rle_decode(compressed), mask)
    assert rle_area(compressed) == int(mask.sum())


def test_colorjitter_stages_independent():
    cj = ColorJitter(seed=7)
    b, c = cj.stages[0].rng, cj.stages[1].rng
    assert not np.allclose(b.random(8), c.random(8))
