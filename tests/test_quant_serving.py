"""Quantized serving memory hierarchy (docs/quantization.md §Serving
memory hierarchy): per-page int8 KV quantization bounds + the MONOTONE
scale floor that makes whole-row write-back exact, fresh-page zero
scales (no stale-scale aliasing across slot reuse), the paged flash
kernel's in-register dequantization vs the gathered-jnp reference, the
int8-vs-f32 token-parity budget the tier-1 gate rides on, the
zero-recompile sweep for the int8 program set, ``weight_quant="int8"``
serving weights, and the /health page-dtype + bytes-per-page
accounting the fleet router scores capacity by.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.attention import Transformer
from bigdl_tpu.ops.flash_attention import paged_decode_attention
from bigdl_tpu.ops.quantized import dequantize_pages, quantize_pages
from bigdl_tpu.serving.decode_engine import (DecodeConfig, DecodeEngine,
                                             LMAdapter)

BOS, EOS = 0, 1


@pytest.fixture(scope="module")
def lm():
    model = Transformer(vocab_size=32, hidden_size=16, num_heads=2,
                        num_layers=2, dropout=0.0, mode="lm")
    v = model.init(jax.random.PRNGKey(0),
                   np.arange(6, dtype=np.int32)[None])
    return model, v["params"]


def _engine(lm, **over):
    model, params = lm
    kw = dict(slots=4, page_size=4, pages_per_slot=8, prompt_chunk=4,
              max_new_tokens=16, eos_id=EOS, prefill_batch=2)
    kw.update(over)
    weight_quant = kw.pop("weight_quant", None)
    cfg = DecodeConfig(**kw)
    return DecodeEngine(LMAdapter(model, params, cap=cfg.cap,
                                  weight_quant=weight_quant), cfg)


def _prompts(n=6, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(2, 32, (int(rs.randint(2, 11)),)).tolist()
            for i in range(n)]


@pytest.fixture(scope="module")
def eng_pair(lm):
    """One warmed f32/int8 engine pair shared by every parity spec —
    warmup dominates 1-core wall time, and the parity contract is
    about STEADY-STATE decode, so sharing (and dirtying) the pool
    across specs is the realistic regime, not a shortcut."""
    e32 = _engine(lm, kv_dtype="float32")
    e8 = _engine(lm, kv_dtype="int8")
    e32.warmup()
    e8.warmup()
    yield e32, e8
    e32.stop()
    e8.stop()


# ---------------------------------------------------------------------------
# per-page quantization math (ops.quantized.quantize_pages)
# ---------------------------------------------------------------------------

def test_quantize_pages_roundtrip_bound():
    """Dequantized error is bounded by half an int8 step of each page's
    OWN abs-max scale — the bound the token-parity budget rests on."""
    rs = np.random.RandomState(0)
    pages = jnp.asarray(rs.randn(6, 2, 4, 8).astype(np.float32) * 3.0)
    q, scales = quantize_pages(pages)
    assert q.dtype == jnp.int8 and q.shape == pages.shape
    assert scales.shape == (6,)
    back = dequantize_pages(q, scales)
    err = np.max(np.abs(np.asarray(back - pages)), axis=(1, 2, 3))
    amax = np.max(np.abs(np.asarray(pages)), axis=(1, 2, 3))
    assert np.all(err <= amax / 127.0 * 0.5 + 1e-6), (err, amax / 127.0)


def test_monotone_floor_requantizes_exactly():
    """Under a monotone floor, re-quantizing a page whose contents came
    FROM that quantization grid is exact: round(q*s / s) == q.  This is
    what makes the engine's dequantize -> insert-token -> requantize
    whole-row write-back safe for the untouched positions."""
    rs = np.random.RandomState(1)
    pages = jnp.asarray(rs.randn(5, 2, 4, 8).astype(np.float32))
    q1, s1 = quantize_pages(pages, floor_scales=jnp.zeros(5))
    deq = dequantize_pages(q1, s1)
    # the page grew (amax can only grow the floor, never shrink it)
    q2, s2 = quantize_pages(deq, floor_scales=s1)
    np.testing.assert_array_equal(np.asarray(q1), np.asarray(q2))
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=0,
                               atol=0)


def test_fresh_page_zero_scale_masks_stale_payload():
    """A freshly allocated page carries scale 0.0: whatever int8 garbage
    its previous owner left behind dequantizes to exact zeros, so slot
    reuse can never alias a dead sequence's KV into a live one."""
    stale = jnp.asarray(
        np.random.RandomState(2).randint(-127, 128, (3, 2, 4, 8)),
        jnp.int8)
    out = dequantize_pages(stale, jnp.zeros(3))
    assert np.all(np.asarray(out) == 0.0)
    # and quantizing genuinely-zero content under a 0.0 floor keeps the
    # scale at 0.0 (no epsilon creep that would resurrect the payload)
    q, s = quantize_pages(jnp.zeros((3, 2, 4, 8)),
                          floor_scales=jnp.zeros(3))
    assert np.all(np.asarray(s) == 0.0)


def test_paged_kernel_scale_validation():
    q = jnp.zeros((2, 2, 8), jnp.float32)
    kq = jnp.zeros((4, 2, 4, 8), jnp.int8)
    kf = jnp.zeros((4, 2, 4, 8), jnp.float32)
    pt = jnp.zeros((2, 2), jnp.int32)
    ln = jnp.zeros((2,), jnp.int32)
    sc = jnp.ones((4,), jnp.float32)
    with pytest.raises(ValueError, match="k_scales"):
        paged_decode_attention(q, kq, kq, pt, ln, interpret=True)
    with pytest.raises(ValueError, match="int8"):
        paged_decode_attention(q, kf, kf, pt, ln, k_scales=sc,
                               v_scales=sc, interpret=True)


def test_paged_kernel_int8_matches_f32_on_dequantized_pages():
    """The kernel's in-register dequantization must agree with handing
    it pre-dequantized f32 pages — same math, different memory format."""
    rs = np.random.RandomState(3)
    S, h, p, d, P, nb = 4, 2, 4, 8, 16, 4
    q = jnp.asarray(rs.randn(S, h, d).astype(np.float32))
    k32 = jnp.asarray(rs.randn(P, h, p, d).astype(np.float32))
    v32 = jnp.asarray(rs.randn(P, h, p, d).astype(np.float32))
    kq, ks = quantize_pages(k32)
    vq, vs = quantize_pages(v32)
    pt = jnp.asarray(rs.permutation(P)[:S * nb].reshape(S, nb),
                     jnp.int32)
    ln = jnp.asarray(rs.randint(0, p * nb, (S,)), jnp.int32)
    ref = paged_decode_attention(q, dequantize_pages(kq, ks),
                                 dequantize_pages(vq, vs), pt, ln,
                                 block_h=1, interpret=True)
    out = paged_decode_attention(q, kq, vq, pt, ln, k_scales=ks,
                                 v_scales=vs, block_h=1, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# engine-level token parity: the tier-1 acceptance budget
# ---------------------------------------------------------------------------

def test_int8_kv_token_parity_budget(eng_pair):
    """Greedy decode through int8 KV pages must produce the SAME tokens
    as f32, with the summed log-prob drifting within the quantization
    budget — this is the acceptance bar for the whole memory hierarchy."""
    prompts = _prompts(6)
    e32, e8 = eng_pair
    ref = e32.generate(prompts, max_new_tokens=12)
    out = e8.generate(prompts, max_new_tokens=12)
    for r, o in zip(ref, out):
        assert r.tokens.tolist() == o.tokens.tolist(), (
            "int8 KV pages changed the greedy token stream")
        assert abs(r.logp - o.logp) < 0.15, (
            f"logp drift {abs(r.logp - o.logp):.4f} blows the int8 "
            "quantization budget")


def test_int8_kv_seeded_sample_parity(eng_pair):
    """Seeded sampling rides the same budget: sampling keys are
    counter-based on absolute position, so with the logit drift inside
    the int8 budget the sampled stream matches f32 token for token."""
    prompts = _prompts(6, seed=11)
    kw = dict(temperature=0.8, top_k=8, top_p=0.9, seed=13)
    e32, e8 = eng_pair
    ref = e32.generate(prompts, max_new_tokens=12, **kw)
    out = e8.generate(prompts, max_new_tokens=12, **kw)
    for r, o in zip(ref, out):
        assert r.tokens.tolist() == o.tokens.tolist(), (
            "int8 KV pages changed the seeded sample stream")
        assert abs(r.logp - o.logp) < 0.15


@pytest.mark.slow
def test_full_hierarchy_token_parity(lm, eng_pair):
    """int8 KV pages + int8 serving weights together: greedy tokens
    still match f32, with a (larger) bounded logp drift."""
    prompts = _prompts(5, seed=4)
    e32, _ = eng_pair
    e8 = _engine(lm, kv_dtype="int8", weight_quant="int8")
    try:
        e8.warmup()
        ref = e32.generate(prompts, max_new_tokens=10)
        out = e8.generate(prompts, max_new_tokens=10)
    finally:
        e8.stop()
    agree = sum(r.tokens.tolist() == o.tokens.tolist()
                for r, o in zip(ref, out))
    assert agree == len(ref), (
        f"only {agree}/{len(ref)} greedy streams survived int8 weights "
        "+ int8 KV")
    drift = max(abs(r.logp - o.logp) for r, o in zip(ref, out))
    assert drift < 1.0, f"logp drift {drift:.3f} out of budget"


@pytest.mark.slow
def test_int8_slot_reuse_no_stale_scale_aliasing(lm):
    """Run two back-to-back waves through the SAME int8 engine (the
    second wave reuses freed slots and pages) and compare the second
    wave against a fresh engine: stale per-page scales from wave one
    must not leak into wave two's dequantization."""
    wave1, wave2 = _prompts(6, seed=5), _prompts(6, seed=6)
    reused = _engine(lm, kv_dtype="int8", slots=3)
    fresh = _engine(lm, kv_dtype="int8", slots=3)
    try:
        reused.warmup()
        fresh.warmup()
        reused.generate(wave1, max_new_tokens=12)   # dirty the pool
        out = reused.generate(wave2, max_new_tokens=12)
        ref = fresh.generate(wave2, max_new_tokens=12)
    finally:
        reused.stop()
        fresh.stop()
    for r, o in zip(ref, out):
        assert r.tokens.tolist() == o.tokens.tolist(), (
            "slot reuse changed int8 decode output: stale scale or "
            "stale payload aliasing")


@pytest.mark.slow
def test_int8_kernel_vs_gathered_jnp_tokens(lm):
    """The Pallas paged-decode kernel (interpret mode on CPU) and the
    gathered-jnp fallback must emit identical greedy tokens from the
    same int8 page pool."""
    prompts = _prompts(5, seed=7)
    ek = _engine(lm, kv_dtype="int8", use_flash_decode=True)
    ej = _engine(lm, kv_dtype="int8", use_flash_decode=False)
    try:
        ek.warmup()
        ej.warmup()
        a = ek.generate(prompts, max_new_tokens=10)
        b = ej.generate(prompts, max_new_tokens=10)
    finally:
        ek.stop()
        ej.stop()
    for x, y in zip(a, b):
        assert x.tokens.tolist() == y.tokens.tolist(), (
            "kernel and jnp int8 decode paths disagree")


def test_int8_mixed_sweep_zero_unexpected_recompiles(eng_pair):
    """The int8 program set stays closed: a mixed prompt/generation
    sweep after warmup triggers zero unexpected XLA recompiles."""
    from bigdl_tpu.obs.attr import recompile_sentinel
    from bigdl_tpu.optim.metrics import global_metrics

    sent = recompile_sentinel()
    _, eng = eng_pair
    m = global_metrics()
    try:
        before = m.counter("train.unexpected_recompiles_total")
        sent.mark_steady()
        rs = np.random.RandomState(8)
        prompts = [rs.randint(2, 32, (int(rs.randint(1, 12)),)).tolist()
                   for _ in range(16)]
        eng.generate(prompts, max_new_tokens=int(rs.randint(4, 13)))
        after = m.counter("train.unexpected_recompiles_total")
        assert after - before == 0, (
            f"{after - before} unexpected XLA recompiles in the int8 "
            "mixed-length sweep")
    finally:
        sent.mark_warmup()


# ---------------------------------------------------------------------------
# int8 serving weights (nn.quantized.quantize_params / weight_quant)
# ---------------------------------------------------------------------------

def test_quantize_params_roundtrip_and_min_dim(lm):
    from bigdl_tpu.nn import quantized as nq

    _, params = lm
    qp = nq.quantize_params(params)
    assert nq.is_quantized_params(qp)
    assert not nq.is_quantized_params(params)
    # idempotent: re-quantizing an already-quantized tree is a no-op
    qp2 = nq.quantize_params(qp)
    assert jax.tree_util.tree_structure(qp) == \
        jax.tree_util.tree_structure(qp2)
    deq = nq.dequantize_params(qp)
    assert jax.tree_util.tree_structure(deq) == \
        jax.tree_util.tree_structure(params)
    # bounded relative error on every quantized matrix
    flat_p = jax.tree_util.tree_leaves_with_path(params)
    flat_d = dict(jax.tree_util.tree_leaves_with_path(deq))
    n_quant = 0
    for path, leaf in flat_p:
        back = flat_d[path]
        if leaf.ndim == 2 and min(leaf.shape) >= 16:
            n_quant += 1
            scale = np.max(np.abs(np.asarray(leaf)), axis=0)
            err = np.max(np.abs(np.asarray(back - leaf)), axis=0)
            assert np.all(err <= scale / 127.0 * 0.5 + 1e-6)
        else:
            np.testing.assert_array_equal(np.asarray(back),
                                          np.asarray(leaf))
    assert n_quant > 0, "fixture model produced no quantizable matrices"


def test_weight_quant_inference_model(lm):
    from bigdl_tpu.serving.inference_model import InferenceModel

    model, params = lm
    variables = {"params": params}
    x = np.arange(6, dtype=np.int32)[None]
    ref = np.asarray(InferenceModel(model, variables).predict(x))
    out = np.asarray(InferenceModel(model, variables,
                                    weight_quant="int8").predict(x))
    assert out.shape == ref.shape
    denom = np.maximum(np.max(np.abs(ref)), 1e-6)
    assert np.max(np.abs(out - ref)) / denom < 0.05, (
        "int8 serving weights drifted the logits beyond the budget")
    with pytest.raises(ValueError, match="weight_quant"):
        InferenceModel(model, variables, weight_quant="int4")


def test_weight_quant_adapter_rejects_unknown(lm):
    model, params = lm
    with pytest.raises(ValueError, match="weight_quant"):
        LMAdapter(model, params, cap=32, weight_quant="fp8")


# ---------------------------------------------------------------------------
# capacity accounting: /health page dtype + bytes per page
# ---------------------------------------------------------------------------

def test_kv_bytes_per_page_and_pressure_fields(lm):
    e32 = _engine(lm, kv_dtype="float32")
    e8 = _engine(lm, kv_dtype="int8")
    try:
        b32, b8 = e32.kv_bytes_per_page(), e8.kv_bytes_per_page()
        # int8 payload is 4x smaller; the per-(layer, page) scale pair
        # keeps the total just above a strict /4
        assert b8 < b32 / 3
        a = e8.adapter
        assert b32 == 2 * a.num_layers * a.num_heads * 4 * a.head_dim * 4
        assert b8 == (2 * a.num_layers * a.num_heads * 4 * a.head_dim
                      + 2 * a.num_layers * 4)
        p32, p8 = e32.decode_pressure(), e8.decode_pressure()
        assert p32["page_dtype"] == "float32"
        assert p8["page_dtype"] == "int8"
        assert p32["kv_bytes_per_page"] == b32
        assert p8["kv_bytes_per_page"] == b8
    finally:
        e32.stop()
        e8.stop()


def test_invalid_kv_dtype_rejected(lm):
    with pytest.raises(ValueError, match="kv_dtype"):
        _engine(lm, kv_dtype="int4")
