"""Nano-equivalent InferenceOptimizer + keras autograd."""

import jax
import numpy as np
import pytest

from bigdl_tpu.keras import autograd as A
from bigdl_tpu.nano import InferenceOptimizer


def _model_and_vars(seed=0):
    from bigdl_tpu.nn.layers import Linear, ReLU
    from bigdl_tpu.nn.module import Sequential

    model = Sequential([Linear(16, 32), ReLU(), Linear(32, 4)])
    x = np.random.RandomState(seed).randn(8, 16).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    return model, variables, x


class TestInferenceOptimizer:
    def test_trace_fp32(self):
        model, variables, x = _model_and_vars()
        tm = InferenceOptimizer.trace(model, variables, x)
        out = np.asarray(tm(x))
        ref, _ = model.apply(variables, x)
        np.testing.assert_allclose(out, np.asarray(ref), rtol=1e-5,
                                   atol=1e-5)

    def test_trace_shape_fixed(self):
        model, variables, x = _model_and_vars()
        tm = InferenceOptimizer.trace(model, variables, x)
        with pytest.raises(ValueError, match="re-trace"):
            tm(x[:4])

    def test_quantize_int8(self):
        model, variables, x = _model_and_vars()
        tm = InferenceOptimizer.quantize(model, variables, x,
                                         precision="int8")
        out = np.asarray(tm(x))
        ref, _ = model.apply(variables, x)
        rel = np.abs(out - np.asarray(ref)).max() / (
            np.abs(np.asarray(ref)).max() + 1e-8)
        assert rel < 0.1, rel

    def test_optimize_picks_best(self):
        model, variables, x = _model_and_vars()
        res = InferenceOptimizer.optimize(
            model, variables, x,
            methods=("fp32", "bf16", "int8", "int8_wo"), repeats=3)
        best, name = res.get_best_model()
        assert name in ("fp32", "bf16", "int8", "int8_wo")
        assert np.asarray(best(x)).shape == (8, 4)
        assert "latency" in res.summary()
        # the weight-only variant ran (not a 'failed' row)
        assert "int8_wo" in res.results
        assert res.results["int8_wo"]["status"] == "ok"

    def test_accuracy_gate(self):
        model, variables, x = _model_and_vars()
        ref, _ = model.apply(variables, x)
        ref = np.asarray(ref)

        # scorer: negative max-deviation from fp32 output; bf16/int8 deviate
        def score(out):
            return -float(np.abs(out - ref).max())

        res = InferenceOptimizer.optimize(
            model, variables, x, methods=("fp32", "int8"), repeats=2,
            accuracy_fn=score, accuracy_budget=1e-9)
        assert res.results["fp32"]["status"] == "ok"
        assert res.results["int8"]["status"] == "accuracy_drop"


class TestAutograd:
    def test_ops_eager(self):
        x = np.array([1.0, -2.0, 3.0], np.float32)
        np.testing.assert_allclose(A.square(x), x ** 2)
        np.testing.assert_allclose(A.abs(x), np.abs(x))
        np.testing.assert_allclose(np.asarray(A.clip(x, -1, 1)),
                                   np.clip(x, -1, 1))

    def test_custom_layer_graph(self):
        from bigdl_tpu.keras.engine import Input, Model
        from bigdl_tpu.nn.layers import Linear

        inp = Input((8,))
        h = Linear(8, 4)(inp)
        out = A.mul(A.softsign(h), 2.0)
        model = Model(inp, out)
        x = np.random.RandomState(0).randn(3, 8).astype(np.float32)
        variables = model.init(jax.random.PRNGKey(0), x)
        y, _ = model.apply(variables, x)
        assert y.shape == (3, 4)
        assert np.abs(np.asarray(y)).max() <= 2.0

    def test_custom_loss_trains(self):
        from bigdl_tpu.keras.engine import Input, Model
        from bigdl_tpu.nn.layers import Linear
        from bigdl_tpu.optim.optim_method import Adam

        rng = np.random.RandomState(0)
        x = rng.randn(64, 4).astype(np.float32)
        y = x @ rng.randn(4, 1).astype(np.float32)

        inp = Input((4,))
        model = Model(inp, Linear(4, 1)(inp))
        loss = A.CustomLoss(
            lambda yt, yp: A.mean(A.square(yp - yt))
            + 0.01 * A.mean(A.abs(yp)))
        model.compile(Adam(learning_rate=1e-1), loss)
        model.fit(x, y, batch_size=32, nb_epoch=50)
        pred = model.predict(x)
        mse = float(np.mean((np.asarray(pred) - y) ** 2))
        assert mse < 0.1, mse


def test_nano_trainer_fit_validate_predict(tmp_path):
    """Reference nano.pytorch.Trainer surface: Lightning-shaped
    fit/validate/predict with bf16 precision toggle."""
    from bigdl_tpu import nn
    from bigdl_tpu.nano import Trainer
    from bigdl_tpu.optim.optim_method import Adam
    from bigdl_tpu.optim.validation import Top1Accuracy

    rs = np.random.RandomState(0)
    x = rs.rand(256, 8).astype(np.float32)
    y = (x.sum(1) > 4).astype(np.int32)
    model = nn.Sequential([nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2)])

    trainer = Trainer(max_epochs=40, batch_size=64, log_every=10000,
                      checkpoint_path=str(tmp_path / "ck"))
    trainer.fit(model, nn.CrossEntropyCriterion(), Adam(learning_rate=5e-3),
                train_data=(x, y), val_data=(x[:64], y[:64]),
                val_methods=[Top1Accuracy()])
    res = trainer.validate((x, y), [Top1Accuracy()])
    assert res["Top1Accuracy"] > 0.8
    pred = trainer.predict(x[:10])
    assert np.asarray(pred).shape == (10, 2)

    # bf16 precision path trains too
    t2 = Trainer(max_epochs=3, batch_size=64, precision="bf16",
                 log_every=10000)
    t2.fit(nn.Sequential([nn.Linear(8, 2)]), nn.CrossEntropyCriterion(),
           Adam(learning_rate=1e-2), train_data=(x, y))
    assert np.asarray(t2.predict(x[:4])).shape == (4, 2)

    import pytest
    with pytest.raises(RuntimeError):
        Trainer().predict(x)
