"""Ring attention / tensor parallel correctness on the simulated 8-device mesh
(the `local[N]` analog — SURVEY.md §5)."""

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

from bigdl_tpu.nn.attention import dot_product_attention
from bigdl_tpu.parallel import ring_attention, tp_linear_pair
from bigdl_tpu.parallel.ring_attention import ring_attention_sharded
from bigdl_tpu.runtime.mesh import AXIS_MODEL, AXIS_SEQ, MeshSpec, build_mesh


@pytest.fixture(scope="module")
def seq_mesh():
    return build_mesh(MeshSpec(data=2, seq=4))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(seq_mesh, causal):
    rs = np.random.RandomState(0)
    b, h, L, d = 2, 3, 32, 8
    q = jnp.asarray(rs.randn(b, h, L, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, L, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, L, d), jnp.float32)

    mask = None
    if causal:
        mask = jnp.tril(jnp.ones((L, L), bool))[None, None]
    ref = dot_product_attention(q, k, v, mask=mask)

    out = ring_attention_sharded(seq_mesh, q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_grad_finite(seq_mesh):
    rs = np.random.RandomState(1)
    b, h, L, d = 1, 2, 16, 4
    q = jnp.asarray(rs.randn(b, h, L, d), jnp.float32)

    def loss(q):
        out = ring_attention_sharded(seq_mesh, q, q, q, causal=True)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(q)
    assert np.all(np.isfinite(np.asarray(g)))


def test_tp_linear_pair_matches_dense():
    mesh = build_mesh(MeshSpec(data=2, model=4))
    rs = np.random.RandomState(2)
    din, dh = 16, 32
    x = jnp.asarray(rs.randn(4, din), jnp.float32)
    w1 = jnp.asarray(rs.randn(din, dh) * 0.1, jnp.float32)
    b1 = jnp.asarray(rs.randn(dh) * 0.1, jnp.float32)
    w2 = jnp.asarray(rs.randn(dh, din) * 0.1, jnp.float32)
    b2 = jnp.asarray(rs.randn(din) * 0.1, jnp.float32)

    ref = jax.nn.gelu(x @ w1 + b1) @ w2 + b2

    fn = shard_map(
        partial(tp_linear_pair, act=jax.nn.gelu),
        mesh=mesh,
        in_specs=(P(), P(None, AXIS_MODEL), P(AXIS_MODEL),
                  P(AXIS_MODEL, None), P()),
        out_specs=P(), check_vma=False)
    out = fn(x, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
