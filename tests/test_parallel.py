"""Ring attention / tensor parallel correctness on the simulated 8-device mesh
(the `local[N]` analog — SURVEY.md §5)."""

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.runtime.mesh import shard_map

from bigdl_tpu.nn.attention import dot_product_attention
from bigdl_tpu.parallel import ring_attention, tp_linear_pair
from bigdl_tpu.parallel.ring_attention import ring_attention_sharded
from bigdl_tpu.runtime.mesh import AXIS_MODEL, AXIS_SEQ, MeshSpec, build_mesh


@pytest.fixture(scope="module")
def seq_mesh():
    return build_mesh(MeshSpec(data=2, seq=4))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(seq_mesh, causal):
    rs = np.random.RandomState(0)
    b, h, L, d = 2, 3, 32, 8
    q = jnp.asarray(rs.randn(b, h, L, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, L, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, L, d), jnp.float32)

    mask = None
    if causal:
        mask = jnp.tril(jnp.ones((L, L), bool))[None, None]
    ref = dot_product_attention(q, k, v, mask=mask)

    out = ring_attention_sharded(seq_mesh, q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_grad_finite(seq_mesh):
    rs = np.random.RandomState(1)
    b, h, L, d = 1, 2, 16, 4
    q = jnp.asarray(rs.randn(b, h, L, d), jnp.float32)

    def loss(q):
        out = ring_attention_sharded(seq_mesh, q, q, q, causal=True)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(q)
    assert np.all(np.isfinite(np.asarray(g)))


def test_tp_linear_pair_matches_dense():
    mesh = build_mesh(MeshSpec(data=2, model=4))
    rs = np.random.RandomState(2)
    din, dh = 16, 32
    x = jnp.asarray(rs.randn(4, din), jnp.float32)
    w1 = jnp.asarray(rs.randn(din, dh) * 0.1, jnp.float32)
    b1 = jnp.asarray(rs.randn(dh) * 0.1, jnp.float32)
    w2 = jnp.asarray(rs.randn(dh, din) * 0.1, jnp.float32)
    b2 = jnp.asarray(rs.randn(din) * 0.1, jnp.float32)

    ref = jax.nn.gelu(x @ w1 + b1) @ w2 + b2

    fn = shard_map(
        partial(tp_linear_pair, act=jax.nn.gelu),
        mesh=mesh,
        in_specs=(P(), P(None, AXIS_MODEL), P(AXIS_MODEL),
                  P(AXIS_MODEL, None), P()),
        out_specs=P())
    out = fn(x, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


class TestGSPMD:
    """GSPMD auto-partitioned training: annotations only, no hand-written
    collectives; results match the single-device program."""

    def _model_and_data(self):
        from bigdl_tpu import nn
        from bigdl_tpu.keras.engine import Input, Model

        rs = np.random.RandomState(0)
        d, heads, t, b = 8, 2, 6, 8
        inp = Input((t, d))
        h = nn.TransformerLayer(d, heads, 4 * d, dropout=0.0)(inp)
        h = nn.Mean(dim=1)(h)
        out = nn.Linear(d, 2)(h)
        model = Model(inp, out)
        x = rs.randn(b, t, d).astype(np.float32)
        y = rs.randint(0, 2, b).astype(np.int32)
        return model, x, y

    def test_matches_single_device_training(self):
        import jax
        from bigdl_tpu import nn
        from bigdl_tpu.optim.optim_method import SGD
        from bigdl_tpu.parallel.gspmd import GSPMDTrainStep
        from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

        model, x, y = self._model_and_data()
        rng = jax.random.PRNGKey(0)
        variables = model.init(rng, jnp.asarray(x[:1]))
        crit = nn.CrossEntropyCriterion()

        mesh = build_mesh(MeshSpec(data=2, model=4))
        # SGD+momentum for the oracle comparison: updates are LINEAR in the
        # gradients, so cross-shard reduction-order noise stays tiny (Adam's
        # g/sqrt(v) early steps amplify 1-ulp differences to ~lr-sized ones)
        step = GSPMDTrainStep(model, crit,
                              SGD(learning_rate=1e-2, momentum=0.9), mesh,
                              variables)
        # QKV/FFN weights are actually model-sharded
        report = step.shard_report()
        assert any("wq" in k for k in report)
        assert any("ffn/l1/weight" in k for k in report)
        losses = [float(step.train_step(i, rng, x, y)) for i in range(5)]

        # single-device oracle: same init, same updates
        from jax.flatten_util import ravel_pytree

        params = jax.tree_util.tree_map(jnp.asarray, variables["params"])
        opt = SGD(learning_rate=1e-2, momentum=0.9)
        state = opt.init_state(params)
        ref_losses = []
        for i in range(5):
            def loss_fn(p):
                out, _ = model.forward(p, {}, jnp.asarray(x),
                                       training=True, rng=rng)
                return crit.forward(out, jnp.asarray(y))
            l, g = jax.value_and_grad(loss_fn)(params)
            params, state = opt.update(i, g, params, state)
            ref_losses.append(float(l))
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=2e-5)
        fa, _ = ravel_pytree(step.get_params())
        fb, _ = ravel_pytree(params)
        np.testing.assert_allclose(np.asarray(fa), np.asarray(fb),
                                   rtol=2e-3, atol=2e-4)

    def test_sharding_actually_splits_buffers(self):
        import jax
        from bigdl_tpu import nn
        from bigdl_tpu.optim.optim_method import Adam
        from bigdl_tpu.parallel.gspmd import GSPMDTrainStep
        from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

        model, x, y = self._model_and_data()
        variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x[:1]))
        mesh = build_mesh(MeshSpec(data=2, model=4))
        step = GSPMDTrainStep(model, nn.CrossEntropyCriterion(),
                              Adam(learning_rate=1e-2), mesh, variables)

        def find(tree, name):
            for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
                if name in "/".join(str(getattr(k, "key", k))
                                    for k in path):
                    return leaf
            raise KeyError(name)

        wq = find(step.params, "wq")
        # column-split over model=4: each shard holds 1/4 of the columns
        shard_shape = wq.addressable_shards[0].data.shape
        assert shard_shape[1] == wq.shape[1] // 4
        # Adam moment for wq is sharded identically (no replicated moments)
        m = find(step.opt_state, "wq")
        assert m.addressable_shards[0].data.shape == shard_shape


def test_gspmd_rank_guard_falls_back_to_replicated():
    import numpy as _np

    from bigdl_tpu.parallel.gspmd import tp_spec_for_path
    from jax.sharding import PartitionSpec as P

    # a 1-D param matching a matrix rule must fall back to replicated,
    # not get a rank-2 spec
    assert tp_spec_for_path("gate/w2", _np.zeros((5,))) == P()
    assert tp_spec_for_path("attn/wq", _np.zeros((4, 8))) == P(None, "model")


def test_gspmd_auto_partitions_encoder_decoder_transformer():
    """The Megatron-style rules shard the NEW translation Transformer's
    MHA/FFN weights (enc + both decoder attentions) and the auto-partitioned
    step executes on a (data x model) mesh."""
    import numpy as np

    from bigdl_tpu.nn import Transformer
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.parallel.gspmd import GSPMDTrainStep, build_param_specs
    from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

    rs = np.random.RandomState(0)
    vocab, t, b = 16, 6, 8
    src = rs.randint(2, vocab, (b, t)).astype(np.int32)
    tgt_in = np.concatenate([np.ones((b, 1), np.int32), src[:, :-1]], 1)
    model = Transformer(vocab, hidden_size=16, num_heads=2, num_layers=1,
                        dropout=0.0)
    variables = model.init(jax.random.PRNGKey(0), src, tgt_in)

    import jax.tree_util as jtu

    specs = build_param_specs(variables["params"])
    flat = jtu.tree_leaves_with_path(
        specs, is_leaf=lambda x: isinstance(x, tuple))
    n_sharded = sum(1 for _, s in flat if len(s) > 0)
    # enc MHA (4) + dec self (4) + dec cross (4) + 3 FFN pairs... >= 16
    assert n_sharded >= 16, n_sharded

    class Wrapper:
        """Adapt (src, tgt) multi-input + 3-D logits to the step's
        (x, y) shape: a tuple batch unpacks into forward's positional
        inputs (the framework-wide multi-input convention), logits
        flatten to (N, V)."""

        def __init__(self, m):
            self.m = m

        def init(self, rng, src, tgt):
            return self.m.init(rng, src, tgt)

        def forward(self, params, state, src, tgt, training=False,
                    rng=None):
            logits, st = self.m.forward(params, state, src, tgt,
                                        training=training, rng=rng)
            return logits.reshape(-1, vocab), st

    mesh = build_mesh(MeshSpec(data=2, model=4))
    step = GSPMDTrainStep(Wrapper(model), CrossEntropyCriterion(),
                          SGD(learning_rate=1e-2), mesh, variables)
    l0 = float(np.asarray(step.train_step(
        0, jax.random.PRNGKey(0), (src, tgt_in), src.reshape(-1))))
    l1 = float(np.asarray(step.train_step(
        1, jax.random.PRNGKey(0), (src, tgt_in), src.reshape(-1))))
    assert np.isfinite(l0) and np.isfinite(l1)
    assert len(step.shard_report()) >= 16


def test_gspmd_remat_matches_plain():
    """remat on the GSPMD step is numerically the identical program."""
    import numpy as np

    from bigdl_tpu.keras.engine import Input as KInput, Model as KModel
    from bigdl_tpu.nn.attention import TransformerLayer
    from bigdl_tpu.nn.layers import Linear
    from bigdl_tpu.nn.layers_extra import Mean
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.parallel.gspmd import GSPMDTrainStep
    from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

    rs = np.random.RandomState(0)
    d = 8
    gi = KInput((6, d))
    gh = TransformerLayer(d, 2, 4 * d, dropout=0.0)(gi)
    go = Linear(d, 2)(Mean(dim=1)(gh))
    gmodel = KModel(gi, go)
    gx = rs.randn(8, 6, d).astype(np.float32)
    gy = rs.randint(0, 2, 8).astype(np.int32)
    rng = jax.random.PRNGKey(0)
    mesh = build_mesh(MeshSpec(data=2, model=4))
    crit = CrossEntropyCriterion()

    losses = {}
    for remat in (False, True):
        gvars = gmodel.init(jax.random.PRNGKey(1), jnp.asarray(gx[:1]))
        step = GSPMDTrainStep(gmodel, crit, SGD(learning_rate=1e-2), mesh,
                              gvars, remat=remat)
        ls = [float(np.asarray(step.train_step(i, rng, gx, gy)))
              for i in range(5)]
        losses[remat] = ls
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-6)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all) sequence parallelism — the second long-context
# strategy: must agree with full attention AND with ring attention.

@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(seq_mesh, causal):
    from bigdl_tpu.parallel import ulysses_attention_sharded

    rs = np.random.RandomState(2)
    b, h, L, d = 2, 4, 32, 8      # heads divisible by the seq axis (4)
    q = jnp.asarray(rs.randn(b, h, L, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, L, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, L, d), jnp.float32)

    mask = None
    if causal:
        mask = jnp.tril(jnp.ones((L, L), bool))[None, None]
    ref = dot_product_attention(q, k, v, mask=mask)

    out = ulysses_attention_sharded(seq_mesh, q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    ring = ring_attention_sharded(seq_mesh, q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ring),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_grad_finite_and_head_constraint(seq_mesh):
    from bigdl_tpu.parallel import ulysses_attention_sharded

    rs = np.random.RandomState(3)
    q = jnp.asarray(rs.randn(1, 4, 16, 4), jnp.float32)

    def loss(q):
        out = ulysses_attention_sharded(seq_mesh, q, q, q, causal=True)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(q)
    assert np.all(np.isfinite(np.asarray(g)))

    # heads (3) not divisible by the seq axis (4) -> clear error
    bad = jnp.asarray(rs.randn(1, 3, 16, 4), jnp.float32)
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention_sharded(seq_mesh, bad, bad, bad)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_grads_match_ring_under_masking(seq_mesh, causal):
    """The two sequence-parallel strategies must agree on GRADIENTS, not
    just outputs, with the causal mask on (VERDICT r4 item 8) — an
    all-to-all layout bug shows up in dq/dk/dv long before it corrupts a
    forward pass at these sizes."""
    from bigdl_tpu.parallel import ulysses_attention_sharded

    rs = np.random.RandomState(13)
    b, h, L, d = 2, 4, 32, 8
    q = jnp.asarray(rs.randn(b, h, L, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, L, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, L, d), jnp.float32)

    def loss_u(args):
        out = ulysses_attention_sharded(seq_mesh, *args, causal=causal)
        return jnp.sum(out ** 2)

    def loss_r(args):
        out = ring_attention_sharded(seq_mesh, *args, causal=causal)
        return jnp.sum(out ** 2)

    gu = jax.grad(loss_u)((q, k, v))
    gr = jax.grad(loss_r)((q, k, v))
    for a, bb, name in zip(gu, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=5e-5, atol=5e-5,
            err_msg=f"d{name} ulysses vs ring (causal={causal})")


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
def test_transformer_layer_seq_parallel_matches_plain(seq_mesh, strategy):
    """MultiHeadAttention(seq_parallel=...) inside a shard_map carrying the
    seq axis == the plain layer on the full sequence (same params)."""
    from bigdl_tpu.nn.attention import TransformerLayer

    rs = np.random.RandomState(5)
    b, L, dmodel, heads = 2, 32, 16, 4
    x = jnp.asarray(rs.randn(b, L, dmodel), jnp.float32)

    plain = TransformerLayer(dmodel, heads, dropout=0.0, causal=True)
    par = TransformerLayer(dmodel, heads, dropout=0.0, causal=True,
                           seq_parallel=strategy)
    variables = plain.init(jax.random.PRNGKey(0), x)
    ref, _ = plain.forward(variables["params"], variables["state"], x,
                           training=False)

    def fwd_block(params, xb):
        out, _ = par.forward(params, {}, xb, training=False)
        return out

    spec = P(None, AXIS_SEQ, None)
    fn = shard_map(fwd_block, mesh=seq_mesh,
                   in_specs=(P(), spec), out_specs=spec)
    out = fn(variables["params"], x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_seq_parallel_validation():
    from bigdl_tpu.nn.attention import MultiHeadAttention

    with pytest.raises(ValueError, match="seq_parallel"):
        MultiHeadAttention(16, 4, seq_parallel="rings")


def test_transformer_layer_seq_parallel_trains(seq_mesh):
    """seq_parallel layers must run training=True with the DEFAULT dropout
    (attention dropout is dropped, residual/FFN dropout kept) and produce
    finite grads."""
    from bigdl_tpu.nn.attention import TransformerLayer

    rs = np.random.RandomState(6)
    x = jnp.asarray(rs.randn(2, 32, 16), jnp.float32)
    layer = TransformerLayer(16, 4, causal=True, seq_parallel="ulysses")
    variables = layer.init(jax.random.PRNGKey(0), x)

    def loss(params, xb, rng):
        out, _ = layer.forward(params, {}, xb, training=True, rng=rng)
        return jnp.sum(out ** 2)

    def block_grad(params, xb, rng):
        g = jax.grad(loss)(params, xb, rng)
        # per-block partial grads sum to the global parameter gradient
        return jax.tree_util.tree_map(
            lambda a: jax.lax.psum(a, AXIS_SEQ), g)

    spec = P(None, AXIS_SEQ, None)
    fn = shard_map(block_grad, mesh=seq_mesh,
                   in_specs=(P(), spec, P()), out_specs=P())
    g = fn(variables["params"], x, jax.random.PRNGKey(1))
    flat = jnp.concatenate([jnp.ravel(l)
                            for l in jax.tree_util.tree_leaves(g)])
    assert np.all(np.isfinite(np.asarray(flat)))
    assert float(jnp.linalg.norm(flat)) > 0.0


class TestSeqParallelTraining:
    """Production long-context path: Optimizer(seq_parallel=True) over a
    (data, seq) mesh must reproduce the flat data-parallel trajectory of
    the same model (dropout 0 => deterministic)."""

    def _model(self, strategy):
        from bigdl_tpu import nn
        from bigdl_tpu.nn.attention import TransformerLayer

        return nn.Sequential([
            nn.Linear(12, 16),
            TransformerLayer(16, 4, dropout=0.0, causal=True,
                             seq_parallel=strategy),
            nn.Linear(16, 12),
        ])

    @pytest.mark.parametrize("strategy", ["ring", "ulysses"])
    def test_matches_flat_dp(self, strategy):
        from bigdl_tpu import nn, optim
        from bigdl_tpu.data.dataset import ArrayDataSet
        from bigdl_tpu.nn.criterion import MSECriterion
        from bigdl_tpu.runtime.engine import Engine, EngineConfig, init_engine
        from bigdl_tpu.runtime.mesh import MeshSpec

        rs = np.random.RandomState(0)
        x = rs.randn(64, 32, 12).astype(np.float32)   # (B, L, D)
        y = np.roll(x, 1, axis=1).astype(np.float32)  # per-token target

        losses = {}
        for label, axes, sp in (("flat", dict(data=-1), None),
                                ("seqpar", dict(data=2, seq=4), strategy)):
            Engine.reset()
            init_engine(EngineConfig(mesh=MeshSpec(**axes)))
            model = self._model(sp)
            opt = optim.Optimizer(model, ArrayDataSet(x, y), MSECriterion(),
                                  batch_size=16, seed=5)
            opt.set_optim_method(optim.SGD(learning_rate=0.05))
            opt.set_end_when(optim.Trigger.max_iteration(8))
            opt.seq_parallel = sp is not None
            opt.log_every = 100
            trained = opt.optimize()
            res = trained.evaluate(ArrayDataSet(x, y),
                                   [optim.Loss(MSECriterion())],
                                   batch_size=16)
            losses[label] = res[0].result
            if sp is not None:
                pred = trained.predict(x[:16])
                assert pred.shape == (16, 32, 12)
                losses["pred_mse"] = float(
                    np.mean((np.asarray(pred) - y[:16]) ** 2))
        Engine.reset()
        assert losses["seqpar"] == pytest.approx(losses["flat"],
                                                 rel=2e-3), losses
        # predict agrees with the evaluated loss scale
        assert losses["pred_mse"] == pytest.approx(losses["seqpar"],
                                                   rel=0.5), losses

    def test_requires_seq_axis(self):
        from bigdl_tpu import nn
        from bigdl_tpu.nn.criterion import MSECriterion
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.optim.train_step import ShardedParameterStep
        from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

        mesh = build_mesh(MeshSpec())   # seq axis of size 1
        model = nn.Linear(4, 4)
        v = model.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.float32))
        with pytest.raises(ValueError, match="seq axis"):
            ShardedParameterStep(model, MSECriterion(), SGD(0.1), mesh, v,
                                 seq_parallel=True)


def test_seq_parallel_rejects_plain_attention_model():
    """A model whose attention layers are NOT seq-parallel-aware must be
    rejected (plain attention would silently attend block-diagonally)."""
    from bigdl_tpu import nn
    from bigdl_tpu.nn.attention import TransformerLayer
    from bigdl_tpu.nn.criterion import MSECriterion
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.train_step import ShardedParameterStep

    mesh = build_mesh(MeshSpec(data=2, seq=4))
    model = nn.Sequential([nn.Linear(8, 16),
                           TransformerLayer(16, 4, dropout=0.0),
                           nn.Linear(16, 8)])
    v = model.init(jax.random.PRNGKey(0),
                   np.zeros((1, 8, 8), np.float32))
    with pytest.raises(ValueError, match="sequence-parallel-aware"):
        ShardedParameterStep(model, MSECriterion(), SGD(0.1), mesh, v,
                             seq_parallel=True)


def test_positional_encoding_global_offsets(seq_mesh):
    """PositionalEncoding under sequence sharding must produce the SAME
    values as on the unsharded sequence (each block offset by its global
    start, not restarting at 0)."""
    from bigdl_tpu.nn.attention import PositionalEncoding

    rs = np.random.RandomState(7)
    x = jnp.asarray(rs.randn(2, 32, 12), jnp.float32)
    layer = PositionalEncoding()
    ref, _ = layer.forward({}, {}, x)

    def block(xb):
        out, _ = layer.forward({}, {}, xb)
        return out

    spec = P(None, AXIS_SEQ, None)
    fn = shard_map(block, mesh=seq_mesh, in_specs=(spec,),
                   out_specs=spec)
    out = fn(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)
