"""Beam search / greedy decode — algorithmic correctness on toy LMs where
the optimal sequence is computable by hand."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.decode import beam_search, greedy_decode

# toy vocab: 0=bos, 1=eos, 2..4 symbols
V = 5
BOS, EOS = 0, 1


def _markov_step(transition):
    """step_fn for a stateless Markov LM: logits depend only on last token."""
    t = jnp.asarray(transition, jnp.float32)

    def step(last, state):
        return jnp.log(t[last] + 1e-12), state

    return step


def test_greedy_follows_argmax_chain():
    # 0 -> 2 -> 3 -> 1(eos) deterministic
    tr = np.full((V, V), 1e-9, np.float32)
    tr[BOS, 2] = 1.0
    tr[2, 3] = 1.0
    tr[3, EOS] = 1.0
    tr[EOS, EOS] = 1.0
    tokens, logp, lengths = greedy_decode(
        _markov_step(tr), {}, batch_size=2, bos_id=BOS, eos_id=EOS,
        max_len=6)
    np.testing.assert_array_equal(np.asarray(tokens[0, :4]), [0, 2, 3, 1])
    assert int(lengths[0]) == 3
    assert abs(float(logp[0])) < 1e-3  # all-prob-1 path


def test_beam_escapes_greedy_trap():
    """Classic trap: first step prefers token 2 (p=.6) but that path dies
    (next is uniform noise); token 3 (p=.4) leads to a certain path.  Greedy
    picks 2; beam>=2 must return the 3-path as best."""
    tr = np.full((V, V), 1e-9, np.float32)
    tr[BOS, 2] = 0.6
    tr[BOS, 3] = 0.4
    # token 2 leads to a fork with low continuation prob
    tr[2, 2] = 0.25
    tr[2, 3] = 0.25
    tr[2, 4] = 0.25
    tr[2, EOS] = 0.25
    # token 3 leads deterministically to eos
    tr[3, EOS] = 1.0
    tr[EOS, EOS] = 1.0

    step = _markov_step(tr)
    g_tokens, _, _ = greedy_decode(step, {}, 1, BOS, EOS, max_len=4)
    assert int(g_tokens[0, 1]) == 2  # greedy falls into the trap

    res = beam_search(step, {}, batch_size=1, vocab_size=V, bos_id=BOS,
                      eos_id=EOS, beam_size=3, max_len=4,
                      length_penalty=0.0)
    # best: [bos, 3, eos] with p=0.4 > [bos, 2, eos] with p=0.15
    np.testing.assert_array_equal(np.asarray(res.tokens[0, 0, :3]),
                                  [0, 3, 1])
    np.testing.assert_allclose(float(res.log_probs[0, 0]), np.log(0.4),
                               atol=1e-4)
    assert int(res.lengths[0, 0]) == 2


def test_beam_batch_rows_independent():
    tr1 = np.full((V, V), 1e-9, np.float32)
    tr1[BOS, 2] = 1.0
    tr1[2, EOS] = 1.0
    tr1[EOS, EOS] = 1.0
    # state-dependent LM: per-batch-row bias selects a different chain
    bias = jnp.asarray([[0.0] * V, [0., 0., -50., 0., 0.]], jnp.float32)

    def step(last, state):
        # state = row bias replicated to (B*K, V)
        return jnp.log(jnp.asarray(tr1)[last] + 1e-12) + state, state

    res = beam_search(step, bias, batch_size=2, vocab_size=V, bos_id=BOS,
                      eos_id=EOS, beam_size=2, max_len=4)
    assert int(res.tokens[0, 0, 1]) == 2     # row 0 takes token 2
    assert int(res.tokens[1, 0, 1]) != 2     # row 1's bias forbids token 2


def test_length_penalty_prefers_longer_when_alpha_high():
    """Two complete hypotheses: short (p=.5) vs 2x longer (p=.3).  With
    alpha=0 the short one wins; with large alpha the longer one wins."""
    tr = np.full((V, V), 1e-9, np.float32)
    tr[BOS, EOS] = 0.5
    tr[BOS, 2] = 0.3
    tr[2, 3] = 1.0
    tr[3, 4] = 1.0
    tr[4, EOS] = 1.0
    tr[EOS, EOS] = 1.0
    step = _markov_step(tr)
    res0 = beam_search(step, {}, 1, V, BOS, EOS, beam_size=3, max_len=6,
                       length_penalty=0.0)
    assert int(res0.lengths[0, 0]) == 1
    res2 = beam_search(step, {}, 1, V, BOS, EOS, beam_size=3, max_len=6,
                       length_penalty=4.0)
    assert int(res2.lengths[0, 0]) == 4


def test_beam_search_jits_and_state_reorders():
    """LSTM-like stateful step under jit: state is (B*K, H) and must be
    gathered with the surviving beams."""
    H = 8
    w = np.random.RandomState(0).randn(H, V).astype(np.float32) * 0.3

    def step(last, state):
        h = jnp.tanh(state + jax.nn.one_hot(last, V) @ w.T)
        return h @ jnp.asarray(w), h

    fn = jax.jit(lambda s: beam_search(
        step, s, batch_size=2, vocab_size=V, bos_id=BOS, eos_id=EOS,
        beam_size=4, max_len=10))
    res = fn(jnp.zeros((2, H)))
    assert res.tokens.shape == (2, 4, 11)
    assert np.isfinite(np.asarray(res.scores)).all()
    # scores sorted descending
    s = np.asarray(res.scores)
    assert (np.diff(s, axis=1) <= 1e-6).all()


class TestSampleDecode:
    def _uniformish(self):
        # bos -> {2,3,4} with probs .5/.3/.2, symbols -> eos
        tr = np.full((V, V), 1e-9, np.float32)
        tr[BOS, 2], tr[BOS, 3], tr[BOS, 4] = 0.5, 0.3, 0.2
        for s in (2, 3, 4):
            tr[s, EOS] = 1.0
        tr[EOS, EOS] = 1.0
        return tr

    def test_temperature_zero_is_greedy(self):
        from bigdl_tpu.nn.decode import sample_decode

        tr = self._uniformish()
        g_tok, _, _ = greedy_decode(_markov_step(tr), {}, 3, BOS, EOS,
                                    max_len=4)
        s_tok, _, _ = sample_decode(_markov_step(tr), {}, 3, BOS, EOS,
                                    jax.random.PRNGKey(0), max_len=4,
                                    temperature=0.0)
        np.testing.assert_array_equal(np.asarray(g_tok), np.asarray(s_tok))

    def test_top_k_one_is_greedy(self):
        from bigdl_tpu.nn.decode import sample_decode

        tr = self._uniformish()
        g_tok, _, _ = greedy_decode(_markov_step(tr), {}, 2, BOS, EOS,
                                    max_len=4)
        s_tok, _, _ = sample_decode(_markov_step(tr), {}, 2, BOS, EOS,
                                    jax.random.PRNGKey(1), max_len=4,
                                    temperature=1.0, top_k=1)
        np.testing.assert_array_equal(np.asarray(g_tok), np.asarray(s_tok))

    def test_sampling_matches_distribution(self):
        from bigdl_tpu.nn.decode import sample_decode

        tr = self._uniformish()
        counts = {2: 0, 3: 0, 4: 0}
        toks, _, _ = sample_decode(_markov_step(tr), {}, 512, BOS, EOS,
                                   jax.random.PRNGKey(2), max_len=2)
        first = np.asarray(toks[:, 1])
        for s in counts:
            counts[s] = int((first == s).sum())
        total = sum(counts.values())
        assert total == 512
        assert abs(counts[2] / total - 0.5) < 0.08
        assert abs(counts[3] / total - 0.3) < 0.08

    def test_top_p_excludes_the_tail(self):
        from bigdl_tpu.nn.decode import sample_decode

        tr = self._uniformish()
        # nucleus .5: only token 2 (p=.5) is kept (prev_mass 0 < .5; next
        # token's prev_mass .5 not < .5) -> deterministic choice of 2
        toks, _, _ = sample_decode(_markov_step(tr), {}, 64, BOS, EOS,
                                   jax.random.PRNGKey(3), max_len=2,
                                   top_p=0.5)
        assert set(np.asarray(toks[:, 1]).tolist()) == {2}

    def test_same_key_is_deterministic_and_jittable(self):
        from functools import partial

        from bigdl_tpu.nn.decode import sample_decode

        tr = self._uniformish()
        fn = jax.jit(partial(sample_decode, _markov_step(tr), {}, 8, BOS,
                             EOS, max_len=4, temperature=1.0, top_k=2))
        a = fn(jax.random.PRNGKey(7))
        b = fn(jax.random.PRNGKey(7))
        np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
        # unfiltered log-likelihood accumulates (negative, finite)
        assert np.isfinite(np.asarray(a[1])).all()


def test_cached_transformer_sampling_path():
    """transformer_decode_cached(rng=...) runs the stochastic decoder over
    the KV-cached step; temperature->0 matches its own greedy path."""
    from bigdl_tpu.nn.attention import Transformer, transformer_decode_cached

    model = Transformer(vocab_size=12, hidden_size=16, num_heads=2,
                        num_layers=1, dropout=0.0, mode="translation")
    src = np.array([[0, 3, 4, 1]], np.int32)
    v = model.init(jax.random.PRNGKey(0), jnp.asarray(src),
                   jnp.asarray(src))
    g_tok, _ = transformer_decode_cached(model, v["params"], src, 0, 1,
                                         max_len=6)
    s_tok, _ = transformer_decode_cached(model, v["params"], src, 0, 1,
                                         max_len=6,
                                         rng=jax.random.PRNGKey(1),
                                         temperature=0.0)
    np.testing.assert_array_equal(np.asarray(g_tok), np.asarray(s_tok))
    # stochastic run with high temperature still emits valid tokens
    r_tok, _ = transformer_decode_cached(model, v["params"], src, 0, 1,
                                         max_len=6,
                                         rng=jax.random.PRNGKey(2),
                                         temperature=2.0, top_k=5)
    r = np.asarray(r_tok)
    assert r.shape == np.asarray(g_tok).shape
    assert ((0 <= r) & (r < 12)).all()
