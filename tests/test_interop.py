"""Torch weight import/export — golden-oracle forward parity."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

torch = pytest.importorskip("torch")

from bigdl_tpu import nn
from bigdl_tpu.utils.interop import from_torch, to_torch

RS = np.random.RandomState(0)
RNG = jax.random.PRNGKey(0)


def _torch_cnn():
    return torch.nn.Sequential(
        torch.nn.Conv2d(3, 8, 3, padding=1),
        torch.nn.BatchNorm2d(8),
        torch.nn.ReLU(),
        torch.nn.MaxPool2d(2),
        torch.nn.Conv2d(8, 16, 3, padding=1),
        torch.nn.ReLU(),
        torch.nn.AdaptiveAvgPool2d(1),
        torch.nn.Flatten(),
        torch.nn.Linear(16, 10),
    )


def _our_cnn():
    return nn.Sequential([
        nn.Conv2D(3, 8, 3, padding=1),
        nn.BatchNorm(8),
        nn.ReLU(),
        nn.MaxPool2D(2),
        nn.Conv2D(8, 16, 3, padding=1),
        nn.ReLU(),
        nn.GlobalAvgPool2D(),
        nn.Linear(16, 10),
    ])


def test_cnn_import_forward_parity():
    tm = _torch_cnn().eval()
    model = _our_cnn()
    x = RS.rand(4, 8, 8, 3).astype(np.float32)
    v = model.init(RNG, jnp.asarray(x))
    v2 = from_torch(tm, model, v)

    y, _ = model.apply(v2, jnp.asarray(x))
    with torch.no_grad():
        ty = tm(torch.tensor(x).permute(0, 3, 1, 2))
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=2e-4)


def test_import_does_not_mutate_input():
    tm = _torch_cnn()
    model = _our_cnn()
    x = jnp.asarray(RS.rand(2, 8, 8, 3).astype(np.float32))
    v = model.init(RNG, x)
    before = np.asarray(v["params"]["0_Conv2D"]["weight"]).copy()
    from_torch(tm, model, v)
    np.testing.assert_array_equal(
        np.asarray(v["params"]["0_Conv2D"]["weight"]), before)


def test_roundtrip_export():
    model = _our_cnn()
    x = RS.rand(2, 8, 8, 3).astype(np.float32)
    v = model.init(RNG, jnp.asarray(x))
    tm = _torch_cnn().eval()
    to_torch(model, v, tm)
    with torch.no_grad():
        ty = tm(torch.tensor(x).permute(0, 3, 1, 2))
    y, _ = model.apply(v, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=2e-4)


def test_embedding_layernorm_prelu_import():
    tm = torch.nn.Sequential(
        torch.nn.Embedding(20, 6),
        torch.nn.LayerNorm(6),
        torch.nn.Flatten(),
        torch.nn.Linear(6 * 7, 8),
        torch.nn.PReLU(8),  # 2-D input: same per-channel convention as ours
        torch.nn.Linear(8, 3),
    ).eval()
    model = nn.Sequential([
        nn.Embedding(20, 6),
        nn.LayerNorm(6),
        nn.Flatten(),
        nn.Linear(6 * 7, 8),
        nn.PReLU(),
        nn.Linear(8, 3),
    ])
    ids = RS.randint(0, 20, (5, 7)).astype(np.int32)
    v = model.init(RNG, jnp.asarray(ids))
    v2 = from_torch(tm, model, v)
    y, _ = model.apply(v2, jnp.asarray(ids))
    with torch.no_grad():
        ty = tm(torch.tensor(ids, dtype=torch.long))
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-4)


def test_structure_mismatch_raises():
    tm = torch.nn.Sequential(torch.nn.Linear(4, 2))
    model = nn.Sequential([nn.Linear(4, 2), nn.Linear(2, 2)])
    x = jnp.ones((1, 4))
    v = model.init(RNG, x)
    with pytest.raises(ValueError, match="structure mismatch"):
        from_torch(tm, model, v)


def test_conv_transpose_import():
    tm = torch.nn.Sequential(
        torch.nn.ConvTranspose2d(3, 5, 3, stride=2, padding=1)).eval()
    model = nn.Sequential([nn.Conv2DTranspose(3, 5, 3, stride=2, padding=1)])
    x = RS.rand(2, 6, 6, 3).astype(np.float32)
    v = model.init(RNG, jnp.asarray(x))
    v2 = from_torch(tm, model, v)
    y, _ = model.apply(v2, jnp.asarray(x))
    with torch.no_grad():
        ty = tm(torch.tensor(x).permute(0, 3, 1, 2)).permute(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=2e-4)


def test_bias_mismatch_refused():
    tm = torch.nn.Sequential(torch.nn.Linear(4, 2, bias=True))
    model = nn.Sequential([nn.Linear(4, 2, with_bias=False)])
    v = model.init(RNG, jnp.ones((1, 4)))
    with pytest.raises(ValueError, match="with_bias=False"):
        from_torch(tm, model, v)


def test_conv_transpose_export_roundtrip():
    model = nn.Sequential([nn.Conv2DTranspose(3, 5, 3, stride=2, padding=1),
                           nn.PReLU()])
    x = RS.rand(2, 6, 6, 3).astype(np.float32)
    v = model.init(RNG, jnp.asarray(x))
    tm = torch.nn.Sequential(
        torch.nn.ConvTranspose2d(3, 5, 3, stride=2, padding=1),
        torch.nn.PReLU(5)).eval()
    to_torch(model, v, tm)
    with torch.no_grad():
        ty = tm(torch.tensor(x).permute(0, 3, 1, 2)).permute(0, 2, 3, 1)
    y, _ = model.apply(v, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=2e-4)
