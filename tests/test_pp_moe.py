"""Pipeline-parallel and MoE/expert-parallel correctness on the simulated
8-device mesh (the `local[N]` analog — SURVEY.md §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from bigdl_tpu.runtime.mesh import shard_map

from bigdl_tpu.parallel.moe import MoE, moe_apply_ep, moe_apply_local
from bigdl_tpu.parallel.pp import (microbatch, pipeline_apply, spmd_pipeline,
                                   stack_stage_params, unmicrobatch)
from bigdl_tpu.runtime.mesh import (AXIS_EXPERT, AXIS_PIPE, MeshSpec,
                                    build_mesh)


# ---------------------------------------------------------------- pipeline
@pytest.fixture(scope="module")
def pipe_mesh():
    return build_mesh(MeshSpec(data=2, pipe=4))


def _mk_stages(rs, n_stages, d):
    stages = [{"w": jnp.asarray(rs.randn(d, d) / np.sqrt(d), jnp.float32),
               "b": jnp.asarray(rs.randn(d) * 0.1, jnp.float32)}
              for _ in range(n_stages)]
    return stages


def _stage_fn(p, x, t):
    # leading stage dim of 1 from the P("pipe") shard
    w, b = p["w"][0], p["b"][0]
    return jnp.tanh(x @ w + b)


def test_pipeline_matches_sequential(pipe_mesh):
    rs = np.random.RandomState(0)
    n_stages, d, B = 4, 6, 8
    stages = _mk_stages(rs, n_stages, d)
    x = jnp.asarray(rs.randn(B, d), jnp.float32)

    ref = x
    for p in stages:
        ref = jnp.tanh(ref @ p["w"] + p["b"])

    stacked = stack_stage_params(stages)
    out = pipeline_apply(pipe_mesh, _stage_fn, stacked, x,
                         num_microbatches=4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_grads_match_sequential(pipe_mesh):
    rs = np.random.RandomState(1)
    n_stages, d, B = 4, 5, 8
    stages = _mk_stages(rs, n_stages, d)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(rs.randn(B, d), jnp.float32)

    def loss_pp(p):
        y = pipeline_apply(pipe_mesh, _stage_fn, p, x, num_microbatches=2)
        return jnp.sum(y ** 2)

    def loss_ref(p):
        y = x
        for i in range(n_stages):
            w = jax.tree_util.tree_map(lambda a: a[i], p)
            y = jnp.tanh(y @ w["w"] + w["b"])
        return jnp.sum(y ** 2)

    g_pp = jax.grad(loss_pp)(stacked)
    g_ref = jax.grad(loss_ref)(stacked)
    for a, b in zip(jax.tree_util.tree_leaves(g_pp),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_microbatch_roundtrip():
    x = jnp.arange(24.0).reshape(8, 3)
    mb = microbatch(x, 4)
    assert mb.shape == (4, 2, 3)
    np.testing.assert_array_equal(np.asarray(unmicrobatch(mb)), np.asarray(x))


# ---------------------------------------------------------------- MoE
def test_moe_module_runs_and_differentiates():
    rs = np.random.RandomState(0)
    layer = MoE(num_experts=4, hidden=16, k=2, capacity_factor=2.0)
    x = jnp.asarray(rs.randn(2, 6, 8), jnp.float32)
    v = layer.init(jax.random.PRNGKey(0), x)
    y, st = layer.apply(v, x)
    assert y.shape == x.shape
    assert float(st["aux_loss"]) >= 0.0

    def loss(p):
        out, _ = layer.forward(p, {}, x)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(v["params"])
    assert all(np.isfinite(np.asarray(t)).all()
               for t in jax.tree_util.tree_leaves(g))


def test_moe_high_capacity_routes_all_tokens():
    # with capacity >= T every token reaches its top-k experts: the combine
    # weights must sum to 1 per token
    rs = np.random.RandomState(1)
    from bigdl_tpu.parallel.moe import moe_gate

    logits = jnp.asarray(rs.randn(16, 4), jnp.float32)
    gate = moe_gate(logits, capacity=16, k=2)
    sums = np.asarray(jnp.sum(gate.combine, axis=(1, 2)))
    np.testing.assert_allclose(sums, np.ones(16), rtol=1e-5)


def test_moe_ep_matches_local():
    mesh = build_mesh(MeshSpec(data=2, expert=4))
    rs = np.random.RandomState(2)
    T, d, E, H = 16, 8, 8, 16
    params = {
        "wg": jnp.asarray(rs.randn(d, E) * 0.1, jnp.float32),
        "w1": jnp.asarray(rs.randn(E, d, H) * 0.1, jnp.float32),
        "b1": jnp.asarray(rs.randn(E, H) * 0.1, jnp.float32),
        "w2": jnp.asarray(rs.randn(E, H, d) * 0.1, jnp.float32),
        "b2": jnp.asarray(rs.randn(E, d) * 0.1, jnp.float32),
    }
    x = jnp.asarray(rs.randn(T, d), jnp.float32)

    y_ref, aux_ref = moe_apply_local(params, x, capacity_factor=4.0, k=2)

    n_shards = mesh.shape[AXIS_EXPERT]

    def fn(p, xx):
        y, aux = moe_apply_ep(p, xx, n_expert_shards=n_shards,
                              capacity_factor=4.0, k=2)
        return y, aux

    pspec = {k: P(AXIS_EXPERT) if k != "wg" else P()
             for k in params}
    mapped = shard_map(fn, mesh=mesh, in_specs=(pspec, P()),
                       out_specs=(P(), P()))
    y_ep, aux_ep = mapped(params, x)
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(float(aux_ep), float(aux_ref), rtol=1e-5)


# ------------------------------------------------------- circular pipeline
def test_circular_pipeline_matches_sequential(pipe_mesh):
    """n_stages=4 devices x circular_repeats=2 -> 8 layers, each device
    owning layers {s, s+4}; output must equal sequential application."""
    from bigdl_tpu.parallel.pp import (pipeline_apply_circular,
                                       stack_stage_params_circular)

    rs = np.random.RandomState(2)
    n_stages, k, d, B = 4, 2, 6, 8
    layers = _mk_stages(rs, n_stages * k, d)
    x = jnp.asarray(rs.randn(B, d), jnp.float32)

    ref = x
    for p in layers:
        ref = jnp.tanh(ref @ p["w"] + p["b"])

    stacked = stack_stage_params_circular(layers, n_stages)
    out = pipeline_apply_circular(pipe_mesh, _stage_fn, stacked, x,
                                  num_microbatches=4, circular_repeats=k)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("k", [2, 3])
def test_circular_pipeline_grads_match_sequential(pipe_mesh, k):
    """k=3 (12 layers over 4 stages, device s owning {s, s+4, s+8}):
    grads through three full ring traversals must still match the
    sequential reference (VERDICT r4 item 8)."""
    from bigdl_tpu.parallel.pp import (pipeline_apply_circular,
                                       stack_stage_params_circular)

    rs = np.random.RandomState(3)
    n_stages, d, B = 4, 5, 8
    layers = _mk_stages(rs, n_stages * k, d)
    stacked = stack_stage_params_circular(layers, n_stages)
    x = jnp.asarray(rs.randn(B, d), jnp.float32)
    # sequential reference follows the INTERLEAVED row order back to
    # logical layer order: row s*k + v holds layer v*n + s
    order = [v * n_stages + s for s in range(n_stages) for v in range(k)]
    inv = np.argsort(order)

    def loss_pp(p):
        y = pipeline_apply_circular(pipe_mesh, _stage_fn, p, x,
                                    num_microbatches=4,
                                    circular_repeats=k)
        return jnp.sum(y ** 2)

    def loss_ref(p):
        y = x
        for li in range(n_stages * k):
            w = jax.tree_util.tree_map(lambda a: a[inv[li]], p)
            y = jnp.tanh(y @ w["w"] + w["b"])
        return jnp.sum(y ** 2)

    g_pp = jax.grad(loss_pp)(stacked)
    g_ref = jax.grad(loss_ref)(stacked)
    for a, b in zip(jax.tree_util.tree_leaves(g_pp),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_circular_reduces_tick_count():
    """The schedule claim: M·k + n − 1 ticks vs blocked GPipe's
    (M + n − 1)·k layer-applications."""
    n, k, M = 4, 4, 8
    circular = (M // n) * n * k + n - 1
    blocked = (M + n - 1) * k
    assert circular == M * k + n - 1 == 35
    assert blocked == 44
    assert circular < blocked


def test_circular_pipeline_validation(pipe_mesh):
    from bigdl_tpu.parallel.pp import (pipeline_apply_circular,
                                       stack_stage_params_circular)

    rs = np.random.RandomState(4)
    layers = _mk_stages(rs, 8, 4)
    with pytest.raises(ValueError, match="divisible"):
        stack_stage_params_circular(layers[:7], 4)
    stacked = stack_stage_params_circular(layers, 4)
    x = jnp.asarray(rs.randn(8, 4), jnp.float32)
    with pytest.raises(ValueError, match="divisible"):
        pipeline_apply_circular(pipe_mesh, _stage_fn, stacked, x,
                                num_microbatches=2, circular_repeats=2)


def test_circular_pipeline_rejects_mismatched_repeats(pipe_mesh):
    """Wrong circular_repeats must raise, not clamp layer indices into
    silently wrong numerics."""
    from bigdl_tpu.parallel.pp import (pipeline_apply_circular,
                                       stack_stage_params_circular)

    rs = np.random.RandomState(5)
    layers = _mk_stages(rs, 8, 4)             # n=4, k=2
    stacked = stack_stage_params_circular(layers, 4)
    x = jnp.asarray(rs.randn(8, 4), jnp.float32)
    with pytest.raises(ValueError, match="circular_repeats"):
        pipeline_apply_circular(pipe_mesh, _stage_fn, stacked, x,
                                num_microbatches=4, circular_repeats=4)


# ------------------------------------------------------- pipeline training
class TestPipelineTrainStep:
    def _setup(self, k=1):
        from bigdl_tpu.parallel.pp import (stack_stage_params,
                                           stack_stage_params_circular)

        rs = np.random.RandomState(7)
        n, d, B = 4, 6, 16
        layers = _mk_stages(rs, n * k, d)
        if k > 1:
            stacked = stack_stage_params_circular(layers, n)
            order = [v * n + s for s in range(n) for v in range(k)]
        else:
            stacked = stack_stage_params(layers)
            order = list(range(n))
        x = rs.randn(B, d).astype(np.float32)
        y = rs.randn(B, d).astype(np.float32)
        return layers, stacked, order, x, y

    @pytest.mark.parametrize("k", [1, 2])
    def test_matches_sequential_training(self, pipe_mesh, k):
        """dp x pipe training == training the unstacked sequential model
        with the same optimizer (SGD is linear in grads)."""
        from bigdl_tpu.nn.criterion import MSECriterion
        from bigdl_tpu.optim.optim_method import SGD
        from bigdl_tpu.parallel.pp_train import PipelineTrainStep

        layers, stacked, order, x, y = self._setup(k)
        crit = MSECriterion()
        engine = PipelineTrainStep(_stage_fn, stacked, crit,
                                   SGD(learning_rate=0.1), pipe_mesh,
                                   num_microbatches=4, circular_repeats=k)
        losses = [float(np.asarray(engine.train_step(i, x, y)))
                  for i in range(6)]

        # sequential oracle on the same (reordered) layers
        opt = SGD(learning_rate=0.1)
        params = [dict(w=jnp.asarray(p["w"]), b=jnp.asarray(p["b"]))
                  for p in layers]
        state = opt.init_state(params)
        ref_losses = []
        xj, yj = jnp.asarray(x), jnp.asarray(y)
        for i in range(6):
            def loss_fn(ps):
                h = xj
                for p in ps:
                    h = jnp.tanh(h @ p["w"] + p["b"])
                return jnp.mean((h - yj) ** 2)
            l, g = jax.value_and_grad(loss_fn)(params)
            params, state = opt.update(i, g, params, state)
            ref_losses.append(float(l))
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-4,
                                   atol=1e-5)
        # trained stacked params match the oracle's (row order mapping)
        got = engine.get_params()
        for row, layer_idx in enumerate(order):
            np.testing.assert_allclose(got["w"][row],
                                       np.asarray(params[layer_idx]["w"]),
                                       rtol=1e-4, atol=1e-5)

    def test_rejects_layerwise_optimizer(self, pipe_mesh):
        from bigdl_tpu.nn.criterion import MSECriterion
        from bigdl_tpu.optim.optim_method import LarsSGD as LARS
        from bigdl_tpu.parallel.pp import stack_stage_params
        from bigdl_tpu.parallel.pp_train import PipelineTrainStep

        rs = np.random.RandomState(8)
        stacked = stack_stage_params(_mk_stages(rs, 4, 4))
        with pytest.raises(ValueError, match="elementwise"):
            PipelineTrainStep(_stage_fn, stacked, MSECriterion(),
                              LARS(learning_rate=0.1), pipe_mesh,
                              num_microbatches=4)


def test_pipeline_train_guards(pipe_mesh):
    """Caller buffers survive donation (defensive copy) and multislice
    meshes are rejected."""
    from bigdl_tpu.nn.criterion import MSECriterion
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.parallel.pp import stack_stage_params
    from bigdl_tpu.parallel.pp_train import PipelineTrainStep
    from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

    rs = np.random.RandomState(9)
    stacked = stack_stage_params(_mk_stages(rs, 4, 4))
    x = rs.randn(8, 4).astype(np.float32)
    y = rs.randn(8, 4).astype(np.float32)
    eng = PipelineTrainStep(_stage_fn, stacked, MSECriterion(),
                            SGD(learning_rate=0.1), pipe_mesh,
                            num_microbatches=4)
    eng.train_step(0, x, y)
    # the caller's stacked arrays are still readable post-donation
    assert np.isfinite(np.asarray(stacked["w"]).sum())

    msl = build_mesh(MeshSpec(dcn_data=2, pipe=2, data=2))
    with pytest.raises(ValueError, match="multislice"):
        PipelineTrainStep(_stage_fn, stacked, MSECriterion(),
                          SGD(learning_rate=0.1), msl,
                          num_microbatches=4)

    # 8 stacked layers on a 4-stage mesh with k=1 still shards evenly
    # (2 rows/device) but would silently train only every other layer —
    # the constructor must reject the row-count mismatch up front
    stacked8 = stack_stage_params(_mk_stages(rs, 8, 4))
    with pytest.raises(ValueError, match="n_stages\\*circular_repeats"):
        PipelineTrainStep(_stage_fn, stacked8, MSECriterion(),
                          SGD(learning_rate=0.1), pipe_mesh,
                          num_microbatches=4)
