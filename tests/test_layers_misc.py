"""Tranche-4 layer/criterion tests — golden-oracle parity vs torch where a
torch twin exists (the reference's Torch7-parity spec pattern, SURVEY.md §5).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.tensor.sparse import SparseTensor


def test_lookup_table_sparse_combiners():
    rng = np.random.RandomState(0)
    table = rng.randn(10, 4).astype(np.float32)
    # batch of 3 rows: row0 has ids [1, 2], row1 has [5], row2 has [7, 7, 3]
    indices = np.array([[0, 0], [0, 1], [1, 0], [2, 0], [2, 1], [2, 2]])
    ids = np.array([1, 2, 5, 7, 7, 3], np.float32)
    sp = SparseTensor(indices, ids, (3, 3))

    for combiner in ("sum", "mean", "sqrtn"):
        layer = nn.LookupTableSparse(10, 4, combiner=combiner)
        variables = layer.init(jax.random.PRNGKey(0), sp)
        variables["params"]["weight"] = jnp.asarray(table)
        y, _ = layer.apply(variables, sp)
        rows = [table[[1, 2]], table[[5]], table[[7, 7, 3]]]
        if combiner == "sum":
            expect = np.stack([r.sum(0) for r in rows])
        elif combiner == "mean":
            expect = np.stack([r.mean(0) for r in rows])
        else:
            expect = np.stack([r.sum(0) / np.sqrt(len(r)) for r in rows])
        np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-5,
                                   atol=1e-6)


def test_lookup_table_sparse_weighted():
    rng = np.random.RandomState(1)
    table = rng.randn(6, 3).astype(np.float32)
    indices = np.array([[0, 0], [0, 1], [1, 0]])
    sp = SparseTensor(indices, np.array([2, 4, 1], np.float32), (2, 2))
    wts = SparseTensor(indices, np.array([0.5, 2.0, 3.0], np.float32), (2, 2))
    layer = nn.LookupTableSparse(6, 3, combiner="sum")
    variables = layer.init(jax.random.PRNGKey(0), sp)
    variables["params"]["weight"] = jnp.asarray(table)
    y, _ = layer.apply(variables, sp, wts)
    expect = np.stack([0.5 * table[2] + 2.0 * table[4], 3.0 * table[1]])
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-5, atol=1e-6)


def test_within_channel_lrn_matches_caffe_formula():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 6, 6, 3).astype(np.float32)
    size, alpha, beta = 3, 2.0, 0.75
    layer = nn.SpatialWithinChannelLRN(size, alpha, beta)
    y, _ = layer.apply({"params": {}, "state": {}}, x)

    pad = size // 2
    xp = np.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    expect = np.empty_like(x)
    for i in range(6):
        for j in range(6):
            win = xp[:, i:i + size, j:j + size, :]
            ssum = (win ** 2).sum(axis=(1, 2))
            expect[:, i, j, :] = x[:, i, j, :] / (
                1 + alpha / size ** 2 * ssum) ** beta
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-4, atol=1e-5)


def test_normalize_scale():
    rng = np.random.RandomState(3)
    x = rng.randn(2, 4, 4, 8).astype(np.float32)
    layer = nn.NormalizeScale(8, scale=20.0)
    variables = layer.init(jax.random.PRNGKey(0), x)
    y, _ = layer.apply(variables, x)
    expect = x / np.sqrt((x ** 2).sum(-1, keepdims=True) + 1e-10) * 20.0
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-4, atol=1e-5)


def test_roi_pooling_shapes_and_max_semantics():
    rng = np.random.RandomState(4)
    feat = rng.rand(16, 16, 5).astype(np.float32)
    boxes = np.array([[0, 0, 8, 8], [4, 4, 12, 12]], np.float32)
    layer = nn.RoiPooling(output_size=4, spatial_scale=1.0)
    y, _ = layer.apply({"params": {}, "state": {}}, feat, boxes)
    y = np.asarray(y)
    assert y.shape == (2, 4, 4, 5)
    # pooled values are bounded by the box-region max
    region = feat[0:9, 0:9]
    assert (y[0] <= region.max(axis=(0, 1)) + 1e-5).all()
    assert y.max() <= feat.max() + 1e-5


def test_lstm_peephole_runs_and_uses_peepholes():
    rng = np.random.RandomState(5)
    x = rng.randn(2, 7, 4).astype(np.float32)
    layer = nn.LSTMPeephole(4, 6)
    variables = layer.init(jax.random.PRNGKey(0), x)
    assert variables["params"]["peep"].shape == (3, 6)
    y0, _ = layer.apply(variables, x)
    assert np.asarray(y0).shape == (2, 7, 6)
    # non-zero peepholes change the output (they're actually wired in)
    variables["params"]["peep"] = variables["params"]["peep"] + 0.5
    y1, _ = layer.apply(variables, x)
    assert not np.allclose(np.asarray(y0), np.asarray(y1))


def test_ctc_criterion_torch_parity():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(6)
    B, T, C, S = 3, 12, 7, 5
    logits = rng.randn(B, T, C).astype(np.float32)
    labels = rng.randint(1, C, size=(B, S)).astype(np.int32)
    input_lengths = np.array([12, 10, 8])
    label_lengths = np.array([5, 3, 2])
    labels_padded = labels.copy()
    for b, L in enumerate(label_lengths):
        labels_padded[b, L:] = 0

    crit = nn.CTCCriterion(blank=0, size_average=False)
    loss = crit(jnp.asarray(logits),
                (labels_padded, input_lengths, label_lengths))

    lp = torch.log_softmax(torch.tensor(logits), dim=-1).transpose(0, 1)
    tloss = torch.nn.CTCLoss(blank=0, reduction="sum")(
        lp, torch.tensor(labels_padded.astype(np.int64)),
        torch.tensor(input_lengths), torch.tensor(label_lengths))
    np.testing.assert_allclose(float(loss), float(tloss), rtol=1e-4)


def test_ctc_criterion_differentiable():
    rng = np.random.RandomState(7)
    logits = jnp.asarray(rng.randn(2, 6, 5).astype(np.float32))
    labels = np.array([[1, 2, 0], [3, 0, 0]], np.int32)
    crit = nn.CTCCriterion()

    g = jax.grad(lambda lg: crit(lg, (labels, np.array([6, 5]),
                                      np.array([2, 1]))))(logits)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).sum()) > 0


def test_class_simplex_criterion_geometry():
    crit = nn.ClassSimplexCriterion(4)
    m = np.asarray(crit.simplex)
    np.testing.assert_allclose((m ** 2).sum(-1), np.ones(4), atol=1e-6)
    gram = m @ m.T
    off = gram[~np.eye(4, dtype=bool)]
    np.testing.assert_allclose(off, -1 / 3, atol=1e-6)

    # loss is zero exactly at the class vertex
    x = jnp.asarray(m[[2, 0]])
    assert float(crit(x, jnp.asarray([2, 0]))) < 1e-10
    assert float(crit(x, jnp.asarray([1, 3]))) > 0.1


def test_weighted_mse_torch_parity():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(8)
    x = rng.randn(4, 5).astype(np.float32)
    y = rng.randn(4, 5).astype(np.float32)
    w = rng.rand(4, 5).astype(np.float32)
    crit = nn.WeightedMSECriterion()
    ours = float(crit(jnp.asarray(x), (jnp.asarray(y), jnp.asarray(w))))
    ref = float((torch.tensor(w) * (torch.tensor(x) - torch.tensor(y)) ** 2)
                .mean())
    np.testing.assert_allclose(ours, ref, rtol=1e-5)


def test_echo_is_identity(capsys):
    x = np.ones((2, 3), np.float32)
    layer = nn.Echo("probe")
    y, _ = layer.apply({"params": {}, "state": {}}, x)
    np.testing.assert_array_equal(np.asarray(y), x)


def test_dilated_share_conv_aliases():
    assert nn.SpatialDilatedConvolution is nn.Conv2D
    assert nn.SpatialShareConvolution is nn.Conv2D


def test_nn_image_reader_and_imageframe_read(tmp_path):
    from PIL import Image

    from bigdl_tpu.nnframes import NNImageReader

    rng = np.random.RandomState(9)
    for i in range(3):
        arr = rng.randint(0, 255, size=(10 + i, 12, 3), dtype=np.uint8)
        Image.fromarray(arr).save(tmp_path / f"img_{i}.png")

    df = NNImageReader.read_images(str(tmp_path / "*.png"), resize=8)
    assert len(df) == 3
    assert all(im.shape == (8, 8, 3) for im in df["image"])
    assert df["origin"][0].endswith("img_0.png")
    assert list(df["n_channels"]) == [3, 3, 3]


def test_prediction_service_concurrent_and_error_contract():
    import threading

    from bigdl_tpu.nn.module import Sequential
    from bigdl_tpu.optim import PredictionService

    model = Sequential([nn.Linear(4, 2)])
    x = np.random.RandomState(10).randn(8, 4).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    svc = PredictionService(model, variables, n_replicas=2)

    expect, _ = model.apply(variables, x)
    results = [None] * 8
    def worker(i):
        results[i] = svc.predict(x)
    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for r in results:
        np.testing.assert_allclose(r, np.asarray(expect), rtol=1e-5,
                                   atol=1e-6)

    out, err = svc.try_predict(np.ones((2, 999), np.float32))  # bad shape
    assert out is None and err is not None


def test_lstm_peephole_bf16_carry():
    x = jnp.asarray(np.random.RandomState(11).randn(2, 5, 4),
                    jnp.bfloat16)
    layer = nn.LSTMPeephole(4, 3)
    variables = layer.init(jax.random.PRNGKey(0), np.zeros((2, 5, 4),
                                                           np.float32))
    y, _ = layer.apply(variables, x)
    assert np.asarray(y).shape == (2, 5, 3)


def test_echo_message_with_braces():
    layer = nn.Echo("gate {0}")
    y, _ = layer.apply({"params": {}, "state": {}},
                       np.ones((2, 2), np.float32))
    np.testing.assert_array_equal(np.asarray(y), np.ones((2, 2)))


def test_imageframe_read_label_mismatch_raises(tmp_path):
    from PIL import Image

    from bigdl_tpu.data.vision import ImageFrame

    for i in range(2):
        Image.fromarray(np.zeros((4, 4, 3), np.uint8)).save(
            tmp_path / f"a_{i}.png")
    with pytest.raises(ValueError, match="labels for"):
        ImageFrame.read(str(tmp_path / "*.png"), labels=[0])


def test_lookup_table_sparse_pad_id_ignored():
    table = np.arange(12, dtype=np.float32).reshape(4, 3)
    # row0: ids [1, pad]; row1: ids [0] (id 0 is REAL in 0-based indexing)
    indices = np.array([[0, 0], [0, 1], [1, 0]])
    sp = SparseTensor(indices, np.array([1, -1, 0], np.float32), (2, 2))
    for combiner, expect in (
            ("sum", np.stack([table[1], table[0]])),
            ("mean", np.stack([table[1], table[0]]))):
        layer = nn.LookupTableSparse(4, 3, combiner=combiner)
        variables = layer.init(jax.random.PRNGKey(0), sp)
        variables["params"]["weight"] = jnp.asarray(table)
        y, _ = layer.apply(variables, sp)
        np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-6)


def test_index_and_bifurcate_split():
    x = np.arange(24, dtype=np.float32).reshape(4, 6)
    idx = np.array([2, 0, 3])
    empty = {"params": {}, "state": {}}
    y, _ = nn.Index(0).apply(empty, x, idx)
    np.testing.assert_array_equal(np.asarray(y), x[[2, 0, 3]])

    (a, b), _ = nn.BifurcateSplitTable(-1).apply(empty, x)
    np.testing.assert_array_equal(np.asarray(a), x[:, :3])
    np.testing.assert_array_equal(np.asarray(b), x[:, 3:])


def test_negative_entropy_penalty():
    p = np.full((2, 4), 0.25, np.float32)  # uniform -> max entropy
    crit = nn.NegativeEntropyPenalty(beta=1.0)
    v_uniform = float(crit(jnp.asarray(p)))
    peaked = np.array([[0.97, 0.01, 0.01, 0.01]] * 2, np.float32)
    v_peaked = float(crit(jnp.asarray(peaked)))
    # sum(p log p) is most negative at the uniform distribution, so peaked
    # (low-entropy) outputs receive the HIGHER penalty value — that is the
    # criterion's purpose (discourage overconfident predictions)
    assert v_peaked > v_uniform
    assert v_uniform < 0 and v_peaked < 0


def test_unfold_matches_manual_patches():
    rng = np.random.RandomState(12)
    x = rng.randn(1, 5, 5, 2).astype(np.float32)
    layer = nn.Unfold(3, stride=1, padding=0)
    y, _ = layer.apply({"params": {}, "state": {}}, x)
    y = np.asarray(y)
    assert y.shape == (1, 9, 18)
    # first patch, channel-major (C, kh, kw) rows
    manual = np.transpose(x[0, :3, :3, :], (2, 0, 1)).reshape(-1)
    np.testing.assert_allclose(y[0, 0], manual, rtol=1e-6)


def test_multilabel_margin_torch_parity():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(13)
    x = rng.randn(4, 6).astype(np.float32)
    # target rows: class indices padded with -1
    t = np.array([[1, 3, -1, -1, -1, -1],
                  [0, -1, -1, -1, -1, -1],
                  [2, 4, 5, -1, -1, -1],
                  [5, -1, -1, -1, -1, -1]], np.int64)
    ours = float(nn.MultiLabelMarginCriterion()(jnp.asarray(x), t))
    ref = float(torch.nn.MultiLabelMarginLoss()(torch.tensor(x),
                                                torch.tensor(t)))
    np.testing.assert_allclose(ours, ref, rtol=1e-5)


def test_smooth_l1_with_weights():
    rng = np.random.RandomState(14)
    x = rng.randn(3, 4).astype(np.float32)
    y = rng.randn(3, 4).astype(np.float32)
    w_in = rng.rand(3, 4).astype(np.float32)
    w_out = rng.rand(3, 4).astype(np.float32)
    crit = nn.SmoothL1CriterionWithWeights(sigma=1.0, size_average=False)
    got = float(crit(jnp.asarray(x), (y, w_in, w_out)))
    d = w_in * (x - y)
    ad = np.abs(d)
    expect = (w_out * np.where(ad < 1, 0.5 * d * d, ad - 0.5)).sum()
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_spatial_dropout3d_channelwise():
    rng = np.random.RandomState(15)
    x = np.ones((2, 3, 4, 5, 6), np.float32)
    layer = nn.SpatialDropout3D(0.5)
    y, _ = layer.forward({}, {}, x, training=True,
                         rng=jax.random.PRNGKey(0))
    y = np.asarray(y)
    # each channel is either fully zero or fully scaled
    per_channel = y.reshape(2, -1, 6)
    for b in range(2):
        for ch in range(6):
            vals = np.unique(per_channel[b, :, ch])
            assert len(vals) == 1
    # identity in eval mode
    y2, _ = layer.forward({}, {}, x, training=False)
    np.testing.assert_array_equal(np.asarray(y2), x)


def test_contiguous_copy_identity():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    for layer in (nn.Contiguous(), nn.Copy()):
        y, _ = layer.apply({"params": {}, "state": {}}, x)
        np.testing.assert_array_equal(np.asarray(y), x)


def test_multilabel_margin_garbage_after_terminator():
    torch = pytest.importorskip("torch")
    x = np.random.RandomState(16).randn(1, 6).astype(np.float32)
    t = np.array([[2, -1, 4, 0, 0, 0]], np.int64)  # garbage after -1
    ours = float(nn.MultiLabelMarginCriterion()(jnp.asarray(x), t))
    ref = float(torch.nn.MultiLabelMarginLoss()(torch.tensor(x),
                                                torch.tensor(t)))
    np.testing.assert_allclose(ours, ref, rtol=1e-5)


def test_spatial_dropout3d_p1_returns_zeros():
    x = np.ones((1, 2, 2, 2, 3), np.float32)
    y, _ = nn.SpatialDropout3D(1.0).forward({}, {}, x, training=True,
                                            rng=jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(y), np.zeros_like(x))
