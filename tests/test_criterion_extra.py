"""Extended criterions, validation methods, LBFGS — numeric checks with torch
golden oracles where a torch equivalent exists (SURVEY.md §5 parity pattern)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn
from bigdl_tpu.optim.optim_method import LBFGS
from bigdl_tpu.optim.validation import AUC, HitRatio, NDCG

RS = np.random.RandomState(0)


def test_multi_criterion_weighted_sum():
    x = jnp.asarray(RS.rand(4, 3).astype(np.float32))
    t = jnp.asarray(RS.rand(4, 3).astype(np.float32))
    mc = nn.MultiCriterion().add(nn.MSECriterion(), 2.0).add(
        nn.AbsCriterion(), 1.0)
    want = 2.0 * float(nn.MSECriterion()(x, t)) + float(nn.AbsCriterion()(x, t))
    np.testing.assert_allclose(float(mc(x, t)), want, rtol=1e-6)


def test_margin_family_torch_parity():
    torch = pytest.importorskip("torch")
    x = RS.randn(6, 5).astype(np.float32)
    y = RS.randint(0, 5, (6,))
    got = float(nn.MultiMarginCriterion()(jnp.asarray(x), jnp.asarray(y)))
    want = float(torch.nn.MultiMarginLoss()(torch.tensor(x),
                                            torch.tensor(y)))
    np.testing.assert_allclose(got, want, rtol=1e-5)

    d = np.abs(RS.randn(8).astype(np.float32))
    t = np.where(RS.rand(8) > 0.5, 1.0, -1.0).astype(np.float32)
    got = float(nn.HingeEmbeddingCriterion(margin=1.0)(
        jnp.asarray(d), jnp.asarray(t)))
    want = float(torch.nn.HingeEmbeddingLoss(margin=1.0)(
        torch.tensor(d), torch.tensor(t)))
    np.testing.assert_allclose(got, want, rtol=1e-5)

    s = RS.randn(8).astype(np.float32)
    got = float(nn.SoftMarginCriterion()(jnp.asarray(s), jnp.asarray(t)))
    want = float(torch.nn.SoftMarginLoss()(torch.tensor(s), torch.tensor(t)))
    np.testing.assert_allclose(got, want, rtol=1e-5)

    ml_t = (RS.rand(4, 5) > 0.5).astype(np.float32)
    logits = RS.randn(4, 5).astype(np.float32)
    got = float(nn.MultiLabelSoftMarginCriterion()(
        jnp.asarray(logits), jnp.asarray(ml_t)))
    want = float(torch.nn.MultiLabelSoftMarginLoss()(
        torch.tensor(logits), torch.tensor(ml_t)))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_kl_poisson_torch_parity():
    torch = pytest.importorskip("torch")
    logp = np.log(RS.dirichlet(np.ones(4), 5).astype(np.float32))
    q = RS.dirichlet(np.ones(4), 5).astype(np.float32)
    # DistKLDivCriterion == KLDivCriterion (one impl): element-mean reduction,
    # torch KLDivLoss reduction="mean"
    assert nn.DistKLDivCriterion is nn.KLDivCriterion
    got = float(nn.DistKLDivCriterion()(jnp.asarray(logp), jnp.asarray(q)))
    want = float(torch.nn.KLDivLoss(reduction="mean")(
        torch.tensor(logp), torch.tensor(q)))
    np.testing.assert_allclose(got, want, rtol=1e-4)

    rate = np.abs(RS.randn(6).astype(np.float32)) + 0.1
    tgt = RS.poisson(2.0, 6).astype(np.float32)
    got = float(nn.PoissonCriterion()(jnp.asarray(rate), jnp.asarray(tgt)))
    want = float(torch.nn.PoissonNLLLoss(log_input=False, full=False)(
        torch.tensor(rate), torch.tensor(tgt)))
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_keras_style_losses():
    p = np.clip(RS.dirichlet(np.ones(3), 4).astype(np.float32), 1e-6, 1)
    t = np.eye(3, dtype=np.float32)[RS.randint(0, 3, 4)]
    got = float(nn.CategoricalCrossEntropy()(jnp.asarray(p), jnp.asarray(t)))
    manual = -np.mean(np.sum(t * np.log(p), axis=-1))
    np.testing.assert_allclose(got, manual, rtol=1e-4)

    kld = float(nn.KullbackLeiblerDivergenceCriterion()(
        jnp.asarray(p), jnp.asarray(p)))
    np.testing.assert_allclose(kld, 0.0, atol=1e-6)

    x = np.abs(RS.randn(5).astype(np.float32)) + 0.5
    msle = float(nn.MeanSquaredLogarithmicCriterion()(
        jnp.asarray(x), jnp.asarray(x)))
    assert msle < 1e-10
    mape = float(nn.MeanAbsolutePercentageCriterion()(
        jnp.asarray(x * 1.1), jnp.asarray(x)))
    np.testing.assert_allclose(mape, 10.0, rtol=1e-3)


def test_cosine_dice_vae_l1cost():
    x = RS.randn(4, 6).astype(np.float32)
    np.testing.assert_allclose(
        float(nn.CosineDistanceCriterion()(jnp.asarray(x), jnp.asarray(x))),
        0.0, atol=1e-6)
    np.testing.assert_allclose(
        float(nn.CosineProximityCriterion()(jnp.asarray(x), jnp.asarray(x))),
        -1.0, rtol=1e-5)

    mask = (RS.rand(2, 8) > 0.5).astype(np.float32)
    dice_perfect = float(nn.DiceCoefficientCriterion()(
        jnp.asarray(mask), jnp.asarray(mask)))
    assert dice_perfect < 0.1
    dice_bad = float(nn.DiceCoefficientCriterion()(
        jnp.asarray(mask), jnp.asarray(1.0 - mask)))
    assert dice_bad > dice_perfect

    mean = jnp.zeros((3, 4))
    log_var = jnp.zeros((3, 4))
    np.testing.assert_allclose(float(nn.KLDCriterion()((mean, log_var))),
                               0.0, atol=1e-6)
    g = float(nn.GaussianCriterion()((mean, log_var), jnp.zeros((3, 4))))
    np.testing.assert_allclose(g, 0.5 * np.log(2 * np.pi) * 4, rtol=1e-5)

    np.testing.assert_allclose(
        float(nn.L1Cost()(jnp.asarray([[-1.0, 2.0]]))), 3.0)

    pos, neg = jnp.asarray([2.0, 0.1]), jnp.asarray([0.5, 0.5])
    rh = float(nn.RankHingeCriterion()((pos, neg)))
    np.testing.assert_allclose(rh, 0.5 * (0.0 + 1.4), rtol=1e-5)

    x1 = jnp.asarray([[1.0, 0.0], [0.0, 0.0]])
    x2 = jnp.asarray([[1.0, 0.0], [3.0, 4.0]])
    l1h = float(nn.L1HingeEmbeddingCriterion(margin=10.0)(
        (x1, x2), jnp.asarray([1.0, -1.0])))
    np.testing.assert_allclose(l1h, 0.5 * (0.0 + 3.0), rtol=1e-5)


def test_margin_criterion_and_transformer():
    x = jnp.asarray([0.5, -2.0])
    y = jnp.asarray([1.0, -1.0])
    got = float(nn.MarginCriterion()(x, y))
    np.testing.assert_allclose(got, 0.5 * (0.5 + 0.0), rtol=1e-6)

    tc = nn.TransformerCriterion(nn.MSECriterion(),
                                 input_transform=lambda v: v * 2.0)
    np.testing.assert_allclose(
        float(tc(jnp.asarray([1.0]), jnp.asarray([2.0]))), 0.0, atol=1e-7)


def test_all_extra_criterions_differentiable():
    """Every new criterion must be jax.grad-able (the autodiff replaces the
    reference's hand-written backward)."""
    x = jnp.asarray(RS.rand(4, 3).astype(np.float32) + 0.1)
    t01 = jnp.asarray((RS.rand(4, 3) > 0.5).astype(np.float32))
    tpm = jnp.asarray(np.where(RS.rand(4, 3) > 0.5, 1.0, -1.0).astype(np.float32))
    cases = [
        (nn.MultiLabelSoftMarginCriterion(), x, t01),
        (nn.MultiMarginCriterion(), x, jnp.asarray([0, 1, 2, 0])),
        (nn.HingeEmbeddingCriterion(), x, tpm),
        (nn.MarginCriterion(), x, tpm),
        (nn.SoftMarginCriterion(), x, tpm),
        (nn.DiceCoefficientCriterion(), x, t01),
        (nn.PoissonCriterion(), x, t01),
        (nn.DistKLDivCriterion(), jnp.log(x), x),
        (nn.KullbackLeiblerDivergenceCriterion(), x, x),
        (nn.MeanAbsolutePercentageCriterion(), x, x + 0.5),
        (nn.MeanSquaredLogarithmicCriterion(), x, x + 0.5),
        (nn.CategoricalCrossEntropy(), x, t01),
        (nn.CosineDistanceCriterion(), x, x + 0.1),
        (nn.CosineProximityCriterion(), x, x + 0.1),
        (nn.L1Cost(), x, None),
    ]
    for crit, inp, tgt in cases:
        g = jax.grad(lambda v: crit(v, tgt))(inp)
        assert np.all(np.isfinite(np.asarray(g))), type(crit).__name__


# ---- validation methods ---------------------------------------------------

def test_hit_ratio_and_ndcg():
    # 4 rows, positive at index 0; scores rank it 1st, 2nd, 3rd, last
    scores = jnp.asarray([
        [9.0, 1.0, 2.0, 3.0],
        [2.5, 9.0, 2.0, 1.0],
        [2.0, 9.0, 8.0, 1.0],
        [0.0, 9.0, 8.0, 7.0],
    ])
    tgt = jnp.zeros((4,), jnp.int32)
    hr2 = HitRatio(k=2)
    s, c = hr2.batch_stats(scores, tgt)
    np.testing.assert_allclose(float(s) / float(c), 0.5)  # ranks 0,1,2,3

    nd = NDCG(k=4)
    s, c = nd.batch_stats(scores, tgt)
    want = np.mean([1.0, 1 / np.log2(3), 1 / np.log2(4), 1 / np.log2(5)])
    np.testing.assert_allclose(float(s) / float(c), want, rtol=1e-5)

    # a collapsed (constant-score) model must NOT look perfect: ties get
    # half credit, so with 8 candidates rank = 3.5 → no hit at k=2
    const = jnp.ones((2, 8))
    s, c = HitRatio(k=2).batch_stats(const, jnp.zeros((2,), jnp.int32))
    assert float(s) == 0.0


def test_auc_batchwise():
    sklearn_like_auc = 1.0  # perfectly separable
    score = jnp.asarray([0.9, 0.8, 0.2, 0.1])
    t = jnp.asarray([1, 1, 0, 0])
    s, c = AUC().batch_stats(score[:, None], t)
    np.testing.assert_allclose(float(s) / float(c), sklearn_like_auc)
    # random interleave → 0.5 with ties
    score2 = jnp.asarray([0.5, 0.5, 0.5, 0.5])
    s, c = AUC().batch_stats(score2[:, None], t)
    np.testing.assert_allclose(float(s) / float(c), 0.5)


# ---- LBFGS ----------------------------------------------------------------

def test_lbfgs_quadratic_beats_sgd():
    """LBFGS on an ill-conditioned quadratic: must reach the optimum far
    faster than first-order SGD at the same step budget."""
    A = jnp.asarray(np.diag([100.0, 1.0]).astype(np.float32))
    b = jnp.asarray([1.0, -3.0])

    def loss(p):
        return 0.5 * p @ A @ p - b @ p

    opt = LBFGS(learning_rate=0.5, history_size=5)
    p = {"w": jnp.asarray([5.0, 5.0])}
    st = opt.init_state(p)
    for i in range(60):
        g = {"w": jax.grad(loss)(p["w"])}
        p, st = opt.update(i, g, p, st)
    final = float(loss(p["w"]))
    optimum = float(loss(jnp.linalg.solve(A, b)))
    assert final - optimum < 1e-3, (final, optimum)


def test_lbfgs_trains_model():
    from bigdl_tpu.nn.criterion import MSECriterion

    x = jnp.asarray(RS.rand(32, 4).astype(np.float32))
    w_true = jnp.asarray(RS.rand(4, 2).astype(np.float32))
    y = x @ w_true
    model = nn.Linear(4, 2)
    v = model.init(jax.random.PRNGKey(0), x)
    crit = MSECriterion()
    opt = LBFGS(learning_rate=0.8)
    params, st = v["params"], opt.init_state(v["params"])
    for i in range(40):
        g = jax.grad(lambda pr: crit(model.forward(pr, {}, x)[0], y))(params)
        params, st = opt.update(i, g, params, st)
    final = float(crit(model.forward(params, {}, x)[0], y))
    assert final < 1e-4, final


def test_hitratio_nan_scores_rank_last():
    scores = jnp.asarray([[np.nan, 1.0, 2.0], [5.0, 1.0, np.nan]])
    tgt = jnp.zeros((2,), jnp.int32)
    s, c = HitRatio(k=3).batch_stats(scores, tgt)
    # NaN anywhere in the row disqualifies it — diverged models score 0
    np.testing.assert_allclose(float(s), 0.0)
