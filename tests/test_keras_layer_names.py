"""Keras-1 API name-breadth tests — reference keras/layers/*.scala surface."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import keras as K


def test_all_exports_resolve():
    for name in K.__all__:
        assert getattr(K, name) is not None, name


def test_merge_modes():
    rng = np.random.RandomState(0)
    a = rng.randn(3, 4).astype(np.float32)
    b = rng.randn(3, 4).astype(np.float32)
    empty = {"params": {}, "state": {}}
    checks = {
        "sum": a + b,
        "mul": a * b,
        "ave": (a + b) / 2,
        "max": np.maximum(a, b),
        "concat": np.concatenate([a, b], -1),
        "dot": (a * b).sum(-1, keepdims=True),
    }
    for mode, expect in checks.items():
        y, _ = K.Merge(mode).apply(empty, a, b)
        np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-5,
                                   atol=1e-6)
    y, _ = K.Merge("cosine").apply(empty, a, b)
    expect = (a * b).sum(-1, keepdims=True) / (
        np.linalg.norm(a, axis=-1, keepdims=True)
        * np.linalg.norm(b, axis=-1, keepdims=True) + 1e-8)
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-4, atol=1e-5)


def test_merge_in_functional_graph():
    ia = K.Input((4,))
    ib = K.Input((4,))
    ha = K.Dense(8)(ia)
    hb = K.Dense(8)(ib)
    out = K.Merge("sum")([ha, hb])
    model = K.Model([ia, ib], out)
    xa = np.random.RandomState(1).randn(2, 4).astype(np.float32)
    xb = np.random.RandomState(2).randn(2, 4).astype(np.float32)
    v = model.init(jax.random.PRNGKey(0), xa, xb)
    y, _ = model.apply(v, xa, xb)
    assert np.asarray(y).shape == (2, 8)


def test_bidirectional_and_maxout_dense():
    x = np.random.RandomState(3).randn(2, 6, 4).astype(np.float32)
    bi = K.Bidirectional(K.LSTM(4, 5))
    v = bi.init(jax.random.PRNGKey(0), x)
    y, _ = bi.apply(v, x)
    assert np.asarray(y).shape == (2, 6, 10)  # concat merge

    md = K.MaxoutDense(4, 7, nb_feature=3)
    x2 = np.random.RandomState(4).randn(5, 4).astype(np.float32)
    v2 = md.init(jax.random.PRNGKey(1), x2)
    y2, _ = md.apply(v2, x2)
    assert np.asarray(y2).shape == (5, 7)


def test_atrous_convolutions():
    conv = K.AtrousConvolution2D(3, 6, 3, atrous_rate=2, padding="SAME")
    assert conv.dilation == (2, 2)
    x = np.random.RandomState(5).randn(1, 10, 10, 3).astype(np.float32)
    v = conv.init(jax.random.PRNGKey(0), x)
    y, _ = conv.apply(v, x)
    assert np.asarray(y).shape == (1, 10, 10, 6)

    c1 = K.AtrousConvolution1D(3, 5, 3, atrous_rate=2, padding="SAME")
    x1 = np.random.RandomState(6).randn(2, 12, 3).astype(np.float32)
    v1 = c1.init(jax.random.PRNGKey(1), x1)
    y1, _ = c1.apply(v1, x1)
    assert np.asarray(y1).shape == (2, 12, 5)


def test_cropping3d():
    from bigdl_tpu import nn

    x = np.random.RandomState(7).randn(1, 6, 8, 10, 2).astype(np.float32)
    layer = nn.Cropping3D(((1, 1), (2, 0), (0, 3)))
    y, _ = layer.apply({"params": {}, "state": {}}, x)
    np.testing.assert_array_equal(np.asarray(y), x[:, 1:5, 2:, :7, :])


def test_activation_factory_breadth_and_error():
    import pytest as _pytest

    for name in ("relu", "relu6", "hard_sigmoid", "softplus", "softsign",
                 "silu", "swish", "mish", "linear"):
        assert K.Activation(name) is not None
    with _pytest.raises(ValueError, match="unknown activation"):
        K.Activation("totally_bogus")


def test_multi_input_fit_predict_evaluate():
    """Two-input functional model through fit/predict with list inputs —
    the reference keras API's multi-input path."""
    ia = K.Input((5,))
    ib = K.Input((5,))
    m = K.Merge("concat")([K.Dense(8)(ia), K.Dense(8)(ib)])
    out = K.Dense(2)(K.Activation("relu")(m))
    from bigdl_tpu.optim import Adam, Top1Accuracy

    model = K.Model([ia, ib], out)
    model.compile(optimizer=Adam(learning_rate=1e-2),
                  loss="sparse_categorical_crossentropy")

    rng = np.random.RandomState(0)
    xa = rng.randn(96, 5).astype(np.float32)
    xb = rng.randn(96, 5).astype(np.float32)
    y = ((xa.sum(1) + xb.sum(1)) > 0).astype(np.int32)
    model.fit([xa, xb], y, batch_size=32, epochs=15, log_every=1000,
              validation_data=([xa[:32], xb[:32]], y[:32]))
    pred = model.predict([xa, xb])
    assert pred.shape == (96, 2)
    acc = (np.argmax(pred, -1) == y).mean()
    assert acc > 0.85, acc
    # batched predict path matches full-batch predict
    pred_b = model.predict([xa, xb], batch_size=40)
    np.testing.assert_allclose(pred, pred_b, rtol=1e-5, atol=1e-5)
    # evaluate with list inputs
    res = model.evaluate([xa, xb], y)
    assert res


def test_list_of_samples_still_means_one_array():
    """Regression: a plain python list of samples on a single-input model
    keeps its keras meaning (stacked into one array), and is NOT
    reinterpreted as a multi-input pack."""
    inp = K.Input((4,))
    model = K.Model(inp, K.Dense(2)(inp))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    x_list = [[0.1 * i, 0.2, 0.3, 0.4] for i in range(32)]
    y = np.arange(32) % 2
    model.fit(x_list, y, batch_size=16, epochs=1, log_every=1000)
    pred = model.predict(x_list)
    assert pred.shape == (32, 2)
    res = model.evaluate(x_list, y)
    assert res


def test_multi_input_fit_without_labels_raises():
    import pytest as _pytest

    ia = K.Input((3,))
    ib = K.Input((3,))
    model = K.Model([ia, ib], K.Merge("sum")([ia, ib]))
    model.compile(optimizer="adam", loss="mse")
    xa = np.zeros((8, 3), np.float32)
    with _pytest.raises(ValueError, match="requires"):
        model.fit([xa, xa])
