"""Serialization round-trip over an auto-enumerated layer catalog.

Reference pattern (SURVEY.md §5): ``utils/serializer/*SerializerSpec`` —
enumerate registered layers, save/load each, compare outputs.  Here the
catalog is a spec table (layer factory + sample input shapes); every entry is
inited, saved with ``utils/serializer.save_model``, reloaded against the
init template, and its forward output compared bit-for-bit."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.utils.serializer import load_model, save_model

RNG = jax.random.PRNGKey(42)
RS = np.random.RandomState(42)

# (name, factory, input_shapes) — one entry per layer family.  Layers whose
# forward needs rng/training are exercised in eval mode (deterministic).
CATALOG = [
    ("Linear", lambda: nn.Linear(6, 4), [(3, 6)]),
    ("Bilinear", lambda: nn.Bilinear(3, 4, 5), [(2, 3), (2, 4)]),
    ("Conv1D", lambda: nn.Conv1D(3, 5, 3, padding="SAME"), [(2, 8, 3)]),
    ("Conv2D", lambda: nn.Conv2D(3, 5, 3, padding="SAME"), [(2, 8, 8, 3)]),
    ("Conv3D", lambda: nn.Conv3D(2, 4, 3, padding="SAME"), [(1, 4, 6, 6, 2)]),
    ("Conv2DTranspose", lambda: nn.Conv2DTranspose(3, 4, 3, stride=2),
     [(1, 5, 5, 3)]),
    ("Conv3DTranspose", lambda: nn.Conv3DTranspose(2, 3, 3, stride=2),
     [(1, 3, 4, 4, 2)]),
    ("DepthwiseConv2D", lambda: nn.DepthwiseConv2D(4, 1, 3), [(1, 6, 6, 4)]),
    ("SeparableConv2D", lambda: nn.SeparableConv2D(3, 6, 3), [(1, 6, 6, 3)]),
    ("LocallyConnected1D", lambda: nn.LocallyConnected1D(3, 4, 3),
     [(2, 8, 3)]),
    ("LocallyConnected2D", lambda: nn.LocallyConnected2D(2, 3, 3),
     [(1, 6, 6, 2)]),
    ("ConvLSTM2D", lambda: nn.ConvLSTM2D(2, 3, 3), [(1, 2, 5, 5, 2)]),
    ("BatchNorm", lambda: nn.BatchNorm(5), [(4, 5)]),
    ("LayerNorm", lambda: nn.LayerNorm(6), [(3, 6)]),
    ("RMSNorm", lambda: nn.RMSNorm(6), [(3, 6)]),
    ("PReLU", lambda: nn.PReLU(), [(3, 6)]),
    ("SReLU", lambda: nn.SReLU(), [(3, 6)]),
    ("Embedding", lambda: nn.Embedding(10, 4), [None]),  # int input
    ("CMul", lambda: nn.CMul((6,)), [(3, 6)]),
    ("CAdd", lambda: nn.CAdd((6,)), [(3, 6)]),
    ("Mul", lambda: nn.Mul(), [(3, 6)]),
    ("Add", lambda: nn.Add(6), [(3, 6)]),
    ("Scale", lambda: nn.Scale((6,)), [(3, 6)]),
    ("Cosine", lambda: nn.Cosine(4, 3), [(2, 4)]),
    ("Euclidean", lambda: nn.Euclidean(4, 3), [(2, 4)]),
    ("Maxout", lambda: nn.Maxout(5, 3, 2), [(4, 5)]),
    ("Highway", lambda: nn.Highway(), [(3, 6)]),
    ("SimpleRNN", lambda: nn.SimpleRNN(4, 3), [(2, 5, 4)]),
    ("LSTM", lambda: nn.LSTM(4, 3), [(2, 5, 4)]),
    ("GRU", lambda: nn.GRU(4, 3), [(2, 5, 4)]),
    ("BiRecurrent", lambda: nn.BiRecurrent(nn.LSTM(4, 3)), [(2, 5, 4)]),
    ("MultiHeadAttention", lambda: nn.MultiHeadAttention(8, 2), [(2, 5, 8)]),
    ("TransformerLayer", lambda: nn.TransformerLayer(8, 2, 16), [(2, 5, 8)]),
    ("Sequential", lambda: nn.Sequential(
        [nn.Linear(6, 8), nn.ReLU(), nn.BatchNorm(8), nn.Linear(8, 2)]),
     [(3, 6)]),
    ("MapTable", lambda: nn.MapTable(nn.Linear(6, 2)), [(3, 6), (3, 6)]),
    ("Bottle", lambda: nn.Bottle(nn.Linear(6, 2)), [(2, 3, 6)]),
    # layers_tail tranche (round 2)
    ("GroupNorm", lambda: nn.GroupNorm(2, 6), [(3, 6)]),
    ("InstanceNorm2D", lambda: nn.InstanceNorm2D(3), [(2, 5, 5, 3)]),
    ("SpatialConvolutionMap",
     lambda: nn.SpatialConvolutionMap([[0, 0], [1, 1]], 3, 2, 2, padding=1),
     [(1, 5, 5, 2)]),
    ("BinaryTreeLSTM", lambda: nn.BinaryTreeLSTM(4, 6),
     lambda: [RS.rand(2, 3, 4).astype(np.float32),
              np.array([[[-1, -1], [-1, -1], [0, 1]]] * 2, np.int32)]),
]


def _sample(shape):
    if shape is None:  # Embedding-style integer input
        return RS.randint(0, 10, size=(3, 5)).astype(np.int32)
    return RS.rand(*shape).astype(np.float32)


@pytest.mark.parametrize("name,factory,shapes",
                         CATALOG, ids=[c[0] for c in CATALOG])
def test_roundtrip(tmp_path, name, factory, shapes):
    layer = factory()
    xs = shapes() if callable(shapes) else [_sample(s) for s in shapes]
    v = layer.init(RNG, *xs)
    y0, _ = layer.apply(v, *xs, training=False)

    path = str(tmp_path / name)
    save_model(path, layer, v)
    v2 = load_model(path, template=layer.init(jax.random.PRNGKey(7), *xs))
    y1, _ = layer.apply(v2, *xs, training=False)

    for a, b in zip(jax.tree_util.tree_leaves(y0),
                    jax.tree_util.tree_leaves(y1)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_quantized_linear_roundtrip(tmp_path):
    """QuantizedLinear is built by conversion (``from_linear``), not init —
    round-trip its int8 weight + scales through the durable format."""
    from bigdl_tpu.nn.quantized import QuantizedLinear

    x = RS.rand(3, 6).astype(np.float32)
    lin = nn.Linear(6, 4)
    v = lin.init(RNG, x)
    q, qp = QuantizedLinear.from_linear(lin, v["params"])
    y0, _ = q.forward(qp, {}, x)

    save_model(str(tmp_path / "q"), q, {"params": qp})
    loaded = load_model(str(tmp_path / "q"), template={"params": qp})
    y1, _ = q.forward(loaded["params"], {}, x)
    np.testing.assert_array_equal(np.asarray(y0), np.asarray(y1))


def test_load_without_template_rebuilds_tree(tmp_path):
    layer = nn.Sequential([nn.Linear(4, 3), nn.Tanh(), nn.Linear(3, 2)])
    x = RS.rand(2, 4).astype(np.float32)
    v = layer.init(RNG, x)
    save_model(str(tmp_path / "m"), layer, v)
    raw = load_model(str(tmp_path / "m"))
    # nested dict rebuilt from flat paths; params present and numerically equal
    flat0 = jax.tree_util.tree_leaves(v["params"])
    flat1 = jax.tree_util.tree_leaves(raw["params"])
    assert len(flat0) == len(flat1)
    for a, b in zip(sorted(np.asarray(a).ravel()[0] for a in flat0),
                    sorted(np.asarray(b).ravel()[0] for b in flat1)):
        np.testing.assert_allclose(a, b)


def test_shape_mismatch_rejected(tmp_path):
    layer = nn.Linear(4, 3)
    x = RS.rand(2, 4).astype(np.float32)
    v = layer.init(RNG, x)
    save_model(str(tmp_path / "m"), layer, v)
    other = nn.Linear(5, 3)
    x5 = RS.rand(2, 5).astype(np.float32)
    with pytest.raises((ValueError, KeyError)):
        load_model(str(tmp_path / "m"), template=other.init(RNG, x5))


def test_weight_only_linear_roundtrip(tmp_path):
    from bigdl_tpu.nn.quantized import WeightOnlyLinear

    x = RS.rand(3, 6).astype(np.float32)
    lin = nn.Linear(6, 4)
    v = lin.init(RNG, x)
    q, qp = WeightOnlyLinear.from_linear(lin, v["params"])
    y0, _ = q.forward(qp, {}, x)
    save_model(str(tmp_path / "wo"), q, {"params": qp})
    loaded = load_model(str(tmp_path / "wo"), template={"params": qp})
    assert loaded["params"]["weight_q"].dtype == np.int8 or \
        str(loaded["params"]["weight_q"].dtype) == "int8"
    y1, _ = q.forward(loaded["params"], {}, x)
    np.testing.assert_array_equal(np.asarray(y0), np.asarray(y1))


def test_lora_variables_roundtrip(tmp_path):
    from bigdl_tpu.nn.lora import apply_lora
    from bigdl_tpu.nn.module import Sequential

    x = RS.rand(4, 6).astype(np.float32)
    model = Sequential([nn.Linear(6, 8), nn.Tanh(), nn.Linear(8, 2)])
    v = model.init(RNG, x)
    lmodel, lvars = apply_lora(model, v, rank=2)
    y0, _ = lmodel.apply(lvars, x)
    save_model(str(tmp_path / "lora"), lmodel, lvars)
    loaded = load_model(str(tmp_path / "lora"), template=lvars)
    y1, _ = lmodel.apply(loaded, x)
    np.testing.assert_array_equal(np.asarray(y0), np.asarray(y1))
