"""Declarative sharding layer (docs/parallelism.md §Declarative layouts).

Tier-1 specs: the ``parallelism=`` combo-string parser (unknown axes and
over-subscribed factors fail EARLY, naming the valid axes and the live
device count), layout-table COMPLETENESS for the transformer / seq2seq /
two-tower families (a new parameter landing in silent-replicate fails),
the replicated-params audit gauge + flight line, the ACCEPTANCE pair —
fsdp x tp training of the 12L transformer matches the dp loss trajectory
from one seed, and the same checkpoint serves model-sharded through
``InferenceModel``/``DecodeEngine`` with zero unexpected recompiles —
plus the Estimator/keras ``parallelism=`` surfaces, the per-axis
collective-bytes ledger math, and the MULTICHIP_LAYOUT sentinel family.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from bigdl_tpu.nn.attention import Transformer
from bigdl_tpu.nn.criterion import CrossEntropyCriterion
from bigdl_tpu.optim.optim_method import SGD
from bigdl_tpu.parallel.gspmd import GSPMDTrainStep, fit_layout
from bigdl_tpu.parallel.layout import (
    SpecLayout, collective_bytes_by_axis, layout_for_model,
    register_layout, tp_activation_bytes, transformer_layout)
from bigdl_tpu.parallel.mesh_policy import (mesh_and_layout,
                                            parse_parallelism,
                                            resolve_parallelism)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
VOCAB, T = 32, 8


# ---------------------------------------------------------------------------
# model zoo for the suite
# ---------------------------------------------------------------------------

def _lm12():
    """THE 12L transformer of the acceptance criteria (GPT-2-small-class
    depth at test width)."""
    return Transformer(VOCAB, hidden_size=16, num_heads=2, ffn_size=32,
                       num_layers=12, dropout=0.0, mode="lm")


def _seq2seq():
    """The translation-mode (seq2seq) Transformer — WMT config family."""
    return Transformer(VOCAB, hidden_size=16, num_heads=2, ffn_size=32,
                       num_layers=2, dropout=0.0, mode="translation")


def _two_tower():
    from bigdl_tpu.models.recsys import TwoTower

    return TwoTower(n_users=32, n_items=64, dim=8, hidden=(16,))


class _LMWrap:
    """(b, t) ids -> flat (N, V) logits, the criterion-friendly shape."""

    def __init__(self, m):
        self.m = m

    def init(self, rng, x):
        return self.m.init(rng, x)

    def forward(self, params, state, x, training=False, rng=None):
        logits, st = self.m.forward(params, state, x, training=training,
                                    rng=rng)
        return logits.reshape(-1, VOCAB), st


class _FlatCE:
    """CrossEntropy over flattened (b, t) integer targets."""

    def __init__(self):
        self.ce = CrossEntropyCriterion()

    def forward(self, out, y):
        return self.ce.forward(out, jnp.reshape(y, (-1,)))


def _param_shapes(model, *init_args):
    """Parameter SHAPES via eval_shape — no compute, no compile."""
    shapes = jax.eval_shape(lambda r, args: model.init(r, *args),
                            jax.random.PRNGKey(0), tuple(init_args))
    return shapes["params"]


# ---------------------------------------------------------------------------
# parallelism= policy strings
# ---------------------------------------------------------------------------

class TestParallelismPolicy:
    def test_parse_and_resolve(self):
        assert parse_parallelism("dp") == {"data": -1}
        assert parse_parallelism("fsdp:2,tp:4") == {"fsdp": 2, "tp": 4}
        # aliases normalize
        assert parse_parallelism("mp:2,sp:2") == {"tp": 2, "seq": 2}
        assert resolve_parallelism("dp", 8) == {
            "data": 8, "fsdp": 1, "tp": 1, "seq": 1}
        # the fill axis absorbs the remainder
        assert resolve_parallelism("tp:2,dp", 8)["data"] == 4
        assert resolve_parallelism("dp:2,fsdp:2,tp:2", 8) == {
            "data": 2, "fsdp": 2, "tp": 2, "seq": 1}

    def test_unknown_axis_lists_valid_axes(self):
        with pytest.raises(ValueError, match="unknown axis 'zz'.*fsdp"):
            parse_parallelism("dp:4,zz:2")

    def test_oversubscription_lists_live_device_count(self):
        with pytest.raises(ValueError,
                           match="needs 16 devices but only 8"):
            resolve_parallelism("dp:8,tp:2", 8)

    def test_parse_rejects_malformed(self):
        with pytest.raises(ValueError, match="given twice"):
            parse_parallelism("dp:2,data:4")
        with pytest.raises(ValueError, match="omit its factor"):
            parse_parallelism("dp,tp")
        with pytest.raises(ValueError, match="must be >= 1"):
            parse_parallelism("dp:0")
        with pytest.raises(ValueError, match="not an integer"):
            parse_parallelism("tp:two")
        with pytest.raises(ValueError, match="non-empty"):
            parse_parallelism("")

    def test_non_divisible_fill_fails(self):
        with pytest.raises(ValueError, match="not divisible"):
            resolve_parallelism("tp:3,dp", 8)

    def test_under_subscription_warns_idle_devices(self):
        # package root has propagate=False, so collect records directly
        import logging

        records = []
        lg = logging.getLogger("bigdl_tpu.parallel.mesh_policy")
        h = logging.Handler()
        h.emit = records.append
        lg.addHandler(h)
        try:
            sizes = resolve_parallelism("dp:2,tp:2", 8)
        finally:
            lg.removeHandler(h)
        assert sizes["data"] == 2  # sub-mesh stays legal (serving tp:N)
        assert any("stay idle" in r.getMessage() for r in records)

    def test_mesh_and_layout_shape(self):
        r = mesh_and_layout("fsdp:2,tp:2")
        assert dict(r.mesh.shape) == {"data": 1, "fsdp": 2, "seq": 1,
                                      "tp": 2}
        assert r.n_batch_shards == 2
        assert r.model_sharded
        assert not mesh_and_layout("dp").model_sharded

    def test_engine_config_env(self, monkeypatch):
        from bigdl_tpu.runtime.engine import EngineConfig

        monkeypatch.setenv("BIGDL_TPU_PARALLELISM", "FSDP:2,TP:4")
        assert EngineConfig.from_env().parallelism == "fsdp:2,tp:4"
        monkeypatch.delenv("BIGDL_TPU_PARALLELISM")
        assert EngineConfig.from_env().parallelism is None


# ---------------------------------------------------------------------------
# canonical specs + layout-table completeness (the satellite test: a new
# parameter landing in silent-replicate FAILS here)
# ---------------------------------------------------------------------------

class TestSpecLayout:
    def test_canonical_specs(self):
        sl = SpecLayout()
        assert sl.vocab_embedding() == P(("fsdp", "tp"), None)
        assert sl.hidden_in() == P("fsdp", "tp")
        assert sl.hidden_out() == P("tp", "fsdp")
        assert sl.col_bias() == P("tp")
        assert sl.norm() == P("fsdp")
        assert sl.batch_spec(2) == P(("data", "fsdp"), "seq")
        assert sl.batch_spec(1) == P(("data", "fsdp"))

    def test_legacy_degradation(self):
        """fsdp/seq = None collapse to the old 2-axis (data x model)
        specs, keeping the rank guard meaningful."""
        sl = SpecLayout(fsdp=None, tp="model", seq=None)
        assert sl.hidden_in() == P(None, "model")
        assert sl.hidden_out() == P("model", None)
        assert sl.vocab_embedding() == P("model", None)
        assert sl.batch_spec(1) == P("data")

    def test_tp_spec_for_path_shim_unchanged(self):
        from bigdl_tpu.parallel.gspmd import tp_spec_for_path

        assert tp_spec_for_path("attn/wq", np.zeros((4, 8))) \
            == P(None, "model")
        assert tp_spec_for_path("gate/w2", np.zeros((5,))) == P()
        assert tp_spec_for_path("embedding", np.zeros((16, 8))) \
            == P("model", None)


class TestTableCompleteness:
    @pytest.mark.parametrize("name,model,args", [
        ("lm12", _lm12, lambda: (np.zeros((1, T), np.int32),)),
        ("seq2seq", _seq2seq, lambda: (np.zeros((1, T), np.int32),
                                       np.zeros((1, T), np.int32))),
        ("two_tower", _two_tower, lambda: (np.zeros((2,), np.int32),
                                           np.zeros((2, 3), np.int32),
                                           np.zeros((2,), np.int32))),
    ])
    def test_no_silent_replication(self, name, model, args):
        m = model()
        shapes = _param_shapes(m, *args())
        table = layout_for_model(m, SpecLayout())
        audit = table.audit(shapes)
        assert audit.fallback_replicated == [], (
            f"{name}: layout table silently replicates "
            f"{audit.fallback_replicated} — add a rule or an explicit "
            "replicate-allowlist entry")
        assert len(audit.sharded) > 0

    def test_new_param_fails_the_audit(self):
        """The teeth: an unknown parameter name must land in the
        fallback list (this is what makes silent replication a test
        failure, not a perf mystery)."""
        m = _lm12()
        shapes = _param_shapes(m, np.zeros((1, T), np.int32))
        shapes["brand_new_giant_table"] = jax.ShapeDtypeStruct(
            (4096, 64), jnp.float32)
        audit = layout_for_model(m, SpecLayout()).audit(shapes)
        assert audit.fallback_replicated == ["brand_new_giant_table"]
        assert audit.fallback_elems == 4096 * 64

    def test_generic_rules_rank_pinned(self):
        """The 2-D Linear rule and the 4-D conv rule share 'weight$':
        rank pinning keeps the conv kernel's spatial dims unsharded and
        splits (cin, cout) instead."""
        from bigdl_tpu.parallel.layout import generic_layout

        table = generic_layout(SpecLayout())
        spec2, kind2 = table.spec_for("head/weight", 2)
        assert (spec2, kind2) == (P("fsdp", "tp"), "linear_kernel")
        spec4, kind4 = table.spec_for("conv1/weight", 4)
        assert (spec4, kind4) == (P(None, None, "fsdp", "tp"),
                                  "conv_kernel_cout")

    def test_register_layout_for_new_model(self):
        from bigdl_tpu.parallel.layout import (GENERIC_REPLICATE,
                                               LayoutRule, ModelLayout)

        class Exotic:
            pass

        try:
            register_layout("Exotic", lambda sl: ModelLayout(
                sl, rules=(LayoutRule("giant", r"(^|/)giant$",
                                      lambda l: l.vocab_embedding()),),
                replicate=GENERIC_REPLICATE, name="exotic"))
            table = layout_for_model(Exotic(), SpecLayout())
            assert table.name == "exotic"
            spec, kind = table.spec_for("giant", 2)
            assert spec == P(("fsdp", "tp"), None) and kind == "giant"
        finally:
            from bigdl_tpu.parallel.layout import _MODEL_TABLES

            _MODEL_TABLES.pop("Exotic", None)

    def test_audit_gauge_and_flight_line(self):
        from bigdl_tpu.obs import flight
        from bigdl_tpu.optim.metrics import global_metrics

        m = _lm12()
        shapes = _param_shapes(m, np.zeros((1, T), np.int32))
        shapes["mystery"] = jax.ShapeDtypeStruct((64, 4), jnp.float32)
        audit = layout_for_model(m, SpecLayout()).audit(shapes)
        audit.export()
        gm = global_metrics()
        assert gm.gauges["parallel.layout.replicated_params"] == 1.0
        evts = [e for e in flight.global_recorder().snapshot()
                if e["kind"] == "layout_replicated_params"]
        assert evts and evts[-1]["paths"] == ["mystery"]
        # a clean audit resets the gauge to 0
        del shapes["mystery"]
        layout_for_model(m, SpecLayout()).audit(shapes).export()
        assert gm.gauges["parallel.layout.replicated_params"] == 0.0


# ---------------------------------------------------------------------------
# ACCEPTANCE: fsdp x tp trains the 12L transformer to the dp trajectory
# from one seed, and the checkpoint serves model-sharded
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def lm12_runs():
    from bigdl_tpu.data import ArrayDataSet

    rs = np.random.RandomState(0)
    x = rs.randint(2, VOCAB, (32, T)).astype(np.int32)
    y = np.roll(x, -1, 1).astype(np.int32)
    model = _lm12()
    runs = {}
    for par in ("dp", "dp:2,fsdp:2,tp:2"):
        trained, stats = fit_layout(
            _LMWrap(model), _FlatCE(),
            SGD(learning_rate=0.05, momentum=0.9),
            ArrayDataSet(x, y), parallelism=par, batch_size=8, epochs=2,
            seed=7, log_every=0)
        runs[par] = (trained, stats)
    return model, x, runs


class TestTrajectoryParity:
    def test_fsdp_tp_matches_dp_loss_trajectory(self, lm12_runs):
        _, _, runs = lm12_runs
        dp = runs["dp"][1]["losses"]
        fsdp_tp = runs["dp:2,fsdp:2,tp:2"][1]["losses"]
        assert len(dp) == 8  # 2 epochs x 4 steps, identical data order
        np.testing.assert_allclose(fsdp_tp, dp, rtol=2e-4, atol=2e-5)
        # and it actually trained: the second epoch's mean loss drops
        # (per-batch losses are noisy under shuffling; epoch means are
        # the stable signal at 8 steps)
        assert np.mean(fsdp_tp[4:]) < np.mean(fsdp_tp[:4])

    def test_embedding_and_opt_state_sharded(self, lm12_runs):
        _, _, runs = lm12_runs
        eng = runs["dp:2,fsdp:2,tp:2"][0]._engine
        report = eng.shard_report()
        emb_shape, emb_spec = report["embedding"]
        assert emb_shape == (VOCAB, 16)
        assert emb_spec[0] == ("fsdp", "tp")
        # the SGD momentum state inherits the param sharding (no
        # replicated moments — the ZeRO/fsdp half of the layout)
        flat = jax.tree_util.tree_flatten_with_path(eng.opt_state)[0]
        wq = next(l for p, l in flat
                  if "wq" in "/".join(str(getattr(k, "key", k))
                                      for k in p))
        assert wq.shape == (16, 16)
        assert wq.addressable_shards[0].data.shape == (8, 8)

    def test_set_variables_round_trip(self, lm12_runs):
        """TrainedModel.set_variables works on a layout engine (the
        Module.loadModule analog used by forecasters): the tree is
        re-placed under the layout's NamedShardings."""
        _, x, runs = lm12_runs
        trained = runs["dp:2,fsdp:2,tp:2"][0]
        before = trained.predict(x[:4])
        v = trained._engine.get_variables()
        trained.set_variables(v)
        np.testing.assert_allclose(trained.predict(x[:4]), before,
                                   rtol=1e-6)
        with pytest.raises(ValueError, match="structure"):
            trained.set_variables({"params": {"wrong": np.zeros((2,))}})

    def test_fit_layout_rejects_indivisible_batch(self):
        from bigdl_tpu.data import ArrayDataSet

        with pytest.raises(ValueError, match="batch shards"):
            fit_layout(_LMWrap(_lm12()), _FlatCE(), SGD(0.1),
                       ArrayDataSet(np.zeros((24, T), np.int32),
                                    np.zeros((24, T), np.int32)),
                       parallelism="dp:2,fsdp:2,tp:2", batch_size=6)

    def test_fit_layout_rejects_empty_probe(self):
        from bigdl_tpu.data import ArrayDataSet

        with pytest.raises(ValueError, match="no batch"):
            fit_layout(_LMWrap(_lm12()), _FlatCE(), SGD(0.1),
                       ArrayDataSet(np.zeros((4, T), np.int32),
                                    np.zeros((4, T), np.int32)),
                       parallelism="dp:2,fsdp:2,tp:2", batch_size=8)

    def test_trained_model_predict_and_ledger(self, lm12_runs):
        _, x, runs = lm12_runs
        # TrainedModel.predict returns one output row per input row —
        # the flat (b*t, V) logits truncate to the first b rows, which
        # is plenty for the cross-layout numeric comparison
        a = runs["dp"][0].predict(x[:8])
        b = runs["dp:2,fsdp:2,tp:2"][0].predict(x[:8])
        assert a.shape == (8, VOCAB)
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-4)
        stats = runs["dp:2,fsdp:2,tp:2"][1]
        by_axis = stats["collective_bytes_by_axis"]
        assert by_axis["fsdp"] > 0
        assert stats["replicated_params"] == 0
        # fsdp x tp shards the params ~4x (fsdp=2 x tp=2)
        dp_bytes = runs["dp"][1]["param_bytes_per_chip"]
        assert stats["param_bytes_per_chip"] < dp_bytes / 3


class TestServingModelSharded:
    def test_serves_sharded_with_zero_unexpected_recompiles(
            self, lm12_runs):
        from bigdl_tpu.obs.attr import recompile_sentinel
        from bigdl_tpu.optim.metrics import global_metrics
        from bigdl_tpu.serving.decode_engine import DecodeConfig
        from bigdl_tpu.serving.inference_model import InferenceModel

        model, x, runs = lm12_runs
        vars_ = runs["dp:2,fsdp:2,tp:2"][0].variables
        ref = InferenceModel(model, vars_, batch_buckets=(1, 4))
        im = InferenceModel(
            model, vars_, batch_buckets=(1, 4), layout="fsdp:2,tp:2",
            decode=DecodeConfig(slots=2, page_size=4, pages_per_slot=3,
                                prompt_chunk=4, prefill_batch=2,
                                max_new_tokens=4, eos_id=1))
        # params actually live sharded on the mesh
        emb = im._params["embedding"]
        assert emb.addressable_shards[0].data.shape == (VOCAB // 4, 16)
        sent = recompile_sentinel()
        m = global_metrics()
        try:
            im.warmup(x[0])
            ref.warmup(x[0])
            before = m.counter("train.unexpected_recompiles_total")
            sent.mark_steady()
            # mixed-size predict sweep: sharded == unsharded numerics
            rs = np.random.RandomState(3)
            for n in (1, 3, 4, 2):
                xb = x[:n]
                np.testing.assert_allclose(
                    im.predict(xb), ref.predict(xb),
                    rtol=2e-3, atol=2e-4)
            # and the decode engine generates through the sharded params
            prompts = [rs.randint(2, VOCAB, (int(k),)).astype(np.int32)
                       for k in (3, 5, 2, 7)]
            outs = im.generate(prompts, max_new_tokens=3)
            assert all(len(o) >= 1 for o in outs)
            assert all(0 <= int(t) < VOCAB for o in outs for t in o)
            after = m.counter("train.unexpected_recompiles_total")
            assert after - before == 0, (
                f"{after - before} unexpected XLA recompiles while "
                "serving the fsdp x tp checkpoint model-sharded")
        finally:
            sent.mark_warmup()
            if im.decode_engine is not None:
                im.decode_engine.stop()

    def test_layout_rejects_custom_predict_fn(self):
        from bigdl_tpu.serving.inference_model import InferenceModel

        with pytest.raises(ValueError, match="custom predict_fn"):
            InferenceModel(predict_fn=lambda x: x, layout="tp:2")


# ---------------------------------------------------------------------------
# Estimator / keras surfaces
# ---------------------------------------------------------------------------

class TestEstimatorSurface:
    def test_estimator_fit_with_parallelism(self):
        from bigdl_tpu import nn
        from bigdl_tpu.estimator import Estimator

        rs = np.random.RandomState(1)
        x = rs.randn(32, 8).astype(np.float32)
        y = rs.randn(32, 4).astype(np.float32)
        est = Estimator.from_module(
            model_creator=lambda cfg: nn.Sequential(
                [nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4)]),
            optimizer_creator=lambda cfg: SGD(learning_rate=0.05),
            loss_creator=lambda cfg: nn.MSECriterion(),
            config={"parallelism": "dp:2,tp:2", "seed": 3})
        stats = est.fit((x, y), epochs=2, batch_size=8,
                        validation_data=(x, y))
        assert stats["parallelism"] == "dp:2,tp:2"
        assert stats["mesh"] == {"data": 2, "fsdp": 1, "tp": 2, "seq": 1}
        assert stats["final_loss"] < stats["first_loss"]
        assert "Loss" in stats["validation"]
        pred = est.predict(x[:4])
        assert pred.shape == (4, 4)
        ev = est.evaluate((x, y), [])
        assert np.isfinite(list(ev.values())[0]) if ev else True

    def test_keras_fit_with_parallelism(self):
        from bigdl_tpu import nn
        from bigdl_tpu.keras.engine import Input, Model

        rs = np.random.RandomState(2)
        x = rs.randn(32, 6).astype(np.float32)
        y = rs.randn(32, 2).astype(np.float32)
        inp = Input((6,))
        out = nn.Linear(8, 2)(nn.ReLU()(nn.Linear(6, 8)(inp)))
        km = Model(inp, out).compile("sgd", "mse")
        km.fit(x, y, batch_size=8, nb_epoch=1, parallelism="tp:2",
               log_every=0)
        assert km.predict(x[:4]).shape == (4, 2)

    def test_estimator_layout_rejects_unsupported_features(self):
        from bigdl_tpu import nn
        from bigdl_tpu.estimator import Estimator

        est = Estimator.from_module(
            model_creator=lambda cfg: nn.Linear(8, 4),
            optimizer_creator=lambda cfg: SGD(learning_rate=0.1),
            loss_creator=lambda cfg: nn.MSECriterion(),
            config={"parallelism": "dp"})
        x = np.zeros((16, 8), np.float32)
        y = np.zeros((16, 4), np.float32)
        with pytest.raises(ValueError, match="fault_tolerance"):
            est.fit((x, y), epochs=1, batch_size=8, fault_tolerance=True)

    def test_keras_layout_rejects_checkpoint_path(self, tmp_path):
        from bigdl_tpu import nn
        from bigdl_tpu.keras.engine import Input, Model

        inp = Input((6,))
        km = Model(inp, nn.Linear(6, 2)(inp)).compile("sgd", "mse")
        with pytest.raises(ValueError, match="checkpoint_path"):
            km.fit(np.zeros((8, 6), np.float32),
                   np.zeros((8, 2), np.float32), parallelism="dp",
                   checkpoint_path=str(tmp_path / "ck"))

    def test_keras_parallelism_excludes_seq_parallel(self):
        from bigdl_tpu import nn
        from bigdl_tpu.keras.engine import Input, Model

        inp = Input((6,))
        km = Model(inp, nn.Linear(6, 2)(inp)).compile("sgd", "mse")
        with pytest.raises(ValueError, match="exclusive"):
            km.fit(np.zeros((8, 6), np.float32),
                   np.zeros((8, 2), np.float32),
                   parallelism="dp", seq_parallel=True)


# ---------------------------------------------------------------------------
# the per-axis ledger + sentinel family
# ---------------------------------------------------------------------------

class TestLedger:
    def test_per_axis_math(self):
        r = mesh_and_layout("fsdp:2,tp:2")
        params = {"w": np.zeros((4, 2), np.float32),
                  "b": np.zeros((2,), np.float32)}
        specs = {"w": P("fsdp", "tp"), "b": P()}
        led = collective_bytes_by_axis(params, specs, r.mesh)
        per = led["per_axis_bytes_per_step"]
        # w sharded on fsdp: 3 ring passes of 8*(1/2) elems * 4 B = 48
        assert per["fsdp"] == pytest.approx(3 * 8 * 0.5 * 4)
        # b replicated on the fsdp batch axis: allreduce ~2x its bytes
        assert per["data"] == pytest.approx(2 * 2 * 4)
        # per-chip params: w split 4 ways, b whole
        assert led["param_bytes_per_chip"] == pytest.approx(
            (8 / 4 + 2) * 4)

    def test_obs_cost_reads_the_layout(self):
        from bigdl_tpu.obs.cost import collective_bytes_for_specs

        r = mesh_and_layout("fsdp:2,tp:2")
        params = {"w": np.zeros((4, 2), np.float32)}
        specs = {"w": P("fsdp", "tp")}
        a = collective_bytes_for_specs(params, specs, r.mesh)
        b = collective_bytes_by_axis(params, specs, r.mesh)
        assert a == b

    def test_tp_activation_estimate(self):
        # 2*(tp-1)/tp * B*S*D * 4 bytes, x3 (fwd + bwd), x n collectives
        assert tp_activation_bytes(2, 4, 8, n_row_collectives=1, tp=2) \
            == pytest.approx(3 * 2 * 0.5 * 2 * 4 * 8 * 4)
        assert tp_activation_bytes(2, 4, 8, 4, tp=1) == 0.0

    def test_gspmd_legacy_ledger_counts_fsdp_as_data(self):
        from bigdl_tpu.parallel.gspmd import collective_bytes_for_specs

        r = mesh_and_layout("fsdp:4,tp:2")
        params = {"w": np.zeros((8,), np.float32)}
        rep = collective_bytes_for_specs(params, {"w": P()}, r.mesh)
        assert rep["n_data_replicas"] == 4.0

    def test_sentinel_layout_family(self):
        from bigdl_tpu.obs import sentinel as obs_sentinel

        row = {"metric": "multichip_layout_param_bytes_reduction",
               "value": 7.9,
               "layout_modes": {
                   "dp": {"per_axis_bytes_per_step": {"data": 100.0},
                          "param_bytes_per_chip": 400.0},
                   "fsdp_tp": {
                       "per_axis_bytes_per_step": {"fsdp": 60.0},
                       "tp_activation_bytes_per_step": 30.0,
                       "param_bytes_per_chip": 50.0}}}
        rows = {r.family: r for r in obs_sentinel.normalize(
            row, "MULTICHIP_LAYOUT_r99.json")}
        assert rows["multichip_layout_param_bytes_reduction"].direction \
            == obs_sentinel.HIGHER
        assert rows["multichip_layout_dp_param_bytes_per_chip"].direction \
            == obs_sentinel.LOWER
        assert rows["multichip_layout_fsdp_tp_fsdp_bytes_per_step"].value \
            == 60.0
        assert ("multichip_layout_fsdp_tp_tp_activation_bytes_per_step"
                in rows)

    def test_committed_layout_artifact_gates(self):
        from bigdl_tpu.obs import sentinel as obs_sentinel

        history = obs_sentinel.load_history(REPO)
        fam = "multichip_layout_fsdp_tp_param_bytes_per_chip"
        assert fam in history, (
            "MULTICHIP_LAYOUT_r*.json must stay committed so the "
            "sentinel gates the layout ledger")
        assert "multichip_layout_param_bytes_reduction" in history
        base = obs_sentinel.baseline_for(fam, history)
        assert base is not None and base.value > 0
