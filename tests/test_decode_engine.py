"""Token-level continuous batching — paged KV-cache decode engine
(docs/serving.md §Autoregressive decode).

Tier-1 specs: continuous-vs-static decode TOKEN PARITY (byte-identical,
greedy AND seeded-sample, including requests inserted mid-flight),
mid-flight insertion/eviction invariants (no page aliasing after slot
reuse, pool accounting restored), the zero-recompile mixed
prompt/generation-length sweep under the PR 6 sentinel, streaming chunk
framing round-trip over the HTTP frontend, the
prefill-never-stalls-decode scheduling spec, per-token deadline
enforcement (an expired streaming request frees its slot immediately,
counted per tenant), the paged single-query flash kernel's parity with
the gathered-jnp path, and the ``serving.decode.*`` metric surface.
"""

import json
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.attention import Transformer
from bigdl_tpu.serving.decode_engine import (DecodeConfig, DecodeEngine,
                                             DecodeRequest, LMAdapter,
                                             Seq2SeqAdapter)

BOS, EOS = 0, 1


@pytest.fixture(scope="module")
def lm():
    model = Transformer(vocab_size=32, hidden_size=16, num_heads=2,
                        num_layers=2, dropout=0.0, mode="lm")
    v = model.init(jax.random.PRNGKey(0),
                   np.arange(6, dtype=np.int32)[None])
    return model, v["params"]


def _lm_engine(lm, **over):
    model, params = lm
    kw = dict(slots=4, page_size=4, pages_per_slot=4, prompt_chunk=4,
              max_new_tokens=8, eos_id=EOS, prefill_batch=2)
    kw.update(over)
    cfg = DecodeConfig(**kw)
    return DecodeEngine(LMAdapter(model, params, cap=cfg.cap), cfg)


def _prompts(ns=(3, 5, 9, 2, 7, 11), seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(2, 32, (n,)).astype(np.int32) for n in ns]


def _requests(prompts, temperature=0.0, **kw):
    return [DecodeRequest(tokens=p, temperature=temperature, seed=100 + i,
                          **kw) for i, p in enumerate(prompts)]


def _run(engine, reqs, stagger_at=None):
    split = stagger_at if stagger_at is not None else len(reqs)
    for r in reqs[:split]:
        engine.submit(r)
    if split < len(reqs):
        time.sleep(0.1)
        for r in reqs[split:]:
            engine.submit(r)
    return [r.wait(timeout=120) for r in reqs]


# ---------------------------------------------------------------------------
# continuous vs static whole-sequence parity
# ---------------------------------------------------------------------------

class TestContinuousStaticParity:
    def test_greedy_byte_identical(self, lm):
        eng = _lm_engine(lm)
        try:
            static = eng.static_generate(_requests(_prompts()))
            res = _run(eng, _requests(_prompts()))
            for a, b in zip(res, static):
                assert a.tokens.tobytes() == b.tokens.tobytes()
                assert np.float32(a.logp) == np.float32(b.logp)
                assert a.finish_reason == b.finish_reason
        finally:
            eng.stop()

    def test_seeded_sample_byte_identical(self, lm):
        """Temperature + top-k + top-p sampling: the per-request
        fold_in(key, position) stream makes the draw independent of
        batch composition — continuous == one-scan to the byte."""
        eng = _lm_engine(lm)
        kw = dict(temperature=1.3, top_k=5, top_p=0.9)
        try:
            static = eng.static_generate(_requests(_prompts(), **kw))
            res = _run(eng, _requests(_prompts(), **kw))
            for a, b in zip(res, static):
                assert a.tokens.tobytes() == b.tokens.tobytes()
                assert np.float32(a.logp) == np.float32(b.logp)
        finally:
            eng.stop()

    def test_mid_flight_insertion_parity(self, lm):
        """Requests inserted while others decode claim freed slots at
        step granularity — and still match the static reference, which
        never saw any co-scheduling at all."""
        eng = _lm_engine(lm)
        kw = dict(temperature=1.3, top_k=5, top_p=0.9)
        try:
            static = eng.static_generate(_requests(_prompts(), **kw))
            res = _run(eng, _requests(_prompts(), **kw), stagger_at=3)
            for a, b in zip(res, static):
                assert a.tokens.tobytes() == b.tokens.tobytes()
        finally:
            eng.stop()

    def test_sampling_varies_by_seed_and_position(self, lm):
        eng = _lm_engine(lm)
        try:
            p = _prompts((6,))[0]
            reqs = [DecodeRequest(tokens=p, temperature=2.0, seed=i)
                    for i in range(4)]
            res = _run(eng, reqs)
            streams = {r.tokens.tobytes() for r in res}
            assert len(streams) > 1   # different seeds draw differently
        finally:
            eng.stop()


# ---------------------------------------------------------------------------
# slot reuse / page accounting
# ---------------------------------------------------------------------------

class TestSlotAndPageInvariants:
    def test_no_page_aliasing_after_slot_reuse(self, lm):
        """Wave B lands in pages wave A dirtied; results must equal a
        FRESH engine's byte-for-byte (stale K/V is never valid)."""
        wave_a = _requests(_prompts((4, 6, 3, 8), seed=1))
        wave_b = _requests(_prompts((7, 2, 9, 5), seed=2),
                           temperature=1.1, top_k=4)
        dirty = _lm_engine(lm)
        fresh = _lm_engine(lm)
        try:
            _run(dirty, wave_a)
            got = _run(dirty, [DecodeRequest(tokens=r.tokens,
                                             temperature=r.temperature,
                                             top_k=r.top_k, seed=r.seed)
                               for r in wave_b])
            want = _run(fresh, wave_b)
            for a, b in zip(got, want):
                assert a.tokens.tobytes() == b.tokens.tobytes()
        finally:
            dirty.stop()
            fresh.stop()

    def test_pool_accounting_restored(self, lm):
        eng = _lm_engine(lm)
        try:
            _run(eng, _requests(_prompts()))
            deadline = time.time() + 5
            while time.time() < deadline and eng.active_slots():
                time.sleep(0.01)
            assert eng.active_slots() == 0
            assert len(eng._free_pages) == eng.cfg.total_pages
            assert eng._reserved_pages == 0
            assert all(s is None for s in eng._slots)
        finally:
            eng.stop()

    def test_page_reservation_gates_admission(self, lm):
        """With a pool smaller than two worst cases, the second request
        waits for the first's mid-flight release — and both finish."""
        eng = _lm_engine(lm, num_pages=5, max_new_tokens=6)
        try:
            reqs = _requests(_prompts((9, 9), seed=3))
            res = _run(eng, reqs)
            assert all(len(r.tokens) > 0 for r in res)
        finally:
            eng.stop()

    def test_whole_batch_restart_mode_answers(self, lm):
        """continuous=False (the bench baseline): gang admission, full
        scan horizon — same answers, just slower seats."""
        eng = _lm_engine(lm, continuous=False, max_new_tokens=6)
        cont = _lm_engine(lm, max_new_tokens=6)
        try:
            res = _run(eng, _requests(_prompts((3, 5, 4, 6, 2), seed=4)))
            want = _run(cont, _requests(_prompts((3, 5, 4, 6, 2),
                                                 seed=4)))
            for a, b in zip(res, want):
                assert a.tokens.tobytes() == b.tokens.tobytes()
        finally:
            eng.stop()
            cont.stop()

    def test_whole_batch_restart_honors_per_request_max_new(self, lm):
        """A wave member asking for MORE than the config default must
        not be truncated by the wave horizon (the horizon is the
        longest member's request)."""
        eng = _lm_engine(lm, continuous=False, max_new_tokens=4)
        cont = _lm_engine(lm, max_new_tokens=4)
        try:
            reqs = lambda: [DecodeRequest(
                tokens=p, temperature=0.0, seed=i, max_new_tokens=10)
                for i, p in enumerate(_prompts((3, 5), seed=6))]
            res = _run(eng, reqs())
            want = _run(cont, reqs())
            for a, b in zip(res, want):
                assert a.tokens.tobytes() == b.tokens.tobytes()
                assert len(a.tokens) > 4 or a.finish_reason == "eos"
        finally:
            eng.stop()
            cont.stop()


# ---------------------------------------------------------------------------
# zero-recompile sweep (the PR 6 closed-set discipline)
# ---------------------------------------------------------------------------

def test_mixed_length_sweep_zero_unexpected_recompiles(lm):
    from bigdl_tpu.obs.attr import recompile_sentinel
    from bigdl_tpu.optim.metrics import global_metrics

    sent = recompile_sentinel()
    eng = _lm_engine(lm, slots=4)
    m = global_metrics()
    try:
        eng.warmup()
        before = m.counter("train.unexpected_recompiles_total")
        sent.mark_steady()
        # every prompt length x generation length the geometry allows
        rs = np.random.RandomState(7)
        reqs = [DecodeRequest(
            tokens=rs.randint(2, 32, (int(rs.randint(1, 12)),)).astype(
                np.int32),
            max_new_tokens=int(rs.randint(1, 9)),
            temperature=float(rs.rand() < 0.5) * 1.2,
            seed=i) for i in range(24)]
        _run(eng, reqs, stagger_at=12)
        after = m.counter("train.unexpected_recompiles_total")
        assert after - before == 0, (
            f"{after - before} unexpected XLA recompiles during the "
            "mixed prompt/generation-length sweep")
    finally:
        sent.mark_warmup()
        eng.stop()


# ---------------------------------------------------------------------------
# scheduling: prefill never stalls decode
# ---------------------------------------------------------------------------

def test_prefill_interleaves_with_decode_steps(lm):
    """While a long prompt chunks through prefill, decode steps for the
    already-active slot keep landing BETWEEN its chunks."""
    eng = _lm_engine(lm, slots=2, pages_per_slot=4, page_size=4,
                     prompt_chunk=4, max_new_tokens=8, prefill_batch=2)
    try:
        short = DecodeRequest(tokens=np.asarray([2, 3], np.int32),
                              max_new_tokens=8, seed=0)
        eng.submit(short)
        # wait until the short request is actively decoding
        deadline = time.time() + 5
        while time.time() < deadline and not eng._active_mask.any():
            time.sleep(0.002)
        long = DecodeRequest(
            tokens=np.arange(2, 15, dtype=np.int32),   # 13 tokens: 4 chunks
            max_new_tokens=2, seed=1)
        eng.submit(long)
        long.wait(30)
        short.wait(30)
        events = list(eng.events)
        chunk_idx = [i for i, e in enumerate(events)
                     if e[0] == "prefill_chunk" and long.rid in e[1]]
        step_idx = [i for i, e in enumerate(events)
                    if e[0] == "decode_step"]
        assert len(chunk_idx) >= 3          # the prompt really chunked
        interleaved = any(
            any(a < s < b for s in step_idx)
            for a, b in zip(chunk_idx, chunk_idx[1:]))
        assert interleaved, (
            "no decode step landed between the long prompt's prefill "
            f"chunks: chunks at {chunk_idx}, steps at {step_idx[:20]}")
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# per-token deadline enforcement
# ---------------------------------------------------------------------------

def test_expired_streaming_request_frees_slot_mid_decode(lm):
    from bigdl_tpu.serving.server import DeadlineExceededError

    eng = _lm_engine(lm, slots=2, max_new_tokens=8)
    try:
        # the stream consumer is slow: the deadline passes mid-decode,
        # long before max_new_tokens would
        seen = []

        def slow_consumer(rid, tok, idx):
            seen.append(tok)
            time.sleep(0.05)

        req = DecodeRequest(tokens=np.asarray([2, 3, 4], np.int32),
                            max_new_tokens=8, seed=0,
                            deadline_t=time.time() + 0.12,
                            on_token=slow_consumer)
        eng.submit(req)
        with pytest.raises(DeadlineExceededError) as ei:
            req.wait(30)
        assert 0 < len(seen) < 8    # streamed some tokens, not all
        assert np.array_equal(
            getattr(ei.value, "partial_tokens", []), seen)
        assert eng.stats["expired"] == 1
        # the slot and its pages freed immediately
        deadline = time.time() + 5
        while time.time() < deadline and any(
                s is not None for s in eng._slots):
            time.sleep(0.01)
        assert len(eng._free_pages) == eng.cfg.total_pages
    finally:
        eng.stop()


def test_empty_prompt_rejected_at_submit(lm):
    """An empty prompt can never prefill, decode, or expire — it must
    be rejected at the door, never parked in a slot forever."""
    eng = _lm_engine(lm)
    try:
        with pytest.raises(ValueError, match="empty prompt"):
            eng.submit(DecodeRequest(tokens=np.asarray([], np.int32)))
        # the engine still serves afterwards
        res = _run(eng, _requests(_prompts((3,))))
        assert len(res[0].tokens) > 0
        assert eng.active_slots() == 0
    finally:
        eng.stop()


def test_queued_expiry_at_pickup(lm):
    from bigdl_tpu.serving.server import DeadlineExceededError

    eng = _lm_engine(lm)
    try:
        req = DecodeRequest(tokens=np.asarray([2, 3], np.int32),
                            deadline_t=time.time() - 0.01, seed=0)
        eng.submit(req)
        with pytest.raises(DeadlineExceededError):
            req.wait(30)
        assert eng.stats["expired"] == 1
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# seq2seq service: engine vs one-scan reference
# ---------------------------------------------------------------------------

class TestSeq2SeqService:
    @pytest.fixture(scope="class")
    def s2s(self):
        model = Transformer(vocab_size=16, hidden_size=16, num_heads=2,
                            num_layers=1, dropout=0.0, mode="translation")
        src = np.array([[2, 5, 6, 3], [2, 3, 4, 5], [7, 8, 9, 10]],
                       np.int32)
        v = model.init(jax.random.PRNGKey(0), src, src)
        return model, v["params"], src

    @pytest.mark.parametrize("sample", [False, True])
    def test_continuous_matches_one_scan(self, s2s, sample):
        from bigdl_tpu.serving.seq2seq import Seq2SeqService

        model, params, src = s2s
        mk = lambda cont: Seq2SeqService(
            model, params, BOS, EOS, max_len=8, sample=sample,
            temperature=2.0, top_k=6, top_p=0.9, continuous=cont)
        a, b = mk(True), mk(False)
        try:
            ta, sa = a.translate(src)
            tb, sb = b.translate(src)
            assert ta.tobytes() == tb.tobytes()
            assert sa.tobytes() == sb.tobytes()
        finally:
            a.stop()
            b.stop()

    def test_warmup_covers_ctx_write_zero_recompiles(self, s2s):
        """The seq2seq engine's ctx-write program must be COMPILED by
        warmup(), not by the first admission — a cold translate after
        warmup triggers zero unexpected recompiles."""
        from bigdl_tpu.obs.attr import recompile_sentinel
        from bigdl_tpu.optim.metrics import global_metrics
        from bigdl_tpu.serving.seq2seq import Seq2SeqService

        model, params, src = s2s
        sent = recompile_sentinel()
        m = global_metrics()
        svc = Seq2SeqService(model, params, BOS, EOS, max_len=8,
                             src_buckets=(8,))
        try:
            svc.warmup()
            before = m.counter("train.unexpected_recompiles_total")
            sent.mark_steady()
            svc.translate(src)
            assert m.counter("train.unexpected_recompiles_total") \
                == before
        finally:
            sent.mark_warmup()
            svc.stop()

    def test_engine_reused_and_slots_released(self, s2s):
        from bigdl_tpu.serving.seq2seq import Seq2SeqService

        model, params, src = s2s
        svc = Seq2SeqService(model, params, BOS, EOS, max_len=8)
        try:
            t1, _ = svc.translate(src)
            t2, _ = svc.translate(src)
            assert t1.shape == t2.shape == (3, 9)
            assert t1.tobytes() == t2.tobytes()   # greedy deterministic
            assert svc.decode_engine.active_slots() == 0
        finally:
            svc.stop()


# ---------------------------------------------------------------------------
# paged single-query flash decode kernel
# ---------------------------------------------------------------------------

class TestPagedDecodeAttention:
    def _ref(self, q, kp, vp, pt, lengths):
        S, h, hd = q.shape
        nb = pt.shape[1]
        page = kp.shape[2]
        k = kp[pt].transpose(0, 2, 1, 3, 4).reshape(S, h, nb * page, hd)
        v = vp[pt].transpose(0, 2, 1, 3, 4).reshape(S, h, nb * page, hd)
        logits = jnp.einsum("shd,shkd->shk", q, k) / np.sqrt(hd)
        valid = (jnp.arange(nb * page)[None, None, :]
                 <= lengths[:, None, None])
        logits = jnp.where(valid, logits, -1e30)
        w = jax.nn.softmax(logits, -1)
        return jnp.einsum("shk,shkd->shd", w, v)

    def test_kernel_matches_gathered_reference(self):
        from bigdl_tpu.ops.flash_attention import paged_decode_attention

        rs = np.random.RandomState(0)
        S, h, page, hd, nb = 4, 4, 4, 8, 4
        P = S * nb
        q = jnp.asarray(rs.randn(S, h, hd), jnp.float32)
        kp = jnp.asarray(rs.randn(P, h, page, hd), jnp.float32)
        vp = jnp.asarray(rs.randn(P, h, page, hd), jnp.float32)
        pt = jnp.asarray(rs.permutation(P).reshape(S, nb), jnp.int32)
        lengths = jnp.asarray([0, 3, 7, 14], jnp.int32)
        for bh in (1, 2, 4):
            out = paged_decode_attention(q, kp, vp, pt, lengths,
                                         block_h=bh)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(self._ref(q, kp, vp, pt,
                                                      lengths)),
                rtol=1e-5, atol=1e-6)

    def test_bad_block_h_rejected(self):
        from bigdl_tpu.ops.flash_attention import paged_decode_attention

        q = jnp.zeros((2, 4, 8), jnp.float32)
        kp = jnp.zeros((4, 4, 4, 8), jnp.float32)
        pt = jnp.zeros((2, 1), jnp.int32)
        with pytest.raises(ValueError, match="divide"):
            paged_decode_attention(q, kp, kp, pt,
                                   jnp.zeros((2,), jnp.int32), block_h=3)

    def test_registered_in_autotuner(self):
        from bigdl_tpu.ops import autotune

        spec = autotune.REGISTRY["flash_attention_decode"]
        assert "block_h" in spec.space
        key = autotune.decode_attention_key(8, 4, 8, 32, 4, "float32")
        tiles = autotune.resolve("flash_attention_decode", key)
        assert tiles["block_h"] in (1, 2, 4, 8)

    def test_engine_flash_path_greedy_tokens_agree(self, lm):
        jnp_eng = _lm_engine(lm, use_flash_decode=False)
        fl_eng = _lm_engine(lm, use_flash_decode=True)
        try:
            a = _run(jnp_eng, _requests(_prompts((3, 5, 9))))
            b = _run(fl_eng, _requests(_prompts((3, 5, 9))))
            for x, y in zip(a, b):
                assert x.tokens.tolist() == y.tokens.tolist()
        finally:
            jnp_eng.stop()
            fl_eng.stop()


# ---------------------------------------------------------------------------
# serving surface: server routing, HTTP streaming framing, metrics
# ---------------------------------------------------------------------------

class TestServingSurface:
    @pytest.fixture(scope="class")
    def served(self, request):
        from bigdl_tpu.serving import (DecodeConfig, HttpClient,
                                       HttpFrontend, InferenceModel,
                                       ServingConfig, ServingServer)

        model = Transformer(vocab_size=32, hidden_size=16, num_heads=2,
                            num_layers=2, dropout=0.0, mode="lm")
        v = model.init(jax.random.PRNGKey(0),
                       np.arange(6, dtype=np.int32)[None])
        im = InferenceModel(model, v, decode=DecodeConfig(
            slots=4, page_size=4, pages_per_slot=4, prompt_chunk=4,
            max_new_tokens=8, eos_id=EOS))
        srv = ServingServer(im, ServingConfig(batch_size=4)).start()
        fe = HttpFrontend(srv, port=0).start()
        cl = HttpClient(fe.url, keep_alive=True)

        def fin():
            cl.close()
            fe.stop()
            srv.stop()
            im.decode_engine.stop()

        request.addfinalizer(fin)
        return im, srv, fe, cl

    def test_generate_and_stream_framing_round_trip(self, served):
        im, srv, fe, cl = served
        want = im.generate([[2, 3, 4]], temperature=0.0)[0]
        got = cl.generate([2, 3, 4], temperature=0.0)
        assert got.tolist() == want.tolist()
        events = list(cl.generate([2, 3, 4], temperature=0.0,
                                  stream=True))
        tokens = [e["token"] for e in events if "token" in e]
        final = events[-1]
        # framing: per-token events in order, indexed, and the final
        # event re-states the full sequence
        assert tokens == want.tolist()
        assert [e["index"] for e in events if "token" in e] \
            == list(range(len(tokens)))
        assert final["done"] is True
        assert final["tokens"] == want.tolist()

    def test_server_query_path_and_queue_client(self, served):
        from bigdl_tpu.serving import InputQueue, OutputQueue

        im, srv, fe, cl = served
        want = im.generate([[5, 6]], temperature=0.0)[0]
        rid = srv.enqueue_generate(np.asarray([5, 6], np.int32))
        assert np.asarray(srv.query(rid)).tolist() == want.tolist()
        iq, oq = InputQueue(srv), OutputQueue(srv)
        rid = iq.enqueue_generate(tokens=[5, 6], temperature=0.0)
        assert oq.query(rid).tolist() == want.tolist()

    def test_unknown_model_and_no_engine(self, served):
        from bigdl_tpu.serving import InferenceModel

        im, srv, fe, cl = served
        with pytest.raises(KeyError):
            srv.enqueue_generate(np.asarray([2]), model="nope")
        srv.register_model("plain", InferenceModel(
            predict_fn=lambda x: np.asarray(x)))
        try:
            with pytest.raises(TypeError, match="decode engine"):
                srv.enqueue_generate(np.asarray([2]), model="plain")
        finally:
            srv.unregister_model("plain")

    def test_submit_rejection_does_not_poison_request_id(self, served):
        """A submit-time rejection (prompt over the cache cap) must
        clean up _pending so the id stays reusable — and must surface
        as the original error, not a duplicate-id conflict."""
        im, srv, fe, cl = served
        big = np.arange(2, 2 + im.decode_engine.cfg.cap + 2,
                        dtype=np.int32)
        for _ in range(2):   # second attempt must not hit 'in flight'
            with pytest.raises(ValueError, match="cache cap"):
                srv.enqueue_generate(big, request_id="poison-probe")
        want = im.generate([[5, 6]], temperature=0.0)[0]
        rid = srv.enqueue_generate(np.asarray([5, 6], np.int32),
                                   request_id="poison-probe")
        assert np.asarray(srv.query(rid)).tolist() == want.tolist()

    def test_lazy_seq2seq_tenant_serves_generate(self, served):
        """A freshly registered Seq2SeqService (engine built lazily on
        first use) must serve generate requests immediately."""
        from bigdl_tpu.serving.seq2seq import Seq2SeqService

        im, srv, fe, cl = served
        model = Transformer(vocab_size=16, hidden_size=16, num_heads=2,
                            num_layers=1, dropout=0.0,
                            mode="translation")
        src = np.array([[2, 5, 6, 3]], np.int32)
        v = model.init(jax.random.PRNGKey(0), src, src)
        svc = Seq2SeqService(model, v["params"], BOS, EOS, max_len=8)
        srv.register_model("mt", svc)
        try:
            rid = srv.enqueue_generate(src[0], model="mt")
            out = np.asarray(srv.query(rid))
            want, _ = svc.translate(src)
            assert out.tolist() == want[0, 1:1 + len(out)].tolist()
        finally:
            srv.unregister_model("mt")
            svc.stop()

    def test_generate_stream_accepts_deadline(self, served):
        im, srv, fe, cl = served
        toks = list(im.generate_stream([2, 3], temperature=0.0,
                                       max_new_tokens=4, deadline_s=30))
        assert toks == im.generate([[2, 3]], temperature=0.0,
                                   max_new_tokens=4)[0].tolist()

    def test_tenant_expired_counter_on_deadline(self, served):
        im, srv, fe, cl = served
        from bigdl_tpu.serving.server import DeadlineExceededError

        before = srv.metrics.counter("serving.tenant.default.expired")
        rid = srv.enqueue_generate(np.asarray([2, 3], np.int32),
                                   deadline_s=-0.01)
        with pytest.raises(DeadlineExceededError):
            srv.query(rid, timeout=10)
        assert srv.metrics.counter(
            "serving.tenant.default.expired") == before + 1

    def test_decode_metrics_exported_with_help(self, served):
        from bigdl_tpu.obs.export import render_prometheus

        im, srv, fe, cl = served
        im.generate([[2, 3, 4]], temperature=0.0)
        text = render_prometheus(srv.metrics)
        for fam in ("serving_decode_tokens_total",
                    "serving_decode_ttft_s",
                    "serving_decode_slot_occupancy",
                    "serving_decode_page_utilization"):
            assert fam in text, fam
        assert "# HELP serving_decode_ttft_s" in text


# ---------------------------------------------------------------------------
# sentinel: the DECODE_r* family
# ---------------------------------------------------------------------------

def test_sentinel_normalizes_and_gates_decode_family():
    from bigdl_tpu.obs import sentinel

    row = {"engine": "continuous", "geometry": "decode_s8_c24",
           "tokens_per_s": 3000.0, "tokens_per_s_user": 120.0,
           "ttft_ms_p50": 10.0, "ttft_ms_p99": 80.0,
           "inter_token_p99_ms": 5.0, "speedup_vs_static": 2.5}
    rows = {r.family: r for r in sentinel.normalize(row, "t")}
    assert rows["decode_tokens_per_s_decode_s8_c24"].direction \
        == sentinel.HIGHER
    assert rows["decode_ttft_ms_p99_decode_s8_c24"].direction \
        == sentinel.LOWER
    assert rows["decode_inter_token_p99_ms_decode_s8_c24"].direction \
        == sentinel.LOWER
    assert rows["decode_speedup_vs_static_decode_s8_c24"].direction \
        == sentinel.HIGHER
    history = {f: [r] for f, r in rows.items()}
    worse = dict(row, tokens_per_s=2000.0, ttft_ms_p99=200.0)
    verdicts = {v.family: v for v in sentinel.check(worse, history)}
    assert verdicts["decode_tokens_per_s_decode_s8_c24"].regressed
    assert verdicts["decode_ttft_ms_p99_decode_s8_c24"].regressed
    ok = dict(row)
    assert not any(v.regressed for v in sentinel.check(ok, history))


def test_committed_decode_artifact_enters_history():
    """DECODE_r01.json is committed evidence: the sentinel must load it
    into the gating trajectory (and it must show the >= 2x speedup the
    acceptance demands)."""
    import os

    from bigdl_tpu.obs import sentinel

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.exists(os.path.join(root, "DECODE_r01.json")):
        pytest.skip("DECODE_r01.json not committed yet")
    history = sentinel.load_history(root)
    fams = [f for f in history if f.startswith("decode_tokens_per_s")]
    assert fams, "DECODE family missing from sentinel history"
    speed = [f for f in history if f.startswith("decode_speedup")]
    assert speed and history[speed[0]][0].value >= 2.0
