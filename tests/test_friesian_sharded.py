"""Shard-parallel Friesian feature ops == single-host FeatureTable.

VERDICT r3 #10: the reference's Friesian value is *distributed* feature
engineering; these specs prove every stat-producing op merges global
statistics correctly (shard-parallel output identical to the single-host
twin on the concatenated frame) and the multi-process stat allgather
round-trips.
"""

import numpy as np
import pandas as pd
import pytest

from bigdl_tpu.friesian.sharded import ShardedFeatureTable, _allgather_objects
from bigdl_tpu.friesian.table import FeatureTable


def _frame(n=200, seed=0):
    rs = np.random.RandomState(seed)
    return pd.DataFrame({
        "user": rs.randint(1, 20, n),
        "item": rs.randint(1, 50, n),
        "cat": rs.choice(["a", "b", "c", "d", "e"], n,
                         p=[0.4, 0.3, 0.15, 0.1, 0.05]),
        "price": rs.rand(n) * 100,
        "label": rs.randint(0, 2, n),
    })


@pytest.fixture
def df():
    return _frame()


@pytest.fixture
def pair(df):
    """(sharded over 4 partitions, single-host) twins of the same frame."""
    return ShardedFeatureTable.partition(df, 4), FeatureTable(df)


class TestShardedEqualsSingleHost:
    def test_gen_string_idx_matches(self, pair):
        sh, single = pair
        assert sh.num_partitions() == 4
        i_sh = sh.gen_string_idx("cat")
        i_single = single.gen_string_idx("cat")
        assert i_sh.mapping == i_single.mapping

    def test_gen_string_idx_freq_limit(self, pair):
        sh, single = pair
        i_sh = sh.gen_string_idx("cat", freq_limit=15)
        i_single = single.gen_string_idx("cat", freq_limit=15)
        assert i_sh.mapping == i_single.mapping
        # per-shard counts alone would prune differently: a category can
        # be under the limit on every shard yet over it globally
        per_shard = [FeatureTable(s).gen_string_idx("cat", freq_limit=15)
                     for s in sh.shards]
        assert any(ix.mapping != i_single.mapping for ix in per_shard)

    def test_category_encode_matches(self, pair):
        sh, single = pair
        enc_sh, _ = sh.category_encode("cat")
        enc_single, _ = single.category_encode("cat")
        got = enc_sh.to_table().df["cat"].to_numpy()
        want = enc_single.df["cat"].to_numpy()
        np.testing.assert_array_equal(got, want)

    def test_count_encode_matches(self, pair):
        sh, single = pair
        got = sh.count_encode("item").to_table().df
        want = single.count_encode("item").df
        np.testing.assert_array_equal(got["item_count"].to_numpy(),
                                      want["item_count"].to_numpy())
        # a naive per-shard count_encode would differ (the global-merge is
        # doing real work)
        naive = pd.concat([FeatureTable(s).count_encode("item").df
                           for s in sh.shards], ignore_index=True)
        assert (naive["item_count"].to_numpy()
                != want["item_count"].to_numpy()).any()

    def test_target_encode_matches(self, pair):
        sh, single = pair
        enc_sh, map_sh = sh.target_encode("cat", "label", smooth=10.0)
        enc_single, map_single = single.target_encode("cat", "label",
                                                      smooth=10.0)
        np.testing.assert_allclose(
            enc_sh.to_table().df["cat_te"].to_numpy(),
            enc_single.df["cat_te"].to_numpy(), rtol=1e-12)
        for k, v in map_single["cat"]["mapping"].items():
            assert map_sh["cat"]["mapping"][k] == pytest.approx(v)

    def test_min_max_scale_matches(self, pair):
        sh, single = pair
        got, stats_sh = sh.min_max_scale("price")
        want, stats_single = single.min_max_scale("price")
        assert stats_sh["price"] == pytest.approx(stats_single["price"])
        np.testing.assert_allclose(
            got.to_table().df["price"].to_numpy(),
            want.df["price"].to_numpy(), rtol=1e-12)

    def test_cross_columns_matches(self, pair):
        sh, single = pair
        got = sh.cross_columns([["user", "item"]], [1000]).to_table().df
        want = single.cross_columns([["user", "item"]], [1000]).df
        np.testing.assert_array_equal(got["user_item"].to_numpy(),
                                      want["user_item"].to_numpy())


class TestShardedNegativeSampling:
    def test_counts_validity_and_stream_independence(self, df):
        sh = ShardedFeatureTable.partition(df, 4)
        out = sh.add_negative_samples(item_size=50, neg_num=2,
                                      seed=3).to_table().df
        assert len(out) == 3 * len(df)
        negs = out[out["label"] == 0]
        assert negs["item"].between(1, 50).all()
        # no negative equals its positive row's item: regenerate per shard
        # and compare against the positives they were drawn for
        per_shard = [FeatureTable(s).add_negative_samples(
                         50, neg_num=2, seed=3 + i).df
                     for i, s in enumerate(sh.shards)]
        for frame in per_shard:
            pos = frame[frame["label"] == 1]
            n = len(pos)
            for j in range(2):
                blk = frame.iloc[n * (j + 1): n * (j + 2)]
                assert (blk["item"].to_numpy()
                        != pos["item"].to_numpy()).all()
        # different shards draw different streams
        a = per_shard[0][per_shard[0]["label"] == 0]["item"].to_numpy()
        b = per_shard[1][per_shard[1]["label"] == 0]["item"].to_numpy()
        m = min(len(a), len(b))
        assert (a[:m] != b[:m]).any()


class TestAllgatherHelper:
    def test_single_process_roundtrip(self):
        obj = {"a": 1, "b": [1, 2, 3], "c": "text"}
        assert _allgather_objects(obj) == [obj]

    def test_row_local_ops_preserve_shards(self, df):
        sh = ShardedFeatureTable.partition(df, 4)
        out = sh.fillna(0.0).select("user", "item")
        assert out.num_partitions() == 4
        assert len(out) == len(df)

    def test_merge_cap_raises_naming_the_op(self):
        # a stat payload over the merge cap must fail loudly BEFORE the
        # collective, naming the op that produced it (docs/recsys.md
        # §Merge cap) — not OOM inside the allgather
        big = {"blob": "x" * 4096}
        with pytest.raises(ValueError, match="gen_string_idx"):
            _allgather_objects(big, op="gen_string_idx", max_bytes=1024)

    def test_merge_bytes_counter_increments(self):
        from bigdl_tpu.optim.metrics import global_metrics

        m = global_metrics()
        before = m.counter("friesian.sharded.merge_bytes_total")
        _allgather_objects({"k": list(range(100))})
        after = m.counter("friesian.sharded.merge_bytes_total")
        assert after > before  # every merge prices its pickled payload

    def test_vocab_feeds_identical_training_step(self, pair):
        # the end-to-end carry: the sharded vocab drives the SAME encoded
        # ids — so the same TwoTower embedding rows — as the single-host
        # twin (vocab drift would silently scramble the embedding table)
        sh, single = pair
        i_sh = sh.gen_string_idx("cat")
        i_single = single.gen_string_idx("cat")
        vals = single.df["cat"]
        np.testing.assert_array_equal(i_sh.encode(vals),
                                      i_single.encode(vals))
        assert i_sh.size == i_single.size


# ---------------------------------------------------------------------------
# true multi-process: each process owns DISJOINT shards; the stat merge must
# cross the jax.distributed rendezvous (the Spark-executor posture)

import os
import socket
import subprocess
import sys
import textwrap


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


MP_WORKER = textwrap.dedent("""
    import numpy as np
    import pandas as pd
    import jax
    jax.config.update("jax_platforms", "cpu")

    from bigdl_tpu.runtime.engine import init_engine
    from bigdl_tpu.data.shards import XShards
    from bigdl_tpu.friesian.sharded import ShardedFeatureTable
    from bigdl_tpu.friesian.table import FeatureTable

    init_engine()
    assert jax.process_count() == 2
    rank = jax.process_index()

    rs = np.random.RandomState(0)
    full = pd.DataFrame({
        "cat": rs.choice(["a", "b", "c", "d"], 120,
                         p=[0.4, 0.3, 0.2, 0.1]),
        "label": rs.randint(0, 2, 120),
    })
    # each process holds ONLY its half (process-local shards)
    mine = full.iloc[rank * 60:(rank + 1) * 60]
    sh = ShardedFeatureTable(XShards([mine], process_local=True))

    idx = sh.gen_string_idx("cat")
    want = FeatureTable(full).gen_string_idx("cat")
    assert idx.mapping == want.mapping, (idx.mapping, want.mapping)

    _, m_sh = sh.target_encode("cat", "label", smooth=5.0)
    _, m_single = FeatureTable(full).target_encode("cat", "label",
                                                   smooth=5.0)
    for k, v in m_single["cat"]["mapping"].items():
        assert abs(m_sh["cat"]["mapping"][k] - v) < 1e-9

    # distributed frame -> training handoff: NNEstimator.fit over the
    # process-local shards (ProcessLocalDataSet keeps step counts agreed)
    full["f0"] = rs.rand(120).astype("float32")
    full["f1"] = rs.rand(120).astype("float32")
    mine2 = full.iloc[rank * 60:(rank + 1) * 60]
    from bigdl_tpu.nnframes import NNClassifier
    from bigdl_tpu.nn.layers import Linear, ReLU
    from bigdl_tpu.nn.module import Sequential
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion
    from bigdl_tpu.optim.optim_method import Adam

    est = (NNClassifier(Sequential([Linear(2, 8), ReLU(), Linear(8, 2)]),
                        CrossEntropyCriterion())
           .set_max_epoch(3).set_batch_size(20)
           .set_optim_method(Adam(learning_rate=1e-2)))
    est.features_col = ["f0", "f1"]
    est.label_col = "label"
    model = est.fit(ShardedFeatureTable(XShards([mine2],
                                                process_local=True)))
    w = np.asarray(model.trained.variables["params"]["0_Linear"]["weight"])
    print(f"RANK{rank}_WSUM={float(np.abs(w).sum()):.8f}")
    print(f"RANK{rank}_FRIESIAN_OK")
""")


@pytest.mark.slow
def test_two_process_stat_merge(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(MP_WORKER)
    procs = []
    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pythonpath = os.pathsep.join(
        p for p in [repo_root, os.environ.get("PYTHONPATH")] if p)
    try:
        for r in range(2):
            env = dict(os.environ,
                       BIGDL_TPU_COORDINATOR=f"127.0.0.1:{port}",
                       BIGDL_TPU_NUM_PROCESSES="2",
                       BIGDL_TPU_PROCESS_ID=str(r),
                       JAX_PLATFORMS="cpu",
                       PYTHONPATH=pythonpath)
            env.pop("XLA_FLAGS", None)
            procs.append(subprocess.Popen(
                [sys.executable, str(script)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = []
        for p in procs:
            try:
                outs.append(p.communicate(timeout=420)[0])
            except subprocess.TimeoutExpired:
                p.kill()
                outs.append(p.communicate()[0])
        codes = [p.returncode for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert codes == [0, 0], f"exit {codes}\n{outs[0]}\n{outs[1]}"
    assert all(any("_FRIESIAN_OK" in ln for ln in o.splitlines())
               for o in outs)
    # the cross-process collectives kept the trained weights in sync even
    # though each process fed DIFFERENT (disjoint) rows
    wsums = sorted(ln.split("=")[1] for o in outs for ln in o.splitlines()
                   if "_WSUM=" in ln)
    assert len(wsums) == 2 and wsums[0] == wsums[1], wsums
