"""Friesian serving stack tests — reference scala/friesian gRPC services
(feature / recall / ranking / recommender) re-designed brokerless."""

import json
import urllib.request

import numpy as np
import pytest

from bigdl_tpu.friesian import (
    FeatureService, RankingService, RecallService, Recommender,
    RecsysHTTPServer,
)


def _stack(dim=8, n_items=200, seed=0):
    rng = np.random.RandomState(seed)
    items = rng.randn(n_items, dim).astype(np.float32)
    items /= np.linalg.norm(items, axis=1, keepdims=True)
    ids = [f"item_{i}" for i in range(n_items)]

    fs = FeatureService()
    fs.put_batch("item", ids, items)  # item feature = its embedding here
    rs = RecallService(dim)
    rs.add_items(ids, items)

    # ranking: score = dot(user_half, item_half) via a predict_fn
    def score(rows):
        u, it = rows[:, :dim], rows[:, dim:]
        return (u * it).sum(-1)

    rank = RankingService(predict_fn=score)
    rec = Recommender(fs, rs, rank, recall_candidates=50)
    return fs, rs, rank, rec, items, ids, rng


def test_recall_exact_topk():
    fs, rs, _, _, items, ids, rng = _stack()
    q = rng.randn(3, 8).astype(np.float32)
    got = rs.search(q, k=5)
    scores = q @ items.T
    for row, g in zip(scores, got):
        expect = np.argsort(-row)[:5]
        assert [ids[i] for i in expect] == [i for i, _ in g]
        np.testing.assert_allclose(sorted(row[expect], reverse=True),
                                   [s for _, s in g], rtol=1e-5)


def test_recall_incremental_add_reindexes():
    rs = RecallService(4)
    rs.add_items(["a"], [[1, 0, 0, 0]])
    rs.add_items(["b"], [[0, 1, 0, 0]])
    out = rs.search(np.array([[0.0, 1.0, 0, 0]]), k=2)[0]
    assert out[0][0] == "b" and rs.n_items == 2


def test_recommender_end_to_end():
    fs, rs, rank, rec, items, ids, rng = _stack()
    user = items[7] + 0.05 * rng.randn(8).astype(np.float32)
    fs.put("user", "u1", user)
    out = rec.recommend("u1", k=5)
    assert len(out) == 5
    # the aligned item must rank at/near the top
    assert "item_7" in [i for i, _ in out[:3]]
    # scores descending
    svals = [s for _, s in out]
    assert svals == sorted(svals, reverse=True)


def test_recommender_unknown_user_raises():
    _, _, _, rec, *_ = _stack()
    import pytest
    with pytest.raises(KeyError):
        rec.recommend("nobody")


def test_http_surface():
    fs, rs, rank, rec, items, ids, rng = _stack(seed=1)
    fs.put("user", "u2", items[3])
    srv = RecsysHTTPServer(rec).start()
    try:
        req = urllib.request.Request(
            srv.url + "/recommend",
            data=json.dumps({"user_id": "u2", "k": 4}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            out = json.loads(r.read())
        assert len(out["items"]) == 4
        assert out["items"][0]["id"] == "item_3"

        req = urllib.request.Request(
            srv.url + "/recall",
            data=json.dumps({"embedding": items[5].tolist(),
                             "k": 3}).encode())
        with urllib.request.urlopen(req, timeout=10) as r:
            out = json.loads(r.read())
        assert out["items"][0]["id"] == "item_5"

        # bad request -> 400, server stays up
        req = urllib.request.Request(srv.url + "/recommend",
                                     data=json.dumps({"k": 1}).encode())
        try:
            urllib.request.urlopen(req, timeout=10)
            assert False, "expected 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        srv.stop()


def test_recommender_backfills_to_k_when_features_sparse():
    fs, rs, rank, rec, items, ids, rng = _stack(seed=2)
    # wipe most item features: only 2 candidates will be rankable
    fs._kv["item"] = {k: v for k, v in list(fs._kv["item"].items())[:2]}
    fs.put("user", "u3", items[0])
    out = rec.recommend("u3", k=10)
    assert len(out) == 10  # backfilled from recall order
    # model-ranked entries carry a float score; backfilled entries carry
    # None (recall scores are not comparable to model scores)
    assert sum(s is not None for _, s in out) == 2
    assert all(s is None for _, s in out[2:])


def test_recall_bucketed_batches_match():
    _, rs, *_ , rng = _stack(seed=3)
    q = rng.randn(3, 8).astype(np.float32)
    one_by_one = [rs.search(q[i:i + 1], k=4)[0] for i in range(3)]
    batched = rs.search(q, k=4)
    for a, b in zip(one_by_one, batched):
        assert [i for i, _ in a] == [i for i, _ in b]


def test_two_tower_feeds_recall_service():
    """Offline flow: train TwoTower briefly, export item embeddings into
    RecallService, query with user-tower embeddings — top-k recalls the
    user's positive item (the reference's faiss-recall + two-tower
    pipeline, exact MIPS here)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.friesian.serving import RecallService
    from bigdl_tpu.models.recsys import TwoTower
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion

    rs = np.random.RandomState(0)
    n_users, n_items, H, N = 30, 25, 4, 64
    users = (np.arange(N) % n_users).astype(np.int32)
    pos = (users % (n_items - 1) + 1).astype(np.int32)
    hist = np.stack([np.where(rs.rand(H) < 0.7, p, 0)
                     for p in pos]).astype(np.int32)

    model = TwoTower(n_users, n_items, dim=16, hidden=(32,))
    variables = model.init(jax.random.PRNGKey(0), users, hist, pos)
    params = variables["params"]
    crit = CrossEntropyCriterion()
    tgt = np.arange(N).astype(np.int32)

    @jax.jit
    def step(p):
        def loss_fn(p):
            logits, _ = model.forward(p, {}, users, hist, pos)
            return crit(logits, tgt)

        loss, g = jax.value_and_grad(loss_fn)(p)
        return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g), loss

    for _ in range(150):
        params, _ = step(params)

    svc = RecallService(embedding_dim=16)
    item_ids = np.arange(n_items)
    svc.add_items(item_ids.tolist(),
                  np.asarray(model.encode_items(params, item_ids)))
    q = np.asarray(model.encode_users(params, users[:8], hist[:8]))
    got = svc.search(q, k=3)
    hit = np.mean([pos[i] in [int(item_id) for item_id, _score in got[i]]
               for i in range(8)])
    assert hit >= 0.75, (got, pos[:8])


class TestIVFRecall:
    def _clustered(self, dim=8, per=40, centers=6, seed=3):
        from bigdl_tpu.friesian.serving import IVFRecallService

        rng = np.random.RandomState(seed)
        mu = rng.randn(centers, dim).astype(np.float32) * 3
        items = np.concatenate(
            [mu[j] + 0.2 * rng.randn(per, dim).astype(np.float32)
             for j in range(centers)])
        ids = [f"i{j}" for j in range(len(items))]
        svc = IVFRecallService(dim, n_clusters=centers, nprobe=2,
                               kmeans_iters=8, seed=0)
        svc.add_items(ids, items)
        return svc, items, ids, rng

    def test_recall_quality_on_clustered_data(self):
        svc, items, ids, rng = self._clustered()
        q = items[rng.choice(len(items), 16, replace=False)] \
            + 0.05 * rng.randn(16, items.shape[1]).astype(np.float32)
        got = svc.search(q, k=10)
        exact = np.argsort(-(q @ items.T), axis=1)[:, :10]
        hits = sum(len({ids[i] for i in row} & {i for i, _ in g})
                   for row, g in zip(exact, got))
        # cluster-local queries with nprobe=2/6 must recall most of top-10
        assert hits / (16 * 10) >= 0.8, hits / 160

    def test_nprobe_all_is_exact(self):
        from bigdl_tpu.friesian.serving import IVFRecallService

        svc, items, ids, rng = self._clustered()
        full = IVFRecallService(items.shape[1], n_clusters=6, nprobe=6,
                                kmeans_iters=8, seed=0)
        full.add_items(ids, items)
        q = rng.randn(4, items.shape[1]).astype(np.float32)
        got = full.search(q, k=5)
        exact = np.argsort(-(q @ items.T), axis=1)[:, :5]
        for row, g in zip(exact, got):
            assert [ids[i] for i in row] == [i for i, _ in g]

    def test_add_items_invalidates_index(self):
        svc, items, ids, _ = self._clustered()
        svc.search(items[:1], k=3)  # builds the index
        new = items[0:1] * 10.0  # extreme vector dominating MIPS
        svc.add_items(["new"], new)
        out = svc.search(new, k=1)[0]
        assert out[0][0] == "new"

    def test_nprobe_validation(self):
        from bigdl_tpu.friesian.serving import IVFRecallService

        with pytest.raises(ValueError, match="nprobe"):
            IVFRecallService(8, n_clusters=4, nprobe=8)

    def test_k_exceeding_candidate_pool_is_clamped(self):
        svc, items, ids, rng = self._clustered()
        # per-cluster ~40 items, nprobe=2 -> pool ~80+pad; ask for far more
        out = svc.search(items[:2], k=10_000)
        for row in out:
            assert 0 < len(row) <= 10_000
            assert all(s != float("-inf") for _, s in row)
            assert len({i for i, _ in row}) == len(row)  # no phantom dups

    def test_k_cap_bounded_by_probe_pool(self):
        # the IVF k-cap is nprobe * padded-list-size, never more than
        # n_items — the k-bucket clamp must respect the probe pool, not
        # the full corpus (docs/recsys.md §Closed compile buckets)
        svc, items, ids, _ = self._clustered()
        cap = svc._k_cap()
        assert 0 < cap <= svc.n_items
        assert svc.nprobe * svc._lists.shape[1] >= cap


class TestRecallKBuckets:
    """The closed (batch, k) compile-bucket discipline
    (docs/recsys.md §Closed compile buckets)."""

    def test_k_bucket_rounds_up_and_clamps(self):
        fs, rs, *_ = _stack(n_items=50)
        assert rs._k_bucket(1) == 1
        assert rs._k_bucket(2) == 8
        assert rs._k_bucket(9) == 32
        assert rs._k_bucket(33) == 50   # clamped to the corpus
        assert rs._k_bucket(500) == 50

    def test_warmup_closes_the_compile_set(self):
        from bigdl_tpu.obs.attr import recompile_sentinel
        from bigdl_tpu.optim.metrics import global_metrics

        fs, rs, *_ , rng = _stack(n_items=150, seed=5)
        rs.warmup()
        sent = recompile_sentinel().install()
        m = global_metrics()
        before = m.counter("train.unexpected_recompiles_total")
        sent.mark_steady()
        try:
            for n, k in [(1, 1), (2, 3), (3, 7), (1, 20), (3, 130),
                         (2, 9_999)]:
                q = rng.randn(n, 8).astype(np.float32)
                got = rs.search(q, k=k)
                assert len(got) == n
                assert all(len(row) == min(k, 150) for row in got)
        finally:
            sent.mark_warmup()
        after = m.counter("train.unexpected_recompiles_total")
        assert after - before == 0, \
            "mixed (batch, k) sweep recompiled after warmup"

    def test_warmup_without_items_raises(self):
        with pytest.raises(RuntimeError, match="no items"):
            RecallService(8).warmup()
