"""Serving chaos suite — the request lifecycle under injected failure.

Deterministic, CPU-only specs for docs/serving.md's lifecycle guarantees:
every ACCEPTED request gets a correct answer or an explicit error (shed /
expired / dropped), never a hang or a silent drop, under worker death,
slow batches, full queues, and shutdown.  Fault injection uses the
``bigdl_tpu.resilience.faults`` points ``serving_predict_fail`` /
``serving_worker_kill`` / ``serving_slow_batch``.

In-process specs run under tier-1; the multi-worker pool chaos tests are
``slow`` (subprocess spawns) and run via ``make test-serving``.
"""

import json
import os
import threading
import time
from urllib import request as urlreq
from urllib.error import HTTPError

import numpy as np
import pytest

from bigdl_tpu.resilience import faults
from bigdl_tpu.resilience.faults import FaultSpec
from bigdl_tpu.serving import (DeadlineExceededError, InferenceModel,
                               RequestDroppedError, ServiceUnavailableError,
                               ServingConfig, ServingServer)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _echo(x):
    return np.asarray(x) * 2.0


def _slow(delay):
    def predict(x):
        time.sleep(delay)
        return np.asarray(x) * 2.0
    return predict


# ---------------------------------------------------------------------------
# deadline expiry


def test_deadline_expires_in_queue_before_predict():
    """A slow model backs the queue up; requests whose deadline passes
    while queued are dropped BEFORE predict with an explicit error."""
    calls = []

    def counting_slow(x):
        calls.append(np.asarray(x).shape[0])
        time.sleep(0.2)
        return np.asarray(x)

    srv = ServingServer(InferenceModel(predict_fn=counting_slow),
                        ServingConfig(batch_size=1,
                                      batch_timeout_s=0.0)).start()
    try:
        r1 = srv.enqueue(np.ones((1, 2), np.float32))      # occupies engine
        for _ in range(1000):      # r1 must be IN predict before r2
            if calls:              # arrives, or r2 jumps it (deadline-
                break              # aware ordering) and gets answered
            time.sleep(0.002)
        assert calls, "r1 never reached predict"
        r2 = srv.enqueue(np.ones((1, 2), np.float32), deadline_s=0.05)
        with pytest.raises(DeadlineExceededError):
            srv.query(r2, timeout=10)
        srv.query(r1, timeout=10)                          # unaffected
        assert srv.stats["expired_requests"] == 1
        # the per-tenant SLO surface says WHOSE deadline expired
        from bigdl_tpu.optim.metrics import global_metrics
        assert global_metrics().counter("serving.tenant.default.expired") >= 1
        # the expired request never reached the chip
        assert sum(calls) == 1, calls
    finally:
        srv.stop()


def test_default_deadline_from_config():
    srv = ServingServer(InferenceModel(predict_fn=_slow(0.2)),
                        ServingConfig(batch_size=1, batch_timeout_s=0.0,
                                      default_deadline_s=0.05)).start()
    try:
        srv.enqueue(np.ones((1, 2), np.float32))
        rid = srv.enqueue(np.ones((1, 2), np.float32))     # inherits default
        with pytest.raises(DeadlineExceededError):
            srv.query(rid, timeout=10)
    finally:
        srv.stop()


def test_deadline_expiry_under_injected_slow_batch():
    """serving_slow_batch makes every batch a straggler; a short-deadline
    request behind an IN-FLIGHT straggler expires, a no-deadline request
    survives.  (The in-flight wait matters: a short-deadline request that
    is merely *queued* jumps the window under deadline-aware ordering and
    would be answered in time.)"""
    faults.install([FaultSpec("serving_slow_batch", every=1, delay_s=0.15,
                              max_fires=4)])
    srv = ServingServer(InferenceModel(predict_fn=_echo),
                        ServingConfig(batch_size=1,
                                      batch_timeout_s=0.0)).start()
    try:
        r1 = srv.enqueue(np.ones((1, 2), np.float32))
        time.sleep(0.05)   # r1's straggler batch is now in predict
        r2 = srv.enqueue(np.ones((1, 2), np.float32), deadline_s=0.05)
        r3 = srv.enqueue(np.ones((1, 2), np.float32))
        np.testing.assert_array_equal(srv.query(r1, timeout=10), 2.0)
        with pytest.raises(DeadlineExceededError):
            srv.query(r2, timeout=10)
        np.testing.assert_array_equal(srv.query(r3, timeout=10), 2.0)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# backpressure


def test_enqueue_never_blocks_on_full_queue():
    """The blocking-put bug: a full queue sheds (429 semantics) instead of
    blocking the client thread indefinitely."""
    srv = ServingServer(InferenceModel(predict_fn=_slow(0.3)),
                        ServingConfig(batch_size=1, batch_timeout_s=0.0,
                                      queue_capacity=2)).start()
    try:
        t0 = time.time()
        shed = 0
        for _ in range(10):
            try:
                srv.enqueue(np.ones((1, 2), np.float32))
            except ServiceUnavailableError as e:
                shed += 1
                assert e.retry_after > 0
        # ten admission attempts against a capacity-2 queue returned
        # quickly — nothing blocked for the engine's 0.3s/batch pace
        assert time.time() - t0 < 0.25
        assert shed >= 6
        assert srv.stats["shed_requests"] == shed
    finally:
        srv.stop()


def test_backpressure_http_429_with_retry_after():
    from bigdl_tpu.serving import HttpFrontend

    srv = ServingServer(InferenceModel(predict_fn=_slow(0.3)),
                        ServingConfig(batch_size=1, batch_timeout_s=0.0,
                                      queue_capacity=1,
                                      retry_after_s=2.5)).start()
    fe = HttpFrontend(srv).start()
    try:
        body = json.dumps({"instances": [[1.0, 2.0]]}).encode()
        saw_429 = None
        for _ in range(8):
            req = urlreq.Request(fe.url + "/predict", data=body,
                                 headers={"Content-Type": "application/json"})
            try:
                # short client timeout: we only care about admission
                urlreq.urlopen(req, timeout=0.05)
            except HTTPError as e:
                if e.code == 429:
                    saw_429 = e.headers.get("Retry-After")
                    break
            except Exception:  # noqa: BLE001 — client-side timeout
                pass
        assert saw_429 == "2.5"
    finally:
        fe.stop()
        srv.stop()


def test_oversized_body_rejected_413():
    from bigdl_tpu.serving import HttpFrontend

    srv = ServingServer(InferenceModel(predict_fn=_echo)).start()
    fe = HttpFrontend(srv, max_body_bytes=512).start()
    try:
        req = urlreq.Request(fe.url + "/predict", data=b"x" * 2048,
                             headers={"Content-Type": "application/json"})
        with pytest.raises(HTTPError) as ei:
            urlreq.urlopen(req, timeout=10)
        assert ei.value.code == 413
        # the engine never saw it
        assert srv.stats["requests"] == 0
    finally:
        fe.stop()
        srv.stop()


# ---------------------------------------------------------------------------
# drain vs drop


def test_drain_finishes_queued_requests():
    srv = ServingServer(InferenceModel(predict_fn=_slow(0.05)),
                        ServingConfig(batch_size=4,
                                      batch_timeout_s=0.0)).start()
    rids = [srv.enqueue(np.full((1, 2), i, np.float32)) for i in range(16)]
    report = srv.drain(timeout=30)
    # nothing dropped; whatever had not completed before drain() began
    # was finished inside the budget
    assert report["dropped"] == 0 and report["drained"] >= 1
    for i, rid in enumerate(rids):
        np.testing.assert_array_equal(srv.query(rid, timeout=1), 2.0 * i)
    assert srv.stats["requests"] == 16
    # no silent leftovers: queue empty, results consumed
    assert srv._in.empty() and not srv._results
    with pytest.raises(ServiceUnavailableError):
        srv.enqueue(np.ones((1, 2), np.float32))   # draining sheds admission
    assert srv.stats["drained_requests"] == report["drained"]


def test_stop_without_drain_fails_queued_explicitly():
    srv = ServingServer(InferenceModel(predict_fn=_slow(0.3)),
                        ServingConfig(batch_size=1,
                                      batch_timeout_s=0.0)).start()
    r_inflight = srv.enqueue(np.ones((1, 2), np.float32))
    queued = [srv.enqueue(np.ones((1, 2), np.float32)) for _ in range(5)]
    time.sleep(0.05)                      # let the engine pick up the first
    srv.stop()
    # the in-flight batch finished; the queued ones got explicit verdicts
    np.testing.assert_array_equal(srv.query(r_inflight, timeout=1), 2.0)
    for rid in queued:
        with pytest.raises(RequestDroppedError):
            srv.query(rid, timeout=1)
    assert srv.stats["dropped_requests"] == 5


def test_drain_budget_exhausted_drops_remainder_explicitly():
    srv = ServingServer(InferenceModel(predict_fn=_slow(0.2)),
                        ServingConfig(batch_size=1,
                                      batch_timeout_s=0.0)).start()
    rids = [srv.enqueue(np.ones((1, 2), np.float32)) for _ in range(8)]
    report = srv.drain(timeout=0.3)
    assert report["dropped"] >= 1 and report["drained"] >= 1
    verdicts = {"ok": 0, "dropped": 0}
    for rid in rids:
        try:
            srv.query(rid, timeout=1)
            verdicts["ok"] += 1
        except RequestDroppedError:
            verdicts["dropped"] += 1
    assert verdicts["ok"] + verdicts["dropped"] == 8   # nobody hangs
    assert verdicts["dropped"] == report["dropped"]


def test_engine_survives_poison_batch():
    """A batch that fails BEFORE predict (shape-mismatched co-batched
    requests break np.concatenate) must not kill the dispatcher thread:
    its requests get the error, later requests still answer."""
    srv = ServingServer(InferenceModel(predict_fn=_echo),
                        ServingConfig(batch_size=8, batch_timeout_s=0.05))
    # enqueue BEFORE start so both requests land in the same first batch
    r1 = srv.enqueue(np.ones((1, 3), np.float32))
    r2 = srv.enqueue(np.ones((1, 4), np.float32))
    srv.start()
    try:
        verdicts = 0
        for rid in (r1, r2):
            try:
                srv.query(rid, timeout=10)
                verdicts += 1          # answered (split across batches)
            except TimeoutError:
                raise AssertionError("poison batch hung the engine")
            except Exception:  # noqa: BLE001 — explicit error is fine
                verdicts += 1
        assert verdicts == 2
        # the engine survived: a fresh request round-trips
        rid = srv.enqueue(np.ones((1, 3), np.float32))
        np.testing.assert_array_equal(srv.query(rid, timeout=10), 2.0)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# result-table TTL GC


def test_abandoned_results_are_gcd():
    srv = ServingServer(InferenceModel(predict_fn=_echo),
                        ServingConfig(result_ttl_s=0.1,
                                      result_gc_interval_s=0.02)).start()
    try:
        for _ in range(5):
            srv.enqueue(np.ones((1, 2), np.float32))   # never queried
        deadline = time.time() + 5
        while time.time() < deadline and srv.stats["results_gc"] < 5:
            time.sleep(0.02)
        assert srv.stats["results_gc"] == 5
        assert not srv._results and not srv._result_expiry
    finally:
        srv.stop()


def test_queried_results_not_gcd_within_ttl():
    srv = ServingServer(InferenceModel(predict_fn=_echo),
                        ServingConfig(result_ttl_s=30.0)).start()
    try:
        rid = srv.enqueue(np.ones((1, 2), np.float32))
        time.sleep(0.1)
        np.testing.assert_array_equal(srv.query(rid, timeout=5), 2.0)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# degradation + half-open probe race


def test_degraded_half_open_probe_race():
    """N threads hit enqueue on a degraded (no-fallback) server at once:
    exactly ONE probe is admitted per interval, the rest shed — the
    check-then-set race is closed by the probe lock."""

    class _Dying:
        def predict(self, x):
            raise RuntimeError("replica down")

    srv = ServingServer(_Dying(), ServingConfig(
        batch_size=1, batch_timeout_s=0.0, degraded_after_failures=1,
        degraded_probe_interval_s=60.0)).start()
    try:
        rid = srv.enqueue(np.ones((1, 2), np.float32))
        with pytest.raises(RuntimeError, match="replica down"):
            srv.query(rid, timeout=10)
        assert srv.degraded
        srv._last_probe_t = 0.0            # open the probe window once
        admitted, sheds = [], []
        barrier = threading.Barrier(8)

        def worker():
            barrier.wait()
            try:
                admitted.append(srv.enqueue(np.ones((1, 2), np.float32)))
            except ServiceUnavailableError:
                sheds.append(1)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        [t.start() for t in threads]
        [t.join(10) for t in threads]
        assert len(admitted) == 1, f"{len(admitted)} probes admitted"
        assert len(sheds) == 7
        assert srv.stats["shed_requests"] == 7
    finally:
        srv.stop()


def test_injected_predict_fail_drives_degradation_and_recovery():
    """serving_predict_fail (bounded fires) degrades the server; the next
    half-open probe after the plan is exhausted clears degradation."""
    faults.install([FaultSpec("serving_predict_fail", every=1, max_fires=2)])
    srv = ServingServer(InferenceModel(predict_fn=_echo), ServingConfig(
        batch_size=1, batch_timeout_s=0.0, degraded_after_failures=2,
        degraded_probe_interval_s=60.0)).start()
    try:
        for _ in range(2):
            rid = srv.enqueue(np.ones((1, 2), np.float32))
            with pytest.raises(faults.InjectedFault):
                srv.query(rid, timeout=10)
        assert srv.degraded
        srv._last_probe_t = 0.0            # probe window open
        rid = srv.enqueue(np.ones((1, 2), np.float32))
        np.testing.assert_array_equal(srv.query(rid, timeout=10), 2.0)
        assert not srv.degraded
        assert srv.stats["failed_batches"] == 2
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# multi-worker pool chaos (subprocess workers -> slow)


def _post(url, payload, timeout=30.0):
    req = urlreq.Request(url, data=json.dumps(payload).encode(),
                         headers={"Content-Type": "application/json"})
    with urlreq.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _pool_env(extra=None):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pythonpath = os.pathsep.join(
        p for p in [repo_root, os.environ.get("PYTHONPATH")] if p)
    env = {"PYTHONPATH": pythonpath, "BIGDL_TPU_POOL_CPU": "1",
           "JAX_PLATFORMS": "cpu"}
    env.update(extra or {})
    return env


@pytest.mark.slow
def test_pool_chaos_worker_kill_and_slow_batch():
    """The acceptance spec: a 2-worker pool under injected worker death
    (mid-request) and straggler batches loses ZERO accepted requests —
    every one gets a correct answer or an explicit error, the breaker/
    supervisor machinery respawns the corpse, and the counters are
    visible via /health."""
    from bigdl_tpu.serving.pool import ServingPool

    # each worker process: every batch is a straggler; the 6th _process
    # invocation exits the process mid-request.  Deterministic triggers:
    # the same plan fires at the same invocations in every run (count/
    # hash based, no live RNG).  Respawned workers inherit the plan, so
    # kills recur for as long as traffic flows.
    fault_plan = ("serving_slow_batch:every=1:delay=0.02:max=12;"
                  "serving_worker_kill:every=6:max=1")
    pool = ServingPool("tests.test_serving_multiproc:_pool_loader",
                       workers=2, batch_size=8,
                       worker_env=_pool_env({"BIGDL_TPU_FAULTS": fault_plan}),
                       supervise_interval_s=0.3, breaker_cooldown_s=0.5,
                       predict_timeout=20.0)
    pool.start()
    # ground truth from the same fixed-seed loader in a CLEAN subprocess
    # (the pytest process forces an 8-virtual-device XLA host via
    # conftest, which perturbs init — the workers run without it)
    import subprocess as sp
    import sys as _sys

    rs = np.random.RandomState(0)
    xs = [rs.rand(2, 8).astype(np.float32) for _ in range(18)]
    ref_out = sp.run(
        [_sys.executable, "-c",
         "import json,sys,numpy as np\n"
         "from tests.test_serving_multiproc import _pool_loader\n"
         "xs = np.asarray(json.loads(sys.stdin.read()), np.float32)\n"
         "im = _pool_loader()\n"
         "print(json.dumps([im.predict(x).tolist() for x in xs]))",
         ], input=json.dumps([x.tolist() for x in xs]),
        capture_output=True, text=True, env=dict(_pool_env(), PATH=os.environ["PATH"]),
        check=True)
    expects = [np.asarray(e, np.float32) for e in json.loads(ref_out.stdout)]
    try:
        answered, sheds, hangs = 0, 0, 0
        for i, (x, expect) in enumerate(zip(xs, expects)):
            # a client retries explicit sheds (429/503) — the lifecycle
            # contract is that those are the ONLY failure surface: an
            # accepted request answers correctly, never hangs, never
            # silently drops
            t_end = time.time() + 90
            while True:
                try:
                    out = _post(pool.url + "/predict",
                                {"instances": x.tolist()}, timeout=30.0)
                    preds = np.asarray(out["predictions"], np.float32)
                    np.testing.assert_allclose(preds, expect, rtol=1e-4,
                                               atol=1e-5)
                    answered += 1
                    break
                except HTTPError as e:
                    assert e.code in (429, 503), e.code
                    sheds += 1
                    if time.time() > t_end:
                        raise AssertionError(
                            f"request {i} shed past the retry budget")
                    time.sleep(0.3)
                except (TimeoutError, OSError) as e:
                    hangs += 1
                    raise AssertionError(f"request {i} hung: {e}")
        assert hangs == 0 and answered == 18
        # the injected kills happened and the supervisor recovered them
        assert pool.restarts >= 1, pool.restarts
        deadline = time.time() + 60
        while time.time() < deadline and not all(
                w.alive() for w in pool.workers):
            time.sleep(0.2)
        assert all(w.alive() for w in pool.workers)
        # counters visible via /health after recovery
        with urlreq.urlopen(pool.url + "/health", timeout=10) as r:
            h = json.loads(r.read())
        assert h["restarts"] >= 1
        assert all("breaker" in w for w in h["workers"])
        assert {w["breaker"]["state"] for w in h["workers"]} <= {
            "closed", "open", "half-open"}
        # respawned workers advertise their NEW urls (stale-corpse fix)
        for w, ww in zip(h["workers"], pool.workers):
            assert w["url"] == ww.url and w["alive"]
        print("CHAOS " + json.dumps({"answered": answered, "sheds": sheds,
                                     "restarts": h["restarts"]}))
    finally:
        pool.stop()


@pytest.mark.slow
def test_pool_drain_before_kill_on_stop():
    """stop() drains workers: requests in flight when stop() begins still
    complete (the worker finishes its queue before exiting)."""
    from bigdl_tpu.serving.pool import ServingPool

    # slow batches so the requests are genuinely in flight when stop()
    # lands — without drain they would die with the worker
    slow_env = _pool_env(
        {"BIGDL_TPU_FAULTS": "serving_slow_batch:every=1:delay=0.8:max=2"})
    pool = ServingPool("tests.test_serving_multiproc:_pool_loader",
                       workers=1, batch_size=8, worker_env=slow_env,
                       drain_timeout_s=10.0)
    pool.start()
    results, errors = [], []
    rs = np.random.RandomState(0)

    def client():
        try:
            x = rs.rand(2, 8).astype(np.float32)
            results.append(_post(pool.url + "/predict",
                                 {"instances": x.tolist()}))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=client) for _ in range(4)]
    [t.start() for t in threads]
    time.sleep(0.3)      # let them reach the worker queue
    pool.stop()
    [t.join(30) for t in threads]
    # drain-before-kill: in-flight work completed rather than dying with
    # the worker
    assert len(results) == 4, errors


@pytest.mark.slow
def test_pool_breaker_opens_and_recovers():
    """A killed worker's breaker opens after connection failures while the
    corpse is still routable-looking (respawn disabled via a huge
    supervise interval), then closes after respawn."""
    from bigdl_tpu.serving.pool import ServingPool

    pool = ServingPool("tests.test_serving_multiproc:_pool_loader",
                       workers=2, batch_size=8, worker_env=_pool_env(),
                       supervise_interval_s=3600.0, breaker_threshold=2,
                       breaker_cooldown_s=0.2)
    pool.start()
    try:
        rs = np.random.RandomState(0)
        _post(pool.url + "/predict",
              {"instances": rs.rand(2, 8).tolist()})
        victim = pool.workers[0]
        victim_url = victim.url
        victim.proc.kill()
        victim.proc.wait(timeout=10)
        # keep the corpse's url so the proxy actually attempts connections
        # (alive() already filters it; simulate the crashed-but-listed
        # window by feeding the breaker directly the way do_POST would)
        for _ in range(2):
            victim.breaker.record_failure()
        assert victim.breaker.state == "open"
        assert not victim.routable()           # the corpse is unroutable
        # an open breaker refuses admission without a connect attempt
        assert not victim.breaker.try_acquire()
        # requests keep flowing through the survivor
        for _ in range(4):
            out = _post(pool.url + "/predict",
                        {"instances": rs.rand(2, 8).tolist()})
            assert np.asarray(out["predictions"]).shape == (2, 4)
        # listing candidates must NOT consume the probe slot: the worker
        # stays plain 'open' until an actual attempt acquires it
        time.sleep(0.25)
        pool._next_workers()
        assert victim.breaker.state == "open"
        # half-open probe admits exactly one attempt after cooldown
        assert victim.breaker.try_acquire()    # the probe
        assert victim.breaker.state == "half-open"
        assert not victim.breaker.try_acquire()  # second caller blocked
        victim.breaker.record_failure()        # probe failed -> re-open
        assert victim.breaker.state == "open"
        time.sleep(0.25)
        assert victim.breaker.try_acquire()
        victim.breaker.record_success()        # probe succeeded -> closed
        assert victim.breaker.state == "closed"
        assert victim.breaker.trips >= 2
        assert victim_url == victim.url        # no respawn happened here
    finally:
        pool.stop()


@pytest.mark.slow
def test_pool_hedged_request_covers_slow_worker():
    """hedge_after_s: a straggling worker (injected slow batches) triggers
    ONE bounded hedge to the other worker; the request still answers fast
    and the hedge is counted."""
    from bigdl_tpu.serving.pool import ServingPool

    # worker-side: every batch sleeps well past the hedge trigger
    fault_plan = "serving_slow_batch:every=1:delay=1.0"
    slow_env = _pool_env({"BIGDL_TPU_FAULTS": fault_plan})
    pool = ServingPool("tests.test_serving_multiproc:_pool_loader",
                       workers=2, batch_size=8, worker_env=slow_env,
                       hedge_after_s=0.15, predict_timeout=20.0)
    pool.start()
    try:
        # both workers are slow (same env), so the hedge does not beat the
        # primary on wall clock — but it must fire, be bounded, and the
        # request must still answer exactly once
        rs = np.random.RandomState(0)
        out = _post(pool.url + "/predict",
                    {"instances": rs.rand(2, 8).tolist()}, timeout=30.0)
        assert np.asarray(out["predictions"]).shape == (2, 4)
        assert pool.stats["hedged_requests"] >= 1
    finally:
        pool.stop()
