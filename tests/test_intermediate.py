"""IR retargeting tests — reference `utils/intermediate` IRGraph/IRToDnn
specs + `nn/mkldnn/Fusion.scala` conv+bn folding."""

import jax
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.keras.engine import Input, Model
from bigdl_tpu.nn.module import Sequential
from bigdl_tpu.utils.intermediate import IRGraph, PallasLayerNorm


def _bn_with_stats(variables, rng, c):
    k = [k for k in variables["state"] if "BatchNorm" in k][0]
    variables["state"][k]["running_mean"] = rng.randn(c).astype(np.float32) * .2
    variables["state"][k]["running_var"] = (
        1.0 + 0.3 * rng.rand(c)).astype(np.float32)
    return variables


def test_xla_engine_identity_rebuild():
    model = Sequential([
        nn.Conv2D(2, 4, 3, padding="SAME"),
        nn.BatchNorm(4),
        nn.ReLU(),
        nn.Flatten(),
        nn.Linear(4 * 6 * 6, 5),
    ])
    rng = np.random.RandomState(0)
    x = rng.randn(2, 6, 6, 2).astype(np.float32)
    variables = _bn_with_stats(model.init(jax.random.PRNGKey(0), x), rng, 4)

    ir = IRGraph.from_model(model, variables)
    m2, v2 = ir.to_model("xla")
    y1, _ = model.apply(variables, x)
    y2, _ = m2.apply(v2, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-5, atol=1e-6)


def test_fused_engine_folds_conv_bn_and_drops_dropout():
    model = Sequential([
        nn.Conv2D(2, 4, 3, padding="SAME"),
        nn.BatchNorm(4),
        nn.ReLU(),
        nn.Dropout(0.5),
        nn.Flatten(),
        nn.Linear(4 * 6 * 6, 5),
    ])
    rng = np.random.RandomState(1)
    x = rng.randn(2, 6, 6, 2).astype(np.float32)
    variables = _bn_with_stats(model.init(jax.random.PRNGKey(0), x), rng, 4)

    m2, v2 = IRGraph.from_model(model, variables).to_model("fused")
    layers = [n.layer for n in m2.order if n.layer is not None]
    assert not any(isinstance(l, nn.BatchNorm) for l in layers)
    assert not any(isinstance(l, nn.Dropout) for l in layers)

    y1, _ = model.apply(variables, x)
    y2, _ = m2.apply(v2, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-4, atol=1e-5)


def test_fused_conv_without_bias_gains_folded_bias():
    model = Sequential([
        nn.Conv2D(3, 6, 3, padding="SAME", with_bias=False),
        nn.BatchNorm(6),
    ])
    rng = np.random.RandomState(2)
    x = rng.randn(2, 5, 5, 3).astype(np.float32)
    variables = _bn_with_stats(model.init(jax.random.PRNGKey(0), x), rng, 6)

    m2, v2 = IRGraph.from_model(model, variables).to_model("fused")
    convs = [n for n in m2.order
             if n.layer is not None and isinstance(n.layer, nn.Conv2D)]
    assert len(convs) == 1 and convs[0].layer.with_bias
    assert "bias" in v2["params"][convs[0].name]

    y1, _ = model.apply(variables, x)
    y2, _ = m2.apply(v2, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-4, atol=1e-5)


def test_fused_linear_bn_fold():
    model = Sequential([
        nn.Linear(8, 6),
        nn.BatchNorm(6),
        nn.Tanh(),
    ])
    rng = np.random.RandomState(3)
    x = rng.randn(4, 8).astype(np.float32)
    variables = _bn_with_stats(model.init(jax.random.PRNGKey(0), x), rng, 6)

    m2, v2 = IRGraph.from_model(model, variables).to_model("fused")
    assert not any(isinstance(n.layer, nn.BatchNorm)
                   for n in m2.order if n.layer is not None)
    y1, _ = model.apply(variables, x)
    y2, _ = m2.apply(v2, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-4, atol=1e-5)


def test_bn_not_folded_when_conv_has_two_consumers():
    inp = Input((5, 5, 3))
    conv = nn.Conv2D(3, 3, 3, padding="SAME")(inp)
    bn = nn.BatchNorm(3)(conv)
    out = nn.CAddTable()([bn, conv])  # conv feeds both bn and the skip
    model = Model(inp, out)
    rng = np.random.RandomState(4)
    x = rng.randn(2, 5, 5, 3).astype(np.float32)
    variables = _bn_with_stats(model.init(jax.random.PRNGKey(0), x), rng, 3)

    m2, v2 = IRGraph.from_model(model, variables).to_model("fused")
    layers = [n.layer for n in m2.order if n.layer is not None]
    assert any(isinstance(l, nn.BatchNorm) for l in layers)  # kept

    y1, _ = model.apply(variables, x)
    y2, _ = m2.apply(v2, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-4, atol=1e-5)


def test_fused_residual_graph_matches():
    inp = Input((6, 6, 4))
    a = nn.Conv2D(4, 4, 3, padding="SAME", with_bias=False)(inp)
    b = nn.BatchNorm(4)(a)
    r = nn.ReLU()(b)
    s = nn.CAddTable()([r, inp])
    model = Model(inp, s)
    rng = np.random.RandomState(5)
    x = rng.randn(2, 6, 6, 4).astype(np.float32)
    variables = _bn_with_stats(model.init(jax.random.PRNGKey(0), x), rng, 4)

    m2, v2 = IRGraph.from_model(model, variables).to_model("fused")
    layers = [n.layer for n in m2.order if n.layer is not None]
    assert not any(isinstance(l, nn.BatchNorm) for l in layers)
    y1, _ = model.apply(variables, x)
    y2, _ = m2.apply(v2, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-4, atol=1e-5)


def test_layernorm_retargets_to_pallas_twin():
    model = Sequential([
        nn.Linear(16, 16),
        nn.LayerNorm(16),
        nn.GELU(),
    ])
    rng = np.random.RandomState(6)
    x = rng.randn(4, 16).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    # non-trivial gamma/beta
    k = [k for k in variables["params"] if "LayerNorm" in k][0]
    variables["params"][k]["weight"] = (
        1 + 0.1 * rng.randn(16)).astype(np.float32)
    variables["params"][k]["bias"] = rng.randn(16).astype(np.float32) * .1

    m2, v2 = IRGraph.from_model(model, variables).to_model("fused")
    assert any(isinstance(n.layer, PallasLayerNorm)
               for n in m2.order if n.layer is not None)
    y1, _ = model.apply(variables, x)
    y2, _ = m2.apply(v2, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-4, atol=1e-5)


def test_ir_from_functional_multi_output():
    inp = Input((4,))
    h = nn.Linear(4, 8)(inp)
    o1 = nn.ReLU()(h)
    o2 = nn.Tanh()(h)
    model = Model(inp, [o1, o2])
    x = np.random.RandomState(7).randn(3, 4).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), x)

    m2, v2 = IRGraph.from_model(model, variables).to_model("xla")
    (a1, a2), _ = model.apply(variables, x)
    (b1, b2), _ = m2.apply(v2, x)
    np.testing.assert_allclose(np.asarray(a1), np.asarray(b1), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(a2), np.asarray(b2), rtol=1e-5)


def test_fused_then_xla_on_same_graph_is_not_corrupted():
    """to_model("fused") must not mutate the IRGraph: a subsequent
    to_model("xla") identity rebuild on the SAME graph must still match the
    original model (regression: the fuse pass used to rewire parents in
    place, silently dropping BN/Dropout from the later xla rebuild)."""
    model = Sequential([
        nn.Conv2D(2, 4, 3, padding="SAME"),
        nn.BatchNorm(4),
        nn.ReLU(),
        nn.Dropout(0.5),
        nn.Flatten(),
        nn.Linear(4 * 6 * 6, 5),
    ])
    rng = np.random.RandomState(3)
    x = rng.randn(2, 6, 6, 2).astype(np.float32)
    variables = _bn_with_stats(model.init(jax.random.PRNGKey(0), x), rng, 4)
    y_ref, _ = model.apply(variables, x)

    ir = IRGraph.from_model(model, variables)
    m_fused, v_fused = ir.to_model("fused")
    m_xla, v_xla = ir.to_model("xla")

    # the xla rebuild still contains BN + Dropout and matches the original
    layers = [n.layer for n in m_xla.order if n.layer is not None]
    assert any(isinstance(l, nn.BatchNorm) for l in layers)
    assert any(isinstance(l, nn.Dropout) for l in layers)
    y_xla, _ = m_xla.apply(v_xla, x)
    np.testing.assert_allclose(np.asarray(y_ref), np.asarray(y_xla),
                               rtol=1e-5, atol=1e-6)
    # and the fused twin still agrees numerically
    y_fused, _ = m_fused.apply(v_fused, x)
    np.testing.assert_allclose(np.asarray(y_ref), np.asarray(y_fused),
                               rtol=1e-4, atol=1e-5)
