"""PPML-equivalent tests: FedAvg rounds over HTTP, PSI, VFL split-NN."""

import threading

import numpy as np
import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.nn.criterion import BCEWithLogitsCriterion, MSECriterion
from bigdl_tpu.optim.optim_method import SGD
from bigdl_tpu.ppml import (FLClient, FLServer, FedAvg, PSIServer,
                            VFLNNTrainer, psi_intersect)

RS = np.random.RandomState(0)


def test_fedavg_weighted_mean():
    agg = FedAvg()
    agg.add({"w": np.asarray([1.0, 2.0])}, weight=1.0)
    agg.add({"w": np.asarray([3.0, 4.0])}, weight=3.0)
    np.testing.assert_allclose(agg.result()["w"], [2.5, 3.5])


def test_fl_two_clients_round_trip():
    model = nn.Linear(4, 2)
    x = jnp.asarray(RS.rand(8, 4).astype(np.float32))
    v1 = model.init(jax.random.PRNGKey(1), x)
    v2 = model.init(jax.random.PRNGKey(2), x)

    with FLServer(world_size=2) as server:
        c1 = FLClient(server.target, "alice")
        c2 = FLClient(server.target, "bob")

        out = {}

        def run(client, v, key):
            out[key] = client.sync(v, weight=1.0)

        t1 = threading.Thread(target=run, args=(c1, v1, "a"))
        t2 = threading.Thread(target=run, args=(c2, v2, "b"))
        t1.start(); t2.start(); t1.join(30); t2.join(30)

        # both got the same global model = mean of the two
        wa = np.asarray(out["a"]["params"]["weight"])
        wb = np.asarray(out["b"]["params"]["weight"])
        want = (np.asarray(v1["params"]["weight"])
                + np.asarray(v2["params"]["weight"])) / 2
        np.testing.assert_allclose(wa, want, atol=1e-6)
        np.testing.assert_allclose(wb, want, atol=1e-6)
        assert c1.status()["round"] == 1


def test_fl_training_converges():
    """Two parties with disjoint data shards train a shared linear model by
    FedAvg rounds; the global model must fit the union."""
    w_true = np.asarray([[2.0], [-1.0], [0.5]], np.float32)
    x_all = RS.rand(64, 3).astype(np.float32)
    y_all = x_all @ w_true
    shards = [(x_all[:32], y_all[:32]), (x_all[32:], y_all[32:])]

    model = nn.Linear(3, 1)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x_all[:1]))
    crit = MSECriterion()

    with FLServer(world_size=2) as server:
        clients = [FLClient(server.target, f"p{i}") for i in range(2)]
        local_vars = [variables, variables]

        def local_train(v, x, y, steps=8, lr=0.3):
            params = v["params"]
            for _ in range(steps):
                g = jax.grad(lambda p: crit(
                    model.forward(p, {}, jnp.asarray(x))[0],
                    jnp.asarray(y)))(params)
                params = jax.tree_util.tree_map(
                    lambda pp, gg: pp - lr * gg, params, g)
            return dict(v, params=params)

        for _ in range(6):  # federated rounds
            results = {}

            def round_fn(i):
                trained = local_train(local_vars[i], *shards[i])
                results[i] = clients[i].sync(trained)

            ts = [threading.Thread(target=round_fn, args=(i,))
                  for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(30)
            local_vars = [results[0], results[1]]

    final = float(crit(model.forward(local_vars[0]["params"], {},
                                     jnp.asarray(x_all))[0],
                       jnp.asarray(y_all)))
    assert final < 0.01, final


def test_psi():
    a = [f"user{i}" for i in range(0, 100, 2)]
    b = [f"user{i}" for i in range(0, 100, 3)]
    inter = psi_intersect(a, b)
    want = sorted(set(a) & set(b))
    assert sorted(inter) == want

    with FLServer(world_size=2) as server:
        pa = PSIServer(server.target, "alice")
        pb = PSIServer(server.target, "bob")
        pa.upload_set(a)
        pb.upload_set(b)
        got_a = pa.download_intersection(a)
        got_b = pb.download_intersection(b)
        assert sorted(got_a) == want
        assert sorted(got_b) == want


def test_vfl_split_nn_trains():
    """Two parties each hold half the features; split-NN training must fit
    a function that needs BOTH parties' features."""
    n = 256
    xa = RS.rand(n, 3).astype(np.float32)
    xb = RS.rand(n, 2).astype(np.float32)
    logits_true = 3.0 * xa[:, 0] - 2.0 * xb[:, 1] - 0.5
    y = (logits_true > 0).astype(np.float32)[:, None]

    bottom_a = nn.Sequential([nn.Linear(3, 8), nn.ReLU()])
    bottom_b = nn.Sequential([nn.Linear(2, 8), nn.ReLU()])
    top = nn.Linear(16, 1)

    va = bottom_a.init(jax.random.PRNGKey(1), jnp.asarray(xa))
    vb = bottom_b.init(jax.random.PRNGKey(2), jnp.asarray(xb))
    vt = top.init(jax.random.PRNGKey(3), jnp.ones((1, 16)))

    trainer = VFLNNTrainer(top, vt, BCEWithLogitsCriterion(),
                           lambda: SGD(learning_rate=0.5))
    trainer.add_party("alice", bottom_a, va)
    trainer.add_party("bob", bottom_b, vb)

    xs = {"alice": jnp.asarray(xa), "bob": jnp.asarray(xb)}
    first = trainer.train_batch(xs, jnp.asarray(y))
    for _ in range(200):
        last = trainer.train_batch(xs, jnp.asarray(y))
    assert last < first * 0.6, (first, last)

    pred = np.asarray(trainer.predict(xs))
    acc = ((pred[:, 0] > 0) == y[:, 0]).mean()
    assert acc > 0.85, acc


def test_fl_round_trip_over_tls(tmp_path):
    """VERDICT #9: https FL round trip — self-signed server cert, client
    pinned to it (reference scala/grpc TLS builders)."""
    import threading

    import jax.numpy as jnp

    from bigdl_tpu.ppml.fl import FLClient, FLServer
    from bigdl_tpu.ppml.tls import generate_self_signed

    cert, key = generate_self_signed(str(tmp_path / "tls"))
    tree = {"w": jnp.asarray([1.0, 2.0]), "b": jnp.asarray([0.5])}
    with FLServer(world_size=2, tls_cert=cert, tls_key=key) as srv:
        assert srv.target.startswith("https://")
        c1 = FLClient(srv.target, "a", cafile=cert)
        c2 = FLClient(srv.target, "b", cafile=cert)
        out = {}

        def run(c, scale, key_):
            scaled = {k: v * scale for k, v in tree.items()}
            out[key_] = c.sync(scaled)

        t = threading.Thread(target=run, args=(c2, 3.0, "b"))
        t.start()
        run(c1, 1.0, "a")
        t.join(timeout=60)
        # FedAvg of 1x and 3x = 2x
        np.testing.assert_allclose(np.asarray(out["a"]["w"]), [2.0, 4.0],
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(out["a"]["b"]),
                                   np.asarray(out["b"]["b"]), rtol=1e-6)


def test_fl_tls_rejects_unpinned_client(tmp_path):
    """A client without the pinned CA must fail the handshake — the cert
    is self-signed, so default trust stores reject it."""
    import urllib.error
    import urllib.request

    from bigdl_tpu.ppml.fl import FLServer
    from bigdl_tpu.ppml.tls import generate_self_signed

    cert, key = generate_self_signed(str(tmp_path / "tls"))
    import pytest

    with FLServer(world_size=1, tls_cert=cert, tls_key=key) as srv:
        with pytest.raises((urllib.error.URLError, OSError)):
            urllib.request.urlopen(f"{srv.target}/status", timeout=10)
