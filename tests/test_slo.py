"""Fleet-wide observability plane (docs/observability.md §Federation /
§SLOs & burn rates / §Decode timelines).

Tier-1 coverage: sliding-window histograms (empty-window NaN vs empty
histogram, rotation under concurrent observe), labeled Prometheus series
+ the collision-safe tenant-label aliases, exposition parse/federate
round-trips, the FEDERATED pool scrape staying well-formed while a worker
is killed mid-scrape (stale series dropped, ``federation_stale``
counted), declarative SLO specs -> multi-window burn rates -> ``slo_burn``
flight events -> the health score the autoscaler consults (chaos spec:
an injected latency violation crosses the burn gauge within one window,
asserted from a single scrape + flight dump), token-level decode
chrome-trace timelines joined by request id, flight dumps carrying the
decode engine's event ring, cluster-side metric federation
(``cluster.host.*{host=}``), and the sentinel's SLO_r* family."""

import json
import math
import re
import threading
import time

import numpy as np
import pytest

import jax

from bigdl_tpu.nn.attention import Transformer
from bigdl_tpu.obs import flight, trace
from bigdl_tpu.obs.export import (federate, parse_exposition,
                                  render_prometheus)
from bigdl_tpu.obs.hist import LogHistogram
from bigdl_tpu.obs.slo import (SLOEvaluator, SLOSpec, bench, load_specs)
from bigdl_tpu.optim.metrics import Metrics, label_key
from bigdl_tpu.serving.http_frontend import HttpClient, HttpFrontend
from bigdl_tpu.serving.pool import ServingPool
from bigdl_tpu.serving.server import ServingConfig, ServingServer

BOS, EOS = 0, 1


@pytest.fixture(autouse=True)
def _clean_obs():
    flight.global_recorder().clear()
    yield
    trace.disable()


class _Model:
    """Minimal predict surface for the continuous engine; ``delay``
    injects the latency violation the SLO chaos specs need."""

    def __init__(self, delay: float = 0.0):
        self.delay = delay

    def predict(self, x):
        if self.delay:
            time.sleep(self.delay)
        return np.asarray(x, np.float32) * 2.0


# a general exposition validator (the test_obs _LINE regex predates
# labels): every line is a comment, a TYPE/HELP header, or a sample with
# an optional label body; each family is declared at most once
_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^{}]*\})? (?:[0-9.eE+-]+|\+Inf|NaN)$")


def _assert_parse_clean(text: str) -> None:
    types = {}
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            name, typ = line[len("# TYPE "):].split(" ", 1)
            assert name not in types, f"family {name} declared twice"
            types[name] = typ
            continue
        if line.startswith("# HELP ") or line.startswith("#"):
            continue
        assert _SAMPLE.match(line), f"unparseable line: {line!r}"
    # no duplicate series: identical name+labels twice fails a real scrape
    seen = set()
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        series = line.rsplit(" ", 1)[0]
        assert series not in seen, f"duplicate series: {series}"
        seen.add(series)


# ---------------------------------------------------------------------------
# sliding-window histograms
# ---------------------------------------------------------------------------

class TestWindowedHistogram:
    def test_empty_window_nan_while_cumulative_has_data(self):
        """The satellite contract: a stale histogram's WINDOW percentile
        is NaN exactly like an empty histogram's — old samples must not
        masquerade as a fresh p99."""
        t = [0.0]
        h = LogHistogram(window_s=10.0, window_slices=5, clock=lambda: t[0])
        for v in (0.01, 0.02, 0.04):
            h.observe(v)
        assert h.percentile(99) > 0                      # cumulative: data
        assert h.window_percentile(99) > 0               # fresh window too
        t[0] = 100.0                                     # window ages out
        assert math.isnan(h.window_percentile(99))
        assert math.isnan(h.window_fraction_over(0.001))
        assert h.window_count() == 0
        assert h.percentile(99) > 0                      # cumulative keeps
        # and a truly empty histogram answers the same way
        h2 = LogHistogram()
        assert math.isnan(h2.window_percentile(99))
        assert math.isnan(h2.percentile(99))

    def test_window_rotation_tracks_recent_samples_only(self):
        t = [0.0]
        h = LogHistogram(window_s=10.0, window_slices=5, clock=lambda: t[0])
        for _ in range(100):
            h.observe(1.0)       # slow era
        t[0] = 20.0
        for _ in range(100):
            h.observe(0.001)     # fast era — the only one in the window
        assert h.window_percentile(99) <= 0.002
        assert h.percentile(50) >= 0.5 or h.n == 200  # cumulative remembers
        assert h.window_fraction_over(0.5) == 0.0
        # partial ageing: half the window later, old slices drop one by one
        t[0] = 26.0
        h.observe(1.0)
        frac = h.window_fraction_over(0.5)
        assert 0.0 < frac < 0.5

    def test_window_fraction_over_bucket_granularity(self):
        h = LogHistogram()
        for _ in range(90):
            h.observe(0.001)
        for _ in range(10):
            h.observe(10.0)
        assert h.window_fraction_over(1.0) == pytest.approx(0.10)
        assert h.window_fraction_over(100.0) == 0.0

    def test_rotation_under_concurrent_observe(self):
        """The regression spec the satellite asks for: writers observing
        through the shared Metrics registry while a reader rotates the
        window concurrently — nothing lost, nothing double-counted, no
        exception."""
        m = Metrics()
        name = "slo_test.concurrent_latency_s"
        # short window so real rotations happen during the test
        with m._lock:
            m.hists[name] = LogHistogram(window_s=0.2, window_slices=4)
        n_threads, per_thread = 4, 1500
        errors = []

        def write():
            try:
                for i in range(per_thread):
                    m.observe(name, 0.001 * (1 + i % 7))
                    if i % 100 == 0:
                        time.sleep(0.002)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        stop = threading.Event()

        def read():
            try:
                while not stop.is_set():
                    p = m.window_percentile(name, 99)
                    assert math.isnan(p) or p > 0
                    f = m.window_fraction_over(name, 0.004)
                    assert math.isnan(f) or 0.0 <= f <= 1.0
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        writers = [threading.Thread(target=write) for _ in range(n_threads)]
        reader = threading.Thread(target=read)
        reader.start()
        for w in writers:
            w.start()
        for w in writers:
            w.join()
        stop.set()
        reader.join()
        assert not errors
        h = m.hists[name]
        assert h.n == n_threads * per_thread       # nothing lost
        assert sum(h.counts) == h.n                # nothing double-counted
        assert h.window_count() <= h.n


# ---------------------------------------------------------------------------
# labeled series + federation
# ---------------------------------------------------------------------------

class TestLabeledExport:
    def test_labeled_series_share_one_family_declaration(self):
        m = Metrics()
        m.inc("serving.tenant_requests_total", 2, labels={"tenant": "a"})
        m.inc("serving.tenant_requests_total", 5, labels={"tenant": "b"})
        m.observe("serving.tenant_latency_seconds", 0.01,
                  labels={"tenant": "a"})
        m.observe("serving.tenant_latency_seconds", 0.02,
                  labels={"tenant": "b"})
        text = render_prometheus(m)
        _assert_parse_clean(text)
        assert text.count("# TYPE serving_tenant_requests_total") == 1
        assert 'serving_tenant_requests_total{tenant="a"} 2.0' in text
        assert 'serving_tenant_requests_total{tenant="b"} 5.0' in text
        # histogram buckets merge the le label with the series labels
        assert re.search(
            r'serving_tenant_latency_seconds_bucket\{tenant="a",'
            r'le="\+Inf"\} 1', text)
        assert 'serving_tenant_latency_seconds_count{tenant="a"} 1' in text

    def test_label_key_escaping(self):
        assert label_key("n", tenant="a") == 'n{tenant="a"}'
        assert label_key("n", b="2", a="1") == 'n{a="1",b="2"}'
        assert label_key("n", v='x"y\\z') == 'n{v="x\\"y\\\\z"}'

    def test_collision_safety_with_legacy_aliases(self):
        """The satellite's collision spec: legacy name-embedded tenant
        series and the labeled aliases coexist in ONE scrape — distinct
        families, each declared once — while two base names that
        sanitize onto the same family still drop the later one."""
        m = Metrics()
        # the doubled emission the server does per request
        m.observe("serving.tenant.alpha.latency_s", 0.01)
        m.observe("serving.tenant_latency_seconds", 0.01,
                  labels={"tenant": "alpha"})
        # a base-name collision: label form vs a dotted name that
        # sanitizes identically
        m.gauge("serving.tenant_queue_depth", 3.0,
                labels={"tenant": "alpha"})
        m.gauge("serving.tenant.queue_depth", 99.0)
        text = render_prometheus(m)
        _assert_parse_clean(text)
        assert "# TYPE serving_tenant_alpha_latency_s histogram" in text
        assert "# TYPE serving_tenant_latency_seconds histogram" in text
        assert text.count("# TYPE serving_tenant_queue_depth gauge") == 1
        # exactly ONE base name wins the family (sorted order: the dotted
        # name); the loser's sample is dropped, never emitted under a
        # foreign declaration
        assert "serving_tenant_queue_depth 99.0" in text
        assert 'serving_tenant_queue_depth{tenant="alpha"} 3.0' \
            not in text

    def test_parse_exposition_round_trip(self):
        m = Metrics()
        m.inc("a.count", 2)
        m.gauge("b.level", 1.5, labels={"k": "v"})
        m.observe("c.lat_s", 0.1)
        fams = parse_exposition(render_prometheus(m))
        by = {f["name"]: f for f in fams}
        assert by["a_count"]["type"] == "counter"
        assert by["b_level"]["type"] == "gauge"
        assert ("b_level", 'k="v"', "1.5") in by["b_level"]["samples"]
        hist = by["c_lat_s"]
        assert hist["type"] == "histogram"
        assert any(s[0] == "c_lat_s_bucket" for s in hist["samples"])

    def test_federate_injects_labels_and_declares_once(self):
        a, b = Metrics(), Metrics()
        a.inc("serving.requests", 2)
        a.observe("serving.latency_s", 0.1)
        b.inc("serving.requests", 7)
        b.observe("serving.latency_s", 0.2)
        text = federate([({"worker": "w0"}, render_prometheus(a)),
                         ({"worker": "w1"}, render_prometheus(b))])
        _assert_parse_clean(text)
        assert text.count("# TYPE serving_requests counter") == 1
        assert 'serving_requests{worker="w0"} 2.0' in text
        assert 'serving_requests{worker="w1"} 7.0' in text
        # bucket lines keep le= AND gain worker=
        assert re.search(
            r'serving_latency_s_bucket\{le="\+Inf",worker="w1"\} 1', text)


class _FakeWorker:
    """In-process stand-in for a pool worker: routable as long as its
    frontend lives (the federation specs need no subprocesses)."""

    def __init__(self, name, url):
        self.name = name
        self.url = url
        from bigdl_tpu.serving.pool import _Breaker

        self.breaker = _Breaker(name=name)
        self._alive = True

    def alive(self):
        return self._alive

    def routable(self):
        return self._alive and self.url is not None


class TestFederatedPoolScrape:
    @pytest.fixture()
    def pool_of_two(self, request):
        """Two in-process 'workers' (own registries, two tenants each)
        behind a real proxy socket — only the proxy HTTP thread runs; no
        supervisor/autoscaler, no subprocesses."""
        workers, fes = [], []
        for i in range(2):
            srv = ServingServer(
                models={"alpha": _Model(), "beta": _Model()},
                config=ServingConfig(batch_size=4, batch_timeout_s=0.001),
                metrics=Metrics()).start()
            fe = HttpFrontend(srv, port=0).start()
            # traffic on BOTH tenants so per-tenant series exist
            for tenant in ("alpha", "beta"):
                rid = srv.enqueue(np.ones((1, 2), np.float32),
                                  model=tenant)
                srv.query(rid, timeout=10)
            workers.append(srv)
            fes.append(fe)
        pool = ServingPool("unused:loader", workers=0)
        pool.workers = [_FakeWorker(f"worker-{i}", fes[i].url)
                        for i in range(2)]
        t = threading.Thread(target=pool._httpd.serve_forever,
                             daemon=True)
        t.start()

        def fin():
            pool._httpd.shutdown()
            pool._httpd.server_close()
            for fe in fes:
                try:
                    fe.stop()
                except Exception:
                    pass
            for srv in workers:
                srv.stop()

        request.addfinalizer(fin)
        return pool, workers, fes

    def test_federated_scrape_covers_workers_and_tenants(self, pool_of_two):
        """Acceptance: ONE proxy scrape, parse-clean, >=2 live workers
        and >=2 tenants visible via labels."""
        pool, _, _ = pool_of_two
        cl = HttpClient(pool.url)
        text = cl.metrics()
        _assert_parse_clean(text)
        for w in ("worker-0", "worker-1"):
            assert f'worker="{w}"' in text
        # the labeled tenant families carry every tenant on every worker
        for w in ("worker-0", "worker-1"):
            for tenant in ("alpha", "beta"):
                assert re.search(
                    r"serving_tenant_requests_total\{tenant=\"%s\","
                    r"worker=\"%s\"\} 1\.0" % (tenant, w), text), \
                    (tenant, w, text[:2000])
        # proxy-side families ride the same scrape, unlabeled
        assert "# TYPE serving_pool_federation_stale counter" in text \
            or "serving_pool_federation_stale" in text

    def test_worker_killed_mid_scrape_degrades_gracefully(self,
                                                          pool_of_two):
        """Acceptance: killing a worker degrades the scrape (its series
        dropped, federation_stale counted) — the scrape itself stays 200
        and parse-clean.  The operator's dashboard must survive exactly
        the moment workers are dying."""
        pool, workers, fes = pool_of_two
        cl = HttpClient(pool.url)
        before = cl.metrics()
        assert 'worker="worker-1"' in before
        fes[1].stop()          # killed mid-scrape: socket gone, worker
        #                        still listed as routable
        # a real kill severs established sockets too; the in-process
        # frontend only closes its listener, so drop the parked
        # keep-alive conns exactly like the supervisor does on death
        pool.conns.clear(fes[1].url)
        after = cl.metrics()
        _assert_parse_clean(after)
        assert 'worker="worker-0"' in after
        assert 'worker="worker-1"' not in after   # stale series dropped
        assert pool.stats["federation_stale"] >= 1
        # ... and the counter is visible in the very scrape that paid it
        m = re.search(r"serving_pool_federation_stale (\d+)", after)
        assert m and int(m.group(1)) >= 1


# ---------------------------------------------------------------------------
# declarative SLOs
# ---------------------------------------------------------------------------

class TestSLOSpecs:
    def test_spec_grammar(self):
        spec = SLOSpec.from_dict({
            "tenant": "ranker",
            "objectives": {"predict_p99_s": 0.2, "ttft_p95_s": 0.5,
                           "availability": 0.999},
            "window_s": 30.0})
        by = {o.name: o for o in spec.objectives}
        assert by["predict_p99_s"].kind == "latency"
        assert by["predict_p99_s"].target == pytest.approx(0.99)
        assert by["predict_p99_s"].threshold_s == 0.2
        assert by["predict_p99_s"].metric \
            == "serving.tenant_latency_seconds"
        assert by["ttft_p95_s"].metric == "serving.tenant_ttft_seconds"
        assert by["ttft_p95_s"].target == pytest.approx(0.95)
        assert by["availability"].kind == "availability"
        assert by["availability"].budget == pytest.approx(0.001)

    def test_spec_rejects_unknown_objective(self):
        with pytest.raises(ValueError, match="unknown SLO objective"):
            SLOSpec.from_dict({"tenant": "x",
                               "objectives": {"p99_of_vibes": 1}})
        with pytest.raises(ValueError, match="availability target"):
            SLOSpec.from_dict({"tenant": "x",
                               "objectives": {"availability": 1.5}})
        # window_s=0 would busy-spin the background evaluator thread
        with pytest.raises(ValueError, match="window_s"):
            SLOSpec.from_dict({"tenant": "x", "window_s": 0,
                               "objectives": {"predict_p99_s": 0.1}})
        with pytest.raises(ValueError, match="long_window_factor"):
            SLOSpec.from_dict({"tenant": "x", "long_window_factor": 0.5,
                               "objectives": {"predict_p99_s": 0.1}})

    def test_evaluator_presizes_hists_for_long_window(self):
        """A spec window longer than the default 60s ring must be
        answerable: the evaluator pre-sizes its tenant histograms to the
        LONG (6x) window at the short window's slice resolution."""
        m = Metrics()
        SLOEvaluator([{"tenant": "t", "window_s": 60.0,
                       "objectives": {"predict_p99_s": 0.1}}], metrics=m)
        h = m.hists[label_key("serving.tenant_latency_seconds",
                              tenant="t")]
        assert h.window_s == 360.0
        assert h._slice_s == pytest.approx(10.0)  # short window / 6

    def test_load_specs_forms(self, tmp_path):
        d = {"tenant": "a", "objectives": {"predict_p99_s": 0.1}}
        assert len(load_specs([d, dict(d, tenant="b")])) == 2
        assert load_specs(d)[0].tenant == "a"
        assert load_specs(json.dumps([d]))[0].tenant == "a"
        p = tmp_path / "slo.json"
        p.write_text(json.dumps([d]))
        assert load_specs(str(p))[0].tenant == "a"
        assert load_specs(None) == []

    def test_latency_burn_rate_math(self):
        """10% of window samples over a p99 bound = 10x the 1% budget."""
        m = Metrics()
        ev = SLOEvaluator([{"tenant": "t", "window_s": 60.0,
                            "objectives": {"predict_p99_s": 0.1}}],
                          metrics=m)
        for _ in range(90):
            m.observe("serving.tenant_latency_seconds", 0.01,
                      labels={"tenant": "t"})
        for _ in range(10):
            m.observe("serving.tenant_latency_seconds", 1.0,
                      labels={"tenant": "t"})
        (st,) = ev.evaluate()
        assert st.burn == pytest.approx(10.0, rel=0.01)
        assert st.burning
        assert ev.health_score() == 0.0
        g = m.gauges[label_key("slo.burn_rate", tenant="t",
                               objective="predict_p99_s")]
        assert g == pytest.approx(10.0, rel=0.01)

    def test_availability_burn_from_counter_deltas(self):
        t = [0.0]
        m = Metrics()
        ev = SLOEvaluator([{"tenant": "t", "window_s": 10.0,
                            "objectives": {"availability": 0.99}}],
                          metrics=m, clock=lambda: t[0])
        lb = {"tenant": "t"}
        ev.evaluate()                       # baseline counter snapshot
        m.inc("serving.tenant_requests_total", 98, labels=lb)
        m.inc("serving.tenant_failed_total", 2, labels=lb)
        t[0] = 1.0
        (st,) = ev.evaluate()
        assert st.burn == pytest.approx(2.0, rel=0.01)  # 2% bad / 1% budget
        assert st.burning
        # good-only traffic pushes the window ratio back under budget
        m.inc("serving.tenant_requests_total", 900, labels=lb)
        t[0] = 2.0
        (st2,) = ev.evaluate()
        assert st2.burn < st.burn

    def test_no_data_is_no_burn(self):
        m = Metrics()
        ev = SLOEvaluator([{"tenant": "ghost",
                            "objectives": {"predict_p99_s": 0.1,
                                           "availability": 0.999}}],
                          metrics=m)
        for st in ev.evaluate():
            assert st.burn == 0.0 and not st.burning
            assert st.samples == 0
        assert ev.health_score() == 1.0

    def test_burn_flight_event_fires_once_and_clears(self, tmp_path):
        t = [0.0]
        m = Metrics()
        ev = SLOEvaluator([{"tenant": "t", "window_s": 5.0,
                            "objectives": {"predict_p99_s": 0.01}}],
                          metrics=m, clock=lambda: t[0])
        lb = {"tenant": "t"}
        # the histogram shares the injected clock so its window ages on
        # the same timeline the evaluator reads
        with m._lock:
            m.hists[label_key("serving.tenant_latency_seconds", **lb)] \
                = LogHistogram(window_s=5.0, clock=lambda: t[0])
        for _ in range(20):
            m.observe("serving.tenant_latency_seconds", 1.0, labels=lb,
                      )
        ev.evaluate()
        ev.evaluate()          # still burning: no second event
        kinds = [e["kind"] for e in flight.global_recorder().snapshot()]
        assert kinds.count("slo_burn") == 1
        assert m.counters["slo.burn_events_total"] == 1
        # recovery: the window ages out -> burn 0 -> cleared event
        t[0] = 1000.0
        ev.evaluate()
        kinds = [e["kind"] for e in flight.global_recorder().snapshot()]
        assert "slo_burn_cleared" in kinds

    def test_autoscaler_consults_slo_health(self):
        """The pure policy spec: a burning SLO scales up even with empty
        queues, and an unhealthy pool never scales down."""
        dec = ServingPool.autoscale_decision
        base = dict(n_workers=2, min_workers=1, max_workers=4,
                    avg_queue_depth=0.0, up_depth=16.0, idle_ticks=10,
                    down_after=3, breaker_open=False,
                    since_last_scale_s=99.0, cooldown_s=5.0)
        assert dec(**base, slo_health=1.0, unhealthy_below=0.5) == "down"
        assert dec(**base, slo_health=0.2, unhealthy_below=0.5) == "up"
        # cooldown still gates the SLO signal
        assert dec(**dict(base, since_last_scale_s=1.0),
                   slo_health=0.2, unhealthy_below=0.5) == "hold"
        # at the max bound: no up, but ALSO no down while unhealthy
        assert dec(**dict(base, n_workers=4),
                   slo_health=0.2, unhealthy_below=0.5) == "hold"
        # signal disabled (unhealthy_below=0): behaves as before
        assert dec(**base, slo_health=0.0, unhealthy_below=0.0) == "down"


class TestSLOChaosAcceptance:
    def test_injected_latency_fires_burn_within_one_window(self, tmp_path):
        """THE acceptance chaos spec: a forced latency injection drives
        the tenant past its declared SLO — the burn gauge crosses 1.0
        within one evaluation window, an slo_burn flight event lands in
        the dump, and the health score the pool consults reflects it.
        Asserted from a single scrape + a single flight dump."""
        window_s = 5.0
        cfg = ServingConfig(
            batch_size=4, batch_timeout_s=0.001,
            slo=[{"tenant": "default", "window_s": window_s,
                  "objectives": {"predict_p99_s": 0.01,
                                 "availability": 0.99}}])
        srv = ServingServer(_Model(delay=0.05), cfg,
                            metrics=Metrics()).start()
        fe = HttpFrontend(srv, port=0).start()
        try:
            assert srv.slo is not None
            assert srv.slo_health() == 1.0          # before the violation
            t_violation = time.time()
            for _ in range(6):                      # every request 5x over
                rid = srv.enqueue(np.ones((1, 2), np.float32))
                srv.query(rid, timeout=10)
            srv.slo.evaluate()
            detect_s = time.time() - t_violation
            assert detect_s < window_s, \
                "burn must cross within one evaluation window"
            # -- one scrape carries the verdict --------------------------
            text = HttpClient(fe.url).metrics()
            _assert_parse_clean(text)
            m = re.search(
                r'slo_burn_rate\{objective="predict_p99_s",'
                r'tenant="default"\} ([0-9.eE+]+)', text)
            assert m, text[:2000]
            assert float(m.group(1)) > 1.0
            hm = re.search(r"^slo_health ([0-9.eE+-]+)", text, re.M)
            assert hm and float(hm.group(1)) < 0.5
            # the pool's scaling policy acts on exactly this number
            assert ServingPool.autoscale_decision(
                n_workers=1, min_workers=1, max_workers=4,
                avg_queue_depth=0.0, up_depth=16.0, idle_ticks=0,
                down_after=3, breaker_open=False,
                since_last_scale_s=99.0, cooldown_s=5.0,
                slo_health=srv.slo_health(),
                unhealthy_below=0.5) == "up"
            # /health surfaces the same verdict for operators
            health = HttpClient(fe.url).health()
            assert health["slo_health"] < 0.5
            assert health["slo"]["objectives"]
            # -- one flight dump carries the event -----------------------
            path = flight.global_recorder().dump(
                str(tmp_path / "flight.jsonl"))
            events = [json.loads(l) for l in open(path)]
            burns = [e for e in events if e.get("kind") == "slo_burn"]
            assert burns and burns[0]["tenant"] == "default"
            assert burns[0]["objective"] == "predict_p99_s"
            assert burns[0]["burn"] > 1.0
        finally:
            fe.stop()
            srv.stop()


# ---------------------------------------------------------------------------
# token-level decode timelines
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def lm_served(request):
    from bigdl_tpu.serving import (DecodeConfig, InferenceModel)

    model = Transformer(vocab_size=32, hidden_size=16, num_heads=2,
                        num_layers=2, dropout=0.0, mode="lm")
    v = model.init(jax.random.PRNGKey(0),
                   np.arange(6, dtype=np.int32)[None])
    im = InferenceModel(model, v, decode=DecodeConfig(
        slots=4, page_size=4, pages_per_slot=4, prompt_chunk=4,
        max_new_tokens=8, eos_id=EOS))
    srv = ServingServer(im, ServingConfig(batch_size=4)).start()
    fe = HttpFrontend(srv, port=0).start()

    def fin():
        fe.stop()
        srv.stop()
        im.decode_engine.stop()

    request.addfinalizer(fin)
    return im, srv, fe


class TestDecodeTimelines:
    def test_streamed_generate_chrome_trace_joined_by_request_id(
            self, lm_served, tmp_path):
        """Acceptance: a chrome-trace export of ONE streamed /generate
        request shows admission, each prefill chunk, and per-token steps
        — all joined by its request_id."""
        im, srv, fe = lm_served
        tracer = trace.enable()
        rid = "trace-req-1"
        cl = HttpClient(fe.url)
        events = list(cl.generate([2, 3, 4, 5, 6], temperature=0.0,
                                  stream=True, request_id=rid))
        tokens = [e["token"] for e in events if "token" in e]
        assert events[-1]["done"] is True
        doc = tracer.chrome_trace()
        path = tmp_path / "decode_trace.json"
        tracer.export_chrome_trace(str(path))
        assert json.loads(path.read_text())["traceEvents"]
        mine = [e for e in doc["traceEvents"]
                if e["args"].get("request_id") == rid]
        names = {}
        for e in mine:
            names.setdefault(e["name"], []).append(e)
        # the whole path, one request id: HTTP ingress -> engine submit
        # -> slot admission -> prefill chunks -> per-token steps ->
        # publish
        assert "serving/http_generate" in names
        assert "serving/enqueue_generate" in names
        assert len(names["decode/admission"]) == 1
        # 5-token prompt at prompt_chunk=4 -> exactly 2 prefill chunks
        chunks = sorted(e["args"]["chunk_start"]
                        for e in names["decode/prefill_chunk"])
        assert chunks == [0, 4]
        # every token after the first (which prefill emits) is one step
        steps = names["decode/token_step"]
        assert len(steps) == len(tokens) - 1
        assert sorted(e["args"]["index"] for e in steps) \
            == list(range(1, len(tokens)))
        (pub,) = names["decode/publish"]
        assert pub["args"]["finish_reason"] in ("eos", "length")
        # events are real chrome-trace complete events with wall windows
        for e in mine:
            assert e["ph"] == "X" and e["dur"] >= 0

    def test_tracing_off_is_free_of_decode_events(self, lm_served):
        im, srv, fe = lm_served
        trace.disable()
        rid = srv.enqueue_generate(np.asarray([5, 6], np.int32))
        srv.query(rid)
        assert trace.get() is None      # nothing installed, no cost paid


class TestFlightDumpDecodeRing:
    def test_dump_carries_engine_event_ring(self, lm_served, tmp_path):
        """Satellite: SIGTERM/excepthook dumps include the decode
        engine's event ring (admissions, expiries, prefill interleave)
        next to the metrics_snapshot line — same dump() path the signal
        handlers call."""
        im, srv, fe = lm_served
        rid = srv.enqueue_generate(np.asarray([7, 8, 9], np.int32))
        srv.query(rid)
        path = flight.global_recorder().dump(
            str(tmp_path / "flight.jsonl"))
        lines = [json.loads(l) for l in open(path)]
        kinds = [l["kind"] for l in lines]
        assert "metrics_snapshot" in kinds
        rings = [l for l in lines if l.get("kind") == "dump_source"
                 and "decode_engine" in str(l.get("source"))]
        assert rings, kinds
        ring = rings[-1]
        event_kinds = {e[0] for e in ring["events"]}
        assert "admit" in event_kinds
        assert "prefill_chunk" in event_kinds
        assert ring["stats"]["requests"] >= 1
        # the metrics_snapshot line still precedes the source lines
        assert kinds.index("metrics_snapshot") \
            < kinds.index("dump_source")


# ---------------------------------------------------------------------------
# cluster-side metric federation
# ---------------------------------------------------------------------------

def test_cluster_leader_merges_host_snapshots(tmp_path):
    """Training-side federation: every host publishes its snapshot onto
    the membership board; the LEADER re-exports them as
    cluster.host.*-labeled series, stragglers included via age_s."""
    from bigdl_tpu.resilience.cluster import (ClusterConfig,
                                              ClusterCoordinator)

    d = str(tmp_path / "ctrl")
    t = [100.0]
    mk = lambda rank, m: ClusterCoordinator(
        ClusterConfig(directory=d, process_index=rank,
                      heartbeat_interval_s=5.0, clock=lambda: t[0]),
        metrics=m)
    m0, m1 = Metrics(), Metrics()
    c0, c1 = mk(0, m0), mk(1, m1)
    m1.gauge("train.step_time_max_s", 0.5)
    m1.inc("train.xla_compiles_total", 3)
    m1.observe("serving.tenant_latency_seconds", 0.02,
               labels={"tenant": "x"})
    c0.sweep()          # leader beats first (so rank 1 never leads)
    c1.sweep()          # rank 1 publishes its snapshot, does not merge
    t[0] = 101.0
    c0.sweep()          # leader merges every host file
    text = render_prometheus(m0)
    _assert_parse_clean(text)
    assert 'cluster_host_train_step_time_max_s{host="1"} 0.5' in text
    assert 'cluster_host_train_xla_compiles_total{host="1"} 3.0' in text
    # labeled peer series keep their labels, plus host=
    assert re.search(
        r'cluster_host_serving_tenant_latency_seconds_p99'
        r'\{tenant="x",host="1"\}', text)
    # staleness, not disappearance: the straggler's snapshot ages
    assert re.search(r'cluster_host_age_s\{host="1"\} 1\.0', text)
    m = re.search(r"cluster_hosts_reporting (\d+)", text)
    assert m and int(m.group(1)) == 2          # self included
    # a non-leader never merges: rank 1's registry carries no host series
    assert "cluster_host_" not in render_prometheus(m1).replace(
        "cluster_host_age_s", "")  # (rank1 published, never merged)


def test_cluster_publish_skips_merged_series(tmp_path):
    """The leader's own merged cluster.host.* gauges must not re-publish
    — federation feedback would grow names without bound."""
    from bigdl_tpu.resilience.cluster import (ClusterConfig,
                                              ClusterCoordinator)

    d = str(tmp_path / "ctrl")
    m0 = Metrics()
    c0 = ClusterCoordinator(
        ClusterConfig(directory=d, process_index=0), metrics=m0)
    m0.gauge("train.mfu", 0.2)
    c0.sweep()
    c0.sweep()          # second sweep republishes after a merge happened
    from bigdl_tpu.utils import storage

    doc = storage.read_json(
        storage.join(d, "metrics", "host-r00000.json"))
    assert "train.mfu" in doc["metrics"]
    assert not any(k.startswith("cluster.host") for k in doc["metrics"])


# ---------------------------------------------------------------------------
# knobs + sentinel family
# ---------------------------------------------------------------------------

def test_engine_config_slo_specs_env(monkeypatch):
    from bigdl_tpu.runtime.engine import EngineConfig

    spec = json.dumps([{"tenant": "default",
                        "objectives": {"predict_p99_s": 0.2}}])
    monkeypatch.setenv("BIGDL_TPU_SLO_SPECS", spec)
    cfg = EngineConfig.from_env()
    assert cfg.slo_specs == spec
    assert load_specs(cfg.slo_specs)[0].objectives[0].threshold_s == 0.2


def test_serving_env_slo_specs(monkeypatch):
    spec = json.dumps([{"tenant": "default",
                        "objectives": {"availability": 0.999}}])
    monkeypatch.setenv("BIGDL_TPU_SLO_SPECS", spec)
    srv = ServingServer(_Model(), ServingConfig(slo_alert_burn=2.0),
                        metrics=Metrics())
    assert srv.slo is not None
    assert srv.slo.specs[0].objectives[0].kind == "availability"
    # the configured alert threshold reaches the env-built evaluator too
    assert srv.slo.alert_burn == 2.0
    srv.stop()


def test_slo_bench_row_and_sentinel_family():
    """The committed SLO_r01.json enters the sentinel history with the
    right directions, and the gate flags a slowed alert."""
    from bigdl_tpu.obs import sentinel

    rows = sentinel.normalize(
        {"slo_alert_latency_s": 0.1, "slo_burn_peak": 37.4}, "x")
    by = {r.family: r for r in rows}
    assert by["slo_alert_latency_s"].direction == sentinel.LOWER
    assert by["slo_burn_peak"].direction == sentinel.HIGHER
    history = sentinel.load_history()
    assert "slo_alert_latency_s" in history, \
        "committed SLO_r*.json artifact missing from the repo root"
    slow = sentinel.Row("slo_alert_latency_s",
                        history["slo_alert_latency_s"][0].value * 2.0,
                        sentinel.LOWER, "fresh")
    v = sentinel.check_row(slow, history)
    assert v is not None and v.regressed


@pytest.mark.slow
def test_slo_bench_runs_end_to_end():
    row = bench(window_s=1.0, warm_s=0.3, timeout_s=5.0)
    assert "error" not in row
    assert row["slo_alert_latency_s"] <= 1.0
    assert row["slo_burn_peak"] >= 1.0
