"""Extended layer catalog tests — shape + numeric checks, torch golden-oracle
where cheap (the reference's Torch-parity-spec pattern, SURVEY.md §5)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn

RNG = jax.random.PRNGKey(0)


def run(layer, *xs, training=False, rng=None):
    v = layer.init(RNG, *xs)
    y, _ = layer.apply(v, *xs, training=training, rng=rng)
    return np.asarray(y) if not isinstance(y, tuple) else y


# ---- conv family ----------------------------------------------------------

def test_conv3d_shape():
    x = jnp.ones((2, 5, 6, 7, 3))
    y = run(nn.Conv3D(3, 4, 3, stride=1, padding=1), x)
    assert y.shape == (2, 5, 6, 7, 4)


def test_conv2d_transpose_parity_with_torch():
    torch = pytest.importorskip("torch")
    x = np.random.RandomState(0).rand(2, 5, 5, 3).astype(np.float32)
    layer = nn.Conv2DTranspose(3, 4, 3, stride=2, padding=1)
    v = layer.init(RNG, jnp.asarray(x))
    w = np.asarray(v["params"]["weight"])  # HWIO
    b = np.asarray(v["params"]["bias"])
    y, _ = layer.apply(v, jnp.asarray(x))

    tconv = torch.nn.ConvTranspose2d(3, 4, 3, stride=2, padding=1)
    with torch.no_grad():
        # torch weight layout: (in, out, kh, kw)
        tconv.weight.copy_(torch.tensor(w).permute(3, 2, 0, 1))
        tconv.bias.copy_(torch.tensor(b))
        ty = tconv(torch.tensor(x).permute(0, 3, 1, 2)).permute(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-4)


def test_depthwise_and_separable():
    x = jnp.ones((2, 8, 8, 4))
    assert run(nn.DepthwiseConv2D(4, 3, padding="SAME"), x).shape == (2, 8, 8, 4)
    assert run(nn.DepthwiseConv2D(4, 3, padding="SAME", depth_multiplier=2),
               x).shape == (2, 8, 8, 8)
    assert run(nn.SeparableConv2D(4, 6, 3, padding="SAME"), x).shape == (2, 8, 8, 6)


def test_locally_connected_matches_dense_per_position():
    x = np.random.RandomState(1).rand(1, 4, 4, 2).astype(np.float32)
    layer = nn.LocallyConnected2D(2, 3, 2, stride=2)
    v = layer.init(RNG, jnp.asarray(x))
    y, _ = layer.apply(v, jnp.asarray(x))
    assert y.shape == (1, 2, 2, 3)
    # manual check at position (0,0): patch (kh,kw,c) flattened @ weight
    w = np.asarray(v["params"]["weight"])  # (OH, OW, KH*KW*C, O)
    b = np.asarray(v["params"]["bias"])
    patch = x[0, 0:2, 0:2, :].reshape(-1)
    want = patch @ w[0, 0] + b[0, 0]
    np.testing.assert_allclose(np.asarray(y)[0, 0, 0], want, atol=1e-5)


# ---- pooling / resize -----------------------------------------------------

def test_pool_1d_3d_global():
    x1 = jnp.arange(12.0).reshape(1, 6, 2)
    assert run(nn.MaxPool1D(2), x1).shape == (1, 3, 2)
    assert run(nn.AvgPool1D(2), x1).shape == (1, 3, 2)
    x3 = jnp.ones((1, 4, 4, 4, 2))
    assert run(nn.MaxPool3D(2), x3).shape == (1, 2, 2, 2, 2)
    assert run(nn.AvgPool3D(2), x3).shape == (1, 2, 2, 2, 2)
    x2 = jnp.ones((2, 5, 5, 3))
    assert run(nn.GlobalMaxPool2D(), x2).shape == (2, 3)
    assert run(nn.GlobalAvgPool1D(), x1).shape == (1, 2)


def test_upsampling_and_crop():
    x = jnp.arange(8.0).reshape(1, 2, 2, 2)
    y = run(nn.UpSampling2D(2), x)
    assert y.shape == (1, 4, 4, 2)
    assert y[0, 0, 0, 0] == y[0, 1, 1, 0] == x[0, 0, 0, 0]
    yb = run(nn.UpSampling2D(2, mode="bilinear"), x)
    assert yb.shape == (1, 4, 4, 2)
    assert run(nn.UpSampling1D(3), jnp.ones((1, 2, 5))).shape == (1, 6, 5)
    assert run(nn.UpSampling3D(2), jnp.ones((1, 2, 2, 2, 1))).shape == (1, 4, 4, 4, 1)
    assert run(nn.Cropping2D(((1, 0), (0, 1))), jnp.ones((1, 5, 5, 2))).shape == (1, 4, 4, 2)
    assert run(nn.Cropping1D((1, 1)), jnp.ones((1, 5, 2))).shape == (1, 3, 2)
    assert run(nn.ZeroPadding1D((1, 2)), jnp.ones((1, 3, 2))).shape == (1, 6, 2)
    assert run(nn.ZeroPadding3D(1), jnp.ones((1, 2, 2, 2, 1))).shape == (1, 4, 4, 4, 1)


def test_padding_negative_pads_front():
    x = jnp.ones((2, 3))
    y = run(nn.Padding(1, -2, value=7.0), x)
    assert y.shape == (2, 5)
    assert float(y[0, 0]) == 7.0 and float(y[0, 2]) == 1.0


# ---- elementwise math / reductions ---------------------------------------

def test_math_layers():
    x = jnp.asarray([[1.0, 4.0]])
    np.testing.assert_allclose(run(nn.Power(2.0, scale=2.0), x), [[4.0, 64.0]])
    np.testing.assert_allclose(run(nn.Square(), x), [[1.0, 16.0]])
    np.testing.assert_allclose(run(nn.Sqrt(), x), [[1.0, 2.0]])
    np.testing.assert_allclose(run(nn.Exp(), jnp.zeros((1, 2))), [[1.0, 1.0]])
    np.testing.assert_allclose(run(nn.Log(), x), np.log([[1.0, 4.0]]), rtol=1e-6)
    np.testing.assert_allclose(run(nn.Abs(), -x), [[1.0, 4.0]])
    np.testing.assert_allclose(run(nn.Negative(), x), [[-1.0, -4.0]])
    np.testing.assert_allclose(run(nn.Clamp(0.0, 2.0), x), [[1.0, 2.0]])
    np.testing.assert_allclose(run(nn.AddConstant(1.0), x), [[2.0, 5.0]])
    np.testing.assert_allclose(run(nn.MulConstant(3.0), x), [[3.0, 12.0]])
    np.testing.assert_allclose(run(nn.Threshold(2.0, -1.0), x), [[-1.0, 4.0]])
    np.testing.assert_allclose(run(nn.ThresholdedReLU(2.0), x), [[0.0, 4.0]])
    sm = run(nn.SoftMin(), x)
    np.testing.assert_allclose(sm.sum(-1), 1.0, rtol=1e-6)
    assert sm[0, 0] > sm[0, 1]


def test_reductions():
    x = jnp.arange(6.0).reshape(2, 3)
    np.testing.assert_allclose(run(nn.Sum(1), x), [3.0, 12.0])
    np.testing.assert_allclose(run(nn.Mean(0), x), [1.5, 2.5, 3.5])
    np.testing.assert_allclose(run(nn.Max(1), x), [2.0, 5.0])
    np.testing.assert_allclose(run(nn.Min(1, keepdims=True), x), [[0.0], [3.0]])


# ---- learnable pointwise --------------------------------------------------

def test_cmul_cadd_scale_grad():
    x = jnp.ones((2, 3))
    layer = nn.Scale((3,))
    v = layer.init(RNG, x)

    def loss(params):
        y, _ = layer.forward(params, {}, x)
        return jnp.sum(y ** 2)

    g = jax.grad(loss)(v["params"])
    assert g["weight"].shape == (3,) and g["bias"].shape == (3,)
    assert run(nn.CMul((3,)), x).shape == (2, 3)
    assert run(nn.CAdd((3,)), x).shape == (2, 3)
    assert run(nn.Mul(), x).shape == (2, 3)
    assert run(nn.Add(), x).shape == (2, 3)


# ---- table ops ------------------------------------------------------------

def test_table_ops():
    a = jnp.asarray([[1.0, 2.0]])
    b = jnp.asarray([[3.0, 4.0]])
    np.testing.assert_allclose(run(nn.CSubTable(), a, b), [[-2.0, -2.0]])
    np.testing.assert_allclose(run(nn.CDivTable(), a, b), [[1 / 3, 0.5]])
    np.testing.assert_allclose(run(nn.CMaxTable(), a, b), [[3.0, 4.0]])
    np.testing.assert_allclose(run(nn.CMinTable(), a, b), [[1.0, 2.0]])
    np.testing.assert_allclose(run(nn.CAveTable(), a, b), [[2.0, 3.0]])
    np.testing.assert_allclose(run(nn.DotProduct(), a, b), [11.0])
    cos = run(nn.CosineDistance(), a, a)
    np.testing.assert_allclose(cos, [1.0], rtol=1e-6)
    np.testing.assert_allclose(run(nn.PairwiseDistance(), a, b),
                               [np.sqrt(8.0)], rtol=1e-6)
    m = jnp.ones((1, 2, 3))
    n = jnp.ones((1, 3, 4))
    assert run(nn.MM(), m, n).shape == (1, 2, 4)
    assert run(nn.MM(trans_a=True), jnp.ones((1, 3, 2)), n).shape == (1, 2, 4)
    assert run(nn.MV(), m, jnp.ones((1, 3))).shape == (1, 2)
    out = run(nn.NarrowTable(1, 2), a, b, a + 1)
    assert isinstance(out, tuple) and len(out) == 2
    flat = run(nn.FlattenTable(), (a, (b, a)))
    assert isinstance(flat, tuple) and len(flat) == 3


# ---- indexing / masking ---------------------------------------------------

def test_select_narrow_masking_repeat_permute():
    x = jnp.arange(24.0).reshape(2, 3, 4)
    assert run(nn.Select(1, 0), x).shape == (2, 4)
    assert run(nn.Narrow(2, 1, 2), x).shape == (2, 3, 2)
    seq = jnp.asarray([[[1.0, 2.0], [0.0, 0.0], [3.0, 0.0]]])
    masked = run(nn.Masking(0.0), seq)
    np.testing.assert_allclose(masked[0, 1], [0.0, 0.0])
    np.testing.assert_allclose(masked[0, 2], [3.0, 0.0])
    assert run(nn.RepeatVector(4), jnp.ones((2, 5))).shape == (2, 4, 5)
    assert run(nn.Permute((1, 0)), x).shape == (2, 4, 3)


# ---- normalize / LRN / noise ---------------------------------------------

def test_normalize_and_lrn_torch_parity():
    torch = pytest.importorskip("torch")
    x = np.random.RandomState(0).rand(2, 4, 4, 6).astype(np.float32)
    y = run(nn.LRN(size=5, alpha=1e-4, beta=0.75, k=1.0), jnp.asarray(x))
    ty = torch.nn.LocalResponseNorm(5, alpha=1e-4, beta=0.75, k=1.0)(
        torch.tensor(x).permute(0, 3, 1, 2)).permute(0, 2, 3, 1)
    np.testing.assert_allclose(y, ty.numpy(), atol=1e-5)

    v = np.random.RandomState(1).rand(3, 5).astype(np.float32)
    yn = run(nn.Normalize(2.0), jnp.asarray(v))
    np.testing.assert_allclose(np.linalg.norm(yn, axis=-1), 1.0, rtol=1e-5)


def test_dropout_noise_layers():
    x = jnp.ones((4, 8, 8, 3))
    k = jax.random.PRNGKey(1)
    y = run(nn.SpatialDropout2D(0.5), x, training=True, rng=k)
    # channel-wise: each (n,c) slice is all-zero or all-scaled
    per_chan = np.asarray(y).reshape(4, -1, 3)
    for i in range(4):
        for c in range(3):
            vals = np.unique(per_chan[i, :, c])
            assert len(vals) == 1
    assert run(nn.SpatialDropout1D(0.5), jnp.ones((2, 5, 3)),
               training=True, rng=k).shape == (2, 5, 3)
    gn = run(nn.GaussianNoise(0.1), jnp.zeros((2, 3)), training=True, rng=k)
    assert np.abs(gn).sum() > 0
    assert run(nn.GaussianNoise(0.1), jnp.zeros((2, 3))).sum() == 0
    gd = run(nn.GaussianDropout(0.3), jnp.ones((2, 3)), training=True, rng=k)
    assert gd.shape == (2, 3)


# ---- parametrized misc ----------------------------------------------------

def test_highway_starts_near_identity():
    x = jnp.asarray(np.random.RandomState(0).rand(2, 6).astype(np.float32))
    y = run(nn.Highway(), x)
    assert y.shape == (2, 6)
    # gate bias -2 → mostly carry (identity-ish)
    assert np.abs(np.asarray(y) - np.asarray(x)).mean() < 0.3


def test_maxout_bilinear_cosine_euclidean_srelu():
    x = jnp.asarray(np.random.RandomState(0).rand(3, 5).astype(np.float32))
    assert run(nn.Maxout(5, 4, pool_size=3), x).shape == (3, 4)
    a = jnp.ones((2, 3))
    b = jnp.ones((2, 4))
    assert run(nn.Bilinear(3, 4, 6), a, b).shape == (2, 6)
    y = run(nn.Cosine(5, 7), x)
    assert y.shape == (3, 7) and np.all(np.abs(np.asarray(y)) <= 1.0 + 1e-5)
    d = run(nn.Euclidean(5, 7), x)
    assert d.shape == (3, 7) and np.all(np.asarray(d) >= 0)
    assert run(nn.SReLU(), x).shape == (3, 5)


def test_extra_layers_in_sequential_jit():
    """Everything composes under jit (XLA-traceable, static shapes)."""
    model = nn.Sequential([
        nn.Conv2DTranspose(3, 4, 3, stride=2, padding=1),
        nn.LRN(3),
        nn.UpSampling2D(2),
        nn.Cropping2D(1),
        nn.GlobalMaxPool2D(),
        nn.Highway(),
        nn.Maxout(4, 2),
    ])
    x = jnp.ones((2, 8, 8, 3))
    v = model.init(RNG, x)

    @jax.jit
    def f(params, x):
        y, _ = model.forward(params, {}, x)
        return y

    assert f(v["params"], x).shape == (2, 2)


def test_spatial_dropout_p1_is_zero_not_nan():
    import jax
    import jax.numpy as jnp
    from bigdl_tpu.nn.layers_extra import SpatialDropout2D

    layer = SpatialDropout2D(p=1.0)
    x = jnp.ones((2, 4, 4, 3))
    y, _ = layer.forward({}, {}, x, training=True,
                         rng=jax.random.PRNGKey(0))
    assert np.all(np.asarray(y) == 0.0)

    # NaN trap under jit-of-grad: gradient must be finite (zero), not NaN
    def loss(x):
        out, _ = layer.forward({}, {}, x, training=True,
                               rng=jax.random.PRNGKey(0))
        return jnp.sum(out ** 2)

    g = jax.jit(jax.grad(loss))(x)
    assert np.all(np.isfinite(np.asarray(g)))
