"""Multislice (DCN-aware) data parallelism.

Reference analog (unverified — mount empty): the reference scales its
AllReduceParameter over Spark's BlockManager across racks; the TPU-native
form is a hierarchical mesh — an inner "data" axis over ICI and an outer
"dcn_data" axis across slice boundaries (BASELINE.md 8->256-chip north
star).  Gradients reduce-scatter within a slice first, only the 1/ndev
slice crosses DCN, and no parameter bytes cross slices at all.
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from bigdl_tpu.runtime.engine import Engine, EngineConfig, init_engine
from bigdl_tpu.runtime.mesh import (AXIS_DATA, AXIS_DCN, MeshSpec,
                                    build_mesh, detect_slice_count)


def _reset_engine(**mesh_axes):
    Engine.reset()
    return init_engine(EngineConfig(mesh=MeshSpec(**mesh_axes)))


class TestMeshSpec:
    def test_dcn_axis_resolution(self):
        sizes = MeshSpec(dcn_data=2).resolve(8)
        assert sizes[AXIS_DCN] == 2 and sizes[AXIS_DATA] == 4

    def test_auto_detect_defaults_to_one(self):
        # CPU devices expose no slice_index -> single slice
        import jax
        assert detect_slice_count(jax.devices()) == 1
        sizes = MeshSpec().resolve(8, detect_slice_count(jax.devices()))
        assert sizes[AXIS_DCN] == 1 and sizes[AXIS_DATA] == 8

    def test_auto_detect_uses_slice_count(self):
        class FakeDev:
            def __init__(self, s):
                self.slice_index = s

        devs = [FakeDev(i // 4) for i in range(8)]
        assert detect_slice_count(devs) == 2
        sizes = MeshSpec().resolve(8, 2)
        assert sizes[AXIS_DCN] == 2 and sizes[AXIS_DATA] == 4

    def test_indivisible_rejected(self):
        with pytest.raises(ValueError, match="not divisible"):
            MeshSpec(dcn_data=3).resolve(8)

    def test_mesh_axis_order_dcn_outermost(self):
        mesh = build_mesh(MeshSpec(dcn_data=2))
        assert mesh.axis_names[0] == AXIS_DCN
        assert dict(mesh.shape)[AXIS_DCN] == 2
        assert dict(mesh.shape)[AXIS_DATA] == 4
        # the outermost axis groups contiguous device ids (slice/process
        # boundaries in a real job)
        import jax
        arr = np.asarray(mesh.devices).reshape(2, -1)
        ids = [[d.id for d in row] for row in arr]
        assert ids[0] == sorted(ids[0]) and max(ids[0]) < min(ids[1])


class TestMultisliceTraining:
    def test_hierarchical_matches_flat_dp(self):
        """dcn_data=2 x data=4 must produce the same training trajectory as
        the flat 8-device run (hierarchical allreduce == flat allreduce) and
        the 1-device run."""
        from bigdl_tpu import nn, optim
        from bigdl_tpu.data.dataset import ArrayDataSet
        from bigdl_tpu.nn.module import Sequential

        rs = np.random.RandomState(0)
        x = rs.randn(512, 10).astype(np.float32)
        y = (x[:, :5].sum(1) > x[:, 5:].sum(1)).astype(np.int32)

        losses = {}
        for label, axes in (("flat", dict(data=-1)),
                            ("multislice", dict(dcn_data=2)),
                            ("single", dict(data=1, dcn_data=1))):
            _reset_engine(**axes)
            model = Sequential([nn.Linear(10, 16), nn.ReLU(),
                                nn.Linear(16, 2)])
            opt = optim.Optimizer(model, ArrayDataSet(x, y),
                                  nn.CrossEntropyCriterion(),
                                  batch_size=64, seed=7)
            opt.set_optim_method(optim.SGD(learning_rate=0.2))
            opt.set_end_when(optim.Trigger.max_iteration(16))
            opt.log_every = 100
            trained = opt.optimize()
            res = trained.evaluate(
                ArrayDataSet(x, y),
                [optim.Loss(nn.CrossEntropyCriterion())], batch_size=64)
            losses[label] = res[0].result
        Engine.reset()
        assert losses["multislice"] == pytest.approx(losses["flat"],
                                                     rel=2e-3), losses
        assert losses["multislice"] == pytest.approx(losses["single"],
                                                     rel=2e-3), losses

    def test_dcn_bytes_accounting(self):
        from bigdl_tpu import nn
        from bigdl_tpu.nn.criterion import MSECriterion
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.optim.train_step import ShardedParameterStep

        _reset_engine(dcn_data=2)
        import jax

        model = nn.Linear(8, 8)
        init = model.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.float32))
        eng = ShardedParameterStep(model, MSECriterion(), SGD(0.1),
                                   Engine.get().mesh, init)
        assert eng.dcn == 2 and eng.ndev == 4
        assert eng.n_data_replicas == 8
        # DCN carries ~2x the 1/ndev gradient slice, not the full vector
        assert eng.dcn_bytes_per_step == 2 * eng.shard_size * 4
        assert eng.dcn_bytes_per_step < eng.collective_bytes_per_step
        Engine.reset()


# ---------------------------------------------------------------------------
# True 2-process multislice: process boundary plays the DCN boundary, four
# virtual devices per process play one slice's ICI mesh.

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


WORKER = textwrap.dedent("""
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    from bigdl_tpu.data.dataset import ArrayDataSet
    from bigdl_tpu import nn
    from bigdl_tpu.nn.criterion import MSECriterion
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.runtime.engine import Engine, init_engine
    from bigdl_tpu.runtime.mesh import AXIS_DCN, AXIS_DATA, MeshSpec

    init_engine(dcn_data=2)
    assert jax.process_count() == 2, jax.process_count()
    mesh = Engine.get().mesh
    shape = dict(mesh.shape)
    assert shape[AXIS_DCN] == 2 and shape[AXIS_DATA] == 4, shape

    rs = np.random.RandomState(0)
    w_true = np.asarray([[2.0], [-1.0]], np.float32)
    x = rs.rand(256, 2).astype(np.float32)
    y = x @ w_true
    model = nn.Linear(2, 1)
    opt = (Optimizer(model, ArrayDataSet(x, y), MSECriterion(),
                     batch_size=64)
           .set_optim_method(SGD(learning_rate=0.4))
           .set_end_when(Trigger.max_epoch(25)))
    trained = opt.optimize()
    w = np.asarray(trained.variables["params"]["weight"])
    err = float(np.abs(w - w_true).max())
    assert err < 0.1, err
    print(f"RANK{jax.process_index()}_ERR={err:.6f}")
""")


@pytest.mark.slow
def test_two_process_multislice_training(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    procs = []
    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pythonpath = os.pathsep.join(
        p for p in [repo_root, os.environ.get("PYTHONPATH")] if p)
    try:
        for r in range(2):
            env = dict(os.environ,
                       BIGDL_TPU_COORDINATOR=f"127.0.0.1:{port}",
                       BIGDL_TPU_NUM_PROCESSES="2",
                       BIGDL_TPU_PROCESS_ID=str(r),
                       JAX_PLATFORMS="cpu",
                       XLA_FLAGS="--xla_force_host_platform_device_count=4",
                       PYTHONPATH=pythonpath)
            procs.append(subprocess.Popen(
                [sys.executable, str(script)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
        outs = []
        for p in procs:
            try:
                outs.append(p.communicate(timeout=420)[0])
            except subprocess.TimeoutExpired:
                p.kill()
                outs.append(p.communicate()[0])
        codes = [p.returncode for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert codes == [0, 0], f"exit {codes}\n--- rank0:\n{outs[0]}\n--- rank1:\n{outs[1]}"
    errs = sorted(line for o in outs for line in o.splitlines()
                  if "_ERR=" in line)
    assert len(errs) == 2
    assert errs[0].split("=")[1] == errs[1].split("=")[1], errs


def test_gspmd_batch_shards_over_dcn_axis():
    """GSPMD on a multislice mesh must shard the batch over BOTH data axes
    — replicating over dcn_data would waste a whole slice's compute."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.keras.engine import Input as KInput, Model as KModel
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion
    from bigdl_tpu.nn.layers import Linear
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.parallel.gspmd import GSPMDTrainStep

    mesh = build_mesh(MeshSpec(dcn_data=2, model=2))
    assert dict(mesh.shape) == {AXIS_DCN: 2, "pipe": 1, AXIS_DATA: 2,
                                "expert": 1, "seq": 1, "model": 2}
    gi = KInput((6,))
    go = Linear(6, 2)(gi)
    gmodel = KModel(gi, go)
    rs = np.random.RandomState(0)
    gx = rs.randn(8, 6).astype(np.float32)
    gy = rs.randint(0, 2, 8).astype(np.int32)
    gvars = gmodel.init(jax.random.PRNGKey(0), jnp.asarray(gx[:1]))
    gstep = GSPMDTrainStep(gmodel, CrossEntropyCriterion(), SGD(1e-2),
                           mesh, gvars)
    spec = gstep.batch_sh.spec
    assert spec[0] == (AXIS_DCN, AXIS_DATA), spec
    loss = float(np.asarray(gstep.train_step(0, jax.random.PRNGKey(1),
                                             gx, gy)))
    assert np.isfinite(loss)
