"""Estimator.from_keras — STOCK tf.keras models trained on the mesh.

Reference call stack being replaced (SURVEY.md §3.3 / §4.3): ``orca/learn/
tf2/estimator.py`` ``Estimator.from_keras(model_creator)`` running workers
under ``MultiWorkerMirroredStrategy``.  Here the keras model converts once
to the native keras-engine Model (weights carried over), trains with the
ZeRO-1 sharded step on the 8-virtual-device mesh, and trained weights
export back into the original keras model via ``export_to_keras``."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from tensorflow import keras as tk  # Keras 3 in this image

from bigdl_tpu.estimator import Estimator, init_context
from bigdl_tpu.optim.validation import Top1Accuracy
from bigdl_tpu.utils.keras_convert import (UnsupportedKerasLayer,
                                           convert_keras_loss,
                                           convert_keras_optimizer,
                                           export_tf_keras_weights,
                                           from_tf_keras)

RS = np.random.RandomState(0)


def _assert_forward_parity(kmodel, x, atol=2e-4):
    model, variables = from_tf_keras(kmodel)
    ours, _ = model.apply(variables, *(x if isinstance(x, tuple) else (x,)),
                          training=False)
    theirs = kmodel.predict(
        list(x) if isinstance(x, tuple) else x, verbose=0)
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=atol)
    return model, variables


def test_sequential_cnn_forward_parity():
    kmodel = tk.Sequential([
        tk.layers.Input((8, 8, 3)),
        tk.layers.Conv2D(8, 3, padding="same", activation="relu"),
        tk.layers.BatchNormalization(),
        tk.layers.MaxPooling2D(2),
        tk.layers.Conv2D(8, 3, padding="valid"),
        tk.layers.Activation("relu"),
        tk.layers.GlobalAveragePooling2D(),
        tk.layers.Dense(4, activation="softmax"),
    ])
    x = RS.rand(4, 8, 8, 3).astype(np.float32)
    _assert_forward_parity(kmodel, x)


def test_functional_residual_forward_parity():
    inp = tk.Input((8, 8, 4))
    h = tk.layers.Conv2D(4, 3, padding="same", activation="relu")(inp)
    res = tk.layers.Add()([inp, h])                       # residual
    cat = tk.layers.Concatenate()([res, h])
    h = tk.layers.AveragePooling2D(2)(cat)
    h = tk.layers.Flatten()(h)
    out = tk.layers.Dense(3)(h)
    kmodel = tk.Model(inp, out)
    x = RS.rand(3, 8, 8, 4).astype(np.float32)
    _assert_forward_parity(kmodel, x)


def test_lstm_and_gru_forward_parity():
    for rnn, kwargs in [(tk.layers.LSTM, {}),
                        (tk.layers.GRU, {}),  # reset_after=True default
                        (tk.layers.LSTM, {"return_sequences": True})]:
        kmodel = tk.Sequential([
            tk.layers.Input((6, 5)),
            rnn(7, **kwargs),
            tk.layers.Dense(2),
        ])
        x = RS.rand(3, 6, 5).astype(np.float32)
        _assert_forward_parity(kmodel, x, atol=5e-4)


def test_bidirectional_lstm_forward_parity():
    kmodel = tk.Sequential([
        tk.layers.Input((5, 4)),
        tk.layers.Bidirectional(tk.layers.LSTM(6, return_sequences=True)),
        tk.layers.Bidirectional(tk.layers.GRU(3)),
        tk.layers.Dense(2),
    ])
    x = RS.rand(3, 5, 4).astype(np.float32)
    _assert_forward_parity(kmodel, x, atol=5e-4)


def test_embedding_lstm_forward_parity():
    kmodel = tk.Sequential([
        tk.layers.Input((7,), dtype="int32"),
        tk.layers.Embedding(30, 8),
        tk.layers.LSTM(6),
        tk.layers.Dense(2, activation="softmax"),
    ])
    ids = RS.randint(0, 30, (4, 7)).astype(np.int32)
    _assert_forward_parity(kmodel, ids, atol=5e-4)


def test_estimator_finetunes_stock_keras_cnn():
    """The VERDICT r2 'done' condition: fine-tune a stock tf.keras CNN
    end-to-end on the 8-device mesh, weights exported back."""
    init_context("local")
    n, classes = 256, 3
    x = RS.rand(n, 8, 8, 3).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) * 9).astype(np.int32) % classes

    def creator(cfg):
        tk.utils.set_random_seed(7)   # keras init is global-RNG seeded
        m = tk.Sequential([
            tk.layers.Input((8, 8, 3)),
            tk.layers.Conv2D(8, 3, padding="same", activation="relu"),
            tk.layers.MaxPooling2D(2),
            tk.layers.Flatten(),
            tk.layers.Dense(16, activation="relu"),
            tk.layers.Dense(classes),
        ])
        m.compile(optimizer=tk.optimizers.Adam(5e-3),
                  loss=tk.losses.SparseCategoricalCrossentropy(
                      from_logits=True))
        return m

    est = Estimator.from_keras(creator)
    before = est.evaluate((x, y), [Top1Accuracy()])["Top1Accuracy"]
    est.fit((x, y), epochs=15, batch_size=64)
    after = est.evaluate((x, y), [Top1Accuracy()])["Top1Accuracy"]
    assert after > max(before, 0.55), (before, after)

    # trained weights round-trip into the ORIGINAL keras model and agree
    km = est.export_to_keras()
    ours = est.predict(x[:8])
    theirs = km.predict(x[:8], verbose=0)
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-3)


def test_estimator_finetunes_stock_keras_lstm():
    init_context("local")
    n = 192
    x = RS.rand(n, 6, 4).astype(np.float32)
    y = (x[:, :, 0].sum(1) > x[:, :, 1].sum(1)).astype(np.int32)

    def creator(cfg):
        tk.utils.set_random_seed(7)   # keras init is global-RNG seeded
        m = tk.Sequential([
            tk.layers.Input((6, 4)),
            tk.layers.LSTM(8),
            tk.layers.Dense(2, activation="softmax"),
        ])
        m.compile(optimizer=tk.optimizers.RMSprop(5e-3),
                  loss="sparse_categorical_crossentropy")
        return m

    est = Estimator.from_keras(creator)
    stats = est.fit((x, y), epochs=10, batch_size=64)
    assert stats["num_samples"] == n
    acc = est.evaluate((x, y), [Top1Accuracy()])["Top1Accuracy"]
    assert acc > 0.6, acc
    km = est.export_to_keras()   # LSTM + GRU-free round trip
    np.testing.assert_allclose(np.asarray(est.predict(x[:6])),
                               km.predict(x[:6], verbose=0), atol=2e-3)


def test_optimizer_and_loss_mapping():
    from bigdl_tpu.optim.optim_method import SGD, Adam, RMSprop
    from bigdl_tpu.nn.criterion import (BCECriterion, CrossEntropyCriterion,
                                        MSECriterion)

    o = convert_keras_optimizer(tk.optimizers.SGD(0.05, momentum=0.9,
                                                  nesterov=True))
    assert isinstance(o, SGD) and o.lr == pytest.approx(0.05) and o.nesterov
    assert isinstance(convert_keras_optimizer(tk.optimizers.Adam(1e-3)), Adam)
    assert isinstance(convert_keras_optimizer(tk.optimizers.RMSprop(1e-3)),
                      RMSprop)
    assert isinstance(convert_keras_loss(
        tk.losses.SparseCategoricalCrossentropy(from_logits=True)),
        CrossEntropyCriterion)
    assert isinstance(convert_keras_loss("mse"), MSECriterion)
    assert isinstance(convert_keras_loss(tk.losses.BinaryCrossentropy()),
                      BCECriterion)
    # from_logits=False maps to NLL-over-probabilities, same value as keras
    probs = np.asarray([[0.7, 0.3], [0.2, 0.8]], np.float32)
    target = np.asarray([0, 1], np.int32)
    ours = float(convert_keras_loss(
        tk.losses.SparseCategoricalCrossentropy())(probs, target))
    theirs = float(tk.losses.SparseCategoricalCrossentropy()(target, probs))
    assert ours == pytest.approx(theirs, rel=1e-5)


def test_unsupported_layers_raise_cleanly():
    km = tk.Sequential([tk.layers.Input((4, 3)),
                        tk.layers.Masking(),          # mask semantics
                        tk.layers.LSTM(4)])
    with pytest.raises(UnsupportedKerasLayer):
        from_tf_keras(km)

    km2 = tk.Sequential([tk.layers.Input((6, 5)),
                         tk.layers.GRU(4, reset_after=False)])
    with pytest.raises(UnsupportedKerasLayer):
        from_tf_keras(km2)

    # shared layer (used twice) is not representable
    inp = tk.Input((4,))
    d = tk.layers.Dense(4)
    out = tk.layers.Add()([d(inp), d(inp)])
    with pytest.raises(UnsupportedKerasLayer):
        from_tf_keras(tk.Model(inp, out))


def test_from_tf_function_frozen_graph_import():
    """Live tf.function -> frozen GraphDef -> native model (the TFNet-style
    inference path through utils/tfio.load_tf_graph)."""
    from bigdl_tpu.utils.tfio import from_tf_function

    kmodel = tk.Sequential([
        tk.layers.Input((10,)),
        tk.layers.Dense(8, activation="relu"),
        tk.layers.Dense(3, activation="softmax"),
    ])
    model, variables = from_tf_function(
        lambda x: kmodel(x), [tf.TensorSpec((1, 10), tf.float32)])
    x = RS.rand(5, 10).astype(np.float32)
    ours, _ = model.apply(variables, x, training=False)
    theirs = kmodel.predict(x, verbose=0)
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-5)


def test_relu_cap_plus_slope_and_dynamic_dims_raise():
    km = tk.Sequential([tk.layers.Input((4,)),
                        tk.layers.ReLU(max_value=6.0, negative_slope=0.1)])
    with pytest.raises(UnsupportedKerasLayer):
        from_tf_keras(km)
    km2 = tk.Sequential([tk.layers.Input((None, 5)), tk.layers.LSTM(4)])
    with pytest.raises(UnsupportedKerasLayer):
        from_tf_keras(km2)


def test_multi_input_functional_model():
    """Two-input functional keras model: both inputs map to engine inputs,
    merge layers take multiple parents, predict via the tuple pack."""
    a = tk.Input((6,))
    b = tk.Input((6,))
    ha = tk.layers.Dense(8, activation="relu")(a)
    hb = tk.layers.Dense(8, activation="relu")(b)
    merged = tk.layers.Concatenate()([ha, hb])
    out = tk.layers.Dense(3)(merged)
    kmodel = tk.Model([a, b], out)

    xa = RS.rand(4, 6).astype(np.float32)
    xb = RS.rand(4, 6).astype(np.float32)
    model, variables = from_tf_keras(kmodel)
    ours, _ = model.apply(variables, xa, xb, training=False)
    theirs = kmodel.predict([xa, xb], verbose=0)
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=1e-5)

    # weights still export back per layer
    export_tf_keras_weights(model, variables, kmodel)
    np.testing.assert_allclose(kmodel.predict([xa, xb], verbose=0), theirs,
                               atol=1e-6)


def test_separable_transpose_timedistributed_parity():
    """SeparableConv2D (Xception-style), Conv2DTranspose (decoder /
    segmentation upsampling), and TimeDistributed(Dense) convert with
    forward parity and per-layer weight export."""
    km = tk.Sequential([
        tk.layers.Input((8, 8, 4)),
        tk.layers.SeparableConv2D(6, 3, padding="same", activation="relu",
                                  depth_multiplier=2),
        tk.layers.Conv2DTranspose(3, 3, strides=2, padding="same"),
    ])
    x = RS.rand(2, 8, 8, 4).astype(np.float32)
    model, variables = _assert_forward_parity(km, x, atol=5e-4)
    export_tf_keras_weights(model, variables, km)   # no raise, same values
    np.testing.assert_allclose(km.predict(x, verbose=0),
                               np.asarray(model.apply(variables, x)[0]),
                               atol=5e-4)

    km2 = tk.Sequential([
        tk.layers.Input((5, 6)),
        tk.layers.TimeDistributed(tk.layers.Dense(4, activation="tanh")),
        tk.layers.GlobalAveragePooling1D(),
        tk.layers.Dense(2),
    ])
    x2 = RS.rand(3, 5, 6).astype(np.float32)
    _assert_forward_parity(km2, x2, atol=1e-5)


def test_quantized_inference_on_converted_keras_model():
    """Interop composes with the quantization path: a converted stock
    keras model runs through nano.InferenceOptimizer int8 with small
    accuracy drift vs fp32."""
    from bigdl_tpu.nano.inference import InferenceOptimizer

    tk.utils.set_random_seed(1)
    km = tk.Sequential([
        tk.layers.Input((10,)),
        tk.layers.Dense(32, activation="relu"),
        tk.layers.Dense(16, activation="relu"),
        tk.layers.Dense(4),
    ])
    model, variables = from_tf_keras(km)
    x = RS.rand(64, 10).astype(np.float32)
    fp32 = InferenceOptimizer.trace(model, variables, x)
    int8 = InferenceOptimizer.quantize(model, variables, sample=x,
                                       precision="int8")
    y32 = np.asarray(fp32(x))
    y8 = np.asarray(int8(x))
    assert y32.shape == y8.shape == (64, 4)
    # int8 tracks fp32 closely on this scale of model
    rel = np.abs(y8 - y32).mean() / (np.abs(y32).mean() + 1e-8)
    assert rel < 0.1, rel
    # and fp32 path matches keras itself
    np.testing.assert_allclose(y32, km.predict(x, verbose=0), atol=2e-4)


def test_converted_model_serializer_roundtrip(tmp_path):
    """Converted keras models save/load through the durable model format
    (the ModuleSerializer analog) — predictions identical after reload."""
    from bigdl_tpu.utils.serializer import load_model, save_model

    tk.utils.set_random_seed(2)
    km = tk.Sequential([
        tk.layers.Input((6, 5)),
        tk.layers.GRU(7),
        tk.layers.Dense(3, activation="softmax"),
    ])
    model, variables = from_tf_keras(km)
    x = RS.rand(4, 6, 5).astype(np.float32)
    y0, _ = model.apply(variables, x)
    p = str(tmp_path / "m")
    save_model(p, model, variables)
    v2 = load_model(p)
    y1, _ = model.apply(v2, x)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1))


def test_keras_mha_self_and_cross_attention_parity():
    """keras-3 MultiHeadAttention (einsum per-head kernels) converts to
    the native fused-projection MHA — self- and cross-attention, with
    weight export back."""
    # self-attention transformer-ish block
    inp = tk.Input((5, 8))
    att = tk.layers.MultiHeadAttention(num_heads=2, key_dim=4)
    h = att(inp, inp)
    h = tk.layers.Add()([inp, h])
    out = tk.layers.LayerNormalization()(h)
    km = tk.Model(inp, out)
    x = RS.rand(3, 5, 8).astype(np.float32)
    model, variables = _assert_forward_parity(km, x, atol=5e-4)
    export_tf_keras_weights(model, variables, km)
    np.testing.assert_allclose(km.predict(x, verbose=0),
                               np.asarray(model.apply(variables, x)[0]),
                               atol=5e-4)

    # cross attention: query sequence attends over a different memory
    q_in = tk.Input((4, 8))
    m_in = tk.Input((6, 8))
    y = tk.layers.MultiHeadAttention(num_heads=2, key_dim=4)(q_in, m_in)
    km2 = tk.Model([q_in, m_in], y)
    qx = RS.rand(2, 4, 8).astype(np.float32)
    mx = RS.rand(2, 6, 8).astype(np.float32)
    model2, v2 = from_tf_keras(km2)
    ours, _ = model2.apply(v2, qx, mx, training=False)
    theirs = km2.predict([qx, mx], verbose=0)
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=5e-4)


def test_categorical_crossentropy_from_logits_mapping():
    logits = np.asarray([[2.0, -1.0, 0.5], [0.1, 0.2, 3.0]], np.float32)
    onehot = np.asarray([[1, 0, 0], [0, 0, 1]], np.float32)
    ours = float(convert_keras_loss(
        tk.losses.CategoricalCrossentropy(from_logits=True))(logits, onehot))
    theirs = float(tk.losses.CategoricalCrossentropy(from_logits=True)(
        onehot, logits))
    assert ours == pytest.approx(theirs, rel=1e-5)


def test_convlstm2d_forward_parity():
    """keras ConvLSTM2D converts onto the native fused-[x;h] ConvLSTM."""
    for ret_seq in (False, True):
        km = tk.Sequential([
            tk.layers.Input((4, 6, 6, 3)),
            tk.layers.ConvLSTM2D(5, 3, padding="same",
                                 return_sequences=ret_seq),
        ])
        x = RS.rand(2, 4, 6, 6, 3).astype(np.float32)
        model, variables = from_tf_keras(km)
        ours, _ = model.apply(variables, x, training=False)
        theirs = km.predict(x, verbose=0)
        np.testing.assert_allclose(np.asarray(ours), theirs, atol=1e-4,
                                   err_msg=f"return_sequences={ret_seq}")
        export_tf_keras_weights(model, variables, km)
        np.testing.assert_allclose(km.predict(x, verbose=0), theirs,
                                   atol=1e-6)


def test_simplernn_forward_parity():
    km = tk.Sequential([
        tk.layers.Input((6, 4)),
        tk.layers.SimpleRNN(5, return_sequences=True),
        tk.layers.SimpleRNN(3),
        tk.layers.Dense(2),
    ])
    x = RS.rand(3, 6, 4).astype(np.float32)
    _assert_forward_parity(km, x, atol=5e-4)


def test_mask_zero_embedding_rejected():
    """mask_zero carries an implicit mask the converted graph cannot honor
    — silent numerics divergence is refused."""
    km = tk.Sequential([tk.layers.Input((5,), dtype="int32"),
                        tk.layers.Embedding(10, 4, mask_zero=True),
                        tk.layers.LSTM(3)])
    with pytest.raises(UnsupportedKerasLayer, match="mask_zero"):
        from_tf_keras(km)


def test_bidirectional_simplernn_parity():
    km = tk.Sequential([
        tk.layers.Input((5, 4)),
        tk.layers.Bidirectional(tk.layers.SimpleRNN(3)),
        tk.layers.Dense(2),
    ])
    x = RS.rand(2, 5, 4).astype(np.float32)
    _assert_forward_parity(km, x, atol=5e-4)


def test_shape_op_layers_parity():
    """Cropping/padding/upsampling/repeat keras layers convert (inference
    parity; noise layers are train-time-only identities here)."""
    km = tk.Sequential([
        tk.layers.Input((8, 8, 3)),
        tk.layers.Cropping2D(((1, 1), (2, 1))),
        tk.layers.UpSampling2D(2),
        tk.layers.GaussianNoise(0.5),      # inference: identity
    ])
    x = RS.rand(2, 8, 8, 3).astype(np.float32)
    _assert_forward_parity(km, x, atol=1e-6)

    km2 = tk.Sequential([
        tk.layers.Input((10, 4)),
        tk.layers.Cropping1D((2, 1)),
        tk.layers.ZeroPadding1D((1, 2)),
        tk.layers.UpSampling1D(2),
    ])
    x2 = RS.rand(2, 10, 4).astype(np.float32)
    _assert_forward_parity(km2, x2, atol=1e-6)

    km3 = tk.Sequential([
        tk.layers.Input((6,)),
        tk.layers.RepeatVector(3),
        tk.layers.SimpleRNN(4),
    ])
    x3 = RS.rand(2, 6).astype(np.float32)
    _assert_forward_parity(km3, x3, atol=5e-4)
