"""Optim-method + trigger + schedule specs (golden vs torch.optim where
applicable), mirroring the reference's optim test strategy."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import optim


def quad_problem():
    """Minimize ||p - t||^2 over a small pytree."""
    target = {"a": jnp.array([1.0, -2.0, 3.0]), "b": jnp.array([[0.5, -0.5]])}
    params = jax.tree_util.tree_map(jnp.zeros_like, target)

    def grads(p):
        return jax.tree_util.tree_map(lambda x, t: 2 * (x - t), p, target)

    return params, target, grads


@pytest.mark.parametrize("method", [
    optim.SGD(learning_rate=0.1),
    optim.SGD(learning_rate=0.1, momentum=0.9),
    optim.SGD(learning_rate=0.1, momentum=0.9, nesterov=True),
    optim.Adam(learning_rate=0.1),
    optim.AdamWeightDecay(learning_rate=0.1, weight_decay=0.0),
    optim.Adagrad(learning_rate=0.5),
    optim.RMSprop(learning_rate=0.05),
    optim.Ftrl(learning_rate=0.5),
    optim.Adadelta(learning_rate=1.0, decay_rate=0.9, epsilon=1e-2),
    optim.Adamax(learning_rate=0.1),
])
def test_methods_converge_on_quadratic(method):
    params, target, grads = quad_problem()
    state = method.init_state(params)
    for step in range(300):
        params, state = method.update(step, grads(params), params, state)
    err = jax.tree_util.tree_map(
        lambda p, t: float(jnp.max(jnp.abs(p - t))), params, target)
    assert max(jax.tree_util.tree_leaves(err)) < 0.05, err


def test_lars_descends():
    # LARS keeps ||update|| ∝ ||param||, so it orbits rather than converges on
    # a quadratic; assert sustained descent instead of tight convergence.
    params, target, grads = quad_problem()
    params = jax.tree_util.tree_map(lambda t: t + 1.0, target)
    m = optim.LarsSGD(learning_rate=0.1, trust_coefficient=0.02, momentum=0.5)
    state = m.init_state(params)

    def loss(p):
        return sum(float(jnp.sum((x - t) ** 2)) for x, t in
                   zip(jax.tree_util.tree_leaves(p),
                       jax.tree_util.tree_leaves(target)))

    l0 = loss(params)
    for step in range(100):
        params, state = m.update(step, grads(params), params, state)
    assert loss(params) < 0.5 * l0


def test_sgd_matches_torch_momentum():
    torch = pytest.importorskip("torch")
    w0 = np.random.RandomState(0).randn(4).astype(np.float32)
    g = np.random.RandomState(1).randn(4).astype(np.float32)

    tp = torch.tensor(w0, requires_grad=True)
    topt = torch.optim.SGD([tp], lr=0.1, momentum=0.9, dampening=0.0)
    m = optim.SGD(learning_rate=0.1, momentum=0.9, dampening=0.0)
    p = jnp.asarray(w0)
    s = m.init_state(p)
    for step in range(5):
        tp.grad = torch.tensor(g)
        topt.step()
        p, s = m.update(step, jnp.asarray(g), p, s)
    np.testing.assert_allclose(np.asarray(p), tp.detach().numpy(), rtol=1e-5)


def test_adam_matches_torch():
    torch = pytest.importorskip("torch")
    w0 = np.random.RandomState(0).randn(6).astype(np.float32)
    g = np.random.RandomState(1).randn(6).astype(np.float32)
    tp = torch.tensor(w0, requires_grad=True)
    topt = torch.optim.Adam([tp], lr=0.01)
    m = optim.Adam(learning_rate=0.01)
    p = jnp.asarray(w0)
    s = m.init_state(p)
    for step in range(10):
        tp.grad = torch.tensor(g)
        topt.step()
        p, s = m.update(step, jnp.asarray(g), p, s)
    np.testing.assert_allclose(np.asarray(p), tp.detach().numpy(), rtol=1e-4,
                               atol=1e-6)


class TestSchedules:
    def test_step(self):
        s = optim.Step(10, 0.5)
        assert float(s(1.0, 0)) == 1.0
        assert float(s(1.0, 10)) == 0.5
        assert float(s(1.0, 25)) == 0.25

    def test_multistep(self):
        s = optim.MultiStep([5, 8], 0.1)
        assert float(s(1.0, 4)) == pytest.approx(1.0)
        assert float(s(1.0, 5)) == pytest.approx(0.1)
        assert float(s(1.0, 9)) == pytest.approx(0.01)

    def test_poly(self):
        s = optim.Poly(2.0, 100)
        assert float(s(1.0, 0)) == 1.0
        assert float(s(1.0, 50)) == pytest.approx(0.25)
        assert float(s(1.0, 100)) == 0.0

    def test_warmup_sequential(self):
        seq = optim.SequentialSchedule()
        seq.add(optim.Warmup(0.1), 5).add(optim.Poly(1.0, 10), 10)
        assert float(seq(1.0, 0)) == pytest.approx(1.0)
        assert float(seq(1.0, 3)) == pytest.approx(1.3)
        # after warmup phase, poly kicks in with local step
        assert float(seq(1.0, 5)) == pytest.approx(1.0)


class TestTrigger:
    def test_max_epoch(self):
        t = optim.Trigger.max_epoch(3)
        assert not t({"epoch": 3, "iteration": 0})
        assert t({"epoch": 4, "iteration": 0})

    def test_every_epoch(self):
        t = optim.Trigger.every_epoch()
        assert t({"epoch_finished": True})
        assert not t({"epoch_finished": False})

    def test_several_iteration(self):
        t = optim.Trigger.several_iteration(5)
        assert t({"iteration": 5})
        assert not t({"iteration": 6})

    def test_combinators(self):
        t = optim.Trigger.and_(optim.Trigger.max_epoch(1),
                               optim.Trigger.min_loss(0.5))
        assert t({"epoch": 2, "loss": 0.1, "iteration": 0})
        assert not t({"epoch": 2, "loss": 1.0, "iteration": 0})


class TestValidationMethods:
    def test_top1(self):
        out = jnp.array([[0.9, 0.1], [0.2, 0.8], [0.6, 0.4]])
        tgt = jnp.array([0, 1, 1])
        s, c = optim.Top1Accuracy().batch_stats(out, tgt)
        assert (float(s), float(c)) == (2.0, 3.0)

    def test_top5(self):
        out = jax.random.normal(jax.random.PRNGKey(0), (10, 20))
        tgt = jnp.argmax(out, -1)
        s, c = optim.Top5Accuracy().batch_stats(out, tgt)
        assert float(s) == 10.0


def test_plateau_schedule_semantics():
    from bigdl_tpu.optim import Plateau

    p = Plateau(factor=0.5, patience=2, mode="max", epsilon=0.0)
    assert p.on_score(0.5) is False        # first score = best
    assert p.on_score(0.6) is False        # improved
    assert p.on_score(0.6) is False        # bad 1
    assert p.on_score(0.6) is True         # bad 2 >= patience -> drop
    assert p.current_factor == 0.5
    assert p.on_score(0.6) is False        # counter reset: bad 1 again
    assert p.on_score(0.9) is False        # new best resets
    assert p(1.0, 0) == 0.5                # factor applied
    floor = Plateau(factor=0.1, patience=0, min_lr=0.05)
    floor.current_factor = 0.001
    assert floor(1.0, 0) == 0.05           # min_lr floor


def test_plateau_wired_through_validation(tmp_path):
    """A stalling validation score must shrink the LR factor mid-run."""
    from bigdl_tpu import nn
    from bigdl_tpu.data.dataset import ArrayDataSet
    from bigdl_tpu.nn.module import Sequential
    from bigdl_tpu.optim import Plateau

    rng = np.random.RandomState(0)
    x = rng.randn(256, 4).astype(np.float32)
    y = rng.randint(0, 2, 256).astype(np.int32)  # pure noise -> no progress
    plateau = Plateau(factor=0.5, patience=1, mode="max", epsilon=1e-6)
    method = optim.SGD(learning_rate=0.05, learning_rate_schedule=plateau)
    model = Sequential([nn.Linear(4, 2)])
    opt = optim.Optimizer(model, ArrayDataSet(x, y), nn.CrossEntropyCriterion(),
                          batch_size=64)
    opt.set_optim_method(method)
    opt.set_end_when(optim.Trigger.max_epoch(8))
    opt.set_validation(optim.Trigger.every_epoch(),
                       ArrayDataSet(x[:64], np.zeros(64, np.int32)),
                       [optim.Top1Accuracy()])
    opt.log_every = 1000
    opt.optimize()
    assert plateau.current_factor < 1.0


def test_plateau_state_survives_checkpoint_resume(tmp_path):
    from bigdl_tpu import nn
    from bigdl_tpu.data.dataset import ArrayDataSet
    from bigdl_tpu.nn.module import Sequential
    from bigdl_tpu.optim import Plateau

    rng = np.random.RandomState(1)
    x = rng.randn(128, 4).astype(np.float32)
    y = rng.randint(0, 2, 128).astype(np.int32)

    def make_opt(plateau):
        method = optim.SGD(learning_rate=0.05,
                           learning_rate_schedule=plateau)
        model = Sequential([nn.Linear(4, 2)])
        opt = optim.Optimizer(model, ArrayDataSet(x, y),
                              nn.CrossEntropyCriterion(), batch_size=64)
        opt.set_optim_method(method)
        opt.set_checkpoint(str(tmp_path), optim.Trigger.every_epoch())
        opt.set_validation(optim.Trigger.every_epoch(),
                           ArrayDataSet(x[:64], np.zeros(64, np.int32)),
                           [optim.Top1Accuracy()])
        opt.log_every = 1000
        return opt

    p1 = Plateau(factor=0.5, patience=0, mode="max", epsilon=1e-6)
    opt1 = make_opt(p1)
    opt1.set_end_when(optim.Trigger.max_epoch(4))
    opt1.optimize()
    assert p1.current_factor < 1.0  # dropped during the stalled run

    # fresh process analog: new schedule instance resumes from checkpoint
    p2 = Plateau(factor=0.5, patience=0, mode="max", epsilon=1e-6)
    opt2 = make_opt(p2)
    opt2.set_end_when(optim.Trigger.max_epoch(5))
    opt2.optimize()
    assert p2.current_factor <= p1.current_factor  # restored, not reset


def test_epoch_based_schedules():
    """Reference SGD.EpochStep / EpochDecay / EpochSchedule semantics with
    epoch derived from step // steps_per_epoch."""
    import jax.numpy as jnp

    from bigdl_tpu.optim import EpochDecay, EpochSchedule, EpochStep

    spe = 10  # steps per epoch
    es = EpochStep(2, 0.5, steps_per_epoch=spe)
    assert float(es(1.0, 0)) == 1.0            # epoch 0
    assert float(es(1.0, 19)) == 1.0           # epoch 1 (< step_size)
    assert float(es(1.0, 20)) == 0.5           # epoch 2
    assert float(es(1.0, 45)) == 0.25          # epoch 4

    ed = EpochDecay(lambda e: jnp.floor(e / 3), steps_per_epoch=spe)
    assert float(ed(1.0, 0)) == 1.0
    np.testing.assert_allclose(float(ed(1.0, 30)), 0.1, rtol=1e-6)
    np.testing.assert_allclose(float(ed(1.0, 60)), 0.01, rtol=1e-6)

    sched = EpochSchedule([(1, 2, 0.1), (3, 5, 0.01), (6, 100, 0.001)],
                          steps_per_epoch=spe)
    assert float(sched(1.0, 0)) == pytest.approx(0.1)     # epoch 1
    assert float(sched(1.0, 25)) == pytest.approx(0.01)   # epoch 3
    assert float(sched(1.0, 99)) == pytest.approx(0.001)  # epoch 10

    # schedules stay jittable (they run inside the compiled step)
    import jax

    f = jax.jit(lambda s: es(1.0, s))
    assert float(f(jnp.asarray(20))) == 0.5


def test_epoch_schedule_last_regime_persists():
    from bigdl_tpu.optim import EpochSchedule

    sched = EpochSchedule([(1, 2, 0.1), (3, 5, 0.01)], steps_per_epoch=10)
    # past the last regime the final rate sticks (no jump back to base lr)
    assert float(sched(1.0, 70)) == pytest.approx(0.01)   # epoch 8


def test_epoch_schedule_gap_carries_previous_regime():
    """An epoch in a GAP between regimes inherits the most recently matched
    regime's rate, not the last regime's (ADVICE r2: the reference mutates
    config in order, so the previous rate sticks)."""
    from bigdl_tpu.optim import EpochSchedule

    sched = EpochSchedule([(1, 2, 0.1), (5, 8, 0.01)], steps_per_epoch=10)
    assert float(sched(1.0, 10)) == pytest.approx(0.1)    # epoch 2, regime 1
    assert float(sched(1.0, 30)) == pytest.approx(0.1)    # epoch 4: GAP
    assert float(sched(1.0, 45)) == pytest.approx(0.01)   # epoch 5, regime 2
    # before the first regime: base lr
    sched2 = EpochSchedule([(3, 5, 0.5)], steps_per_epoch=10)
    assert float(sched2(1.0, 0)) == pytest.approx(1.0)    # epoch 1


def test_epoch_schedule_accepts_unsorted_regimes():
    """Regimes given out of start-epoch order must still resolve correctly
    (the reference accepts any order)."""
    from bigdl_tpu.optim import EpochSchedule

    sched = EpochSchedule([(5, 8, 0.01), (1, 2, 0.1)], steps_per_epoch=10)
    assert float(sched(1.0, 10)) == pytest.approx(0.1)    # epoch 2
    assert float(sched(1.0, 55)) == pytest.approx(0.01)   # epoch 6


def test_cosine_schedule():
    """Warmup -> cosine-to-floor, the standard TPU large-batch recipe."""
    from bigdl_tpu.optim import Cosine, SequentialSchedule, Warmup

    c = optim.Cosine(100, alpha=0.1)
    assert float(c(1.0, 0)) == pytest.approx(1.0)
    assert float(c(1.0, 50)) == pytest.approx(0.55)     # midpoint
    assert float(c(1.0, 100)) == pytest.approx(0.1)     # floor
    assert float(c(1.0, 500)) == pytest.approx(0.1)     # floor persists

    seq = SequentialSchedule()
    seq.add(Warmup(0.01), 10).add(Cosine(90), 90)
    assert float(seq(0.1, 0)) == pytest.approx(0.1)
    assert float(seq(0.1, 10)) == pytest.approx(0.1)    # cosine start
    assert float(seq(0.1, 100)) == pytest.approx(0.0, abs=1e-6)

    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda s: c(1.0, s))                    # jit-traceable
    assert float(f(jnp.asarray(50))) == pytest.approx(0.55)
    with pytest.raises(ValueError):
        optim.Cosine(0)


def test_precision_recall_methods():
    import jax.numpy as jnp

    out = jnp.asarray([[0.9, 0.1], [0.2, 0.8], [0.3, 0.7], [0.6, 0.4]])
    tgt = jnp.asarray([1, 1, 0, 0])      # preds: 0,1,1,0
    p = optim.Precision()
    s, c = p.batch_stats(out, tgt)
    assert (float(s), float(c)) == (1.0, 2.0)   # TP=1 of 2 predicted-pos
    r = optim.Recall()
    s, c = r.batch_stats(out, tgt)
    assert (float(s), float(c)) == (1.0, 2.0)   # TP=1 of 2 actual-pos
    # padded rows (weight 0) are excluded
    w = jnp.asarray([1.0, 1.0, 0.0, 1.0])
    s, c = p.batch_stats(out, tgt, w)
    assert (float(s), float(c)) == (1.0, 1.0)


def test_layer_trainable_false_freezes_through_optimizer():
    """keras-1 layer.trainable=False: the Optimizer auto-derives the
    engine mask; frozen layer params stay bitwise fixed while the rest
    train."""
    import jax

    from bigdl_tpu import nn
    from bigdl_tpu.data.dataset import ArrayDataSet
    from bigdl_tpu.nn.criterion import MSECriterion
    from bigdl_tpu.nn.module import Sequential
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.optim.optimizer import Optimizer
    from bigdl_tpu.optim.trigger import Trigger

    rs = np.random.RandomState(0)
    x = rs.randn(64, 6).astype(np.float32)
    y = rs.randn(64, 2).astype(np.float32)

    frozen = nn.Linear(6, 16)
    frozen.trainable = False
    model = Sequential([frozen, nn.Tanh(), nn.Linear(16, 2)])

    init_vars = model.init(jax.random.PRNGKey(0), x[:1])
    init = jax.tree_util.tree_map(np.copy, init_vars["params"])
    opt = (Optimizer(model, ArrayDataSet(x, y), MSECriterion(),
                     batch_size=32)
           .set_optim_method(SGD(learning_rate=0.1))
           .set_end_when(Trigger.max_epoch(3)))
    opt._initial_variables = init_vars  # pin the starting point
    trained = opt.optimize()
    params = trained.variables["params"]
    k0 = model._key(0)
    np.testing.assert_array_equal(np.asarray(params[k0]["weight"]),
                                  np.asarray(init[k0]["weight"]))
    # the head DID train
    k2 = model._key(2)
    assert np.abs(np.asarray(params[k2]["weight"])
                  - np.asarray(init[k2]["weight"])).max() > 1e-4


def test_plateau_trigger_early_stops():
    """keras-EarlyStopping analog: observes once per validation event (or
    epoch for loss), fires after `patience` stale observations, resets on
    improvement, ignores NaN, and re-seeing the same score between events
    does NOT burn patience."""
    import pytest

    from bigdl_tpu.optim.trigger import Trigger

    t = Trigger.plateau(monitor="loss", patience=2, min_delta=0.01)
    seq = [1.0, 0.8, 0.795, 0.796]          # two non-improvements -> fire
    fired = [t({"loss": v, "epoch": i}) for i, v in enumerate(seq)]
    assert fired == [False, False, False, True]

    t2 = Trigger.plateau(monitor="loss", patience=2, min_delta=0.01)
    fired2 = [t2({"loss": v, "epoch": i}) for i, v in
              enumerate([1.0, 0.99, 0.5, 0.499, 0.498])]
    assert fired2 == [False, False, False, False, True]

    t3 = Trigger.plateau(monitor="score", patience=1)
    assert t3({"score": float("nan"), "n_validations": 1}) is False
    assert t3({"score": 0.5, "n_validations": 2}) is False  # baseline
    # SAME event re-seen on later iterations: patience not burned
    assert t3({"score": 0.5, "n_validations": 2}) is False
    assert t3({"score": 0.5, "n_validations": 2}) is False
    # next validation event with no improvement -> fire (patience 1)
    assert t3({"score": 0.5, "n_validations": 3}) is True

    # no validation ever run: trigger stays inert, never fires
    t4 = Trigger.plateau(monitor="score", patience=1)
    assert t4({"loss": 1.0}) is False

    # failure-retry rollback REPLAYS events: replayed (<= last seen)
    # observations must not burn patience a second time
    t5 = Trigger.plateau(monitor="score", patience=2)
    assert t5({"score": 0.9, "n_validations": 1}) is False  # baseline
    assert t5({"score": 0.9, "n_validations": 2}) is False  # stale 1
    # rollback to event 1 and replay: skipped, stale stays 1
    assert t5({"score": 0.9, "n_validations": 1}) is False
    assert t5({"score": 0.9, "n_validations": 2}) is False
    # a genuinely NEW event with no improvement -> stale 2 -> fire
    assert t5({"score": 0.9, "n_validations": 3}) is True

    with pytest.raises(ValueError, match="plateau monitor"):
        Trigger.plateau(monitor="val_loss")
