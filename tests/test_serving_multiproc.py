"""Out-of-process serving: engine + HTTP frontend in a SUBPROCESS, driven
by concurrent clients over real sockets.

Reference analog (unverified — mount empty): ``scala/serving/`` decouples
the serving engine from clients via Flink/Redis processes; these specs
prove the TPU-native stack holds up across a process boundary — dynamic
batching under concurrency, bounded-queue backpressure (non-blocking
shed + client retry, never an unbounded block), and recorded p50/p99
latency (VERDICT r3 #9).
"""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time
from urllib import request as urlreq

import numpy as np
import pytest

SERVER = textwrap.dedent("""
    import sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    from bigdl_tpu import nn
    from bigdl_tpu.serving.inference_model import InferenceModel
    from bigdl_tpu.serving.server import ServingConfig, ServingServer
    from bigdl_tpu.serving.http_frontend import HttpFrontend

    model = nn.Sequential([nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4)])
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 8), np.float32))
    im = InferenceModel(model, variables)
    srv = ServingServer(im, ServingConfig(batch_size=16,
                                          batch_timeout_s=0.01,
                                          queue_capacity=64)).start()
    fe = HttpFrontend(srv, port=0).start()
    print(f"URL={fe.url}", flush=True)
    sys.stdin.readline()        # parent closes stdin to stop us
    fe.stop(); srv.stop()
    print(f"STATS={srv.stats['batches']},{srv.stats['requests']}",
          flush=True)
""")


def _post(url, payload, timeout=30.0):
    req = urlreq.Request(url, data=json.dumps(payload).encode(),
                         headers={"Content-Type": "application/json"})
    with urlreq.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


@pytest.mark.slow
def test_serving_subprocess_concurrent_clients(tmp_path):
    script = tmp_path / "server.py"
    script.write_text(SERVER)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pythonpath = os.pathsep.join(
        p for p in [repo_root, os.environ.get("PYTHONPATH")] if p)
    env = dict(os.environ, PYTHONPATH=pythonpath, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen([sys.executable, str(script)], env=env,
                            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                            text=True)
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("URL="), line
        url = line[4:] + "/predict"

        rs = np.random.RandomState(0)
        n_clients, n_requests = 8, 20
        latencies = [[] for _ in range(n_clients)]
        errors = []

        def client(ci):
            try:
                for _ in range(n_requests):
                    x = rs.rand(2, 8).astype(np.float32)
                    t0 = time.perf_counter()
                    out = _post(url, {"instances": x.tolist()})
                    latencies[ci].append(time.perf_counter() - t0)
                    preds = np.asarray(out["predictions"])
                    assert preds.shape == (2, 4), preds.shape
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_clients)]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        wall = time.time() - t0
        assert not errors, errors

        # health endpoint reports engine stats across the process boundary
        with urlreq.urlopen(line[4:] + "/health", timeout=10) as r:
            health = json.loads(r.read())
        assert health["status"] == "ok"
        total = n_clients * n_requests
        assert health["requests"] == total, health
        # concurrency => dynamic batching actually coalesced requests
        assert health["batches"] < total, health

        lat = np.sort(np.concatenate(latencies))
        artifact = {
            "requests": total,
            "concurrent_clients": n_clients,
            "batches": int(health["batches"]),
            "avg_batch_size": round(total / health["batches"], 2),
            "wall_s": round(wall, 2),
            "throughput_rps": round(total / wall, 1),
            "p50_ms": round(float(lat[int(0.50 * (len(lat) - 1))]) * 1e3, 2),
            "p99_ms": round(float(lat[int(0.99 * (len(lat) - 1))]) * 1e3, 2),
        }
        print("SERVING_LATENCY " + json.dumps(artifact))
        if os.environ.get("BIGDL_TPU_WRITE_ARTIFACTS"):
            with open(os.path.join(repo_root, "SERVING_r05.json"), "w") as f:
                json.dump(artifact, f, indent=1)
    finally:
        if proc.poll() is None:
            proc.stdin.close()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
    out_rest = proc.stdout.read()
    assert "STATS=" in out_rest, out_rest


def test_bounded_queue_backpressure():
    """The request queue is BOUNDED and admission never blocks: when the
    engine falls behind, enqueue sheds (``ServiceUnavailableError``) and
    the producer retries — every ACCEPTED request still completes."""
    from bigdl_tpu.serving.inference_model import InferenceModel
    from bigdl_tpu.serving.server import (ServiceUnavailableError,
                                          ServingConfig, ServingServer)

    def slow_predict(x):
        time.sleep(0.02)
        return x * 2.0

    im = InferenceModel(predict_fn=slow_predict)
    srv = ServingServer(im, ServingConfig(batch_size=4,
                                          batch_timeout_s=0.001,
                                          queue_capacity=4)).start()
    try:
        seen_qsize = []
        rids = []
        retries = [0]
        lock = threading.Lock()

        def producer(k):
            for i in range(10):
                payload = np.full((1, 3), float(k * 10 + i), np.float32)
                while True:        # shed -> bounded client-side retry
                    try:
                        rid = srv.enqueue(payload)
                        break
                    except ServiceUnavailableError as e:
                        with lock:
                            retries[0] += 1
                        time.sleep(min(e.retry_after, 0.01))
                with lock:
                    rids.append(rid)
                    seen_qsize.append(srv._in.qsize())

        threads = [threading.Thread(target=producer, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert max(seen_qsize) <= 4, max(seen_qsize)
        for rid in rids:
            res = srv.query(rid, timeout=30)
            assert res.shape == (1, 3)
        assert srv.stats["requests"] == 40
        # the bounded queue actually pushed back on the producers
        assert retries[0] > 0
        assert srv.stats["shed_requests"] == retries[0]
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# multi-WORKER scale-out: N process-isolated engines behind one round-robin
# proxy with supervision (the Flink task-manager posture)

def _pool_loader():
    """Worker-side model factory (resolved as tests.test_serving_multiproc:
    _pool_loader in the worker's own interpreter)."""
    import numpy as np
    import jax

    from bigdl_tpu import nn
    from bigdl_tpu.serving.inference_model import InferenceModel

    model = nn.Sequential([nn.Linear(8, 4)])
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 8), np.float32))
    return InferenceModel(model, variables)


@pytest.mark.slow
def test_serving_pool_scaleout_and_supervision():
    from bigdl_tpu.serving.pool import ServingPool

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pythonpath = os.pathsep.join(
        p for p in [repo_root, os.environ.get("PYTHONPATH")] if p)
    env = {"PYTHONPATH": pythonpath, "BIGDL_TPU_POOL_CPU": "1",
           "JAX_PLATFORMS": "cpu"}
    pool = ServingPool("tests.test_serving_multiproc:_pool_loader",
                       workers=2, batch_size=8, worker_env=env,
                       supervise_interval_s=0.3)
    pool.start()
    try:
        rs = np.random.RandomState(0)

        def many(n):
            for _ in range(n):
                x = rs.rand(2, 8).astype(np.float32)
                out = _post(pool.url + "/predict", {"instances": x.tolist()})
                assert np.asarray(out["predictions"]).shape == (2, 4)

        many(12)
        with urlreq.urlopen(pool.url + "/health", timeout=10) as r:
            health = json.loads(r.read())
        assert health["requests"] == 12
        per_worker = [int(w.get("requests", 0)) for w in health["workers"]]
        # round-robin actually spread load over BOTH workers
        assert all(p > 0 for p in per_worker), per_worker

        # supervision: kill one worker; requests keep succeeding (the
        # proxy skips the corpse) and the supervisor respawns it
        victim = pool.workers[0]
        victim.proc.kill()
        victim.proc.wait(timeout=10)
        many(6)                      # served by the survivor
        deadline = time.time() + 60
        while time.time() < deadline and not (victim.alive()
                                              and pool.restarts >= 1):
            time.sleep(0.2)
        assert pool.restarts >= 1
        assert all(w.alive() for w in pool.workers)
        many(6)                      # both workers back in rotation
    finally:
        pool.stop()
