"""Streaming input pipeline specs (docs/data.md): stage-parallel
read→decode→assemble over the buffer ring — determinism for any worker
count, crash propagation (never a hang), ring slot-lending safety, the
prefetch leak fix, and the data.* observability surface."""

import threading
import time

import numpy as np
import pytest

from bigdl_tpu.data.pipeline import (
    BufferRing, PipelineError, RingBatch, StreamingPipeline,
    autotune_depths, dispatch_to_device,
)
from bigdl_tpu.data.prefetch import prefetch_to_device
from bigdl_tpu.data.records import RecordDataSet, write_records
from bigdl_tpu.data.vision import AugmentedRecordImages
from bigdl_tpu.optim.metrics import Metrics

RS = np.random.RandomState(7)


@pytest.fixture
def rec(tmp_path):
    x = RS.rand(100, 4, 4, 3).astype(np.float32)
    y = RS.randint(0, 5, 100).astype(np.int32)
    p = str(tmp_path / "train.btrec")
    write_records(p, {"x": x, "y": y})
    return p, x, y


@pytest.fixture
def img_rec(tmp_path):
    xs = RS.randint(0, 255, (64, 40, 40, 3), np.uint8)
    ys = RS.randint(0, 10, 64).astype(np.int32)
    p = str(tmp_path / "imgs.btrec")
    write_records(p, {"image": xs, "label": ys})
    return p, xs, ys


def _snap(mb):
    # RingBatch arrays are views over reusable slots: copy before the next
    # pull (the documented consumer contract)
    return {k: np.array(v) for k, v in mb.items()}


# ---------------------------------------------------------------------------
# determinism
# ---------------------------------------------------------------------------

def test_stream_matches_serial_any_worker_count(rec):
    """stream_batches is byte-identical to batches() for 1 and N workers —
    geometry and order come from the plan, never worker scheduling."""
    p, x, y = rec
    ds = RecordDataSet(p)
    ref = [_snap(mb) for mb in ds.batches(16, shuffle=True, seed=3,
                                          epoch=1, drop_last=False)]
    for w in (1, 3):
        got = [_snap(mb) for mb in ds.stream_batches(
            16, shuffle=True, seed=3, epoch=1, drop_last=False, workers=w)]
        assert len(got) == len(ref)
        for a, b in zip(ref, got):
            assert set(a) == set(b)
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])
    ds.close()


def test_augmented_epochs_identical_for_1_vs_n_workers(img_rec):
    """Seeded augmentation (random crop + flip) through the fused native
    transform: identical epochs for 1 vs 3 decode workers, and identical
    to the serial stage path."""
    p, xs, ys = img_rec
    mean, std = (0.5 * 255,) * 3, (0.25 * 255,) * 3
    ds = AugmentedRecordImages(p, (24, 24), mean, std, resize_hw=(32, 32),
                               random_crop=True, random_flip=True)
    for epoch in (0, 2):
        ref = [_snap(mb) for mb in ds.batches(16, shuffle=True, seed=5,
                                              epoch=epoch)]
        for w in (1, 3):
            got = [_snap(mb) for mb in ds.stream_batches(
                16, shuffle=True, seed=5, epoch=epoch, workers=w)]
            assert len(got) == len(ref) > 0
            for a, b in zip(ref, got):
                for k in a:
                    np.testing.assert_array_equal(
                        a[k], b[k], err_msg=f"epoch {epoch} workers {w} {k}")
    ds.close()


# ---------------------------------------------------------------------------
# failure propagation
# ---------------------------------------------------------------------------

def test_decode_crash_propagates_not_hangs():
    """A worker exception re-raises at the consumer within a bounded wait
    (the training loop's retry path sees it; the run never wedges)."""
    def bad_decode(item, raw, bufs, lo, hi, slot):
        if item >= 2:
            raise RuntimeError("decoder exploded")
        bufs["x"][lo:hi] = item
        return {"n": 4}

    pl = StreamingPipeline(iter(range(8)), lambda i, s: i, bad_decode,
                           {"x": ((4, 2), np.float32)}, rows=4, workers=2)
    t0 = time.time()
    with pytest.raises(PipelineError) as ei:
        for _ in pl:
            pass
    assert time.time() - t0 < 30
    assert "exploded" in str(ei.value.__cause__)


def test_empty_and_dry_plans_terminate_not_hang(rec):
    """A plan that yields nothing (shard smaller than the batch with
    drop_last) — or runs dry while the consumer is already parked in
    pop() — ends iteration instead of spinning forever."""
    p, _, _ = rec
    ds = RecordDataSet(p)
    t0 = time.time()
    # 100 records, batch 128, drop_last=True -> zero planned batches
    assert list(ds.stream_batches(128, shuffle=False, workers=2)) == []
    assert time.time() - t0 < 30
    ds.close()

    def slow_plan():
        yield 0
        time.sleep(0.3)  # consumer parks in pop(seq=1) before plan ends

    def decode(item, raw, bufs, lo, hi, slot):
        bufs["x"][lo:hi] = item
        return {"n": 2}

    pl = StreamingPipeline(slow_plan(), lambda i, s: i, decode,
                           {"x": ((2,), np.float32)}, rows=2, workers=1)
    t0 = time.time()
    assert len(list(pl)) == 1
    assert time.time() - t0 < 30


def test_fetch_crash_propagates():
    def fetch(item, slot):
        raise OSError("disk fell off")

    pl = StreamingPipeline(iter(range(3)), fetch,
                           lambda *a: None, {"x": ((2,), np.float32)},
                           rows=2, workers=1)
    with pytest.raises(PipelineError) as ei:
        next(iter(pl))
    assert isinstance(ei.value.__cause__, OSError)


def test_abandoned_consumer_stops_stage_threads(rec):
    """Walking away mid-epoch (preemption break, end_when) shuts the read
    and decode threads down instead of leaking them per epoch."""
    p, _, _ = rec
    ds = RecordDataSet(p)
    before = threading.active_count()
    sp = ds.stream_batches(16, workers=2)
    it = iter(sp)
    next(it)
    it.close()  # the driver's generator-close path
    deadline = time.time() + 10
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before
    ds.close()


# ---------------------------------------------------------------------------
# ring safety
# ---------------------------------------------------------------------------

def test_ring_never_lends_slot_in_flight():
    """A slot is never re-assigned while READY or LENT: writers see only
    FREE slots, and the strict state machine rejects protocol violations."""
    ring = BufferRing({"x": ((2,), np.float32)}, depth=2)
    stop = threading.Event()
    s0 = ring.assign(0, 1, stop)
    s1 = ring.assign(1, 1, stop)
    assert {s0, s1} == {0, 1}
    # ring full: a non-blocking probe must find nothing FREE
    got = []
    t = threading.Thread(target=lambda: got.append(
        ring.assign(2, 1, stop, timeout=0.01)))
    stop2 = threading.Event()
    ring.part_done(s0, {"n": 2})
    slot, bufs, meta = ring.pop(0, stop2, lambda: None)
    assert slot == s0 and meta["n"] == 2
    # still LENT: seq-2 assignment can only take the OTHER slot once it
    # becomes free
    ring.part_done(s1)
    t.start()
    ring.pop(1, stop2, lambda: None)
    ring.release(s1)
    t.join(5)
    assert got == [s1]  # never the LENT s0
    # protocol violations raise instead of corrupting
    with pytest.raises(PipelineError):
        ring.release(s1)  # not lent anymore (double release path)
    ring.release(s0)
    with pytest.raises(PipelineError):
        ring.release(s0)  # double release
    with pytest.raises(PipelineError):
        ring.part_done(s0)  # not assigned


def test_ring_reuse_no_allocation_and_no_corruption(rec):
    """Slots recycle (bounded buffer identity set) and in-order delivery
    survives a slow consumer — data read before the next pull is intact."""
    p, x, _ = rec
    ds = RecordDataSet(p)
    seen_ids = set()
    total = 0
    for e in range(3):
        got = []
        for mb in ds.stream_batches(20, shuffle=False, epoch=e, workers=2):
            seen_ids.add(id(mb["input"].base)
                         if mb["input"].base is not None
                         else id(mb["input"]))
            got.append(np.array(mb["input"]))
            total += 1
            time.sleep(0.002)  # let producers run ahead into the ring
        np.testing.assert_array_equal(np.concatenate(got), x)
    # ring buffers are cached on the dataset and reused across epochs:
    # 15 batches flow through at most one ring's worth of arrays
    assert total == 15 and len(seen_ids) <= 8
    ds.close()


def test_ring_batch_release_idempotent():
    calls = []
    rb = RingBatch(lambda: calls.append(1), input=np.zeros(2))
    rb.release()
    rb.release()
    assert calls == [1]


# ---------------------------------------------------------------------------
# dispatch + prefetch satellites
# ---------------------------------------------------------------------------

def test_dispatch_to_device_survives_slot_reuse(rec):
    """Device arrays keep their batch's data even after the ring slot they
    came from is recycled many times over — the XLA:CPU zero-copy
    device_put alias trap (a released slot refilled under a live device
    array corrupts training silently).  Small ring + many batches forces
    heavy reuse; every device array must still match the serial epoch."""
    import jax

    p, x, _ = rec
    ds = RecordDataSet(p)
    for epoch in range(3):
        stream = ds.stream_batches(10, shuffle=True, seed=7, epoch=epoch,
                                   workers=2, ring_depth=2, raw_depth=1)
        devs = list(dispatch_to_device(
            stream, lambda mb: (jax.device_put(np.asarray(mb["input"])),
                                jax.device_put(np.asarray(mb["target"]))),
            size=2))
        ref = list(ds.batches(10, shuffle=True, seed=7, epoch=epoch))
        assert len(devs) == len(ref) == 10
        for (xd, yd), mb in zip(devs, ref):
            np.testing.assert_array_equal(np.asarray(xd), mb["input"])
            np.testing.assert_array_equal(np.asarray(yd), mb["target"])
    ds.close()


class _ClosableIter:
    def __init__(self, n):
        self._it = iter(range(n))
        self.closed = False

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._it)

    def close(self):
        self.closed = True


def test_prefetch_to_device_closes_upstream_on_abandonment():
    """Satellite: prefetch_to_device mirrors thread_prefetch's cleanup —
    abandoning the iterator closes the upstream producer."""
    src = _ClosableIter(100)
    it = prefetch_to_device(src, lambda b: b, size=3)
    assert next(it) == 0
    it.close()  # abandon mid-stream
    assert src.closed

    # ...but a normally-exhausted iterator does NOT re-close its upstream
    src2 = _ClosableIter(3)
    assert list(prefetch_to_device(src2, lambda b: b, size=2)) == [0, 1, 2]
    assert not src2.closed


# ---------------------------------------------------------------------------
# observability + autotune
# ---------------------------------------------------------------------------

def test_stage_metrics_and_gauges_exported(rec):
    """data.* counters and queue-depth gauges land in the registry and
    render as Prometheus lines — the /metrics view of the pipeline."""
    from bigdl_tpu.obs.export import render_prometheus

    p, _, _ = rec
    ds = RecordDataSet(p)
    m = Metrics()
    for _ in ds.stream_batches(20, shuffle=False, metrics=m, workers=2):
        pass
    s = m.summary()
    assert s["data.read_batches"] == 5
    assert s["data.decoded_images"] == 100
    assert "data.queue_depth.ring" in s
    text = render_prometheus(m)
    assert "# TYPE data_read_batches counter" in text
    assert "# TYPE data_queue_depth_ring gauge" in text
    ds.close()


def test_data_wait_histogram_recorded_by_driver(rec):
    """The optimizer's data phase lands waits in train.data_wait_s — the
    input-bound-vs-device-bound verdict metric."""
    from bigdl_tpu import nn, optim

    p, _, _ = rec
    ds = RecordDataSet(p)
    model = nn.Sequential([nn.Flatten(), nn.Linear(48, 5)])
    opt = optim.Optimizer(model, ds, nn.CrossEntropyCriterion(),
                          batch_size=40)
    opt.set_optim_method(optim.Adam(learning_rate=0.05))
    opt.set_end_when(optim.Trigger.max_iteration(4))
    assert opt.host_prefetch == 2  # satellite: lookahead on by default
    trained = opt.optimize()
    assert trained is not None
    snap = opt.metrics.snapshot()
    assert snap["hists"]["train.data_wait_s"]["n"] >= 4
    ds.close()


def test_autotune_depths_tracks_stage_ratio():
    fast_read = autotune_depths(read_rate=100.0, decode_rate=5.0, workers=4)
    assert fast_read["raw_depth"] == 1  # reader far ahead: no lookahead
    slow_read = autotune_depths(read_rate=5.0, decode_rate=100.0, workers=4)
    assert slow_read["raw_depth"] == 4  # reader is the bottleneck
    # sub-batch parts (default): workers share a slot, ring stays small —
    # image-batch slots are hundreds of MB each
    assert slow_read["ring_depth"] == 4
    # whole-batch parts: each worker fills its own slot
    assert autotune_depths(5.0, 100.0, 4,
                           parts_per_batch=1)["ring_depth"] == 7
    assert autotune_depths(0, 0, 2)["ring_depth"] == 4


def test_shared_memory_decode_pool_matches_native(img_rec):
    """The PIL fallback's multiprocess shared-memory decode produces the
    same batches as the native path (same math, same rounding)."""
    import io

    from PIL import Image

    from bigdl_tpu.data.vision import stream_jpeg_batches

    _, xs, ys = img_rec
    enc = []
    for i in range(24):
        buf = io.BytesIO()
        Image.fromarray(xs[i]).save(buf, "JPEG", quality=90)
        enc.append(buf.getvalue())
    mean, std = (0.5 * 255,) * 3, (0.25 * 255,) * 3
    kw = dict(labels=ys[:24], resize_hw=(32, 32), random_crop=True,
              random_flip=True, seed=1, workers=2)
    a = [_snap(mb) for mb in stream_jpeg_batches(
        enc, 8, (24, 24), mean, std, use_processes=False, **kw)]
    b = [_snap(mb) for mb in stream_jpeg_batches(
        enc, 8, (24, 24), mean, std, use_processes=True, **kw)]
    assert len(a) == len(b) == 3
    for x1, x2 in zip(a, b):
        np.testing.assert_array_equal(x1["target"], x2["target"])
        np.testing.assert_allclose(x1["input"], x2["input"], atol=1e-5)
