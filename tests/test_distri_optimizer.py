"""End-to-end distributed training specs.

Mirrors the reference's ``optim/DistriOptimizerSpec.scala`` (SURVEY.md §5):
train tiny models on synthetic data over the simulated 8-device mesh, assert
convergence, checkpoint/resume, and single-vs-multi-device equivalence.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn, optim
from bigdl_tpu.data import ArrayDataSet
from bigdl_tpu.runtime.engine import Engine


def synthetic_classification(n=1024, d=16, classes=4, seed=0):
    """Linearly-separable-ish synthetic data, learnable to >95%."""
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, d) * 3
    y = rng.randint(0, classes, size=n)
    x = centers[y] + rng.randn(n, d)
    return x.astype(np.float32), y.astype(np.int32)


def mlp(classes=4):
    return nn.Sequential([
        nn.Linear(16, 64), nn.ReLU(),
        nn.Linear(64, classes), nn.LogSoftMax(),
    ])


class TestDistriOptimizer:
    def test_convergence_and_validation(self):
        x, y = synthetic_classification()
        train = ArrayDataSet(x[:896], y[:896])
        val = ArrayDataSet(x[896:], y[896:])
        model = mlp()
        opt = optim.Optimizer(model, train, nn.ClassNLLCriterion(),
                              batch_size=128)
        opt.set_optim_method(optim.Adam(learning_rate=1e-2))
        opt.set_end_when(optim.Trigger.max_epoch(8))
        opt.set_validation(optim.Trigger.every_epoch(), val,
                           [optim.Top1Accuracy()])
        opt.log_every = 10
        trained = opt.optimize()
        results = trained.evaluate(val, [optim.Top1Accuracy()], batch_size=128)
        assert results[0].result > 0.9, results

        # predict agrees with evaluate
        preds = trained.predict(x[896:])
        acc = float(np.mean(np.argmax(preds, -1) == y[896:]))
        assert acc == pytest.approx(results[0].result, abs=1e-6)

    def test_multi_device_matches_single_device(self, tmp_path):
        """Same data, same seeds: 8-device ZeRO-sharded run must track the
        1-device run closely (allreduce-mean == full-batch gradient)."""
        x, y = synthetic_classification(n=512)
        losses = {}
        for ndev in (1, 8):
            Engine.reset()
            from bigdl_tpu.runtime.engine import EngineConfig, init_engine
            from bigdl_tpu.runtime.mesh import MeshSpec
            init_engine(EngineConfig(
                mesh=MeshSpec(data=ndev)) if ndev == 1 else EngineConfig())
            ds = ArrayDataSet(x, y)
            model = mlp()
            opt = optim.Optimizer(model, ds, nn.ClassNLLCriterion(),
                                  batch_size=64, seed=7)
            opt.set_optim_method(optim.SGD(learning_rate=0.1))
            opt.set_end_when(optim.Trigger.max_iteration(20))
            opt.log_every = 100
            trained = opt.optimize()
            res = trained.evaluate(ds, [optim.Loss(nn.CrossEntropyCriterion())],
                                   batch_size=64)
            losses[ndev] = res[0].result
        assert losses[1] == pytest.approx(losses[8], rel=2e-3), losses

    def test_checkpoint_resume(self, tmp_path):
        x, y = synthetic_classification(n=256)
        ds = ArrayDataSet(x, y)
        ckpt_dir = str(tmp_path / "ckpt")

        def run(max_iter):
            Engine.reset()
            model = mlp()
            opt = optim.Optimizer(model, ds, nn.ClassNLLCriterion(),
                                  batch_size=64, seed=3)
            opt.set_optim_method(optim.Adam(learning_rate=1e-2))
            opt.set_end_when(optim.Trigger.max_iteration(max_iter))
            opt.set_checkpoint(ckpt_dir, optim.Trigger.several_iteration(4))
            opt.log_every = 100
            return opt.optimize()

        run(8)  # writes ckpt-4, ckpt-8
        from bigdl_tpu.optim import checkpoint as ckpt_mod
        latest = ckpt_mod.latest_checkpoint(ckpt_dir)
        assert latest and latest.endswith("ckpt-8")

        # resume continues from iteration 8 (fresh driver resumes and runs to 12)
        trained = run(12)
        latest = ckpt_mod.latest_checkpoint(ckpt_dir)
        assert latest.endswith("ckpt-12")
        res = trained.evaluate(ds, [optim.Top1Accuracy()])
        assert res[0].result > 0.8

    def test_gradient_clipping_runs(self):
        x, y = synthetic_classification(n=256)
        ds = ArrayDataSet(x, y)
        model = mlp()
        opt = optim.Optimizer(model, ds, nn.ClassNLLCriterion(), batch_size=64)
        opt.set_optim_method(optim.SGD(learning_rate=0.05))
        opt.set_gradient_clipping_by_l2_norm(1.0)
        opt.set_end_when(optim.Trigger.max_iteration(10))
        opt.log_every = 100
        trained = opt.optimize()
        assert trained is not None

    def test_bn_dropout_model_trains(self):
        """Stateful (BN) + rng (Dropout) paths through the sharded step."""
        x, y = synthetic_classification(n=512)
        ds = ArrayDataSet(x, y)
        model = nn.Sequential([
            nn.Linear(16, 32), nn.BatchNorm(), nn.ReLU(), nn.Dropout(0.2),
            nn.Linear(32, 4), nn.LogSoftMax(),
        ])
        opt = optim.Optimizer(model, ds, nn.ClassNLLCriterion(), batch_size=64)
        opt.set_optim_method(optim.Adam(learning_rate=1e-2))
        opt.set_end_when(optim.Trigger.max_epoch(6))
        opt.log_every = 100
        trained = opt.optimize()
        res = trained.evaluate(ds, [optim.Top1Accuracy()])
        assert res[0].result > 0.85
        # BN state was actually updated
        st = jax.tree_util.tree_leaves(trained.variables["state"])
        assert any(float(jnp.max(jnp.abs(s))) > 1e-3 for s in st)

    def test_lars_replicated_path(self):
        x, y = synthetic_classification(n=256)
        ds = ArrayDataSet(x, y)
        model = mlp()
        opt = optim.Optimizer(model, ds, nn.ClassNLLCriterion(), batch_size=64)
        opt.set_optim_method(optim.LarsSGD(learning_rate=0.05,
                                           trust_coefficient=0.02))
        opt.set_end_when(optim.Trigger.max_iteration(15))
        opt.log_every = 100
        trained = opt.optimize()
        res = trained.evaluate(ds, [optim.Top1Accuracy()])
        assert res[0].result > 0.5


def test_prefetch_to_device_order_and_depth():
    from bigdl_tpu.data.prefetch import prefetch_to_device

    dispatched = []

    def put(b):
        dispatched.append(b)
        return b * 10

    out = []
    gen = prefetch_to_device(iter(range(5)), put, size=2)
    first = next(gen)
    # depth-2: two dispatches before the first yield
    assert dispatched == [0, 1]
    assert first == 0
    out = [first] + list(gen)
    assert out == [0, 10, 20, 30, 40]
    assert dispatched == [0, 1, 2, 3, 4]

    import pytest

    with pytest.raises(ValueError):
        list(prefetch_to_device(iter([1]), put, size=0))


def test_bf16_grads_and_remat_options():
    """bf16 gradient reduce-scatter (the FP16CompressedTensor analog)
    halves the collective bytes and still converges; remat produces the
    same loss trajectory as the plain step (identical numerics, only the
    backward's memory/compute tradeoff changes)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.nn.module import Sequential
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.optim.train_step import ShardedParameterStep
    from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

    rs = np.random.RandomState(0)
    x = rs.randn(64, 8).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    mesh = build_mesh(MeshSpec(data=8))

    def make(**kw):
        model = Sequential([nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2)])
        variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x[:2]))
        return ShardedParameterStep(model, nn.CrossEntropyCriterion(),
                                    SGD(learning_rate=0.2), mesh, variables,
                                    **kw)

    plain = make()
    bf16 = make(bf16_grads=True)
    remat = make(remat=True)
    assert bf16.collective_bytes_per_step < plain.collective_bytes_per_step

    rng = jax.random.PRNGKey(1)
    losses = {"plain": [], "bf16": [], "remat": []}
    for i in range(30):
        losses["plain"].append(float(plain.train_step(i, rng, x, y)))
        losses["bf16"].append(float(bf16.train_step(i, rng, x, y)))
        losses["remat"].append(float(remat.train_step(i, rng, x, y)))
    # remat is numerically the SAME program
    np.testing.assert_allclose(losses["remat"], losses["plain"], rtol=1e-5)
    # bf16 grads converge to the same ballpark
    assert losses["bf16"][-1] < 0.5 * losses["bf16"][0]

    # selective remat ("dots": keep MXU outputs, recompute the elementwise
    # tail) is also the same program numerically
    dots = make(remat=True, remat_policy="dots")
    ld = [float(dots.train_step(i, rng, x, y)) for i in range(10)]
    np.testing.assert_allclose(ld, losses["plain"][:10], rtol=1e-5)
    with pytest.raises(ValueError, match="remat_policy"):
        make(remat=True, remat_policy="bogus")
    assert abs(losses["bf16"][-1] - losses["plain"][-1]) < 0.1


def test_failure_retry_resumes_from_checkpoint(tmp_path):
    """SURVEY §6.3 driver retry: a mid-epoch failure (input pipeline
    raises, the task-closure-throw analog) is retried from the last
    checkpoint and training still completes; without a checkpoint the
    failure is fatal."""
    import jax

    from bigdl_tpu import nn, optim
    from bigdl_tpu.data.dataset import ArrayDataSet
    from bigdl_tpu.nn.module import Sequential

    from bigdl_tpu.runtime.engine import Engine, init_engine

    init_engine()
    Engine.get().config.failure_retry_interval_s = 0.1  # keep the test fast

    rs = np.random.RandomState(0)
    x = rs.randn(128, 6).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)

    class FlakyDataSet(ArrayDataSet):
        """Raises ONCE partway through the second epoch (epochs are
        1-based like the reference, so that's epoch == 2 — after the
        first every_epoch checkpoint exists)."""

        fired = False

        def batches(self, *a, **kw):
            for i, mb in enumerate(super().batches(*a, **kw)):
                if kw.get("epoch") == 2 and i == 1 \
                        and not FlakyDataSet.fired:
                    FlakyDataSet.fired = True
                    raise RuntimeError("injected input failure")
                yield mb

    def build(ds, ckpt=None):
        model = Sequential([nn.Linear(6, 8), nn.ReLU(), nn.Linear(8, 2)])
        opt = optim.Optimizer(model, ds, nn.CrossEntropyCriterion(),
                              batch_size=32)
        opt.set_optim_method(optim.SGD(learning_rate=0.3))
        opt.set_end_when(optim.Trigger.max_epoch(6))
        if ckpt:
            opt.set_checkpoint(ckpt, optim.Trigger.every_epoch())
        return opt

    FlakyDataSet.fired = False
    trained = build(FlakyDataSet(x, y),
                    str(tmp_path / "ck")).optimize()
    assert FlakyDataSet.fired          # the failure really happened
    res = trained.evaluate(ArrayDataSet(x, y), [optim.Top1Accuracy()], 32)
    assert res[0].result > 0.9, res

    # no checkpoint configured -> failure is fatal (reference semantics)
    FlakyDataSet.fired = False
    with pytest.raises(RuntimeError, match="injected"):
        build(FlakyDataSet(x, y)).optimize()


def test_async_checkpoint_write(tmp_path):
    """async_write=True checkpoints on a background thread; the trained
    run leaves complete, loadable checkpoints and resume works."""
    import jax

    from bigdl_tpu import nn, optim
    from bigdl_tpu.data.dataset import ArrayDataSet
    from bigdl_tpu.nn.module import Sequential
    from bigdl_tpu.optim import checkpoint as ckpt

    rs = np.random.RandomState(0)
    x = rs.randn(96, 5).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int32)
    d = str(tmp_path / "ck")

    def build():
        model = Sequential([nn.Linear(5, 8), nn.ReLU(), nn.Linear(8, 2)])
        opt = optim.Optimizer(model, ArrayDataSet(x, y),
                              nn.CrossEntropyCriterion(), batch_size=32)
        opt.set_optim_method(optim.SGD(learning_rate=0.3))
        opt.set_checkpoint(d, optim.Trigger.every_epoch(), async_write=True)
        return opt

    opt = build()
    opt.set_end_when(optim.Trigger.max_epoch(4))
    trained = opt.optimize()
    last = ckpt.latest_checkpoint(d)
    assert last and last.endswith("ckpt-12")        # 3 batches x 4 epochs
    # the directory is complete (manifest + all blobs)
    import os

    assert {"manifest.json", "params.npz", "opt_state.npz",
            "model_state.npz"} <= set(os.listdir(last))
    # resume from the async-written checkpoint continues cleanly
    opt2 = build()
    opt2.set_end_when(optim.Trigger.max_epoch(6))
    trained2 = opt2.optimize()
    res = trained2.evaluate(ArrayDataSet(x, y), [optim.Top1Accuracy()], 32)
    assert res[0].result > 0.9, res


def test_grad_accumulation_matches_full_batch():
    """accum_steps=k computes the SAME mean gradient as the full batch in
    one pass: identical loss trajectories (stateless model, f32)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.nn.module import Sequential
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.optim.train_step import ShardedParameterStep
    from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

    rs = np.random.RandomState(0)
    x = rs.randn(64, 8).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    mesh = build_mesh(MeshSpec(data=8))

    def make(**kw):
        model = Sequential([nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 2)])
        variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x[:2]))
        return ShardedParameterStep(model, nn.CrossEntropyCriterion(),
                                    SGD(learning_rate=0.2), mesh, variables,
                                    **kw)

    rng = jax.random.PRNGKey(1)
    full = make()
    acc = make(accum_steps=4)          # 8 per device -> 4 microbatches of 2
    for i in range(15):
        lf = float(full.train_step(i, rng, x, y))
        la = float(acc.train_step(i, rng, x, y))
        np.testing.assert_allclose(la, lf, rtol=2e-5,
                                   err_msg=f"step {i}")

    # LARS (layerwise, non-elementwise path) also accepts accumulation
    from bigdl_tpu.optim.optim_method import LarsSGD

    model = Sequential([nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 2)])
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x[:2]))
    lars = ShardedParameterStep(model, nn.CrossEntropyCriterion(),
                                LarsSGD(learning_rate=0.05,
                                        trust_coefficient=0.02),
                                mesh, variables, accum_steps=2)
    l0 = float(lars.train_step(0, rng, x, y))
    assert np.isfinite(l0)


def test_optimizer_exposes_step_knobs():
    """bf16_grads/remat/accum_steps set on the Optimizer reach the step
    engine and training still converges."""
    x, y = synthetic_classification(n=256)
    ds = ArrayDataSet(x, y)
    opt = optim.Optimizer(mlp(), ds, nn.ClassNLLCriterion(), batch_size=64)
    opt.accum_steps = 2
    opt.remat = True
    opt.set_optim_method(optim.Adam(learning_rate=1e-2))
    opt.set_end_when(optim.Trigger.max_epoch(6))
    opt.log_every = 100
    trained = opt.optimize()
    res = trained.evaluate(ds, [optim.Top1Accuracy()])
    assert res[0].result > 0.9, res


def test_ema_weights_in_step():
    """ema_decay keeps a weight EMA inside the jitted step: after training,
    EMA params differ from the live params, track them closely, and
    evaluate as a valid model (the ImageNet EMA-eval recipe)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.nn.module import Sequential
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.optim.train_step import ShardedParameterStep
    from bigdl_tpu.runtime.mesh import MeshSpec, build_mesh

    rs = np.random.RandomState(0)
    x = rs.randn(64, 6).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    mesh = build_mesh(MeshSpec(data=8))
    model = Sequential([nn.Linear(6, 8), nn.ReLU(), nn.Linear(8, 2)])
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x[:2]))
    step = ShardedParameterStep(model, nn.CrossEntropyCriterion(),
                                SGD(learning_rate=0.3), mesh, variables,
                                ema_decay=0.9)
    rng = jax.random.PRNGKey(1)
    for i in range(40):
        loss = step.train_step(i, rng, x, y)
    assert np.isfinite(float(loss))

    live = step.get_variables()["params"]
    ema = step.get_variables(ema=True)["params"]
    lf, _ = jax.flatten_util.ravel_pytree(live)
    ef, _ = jax.flatten_util.ravel_pytree(ema)
    diff = float(jnp.linalg.norm(lf - ef))
    assert diff > 1e-4                       # EMA genuinely lags
    assert diff < 0.5 * float(jnp.linalg.norm(lf))   # ...but tracks

    # EMA params evaluate as a working model
    out, _ = model.apply({"params": ema, "state": {}}, jnp.asarray(x))
    acc = float((jnp.argmax(out, -1) == jnp.asarray(y)).mean())
    assert acc > 0.8, acc


def test_ema_checkpoints_and_survives_resume(tmp_path):
    """EMA state is checkpointed, restored by the retry/resume paths, and
    publicly reachable via TrainedModel.ema_variables."""
    import jax

    from bigdl_tpu.optim import checkpoint as ckpt_mod

    x, y = synthetic_classification(n=256)
    ds = ArrayDataSet(x, y)
    d = str(tmp_path / "ck")

    def run(max_iter):
        Engine.reset()
        opt = optim.Optimizer(mlp(), ds, nn.ClassNLLCriterion(),
                              batch_size=64, seed=3)
        opt.ema_decay = 0.95
        opt.set_optim_method(optim.Adam(learning_rate=1e-2))
        opt.set_end_when(optim.Trigger.max_iteration(max_iter))
        opt.set_checkpoint(d, optim.Trigger.several_iteration(4))
        opt.log_every = 100
        return opt.optimize()

    run(8)
    latest = ckpt_mod.latest_checkpoint(d)
    import os

    assert "ema.npz" in os.listdir(latest)        # EMA blob saved
    trained = run(16)                             # resumes, EMA restored
    ema_vars = trained.ema_variables
    assert ema_vars is not None
    # EMA weights are a working model (not random-init contamination)
    res = trained.evaluate(ds, [optim.Top1Accuracy()])
    trained.set_variables(ema_vars)
    res_ema = trained.evaluate(ds, [optim.Top1Accuracy()])
    assert res_ema[0].result > 0.7, (res[0].result, res_ema[0].result)


def test_async_checkpoint_snapshots_driver_state(tmp_path):
    """ADVICE r3: the async writer must serialize a SNAPSHOT of the driver
    state — the training loop keeps mutating the live dict, and a manifest
    recording a later iteration than its params skews resume."""
    from bigdl_tpu import nn, optim
    from bigdl_tpu.data.dataset import ArrayDataSet
    from bigdl_tpu.nn.module import Sequential

    rs = np.random.RandomState(0)
    x = rs.randn(32, 5).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int32)
    model = Sequential([nn.Linear(5, 4), nn.ReLU(), nn.Linear(4, 2)])
    opt = optim.Optimizer(model, ArrayDataSet(x, y),
                          nn.CrossEntropyCriterion(), batch_size=32)
    opt.set_optim_method(optim.SGD(learning_rate=0.1))
    opt.set_checkpoint(str(tmp_path / "ck"),
                       optim.Trigger.every_epoch(), async_write=True)

    captured = {}

    class CapturingAsync:
        def submit(self, path, step, **kw):
            captured["driver_state"] = kw["driver_state"]

        def wait(self, raise_error=True):
            pass

    opt._ckpt_async = CapturingAsync()
    from bigdl_tpu.optim.train_step import ShardedParameterStep
    from bigdl_tpu.runtime.engine import Engine

    init_vars = model.init(jax.random.PRNGKey(0), x[:1])
    engine = ShardedParameterStep(model, opt.criterion, opt.optim_method,
                                  Engine.get().mesh, init_vars)
    state = {"iteration": 7, "epoch": 1, "loss": np.float32(0.5)}
    opt._save_checkpoint(engine, state)
    state["iteration"] = 99          # training loop moves on
    state["loss"] = np.float32(9.9)
    snap = captured["driver_state"]
    assert snap is not state
    assert snap["iteration"] == 7
    assert float(snap["loss"]) == pytest.approx(0.5)
