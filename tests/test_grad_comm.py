"""Quantized + bucketed gradient collectives (docs/parallelism.md
§Gradient compression & bucketed overlap).

Tier-1 on a 2-device CPU mesh (4 devices where the DCN hop needs a
2x2): blockwise-int8 primitives, the all_to_all reduce-scatter vs the
f32 oracle, int8-vs-fp32 LOSS PARITY (the acceptance test), bucketed ==
monolithic trajectories, the honest wire-dtype ledger, the bf16_grads
deprecation shim, overlap audit, and the MULTICHIP sentinel families.
`make test-collectives` runs exactly this file.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.nn.module import Sequential
from bigdl_tpu.optim.optim_method import SGD, Adam
from bigdl_tpu.optim.train_step import ShardedParameterStep
from bigdl_tpu.parallel import collectives
from bigdl_tpu.runtime.mesh import AXIS_DATA, MeshSpec, build_mesh, \
    shard_map
from jax.sharding import PartitionSpec as P


def _mesh(n):
    return build_mesh(MeshSpec(data=n), devices=jax.devices()[:n])


def _data(n=64, d=8, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, d).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    return x, y


def _step(mesh, x, hidden=16, lr=0.2, seed=0, optim=None, **kw):
    model = Sequential([nn.Linear(x.shape[1], hidden), nn.ReLU(),
                        nn.Linear(hidden, 2)])
    variables = model.init(jax.random.PRNGKey(seed), jnp.asarray(x[:2]))
    return ShardedParameterStep(
        model, nn.CrossEntropyCriterion(),
        optim or SGD(learning_rate=lr, momentum=0.9), mesh, variables,
        **kw)


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------

def test_blockwise_quant_roundtrip_error_bound():
    """Dequantized values sit within half a quantization step of the
    original, per block (symmetric abs-max: step = blockmax/127)."""
    from bigdl_tpu.ops.quantized import (dequantize_blockwise,
                                         quantize_blockwise)

    rs = np.random.RandomState(1)
    x = (rs.randn(3, 256) * np.array([1e-3, 1.0, 50.0])[:, None]) \
        .astype(np.float32)
    q, scales = quantize_blockwise(jnp.asarray(x), 64)
    assert q.dtype == jnp.int8 and q.shape == x.shape
    assert scales.shape == (3, 4)
    back = np.asarray(dequantize_blockwise(q, scales))
    blockmax = np.abs(x.reshape(3, 4, 64)).max(-1)
    tol = (blockmax / 127.0 * 0.5 + 1e-9).repeat(64, -1).reshape(x.shape)
    assert np.all(np.abs(back - x) <= tol + 1e-6 * np.abs(x))

    with pytest.raises(ValueError, match="not a multiple"):
        quantize_blockwise(jnp.zeros((10,)), 64)


def test_quantized_reduce_scatter_matches_fp32_oracle():
    """The all_to_all int8 cycle equals psum_scatter up to blockwise
    quantization error, on a real 4-device axis."""
    n = 4
    mesh = _mesh(n)
    rs = np.random.RandomState(2)
    # per-device distinct gradients, global shape (n, n*w)
    w = 96
    g = rs.randn(n, n * w).astype(np.float32)

    def body(gl):
        # gl: this device's (1, n*w) row -> flat (n*w,)
        flat = gl.reshape(-1)
        ref = jax.lax.psum_scatter(flat, AXIS_DATA, scatter_dimension=0,
                                   tiled=True)
        quant = collectives.reduce_scatter_quantized(
            flat.reshape(n, w), AXIS_DATA, block=32)
        return ref[None], quant[None]

    fn = jax.jit(shard_map(body, mesh=mesh,
                           in_specs=P(AXIS_DATA),
                           out_specs=(P(AXIS_DATA), P(AXIS_DATA))))
    ref, quant = fn(jnp.asarray(g))
    ref, quant = np.asarray(ref).ravel(), np.asarray(quant).ravel()
    # n sources, each within half a step of its own blockmax (<= global
    # abs max / 127 * 0.5 per source)
    tol = n * (np.abs(g).max() / 127.0)
    np.testing.assert_allclose(quant, ref, atol=tol)
    # and it is a real reduction: matches the numpy sum too
    np.testing.assert_allclose(
        ref, g.sum(0).reshape(n, w).ravel(), rtol=1e-5, atol=1e-5)


def test_quantized_psum_matches_and_replicates():
    """psum_quantized equals the f32 psum within tolerance and returns
    the bit-identical vector on EVERY rank (the no-param-bytes-over-DCN
    invariant)."""
    n = 4
    mesh = _mesh(n)
    rs = np.random.RandomState(3)
    v = rs.randn(n, 70).astype(np.float32)  # 70: not block/n aligned

    def body(vl):
        vec = vl.reshape(-1)
        ref = jax.lax.psum(vec, AXIS_DATA)
        quant = collectives.psum_quantized(vec, AXIS_DATA, n, block=16)
        return ref[None], quant[None]

    fn = jax.jit(shard_map(body, mesh=mesh, in_specs=P(AXIS_DATA),
                           out_specs=(P(AXIS_DATA), P(AXIS_DATA))))
    out = fn(jnp.asarray(v))
    ref, quant = np.asarray(out[0]), np.asarray(out[1])
    tol = n * (np.abs(v).max() / 127.0) + np.abs(v.sum(0)).max() / 127.0
    for r in range(n):
        np.testing.assert_allclose(quant[r], ref[0], atol=tol)
        # bit-identical across ranks: every rank gathered the same int8
        np.testing.assert_array_equal(quant[r], quant[0])


def test_bucket_columns_and_wire_bytes():
    cols = collectives.bucket_columns(1000, 4, bucket_bytes=None)
    assert cols == [(0, 1000)]
    cols = collectives.bucket_columns(1000, 4, bucket_bytes=1600,
                                      wire_bytes=4.0)
    assert cols[0] == (0, 100) and cols[-1][1] == 1000
    assert all(c1 - c0 <= 100 for c0, c1 in cols)
    # int8 buckets align to the quantization block
    cols = collectives.bucket_columns(1000, 4, bucket_bytes=1600,
                                      wire_bytes=1.0, block=64)
    assert all((c1 - c0) % 64 == 0 for c0, c1 in cols[:-1])
    # estimators: fp32/bf16 payloads, int8 payload + scales + padding
    assert collectives.rs_wire_bytes(100, 4, "fp32") == 1600
    assert collectives.rs_wire_bytes(100, 4, "bf16") == 800
    assert collectives.rs_wire_bytes(100, 4, "int8", block=64) == \
        4 * 128 + 4 * 2 * 4
    assert collectives.rs_wire_bytes(100, 1, "fp32") == 0
    assert collectives.psum_wire_bytes(100, 2, "fp32") == 800
    # per-chunk clamp: block shrinks to ceil(100/2)=50, no padding blowup
    assert collectives.psum_wire_bytes(100, 2, "int8", block=64) == \
        2 * (2 * 50 + 2 * 1 * 4)
    # a tiny shard never pays more wire than fp32 (the clamp invariant)
    assert collectives.rs_wire_bytes(77, 8, "int8", block=1024) < \
        collectives.rs_wire_bytes(77, 8, "fp32")


# ---------------------------------------------------------------------------
# the train-step cycle
# ---------------------------------------------------------------------------

def test_loss_parity_int8_vs_fp32():
    """ACCEPTANCE (ISSUE 11): training with grad_comm="int8" lands within
    tolerance of the fp32 sync on the same data/seed — 2-device CPU
    mesh, both runs converging."""
    mesh = _mesh(2)
    x, y = _data()
    rng = jax.random.PRNGKey(1)
    fp32 = _step(mesh, x)
    int8 = _step(mesh, x, grad_comm="int8", quant_block=64)
    lf = [float(fp32.train_step(i, rng, x, y)) for i in range(30)]
    lq = [float(int8.train_step(i, rng, x, y)) for i in range(30)]
    assert lf[-1] < 0.5 * lf[0], "fp32 baseline failed to converge"
    assert lq[-1] < 0.5 * lq[0], "int8 run failed to converge"
    tol = max(0.05 * abs(lf[-1]), 0.02)
    assert abs(lq[-1] - lf[-1]) <= tol, (lq[-1], lf[-1], tol)


def test_bucketed_matches_monolithic_fp32():
    """Bucketing changes ONLY the collective structure: the fp32
    trajectory and final params match the monolithic sync (shard
    ownership and optimizer-state layout are identical)."""
    mesh = _mesh(2)
    x, y = _data()
    rng = jax.random.PRNGKey(1)
    mono = _step(mesh, x, optim=Adam(learning_rate=0.02))
    buck = _step(mesh, x, optim=Adam(learning_rate=0.02),
                 comm_bucket_bytes=256)
    assert buck.comm_buckets > 1
    lm = [float(mono.train_step(i, rng, x, y)) for i in range(10)]
    lb = [float(buck.train_step(i, rng, x, y)) for i in range(10)]
    np.testing.assert_allclose(lb, lm, rtol=2e-4, atol=1e-6)
    pm = jax.tree_util.tree_leaves(mono.get_variables()["params"])
    pb = jax.tree_util.tree_leaves(buck.get_variables()["params"])
    for a, b in zip(pm, pb):
        np.testing.assert_allclose(b, a, rtol=2e-4, atol=1e-6)


def test_int8_bucketed_bundle_with_clip_and_ema():
    """The quantized bucketed cycle composes with the K-step bundle,
    gradient clipping, EMA and accumulation — finite losses, positive
    grad norms, K=1+2 byte-identical to K=3."""
    from bigdl_tpu.optim.train_step import GradientClipping

    mesh = _mesh(2)
    x, y = _data()

    def make():
        return _step(mesh, x, grad_comm="int8", quant_block=32,
                     comm_bucket_bytes=256, ema_decay=0.9, accum_steps=2,
                     clip=GradientClipping(l2_norm=1.0))

    a, b = make(), make()
    a.set_step_seed(3)
    b.set_step_seed(3)
    xd, yd = a.shard_batch(x), a.shard_batch(y)
    la1, g1 = a.train_bundle_device(0, [xd], [yd])
    la2, _ = a.train_bundle_device(1, [xd, xd], [yd, yd])
    lb, gb = b.train_bundle_device(0, [xd, xd, xd], [yd, yd, yd])
    traj = np.concatenate([np.ravel(la1), np.ravel(la2)])
    np.testing.assert_array_equal(traj.astype(np.float32),
                                  np.ravel(lb).astype(np.float32))
    assert np.all(np.isfinite(np.ravel(lb)))
    assert np.all(np.ravel(gb) > 0)


def test_int8_over_dcn_hop():
    """Multislice: the int8 cycle runs the quantized hierarchical psum
    over the dcn axis, trains in parity with fp32, and the DCN ledger
    shrinks accordingly."""
    mesh = build_mesh(MeshSpec(data=2, dcn_data=2),
                      devices=jax.devices()[:4])
    x, y = _data()
    rng = jax.random.PRNGKey(1)
    fp32 = _step(mesh, x)
    int8 = _step(mesh, x, grad_comm="int8", quant_block=32)
    lf = [float(fp32.train_step(i, rng, x, y)) for i in range(20)]
    lq = [float(int8.train_step(i, rng, x, y)) for i in range(20)]
    assert abs(lq[-1] - lf[-1]) <= max(0.05 * abs(lf[-1]), 0.02)
    assert 0 < int8.dcn_bytes_per_step < fp32.dcn_bytes_per_step
    assert int8.grad_sync_ici_bytes_per_step < \
        fp32.grad_sync_ici_bytes_per_step


def test_ledger_reports_actual_wire_dtype():
    """The collective-bytes ledger counts what actually crosses the wire:
    bf16 halves the gradient bytes, int8 counts payload + per-block f32
    scales (+ padding), and the param gather stays f32 in every mode."""
    from bigdl_tpu.obs.cost import collective_ledger

    mesh = _mesh(2)
    x, _ = _data(d=8)
    fp32 = _step(mesh, x, hidden=256)
    bf16 = _step(mesh, x, hidden=256, grad_comm="bf16")
    int8 = _step(mesh, x, hidden=256, grad_comm="int8", quant_block=64)
    n_pad, shard = fp32.n_pad, fp32.shard_size

    assert fp32.grad_sync_ici_bytes_per_step == n_pad * 4
    assert bf16.grad_sync_ici_bytes_per_step == n_pad * 2
    wq = -(-shard // 64) * 64
    assert int8.grad_sync_ici_bytes_per_step == \
        2 * wq + 2 * (wq // 64) * 4
    for s in (fp32, bf16, int8):
        assert s.param_sync_ici_bytes_per_step == n_pad * 4
        led = collective_ledger(s)
        assert led["grad_comm"] == s.grad_comm
        assert led["grad_ici_bytes_per_step"] == \
            s.grad_sync_ici_bytes_per_step
        assert led["param_ici_bytes_per_step"] == n_pad * 4
        assert led["ici_bytes_per_step"] == \
            led["grad_ici_bytes_per_step"] + led["param_ici_bytes_per_step"]
    # the acceptance ratio on a realistically-sized layer stack: >= 3x
    # fewer gradient-sync bytes than fp32
    assert fp32.grad_sync_ici_bytes_per_step / \
        int8.grad_sync_ici_bytes_per_step >= 3.0


def test_loss_parity_param_comm_int8():
    """ACCEPTANCE (ISSUE 19): the int8 delta param gather
    (``param_comm="int8"``) lands within the same loss-parity tolerance
    as the fp32 gather, alone and composed with the quantized gradient
    wire."""
    mesh = _mesh(2)
    x, y = _data()
    rng = jax.random.PRNGKey(1)
    fp32 = _step(mesh, x)
    q = _step(mesh, x, param_comm="int8", quant_block=64)
    lf = [float(fp32.train_step(i, rng, x, y)) for i in range(30)]
    lq = [float(q.train_step(i, rng, x, y)) for i in range(30)]
    assert lf[-1] < 0.5 * lf[0], "fp32 baseline failed to converge"
    assert lq[-1] < 0.5 * lq[0], "param_comm=int8 failed to converge"
    assert abs(lq[-1] - lf[-1]) <= max(0.05 * abs(lf[-1]), 0.02)
    # the fully-quantized cycle (int8 gradients AND int8 param deltas)
    full = _step(mesh, x, grad_comm="int8", param_comm="int8",
                 quant_block=64)
    lfull = [float(full.train_step(i, rng, x, y)) for i in range(30)]
    assert lfull[-1] < 0.5 * lfull[0], "fully-quantized cycle diverged"
    assert abs(lfull[-1] - lf[-1]) <= max(0.05 * abs(lf[-1]), 0.03)


def test_param_comm_ledger_and_validation():
    """param_comm="int8" prices the param gather in its actual wire
    dtype (payload + scales), fp32 stays the classic n_pad * 4, the
    pure layout math mirrors the engine, bad modes are rejected."""
    from bigdl_tpu.obs.cost import collective_ledger

    mesh = _mesh(2)
    x, _ = _data(d=8)
    fp32 = _step(mesh, x, hidden=256)
    q = _step(mesh, x, hidden=256, param_comm="int8", quant_block=64)
    n_pad, shard = fp32.n_pad, fp32.shard_size
    assert fp32.param_sync_ici_bytes_per_step == n_pad * 4
    wq = -(-shard // 64) * 64
    assert q.param_sync_ici_bytes_per_step == 2 * wq + 2 * (wq // 64) * 4
    assert q.param_sync_ici_bytes_per_step < \
        fp32.param_sync_ici_bytes_per_step / 3
    led = collective_ledger(q)
    assert led["param_comm"] == "int8"
    assert led["param_ici_bytes_per_step"] == \
        q.param_sync_ici_bytes_per_step
    assert led["ici_bytes_per_step"] == \
        led["grad_ici_bytes_per_step"] + led["param_ici_bytes_per_step"]
    ll = collectives.layout_ledger(fp32.n_real, 2, param_comm="int8",
                                   block=64)
    assert ll["param_comm"] == "int8"
    assert ll["param_sync_ici_bytes_per_step"] == \
        q.param_sync_ici_bytes_per_step
    # estimator: fp32 payload, int8 payload + scales + block padding
    assert collectives.ag_wire_bytes(100, 4, "fp32") == 1600
    assert collectives.ag_wire_bytes(100, 4, "int8", block=64) == \
        4 * 128 + 4 * 2 * 4
    assert collectives.ag_wire_bytes(100, 1, "int8") == 0
    with pytest.raises(ValueError, match="param_comm"):
        _step(mesh, x, param_comm="bf16")
    assert _step(mesh, x, param_comm=" INT8 ").param_comm == "int8"


def test_param_comm_overlap_probe():
    """The comm-only probe mirrors the int8 delta gather's wire shape,
    so the overlap audit times the same collectives the step runs."""
    mesh = _mesh(2)
    x, y = _data()
    s = _step(mesh, x, grad_comm="int8", param_comm="int8",
              quant_block=32)
    xd, yd = s.shard_batch(x), s.shard_batch(y)
    ov = s.measure_overlap(xd, yd, steps=2)
    assert ov["collective_s"] > 0
    assert 0.0 <= ov["overlap_efficiency"] <= 1.0
    assert np.isfinite(float(s.train_step(0, jax.random.PRNGKey(0),
                                          x, y)))


def test_invalid_grad_comm_rejected():
    mesh = _mesh(2)
    x, _ = _data()
    with pytest.raises(ValueError, match="grad_comm"):
        _step(mesh, x, grad_comm="int4")
    # spellings normalize like BIGDL_TPU_GRAD_COMM does, at every entry
    assert _step(mesh, x, grad_comm="INT8").grad_comm == "int8"
    assert _step(mesh, x, grad_comm=" Bf16 ").grad_comm == "bf16"


def test_bucketing_rejects_non_elementwise_state():
    """Per-bucket updates slice every optimizer-state leaf like the param
    slice; an OptimMethod whose state is not strictly per-element must be
    rejected LOUDLY when bucketing is on (it would silently diverge)."""
    from bigdl_tpu.optim.optim_method import OptimMethod

    class ScalarStateSGD(OptimMethod):
        lr = 0.1

        def init_state(self, params):
            return {"gsq_mean": jnp.asarray(0.0, jnp.float32)}

        def update(self, step, grads, params, state):
            s = 0.9 * state["gsq_mean"] + 0.1 * jnp.mean(grads * grads)
            return params - self.lr * grads, {"gsq_mean": s}

    mesh = _mesh(2)
    x, _ = _data()
    with pytest.raises(ValueError, match="per-element"):
        _step(mesh, x, optim=ScalarStateSGD(), comm_bucket_bytes=256)


def test_measure_overlap_audit():
    """The overlap audit returns a sane decomposition: all timings
    positive, exposed <= total collective, efficiency in [0, 1]."""
    mesh = _mesh(2)
    x, y = _data()
    s = _step(mesh, x, grad_comm="int8", quant_block=32,
              comm_bucket_bytes=256)
    xd, yd = s.shard_batch(x), s.shard_batch(y)
    ov = s.measure_overlap(xd, yd, steps=3)
    assert ov["step_s"] > 0 and ov["compute_s"] > 0
    assert ov["collective_s"] > 0
    assert 0.0 <= ov["overlap_efficiency"] <= 1.0
    assert ov["exposed_collective_s"] >= 0.0
    assert ov["grad_comm"] == "int8" and ov["comm_buckets"] >= 1
    # the audit never consumes training state: stepping still works
    assert np.isfinite(float(s.train_step(0, jax.random.PRNGKey(0), x, y)))


# ---------------------------------------------------------------------------
# deprecation shim + config plumbing
# ---------------------------------------------------------------------------

def test_bf16_grads_deprecation_shim():
    """bf16_grads=True keeps working: mapped to grad_comm="bf16" with a
    DeprecationWarning, same halved collective bytes, and the legacy
    .bf16_grads attribute still reads True for old callers."""
    mesh = _mesh(2)
    x, y = _data()
    with pytest.warns(DeprecationWarning, match="bf16_grads"):
        shim = _step(mesh, x, bf16_grads=True)
    assert shim.grad_comm == "bf16" and shim.bf16_grads
    modern = _step(mesh, x, grad_comm="bf16")
    assert shim.collective_bytes_per_step == \
        modern.collective_bytes_per_step
    # explicit grad_comm wins over the legacy flag
    with pytest.warns(DeprecationWarning):
        both = _step(mesh, x, bf16_grads=True, grad_comm="int8")
    assert both.grad_comm == "int8" and not both.bf16_grads
    assert np.isfinite(float(shim.train_step(0, jax.random.PRNGKey(0),
                                             x, y)))


def test_optimizer_grad_comm_resolution():
    """Optimizer-level resolution: explicit grad_comm > deprecated
    bf16_grads (warned) > EngineConfig.grad_comm > fp32."""
    from bigdl_tpu import optim
    from bigdl_tpu.data import ArrayDataSet
    from bigdl_tpu.runtime.engine import EngineConfig

    x, y = _data()
    opt = optim.Optimizer(Sequential([nn.Linear(8, 2)]),
                          ArrayDataSet(x, y), nn.CrossEntropyCriterion())
    cfg = EngineConfig()
    assert opt._resolved_grad_comm(cfg) == "fp32"
    cfg.grad_comm = "int8"
    assert opt._resolved_grad_comm(cfg) == "int8"
    opt.bf16_grads = True
    with pytest.warns(DeprecationWarning, match="bf16_grads"):
        assert opt._resolved_grad_comm(cfg) == "bf16"
    opt.grad_comm = "int8"
    with pytest.warns(DeprecationWarning, match="wins"):
        assert opt._resolved_grad_comm(cfg) == "int8"


def test_engineconfig_grad_comm_env(monkeypatch):
    from bigdl_tpu.runtime.engine import EngineConfig

    monkeypatch.setenv("BIGDL_TPU_GRAD_COMM", "INT8")
    monkeypatch.setenv("BIGDL_TPU_COMM_BUCKET_BYTES", "1048576")
    cfg = EngineConfig.from_env()
    assert cfg.grad_comm == "int8"
    assert cfg.comm_bucket_bytes == 1048576


def test_optimizer_int8_run_exports_gauges(monkeypatch):
    """End-to-end driver run under grad_comm="int8": converges, and one
    /metrics snapshot carries the honest wire ledger (grad vs param
    split, bucket count) plus the overlap-audit gauges when the env
    opts in."""
    from bigdl_tpu import optim
    from bigdl_tpu.data import ArrayDataSet

    monkeypatch.setenv("BIGDL_TPU_MEASURE_OVERLAP", "1")
    x, y = _data(n=64)
    model = Sequential([nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2),
                        nn.LogSoftMax()])
    opt = optim.Optimizer(model, ArrayDataSet(x, y),
                          nn.ClassNLLCriterion(), batch_size=32)
    opt.grad_comm = "int8"
    opt.quant_block = 64
    opt.set_optim_method(optim.Adam(learning_rate=1e-2))
    opt.set_end_when(optim.Trigger.max_iteration(6))
    opt.log_every = 3
    opt.optimize()
    g = opt.metrics.snapshot()["gauges"]
    assert g["train.grad_comm_buckets"] >= 1
    grad_b = g["train.collective_grad_ici_bytes_per_step"]
    param_b = g["train.collective_param_ici_bytes_per_step"]
    assert 0 < grad_b < param_b  # int8 payload < f32 gather
    assert g["train.collective_ici_bytes_per_step"] == grad_b + param_b
    assert 0.0 <= g["train.comm_overlap_efficiency"] <= 1.0
    assert g["train.comm_exposed_collective_s"] >= 0.0


# ---------------------------------------------------------------------------
# sentinel: the MULTICHIP families
# ---------------------------------------------------------------------------

def test_sentinel_gates_gradcomm_and_multichip_bytes():
    from bigdl_tpu.obs import sentinel

    gradcomm_row = {
        "metric": "multichip_grad_bytes_reduction", "value": 3.98,
        "grad_bytes_reduction_vs_fp32": 3.98,
        "grad_sync_ici_bytes_per_step": 25658880.0,
        "grad_sync_dcn_bytes_per_step": 12829440.0,
    }
    rows = {r.family: r for r in sentinel.normalize(gradcomm_row, "t")}
    assert rows["multichip_grad_bytes_reduction"].direction == \
        sentinel.HIGHER
    assert rows["multichip_grad_sync_ici_bytes_per_step"].direction == \
        sentinel.LOWER
    assert rows["multichip_grad_sync_dcn_bytes_per_step"].value == \
        12829440.0

    large_row = {"modes": {"dp_resnet50_multislice": {
        "ici_collective_bytes_per_step": 204456256,
        "dcn_collective_bytes_per_step": 51114064}}, "ok": True}
    rows = {r.family: r for r in sentinel.normalize(large_row, "t")}
    assert rows["multichip_ici_bytes_per_step"].value == 204456256
    assert rows["multichip_ici_bytes_per_step"].direction == sentinel.LOWER

    # the committed history gates a fresh row whose wire re-inflates
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    history = sentinel.load_history(repo)
    assert "multichip_grad_bytes_reduction" in history
    base = sentinel.baseline_for("multichip_grad_sync_ici_bytes_per_step",
                                 history)
    fat = sentinel.Row("multichip_grad_sync_ici_bytes_per_step",
                       base.value * 1.25, sentinel.LOWER, "synthetic")
    v = sentinel.check_row(fat, history)
    assert v is not None and v.regressed
    ok = sentinel.Row("multichip_grad_sync_ici_bytes_per_step",
                      base.value, sentinel.LOWER, "synthetic")
    assert not sentinel.check_row(ok, history).regressed
