"""Observability subsystem specs (docs/observability.md).

Tier-1 coverage for the obs package and its wiring: span tracer +
Chrome-trace export joined to serving requests by request id, Prometheus
text exposition (sanitization, counter/summary/histogram lines parse),
log-bucketed latency percentiles, the crash flight recorder under injected
faults, the Metrics read-path lock, SummaryWriter lifecycle, TFRecord
framing round-trip, and the profile_dir wiring."""

import json
import os
import re
import signal
import struct
import threading
import time
from urllib import request as urlreq

import numpy as np
import pytest

from bigdl_tpu.obs import flight, trace
from bigdl_tpu.obs.export import (MetricsServer, render_prometheus,
                                  sanitize_metric_name)
from bigdl_tpu.obs.flight import FlightRecorder
from bigdl_tpu.obs.hist import LogHistogram
from bigdl_tpu.optim.metrics import Metrics, SummaryWriter, global_metrics
from bigdl_tpu.resilience import faults
from bigdl_tpu.resilience.faults import FaultSpec
from bigdl_tpu.serving import (HttpFrontend, InferenceModel, ServingConfig,
                               ServingServer)


@pytest.fixture(autouse=True)
def _clean_obs():
    faults.clear()
    flight.global_recorder().clear()
    yield
    faults.clear()
    trace.disable()


def _echo(x):
    return np.asarray(x) * 2.0


# ---------------------------------------------------------------------------
# log-bucketed histogram
# ---------------------------------------------------------------------------

def test_log_histogram_percentiles_bounded_error():
    h = LogHistogram()
    rng = np.random.RandomState(0)
    samples = rng.exponential(0.05, size=5000)
    for v in samples:
        h.observe(v)
    assert h.n == 5000
    assert h.sum == pytest.approx(float(samples.sum()))
    for q in (50, 95, 99):
        exact = float(np.percentile(samples, q))
        approx = h.percentile(q)
        # log-bucketed with growth 2: at most one bucket (2x) of error
        assert exact / 2 <= approx <= exact * 2, (q, exact, approx)
    assert h.percentile(100) == pytest.approx(h.max)


def test_log_histogram_empty_percentile_is_nan():
    """No data must be distinguishable from a 0.0s latency: every
    percentile of an empty histogram is NaN, not 0 and not a bucket
    bound."""
    h = LogHistogram()
    for q in (0, 50, 99, 100):
        assert np.isnan(h.percentile(q)), q
    assert all(np.isnan(v) for v in h.quantiles().values())
    # snapshot of an empty histogram still renders (min/max report 0)
    snap = h.snapshot()
    assert snap["n"] == 0 and snap["min"] == 0.0 and snap["max"] == 0.0


def test_log_histogram_single_observation():
    """One sample: every percentile reports that sample (its bucket's
    upper bound clamps to the observed max == the sample)."""
    h = LogHistogram()
    h.observe(0.037)
    for q in (1, 50, 99, 100):
        assert h.percentile(q) == pytest.approx(0.037), q
    assert h.quantiles()["p50"] == pytest.approx(0.037)


def test_log_histogram_overflow_and_bad_samples():
    h = LogHistogram(base=1e-4, growth=2.0, n_buckets=4)
    h.observe(1e9)      # beyond the last bound: overflow bucket
    h.observe(-5.0)     # clock bug: clamped, never corrupts
    h.observe(float("nan"))
    h.observe(float("inf"))  # timeout sentinel: OVERFLOW, never underflow
    assert h.n == 4
    assert h.counts[-1] == 2
    assert h.counts[0] == 2
    assert h.sum == pytest.approx(1e9)  # inf kept out of the mean
    snap = h.snapshot()
    assert len(snap["bounds"]) == len(snap["counts"]) - 1


# ---------------------------------------------------------------------------
# Metrics registry: locking, histograms, mirroring
# ---------------------------------------------------------------------------

def test_metrics_reads_take_lock_and_never_mutate():
    m = Metrics()
    m.add("t", 1.0)
    # a read of a missing key must not insert it (the defaultdict-indexing
    # race this PR fixes) and must not raise
    assert m.mean("missing") == 0.0
    assert m.counter("missing") == 0.0
    assert "missing" not in m.sums and "missing" not in m.counts
    assert "missing" not in m.counters


def test_metrics_concurrent_read_write():
    m = Metrics()
    stop = threading.Event()
    errors = []

    def writer(i):
        while not stop.is_set():
            m.add(f"timer.{i}", 0.001)
            m.inc(f"counter.{i}")
            m.observe(f"hist.{i}", 0.01)

    def reader():
        try:
            while not stop.is_set():
                m.summary()
                m.mean("timer.0")
                m.counter("counter.1")
                m.snapshot()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(3)]
    threads += [threading.Thread(target=reader) for _ in range(3)]
    [t.start() for t in threads]
    time.sleep(0.3)
    stop.set()
    [t.join(10) for t in threads]
    assert not errors, errors


def test_metrics_counters_mirror_into_global():
    m = Metrics()
    g = global_metrics()
    base = g.counter("obs_test.mirrored_total")
    m.inc("obs_test.mirrored_total", 3)
    m.observe("obs_test.mirrored_hist_s", 0.02)
    assert m.counter("obs_test.mirrored_total") == 3
    assert g.counter("obs_test.mirrored_total") == base + 3
    assert g.percentile("obs_test.mirrored_hist_s", 50) > 0


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------

def test_sanitize_metric_name():
    assert sanitize_metric_name("serving.shed_requests") == \
        "serving_shed_requests"
    assert sanitize_metric_name("retries_by_cause.poisoned-batch") == \
        "retries_by_cause_poisoned_batch"
    assert sanitize_metric_name("9lives") == "_9lives"
    valid = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    for ugly in ("a b", "a{b}", 'a"b"', "Ж.metric", ""):
        assert valid.match(sanitize_metric_name(ugly)), ugly


_LINE = re.compile(
    r"^(?:# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (?:counter|gauge|summary|histogram)"
    r"|# HELP [a-zA-Z_:][a-zA-Z0-9_:]* \S.*"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{le=\"[^\"]+\"\})? "
    r"(?:[0-9.eE+-]+|\+Inf|NaN))$")


def test_render_prometheus_text_format_parses():
    m = Metrics()
    m.inc("serving.shed_requests", 2)
    m.add("step_dispatch", 0.25)
    m.add("step_dispatch", 0.35)
    for v in (0.001, 0.002, 0.004, 0.4):
        m.observe("serving.latency_s", v)
    text = render_prometheus(m)
    for line in text.strip().split("\n"):
        assert _LINE.match(line), f"unparseable exposition line: {line!r}"
    assert "# TYPE serving_shed_requests counter" in text
    assert "serving_shed_requests 2.0" in text
    assert "step_dispatch_sum 0.6" in text
    assert "step_dispatch_count 2" in text
    # histogram: cumulative bucket lines, +Inf equals the sample count
    buckets = re.findall(
        r'serving_latency_s_bucket\{le="([^"]+)"\} (\d+)', text)
    assert len(buckets) > 2
    counts = [int(c) for _, c in buckets]
    assert counts == sorted(counts), "bucket counts must be cumulative"
    assert buckets[-1][0] == "+Inf" and counts[-1] == 4
    assert "serving_latency_s_count 4" in text


def test_render_prometheus_help_lines_and_type_once():
    """# HELP rides next to # TYPE (describe() strings win over the
    framework catalog), and a family header is emitted at most once per
    scrape even when two dotted names sanitize to the same family."""
    m = Metrics()
    m.inc("obs_help.requests_total", 1)
    m.describe("obs_help.requests_total", "requests seen by the test")
    for v in (0.001, 0.4):
        m.observe("serving.latency_s", v)  # catalog help, no describe()
    # two names that collide after sanitization: the family header must
    # not be re-declared for the second one
    m.gauge("collide.name", 1.0)
    m.gauge("collide_name", 2.0)
    text = render_prometheus(m)
    assert "# HELP obs_help_requests_total requests seen by the test" \
        in text
    assert "# HELP serving_latency_s " in text
    help_then_type = text.index("# HELP obs_help_requests_total")
    assert text.index("# TYPE obs_help_requests_total counter") \
        > help_then_type
    assert text.count("# TYPE collide_name gauge") == 1
    # ... and the losing name's SAMPLE is dropped too: two series with
    # identical name+labels would fail the whole scrape at a real
    # Prometheus, which is worse than losing the shadowed series
    samples = [l for l in text.splitlines()
               if l.startswith("collide_name ")]
    assert samples == ["collide_name 1.0"]
    # every line still parses
    for line in text.strip().split("\n"):
        assert _LINE.match(line), f"unparseable exposition line: {line!r}"


def test_render_prometheus_new_perf_gauge_lines_parse():
    """The attribution/MFU/collective families render as valid exposition
    a Prometheus scraper accepts."""
    m = Metrics()
    m.gauge("train.mfu", 0.187)
    m.gauge("train.flops_per_step", 3.2e12)
    m.gauge("train.collective_ici_bytes_per_step", 204e6)
    m.inc("train.collective_ici_bytes_total", 204e6 * 10)
    for v in (0.01, 0.02):
        m.observe("train.attr.device_s", v)
    text = render_prometheus(m)
    for line in text.strip().split("\n"):
        assert _LINE.match(line), f"unparseable exposition line: {line!r}"
    assert "# TYPE train_mfu gauge" in text
    assert re.search(r"^train_mfu 0\.187$", text, re.M)
    assert "# HELP train_mfu " in text
    assert "# TYPE train_attr_device_s histogram" in text
    assert 'train_attr_device_s_bucket{le="+Inf"} 2' in text
    assert re.search(r"^train_collective_ici_bytes_total 2040000000\.0$",
                     text, re.M)


def test_metrics_server_concurrent_scrape_with_mutation():
    """Scrapes race registry mutation: every scrape must parse (snapshot
    consistency under the lock) and a counter must never move backwards
    between successive scrapes."""
    m = Metrics()
    srv = MetricsServer(m).start()
    stop = threading.Event()
    errors = []

    def mutate(i):
        n = 0
        while not stop.is_set():
            m.inc("scrape_race.counter_total")
            m.gauge(f"scrape_race.gauge_{i}", n)
            m.observe("scrape_race.hist_s", 0.001 * (n % 7 + 1))
            m.add("scrape_race.timer", 0.001)
            n += 1

    threads = [threading.Thread(target=mutate, args=(i,)) for i in range(3)]
    [t.start() for t in threads]
    try:
        last = -1.0
        for _ in range(20):
            with urlreq.urlopen(srv.url, timeout=10) as resp:
                text = resp.read().decode()
            for line in text.strip().split("\n"):
                assert _LINE.match(line), \
                    f"unparseable line under mutation: {line!r}"
            got = re.search(r"^scrape_race_counter_total ([0-9.eE+]+)$",
                            text, re.M)
            if got:
                v = float(got.group(1))
                assert v >= last, "counter moved backwards between scrapes"
                last = v
        assert last > 0, "mutators never landed a counter"
    finally:
        stop.set()
        [t.join(10) for t in threads]
        srv.stop()


def test_metrics_server_scrape():
    m = Metrics()
    m.inc("standalone.scrapes_total")
    srv = MetricsServer(m).start()
    try:
        with urlreq.urlopen(srv.url, timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode()
        assert "standalone_scrapes_total 1.0" in body
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------

def test_tracer_span_tree_and_chrome_export(tmp_path):
    t = trace.enable()
    with trace.span("outer", step=7) as outer:
        with trace.span("inner") as inner:
            assert trace.current_span() is inner
            inner.set_attribute("late", "yes")
        assert trace.current_span() is outer
    assert trace.current_span() is None
    spans = {s.name: s for s in t.spans()}
    assert spans["inner"].parent_id == spans["outer"].span_id
    assert spans["inner"].trace_id == spans["outer"].trace_id
    assert spans["outer"].attrs["step"] == 7
    assert spans["inner"].attrs["late"] == "yes"
    path = t.export_chrome_trace(str(tmp_path / "trace.json"))
    doc = json.load(open(path))
    assert doc["traceEvents"], "chrome trace must contain events"
    for evt in doc["traceEvents"]:
        assert evt["ph"] == "X"
        assert {"name", "ts", "dur", "pid", "tid", "args"} <= set(evt)
    inner_evt = next(e for e in doc["traceEvents"] if e["name"] == "inner")
    assert inner_evt["args"]["parent_id"] == spans["outer"].span_id


def test_tracer_disabled_is_noop():
    trace.disable()
    with trace.span("nothing", a=1) as sp:
        sp.set_attribute("b", 2)
    assert trace.get() is None


def test_tracer_records_exceptions():
    t = trace.enable()
    with pytest.raises(ValueError):
        with trace.span("boom"):
            raise ValueError("no")
    (s,) = t.spans()
    assert "ValueError" in s.attrs["error"]


def test_span_end_exports_before_context_exit():
    # handlers whose LAST write signals completion end the span first,
    # so a reader reacting to that write finds it exported; the context
    # exit then must not double-record or clobber the recorded end time
    t = trace.enable()
    with trace.span("early") as sp:
        sp.end()
        assert trace.current_span() is None
        assert [s.name for s in t.spans()] == ["early"]
        recorded_end = sp.end_s
    assert len(t.spans()) == 1, "context exit double-recorded the span"
    assert sp.end_s == recorded_end
    trace.disable()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_recorder_ring_is_bounded(tmp_path):
    rec = FlightRecorder(capacity=8)
    for i in range(20):
        rec.record("evt", i=i)
    events = rec.snapshot()
    assert len(events) == 8
    assert [e["i"] for e in events] == list(range(12, 20))
    assert rec.events_total == 20
    path = rec.dump(str(tmp_path / "fl.jsonl"))
    lines = [json.loads(x) for x in open(path)]
    assert lines[0]["kind"] == "flight_dump"
    assert lines[0]["events"] == 8 and lines[0]["events_total"] == 20
    evts = [x for x in lines if x["kind"] == "evt"]
    assert [x["i"] for x in evts] == list(range(12, 20))


def test_flight_dump_carries_metrics_snapshot(tmp_path):
    """The dump includes final metric state (counters + gauges), so a
    post-mortem shows how far the job got — not just the event ring."""
    global_metrics().inc("obs_test.flight_counter_total", 7)
    global_metrics().gauge("obs_test.flight_gauge", 3.5)
    rec = FlightRecorder(capacity=4)
    rec.record("evt", i=1)
    path = rec.dump(str(tmp_path / "fl2.jsonl"))
    lines = [json.loads(x) for x in open(path)]
    snap = next(x for x in lines if x["kind"] == "metrics_snapshot")
    assert snap["counters"]["obs_test.flight_counter_total"] >= 7
    assert snap["gauges"]["obs_test.flight_gauge"] == 3.5
    # snapshot rides between the header and the event ring
    assert lines[0]["kind"] == "flight_dump"
    assert [x for x in lines if x["kind"] == "evt"]


def test_flight_recorder_signal_dump(tmp_path):
    rec = FlightRecorder(capacity=16, path=str(tmp_path / "sig.jsonl"))
    rec.record("before_signal")
    old = signal.signal(signal.SIGUSR1, lambda *a: None)
    try:
        rec.install(signals=(signal.SIGUSR1,))
        os.kill(os.getpid(), signal.SIGUSR1)
        deadline = time.time() + 5
        while not os.path.exists(rec.path) and time.time() < deadline:
            time.sleep(0.01)
        lines = [json.loads(x) for x in open(rec.path)]
        kinds = [x["kind"] for x in lines]
        assert "before_signal" in kinds and "signal" in kinds
        assert "signal" in lines[0]["reason"]
    finally:
        signal.signal(signal.SIGUSR1, old)


def test_flight_records_injected_fault_and_recovery():
    """Acceptance: under an injected serving fault, the dump shows the
    fault events and the degradation/recovery transitions that followed."""
    faults.install([FaultSpec(point="serving_predict_fail", every=1,
                              max_fires=3)])
    srv = ServingServer(
        InferenceModel(predict_fn=_echo),
        ServingConfig(batch_size=1, batch_timeout_s=0.0,
                      degraded_after_failures=3,
                      degraded_probe_interval_s=0.05)).start()
    try:
        x = np.ones((1, 2), np.float32)
        # three failed batches: the injected fault fires on each, the
        # third flips the server DEGRADED (no fallback -> shedding)
        for _ in range(3):
            rid = srv.enqueue(x)
            with pytest.raises(Exception):
                srv.query(rid, timeout=10)
        deadline = time.time() + 5
        while not srv.degraded and time.time() < deadline:
            time.sleep(0.01)
        assert srv.degraded
        # fault plan exhausted: the half-open probe goes through predict
        # successfully and clears degradation
        out = None
        deadline = time.time() + 10
        while out is None and time.time() < deadline:
            try:
                rid = srv.enqueue(x)
                out = srv.query(rid, timeout=10)
            except Exception:
                time.sleep(0.06)
        assert out is not None and not srv.degraded
    finally:
        srv.stop()
    kinds = [e["kind"] for e in flight.global_recorder().snapshot()]
    assert kinds.count("fault_injected") == 3
    assert "serving_degraded" in kinds
    assert "serving_recovered" in kinds
    assert kinds.index("fault_injected") \
        < kinds.index("serving_degraded") < kinds.index("serving_recovered")


def test_flight_records_breaker_transitions():
    from bigdl_tpu.serving.pool import _Breaker

    b = _Breaker(fail_threshold=2, cooldown_s=0.05, name="worker-9")
    b.record_failure()
    b.record_failure()          # trips open
    assert b.state == "open"
    time.sleep(0.06)
    assert b.try_acquire()      # half-open probe admitted
    b.record_success()          # probe closes it
    kinds = [(e["kind"], e.get("breaker"))
             for e in flight.global_recorder().snapshot()
             if e["kind"].startswith("breaker_")]
    assert kinds == [("breaker_open", "worker-9"),
                     ("breaker_half_open", "worker-9"),
                     ("breaker_closed", "worker-9")]


# ---------------------------------------------------------------------------
# SummaryWriter lifecycle + TFRecord framing
# ---------------------------------------------------------------------------

def test_summary_writer_context_manager_closes_both_sinks(tmp_path):
    with SummaryWriter(str(tmp_path), "train") as sw:
        for i in range(3):
            sw.add_scalar("loss", 1.0 / (i + 1), i)
        tb_path = sw._tb.path
    # exit closed BOTH sinks (the TensorBoard writer's tail events were
    # the bug); close() again is a no-op, not a ValueError
    assert sw._f.closed and sw._tb._f.closed
    sw.close()
    from bigdl_tpu.utils.tbwriter import read_scalars

    recs = read_scalars(tb_path)
    assert [(s, t) for s, t, _ in recs] == [(0, "loss"), (1, "loss"),
                                            (2, "loss")]
    assert sw.read_scalar("loss") == [(0, 1.0), (1, 0.5),
                                      (2, pytest.approx(1 / 3))]


def test_tbwriter_tfrecord_masked_crc_framing(tmp_path):
    """Every record in the event file must carry valid masked-crc32c
    framing — stock TensorBoard silently drops records that don't."""
    from bigdl_tpu.utils import tbwriter

    w = tbwriter.TensorBoardWriter(str(tmp_path))
    w.add_scalar("acc", 0.75, 1)
    w.add_histogram("params", np.arange(100.0), 1)
    w.close()
    data = open(w.path, "rb").read()
    pos, records = 0, 0
    while pos < len(data):
        header = data[pos:pos + 8]
        (length,) = struct.unpack("<Q", header)
        (hcrc,) = struct.unpack_from("<I", data, pos + 8)
        assert hcrc == tbwriter._masked_crc(header)
        payload = data[pos + 12:pos + 12 + length]
        assert len(payload) == length, "truncated record"
        (pcrc,) = struct.unpack_from("<I", data, pos + 12 + length)
        assert pcrc == tbwriter._masked_crc(payload)
        pos += 12 + length + 4
        records += 1
    assert pos == len(data), "trailing garbage after last record"
    assert records == 3  # file_version + scalar + histogram
    # and the known crc32c test vector still holds (Castagnoli, RFC 3720)
    assert tbwriter._crc32c(b"123456789") == 0xE3069283


# ---------------------------------------------------------------------------
# serving integration: /metrics + request-id correlated spans
# ---------------------------------------------------------------------------

def test_frontend_metrics_endpoint_and_request_id():
    """Acceptance: GET /metrics on a running HttpFrontend returns
    Prometheus text containing serving lifecycle counters, mirrored
    training/resilience counters, and histogram bucket lines."""
    # a training-side registry records a recovery; mirroring must make it
    # visible on the serving scrape without sharing the instance
    Metrics().inc("recoveries_total")
    srv = ServingServer(InferenceModel(predict_fn=_echo),
                        ServingConfig(batch_size=4)).start()
    fe = HttpFrontend(srv).start()
    try:
        body = json.dumps(
            {"instances": np.ones((2, 3)).tolist()}).encode()
        req = urlreq.Request(fe.url + "/predict", data=body, headers={
            "Content-Type": "application/json",
            "X-Request-Id": "req-obs-123"})
        with urlreq.urlopen(req, timeout=30) as resp:
            assert resp.headers["X-Request-Id"] == "req-obs-123"
            out = json.loads(resp.read())
        np.testing.assert_allclose(out["predictions"],
                                   np.ones((2, 3)) * 2.0)
        with urlreq.urlopen(fe.url + "/metrics", timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
        assert re.search(r"^serving_requests \d", text, re.M)
        assert re.search(r"^recoveries_total \d", text, re.M)
        assert 'serving_latency_s_bucket{le="+Inf"}' in text
        assert re.search(r"^serving_latency_s_count [1-9]", text, re.M)
    finally:
        fe.stop()
        srv.stop()


def test_request_id_header_injection_rejected():
    """A payload-supplied request id is echoed into a RESPONSE header —
    CRLF (and any non-token char) must be rejected with 400, never
    emitted."""
    srv = ServingServer(InferenceModel(predict_fn=_echo),
                        ServingConfig(batch_size=4)).start()
    fe = HttpFrontend(srv).start()
    try:
        for evil in ("x\r\nSet-Cookie: evil=1", "abc\n", "a b", ""):
            body = json.dumps({
                "instances": np.ones((1, 2)).tolist(),
                "request_id": evil}).encode()
            req = urlreq.Request(fe.url + "/predict", data=body, headers={
                "Content-Type": "application/json"})
            try:
                urlreq.urlopen(req, timeout=10)
                assert False, f"expected HTTP 400 for {evil!r}"
            except urlreq.HTTPError as e:  # noqa: F841
                assert e.code == 400, evil
                assert e.headers.get("Set-Cookie") is None
        # a well-formed id still round-trips
        body = json.dumps({"instances": np.ones((1, 2)).tolist(),
                           "request_id": "good-id_1:2.3"}).encode()
        req = urlreq.Request(fe.url + "/predict", data=body, headers={
            "Content-Type": "application/json"})
        with urlreq.urlopen(req, timeout=30) as resp:
            assert resp.headers["X-Request-Id"] == "good-id_1:2.3"
    finally:
        fe.stop()
        srv.stop()


def test_duplicate_inflight_request_id_rejected():
    """A caller-supplied id that duplicates an IN-FLIGHT request must be
    rejected at admission (it keys the result table); a delivered id is
    reusable."""
    import queue as _q

    srv = ServingServer(InferenceModel(predict_fn=_echo),
                        ServingConfig(batch_size=4))
    # not started: the first enqueue stays in flight
    x = np.ones((1, 2), np.float32)
    srv.enqueue(x, request_id="dup-1")
    with pytest.raises(ValueError, match="already in flight"):
        srv.enqueue(x, request_id="dup-1")
    srv.start()
    try:
        out = srv.query("dup-1", timeout=10)
        np.testing.assert_allclose(out, x * 2.0)
        # delivered and queried: the id is free again
        srv.enqueue(x, request_id="dup-1")
        srv.query("dup-1", timeout=10)
        # completed but NEVER fetched (first waiter timed out, or the id
        # reused with a new payload): the stale verdict is discarded and
        # the request recomputes — never a silently-stale answer
        srv.enqueue(x, request_id="dup-2")
        deadline = time.time() + 10
        with srv._result_cv:
            while "dup-2" not in srv._results and time.time() < deadline:
                srv._result_cv.wait(0.1)
        x2 = np.full((1, 2), 3.0, np.float32)
        assert srv.enqueue(x2, request_id="dup-2") == "dup-2"
        np.testing.assert_allclose(srv.query("dup-2", timeout=10), x2 * 2.0)
    finally:
        srv.stop()


def test_chrome_trace_joins_training_and_serving_by_request_id(tmp_path):
    """Acceptance: a short training run plus one served request produce a
    single Chrome-trace JSON whose serving spans carry the request id."""
    from bigdl_tpu import nn, optim
    from bigdl_tpu.data import ArrayDataSet

    t = trace.enable()
    # -- short training run ------------------------------------------------
    x = np.random.RandomState(0).rand(64, 4).astype(np.float32)
    y = (x.sum(-1) > 2).astype(np.int32)
    model = nn.Sequential([nn.Linear(4, 2), nn.LogSoftMax()])
    opt = optim.Optimizer(model, ArrayDataSet(x, y), nn.ClassNLLCriterion(),
                          batch_size=32)
    opt.set_end_when(optim.Trigger.max_iteration(3))
    opt.set_checkpoint(str(tmp_path / "ckpt"),
                       optim.Trigger.max_iteration(2))
    opt.optimize()
    # -- one served request, correlated by X-Request-Id --------------------
    srv = ServingServer(InferenceModel(predict_fn=_echo),
                        ServingConfig(batch_size=4)).start()
    fe = HttpFrontend(srv).start()
    try:
        from bigdl_tpu.serving import HttpClient

        HttpClient(fe.url).predict(np.ones((1, 4)), request_id="trace-rid-1")
    finally:
        fe.stop()
        srv.stop()
    path = t.export_chrome_trace(str(tmp_path / "run.json"))
    doc = json.load(open(path))
    names = [e["name"] for e in doc["traceEvents"]]
    assert names.count("train/step") == 3
    assert "train/dispatch" in names and "train/data" in names
    assert "checkpoint/save" in names
    # every serving phase of THIS request carries its id
    by_rid = [e for e in doc["traceEvents"]
              if e["args"].get("request_id") == "trace-rid-1"
              or "trace-rid-1" in str(e["args"].get("request_ids", ""))]
    got = {e["name"] for e in by_rid}
    assert {"serving/http_request", "serving/enqueue", "serving/batch",
            "serving/predict", "serving/publish"} <= got, got
    # parent links: the engine-side enqueue span nests under the HTTP span
    http_span = next(e for e in doc["traceEvents"]
                     if e["name"] == "serving/http_request")
    enq = next(e for e in doc["traceEvents"]
               if e["name"] == "serving/enqueue")
    assert enq["args"]["parent_id"] == http_span["args"]["span_id"]


def test_profile_dir_wires_iteration_profiler(tmp_path):
    """EngineConfig.profile_dir arms the IterationProfiler for every
    optimize(); training ending INSIDE the trace window still closes it
    (the driver's finally)."""
    from bigdl_tpu import nn, optim
    from bigdl_tpu.data import ArrayDataSet
    from bigdl_tpu.runtime.engine import Engine, EngineConfig, init_engine

    Engine.reset()
    prof_dir = tmp_path / "prof"
    init_engine(EngineConfig(profile_dir=str(prof_dir)))
    x = np.random.RandomState(0).rand(64, 4).astype(np.float32)
    y = (x.sum(-1) > 2).astype(np.int32)
    model = nn.Sequential([nn.Linear(4, 2), nn.LogSoftMax()])
    opt = optim.Optimizer(model, ArrayDataSet(x, y), nn.ClassNLLCriterion(),
                          batch_size=32)
    # window is [10, 15); 12 iterations end mid-window
    opt.set_end_when(optim.Trigger.max_iteration(12))
    opt.optimize()
    assert opt._profiler is not None
    assert opt._profiler.done and not opt._profiler._active
    # the jax.profiler trace actually landed on disk
    assert any(prof_dir.rglob("*")), "no trace files written"


def test_iteration_profiler_context_manager():
    from bigdl_tpu.utils.profiling import IterationProfiler

    with IterationProfiler("/tmp/unused", start_iter=5) as prof:
        pass  # never started a trace window
    assert not prof._active
    prof.close()  # idempotent
