"""Systematic torch-golden-parity sweep.

The reference's golden-oracle pattern (SURVEY.md §5): ``*TorchSpec``-style
specs shell out to a local Torch7 to compare layer numerics.  Here torch is
importable in-process, so every case checks BOTH the forward output and the
input gradient (d sum(y^2)/dx — exercises the whole backward) within
tolerance.  Layout conversions (NHWC<->NCHW etc.) are applied at the test
boundary; parameterized layers copy torch's weights into our pytree first.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax
import jax.numpy as jnp

from bigdl_tpu import nn

RNG = jax.random.PRNGKey(0)
RS = np.random.RandomState(0)


# ---------------------------------------------------------------------------
# layout adapters: ours -> torch input / torch -> ours output
# ---------------------------------------------------------------------------

_LAYOUTS = {
    "same": (lambda a: a, lambda a: a),
    "nwc": (lambda a: np.transpose(a, (0, 2, 1)),       # (b,t,c)->(b,c,t)
            lambda a: np.transpose(a, (0, 2, 1))),
    "nhwc": (lambda a: np.transpose(a, (0, 3, 1, 2)),
             lambda a: np.transpose(a, (0, 2, 3, 1))),
    "ndhwc": (lambda a: np.transpose(a, (0, 4, 1, 2, 3)),
              lambda a: np.transpose(a, (0, 2, 3, 4, 1))),
}


def t_(a):
    return torch.tensor(np.asarray(a))


def check_forward_and_grad(layer, tmod, x, layout="same", sync=None,
                           out_layout=None, atol=1e-4, rtol=1e-4):
    """Forward + input-gradient parity for one (ours, torch) layer pair."""
    to_t, from_t = _LAYOUTS[layout]
    out_from_t = _LAYOUTS[out_layout or layout][1]
    xj = jnp.asarray(x)
    variables = layer.init(RNG, xj)
    params, state = variables["params"], variables["state"]
    if sync is not None:
        params, state = sync(dict(params), dict(state), tmod)

    y_ours, _ = layer.forward(params, state, xj, training=False)

    tmod = tmod.eval() if hasattr(tmod, "eval") else tmod
    tx = torch.tensor(to_t(x), requires_grad=True)
    ty = tmod(tx)
    np.testing.assert_allclose(
        np.asarray(y_ours), out_from_t(ty.detach().numpy()),
        atol=atol, rtol=rtol, err_msg=f"{type(layer).__name__} forward")

    # input gradient of sum(y^2)
    def loss(xi):
        out, _ = layer.forward(params, state, xi, training=False)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g_ours = jax.grad(loss)(xj)
    (ty.float() ** 2).sum().backward()
    np.testing.assert_allclose(
        np.asarray(g_ours), from_t(tx.grad.numpy()),
        atol=atol * 10, rtol=rtol * 10,
        err_msg=f"{type(layer).__name__} input grad")


# ---------------------------------------------------------------------------
# 1. parameterless activations / shape ops (layout "same")
# ---------------------------------------------------------------------------

_ACTIVATIONS = [
    ("relu", lambda: nn.ReLU(), lambda: torch.nn.ReLU()),
    ("relu6", lambda: nn.ReLU6(), lambda: torch.nn.ReLU6()),
    ("elu", lambda: nn.ELU(), lambda: torch.nn.ELU()),
    ("gelu", lambda: nn.GELU(), lambda: torch.nn.GELU(approximate="tanh")),
    ("silu", lambda: nn.SiLU(), lambda: torch.nn.SiLU()),
    ("sigmoid", lambda: nn.Sigmoid(), lambda: torch.nn.Sigmoid()),
    ("tanh", lambda: nn.Tanh(), lambda: torch.nn.Tanh()),
    ("softplus", lambda: nn.SoftPlus(), lambda: torch.nn.Softplus()),
    ("softsign", lambda: nn.SoftSign(), lambda: torch.nn.Softsign()),
    ("logsigmoid", lambda: nn.LogSigmoid(), lambda: torch.nn.LogSigmoid()),
    ("leakyrelu", lambda: nn.LeakyReLU(0.2),
     lambda: torch.nn.LeakyReLU(0.2)),
    ("hardtanh", lambda: nn.HardTanh(-0.5, 0.5),
     lambda: torch.nn.Hardtanh(-0.5, 0.5)),
    ("mish", lambda: nn.Mish(), lambda: torch.nn.Mish()),
    ("tanhshrink", lambda: nn.TanhShrink(), lambda: torch.nn.Tanhshrink()),
    ("softshrink", lambda: nn.SoftShrink(0.3),
     lambda: torch.nn.Softshrink(0.3)),
    ("hardshrink", lambda: nn.HardShrink(0.3),
     lambda: torch.nn.Hardshrink(0.3)),
    ("softmax", lambda: nn.SoftMax(), lambda: torch.nn.Softmax(dim=-1)),
    ("logsoftmax", lambda: nn.LogSoftMax(),
     lambda: torch.nn.LogSoftmax(dim=-1)),
    ("softmin", lambda: nn.SoftMin(), lambda: torch.nn.Softmin(dim=-1)),
    ("hardswish", lambda: nn.HardSwish(), lambda: torch.nn.Hardswish()),
    ("hardsigmoid", lambda: nn.HardSigmoid(),
     lambda: torch.nn.Hardsigmoid()),
]


@pytest.mark.parametrize("name,ours,theirs",
                         _ACTIVATIONS, ids=[c[0] for c in _ACTIVATIONS])
def test_activation_parity(name, ours, theirs):
    x = RS.randn(4, 9).astype(np.float32)
    check_forward_and_grad(ours(), theirs(), x)


# ---------------------------------------------------------------------------
# 2. pooling
# ---------------------------------------------------------------------------

_POOLS = [
    ("maxpool1d", lambda: nn.MaxPool1D(2), lambda: torch.nn.MaxPool1d(2),
     (2, 8, 3), "nwc"),
    ("maxpool1d_k3s2", lambda: nn.MaxPool1D(3, 2),
     lambda: torch.nn.MaxPool1d(3, 2), (2, 9, 3), "nwc"),
    ("avgpool1d", lambda: nn.AvgPool1D(2), lambda: torch.nn.AvgPool1d(2),
     (2, 8, 3), "nwc"),
    ("maxpool2d", lambda: nn.MaxPool2D(2), lambda: torch.nn.MaxPool2d(2),
     (2, 8, 8, 3), "nhwc"),
    ("maxpool2d_k3s2", lambda: nn.MaxPool2D(3, 2),
     lambda: torch.nn.MaxPool2d(3, 2), (2, 9, 9, 3), "nhwc"),
    ("avgpool2d", lambda: nn.AvgPool2D(2), lambda: torch.nn.AvgPool2d(2),
     (2, 8, 8, 3), "nhwc"),
    ("maxpool3d", lambda: nn.MaxPool3D(2), lambda: torch.nn.MaxPool3d(2),
     (2, 4, 4, 4, 2), "ndhwc"),
    ("avgpool3d", lambda: nn.AvgPool3D(2), lambda: torch.nn.AvgPool3d(2),
     (2, 4, 4, 4, 2), "ndhwc"),
]


@pytest.mark.parametrize("name,ours,theirs,shape,layout",
                         _POOLS, ids=[c[0] for c in _POOLS])
def test_pool_parity(name, ours, theirs, shape, layout):
    x = RS.randn(*shape).astype(np.float32)
    check_forward_and_grad(ours(), theirs(), x, layout=layout)


def test_global_avg_pool2d_parity():
    x = RS.randn(2, 6, 6, 3).astype(np.float32)
    tmod = torch.nn.Sequential(torch.nn.AdaptiveAvgPool2d(1),
                               torch.nn.Flatten())
    check_forward_and_grad(nn.GlobalAvgPool2D(), tmod, x,
                           layout="nhwc", out_layout="same")


def test_global_max_pool2d_parity():
    x = RS.randn(2, 6, 6, 3).astype(np.float32)
    tmod = torch.nn.Sequential(torch.nn.AdaptiveMaxPool2d(1),
                               torch.nn.Flatten())
    check_forward_and_grad(nn.GlobalMaxPool2D(), tmod, x,
                           layout="nhwc", out_layout="same")


# ---------------------------------------------------------------------------
# 3. parameterized layers (weights copied torch -> ours)
# ---------------------------------------------------------------------------


def _sync_linear(params, state, tm):
    params["weight"] = jnp.asarray(tm.weight.detach().numpy().T)
    if tm.bias is not None:
        params["bias"] = jnp.asarray(tm.bias.detach().numpy())
    return params, state


def _sync_conv2d(params, state, tm):
    params["weight"] = jnp.asarray(
        tm.weight.detach().numpy().transpose(2, 3, 1, 0))
    if tm.bias is not None:
        params["bias"] = jnp.asarray(tm.bias.detach().numpy())
    return params, state


def _sync_conv1d(params, state, tm):
    params["weight"] = jnp.asarray(
        tm.weight.detach().numpy().transpose(2, 1, 0))
    if tm.bias is not None:
        params["bias"] = jnp.asarray(tm.bias.detach().numpy())
    return params, state


def _sync_conv3d(params, state, tm):
    params["weight"] = jnp.asarray(
        tm.weight.detach().numpy().transpose(2, 3, 4, 1, 0))
    if tm.bias is not None:
        params["bias"] = jnp.asarray(tm.bias.detach().numpy())
    return params, state


def _sync_norm(params, state, tm):
    params["weight"] = jnp.asarray(tm.weight.detach().numpy())
    params["bias"] = jnp.asarray(tm.bias.detach().numpy())
    if hasattr(tm, "running_mean") and tm.running_mean is not None:
        state["running_mean"] = jnp.asarray(tm.running_mean.numpy())
        state["running_var"] = jnp.asarray(tm.running_var.numpy())
    return params, state


def _sync_prelu(params, state, tm):
    params["alpha"] = jnp.asarray(tm.weight.detach().numpy())
    return params, state


_PARAM_LAYERS = [
    ("linear", lambda: nn.Linear(6, 4), lambda: torch.nn.Linear(6, 4),
     (3, 6), "same", _sync_linear),
    ("linear_nobias", lambda: nn.Linear(6, 4, with_bias=False),
     lambda: torch.nn.Linear(6, 4, bias=False), (3, 6), "same", _sync_linear),
    ("conv1d", lambda: nn.Conv1D(3, 5, 3, padding=1),
     lambda: torch.nn.Conv1d(3, 5, 3, padding=1), (2, 8, 3), "nwc",
     _sync_conv1d),
    ("conv2d", lambda: nn.Conv2D(3, 5, 3, padding=1),
     lambda: torch.nn.Conv2d(3, 5, 3, padding=1), (2, 8, 8, 3), "nhwc",
     _sync_conv2d),
    ("conv2d_stride2", lambda: nn.Conv2D(3, 5, 3, stride=2, padding=1),
     lambda: torch.nn.Conv2d(3, 5, 3, stride=2, padding=1),
     (2, 9, 9, 3), "nhwc", _sync_conv2d),
    ("conv2d_groups", lambda: nn.Conv2D(4, 8, 3, padding=1, groups=2),
     lambda: torch.nn.Conv2d(4, 8, 3, padding=1, groups=2),
     (2, 6, 6, 4), "nhwc", _sync_conv2d),
    ("conv2d_dilated", lambda: nn.Conv2D(3, 5, 3, padding=2, dilation=2),
     lambda: torch.nn.Conv2d(3, 5, 3, padding=2, dilation=2),
     (2, 9, 9, 3), "nhwc", _sync_conv2d),
    ("conv3d", lambda: nn.Conv3D(2, 4, 3, padding=1),
     lambda: torch.nn.Conv3d(2, 4, 3, padding=1), (2, 5, 5, 5, 2), "ndhwc",
     _sync_conv3d),
    ("batchnorm_eval", lambda: nn.BatchNorm(5),
     lambda: _bn_with_stats(5), (4, 5), "same", _sync_norm),
    ("batchnorm2d_eval", lambda: nn.BatchNorm(5),
     lambda: _bn2d_with_stats(5), (2, 6, 6, 5), "nhwc", _sync_norm),
    ("layernorm", lambda: nn.LayerNorm(7),
     lambda: torch.nn.LayerNorm(7, eps=1e-6), (4, 7), "same", _sync_norm),
    ("prelu", lambda: nn.PReLU(), lambda: torch.nn.PReLU(),
     (4, 9), "same", _sync_prelu),
    ("groupnorm", lambda: nn.GroupNorm(2, 6),
     lambda: _affine_norm(torch.nn.GroupNorm(2, 6)), (2, 4, 4, 6), "nhwc",
     _sync_norm),
    ("instancenorm2d", lambda: nn.InstanceNorm2D(5),
     lambda: _affine_norm(torch.nn.InstanceNorm2d(5, affine=True)),
     (2, 6, 6, 5), "nhwc", _sync_norm),
    ("depthwise_conv2d",
     lambda: nn.DepthwiseConv2D(4, 3, padding=1, depth_multiplier=2),
     lambda: torch.nn.Conv2d(4, 8, 3, padding=1, groups=4),
     (2, 6, 6, 4), "nhwc", lambda p, s, tm: _sync_depthwise(p, s, tm)),
]


def _affine_norm(m):
    with torch.no_grad():
        m.weight.copy_(torch.tensor(
            (1 + 0.2 * RS.randn(m.weight.shape[0])).astype(np.float32)))
        m.bias.copy_(torch.tensor(
            RS.randn(m.bias.shape[0]).astype(np.float32) * 0.1))
    return m


def _sync_depthwise(params, state, tm):
    # torch grouped-conv weight (cout, 1, kh, kw) with groups=cin ->
    # ours (kh, kw, 1, cout)
    params["weight"] = jnp.asarray(
        tm.weight.detach().numpy().transpose(2, 3, 1, 0))
    if tm.bias is not None:
        params["bias"] = jnp.asarray(tm.bias.detach().numpy())
    return params, state


def _bn_with_stats(c):
    bn = torch.nn.BatchNorm1d(c)
    bn.running_mean.copy_(torch.tensor(RS.randn(c).astype(np.float32) * .3))
    bn.running_var.copy_(torch.tensor(
        (1 + 0.4 * RS.rand(c)).astype(np.float32)))
    with torch.no_grad():
        bn.weight.copy_(torch.tensor(
            (1 + 0.2 * RS.randn(c)).astype(np.float32)))
        bn.bias.copy_(torch.tensor(RS.randn(c).astype(np.float32) * .1))
    return bn


def _bn2d_with_stats(c):
    bn = torch.nn.BatchNorm2d(c)
    bn.running_mean.copy_(torch.tensor(RS.randn(c).astype(np.float32) * .3))
    bn.running_var.copy_(torch.tensor(
        (1 + 0.4 * RS.rand(c)).astype(np.float32)))
    with torch.no_grad():
        bn.weight.copy_(torch.tensor(
            (1 + 0.2 * RS.randn(c)).astype(np.float32)))
        bn.bias.copy_(torch.tensor(RS.randn(c).astype(np.float32) * .1))
    return bn


@pytest.mark.parametrize("name,ours,theirs,shape,layout,sync",
                         _PARAM_LAYERS, ids=[c[0] for c in _PARAM_LAYERS])
def test_param_layer_parity(name, ours, theirs, shape, layout, sync):
    x = RS.randn(*shape).astype(np.float32)
    check_forward_and_grad(ours(), theirs(), x, layout=layout, sync=sync)


def test_conv2d_transpose_parity():
    ours = nn.Conv2DTranspose(3, 5, 3, stride=2, padding=1)
    tm = torch.nn.ConvTranspose2d(3, 5, 3, stride=2, padding=1)
    x = RS.randn(2, 5, 5, 3).astype(np.float32)

    def sync(params, state, tm):
        params["weight"] = jnp.asarray(
            tm.weight.detach().numpy().transpose(2, 3, 1, 0))
        params["bias"] = jnp.asarray(tm.bias.detach().numpy())
        return params, state

    check_forward_and_grad(ours, tm, x, layout="nhwc", sync=sync)


def test_embedding_parity():
    ours = nn.Embedding(11, 6)
    tm = torch.nn.Embedding(11, 6)
    idx = RS.randint(0, 11, (4, 7)).astype(np.int32)

    variables = ours.init(RNG, jnp.asarray(idx))
    params = dict(variables["params"])
    params["weight"] = jnp.asarray(tm.weight.detach().numpy())
    y, _ = ours.forward(params, variables["state"], jnp.asarray(idx))
    with torch.no_grad():
        ty = tm(torch.tensor(idx, dtype=torch.long))
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-6)


# ---------------------------------------------------------------------------
# 4. recurrent + attention
# ---------------------------------------------------------------------------


def test_lstm_parity():
    d, h = 5, 7
    ours = nn.LSTM(d, h, return_sequences=True)
    tm = torch.nn.LSTM(d, h, batch_first=True)
    x = RS.randn(3, 6, d).astype(np.float32)

    def sync(params, state, _):
        # torch gate order i,f,g,o matches ours; bias = b_ih + b_hh
        params["w_in"] = jnp.asarray(tm.weight_ih_l0.detach().numpy().T)
        params["w_rec"] = jnp.asarray(tm.weight_hh_l0.detach().numpy().T)
        params["bias"] = jnp.asarray(
            (tm.bias_ih_l0 + tm.bias_hh_l0).detach().numpy())
        return params, state

    def t_fwd(tx):
        return tm(tx)[0]

    check_forward_and_grad(ours, t_fwd, x, sync=sync, atol=1e-5)


def test_gru_parity():
    d, h = 5, 7
    ours = nn.GRU(d, h, return_sequences=True)
    tm = torch.nn.GRU(d, h, batch_first=True)
    # our GRU puts ONE fused bias outside the reset gate; torch's b_hn sits
    # inside r*(...). Zero b_hh so the two formulations coincide exactly.
    with torch.no_grad():
        tm.bias_hh_l0.zero_()
    x = RS.randn(3, 6, d).astype(np.float32)

    def sync(params, state, _):
        params["w_in"] = jnp.asarray(tm.weight_ih_l0.detach().numpy().T)
        params["w_rec"] = jnp.asarray(tm.weight_hh_l0.detach().numpy().T)
        params["bias"] = jnp.asarray(tm.bias_ih_l0.detach().numpy())
        return params, state

    def t_fwd(tx):
        return tm(tx)[0]

    check_forward_and_grad(ours, t_fwd, x, sync=sync, atol=1e-5)


def test_mha_parity():
    e, heads, b, t = 8, 2, 2, 5
    ours = nn.MultiHeadAttention(e, heads, use_flash=False)
    tm = torch.nn.MultiheadAttention(e, heads, batch_first=True)
    x = RS.randn(b, t, e).astype(np.float32)

    def sync(params, state, _):
        w = tm.in_proj_weight.detach().numpy()   # (3e, e) rows q,k,v
        bvec = tm.in_proj_bias.detach().numpy()
        params["wq"] = jnp.asarray(w[:e].T)
        params["wk"] = jnp.asarray(w[e:2 * e].T)
        params["wv"] = jnp.asarray(w[2 * e:].T)
        params["bq"] = jnp.asarray(bvec[:e])
        params["bk"] = jnp.asarray(bvec[e:2 * e])
        params["bv"] = jnp.asarray(bvec[2 * e:])
        params["wo"] = jnp.asarray(tm.out_proj.weight.detach().numpy().T)
        params["bo"] = jnp.asarray(tm.out_proj.bias.detach().numpy())
        return params, state

    def t_fwd(tx):
        return tm(tx, tx, tx, need_weights=False)[0]

    check_forward_and_grad(ours, t_fwd, x, sync=sync, atol=1e-5)


# ---------------------------------------------------------------------------
# 5. criterions: forward + input-grad parity
# ---------------------------------------------------------------------------


def _logits(b=6, c=5):
    return RS.randn(b, c).astype(np.float32)


def _labels(b=6, c=5):
    return RS.randint(0, c, (b,))


_CRITERIA = [
    ("mse", lambda: nn.MSECriterion(), lambda: torch.nn.MSELoss(),
     lambda: (_logits(), RS.randn(6, 5).astype(np.float32)), None),
    ("l1", lambda: nn.AbsCriterion(), lambda: torch.nn.L1Loss(),
     lambda: (_logits(), RS.randn(6, 5).astype(np.float32)), None),
    ("smoothl1", lambda: nn.SmoothL1Criterion(),
     lambda: torch.nn.SmoothL1Loss(),
     lambda: (_logits(), RS.randn(6, 5).astype(np.float32)), None),
    ("crossentropy", lambda: nn.CrossEntropyCriterion(),
     lambda: torch.nn.CrossEntropyLoss(),
     lambda: (_logits(), _labels()), "long"),
    ("classnll", lambda: nn.ClassNLLCriterion(),
     lambda: torch.nn.NLLLoss(),
     lambda: (np.log(RS.dirichlet(np.ones(5), 6)).astype(np.float32),
              _labels()), "long"),
    ("bce", lambda: nn.BCECriterion(), lambda: torch.nn.BCELoss(),
     lambda: (RS.uniform(0.05, 0.95, (6, 1)).astype(np.float32),
              RS.randint(0, 2, (6, 1)).astype(np.float32)), None),
    ("bcelogits", lambda: nn.BCEWithLogitsCriterion(),
     lambda: torch.nn.BCEWithLogitsLoss(),
     lambda: (_logits(6, 1), RS.randint(0, 2, (6, 1)).astype(np.float32)),
     None),
    ("kldiv", lambda: nn.DistKLDivCriterion(),
     lambda: torch.nn.KLDivLoss(reduction="mean"),
     lambda: (np.log(RS.dirichlet(np.ones(5), 6)).astype(np.float32),
              RS.dirichlet(np.ones(5), 6).astype(np.float32)), None),
    ("softmargin", lambda: nn.SoftMarginCriterion(),
     lambda: torch.nn.SoftMarginLoss(),
     lambda: (_logits(6, 1),
              (RS.randint(0, 2, (6, 1)) * 2 - 1).astype(np.float32)), None),
    ("multilabelsoftmargin", lambda: nn.MultiLabelSoftMarginCriterion(),
     lambda: torch.nn.MultiLabelSoftMarginLoss(),
     lambda: (_logits(), RS.randint(0, 2, (6, 5)).astype(np.float32)), None),
    ("hingeembedding", lambda: nn.HingeEmbeddingCriterion(),
     lambda: torch.nn.HingeEmbeddingLoss(),
     lambda: (np.abs(RS.randn(8)).astype(np.float32),
              (RS.randint(0, 2, (8,)) * 2 - 1).astype(np.float32)), None),
    ("multimargin", lambda: nn.MultiMarginCriterion(),
     lambda: torch.nn.MultiMarginLoss(),
     lambda: (_logits(), _labels()), "long"),
]


@pytest.mark.parametrize("name,ours,theirs,data,tdtype",
                         _CRITERIA, ids=[c[0] for c in _CRITERIA])
def test_criterion_parity(name, ours, theirs, data, tdtype):
    crit, tcrit = ours(), theirs()
    inp, target = data()
    loss_ours = float(crit.forward(jnp.asarray(inp), jnp.asarray(target)))
    ti = torch.tensor(inp, requires_grad=True)
    tt = torch.tensor(target if tdtype != "long" else target,
                      dtype=torch.long if tdtype == "long" else None)
    tloss = tcrit(ti, tt)
    np.testing.assert_allclose(loss_ours, float(tloss), atol=1e-5, rtol=1e-5,
                               err_msg=f"{name} forward")

    g_ours = jax.grad(
        lambda i: crit.forward(i, jnp.asarray(target)))(jnp.asarray(inp))
    tloss.backward()
    np.testing.assert_allclose(np.asarray(g_ours), ti.grad.numpy(),
                               atol=1e-5, rtol=1e-4,
                               err_msg=f"{name} input grad")


def test_cosine_embedding_parity():
    crit = nn.CosineEmbeddingCriterion(margin=0.2)
    tcrit = torch.nn.CosineEmbeddingLoss(margin=0.2)
    x1 = RS.randn(6, 5).astype(np.float32)
    x2 = RS.randn(6, 5).astype(np.float32)
    y = (RS.randint(0, 2, (6,)) * 2 - 1).astype(np.float32)
    ours = float(crit.forward((jnp.asarray(x1), jnp.asarray(x2)),
                              jnp.asarray(y)))
    theirs = float(tcrit(torch.tensor(x1), torch.tensor(x2),
                         torch.tensor(y)))
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


def test_margin_ranking_parity():
    crit = nn.MarginRankingCriterion(margin=0.5)
    tcrit = torch.nn.MarginRankingLoss(margin=0.5)
    x1 = RS.randn(8).astype(np.float32)
    x2 = RS.randn(8).astype(np.float32)
    y = (RS.randint(0, 2, (8,)) * 2 - 1).astype(np.float32)
    ours = float(crit.forward((jnp.asarray(x1), jnp.asarray(x2)),
                              jnp.asarray(y)))
    theirs = float(tcrit(torch.tensor(x1), torch.tensor(x2),
                         torch.tensor(y)))
    np.testing.assert_allclose(ours, theirs, atol=1e-5)
