"""Native C++ IO library + vision pipeline.

The C path and the numpy fallback are both exercised and compared —
the reference's MKL-vs-pure-JVM duality (SURVEY.md §3.1 tensor row).
"""

import numpy as np
import pytest

from bigdl_tpu import native
from bigdl_tpu.data.vision import (CenterCrop, ChannelNormalize, HFlip,
                                   ImageFrame, ImageFrameToBatches,
                                   RandomCrop, Resize, ResizeShortSide)


def _img(rng, h=32, w=48, c=3):
    return rng.integers(0, 256, (h, w, c), dtype=np.uint8)


class TestNativeLib:
    def test_builds(self):
        assert native.available(), "native lib should build with g++"

    def test_resize_matches_fallback(self):
        rng = np.random.default_rng(0)
        img = _img(rng)
        out = native.resize_bilinear(img, 16, 24)
        assert out.shape == (16, 24, 3) and out.dtype == np.uint8
        from bigdl_tpu.native import lib as L
        real = L._lib
        try:
            L._lib = None
            ref = native.resize_bilinear(img, 16, 24)
        finally:
            L._lib = real
        # identical sampling; allow ±1 for rounding differences
        assert np.abs(out.astype(int) - ref.astype(int)).max() <= 1

    def test_crop_flip_normalize(self):
        rng = np.random.default_rng(1)
        img = _img(rng)
        c = native.crop(img, 2, 3, 10, 12)
        np.testing.assert_array_equal(c, img[2:12, 3:15])
        f = native.hflip(img)
        np.testing.assert_array_equal(f, img[:, ::-1])
        mean = [0.5, 0.4, 0.3]
        std = [0.2, 0.25, 0.3]
        n = native.normalize(img, mean, std)
        ref = (img.astype(np.float32) / 255.0 - np.float32(mean)) / \
            np.float32(std)
        np.testing.assert_allclose(n, ref, rtol=1e-5, atol=1e-5)

    def test_batch_pipeline(self):
        rng = np.random.default_rng(2)
        images = [_img(rng, 40, 50) for _ in range(7)]
        pipe = native.BatchPipeline(2)
        mean, std = [0.5] * 3, [0.25] * 3
        out = pipe.process_batch(images, (24, 24), mean, std,
                                 resize_hw=(32, 32),
                                 crops=[(4, 4)] * 7,
                                 flips=[True, False] * 3 + [True])
        assert out.shape == (7, 24, 24, 3) and out.dtype == np.float32
        # reference computation for image 1 (no flip)
        r = native.resize_bilinear(images[1], 32, 32)[4:28, 4:28]
        ref = (r.astype(np.float32) / 255.0 - 0.5) / 0.25
        np.testing.assert_allclose(out[1], ref, atol=1e-5)
        # image 0 flipped
        r0 = native.resize_bilinear(images[0], 32, 32)[4:28, 4:28][:, ::-1]
        ref0 = (r0.astype(np.float32) / 255.0 - 0.5) / 0.25
        np.testing.assert_allclose(out[0], ref0, atol=1e-5)
        pipe.close()

    def test_crop_out_of_bounds_rejected(self):
        import pytest

        rng = np.random.default_rng(7)
        img = _img(rng, 20, 20)
        with pytest.raises(ValueError, match="out of bounds"):
            native.crop(img, 0, 0, 32, 32)
        pipe = native.BatchPipeline(1)
        with pytest.raises(ValueError, match="out of bounds"):
            pipe.process_batch([img], (32, 32), [0.5] * 3, [0.25] * 3)
        pipe.close()

    def test_gather_rows(self):
        rng = np.random.default_rng(3)
        src = rng.standard_normal((20, 6, 4)).astype(np.float32)
        idx = np.array([3, 0, 19, 7], np.int64)
        pipe = native.BatchPipeline(2)
        out = pipe.gather_rows(src, idx)
        np.testing.assert_array_equal(out, src[idx])
        pipe.close()


class TestVisionPipeline:
    def test_transform_chain(self):
        rng = np.random.default_rng(4)
        frame = ImageFrame.from_arrays([_img(rng, 50, 60) for _ in range(4)],
                                       labels=[0, 1, 2, 3])
        chain = (ResizeShortSide(36) >> CenterCrop(32, 32)
                 >> ChannelNormalize([0.5] * 3, [0.25] * 3))
        out = frame.transform(chain)
        assert len(out) == 4
        for f in out:
            assert f.image.shape == (32, 32, 3)
            assert f.image.dtype == np.float32

    def test_augmentations(self):
        rng = np.random.default_rng(5)
        frame = ImageFrame.from_arrays([_img(rng, 40, 40)])
        out = frame.transform(Resize(20, 20) >> RandomCrop(16, 16, seed=0)
                              >> HFlip(p=1.0))
        assert out.features[0].image.shape == (16, 16, 3)

    def test_batches(self):
        rng = np.random.default_rng(6)
        frame = ImageFrame.from_arrays(
            [_img(rng, 40, 40) for _ in range(10)], labels=list(range(10)))
        to_batches = ImageFrameToBatches(
            (24, 24), [0.5] * 3, [0.25] * 3, resize_hw=(32, 32),
            random_crop=True, random_flip=True, seed=0)
        batches = list(to_batches(frame, batch_size=4, shuffle=True))
        assert len(batches) == 2  # drop_last
        for b in batches:
            assert b["input"].shape == (4, 24, 24, 3)
            assert b["target"].shape == (4,)


class TestJpegDecode:
    def _jpeg_bytes(self, rs, h=40, w=56, quality=92):
        import io

        from PIL import Image

        arr = (rs.rand(h, w, 3) * 255).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, "JPEG", quality=quality)
        return buf.getvalue()

    def test_native_decode_matches_pil(self):
        import io

        from PIL import Image

        from bigdl_tpu.native import lib as native

        rs = np.random.RandomState(0)
        data = self._jpeg_bytes(rs)
        got = native.decode_jpeg(data)
        with Image.open(io.BytesIO(data)) as im:
            ref = np.asarray(im.convert("RGB"), np.uint8)
        assert got.shape == ref.shape
        # different IDCT implementations may differ by a few levels
        diff = np.abs(got.astype(np.int16) - ref.astype(np.int16))
        assert float(diff.mean()) < 2.0, float(diff.mean())
        assert int(diff.max()) <= 32

    def test_decode_batch_matches_single(self):
        from bigdl_tpu.native import lib as native

        rs = np.random.RandomState(1)
        enc = [self._jpeg_bytes(rs, 48, 64), self._jpeg_bytes(rs, 40, 40),
               self._jpeg_bytes(rs, 64, 48)]
        mean = np.array([0.5, 0.5, 0.5], np.float32)
        std = np.array([0.25, 0.25, 0.25], np.float32)
        pipe = native.BatchPipeline(2)
        try:
            out = pipe.decode_batch(enc, (32, 32), mean, std,
                                    resize_hw=(36, 36),
                                    crops=[(0, 0), (2, 2), (4, 4)],
                                    flips=[False, True, False])
            assert out.shape == (3, 32, 32, 3)
            ref = pipe.process_batch(
                [native.decode_jpeg(e) for e in enc], (32, 32), mean, std,
                resize_hw=(36, 36), crops=[(0, 0), (2, 2), (4, 4)],
                flips=[False, True, False])
            np.testing.assert_allclose(out, ref, atol=1e-5)
        finally:
            pipe.close()

    def test_corrupt_jpeg_raises(self):
        from bigdl_tpu.native import lib as native

        with pytest.raises(ValueError):
            native.decode_jpeg(b"\xff\xd8\xff notajpeg")

        pipe = native.BatchPipeline(2)
        try:
            # native path reports the failing batch indices; the PIL
            # fallback raises from decode_jpeg — ValueError either way
            with pytest.raises(ValueError):
                pipe.decode_batch(
                    [b"\xff\xd8\xff junk"], (8, 8),
                    np.zeros(3, np.float32), np.ones(3, np.float32))
        finally:
            pipe.close()

    def test_crop_out_of_bounds_flagged(self):
        from bigdl_tpu.native import lib as native

        if not native.jpeg_available():
            pytest.skip("native libjpeg not available")
        rs = np.random.RandomState(2)
        enc = [self._jpeg_bytes(rs, 24, 24)]
        pipe = native.BatchPipeline(1)
        try:
            with pytest.raises(ValueError):
                # crop 32x32 from a 24x24 decode with no resize
                pipe.decode_batch(enc, (32, 32), np.zeros(3, np.float32),
                                  np.ones(3, np.float32))
        finally:
            pipe.close()


def test_bytes_to_mat_transformer():
    """reference BytesToMat.scala: encoded bytes -> image slot, chains
    with the rest of the augmentation DSL."""
    import io

    from PIL import Image

    from bigdl_tpu.data.vision import (BytesToMat, ImageFeature, ImageFrame,
                                       Resize)

    rs = np.random.RandomState(0)
    arr = (rs.rand(30, 40, 3) * 255).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, "JPEG", quality=95)

    frame = ImageFrame([ImageFeature(bytes=buf.getvalue(), label=1)])
    out = frame.transform(BytesToMat() >> Resize(16, 16))
    f = out.features[0]
    assert f.image.shape == (16, 16, 3)
    assert f[ImageFeature.KEY_LABEL] == 1

    with pytest.raises(KeyError, match="bytes"):
        ImageFrame([ImageFeature(image=arr)]).transform(BytesToMat())


def test_decode_batch_distinguishes_crop_bug_from_corrupt_data():
    from bigdl_tpu.native import lib as native

    if not native.jpeg_available():
        pytest.skip("native libjpeg not available")
    import io

    from PIL import Image

    rs = np.random.RandomState(3)
    buf = io.BytesIO()
    Image.fromarray((rs.rand(24, 24, 3) * 255).astype(np.uint8)).save(
        buf, "JPEG")
    pipe = native.BatchPipeline(1)
    try:
        with pytest.raises(ValueError, match="geometry bug"):
            pipe.decode_batch([buf.getvalue()], (32, 32),
                              np.zeros(3, np.float32),
                              np.ones(3, np.float32))
    finally:
        pipe.close()
