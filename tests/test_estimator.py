"""Orca-equivalent Estimator + XShards + serializer round-trip tests
(reference test analog: orca estimator tests run with cluster_mode="local" —
SURVEY.md §5)."""

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.data.shards import XShards, read_csv
from bigdl_tpu.estimator import Estimator, init_context
from bigdl_tpu.nn.criterion import CrossEntropyCriterion
from bigdl_tpu.optim.optim_method import Adam
from bigdl_tpu.optim.validation import Loss, Top1Accuracy


def _toy(n=256, d=8, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.rand(n, d).astype(np.float32)
    y = (x.sum(1) > d / 2).astype(np.int32)
    return x, y


def _make_est():
    return Estimator.from_module(
        model_creator=lambda cfg: nn.Sequential(
            [nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2)]),
        optimizer_creator=lambda cfg: Adam(
            learning_rate=cfg.get("lr", 1e-2)),
        loss_creator=lambda cfg: CrossEntropyCriterion(),
        config={"lr": 1e-2})


def test_estimator_fit_evaluate_predict():
    init_context("local")
    x, y = _toy()
    est = _make_est()
    stats = est.fit((x, y), epochs=30, batch_size=64,
                    validation_data=(x, y),
                    validation_methods=[Top1Accuracy()])
    assert stats["num_samples"] == 256
    res = est.evaluate((x, y), [Top1Accuracy(), Loss(CrossEntropyCriterion())])
    assert res["Top1Accuracy"] > 0.85
    pred = est.predict(x[:10])
    assert pred.shape == (10, 2)


def test_estimator_xshards_and_save_load(tmp_path):
    init_context("local")
    x, y = _toy(seed=1)
    shards = XShards.partition({"x": x, "y": y}, num_shards=4)
    assert shards.num_partitions() == 4

    est = _make_est()
    est.fit(shards, epochs=8, batch_size=64)
    ref_pred = est.predict(x[:16])

    path = str(tmp_path / "model")
    est.save(path)

    est2 = _make_est()
    est2.load(path)
    pred2 = est2.predict(x[:16])
    np.testing.assert_allclose(np.asarray(pred2), np.asarray(ref_pred),
                               rtol=1e-5, atol=1e-5)


def test_xshards_ops():
    x = np.arange(100).reshape(50, 2).astype(np.float32)
    s = XShards.partition(x, num_shards=5)
    s2 = s.transform_shard(lambda a: a * 2)
    assert np.allclose(s2.concat(), x * 2)
    s3 = s.repartition(3)
    assert s3.num_partitions() == 3
    assert np.allclose(s3.concat(), x)


def test_xshards_transform_preserves_process_local():
    """ADVICE r2 (medium): sharded reads mark their collections
    process-local; transform_shard/repartition must PROPAGATE that flag or
    owned() re-slices [p::n] over already-disjoint local shards and drops
    (n-1)/n of the data in multihost jobs."""
    x = np.arange(24).reshape(12, 2).astype(np.float32)
    local = XShards([x[:6], x[6:]], process_local=True)
    t = local.transform_shard(lambda a: a + 1)
    assert t._process_local
    assert np.allclose(np.concatenate(t.owned()), x + 1)  # nothing dropped
    r = local.repartition(3)
    assert r._process_local
    assert np.allclose(np.concatenate(r.owned()), x)
    # non-local collections keep slicing in owned() (single process: all)
    glob = XShards([x[:6], x[6:]]).transform_shard(lambda a: a)
    assert not glob._process_local


def test_read_csv(tmp_path):
    import pandas as pd

    for i in range(3):
        pd.DataFrame({"a": np.arange(10) + i, "b": np.arange(10)}).to_csv(
            tmp_path / f"part{i}.csv", index=False)
    xs = read_csv(str(tmp_path))
    assert xs.num_partitions() == 3
    df = xs.concat()
    assert len(df) == 30


def test_estimator_rejects_unknown_backend():
    with pytest.raises(ValueError):
        Estimator.from_module(lambda c: None, lambda c: None, lambda c: None,
                              backend="ray")


def test_estimator_loaded_weights_evaluate_and_multiinput_predict(tmp_path):
    """Loaded-weights (no prior fit) paths: evaluate works, and predict
    handles the multi-input tuple pack like the trained path."""
    from bigdl_tpu.keras.engine import Input, Model
    from bigdl_tpu.nn.module import Sequential

    init_context("local")
    x, y = _toy(seed=3)
    est = _make_est()
    est.fit((x, y), epochs=15, batch_size=64)
    ref_eval = est.evaluate((x, y), [Top1Accuracy()])
    ref_pred = est.predict(x[:16])
    path = str(tmp_path / "m")
    est.save(path)

    est2 = _make_est()
    est2.load(path)
    # evaluate without a prior fit (used to raise "call fit() first")
    got = est2.evaluate((x, y), [Top1Accuracy()])
    assert abs(got["Top1Accuracy"] - ref_eval["Top1Accuracy"]) < 1e-6
    np.testing.assert_allclose(est2.predict(x[:16]), ref_pred,
                               rtol=1e-5, atol=1e-6)

    # multi-input model through the loaded-weights predict path
    ia, ib = Input((4,)), Input((4,))
    from bigdl_tpu.keras.layers import Merge
    out = nn.Linear(8, 2)(Merge("concat")([ia, ib]))
    m = Model([ia, ib], out)
    a = np.random.RandomState(0).rand(32, 4).astype(np.float32)
    b = np.random.RandomState(1).rand(32, 4).astype(np.float32)
    yy = np.random.RandomState(2).randint(0, 2, 32).astype(np.int32)
    m.compile(Adam(1e-2), CrossEntropyCriterion())
    m.fit([a, b], yy, batch_size=16, nb_epoch=1)

    est3 = Estimator.from_module(
        model_creator=lambda cfg: m,
        optimizer_creator=lambda cfg: Adam(1e-2),
        loss_creator=lambda cfg: CrossEntropyCriterion())
    mpath = str(tmp_path / "mi")
    from bigdl_tpu.utils.serializer import save_model
    save_model(mpath, m, m._trained.variables)
    est3.load(mpath)
    pred = est3.predict((a, b), batch_size=16)
    assert pred.shape == (32, 2)


def test_sharded_read_csv_disjoint(tmp_path):
    """Sharded reads take disjoint round-robin file slices per process."""
    import pandas as pd

    for i in range(5):
        pd.DataFrame({"a": np.full(4, i)}).to_csv(
            tmp_path / f"p{i}.csv", index=False)
    s0 = read_csv(str(tmp_path), process_id=0, process_count=2)
    s1 = read_csv(str(tmp_path), process_id=1, process_count=2)
    v0 = set(s0.concat()["a"])
    v1 = set(s1.concat()["a"])
    assert v0 == {0, 2, 4} and v1 == {1, 3}
    # process-local collections own everything local
    assert len(s0.owned()) == s0.num_partitions() == 3


def test_two_process_sharded_read_feeds_estimator(tmp_path):
    """VERDICT #8 'Done' spec: 2-process CPU run where each process reads
    distinct files and the estimator consumes them without a full-host
    concat."""
    import socket
    import subprocess
    import sys
    import textwrap

    import pandas as pd

    rs = np.random.RandomState(0)
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    for i in range(4):
        x = rs.rand(64, 4).astype(np.float32)
        df = pd.DataFrame(x, columns=[f"f{j}" for j in range(4)])
        df["y"] = (x.sum(1) > 2).astype(np.int32)
        df.to_csv(data_dir / f"part{i}.csv", index=False)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    worker = textwrap.dedent(f"""
        import numpy as np
        import jax
        jax.config.update("jax_platforms", "cpu")
        from bigdl_tpu.data.shards import read_csv
        from bigdl_tpu.estimator import Estimator, init_context
        from bigdl_tpu import nn
        from bigdl_tpu.optim.optim_method import Adam

        init_context("multihost")
        assert jax.process_count() == 2
        xs = read_csv({str(data_dir)!r}, sharded=True)
        assert xs.num_partitions() == 2   # 4 files round-robin over 2 procs
        df = xs.owned_concat()
        assert len(df) == 128             # half the 256 global rows
        data = (df[[c for c in df.columns if c.startswith("f")]].values
                .astype(np.float32), df["y"].values.astype(np.int32))
        est = Estimator.from_module(
            lambda c: nn.Sequential([nn.Linear(4, 2)]),
            lambda c: Adam(learning_rate=1e-2),
            lambda c: nn.CrossEntropyCriterion())
        stats = est.fit(data, epochs=2, batch_size=32)
        print(f"RANK{{jax.process_index()}}_OK={{stats['num_samples']}}")
    """)
    script = tmp_path / "worker.py"
    script.write_text(worker)
    import os as _os
    repo_root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    pythonpath = _os.pathsep.join(
        p for p in [repo_root, _os.environ.get("PYTHONPATH")] if p)
    procs = []
    try:
        for r in range(2):
            env = dict(_os.environ,
                       BIGDL_TPU_COORDINATOR=f"127.0.0.1:{port}",
                       BIGDL_TPU_NUM_PROCESSES="2",
                       BIGDL_TPU_PROCESS_ID=str(r),
                       JAX_PLATFORMS="cpu",
                       PYTHONPATH=pythonpath)
            env.pop("XLA_FLAGS", None)
            procs.append(subprocess.Popen(
                [sys.executable, str(script)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
        outs = [p.communicate(timeout=240)[0] for p in procs]
        for r, out in enumerate(outs):
            assert procs[r].returncode == 0, out[-2000:]
            assert f"RANK{r}_OK=128" in out, out[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_sharded_read_empty_slice_raises_clearly(tmp_path):
    import pandas as pd

    d = tmp_path / "few"
    d.mkdir()
    pd.DataFrame({"a": [1]}).to_csv(d / "only.csv", index=False)
    with pytest.raises(ValueError, match="owns no files"):
        read_csv(str(d), process_id=1, process_count=2)
