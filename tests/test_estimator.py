"""Orca-equivalent Estimator + XShards + serializer round-trip tests
(reference test analog: orca estimator tests run with cluster_mode="local" —
SURVEY.md §5)."""

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.data.shards import XShards, read_csv
from bigdl_tpu.estimator import Estimator, init_context
from bigdl_tpu.nn.criterion import CrossEntropyCriterion
from bigdl_tpu.optim.optim_method import Adam
from bigdl_tpu.optim.validation import Loss, Top1Accuracy


def _toy(n=256, d=8, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.rand(n, d).astype(np.float32)
    y = (x.sum(1) > d / 2).astype(np.int32)
    return x, y


def _make_est():
    return Estimator.from_module(
        model_creator=lambda cfg: nn.Sequential(
            [nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2)]),
        optimizer_creator=lambda cfg: Adam(
            learning_rate=cfg.get("lr", 1e-2)),
        loss_creator=lambda cfg: CrossEntropyCriterion(),
        config={"lr": 1e-2})


def test_estimator_fit_evaluate_predict():
    init_context("local")
    x, y = _toy()
    est = _make_est()
    stats = est.fit((x, y), epochs=30, batch_size=64,
                    validation_data=(x, y),
                    validation_methods=[Top1Accuracy()])
    assert stats["num_samples"] == 256
    res = est.evaluate((x, y), [Top1Accuracy(), Loss(CrossEntropyCriterion())])
    assert res["Top1Accuracy"] > 0.85
    pred = est.predict(x[:10])
    assert pred.shape == (10, 2)


def test_estimator_xshards_and_save_load(tmp_path):
    init_context("local")
    x, y = _toy(seed=1)
    shards = XShards.partition({"x": x, "y": y}, num_shards=4)
    assert shards.num_partitions() == 4

    est = _make_est()
    est.fit(shards, epochs=8, batch_size=64)
    ref_pred = est.predict(x[:16])

    path = str(tmp_path / "model")
    est.save(path)

    est2 = _make_est()
    est2.load(path)
    pred2 = est2.predict(x[:16])
    np.testing.assert_allclose(np.asarray(pred2), np.asarray(ref_pred),
                               rtol=1e-5, atol=1e-5)


def test_xshards_ops():
    x = np.arange(100).reshape(50, 2).astype(np.float32)
    s = XShards.partition(x, num_shards=5)
    s2 = s.transform_shard(lambda a: a * 2)
    assert np.allclose(s2.concat(), x * 2)
    s3 = s.repartition(3)
    assert s3.num_partitions() == 3
    assert np.allclose(s3.concat(), x)


def test_read_csv(tmp_path):
    import pandas as pd

    for i in range(3):
        pd.DataFrame({"a": np.arange(10) + i, "b": np.arange(10)}).to_csv(
            tmp_path / f"part{i}.csv", index=False)
    xs = read_csv(str(tmp_path))
    assert xs.num_partitions() == 3
    df = xs.concat()
    assert len(df) == 30


def test_estimator_rejects_unknown_backend():
    with pytest.raises(ValueError):
        Estimator.from_module(lambda c: None, lambda c: None, lambda c: None,
                              backend="ray")


def test_estimator_loaded_weights_evaluate_and_multiinput_predict(tmp_path):
    """Loaded-weights (no prior fit) paths: evaluate works, and predict
    handles the multi-input tuple pack like the trained path."""
    from bigdl_tpu.keras.engine import Input, Model
    from bigdl_tpu.nn.module import Sequential

    init_context("local")
    x, y = _toy(seed=3)
    est = _make_est()
    est.fit((x, y), epochs=15, batch_size=64)
    ref_eval = est.evaluate((x, y), [Top1Accuracy()])
    ref_pred = est.predict(x[:16])
    path = str(tmp_path / "m")
    est.save(path)

    est2 = _make_est()
    est2.load(path)
    # evaluate without a prior fit (used to raise "call fit() first")
    got = est2.evaluate((x, y), [Top1Accuracy()])
    assert abs(got["Top1Accuracy"] - ref_eval["Top1Accuracy"]) < 1e-6
    np.testing.assert_allclose(est2.predict(x[:16]), ref_pred,
                               rtol=1e-5, atol=1e-6)

    # multi-input model through the loaded-weights predict path
    ia, ib = Input((4,)), Input((4,))
    from bigdl_tpu.keras.layers import Merge
    out = nn.Linear(8, 2)(Merge("concat")([ia, ib]))
    m = Model([ia, ib], out)
    a = np.random.RandomState(0).rand(32, 4).astype(np.float32)
    b = np.random.RandomState(1).rand(32, 4).astype(np.float32)
    yy = np.random.RandomState(2).randint(0, 2, 32).astype(np.int32)
    m.compile(Adam(1e-2), CrossEntropyCriterion())
    m.fit([a, b], yy, batch_size=16, nb_epoch=1)

    est3 = Estimator.from_module(
        model_creator=lambda cfg: m,
        optimizer_creator=lambda cfg: Adam(1e-2),
        loss_creator=lambda cfg: CrossEntropyCriterion())
    mpath = str(tmp_path / "mi")
    from bigdl_tpu.utils.serializer import save_model
    save_model(mpath, m, m._trained.variables)
    est3.load(mpath)
    pred = est3.predict((a, b), batch_size=16)
    assert pred.shape == (32, 2)
