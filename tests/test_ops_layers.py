"""TF-op-compatible layer tranche (nn/ops analog) — numeric checks vs numpy
and jit-compatibility of representative graphs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn

RNG = jax.random.PRNGKey(0)
RS = np.random.RandomState(0)
X = RS.randn(3, 5).astype(np.float32)
XP = np.abs(X) + 0.1  # strictly positive
A = RS.randn(3, 5).astype(np.float32)
B = RS.randn(3, 5).astype(np.float32) + 0.3


def _run(layer, *xs):
    v = layer.init(RNG, *xs)
    y, _ = layer.apply(v, *xs)
    return np.asarray(y)


UNARY_CASES = [
    (nn.Ceil, X, np.ceil),
    (nn.Floor, X, np.floor),
    (nn.Rint, X, np.rint),
    (nn.Round, X, np.round),
    (nn.Sign, X, np.sign),
    (nn.Expm1, X, np.expm1),
    (nn.Log1p, XP, np.log1p),
    (nn.Inv, XP, lambda x: 1.0 / x),
    (nn.Rsqrt, XP, lambda x: 1.0 / np.sqrt(x)),
    (nn.Sin, X, np.sin),
    (nn.Cos, X, np.cos),
    (nn.Tan, X, np.tan),
    (nn.Asin, np.clip(X, -0.9, 0.9), np.arcsin),
    (nn.Acos, np.clip(X, -0.9, 0.9), np.arccos),
    (nn.Atan, X, np.arctan),
    (nn.Sinh, X, np.sinh),
    (nn.Cosh, X, np.cosh),
    (nn.Asinh, X, np.arcsinh),
    (nn.Acosh, XP + 1.0, np.arccosh),
    (nn.Atanh, np.clip(X, -0.9, 0.9), np.arctanh),
    (nn.IsFinite, X, np.isfinite),
    (nn.LogicalNot, X > 0, np.logical_not),
]


@pytest.mark.parametrize("cls,x,ref", UNARY_CASES,
                         ids=[c[0].__name__ for c in UNARY_CASES])
def test_unary(cls, x, ref):
    np.testing.assert_allclose(_run(cls(), x), ref(x), rtol=2e-5, atol=2e-5)


def test_special_fns():
    from scipy import special as sp  # scipy ships with jax

    np.testing.assert_allclose(_run(nn.Erf(), X), sp.erf(X), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(_run(nn.Erfc(), X), sp.erfc(X), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(_run(nn.Lgamma(), XP), sp.gammaln(XP),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(_run(nn.Digamma(), XP + 1.0),
                               sp.digamma(XP + 1.0), rtol=1e-4, atol=1e-4)


BINARY_CASES = [
    (nn.Maximum, np.maximum),
    (nn.Minimum, np.minimum),
    (nn.Mod, np.fmod),  # TF raw-op Mod: truncated (C) semantics
    (nn.FloorDiv, np.floor_divide),
    (nn.Atan2, np.arctan2),
    (nn.SquaredDifference, lambda a, b: (a - b) ** 2),
    (nn.Equal, np.equal),
    (nn.NotEqual, np.not_equal),
    (nn.Greater, np.greater),
    (nn.GreaterEqual, np.greater_equal),
    (nn.Less, np.less),
    (nn.LessEqual, np.less_equal),
]


@pytest.mark.parametrize("cls,ref", BINARY_CASES,
                         ids=[c[0].__name__ for c in BINARY_CASES])
def test_binary(cls, ref):
    np.testing.assert_allclose(_run(cls(), A, B), ref(A, B), rtol=2e-5,
                               atol=2e-5)


def test_truncate_div():
    np.testing.assert_allclose(_run(nn.TruncateDiv(), A, B),
                               np.trunc(A / B), rtol=1e-5, atol=1e-5)


def test_logical():
    a, b = A > 0, B > 0
    np.testing.assert_array_equal(_run(nn.LogicalAnd(), a, b),
                                  np.logical_and(a, b))
    np.testing.assert_array_equal(_run(nn.LogicalOr(), a, b),
                                  np.logical_or(a, b))
    np.testing.assert_array_equal(_run(nn.LogicalXor(), a, b),
                                  np.logical_xor(a, b))


def test_reductions():
    m = X > 0
    np.testing.assert_array_equal(_run(nn.All(axis=1), m), m.all(axis=1))
    np.testing.assert_array_equal(_run(nn.Any(axis=0), m), m.any(axis=0))
    np.testing.assert_allclose(_run(nn.Prod(axis=1), X), X.prod(axis=1),
                               rtol=1e-5)
    np.testing.assert_allclose(_run(nn.CumSum(axis=1), X), X.cumsum(axis=1),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(_run(nn.CumProd(axis=1), X),
                               X.cumprod(axis=1), rtol=1e-5, atol=1e-6)
    # reverse + exclusive cumsum (tf semantics)
    y = _run(nn.CumSum(axis=1, reverse=True), X)
    np.testing.assert_allclose(y, np.flip(np.flip(X, 1).cumsum(1), 1),
                               rtol=1e-5, atol=1e-6)
    ye = _run(nn.CumSum(axis=1, exclusive=True), X)
    expect = np.concatenate(
        [np.zeros((3, 1), np.float32), X.cumsum(1)[:, :-1]], axis=1)
    np.testing.assert_allclose(ye, expect, rtol=1e-5, atol=1e-6)


def test_shape_dtype_index_ops():
    assert _run(nn.Cast(jnp.int32), X).dtype == np.int32
    assert int(_run(nn.Rank(), X)) == 2
    np.testing.assert_array_equal(_run(nn.ShapeOp(), X), [3, 5])
    assert int(_run(nn.SizeOp(), X)) == 15
    assert _run(nn.ExpandDims(1), X).shape == (3, 1, 5)
    assert _run(nn.Tile((2, 1)), X).shape == (6, 5)
    idx = np.array([2, 0], np.int32)
    np.testing.assert_allclose(_run(nn.Gather(axis=0), X, idx), X[idx])
    np.testing.assert_allclose(_run(nn.SliceOp((1, 2), (2, -1)), X),
                               X[1:3, 2:])
    y = _run(nn.PadOp([[1, 1], [0, 2]], value=9.0), X)
    assert y.shape == (5, 7) and y[0, 0] == 9.0
    oh = _run(nn.OneHot(4), np.array([1, 3], np.int32))
    np.testing.assert_allclose(oh, np.eye(4, dtype=np.float32)[[1, 3]])
    np.testing.assert_array_equal(_run(nn.ArgMax(axis=1), X), X.argmax(1))
    np.testing.assert_array_equal(_run(nn.ArgMin(axis=1), X), X.argmin(1))


def test_topk_intopk():
    layer = nn.TopK(2)
    v = layer.init(RNG, X)
    (vals, idx), _ = layer.apply(v, X)
    srt = np.sort(X, axis=1)[:, ::-1][:, :2]
    np.testing.assert_allclose(np.asarray(vals), srt, rtol=1e-6)
    pred = np.array([[0.1, 0.5, 0.4], [0.8, 0.05, 0.15]], np.float32)
    tgt = np.array([2, 1], np.int32)
    np.testing.assert_array_equal(_run(nn.InTopK(2), pred, tgt),
                                  [True, False])


def test_misc_ops():
    np.testing.assert_allclose(_run(nn.RangeOp(0, 5)), np.arange(5.0))
    np.testing.assert_allclose(_run(nn.Fill(3.0), X), np.full_like(X, 3.0))
    cond = X > 0
    np.testing.assert_allclose(_run(nn.Where(), cond, A, B),
                               np.where(cond, A, B))
    np.testing.assert_allclose(_run(nn.L2Loss(), X),
                               0.5 * np.sum(X ** 2), rtol=1e-6)


def test_batch_matmul():
    a = RS.randn(2, 3, 4).astype(np.float32)
    b = RS.randn(2, 4, 5).astype(np.float32)
    np.testing.assert_allclose(_run(nn.BatchMatMul(), a, b), a @ b,
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        _run(nn.BatchMatMul(adj_x=True), a.transpose(0, 2, 1), b), a @ b,
        rtol=1e-5, atol=1e-5)


def test_depth_space_roundtrip():
    x = RS.randn(2, 4, 4, 8).astype(np.float32)
    d2s = _run(nn.DepthToSpace(2), x)
    assert d2s.shape == (2, 8, 8, 2)
    back = _run(nn.SpaceToDepth(2), d2s)
    np.testing.assert_allclose(back, x, rtol=1e-6)
    # torch pixel_shuffle parity: torch groups channels c-major
    # (k = c*r*r + i*r + j), TF/ours block-major (k = (i*r+j)*C_out + c) —
    # permute channels to torch's order before comparing.
    import torch

    r, c_out = 2, 2
    perm = np.array([(i * r + j) * c_out + c
                     for c in range(c_out)
                     for i in range(r) for j in range(r)])
    t = torch.nn.functional.pixel_shuffle(
        torch.from_numpy(x.transpose(0, 3, 1, 2)[:, perm]), r)
    np.testing.assert_allclose(d2s, t.numpy().transpose(0, 2, 3, 1),
                               rtol=1e-6)


def test_random_ops():
    y = _run_rng(nn.RandomUniformOp(2.0, 3.0), X)
    assert y.shape == X.shape and (y >= 2.0).all() and (y < 3.0).all()
    z = _run_rng(nn.TruncatedNormalOp(1.0, 0.5), X)
    assert abs(float(z.mean()) - 1.0) < 0.5
    assert (np.abs((z - 1.0) / 0.5) <= 2.0 + 1e-6).all()


def _run_rng(layer, *xs):
    v = layer.init(RNG, *xs)
    y, _ = layer.apply(v, *xs, rng=RNG)
    return np.asarray(y)


def test_ops_graph_jits():
    """A graph of op-layers compiles to one jitted function."""
    seq = nn.Sequential([nn.SquaredDifference(), nn.Log1p(),
                         nn.Prod(axis=1)])
    v = seq.init(RNG, (jnp.abs(jnp.asarray(A)), jnp.abs(jnp.asarray(B))))

    @jax.jit
    def f(a, b):
        y, _ = seq.apply(v, (a, b))
        return y

    y = np.asarray(f(jnp.abs(jnp.asarray(A)), jnp.abs(jnp.asarray(B))))
    expect = np.log1p((np.abs(A) - np.abs(B)) ** 2).prod(axis=1)
    np.testing.assert_allclose(y, expect, rtol=1e-5, atol=1e-6)


class TestIndexedSegmentOps:
    def test_gather_nd(self):
        data = np.arange(12, dtype=np.float32).reshape(3, 4)
        idx = np.array([[0, 1], [2, 3]], np.int32)
        y, _ = nn.GatherNd().forward({}, {}, (jnp.asarray(data),
                                              jnp.asarray(idx)))
        np.testing.assert_array_equal(np.asarray(y), [1.0, 11.0])

    def test_scatter_nd_accumulates(self):
        idx = np.array([[0], [2], [0]], np.int32)
        upd = np.array([1.0, 2.0, 3.0], np.float32)
        y, _ = nn.ScatterNd((4,)).forward({}, {}, (jnp.asarray(idx),
                                                   jnp.asarray(upd)))
        np.testing.assert_array_equal(np.asarray(y), [4.0, 0.0, 2.0, 0.0])

    def test_segment_reducers(self):
        data = np.array([[1.0], [2.0], [3.0], [4.0]], np.float32)
        seg = np.array([0, 0, 1, 1], np.int32)
        s, _ = nn.SegmentSum(2).forward({}, {}, (jnp.asarray(data),
                                                 jnp.asarray(seg)))
        np.testing.assert_array_equal(np.asarray(s), [[3.0], [7.0]])
        m, _ = nn.SegmentMean(2).forward({}, {}, (jnp.asarray(data),
                                                  jnp.asarray(seg)))
        np.testing.assert_array_equal(np.asarray(m), [[1.5], [3.5]])
        mx, _ = nn.SegmentMax(2).forward({}, {}, (jnp.asarray(data),
                                                  jnp.asarray(seg)))
        np.testing.assert_array_equal(np.asarray(mx), [[2.0], [4.0]])
        # unsorted ids work (the UnsortedSegmentSum role)
        seg2 = np.array([1, 0, 1, 0], np.int32)
        s2, _ = nn.UnsortedSegmentSum(2).forward(
            {}, {}, (jnp.asarray(data), jnp.asarray(seg2)))
        np.testing.assert_array_equal(np.asarray(s2), [[6.0], [4.0]])

    def test_strided_slice(self):
        x = np.arange(24, dtype=np.float32).reshape(4, 6)
        y, _ = nn.StridedSlice([(1, 4, 2), (0, 6, 3)]).forward(
            {}, {}, jnp.asarray(x))
        np.testing.assert_array_equal(np.asarray(y), x[1:4:2, 0:6:3])

    def test_reverse_sequence(self):
        x = np.arange(8, dtype=np.float32).reshape(2, 4, 1)
        lengths = np.array([3, 2], np.int32)
        y, _ = nn.ReverseSequence().forward({}, {}, (jnp.asarray(x),
                                                     jnp.asarray(lengths)))
        got = np.asarray(y)[..., 0]
        np.testing.assert_array_equal(got[0], [2, 1, 0, 3])
        np.testing.assert_array_equal(got[1], [5, 4, 6, 7])


class TestSpatialBlockOps:
    def test_space_to_batch_round_trip(self):
        x = np.random.RandomState(0).rand(2, 4, 4, 3).astype(np.float32)
        y, _ = nn.SpaceToBatchND(2).forward({}, {}, jnp.asarray(x))
        assert y.shape == (8, 2, 2, 3)
        z, _ = nn.BatchToSpaceND(2).forward({}, {}, y)
        np.testing.assert_allclose(np.asarray(z), x)

    def test_dilation2d_zero_filter_is_maxpool(self):
        x = np.random.RandomState(1).rand(1, 6, 6, 2).astype(np.float32)
        layer = nn.Dilation2D(kernel_size=3, stride=1, padding="VALID")
        v = layer.init(jax.random.PRNGKey(0), jnp.asarray(x))
        y, _ = layer.forward(v["params"], v["state"], jnp.asarray(x))
        # zero filter -> plain max over 3x3 windows
        want = np.stack([
            [[x[0, i:i+3, j:j+3, c].max() for c in range(2)]
             for j in range(4)] for i in range(4)])[None]
        np.testing.assert_allclose(np.asarray(y), want, rtol=1e-6)

    def test_resize_nearest(self):
        x = np.arange(4, dtype=np.float32).reshape(1, 2, 2, 1)
        y, _ = nn.ResizeNearestNeighbor(2).forward({}, {}, jnp.asarray(x))
        assert y.shape == (1, 4, 4, 1)
        np.testing.assert_array_equal(np.asarray(y)[0, :2, :2, 0],
                                      [[0, 0], [0, 0]])
        np.testing.assert_array_equal(np.asarray(y)[0, 2:, 2:, 0],
                                      [[3, 3], [3, 3]])
